// Custom-device plugin loader + registry.
//
// Analog of DeviceManager::Register + LoadCustomRuntimeLib
// (paddle/phi/backends/device_manager.h:134,298, custom_device.cc:42
// wrapping the plugin table into a DeviceInterface). dlopens a vendor
// .so, resolves PT_InitDevicePlugin, validates the required slots, and
// exposes the table to Python through a flat C surface.
#include <dlfcn.h>

#include <cstring>
#include <map>
#include <mutex>
#include <string>

#include "device_ext.h"
#include "pt_common.h"

namespace {

struct Plugin {
  void* dl = nullptr;
  PT_DeviceInterface iface{};
  bool initialized = false;
};

std::mutex g_mu;
std::map<std::string, Plugin>& registry() {
  static std::map<std::string, Plugin> r;
  return r;
}

bool validate(const PT_DeviceInterface& i) {
  return i.abi_version == PT_DEVICE_ABI_VERSION && i.device_type &&
         i.init && i.get_device_count && i.device_malloc && i.device_free &&
         i.memcpy_h2d && i.memcpy_d2h;
}

Plugin* find(const char* dev_type) {
  auto it = registry().find(dev_type ? dev_type : "");
  return it == registry().end() ? nullptr : &it->second;
}

// Copy the fn-pointer table out under the lock, call outside it: a bulk
// memcpy must not serialize every other plugin call process-wide.
// Registry entries are never erased, so the copied table stays valid.
bool iface_of(const char* dev_type, PT_DeviceInterface* out) {
  std::lock_guard<std::mutex> lk(g_mu);
  Plugin* p = find(dev_type);
  if (!p) return false;
  *out = p->iface;
  return true;
}

}  // namespace

// Returns the registered device_type name, or null on failure.
PT_EXPORT const char* pt_plugin_load(const char* path) {
  std::lock_guard<std::mutex> lk(g_mu);
  void* dl = dlopen(path, RTLD_NOW | RTLD_LOCAL);
  if (!dl) {
    pt::set_last_error(std::string("dlopen: ") + dlerror());
    return nullptr;
  }
  auto init_fn = reinterpret_cast<PT_InitDevicePluginFn>(
      dlsym(dl, "PT_InitDevicePlugin"));
  if (!init_fn) {
    pt::set_last_error("plugin lacks PT_InitDevicePlugin");
    dlclose(dl);
    return nullptr;
  }
  PT_DeviceInterface iface{};
  if (init_fn(&iface) != PT_STATUS_OK || !validate(iface)) {
    pt::set_last_error("plugin init failed or ABI invalid");
    dlclose(dl);
    return nullptr;
  }
  // duplicate check BEFORE init(): re-loading the same .so shares its
  // globals with the live registration, so init/deinit on the duplicate
  // would tear down the first handle's state
  auto it = registry().find(iface.device_type);
  if (it != registry().end()) {
    dlclose(dl);
    return it->second.iface.device_type;
  }
  if (iface.init() != PT_STATUS_OK) {
    pt::set_last_error("plugin device init failed");
    dlclose(dl);
    return nullptr;
  }
  Plugin p;
  p.dl = dl;
  p.iface = iface;
  p.initialized = true;
  auto res = registry().emplace(iface.device_type, p);
  return res.first->second.iface.device_type;
}

PT_EXPORT int pt_plugin_device_count(const char* dev_type) {
  PT_DeviceInterface i{};
  if (!iface_of(dev_type, &i)) return -1;
  int n = 0;
  return i.get_device_count(&n) == PT_STATUS_OK ? n : -1;
}

PT_EXPORT void* pt_plugin_malloc(const char* dev_type, int device,
                                 uint64_t size) {
  PT_DeviceInterface i{};
  if (!iface_of(dev_type, &i)) return nullptr;
  void* ptr = nullptr;
  if (i.device_malloc(device, &ptr, size) != PT_STATUS_OK) return nullptr;
  return ptr;
}

PT_EXPORT int pt_plugin_free(const char* dev_type, int device, void* ptr) {
  PT_DeviceInterface i{};
  if (!iface_of(dev_type, &i)) return -1;
  return i.device_free(device, ptr) == PT_STATUS_OK ? 0 : -1;
}

PT_EXPORT int pt_plugin_memcpy(const char* dev_type, int device, void* dst,
                               const void* src, uint64_t size, int kind
                               /*0=h2d,1=d2h,2=d2d*/) {
  PT_DeviceInterface i{};
  if (!iface_of(dev_type, &i)) return -1;
  PT_Status (*fn)(int, void*, const void*, size_t) =
      kind == 0 ? i.memcpy_h2d : kind == 1 ? i.memcpy_d2h : i.memcpy_d2d;
  if (!fn) return -1;
  return fn(device, dst, src, size) == PT_STATUS_OK ? 0 : -1;
}

PT_EXPORT int pt_plugin_mem_stats(const char* dev_type, int device,
                                  uint64_t* total, uint64_t* free_) {
  PT_DeviceInterface i{};
  if (!iface_of(dev_type, &i) || !i.device_mem_stats) return -1;
  size_t t = 0, f = 0;
  if (i.device_mem_stats(device, &t, &f) != PT_STATUS_OK) return -1;
  *total = t;
  *free_ = f;
  return 0;
}

// One stream round-trip: create, record+sync an event, destroy — the
// contract smoke the fake-device test drives.
PT_EXPORT int pt_plugin_stream_check(const char* dev_type, int device) {
  PT_DeviceInterface i{};
  if (!iface_of(dev_type, &i) || !i.stream_create) return -1;
  PT_Stream s = nullptr;
  PT_Event e = nullptr;
  if (i.stream_create(device, &s) != PT_STATUS_OK) return -1;
  int rc = 0;
  // every event slot is optional per the header: guard each pointer
  if (i.event_create && i.event_record && i.event_synchronize &&
      (i.event_create(device, &e) != PT_STATUS_OK ||
       i.event_record(device, s, e) != PT_STATUS_OK ||
       i.event_synchronize(device, e) != PT_STATUS_OK))
    rc = -1;
  if (e && i.event_destroy) i.event_destroy(device, e);
  if (i.stream_synchronize &&
      i.stream_synchronize(device, s) != PT_STATUS_OK)
    rc = -1;
  if (i.stream_destroy) i.stream_destroy(device, s);
  return rc;
}

PT_EXPORT int pt_plugin_ccl_all_reduce(const char* dev_type, int device,
                                       void* data, uint64_t count,
                                       int dtype, int op) {
  PT_DeviceInterface i{};
  if (!iface_of(dev_type, &i) || !i.ccl_all_reduce) return -1;
  return i.ccl_all_reduce(device, data, count, dtype, op) == PT_STATUS_OK
             ? 0
             : -1;
}

// ---------------------------------------------------------------------
// Custom-op extension point (paddle/extension.h + custom_operator.cc
// analog): a .so exports PT_CUSTOM_OP(name) functions operating on host
// buffers; Python wires them in as ops (eager + jax.pure_callback under
// jit). Signature: int fn(const void** ins, const int64_t* in_sizes,
// int n_in, void* out, int64_t out_size)
// where sizes are element counts of float32 buffers.
typedef int (*PT_CustomOpFn)(const void**, const int64_t*, int, void*,
                             int64_t);

namespace {
std::mutex g_op_mu;
std::map<std::string, PT_CustomOpFn>& op_registry() {
  static std::map<std::string, PT_CustomOpFn> r;
  return r;
}
}  // namespace

PT_EXPORT int pt_custom_op_load(const char* path, const char* name) {
  std::lock_guard<std::mutex> lk(g_op_mu);
  void* dl = dlopen(path, RTLD_NOW | RTLD_LOCAL);
  if (!dl) {
    pt::set_last_error(std::string("dlopen: ") + dlerror());
    return -1;
  }
  std::string sym = std::string("pt_op_") + name;
  auto fn = reinterpret_cast<PT_CustomOpFn>(dlsym(dl, sym.c_str()));
  if (!fn) {
    pt::set_last_error("custom op symbol not found: " + sym);
    dlclose(dl);
    return -1;
  }
  op_registry()[name] = fn;
  return 0;
}

PT_EXPORT int pt_custom_op_call(const char* name, const void** ins,
                                const int64_t* in_sizes, int n_in,
                                void* out, int64_t out_size) {
  PT_CustomOpFn fn = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_op_mu);
    auto it = op_registry().find(name);
    if (it == op_registry().end()) {
      pt::set_last_error("custom op not registered");
      return -1;
    }
    fn = it->second;
  }
  return fn(ins, in_sizes, n_in, out, out_size);
}
