// C++ JIT layer: the deployable saved-model container.
//
// Analog of the reference's C++ jit layer (paddle/fluid/jit/layer.h +
// compilation_unit.cc): owns a serialized program + parameters and hands
// both to an execution engine. Here the program is serialized StableHLO
// (jit.save's .pdmodel) and execution is PJRT via jax.export on the
// Python side; this container owns the ARTIFACT — it memory-maps the
// .pdiparams safetensors-style file (8-byte header length, JSON header,
// raw buffers), parses the header with a built-in minimal JSON reader
// (no third-party deps), validates offsets, and serves zero-copy
// parameter views plus the program bytes through a C ABI.
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

#include "pt_common.h"

namespace {

struct ParamMeta {
  std::string name;
  std::string dtype;
  std::vector<int64_t> shape;
  uint64_t begin = 0;
  uint64_t end = 0;
};

struct JitLayer {
  int fd = -1;
  void* map = nullptr;
  size_t map_size = 0;
  const char* data = nullptr;  // start of raw buffers
  std::vector<ParamMeta> params;
  std::vector<char> program;   // .pdmodel bytes

  ~JitLayer() {
    if (map) munmap(map, map_size);
    if (fd >= 0) close(fd);
  }
};

// ---- minimal JSON reader for the restricted header schema -----------
struct Cursor {
  const char* p;
  const char* end;
  bool fail = false;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' ||
                       *p == '\r'))
      ++p;
  }
  bool eat(char c) {
    ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    fail = true;
    return false;
  }
  bool peek(char c) {
    ws();
    return p < end && *p == c;
  }
  std::string str() {
    ws();
    std::string out;
    if (p >= end || *p != '"') {
      fail = true;
      return out;
    }
    ++p;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) ++p;  // unescape minimally
      out.push_back(*p++);
    }
    if (p < end) ++p;
    return out;
  }
  int64_t num() {
    ws();
    int64_t sign = 1;
    if (p < end && *p == '-') {
      sign = -1;
      ++p;
    }
    int64_t v = 0;
    bool any = false;
    while (p < end && *p >= '0' && *p <= '9') {
      v = v * 10 + (*p - '0');
      ++p;
      any = true;
    }
    if (!any) fail = true;
    return sign * v;
  }
};

bool parse_header(const char* buf, size_t n,
                  std::vector<ParamMeta>* out) {
  Cursor c{buf, buf + n};
  if (!c.eat('{')) return false;
  if (c.peek('}')) {
    c.eat('}');
    return !c.fail;
  }
  while (true) {
    ParamMeta m;
    m.name = c.str();
    if (!c.eat(':') || !c.eat('{')) return false;
    while (true) {
      std::string key = c.str();
      if (!c.eat(':')) return false;
      if (key == "dtype") {
        m.dtype = c.str();
      } else if (key == "shape") {
        if (!c.eat('[')) return false;
        if (!c.peek(']')) {
          while (true) {
            m.shape.push_back(c.num());
            if (c.peek(']')) break;
            if (!c.eat(',')) return false;
          }
        }
        c.eat(']');
      } else if (key == "offsets") {
        if (!c.eat('[')) return false;
        m.begin = static_cast<uint64_t>(c.num());
        if (!c.eat(',')) return false;
        m.end = static_cast<uint64_t>(c.num());
        if (!c.eat(']')) return false;
      } else {
        return false;  // unknown key: refuse rather than misparse
      }
      if (c.peek('}')) {
        c.eat('}');
        break;
      }
      if (!c.eat(',')) return false;
    }
    out->push_back(std::move(m));
    if (c.peek('}')) {
      c.eat('}');
      break;
    }
    if (!c.eat(',')) return false;
  }
  return !c.fail;
}

}  // namespace

// path_prefix: the jit.save path; opens <prefix>.pdiparams (mmap) and
// <prefix>.pdmodel (read).
PT_EXPORT void* pt_jit_open(const char* path_prefix) {
  auto layer = new JitLayer();
  std::string params_path = std::string(path_prefix) + ".pdiparams";
  layer->fd = open(params_path.c_str(), O_RDONLY);
  if (layer->fd < 0) {
    pt::set_last_error("jit: cannot open " + params_path);
    delete layer;
    return nullptr;
  }
  struct stat st {};
  fstat(layer->fd, &st);
  layer->map_size = static_cast<size_t>(st.st_size);
  if (layer->map_size < 8) {
    pt::set_last_error("jit: param file too small");
    delete layer;
    return nullptr;
  }
  layer->map = mmap(nullptr, layer->map_size, PROT_READ, MAP_PRIVATE,
                    layer->fd, 0);
  if (layer->map == MAP_FAILED) {
    layer->map = nullptr;
    pt::set_last_error("jit: mmap failed");
    delete layer;
    return nullptr;
  }
  const char* base = static_cast<const char*>(layer->map);
  uint64_t head_len = 0;
  memcpy(&head_len, base, 8);  // little-endian host assumed (POSIX x86/arm)
  // map_size >= 8 checked above; this form cannot wrap on crafted input
  if (head_len > layer->map_size - 8) {
    pt::set_last_error("jit: corrupt header length");
    delete layer;
    return nullptr;
  }
  if (!parse_header(base + 8, head_len, &layer->params)) {
    pt::set_last_error("jit: header parse failed");
    delete layer;
    return nullptr;
  }
  layer->data = base + 8 + head_len;
  size_t payload = layer->map_size - 8 - head_len;
  for (const auto& m : layer->params) {
    if (m.end < m.begin || m.end > payload) {
      pt::set_last_error("jit: parameter offsets out of bounds: " +
                         m.name);
      delete layer;
      return nullptr;
    }
  }
  std::ifstream prog(std::string(path_prefix) + ".pdmodel",
                     std::ios::binary);
  if (prog) {
    layer->program.assign(std::istreambuf_iterator<char>(prog),
                          std::istreambuf_iterator<char>());
  }
  return layer;
}

PT_EXPORT int pt_jit_num_params(void* h) {
  return static_cast<int>(static_cast<JitLayer*>(h)->params.size());
}

PT_EXPORT const char* pt_jit_param_name(void* h, int i) {
  auto* l = static_cast<JitLayer*>(h);
  if (i < 0 || i >= static_cast<int>(l->params.size())) return nullptr;
  return l->params[i].name.c_str();
}

PT_EXPORT const char* pt_jit_param_dtype(void* h, int i) {
  auto* l = static_cast<JitLayer*>(h);
  if (i < 0 || i >= static_cast<int>(l->params.size())) return nullptr;
  return l->params[i].dtype.c_str();
}

// writes up to max_dims dims; returns ndim
PT_EXPORT int pt_jit_param_shape(void* h, int i, int64_t* dims,
                                 int max_dims) {
  auto* l = static_cast<JitLayer*>(h);
  if (i < 0 || i >= static_cast<int>(l->params.size())) return -1;
  const auto& s = l->params[i].shape;
  for (int d = 0; d < static_cast<int>(s.size()) && d < max_dims; ++d)
    dims[d] = s[d];
  return static_cast<int>(s.size());
}

// zero-copy view into the mmap; size_out gets the byte length
PT_EXPORT const void* pt_jit_param_data(void* h, int i,
                                        uint64_t* size_out) {
  auto* l = static_cast<JitLayer*>(h);
  if (i < 0 || i >= static_cast<int>(l->params.size())) return nullptr;
  const auto& m = l->params[i];
  *size_out = m.end - m.begin;
  return l->data + m.begin;
}

PT_EXPORT const void* pt_jit_program(void* h, uint64_t* size_out) {
  auto* l = static_cast<JitLayer*>(h);
  *size_out = l->program.size();
  return l->program.empty() ? "" : l->program.data();
}

PT_EXPORT void pt_jit_close(void* h) { delete static_cast<JitLayer*>(h); }
