#include "pt_common.h"

namespace pt {

std::string& last_error() {
  static thread_local std::string err;
  return err;
}

void set_last_error(const std::string& msg) { last_error() = msg; }

}  // namespace pt

PT_EXPORT const char* pt_last_error() { return pt::last_error().c_str(); }
