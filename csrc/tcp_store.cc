// TCPStore: TCP key-value rendezvous for multi-host jobs.
//
// Native analog of the reference's TCPStore
// (paddle/phi/core/distributed/store/tcp_store.h:121, socket.cpp): master
// rank runs the server; every rank connects a client; collectives'
// unique-id exchange, barrier-by-key, and elastic membership ride on
// set/get/add/wait. Protocol: 1-byte command, u32-length-prefixed key and
// value; WAIT blocks on a condition variable server-side.
#include "pt_common.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <map>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace pt {
namespace {

enum Cmd : uint8_t {
  kSet = 0, kGet = 1, kAdd = 2, kWait = 3, kPing = 4, kDel = 5
};

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_u32(int fd, uint32_t v) {
  uint32_t nv = htonl(v);
  return send_all(fd, &nv, 4);
}

bool recv_u32(int fd, uint32_t* v) {
  uint32_t nv;
  if (!recv_all(fd, &nv, 4)) return false;
  *v = ntohl(nv);
  return true;
}

bool send_bytes(int fd, const void* data, uint32_t n) {
  return send_u32(fd, n) && (n == 0 || send_all(fd, data, n));
}

bool recv_bytes(int fd, std::vector<uint8_t>* out) {
  uint32_t n;
  if (!recv_u32(fd, &n)) return false;
  out->resize(n);
  return n == 0 || recv_all(fd, out->data(), n);
}

class StoreServer {
 public:
  explicit StoreServer(int port) : port_(port) {}

  bool Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) {
      set_last_error("socket() failed");
      return false;
    }
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0) {
      set_last_error("bind() failed on port " + std::to_string(port_));
      ::close(listen_fd_);
      return false;
    }
    if (port_ == 0) {  // ephemeral: report the picked port
      socklen_t len = sizeof(addr);
      ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
      port_ = ntohs(addr.sin_port);
    }
    ::listen(listen_fd_, 128);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  void Stop() {
    stopping_.store(true);
    {
      // wake any kWait waiters blocked on the condition variable so
      // client threads can exit instead of sleeping out their timeout
      std::lock_guard<std::mutex> g(data_mu_);
      cv_.notify_all();
    }
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    {
      std::lock_guard<std::mutex> g(mu_);
      for (int fd : client_fds_) {
        ::shutdown(fd, SHUT_RDWR);
        ::close(fd);
      }
      client_fds_.clear();
    }
    for (auto& t : client_threads_)
      if (t.joinable()) t.join();
    client_threads_.clear();
  }

  int port() const { return port_; }

  ~StoreServer() { Stop(); }

 private:
  void AcceptLoop() {
    while (!stopping_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(mu_);
      client_fds_.push_back(fd);
      client_threads_.emplace_back([this, fd] { ClientLoop(fd); });
    }
  }

  void ClientLoop(int fd) {
    while (!stopping_.load()) {
      uint8_t cmd;
      if (!recv_all(fd, &cmd, 1)) break;
      std::vector<uint8_t> key_raw;
      if (!recv_bytes(fd, &key_raw)) break;
      std::string key(key_raw.begin(), key_raw.end());
      if (cmd == kSet) {
        std::vector<uint8_t> val;
        if (!recv_bytes(fd, &val)) break;
        {
          std::lock_guard<std::mutex> g(data_mu_);
          data_[key] = std::move(val);
        }
        cv_.notify_all();
        if (!send_u32(fd, 0)) break;
      } else if (cmd == kGet || cmd == kWait) {
        uint32_t timeout_ms;
        if (!recv_u32(fd, &timeout_ms)) break;
        std::unique_lock<std::mutex> g(data_mu_);
        bool ok = cv_.wait_for(
            g, std::chrono::milliseconds(timeout_ms),
            [&] { return stopping_.load() || data_.count(key) > 0; });
        if (!ok || stopping_.load()) {
          g.unlock();
          uint8_t status = 1;  // timeout
          if (!send_all(fd, &status, 1)) break;
          continue;
        }
        uint8_t status = 0;
        std::vector<uint8_t> val = (cmd == kGet) ? data_[key]
                                                 : std::vector<uint8_t>{};
        g.unlock();
        if (!send_all(fd, &status, 1)) break;
        if (cmd == kGet && !send_bytes(fd, val.data(),
                                       static_cast<uint32_t>(val.size())))
          break;
      } else if (cmd == kAdd) {
        int64_t delta;
        if (!recv_all(fd, &delta, 8)) break;
        int64_t result;
        {
          std::lock_guard<std::mutex> g(data_mu_);
          auto& val = data_[key];
          int64_t cur = 0;
          if (val.size() == 8) std::memcpy(&cur, val.data(), 8);
          cur += delta;
          val.resize(8);
          std::memcpy(val.data(), &cur, 8);
          result = cur;
        }
        cv_.notify_all();
        if (!send_all(fd, &result, 8)) break;
      } else if (cmd == kDel) {
        {
          std::lock_guard<std::mutex> g(data_mu_);
          data_.erase(key);
        }
        if (!send_u32(fd, 0)) break;
      } else if (cmd == kPing) {
        if (!send_u32(fd, 0)) break;
      } else {
        break;
      }
    }
    ::close(fd);
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::mutex mu_;
  std::vector<int> client_fds_;
  std::vector<std::thread> client_threads_;

  std::mutex data_mu_;
  std::condition_variable cv_;
  std::unordered_map<std::string, std::vector<uint8_t>> data_;
};

class StoreClient {
 public:
  bool Connect(const std::string& host, int port, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      // not an IPv4 literal: resolve via getaddrinfo (hostnames, FQDNs)
      addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      int rc = ::getaddrinfo(host.c_str(), nullptr, &hints, &res);
      if (rc != 0 || res == nullptr) {
        set_last_error("getaddrinfo failed for " + host + ": " +
                       gai_strerror(rc));
        return false;
      }
      addr.sin_addr =
          reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      ::freeaddrinfo(res);
    }
    while (true) {
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr),
                    sizeof(addr)) == 0) {
        int one = 1;
        ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return true;
      }
      ::close(fd_);
      if (std::chrono::steady_clock::now() > deadline) {
        set_last_error("connect timeout to " + host + ":" +
                       std::to_string(port));
        return false;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
  }

  bool Set(const std::string& key, const void* data, uint32_t n) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kSet;
    if (!send_all(fd_, &cmd, 1) ||
        !send_bytes(fd_, key.data(), static_cast<uint32_t>(key.size())) ||
        !send_bytes(fd_, data, n))
      return fail("set send");
    uint32_t status;
    return recv_u32(fd_, &status) || fail("set recv");
  }

  // blocking get with timeout; returns -1 on timeout/error
  int64_t Get(const std::string& key, std::vector<uint8_t>* out,
              uint32_t timeout_ms) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kGet;
    if (!send_all(fd_, &cmd, 1) ||
        !send_bytes(fd_, key.data(), static_cast<uint32_t>(key.size())) ||
        !send_u32(fd_, timeout_ms))
      return fail("get send") ? -1 : -1;
    uint8_t status;
    if (!recv_all(fd_, &status, 1)) return -1;
    if (status != 0) {
      set_last_error("get('" + key + "') timed out");
      return -1;
    }
    if (!recv_bytes(fd_, out)) return -1;
    return static_cast<int64_t>(out->size());
  }

  bool Wait(const std::string& key, uint32_t timeout_ms) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kWait;
    if (!send_all(fd_, &cmd, 1) ||
        !send_bytes(fd_, key.data(), static_cast<uint32_t>(key.size())) ||
        !send_u32(fd_, timeout_ms))
      return fail("wait send");
    uint8_t status;
    if (!recv_all(fd_, &status, 1)) return fail("wait recv");
    if (status != 0) {
      set_last_error("wait('" + key + "') timed out");
      return false;
    }
    return true;
  }

  bool Del(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kDel;
    if (!send_all(fd_, &cmd, 1) ||
        !send_bytes(fd_, key.data(), static_cast<uint32_t>(key.size())))
      return fail("del send");
    uint32_t status;
    return recv_u32(fd_, &status) || fail("del recv");
  }

  bool Add(const std::string& key, int64_t delta, int64_t* result) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kAdd;
    if (!send_all(fd_, &cmd, 1) ||
        !send_bytes(fd_, key.data(), static_cast<uint32_t>(key.size())) ||
        !send_all(fd_, &delta, 8))
      return fail("add send");
    return recv_all(fd_, result, 8) || fail("add recv");
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  bool fail(const char* what) {
    set_last_error(std::string("tcp_store client: ") + what + " failed");
    return false;
  }
  int fd_ = -1;
  std::mutex mu_;
};

}  // namespace
}  // namespace pt

using pt::StoreClient;
using pt::StoreServer;

PT_EXPORT void* pt_store_server_start(int port) {
  auto* s = new StoreServer(port);
  if (!s->Start()) {
    delete s;
    return nullptr;
  }
  return s;
}

PT_EXPORT int pt_store_server_port(void* h) {
  return static_cast<StoreServer*>(h)->port();
}

PT_EXPORT void pt_store_server_stop(void* h) {
  delete static_cast<StoreServer*>(h);
}

PT_EXPORT void* pt_store_client_connect(const char* host, int port,
                                        int timeout_ms) {
  auto* c = new StoreClient();
  if (!c->Connect(host, port, timeout_ms)) {
    delete c;
    return nullptr;
  }
  return c;
}

PT_EXPORT void pt_store_client_close(void* h) {
  delete static_cast<StoreClient*>(h);
}

PT_EXPORT int pt_store_set(void* h, const char* key, const void* data,
                           uint32_t n) {
  return static_cast<StoreClient*>(h)->Set(key, data, n) ? 0 : -1;
}

// Returns value length (copied into buf up to buf_len) or -1.
PT_EXPORT int64_t pt_store_get(void* h, const char* key, void* buf,
                               int64_t buf_len, uint32_t timeout_ms) {
  std::vector<uint8_t> out;
  int64_t n = static_cast<StoreClient*>(h)->Get(key, &out, timeout_ms);
  if (n < 0) return -1;
  if (buf && buf_len >= n) std::memcpy(buf, out.data(), n);
  return n;
}

PT_EXPORT int pt_store_del(void* h, const char* key) {
  return static_cast<StoreClient*>(h)->Del(key) ? 0 : -1;
}

PT_EXPORT int pt_store_wait(void* h, const char* key, uint32_t timeout_ms) {
  return static_cast<StoreClient*>(h)->Wait(key, timeout_ms) ? 0 : -1;
}

PT_EXPORT int64_t pt_store_add(void* h, const char* key, int64_t delta) {
  int64_t result = 0;
  if (!static_cast<StoreClient*>(h)->Add(key, delta, &result)) return -1;
  return result;
}
