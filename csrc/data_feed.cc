// Threaded prefetching token-data feed.
//
// Native analog of the reference's C++ DataFeed/Dataset input pipeline
// (paddle/fluid/framework/data_feed.h, data_set.h) and the multiprocess
// DataLoader workers (python/paddle/io/dataloader/dataloader_iter.py:368):
// a worker thread mmap-reads a flat binary token file (int32), cuts
// shuffled fixed-length windows, and keeps a bounded ring of ready
// [batch, seq_len+1] buffers so the accelerator never waits on the host.
#include "pt_common.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace pt {
namespace {

class TokenFeed {
 public:
  TokenFeed(const std::string& path, int64_t seq_len, int64_t batch,
            bool shuffle, uint64_t seed, int depth)
      : seq_len_(seq_len),
        batch_(batch),
        shuffle_(shuffle),
        rng_(seed),
        depth_(depth > 0 ? depth : 4) {
    fd_ = ::open(path.c_str(), O_RDONLY);
    if (fd_ < 0) {
      set_last_error("data_feed: cannot open " + path);
      return;
    }
    struct stat st{};
    ::fstat(fd_, &st);
    file_bytes_ = static_cast<size_t>(st.st_size);
    n_tokens_ = file_bytes_ / sizeof(int32_t);
    if (n_tokens_ < static_cast<size_t>(seq_len_ + 1)) {
      set_last_error("data_feed: file too small for seq_len");
      ::close(fd_);
      fd_ = -1;
      return;
    }
    map_ = static_cast<const int32_t*>(
        ::mmap(nullptr, file_bytes_, PROT_READ, MAP_PRIVATE, fd_, 0));
    if (map_ == MAP_FAILED) {
      set_last_error("data_feed: mmap failed");
      map_ = nullptr;
      ::close(fd_);
      fd_ = -1;
      return;
    }
    ::madvise(const_cast<int32_t*>(map_), file_bytes_, MADV_SEQUENTIAL);
    n_windows_ = (n_tokens_ - 1) / seq_len_;
    worker_ = std::thread([this] { Produce(); });
  }

  bool ok() const { return map_ != nullptr; }
  int64_t num_windows() const { return static_cast<int64_t>(n_windows_); }

  // copy the next ready batch ([batch, seq_len+1] int32) into out
  bool Next(int32_t* out) {
    std::unique_lock<std::mutex> g(mu_);
    cv_consumer_.wait(g,
                      [&] { return stopping_.load() || !ready_.empty(); });
    if (stopping_.load() && ready_.empty()) return false;
    std::vector<int32_t> buf = std::move(ready_.front());
    ready_.pop_front();
    g.unlock();
    cv_producer_.notify_one();
    std::memcpy(out, buf.data(), buf.size() * sizeof(int32_t));
    return true;
  }

  ~TokenFeed() {
    stopping_.store(true);
    cv_producer_.notify_all();
    cv_consumer_.notify_all();
    if (worker_.joinable()) worker_.join();
    if (map_) ::munmap(const_cast<int32_t*>(map_), file_bytes_);
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  void Produce() {
    const size_t row = static_cast<size_t>(seq_len_) + 1;
    std::vector<size_t> order(n_windows_);
    for (size_t i = 0; i < n_windows_; ++i) order[i] = i;
    size_t cursor = n_windows_;  // trigger (re)shuffle on first use
    while (!stopping_.load()) {
      std::vector<int32_t> buf(static_cast<size_t>(batch_) * row);
      for (int64_t b = 0; b < batch_; ++b) {
        if (cursor >= n_windows_) {
          if (shuffle_) {
            std::shuffle(order.begin(), order.end(), rng_);
          }
          cursor = 0;
        }
        size_t start = order[cursor++] * static_cast<size_t>(seq_len_);
        // window overlaps next token for labels; clamp to file end
        if (start + row > n_tokens_) start = n_tokens_ - row;
        std::memcpy(buf.data() + static_cast<size_t>(b) * row,
                    map_ + start, row * sizeof(int32_t));
      }
      std::unique_lock<std::mutex> g(mu_);
      cv_producer_.wait(g, [&] {
        return stopping_.load() ||
               ready_.size() < static_cast<size_t>(depth_);
      });
      if (stopping_.load()) return;
      ready_.push_back(std::move(buf));
      g.unlock();
      cv_consumer_.notify_one();
    }
  }

  int64_t seq_len_, batch_;
  bool shuffle_;
  std::mt19937_64 rng_;
  int depth_;
  int fd_ = -1;
  size_t file_bytes_ = 0;
  size_t n_tokens_ = 0;
  size_t n_windows_ = 0;
  const int32_t* map_ = nullptr;

  std::thread worker_;
  std::atomic<bool> stopping_{false};
  std::mutex mu_;
  std::condition_variable cv_producer_, cv_consumer_;
  std::deque<std::vector<int32_t>> ready_;
};

}  // namespace
}  // namespace pt

using pt::TokenFeed;

PT_EXPORT void* pt_feed_create(const char* path, int64_t seq_len,
                               int64_t batch, int shuffle, uint64_t seed,
                               int depth) {
  auto* f = new TokenFeed(path, seq_len, batch, shuffle != 0, seed, depth);
  if (!f->ok()) {
    delete f;
    return nullptr;
  }
  return f;
}

PT_EXPORT int64_t pt_feed_num_windows(void* h) {
  return static_cast<TokenFeed*>(h)->num_windows();
}

PT_EXPORT int pt_feed_next(void* h, int32_t* out) {
  return static_cast<TokenFeed*>(h)->Next(out) ? 0 : -1;
}

PT_EXPORT void pt_feed_destroy(void* h) {
  delete static_cast<TokenFeed*>(h);
}
