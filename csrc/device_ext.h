// Custom-device plugin C ABI.
//
// TPU-native analog of the reference's out-of-tree device seam
// (paddle/phi/backends/device_ext.h:96 C_DeviceInterface — 67 C_Status
// fn pointers incl. xccl_* collective hooks at :557-657 — loaded via
// DeviceManager::LoadCustomRuntimeLib, device_manager.h:298). A vendor
// ships one .so exporting PT_InitDevicePlugin; the runtime dlopens it and
// drives devices through this table. PJRT plays this role for real TPU
// silicon; this ABI exists for the same reasons the reference keeps one
// anyway: fake devices for contract tests, host-staging backends, and
// vendor fabrics that want the framework's runtime without XLA.
//
// All functions return PT_STATUS_OK (0) on success. Unused slots may be
// null; the loader validates the required core set.
#pragma once

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define PT_DEVICE_ABI_VERSION 1

typedef enum {
  PT_STATUS_OK = 0,
  PT_STATUS_FAILED = 1,
  PT_STATUS_INVALID = 2,
} PT_Status;

typedef struct PT_Stream_st* PT_Stream;
typedef struct PT_Event_st* PT_Event;

typedef struct {
  size_t abi_version;           // must be PT_DEVICE_ABI_VERSION
  const char* device_type;      // e.g. "fake_cpu"

  // ------------------------------------------------------ device control
  PT_Status (*init)(void);
  PT_Status (*deinit)(void);
  PT_Status (*get_device_count)(int* count);
  PT_Status (*set_device)(int device);
  PT_Status (*get_device)(int* device);

  // ------------------------------------------------------------- memory
  PT_Status (*device_malloc)(int device, void** ptr, size_t size);
  PT_Status (*device_free)(int device, void* ptr);
  PT_Status (*memcpy_h2d)(int device, void* dst, const void* src,
                          size_t size);
  PT_Status (*memcpy_d2h)(int device, void* dst, const void* src,
                          size_t size);
  PT_Status (*memcpy_d2d)(int device, void* dst, const void* src,
                          size_t size);
  PT_Status (*device_mem_stats)(int device, size_t* total, size_t* free_);

  // ------------------------------------------------------------ streams
  PT_Status (*stream_create)(int device, PT_Stream* stream);
  PT_Status (*stream_destroy)(int device, PT_Stream stream);
  PT_Status (*stream_synchronize)(int device, PT_Stream stream);

  // ------------------------------------------------------------- events
  PT_Status (*event_create)(int device, PT_Event* event);
  PT_Status (*event_destroy)(int device, PT_Event event);
  PT_Status (*event_record)(int device, PT_Stream stream, PT_Event event);
  PT_Status (*event_synchronize)(int device, PT_Event event);

  // ------------------------------- collective hooks (xccl_* analog)
  // Optional: a fabric plugin implements these; the framework routes
  // host-driven collectives for this device type through them.
  PT_Status (*ccl_all_reduce)(int device, void* data, size_t count,
                              int dtype /*0=f32,1=f64,2=i32,3=i64*/,
                              int op /*0=sum,1=max,2=min,3=prod*/);
  PT_Status (*ccl_broadcast)(int device, void* data, size_t nbytes,
                             int root);
} PT_DeviceInterface;

// The single symbol a plugin must export:
//   PT_Status PT_InitDevicePlugin(PT_DeviceInterface* iface);
typedef PT_Status (*PT_InitDevicePluginFn)(PT_DeviceInterface*);

#ifdef __cplusplus
}  // extern "C"
#endif
