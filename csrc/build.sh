#!/bin/sh
# Build libpaddle_tpu_rt.so (native runtime: tcp_store, allocator,
# data_feed, flags). Invoked by paddle_tpu._core.native on demand.
set -e
cd "$(dirname "$0")"
mkdir -p build
g++ -std=c++17 -O2 -fPIC -shared -pthread \
    -fvisibility=hidden \
    pt_error.cc tcp_store.cc allocator.cc data_feed.cc flags.cc \
    comm_context.cc device_plugin.cc jit_layer.cc \
    -ldl -o build/libpaddle_tpu_rt.so
# fake custom-device plugin (contract-test backend, fake_cpu_device.h analog)
g++ -std=c++17 -O2 -fPIC -shared \
    fake_device.cc -o build/libpt_fake_device.so
echo "built csrc/build/libpaddle_tpu_rt.so + libpt_fake_device.so"
