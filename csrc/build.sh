#!/bin/sh
# Build libpaddle_tpu_rt.so (native runtime: tcp_store, allocator,
# data_feed, flags). Invoked by paddle_tpu._core.native on demand.
set -e
cd "$(dirname "$0")"
mkdir -p build
g++ -std=c++17 -O2 -fPIC -shared -pthread \
    -fvisibility=hidden \
    pt_error.cc tcp_store.cc allocator.cc data_feed.cc flags.cc \
    comm_context.cc device_plugin.cc jit_layer.cc \
    -ldl -o build/libpaddle_tpu_rt.so
# fake custom-device plugin (contract-test backend, fake_cpu_device.h analog)
g++ -std=c++17 -O2 -fPIC -shared \
    fake_device.cc -o build/libpt_fake_device.so
# eager hot-path CPython extension (dispatch key + backward BFS + the
# native record core: skeleton matcher, aval cache, interns).
# BEST-EFFORT: it needs Python dev headers and must be built against
# the interpreter that will import it (PT_PYTHON, set by
# _core/native.py to sys.executable) — a failure here must never take
# down the core runtime library built above; the pure-python record
# fast path stands alone. -fvisibility=hidden keeps the record-core
# helpers internal (PyInit_* carries its own default visibility).
PY="${PT_PYTHON:-python3}"
PYINC="$("$PY" -c 'import sysconfig; print(sysconfig.get_paths()["include"])' 2>/dev/null || true)"
if [ -n "$PYINC" ] && [ -f "$PYINC/Python.h" ]; then
    g++ -std=c++17 -O2 -fPIC -shared -fvisibility=hidden \
        -I"$PYINC" eager_core.cc -o build/pt_eager_core.so \
        || echo "WARN: pt_eager_core build failed (python fallback stays)"
else
    echo "WARN: Python.h not found; skipping pt_eager_core (python fallback)"
fi
echo "built csrc/build/libpaddle_tpu_rt.so + libpt_fake_device.so"
