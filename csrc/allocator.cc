// Auto-growth best-fit host allocator.
//
// Native analog of the reference's default allocator strategy
// (paddle/phi/core/memory/allocation/auto_growth_best_fit_allocator.cc):
// carve allocations from large chunks, best-fit over a size-ordered free
// map, coalesce neighbors on free, grow by max(chunk, aligned request)
// when no block fits. Device memory belongs to PJRT/XLA on TPU; this pool
// serves host staging buffers (input pipeline, checkpoint IO) where malloc
// churn and page faults would stall the feed path.
#include "pt_common.h"

#include <cstdlib>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace pt {
namespace {

constexpr size_t kAlign = 256;

size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

class AutoGrowthBestFit {
 public:
  explicit AutoGrowthBestFit(size_t chunk_size)
      : chunk_size_(align_up(chunk_size ? chunk_size : (64u << 20))) {}

  ~AutoGrowthBestFit() {
    for (void* c : chunks_) std::free(c);
  }

  void* Alloc(size_t size) {
    size = align_up(size ? size : kAlign);
    std::lock_guard<std::mutex> g(mu_);
    auto it = free_by_size_.lower_bound(size);
    if (it == free_by_size_.end()) {
      size_t grow = std::max(chunk_size_, size);
      void* chunk = std::aligned_alloc(kAlign, grow);
      if (!chunk) {
        set_last_error("allocator: aligned_alloc of " +
                       std::to_string(grow) + " bytes failed");
        return nullptr;
      }
      chunks_.push_back(chunk);
      reserved_ += grow;
      it = InsertFree(static_cast<char*>(chunk), grow);
    }
    char* base = it->second;
    size_t block = it->first;
    EraseFree(it);
    if (block > size + kAlign) {  // split
      InsertFree(base + size, block - size);
      block = size;
    }
    allocated_[base] = block;
    in_use_ += block;
    return base;
  }

  bool Free(void* p) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = allocated_.find(static_cast<char*>(p));
    if (it == allocated_.end()) {
      set_last_error("allocator: free of unknown pointer");
      return false;
    }
    char* base = it->first;
    size_t size = it->second;
    allocated_.erase(it);
    in_use_ -= size;
    // coalesce with free neighbors
    auto right = free_by_addr_.find(base + size);
    if (right != free_by_addr_.end()) {
      size += right->second;
      EraseFreeByAddr(right);
    }
    if (!free_by_addr_.empty()) {
      auto left = free_by_addr_.lower_bound(base);
      if (left != free_by_addr_.begin()) {
        --left;
        if (left->first + left->second == base) {
          base = left->first;
          size += left->second;
          EraseFreeByAddr(left);
        }
      }
    }
    InsertFree(base, size);
    return true;
  }

  void Stats(uint64_t* in_use, uint64_t* reserved) const {
    std::lock_guard<std::mutex> g(mu_);
    *in_use = in_use_;
    *reserved = reserved_;
  }

 private:
  using FreeBySize = std::multimap<size_t, char*>;

  FreeBySize::iterator InsertFree(char* base, size_t size) {
    auto it = free_by_size_.emplace(size, base);
    free_by_addr_[base] = size;
    return it;
  }

  void EraseFree(FreeBySize::iterator it) {
    free_by_addr_.erase(it->second);
    free_by_size_.erase(it);
  }

  void EraseFreeByAddr(std::map<char*, size_t>::iterator it) {
    auto range = free_by_size_.equal_range(it->second);
    for (auto i = range.first; i != range.second; ++i) {
      if (i->second == it->first) {
        free_by_size_.erase(i);
        break;
      }
    }
    free_by_addr_.erase(it);
  }

  size_t chunk_size_;
  mutable std::mutex mu_;
  FreeBySize free_by_size_;
  std::map<char*, size_t> free_by_addr_;
  std::unordered_map<char*, size_t> allocated_;
  std::vector<void*> chunks_;
  uint64_t in_use_ = 0;
  uint64_t reserved_ = 0;
};

}  // namespace
}  // namespace pt

using pt::AutoGrowthBestFit;

PT_EXPORT void* pt_alloc_create(uint64_t chunk_size) {
  return new AutoGrowthBestFit(static_cast<size_t>(chunk_size));
}

PT_EXPORT void pt_alloc_destroy(void* h) {
  delete static_cast<AutoGrowthBestFit*>(h);
}

PT_EXPORT void* pt_alloc_malloc(void* h, uint64_t size) {
  return static_cast<AutoGrowthBestFit*>(h)->Alloc(
      static_cast<size_t>(size));
}

PT_EXPORT int pt_alloc_free(void* h, void* p) {
  return static_cast<AutoGrowthBestFit*>(h)->Free(p) ? 0 : -1;
}

PT_EXPORT void pt_alloc_stats(void* h, uint64_t* in_use,
                              uint64_t* reserved) {
  static_cast<AutoGrowthBestFit*>(h)->Stats(in_use, reserved);
}
