// Eager hot-path primitives as a CPython extension.
//
// The reference keeps eager dispatch and the autograd walk in C++
// (phi/core/kernel_factory.h:316 SelectKernelOrThrowError,
// fluid/eager/backward.cc:106 RunBackward); this module is the
// TPU-native equivalent of the pieces that still cost python time per
// op after XLA owns the math:
//
//   attrs_key(name, backend, attrs) — the canonical executable-cache
//       key (KernelKey construction): sorted (k, v) attr tuple built in
//       one C pass. Returns None for attr values outside the primitive
//       set so the caller can fall back to the python path.
//   discover(roots)               — the backward engine's in-degree BFS
//       (RunBackward's node_in_degree_map): one C loop over .edges.
//
// Plain CPython C API (no pybind per the build rules); compiled into
// its own extension .so by _core/native.py next to libpaddle_tpu_rt.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <vector>

namespace {

// value is cache-key-safe if hashable AND compares by value:
// primitives and tuples thereof. (Lists/dicts/arrays -> python path.)
bool key_safe(PyObject* v) {
  if (v == Py_None || PyBool_Check(v) || PyLong_Check(v) ||
      PyFloat_Check(v) || PyUnicode_Check(v) || PyBytes_Check(v)) {
    return true;
  }
  if (PyTuple_Check(v)) {
    Py_ssize_t n = PyTuple_GET_SIZE(v);
    for (Py_ssize_t i = 0; i < n; ++i) {
      if (!key_safe(PyTuple_GET_ITEM(v, i))) return false;
    }
    return true;
  }
  return false;
}

PyObject* attrs_key(PyObject*, PyObject* args) {
  PyObject* name;
  PyObject* backend;
  PyObject* attrs;
  if (!PyArg_ParseTuple(args, "OOO!", &name, &backend, &PyDict_Type,
                        &attrs)) {
    return nullptr;
  }

  Py_ssize_t n = PyDict_Size(attrs);
  std::vector<std::pair<PyObject*, PyObject*>> items;
  items.reserve(n);
  PyObject *k, *v;
  Py_ssize_t pos = 0;
  while (PyDict_Next(attrs, &pos, &k, &v)) {
    if (!PyUnicode_Check(k) || !key_safe(v)) {
      Py_RETURN_NONE;  // exotic attr: python fallback builds the key
    }
    items.emplace_back(k, v);
  }
  std::sort(items.begin(), items.end(),
            [](const std::pair<PyObject*, PyObject*>& a,
               const std::pair<PyObject*, PyObject*>& b) {
              return PyUnicode_Compare(a.first, b.first) < 0;
            });

  PyObject* inner = PyTuple_New(n);
  if (!inner) return nullptr;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* pair = PyTuple_New(2);
    if (!pair) {
      Py_DECREF(inner);
      return nullptr;
    }
    Py_INCREF(items[i].first);
    Py_INCREF(items[i].second);
    PyTuple_SET_ITEM(pair, 0, items[i].first);
    PyTuple_SET_ITEM(pair, 1, items[i].second);
    PyTuple_SET_ITEM(inner, i, pair);
  }

  PyObject* key = PyTuple_New(3);
  if (!key) {
    Py_DECREF(inner);
    return nullptr;
  }
  Py_INCREF(name);
  Py_INCREF(backend);
  PyTuple_SET_ITEM(key, 0, name);
  PyTuple_SET_ITEM(key, 1, backend);
  PyTuple_SET_ITEM(key, 2, inner);
  return key;
}

// discover(roots: list[GradNode]) -> dict {node: in_degree}
// Mirrors autograd._discover: BFS over node.edges; an edge object with
// .kind == "node" contributes one in-degree to .node.
PyObject* discover(PyObject*, PyObject* args) {
  PyObject* roots;
  if (!PyArg_ParseTuple(args, "O", &roots)) return nullptr;
  PyObject* seq = PySequence_Fast(roots, "discover expects a sequence");
  if (!seq) return nullptr;

  PyObject* deps = PyDict_New();
  if (!deps) {
    Py_DECREF(seq);
    return nullptr;
  }
  PyObject* zero = PyLong_FromLong(0);
  PyObject* kind_node = PyUnicode_InternFromString("node");
  PyObject* s_edges = PyUnicode_InternFromString("edges");
  PyObject* s_kind = PyUnicode_InternFromString("kind");
  PyObject* s_node = PyUnicode_InternFromString("node");

  std::vector<PyObject*> queue;  // borrowed refs kept alive by deps
  Py_ssize_t nroots = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < nroots; ++i) {
    PyObject* r = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyDict_Contains(deps, r)) {
      if (PyDict_SetItem(deps, r, zero) < 0) goto fail;
      queue.push_back(r);
    }
  }

  for (size_t qi = 0; qi < queue.size(); ++qi) {
    PyObject* node = queue[qi];
    PyObject* edges = PyObject_GetAttr(node, s_edges);
    if (!edges) goto fail;
    PyObject* eseq = PySequence_Fast(edges, "edges must be a sequence");
    Py_DECREF(edges);
    if (!eseq) goto fail;
    Py_ssize_t ne = PySequence_Fast_GET_SIZE(eseq);
    for (Py_ssize_t i = 0; i < ne; ++i) {
      PyObject* e = PySequence_Fast_GET_ITEM(eseq, i);
      PyObject* kind = PyObject_GetAttr(e, s_kind);
      if (!kind) {
        Py_DECREF(eseq);
        goto fail;
      }
      int is_node = PyObject_RichCompareBool(kind, kind_node, Py_EQ);
      Py_DECREF(kind);
      if (is_node < 0) {
        Py_DECREF(eseq);
        goto fail;
      }
      if (!is_node) continue;
      PyObject* child = PyObject_GetAttr(e, s_node);
      if (!child) {
        Py_DECREF(eseq);
        goto fail;
      }
      PyObject* cur = PyDict_GetItem(deps, child);  // borrowed
      long count = cur ? PyLong_AsLong(cur) : 0;
      PyObject* nv = PyLong_FromLong(count + 1);
      int rc = nv ? PyDict_SetItem(deps, child, nv) : -1;
      Py_XDECREF(nv);
      if (rc < 0) {
        Py_DECREF(child);
        Py_DECREF(eseq);
        goto fail;
      }
      if (!cur) queue.push_back(child);
      Py_DECREF(child);
    }
    Py_DECREF(eseq);
  }

  Py_DECREF(zero);
  Py_DECREF(kind_node);
  Py_DECREF(s_edges);
  Py_DECREF(s_kind);
  Py_DECREF(s_node);
  Py_DECREF(seq);
  return deps;

fail:
  Py_XDECREF(zero);
  Py_XDECREF(kind_node);
  Py_XDECREF(s_edges);
  Py_XDECREF(s_kind);
  Py_XDECREF(s_node);
  Py_DECREF(deps);
  Py_DECREF(seq);
  return nullptr;
}

PyMethodDef methods[] = {
    {"attrs_key", attrs_key, METH_VARARGS,
     "Canonical (name, backend, sorted attrs) executable-cache key; "
     "None if any attr value needs the python fallback."},
    {"discover", discover, METH_VARARGS,
     "Backward-engine in-degree BFS over GradNode.edges."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "pt_eager_core",
                      "Eager hot-path primitives (csrc/eager_core.cc).",
                      -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit_pt_eager_core(void) {
  return PyModule_Create(&module);
}
