// Eager hot-path primitives as a CPython extension.
//
// The reference keeps eager dispatch and the autograd walk in C++
// (phi/core/kernel_factory.h:316 SelectKernelOrThrowError,
// fluid/eager/backward.cc:106 RunBackward); this module is the
// TPU-native equivalent of the pieces that still cost python time per
// op after XLA owns the math:
//
//   attrs_key(name, backend, attrs) — the canonical executable-cache
//       key (KernelKey construction): sorted (k, v) attr tuple built in
//       one C pass. Returns None for attr values outside the primitive
//       set so the caller can fall back to the python path.
//   discover(roots)               — the backward engine's in-degree BFS
//       (RunBackward's node_in_degree_map): one C loop over .edges.
//
// THE NATIVE RECORD CORE (_core/lazy.py's record hot path in C —
// every entry point stands alone in pure python when this library is
// unavailable, and the two prongs are benched separately in
// bench_suite row 17):
//
//   sorted_attrs(attrs)      — attrs-only canonical key: one-pass
//       sorted (k, v) tuple interned in a C-side pool (None for exotic
//       values -> python fallback), the per-record half of attrs_key.
//   sig_entry(entry)         — content-intern of one per-op segment
//       signature entry; pool CLEARED past 65536 entries (the python
//       pool's overflow rule — identity compares degrade to equality,
//       never correctness; pinned in tests/test_record_fastpath.py).
//   aval_cache_get/put/clear — the record-time out-aval cache keyed by
//       (op, backend, attrs-key, per-aval atoms): the key is built in
//       one C pass over INTERNED (shape, dtype-str, weak_type) atoms
//       and probed with zero python-level tuple construction.
//   bind_types(...)          — one-time registration of the LazyRef /
//       Tensor / AutogradMeta / _PendingOp classes skel_record mints.
//   skel_record(ctx, ctups, in_sig, op, ts, attrs, ige) — trace-stable
//       skeleton replay of ONE record: validates (op, attrs, input
//       wiring, grad intent) against the retained skeleton op,
//       registers fresh external inputs, and constructs the LazyRef /
//       Tensor outputs + _PendingOp from the skeleton's cached avals —
//       no jax, no aval inference. Returns the out-tensor tuple, None
//       on a mismatch (the caller falls back to the full record path),
//       or NotImplemented to punt to the python fast path (exotic
//       attrs / unexpected object shapes). NOTHING is mutated unless
//       the whole op validated.
//
// Plain CPython C API (no pybind per the build rules); compiled into
// its own extension .so by _core/native.py next to libpaddle_tpu_rt.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <vector>

namespace {

// ---- interned pools + bound types (module-lifetime globals)
PyObject* g_dtype_str = nullptr;     // dtype obj -> str(dtype)
PyObject* g_atom_intern = nullptr;   // (shape, dstr, weak) -> itself
PyObject* g_aval_cache = nullptr;    // aval key -> out-aval tuple
PyObject* g_entry_intern = nullptr;  // sig entry -> itself
PyObject* g_attrs_intern = nullptr;  // sorted attrs tuple -> itself
PyObject* g_lazyref_t = nullptr;     // lazy.LazyRef
PyObject* g_tensor_t = nullptr;      // tensor.Tensor
PyObject* g_agmeta_t = nullptr;      // autograd.AutogradMeta
PyObject* g_pending_t = nullptr;     // lazy._PendingOp
PyObject* g_tracer_t = nullptr;      // jax.core.Tracer (optional)

constexpr Py_ssize_t kAvalCacheCap = 65536;
constexpr Py_ssize_t kEntryCap = 65536;
constexpr Py_ssize_t kAttrsCap = 8192;

PyObject* intern_str(const char* s) { return PyUnicode_InternFromString(s); }

// interned attribute-name strings (filled at module init)
PyObject* g_one = nullptr;  // cached small-int 1
PyObject *s_skel_pos, *s_fast_ops, *s_ops_recorded;
PyObject *s_payload, *s_shape, *s_dtype, *s_weak_type, *s_stop_gradient,
    *s_autograd_meta, *s_inplace_version, *s_ctx, *s_op_idx, *s_slot,
    *s_aval, *s_requires_grad, *s_trefs, *s_in_ids, *s_in_tensors,
    *s_in_pins, *s_in_vals, *s_in_meta, *s_pending_attr, *s_sig_ops,
    *s_on_flush, *s_grad, *s_grad_node, *s_out_slot, *s_hooks,
    *s_retain_grads, *s_name_attr, *s_persistable, *s_dist_attr, *s_op,
    *s_attrs, *s_wiring, *s_out_refs, *s_n_outs, *s_src, *s_is_lazy_ref;

// value is cache-key-safe if hashable AND compares by value:
// primitives and tuples thereof. (Lists/dicts/arrays -> python path.)
bool key_safe(PyObject* v) {
  if (v == Py_None || PyBool_Check(v) || PyLong_Check(v) ||
      PyFloat_Check(v) || PyUnicode_Check(v) || PyBytes_Check(v)) {
    return true;
  }
  if (PyTuple_Check(v)) {
    Py_ssize_t n = PyTuple_GET_SIZE(v);
    for (Py_ssize_t i = 0; i < n; ++i) {
      if (!key_safe(PyTuple_GET_ITEM(v, i))) return false;
    }
    return true;
  }
  return false;
}

PyObject* attrs_key(PyObject*, PyObject* args) {
  PyObject* name;
  PyObject* backend;
  PyObject* attrs;
  if (!PyArg_ParseTuple(args, "OOO!", &name, &backend, &PyDict_Type,
                        &attrs)) {
    return nullptr;
  }

  Py_ssize_t n = PyDict_Size(attrs);
  std::vector<std::pair<PyObject*, PyObject*>> items;
  items.reserve(n);
  PyObject *k, *v;
  Py_ssize_t pos = 0;
  while (PyDict_Next(attrs, &pos, &k, &v)) {
    if (!PyUnicode_Check(k) || !key_safe(v)) {
      Py_RETURN_NONE;  // exotic attr: python fallback builds the key
    }
    items.emplace_back(k, v);
  }
  std::sort(items.begin(), items.end(),
            [](const std::pair<PyObject*, PyObject*>& a,
               const std::pair<PyObject*, PyObject*>& b) {
              return PyUnicode_Compare(a.first, b.first) < 0;
            });

  PyObject* inner = PyTuple_New(n);
  if (!inner) return nullptr;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* pair = PyTuple_New(2);
    if (!pair) {
      Py_DECREF(inner);
      return nullptr;
    }
    Py_INCREF(items[i].first);
    Py_INCREF(items[i].second);
    PyTuple_SET_ITEM(pair, 0, items[i].first);
    PyTuple_SET_ITEM(pair, 1, items[i].second);
    PyTuple_SET_ITEM(inner, i, pair);
  }

  PyObject* key = PyTuple_New(3);
  if (!key) {
    Py_DECREF(inner);
    return nullptr;
  }
  Py_INCREF(name);
  Py_INCREF(backend);
  PyTuple_SET_ITEM(key, 0, name);
  PyTuple_SET_ITEM(key, 1, backend);
  PyTuple_SET_ITEM(key, 2, inner);
  return key;
}

// discover(roots: list[GradNode]) -> dict {node: in_degree}
// Mirrors autograd._discover: BFS over node.edges; an edge object with
// .kind == "node" contributes one in-degree to .node.
PyObject* discover(PyObject*, PyObject* args) {
  PyObject* roots;
  if (!PyArg_ParseTuple(args, "O", &roots)) return nullptr;
  PyObject* seq = PySequence_Fast(roots, "discover expects a sequence");
  if (!seq) return nullptr;

  PyObject* deps = PyDict_New();
  if (!deps) {
    Py_DECREF(seq);
    return nullptr;
  }
  PyObject* zero = PyLong_FromLong(0);
  PyObject* kind_node = PyUnicode_InternFromString("node");
  PyObject* s_edges = PyUnicode_InternFromString("edges");
  PyObject* s_kind = PyUnicode_InternFromString("kind");
  PyObject* s_node = PyUnicode_InternFromString("node");

  std::vector<PyObject*> queue;  // borrowed refs kept alive by deps
  Py_ssize_t nroots = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < nroots; ++i) {
    PyObject* r = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyDict_Contains(deps, r)) {
      if (PyDict_SetItem(deps, r, zero) < 0) goto fail;
      queue.push_back(r);
    }
  }

  for (size_t qi = 0; qi < queue.size(); ++qi) {
    PyObject* node = queue[qi];
    PyObject* edges = PyObject_GetAttr(node, s_edges);
    if (!edges) goto fail;
    PyObject* eseq = PySequence_Fast(edges, "edges must be a sequence");
    Py_DECREF(edges);
    if (!eseq) goto fail;
    Py_ssize_t ne = PySequence_Fast_GET_SIZE(eseq);
    for (Py_ssize_t i = 0; i < ne; ++i) {
      PyObject* e = PySequence_Fast_GET_ITEM(eseq, i);
      PyObject* kind = PyObject_GetAttr(e, s_kind);
      if (!kind) {
        Py_DECREF(eseq);
        goto fail;
      }
      int is_node = PyObject_RichCompareBool(kind, kind_node, Py_EQ);
      Py_DECREF(kind);
      if (is_node < 0) {
        Py_DECREF(eseq);
        goto fail;
      }
      if (!is_node) continue;
      PyObject* child = PyObject_GetAttr(e, s_node);
      if (!child) {
        Py_DECREF(eseq);
        goto fail;
      }
      PyObject* cur = PyDict_GetItem(deps, child);  // borrowed
      long count = cur ? PyLong_AsLong(cur) : 0;
      PyObject* nv = PyLong_FromLong(count + 1);
      int rc = nv ? PyDict_SetItem(deps, child, nv) : -1;
      Py_XDECREF(nv);
      if (rc < 0) {
        Py_DECREF(child);
        Py_DECREF(eseq);
        goto fail;
      }
      if (!cur) queue.push_back(child);
      Py_DECREF(child);
    }
    Py_DECREF(eseq);
  }

  Py_DECREF(zero);
  Py_DECREF(kind_node);
  Py_DECREF(s_edges);
  Py_DECREF(s_kind);
  Py_DECREF(s_node);
  Py_DECREF(seq);
  return deps;

fail:
  Py_XDECREF(zero);
  Py_XDECREF(kind_node);
  Py_XDECREF(s_edges);
  Py_XDECREF(s_kind);
  Py_XDECREF(s_node);
  Py_DECREF(deps);
  Py_DECREF(seq);
  return nullptr;
}

// ------------------------------------------------- native record core

// intern `obj` in `pool` (cap -> clear, the python overflow rule).
// Returns a NEW reference to the canonical object, or null on error.
PyObject* pool_intern(PyObject* pool, PyObject* obj, Py_ssize_t cap) {
  PyObject* found = PyDict_GetItem(pool, obj);  // borrowed, no errors
  if (found) {
    Py_INCREF(found);
    return found;
  }
  if (PyDict_Size(pool) > cap) PyDict_Clear(pool);
  if (PyDict_SetItem(pool, obj, obj) < 0) return nullptr;
  Py_INCREF(obj);
  return obj;
}

// sorted_attrs(attrs: dict) -> interned ((k, v), ...) | None (exotic)
PyObject* sorted_attrs(PyObject*, PyObject* args) {
  PyObject* attrs;
  if (!PyArg_ParseTuple(args, "O!", &PyDict_Type, &attrs)) return nullptr;
  Py_ssize_t n = PyDict_Size(attrs);
  std::vector<std::pair<PyObject*, PyObject*>> items;
  items.reserve(n);
  PyObject *k, *v;
  Py_ssize_t pos = 0;
  while (PyDict_Next(attrs, &pos, &k, &v)) {
    if (!PyUnicode_Check(k) || !key_safe(v)) Py_RETURN_NONE;
    items.emplace_back(k, v);
  }
  std::sort(items.begin(), items.end(),
            [](const std::pair<PyObject*, PyObject*>& a,
               const std::pair<PyObject*, PyObject*>& b) {
              return PyUnicode_Compare(a.first, b.first) < 0;
            });
  PyObject* key = PyTuple_New(n);
  if (!key) return nullptr;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* pair = PyTuple_New(2);
    if (!pair) {
      Py_DECREF(key);
      return nullptr;
    }
    Py_INCREF(items[i].first);
    Py_INCREF(items[i].second);
    PyTuple_SET_ITEM(pair, 0, items[i].first);
    PyTuple_SET_ITEM(pair, 1, items[i].second);
    PyTuple_SET_ITEM(key, i, pair);
  }
  PyObject* interned = pool_intern(g_attrs_intern, key, kAttrsCap);
  Py_DECREF(key);
  return interned;
}

// sig_entry(entry: tuple) -> the interned canonical entry
PyObject* sig_entry(PyObject*, PyObject* args) {
  PyObject* entry;
  if (!PyArg_ParseTuple(args, "O", &entry)) return nullptr;
  return pool_intern(g_entry_intern, entry, kEntryCap);
}

// str(dtype) memoized per dtype object. NEW reference.
PyObject* dtype_str(PyObject* dt) {
  PyObject* s = PyDict_GetItem(g_dtype_str, dt);  // borrowed
  if (s) {
    Py_INCREF(s);
    return s;
  }
  s = PyObject_Str(dt);
  if (!s) return nullptr;
  if (PyDict_SetItem(g_dtype_str, dt, s) < 0) {
    Py_DECREF(s);
    return nullptr;
  }
  return s;
}

// (tuple(shape), str(dtype), weak_type) atom for one aval, interned.
// NEW reference; null on error (caller clears + falls back).
PyObject* aval_atom(PyObject* a) {
  PyObject* shape = PyObject_GetAttr(a, s_shape);
  if (!shape) return nullptr;
  if (!PyTuple_Check(shape)) {
    PyObject* t = PySequence_Tuple(shape);
    Py_DECREF(shape);
    if (!t) return nullptr;
    shape = t;
  }
  PyObject* dt = PyObject_GetAttr(a, s_dtype);
  if (!dt) {
    Py_DECREF(shape);
    return nullptr;
  }
  PyObject* ds = dtype_str(dt);
  Py_DECREF(dt);
  if (!ds) {
    Py_DECREF(shape);
    return nullptr;
  }
  PyObject* weak = PyObject_GetAttr(a, s_weak_type);
  if (!weak) {
    PyErr_Clear();
    weak = Py_False;
    Py_INCREF(weak);
  }
  PyObject* atom = PyTuple_New(3);
  if (!atom) {
    Py_DECREF(shape);
    Py_DECREF(ds);
    Py_DECREF(weak);
    return nullptr;
  }
  PyTuple_SET_ITEM(atom, 0, shape);
  PyTuple_SET_ITEM(atom, 1, ds);
  PyTuple_SET_ITEM(atom, 2, weak);
  PyObject* interned = pool_intern(g_atom_intern, atom, kAvalCacheCap);
  Py_DECREF(atom);
  return interned;
}

// (name, backend, akey, (atom|None, ...)) — NEW reference.
PyObject* build_aval_key(PyObject* name, PyObject* backend, PyObject* akey,
                         PyObject* avals) {
  PyObject* seq = PySequence_Fast(avals, "avals must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject* atoms = PyTuple_New(n);
  if (!atoms) {
    Py_DECREF(seq);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PySequence_Fast_GET_ITEM(seq, i);
    if (a == Py_None) {
      Py_INCREF(Py_None);
      PyTuple_SET_ITEM(atoms, i, Py_None);
      continue;
    }
    PyObject* atom = aval_atom(a);
    if (!atom) {
      Py_DECREF(atoms);
      Py_DECREF(seq);
      return nullptr;
    }
    PyTuple_SET_ITEM(atoms, i, atom);
  }
  Py_DECREF(seq);
  PyObject* key = PyTuple_New(4);
  if (!key) {
    Py_DECREF(atoms);
    return nullptr;
  }
  Py_INCREF(name);
  Py_INCREF(backend);
  Py_INCREF(akey);
  PyTuple_SET_ITEM(key, 0, name);
  PyTuple_SET_ITEM(key, 1, backend);
  PyTuple_SET_ITEM(key, 2, akey);
  PyTuple_SET_ITEM(key, 3, atoms);
  return key;
}

// aval_cache_get(name, backend, akey, avals) -> outs tuple | None
PyObject* aval_cache_get(PyObject*, PyObject* args) {
  PyObject *name, *backend, *akey, *avals;
  if (!PyArg_ParseTuple(args, "OOOO", &name, &backend, &akey, &avals)) {
    return nullptr;
  }
  PyObject* key = build_aval_key(name, backend, akey, avals);
  if (!key) return nullptr;
  PyObject* v = PyDict_GetItem(g_aval_cache, key);  // borrowed
  Py_DECREF(key);
  if (v) {
    Py_INCREF(v);
    return v;
  }
  Py_RETURN_NONE;
}

// aval_cache_put(name, backend, akey, avals, outs[, cap]) — `cap`
// (FLAGS_executable_cache_capacity, read by the cold-path caller)
// bounds the pool: past it the cache clears in full (simple-clear
// rather than LRU — inserts are compile-path cold). 0/absent = the
// built-in 65536 ceiling.
PyObject* aval_cache_put(PyObject*, PyObject* args) {
  PyObject *name, *backend, *akey, *avals, *outs;
  Py_ssize_t cap = 0;
  if (!PyArg_ParseTuple(args, "OOOOO|n", &name, &backend, &akey, &avals,
                        &outs, &cap)) {
    return nullptr;
  }
  if (cap <= 0 || cap > kAvalCacheCap) cap = kAvalCacheCap;
  PyObject* key = build_aval_key(name, backend, akey, avals);
  if (!key) return nullptr;
  if (PyDict_Size(g_aval_cache) > cap) PyDict_Clear(g_aval_cache);
  int rc = PyDict_SetItem(g_aval_cache, key, outs);
  Py_DECREF(key);
  if (rc < 0) return nullptr;
  Py_RETURN_NONE;
}

PyObject* aval_cache_clear(PyObject*, PyObject*) {
  PyDict_Clear(g_aval_cache);
  Py_RETURN_NONE;
}

PyObject* intern_sizes(PyObject*, PyObject*) {
  return Py_BuildValue(
      "{s:n,s:n,s:n,s:n,s:n}", "aval_cache", PyDict_Size(g_aval_cache),
      "aval_atoms", PyDict_Size(g_atom_intern), "sig_entry",
      PyDict_Size(g_entry_intern), "attrs", PyDict_Size(g_attrs_intern),
      "dtype_str", PyDict_Size(g_dtype_str));
}

// bind_types(LazyRef, Tensor, AutogradMeta, _PendingOp, Tracer)
PyObject* bind_types(PyObject*, PyObject* args) {
  PyObject *lr, *tt, *ag, *po, *tr;
  if (!PyArg_ParseTuple(args, "OOOOO", &lr, &tt, &ag, &po, &tr)) {
    return nullptr;
  }
  Py_XDECREF(g_lazyref_t);
  Py_XDECREF(g_tensor_t);
  Py_XDECREF(g_agmeta_t);
  Py_XDECREF(g_pending_t);
  Py_XDECREF(g_tracer_t);
  Py_INCREF(lr);
  Py_INCREF(tt);
  Py_INCREF(ag);
  Py_INCREF(po);
  Py_INCREF(tr);
  g_lazyref_t = lr;
  g_tensor_t = tt;
  g_agmeta_t = ag;
  g_pending_t = po;
  g_tracer_t = tr;
  Py_RETURN_NONE;
}

// allocate an instance of a bound slots class WITHOUT running __init__
// (the C analog of object.__new__(cls)); slots are filled by SetAttr.
PyObject* alloc_instance(PyObject* type) {
  PyTypeObject* tp = (PyTypeObject*)type;
  return tp->tp_alloc(tp, 0);
}

// set one slot, return false on error
bool set_slot(PyObject* obj, PyObject* name, PyObject* v) {
  return PyObject_SetAttr(obj, name, v) == 0;
}

// the result protocol of skel_record: nullptr = python error raised;
// MISS  -> Py_None (skeleton mismatch, caller takes the full path);
// PUNT  -> Py_NotImplemented (C cannot judge; python fast path decides)
PyObject* miss() { Py_RETURN_NONE; }
PyObject* punt() {
  PyErr_Clear();
  Py_RETURN_NOTIMPLEMENTED;
}

// skel_record(ctx, ctups, in_sig, op, ts, attrs, ige) — see file
// header. Reads and advances ctx._skel_pos itself (and bumps
// ctx._fast_ops / ctx.ops_recorded on success) so the python wrapper
// is one call + one result check per replayed op.
// ctups[pos] = (op, akey, attrs, fast_attrs, wiring, out_avals,
//               out_req, req, has_inexact, entry, n_outs).
PyObject* skel_record(PyObject*, PyObject* const* fargs,
                      Py_ssize_t nargs) {
  if (nargs != 7) {
    PyErr_SetString(PyExc_TypeError, "skel_record expects 7 arguments");
    return nullptr;
  }
  PyObject* ctx = fargs[0];
  PyObject* ctups = fargs[1];
  PyObject* in_sig = fargs[2];
  PyObject* op = fargs[3];
  PyObject* ts = fargs[4];
  PyObject* attrs = fargs[5];
  PyObject* ige = fargs[6];
  if (!PyList_Check(ctups) || !g_lazyref_t) return punt();
  PyObject* pos_o = PyObject_GetAttr(ctx, s_skel_pos);
  if (!pos_o) return punt();
  Py_ssize_t pos = PyLong_AsSsize_t(pos_o);
  Py_DECREF(pos_o);
  if (pos < 0 && PyErr_Occurred()) return punt();
  if (pos >= PyList_GET_SIZE(ctups)) return miss();
  PyObject* ctup = PyList_GET_ITEM(ctups, pos);  // borrowed
  if (!PyTuple_Check(ctup) || PyTuple_GET_SIZE(ctup) != 11) {
    return punt();
  }
  PyObject* skel_op = PyTuple_GET_ITEM(ctup, 0);
  PyObject* s_attrs_d = PyTuple_GET_ITEM(ctup, 2);
  PyObject* fast_attrs = PyTuple_GET_ITEM(ctup, 3);
  PyObject* wiring = PyTuple_GET_ITEM(ctup, 4);
  PyObject* out_avals = PyTuple_GET_ITEM(ctup, 5);
  PyObject* out_req = PyTuple_GET_ITEM(ctup, 6);
  PyObject* s_req = PyTuple_GET_ITEM(ctup, 7);
  PyObject* has_inexact = PyTuple_GET_ITEM(ctup, 8);
  PyObject* entry = PyTuple_GET_ITEM(ctup, 9);

  if (skel_op != op) return miss();
  if (fast_attrs != Py_True) return punt();  // exotic attrs: python path
  if (!PyTuple_Check(wiring)) return punt();
  Py_ssize_t n_in = PyTuple_GET_SIZE(wiring);
  PyObject* tseq = PySequence_Fast(ts, "ts must be a sequence");
  if (!tseq) return punt();
  if (PySequence_Fast_GET_SIZE(tseq) != n_in) {
    Py_DECREF(tseq);
    return miss();
  }
  int eq = PyObject_RichCompareBool(attrs, s_attrs_d, Py_EQ);
  if (eq < 0) {
    Py_DECREF(tseq);
    return punt();
  }
  if (!eq) {
    Py_DECREF(tseq);
    return miss();
  }

  // context state (fresh lists per segment; read once per record)
  PyObject* in_ids = PyObject_GetAttr(ctx, s_in_ids);
  PyObject* in_tensors = PyObject_GetAttr(ctx, s_in_tensors);
  PyObject* in_vals = PyObject_GetAttr(ctx, s_in_vals);
  PyObject* in_meta = PyObject_GetAttr(ctx, s_in_meta);
  PyObject* in_pins = PyObject_GetAttr(ctx, s_in_pins);
  PyObject* on_flush = PyObject_GetAttr(ctx, s_on_flush);
  PyObject* pending = PyObject_GetAttr(ctx, s_pending_attr);
  PyObject* sig_ops = PyObject_GetAttr(ctx, s_sig_ops);
  if (!in_ids || !in_tensors || !in_vals || !in_meta || !in_pins ||
      !on_flush || !pending || !sig_ops || !PyDict_Check(in_ids) ||
      !PyList_Check(in_tensors) || !PyList_Check(in_vals) ||
      !PyList_Check(in_meta) || !PyList_Check(in_pins) ||
      !PyList_Check(pending) || !PyList_Check(sig_ops)) {
    Py_XDECREF(in_ids);
    Py_XDECREF(in_tensors);
    Py_XDECREF(in_vals);
    Py_XDECREF(in_meta);
    Py_XDECREF(in_pins);
    Py_XDECREF(on_flush);
    Py_XDECREF(pending);
    Py_XDECREF(sig_ops);
    Py_DECREF(tseq);
    return punt();
  }

  struct Cleanup {
    std::vector<PyObject*> owned;
    ~Cleanup() {
      for (PyObject* o : owned) Py_XDECREF(o);
    }
  } cl;
  cl.owned = {in_ids, in_tensors, in_vals, in_meta, in_pins,
              on_flush,  pending,   sig_ops, tseq};

  Py_ssize_t base_in = PyList_GET_SIZE(in_vals);
  std::vector<PyObject*> new_ext;  // borrowed (alive via tseq/ts)
  bool req = false;
  bool result_miss = false;
  bool result_punt = false;

  for (Py_ssize_t i = 0; i < n_in; ++i) {
    PyObject* t = PySequence_Fast_GET_ITEM(tseq, i);  // borrowed
    PyObject* w = PyTuple_GET_ITEM(wiring, i);        // borrowed
    if (t == Py_None) {
      if (w != Py_None) {
        result_miss = true;
        break;
      }
      continue;
    }
    PyObject* p = PyObject_GetAttr(t, s_payload);
    if (!p) {
      result_punt = true;
      break;
    }
    if (Py_TYPE(p) == (PyTypeObject*)g_lazyref_t) {
      // op-ref input: must point at the same (op, slot) of THIS ctx
      PyObject* pctx = PyObject_GetAttr(p, s_ctx);
      PyObject* pidx = PyObject_GetAttr(p, s_op_idx);
      PyObject* pslot = PyObject_GetAttr(p, s_slot);
      PyObject* preq = PyObject_GetAttr(p, s_requires_grad);
      bool ok = pctx && pidx && pslot && preq;
      bool match = false;
      if (ok && pctx == ctx && pidx != Py_None && w != Py_None &&
          PyTuple_Check(w) && PyTuple_GET_SIZE(w) == 3) {
        PyObject* w0 = PyTuple_GET_ITEM(w, 0);
        int is_op = PyUnicode_Check(w0) &&
                    PyUnicode_CompareWithASCIIString(w0, "op") == 0;
        if (is_op &&
            PyObject_RichCompareBool(PyTuple_GET_ITEM(w, 1), pidx,
                                     Py_EQ) == 1 &&
            PyObject_RichCompareBool(PyTuple_GET_ITEM(w, 2), pslot,
                                     Py_EQ) == 1) {
          match = true;
          if (preq == Py_True) req = true;
        }
      }
      Py_XDECREF(pctx);
      Py_XDECREF(pidx);
      Py_XDECREF(pslot);
      Py_XDECREF(preq);
      Py_DECREF(p);
      if (!ok) {
        result_punt = true;
        break;
      }
      if (!match) {
        result_miss = true;
        break;
      }
      continue;
    }
    // tracer payload: the op runs under an enclosing jax trace and
    // must NEVER be recorded into the fusion window — punt so the
    // executor's slow path dispatches it inline (its own tracer scan
    // re-detects this)
    if (g_tracer_t && PyObject_TypeCheck(p, (PyTypeObject*)g_tracer_t)) {
      Py_DECREF(p);
      result_punt = true;
      break;
    }
    // external input: wiring must be ("in", idx) at the index this
    // tensor lands on, with the sealed in-signature's aval when fresh
    if (w == Py_None || !PyTuple_Check(w) || PyTuple_GET_SIZE(w) != 2) {
      Py_DECREF(p);
      result_miss = true;
      break;
    }
    {
      PyObject* w0 = PyTuple_GET_ITEM(w, 0);
      if (!PyUnicode_Check(w0) ||
          PyUnicode_CompareWithASCIIString(w0, "in") != 0) {
        Py_DECREF(p);
        result_miss = true;
        break;
      }
    }
    Py_ssize_t widx = PyLong_AsSsize_t(PyTuple_GET_ITEM(w, 1));
    if (widx < 0 && PyErr_Occurred()) {
      Py_DECREF(p);
      result_punt = true;
      break;
    }
    PyObject* idkey = PyLong_FromVoidPtr(t);
    if (!idkey) {
      Py_DECREF(p);
      result_punt = true;
      break;
    }
    PyObject* idxo = PyDict_GetItem(in_ids, idkey);  // borrowed
    Py_ssize_t idx = -1;
    if (idxo) {
      idx = PyLong_AsSsize_t(idxo);
      // validate against id() reuse: the weakref at that slot must
      // still point at THIS tensor
      if (idx >= 0 && idx < PyList_GET_SIZE(in_tensors)) {
        PyObject* wr = PyList_GET_ITEM(in_tensors, idx);
        if (!PyWeakref_Check(wr)) {
          Py_DECREF(idkey);
          Py_DECREF(p);
          result_punt = true;
          break;
        }
        if (PyWeakref_GetObject(wr) != t) idx = -1;
      } else {
        idx = -1;
      }
    }
    if (idx < 0) {
      // not registered yet — maybe earlier in THIS op's operand list
      for (size_t k = 0; k < new_ext.size(); ++k) {
        if (new_ext[k] == t) {
          idx = base_in + (Py_ssize_t)k;
          break;
        }
      }
    }
    if (idx < 0) {
      idx = base_in + (Py_ssize_t)new_ext.size();
      // fresh registration: validate the payload aval against the
      // sealed segment's in-signature at this index
      if (!PyTuple_Check(in_sig) || idx >= PyTuple_GET_SIZE(in_sig)) {
        Py_DECREF(idkey);
        Py_DECREF(p);
        result_miss = true;
        break;
      }
      PyObject* isig = PyTuple_GET_ITEM(in_sig, idx);
      if (!PyTuple_Check(isig) || PyTuple_GET_SIZE(isig) != 3) {
        Py_DECREF(idkey);
        Py_DECREF(p);
        result_punt = true;
        break;
      }
      PyObject* atom = aval_atom(p);
      if (!atom) {
        Py_DECREF(idkey);
        Py_DECREF(p);
        result_punt = true;
        break;
      }
      // atom = (shape, dstr, weak); isig = (shape, dstr, weak_bool)
      int m1 = PyObject_RichCompareBool(PyTuple_GET_ITEM(atom, 0),
                                        PyTuple_GET_ITEM(isig, 0), Py_EQ);
      int m2 = PyObject_RichCompareBool(PyTuple_GET_ITEM(atom, 1),
                                        PyTuple_GET_ITEM(isig, 1), Py_EQ);
      int w_truth = PyObject_IsTrue(PyTuple_GET_ITEM(atom, 2));
      int s_truth = PyObject_IsTrue(PyTuple_GET_ITEM(isig, 2));
      Py_DECREF(atom);
      if (m1 < 0 || m2 < 0 || w_truth < 0 || s_truth < 0) {
        Py_DECREF(idkey);
        Py_DECREF(p);
        result_punt = true;
        break;
      }
      if (m1 != 1 || m2 != 1 || w_truth != s_truth) {
        Py_DECREF(idkey);
        Py_DECREF(p);
        result_miss = true;
        break;
      }
      new_ext.push_back(t);
    }
    Py_DECREF(idkey);
    Py_DECREF(p);
    if (widx != idx) {
      result_miss = true;
      break;
    }
    PyObject* sg = PyObject_GetAttr(t, s_stop_gradient);
    if (!sg) {
      result_punt = true;
      break;
    }
    if (sg == Py_False) req = true;
    Py_DECREF(sg);
  }
  if (result_punt) return punt();
  if (result_miss) return miss();

  if (has_inexact == Py_True) {
    bool effective = false;
    if (req) {
      PyObject* g = PyObject_CallObject(ige, nullptr);
      if (!g) return punt();
      int truth = PyObject_IsTrue(g);
      Py_DECREF(g);
      if (truth < 0) return punt();
      effective = truth == 1;
    }
    if (effective != (s_req == Py_True)) return miss();
  }

  // ---- commit (everything validated; nothing was mutated above)
  bool pinned = on_flush != Py_None;
  for (size_t k = 0; k < new_ext.size(); ++k) {
    PyObject* t = new_ext[k];
    PyObject* idkey = PyLong_FromVoidPtr(t);
    PyObject* idxo = PyLong_FromSsize_t(base_in + (Py_ssize_t)k);
    PyObject* wr = idkey && idxo ? PyWeakref_NewRef(t, nullptr) : nullptr;
    PyObject* p = wr ? PyObject_GetAttr(t, s_payload) : nullptr;
    PyObject* sg = p ? PyObject_GetAttr(t, s_stop_gradient) : nullptr;
    PyObject* ag = sg ? PyObject_GetAttr(t, s_autograd_meta) : nullptr;
    PyObject* iv = ag ? PyObject_GetAttr(t, s_inplace_version) : nullptr;
    PyObject* meta = nullptr;
    if (iv) {
      meta = PyTuple_New(3);
      if (meta) {
        PyObject* nreq = sg == Py_True ? Py_False : Py_True;
        Py_INCREF(nreq);
        PyTuple_SET_ITEM(meta, 0, nreq);
        Py_INCREF(ag);
        PyTuple_SET_ITEM(meta, 1, ag);
        Py_INCREF(iv);
        PyTuple_SET_ITEM(meta, 2, iv);
      }
    }
    bool ok = meta && PyDict_SetItem(in_ids, idkey, idxo) == 0 &&
              PyList_Append(in_tensors, wr) == 0 &&
              (!pinned || PyList_Append(in_pins, t) == 0) &&
              PyList_Append(in_vals, p) == 0 &&
              PyList_Append(in_meta, meta) == 0;
    Py_XDECREF(idkey);
    Py_XDECREF(idxo);
    Py_XDECREF(wr);
    Py_XDECREF(p);
    Py_XDECREF(sg);
    Py_XDECREF(ag);
    Py_XDECREF(iv);
    Py_XDECREF(meta);
    if (!ok) return nullptr;  // commit failed: propagate (fatal)
  }

  Py_ssize_t op_idx = PyList_GET_SIZE(pending);
  Py_ssize_t n_outs = PyTuple_GET_SIZE(out_avals);
  PyObject* op_idx_o = PyLong_FromSsize_t(op_idx);
  PyObject* out_refs = PyList_New(n_outs);
  PyObject* outs = PyTuple_New(n_outs);
  if (!op_idx_o || !out_refs || !outs) {
    Py_XDECREF(op_idx_o);
    Py_XDECREF(out_refs);
    Py_XDECREF(outs);
    return nullptr;
  }
  PyObject* zero = PyLong_FromLong(0);
  bool ok = zero != nullptr;
  for (Py_ssize_t slot = 0; ok && slot < n_outs; ++slot) {
    PyObject* rg = PyTuple_GET_ITEM(out_req, slot);      // borrowed bool
    PyObject* aval = PyTuple_GET_ITEM(out_avals, slot);  // borrowed
    PyObject* slot_o = PyLong_FromSsize_t(slot);
    PyObject* trefs = PyList_New(0);
    PyObject* ref = alloc_instance(g_lazyref_t);
    ok = slot_o && trefs && ref && set_slot(ref, s_ctx, ctx) &&
         set_slot(ref, s_op_idx, op_idx_o) &&
         set_slot(ref, s_slot, slot_o) && set_slot(ref, s_aval, aval) &&
         set_slot(ref, s_requires_grad, rg) &&
         set_slot(ref, s_trefs, trefs);
    PyObject* meta = ok ? alloc_instance(g_agmeta_t) : nullptr;
    ok = ok && meta && set_slot(meta, s_grad, Py_None) &&
         set_slot(meta, s_grad_node, Py_None) &&
         set_slot(meta, s_out_slot, zero);
    PyObject* hooks = ok ? PyList_New(0) : nullptr;
    ok = ok && hooks && set_slot(meta, s_hooks, hooks) &&
         set_slot(meta, s_retain_grads, Py_False);
    PyObject* tensor = ok ? alloc_instance(g_tensor_t) : nullptr;
    ok = ok && tensor && set_slot(tensor, s_payload, ref) &&
         set_slot(tensor, s_stop_gradient,
                  rg == Py_True ? Py_False : Py_True) &&
         set_slot(tensor, s_autograd_meta, meta) &&
         set_slot(tensor, s_inplace_version, zero) &&
         set_slot(tensor, s_name_attr, Py_None) &&
         set_slot(tensor, s_persistable, Py_False) &&
         set_slot(tensor, s_dist_attr, Py_None);
    // ref.add_tref(tensor): the alias backref is a weakref
    if (ok) {
      PyObject* twr = PyWeakref_NewRef(tensor, nullptr);
      ok = twr && PyList_Append(trefs, twr) == 0;
      Py_XDECREF(twr);
    }
    if (ok) {
      Py_INCREF(ref);
      PyList_SET_ITEM(out_refs, slot, ref);
      Py_INCREF(tensor);
      PyTuple_SET_ITEM(outs, slot, tensor);
    }
    Py_XDECREF(slot_o);
    Py_XDECREF(trefs);
    Py_XDECREF(ref);
    Py_XDECREF(meta);
    Py_XDECREF(hooks);
    Py_XDECREF(tensor);
  }
  PyObject* pop = ok ? alloc_instance(g_pending_t) : nullptr;
  PyObject* n_outs_o = ok ? PyLong_FromSsize_t(n_outs) : nullptr;
  ok = ok && pop && n_outs_o && set_slot(pop, s_op, op) &&
       set_slot(pop, s_attrs, s_attrs_d) &&
       set_slot(pop, s_wiring, wiring) &&
       set_slot(pop, s_out_refs, out_refs) &&
       set_slot(pop, s_n_outs, n_outs_o) &&
       set_slot(pop, s_src, Py_None) && PyList_Append(pending, pop) == 0 &&
       PyList_Append(sig_ops, entry) == 0;
  Py_XDECREF(pop);
  Py_XDECREF(n_outs_o);
  Py_XDECREF(op_idx_o);
  Py_XDECREF(out_refs);
  Py_XDECREF(zero);
  if (!ok) {
    Py_DECREF(outs);
    return nullptr;
  }
  // advance the replay cursor + per-segment / lifetime counters so the
  // python wrapper is one call per replayed op
  PyObject* next_pos = PyLong_FromSsize_t(pos + 1);
  ok = next_pos && PyObject_SetAttr(ctx, s_skel_pos, next_pos) == 0;
  Py_XDECREF(next_pos);
  for (PyObject* ctr : {s_fast_ops, s_ops_recorded}) {
    if (!ok) break;
    PyObject* cur = PyObject_GetAttr(ctx, ctr);
    ok = cur != nullptr;
    if (ok) {
      PyObject* inc = PyNumber_Add(cur, g_one);
      ok = inc && PyObject_SetAttr(ctx, ctr, inc) == 0;
      Py_XDECREF(inc);
      Py_DECREF(cur);
    }
  }
  if (!ok) {
    Py_DECREF(outs);
    return nullptr;
  }
  return outs;
}

PyMethodDef methods[] = {
    {"attrs_key", attrs_key, METH_VARARGS,
     "Canonical (name, backend, sorted attrs) executable-cache key; "
     "None if any attr value needs the python fallback."},
    {"discover", discover, METH_VARARGS,
     "Backward-engine in-degree BFS over GradNode.edges."},
    {"sorted_attrs", sorted_attrs, METH_VARARGS,
     "Interned attrs-only canonical key; None for exotic values."},
    {"sig_entry", sig_entry, METH_VARARGS,
     "Content-intern one per-op segment signature entry (pool cleared "
     "past 65536 entries)."},
    {"aval_cache_get", aval_cache_get, METH_VARARGS,
     "Record-time out-aval cache probe: key built in one C pass over "
     "interned (shape, dtype-str, weak_type) atoms."},
    {"aval_cache_put", aval_cache_put, METH_VARARGS,
     "Insert one out-aval tuple under the C-built key."},
    {"aval_cache_clear", aval_cache_clear, METH_NOARGS,
     "Drop every cached out-aval entry."},
    {"intern_sizes", intern_sizes, METH_NOARGS,
     "Sizes of the C-side intern pools (tests)."},
    {"bind_types", bind_types, METH_VARARGS,
     "Register the LazyRef/Tensor/AutogradMeta/_PendingOp classes "
     "skel_record constructs."},
    {"skel_record", (PyCFunction)(void (*)())skel_record, METH_FASTCALL,
     "Trace-stable skeleton replay of one record: validate against the "
     "retained skeleton op and mint the outputs from its cached avals. "
     "Returns outs | None (mismatch) | NotImplemented (punt)."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "pt_eager_core",
                      "Eager hot-path primitives (csrc/eager_core.cc).",
                      -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit_pt_eager_core(void) {
  g_dtype_str = PyDict_New();
  g_atom_intern = PyDict_New();
  g_aval_cache = PyDict_New();
  g_entry_intern = PyDict_New();
  g_attrs_intern = PyDict_New();
  if (!g_dtype_str || !g_atom_intern || !g_aval_cache || !g_entry_intern ||
      !g_attrs_intern) {
    return nullptr;
  }
  g_one = PyLong_FromLong(1);
  if (!g_one) return nullptr;
  s_skel_pos = intern_str("_skel_pos");
  s_fast_ops = intern_str("_fast_ops");
  s_ops_recorded = intern_str("ops_recorded");
  s_payload = intern_str("_payload");
  s_shape = intern_str("shape");
  s_dtype = intern_str("dtype");
  s_weak_type = intern_str("weak_type");
  s_stop_gradient = intern_str("_stop_gradient");
  s_autograd_meta = intern_str("_autograd_meta");
  s_inplace_version = intern_str("_inplace_version");
  s_ctx = intern_str("ctx");
  s_op_idx = intern_str("op_idx");
  s_slot = intern_str("slot");
  s_aval = intern_str("aval");
  s_requires_grad = intern_str("requires_grad");
  s_trefs = intern_str("trefs");
  s_in_ids = intern_str("_in_ids");
  s_in_tensors = intern_str("_in_tensors");
  s_in_pins = intern_str("_in_pins");
  s_in_vals = intern_str("_in_vals");
  s_in_meta = intern_str("_in_meta");
  s_pending_attr = intern_str("pending");
  s_sig_ops = intern_str("_sig_ops");
  s_on_flush = intern_str("on_flush");
  s_grad = intern_str("grad");
  s_grad_node = intern_str("grad_node");
  s_out_slot = intern_str("out_slot");
  s_hooks = intern_str("hooks");
  s_retain_grads = intern_str("retain_grads");
  s_name_attr = intern_str("name");
  s_persistable = intern_str("persistable");
  s_dist_attr = intern_str("_dist_attr");
  s_op = intern_str("op");
  s_attrs = intern_str("attrs");
  s_wiring = intern_str("wiring");
  s_out_refs = intern_str("out_refs");
  s_n_outs = intern_str("n_outs");
  s_src = intern_str("src");
  s_is_lazy_ref = intern_str("_is_lazy_ref");
  return PyModule_Create(&module);
}
