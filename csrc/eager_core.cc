// Eager hot-path primitives as a CPython extension.
//
// The reference keeps eager dispatch and the autograd walk in C++
// (phi/core/kernel_factory.h:316 SelectKernelOrThrowError,
// fluid/eager/backward.cc:106 RunBackward); this module is the
// TPU-native equivalent of the pieces that still cost python time per
// op after XLA owns the math:
//
//   attrs_key(name, backend, attrs) — the canonical executable-cache
//       key (KernelKey construction): sorted (k, v) attr tuple built in
//       one C pass. Returns None for attr values outside the primitive
//       set so the caller can fall back to the python path.
//   discover(roots)               — the backward engine's in-degree BFS
//       (RunBackward's node_in_degree_map): one C loop over .edges.
//
// THE NATIVE RECORD CORE (_core/lazy.py's record hot path in C —
// every entry point stands alone in pure python when this library is
// unavailable, and the two prongs are benched separately in
// bench_suite row 17):
//
//   sorted_attrs(attrs)      — attrs-only canonical key: one-pass
//       sorted (k, v) tuple interned in a C-side pool (None for exotic
//       values -> python fallback), the per-record half of attrs_key.
//   sig_entry(entry)         — content-intern of one per-op segment
//       signature entry; pool CLEARED past 65536 entries (the python
//       pool's overflow rule — identity compares degrade to equality,
//       never correctness; pinned in tests/test_record_fastpath.py).
//   aval_cache_get/put/clear — the record-time out-aval cache keyed by
//       (op, backend, attrs-key, per-aval atoms): the key is built in
//       one C pass over INTERNED (shape, dtype-str, weak_type) atoms
//       and probed with zero python-level tuple construction.
//   bind_types(...)          — one-time registration of the LazyRef /
//       Tensor / AutogradMeta / _PendingOp classes skel_record mints.
//   skel_record(ctx, ctups, in_sig, op, ts, attrs, ige) — trace-stable
//       skeleton replay of ONE record: validates (op, attrs, input
//       wiring, grad intent) against the retained skeleton op,
//       registers fresh external inputs, and constructs the LazyRef /
//       Tensor outputs + _PendingOp from the skeleton's cached avals —
//       no jax, no aval inference. Returns the out-tensor tuple, None
//       on a mismatch (the caller falls back to the full record path),
//       or NotImplemented to punt to the python fast path (exotic
//       attrs / unexpected object shapes). NOTHING is mutated unless
//       the whole op validated.
//   bind_drive(...) / drive_record(drv, op_name, inputs, attrs, ige) —
//       the WHOLE-STEP driver (zero-python steady state): once the
//       executor arms a lazy._DriveState in lazy._DRIVE_CELL, ONE
//       fastcall per dispatched op coerces the raw operands (exact
//       Tensors pass; python scalars resolve through the live
//       executor._SCALAR_TENSORS wrapper cache), resolves the op from
//       the registry, validates + commits through the same replay core
//       as skel_record against the plan cursor held IN the drive
//       state, and returns the final user-facing value (multi_output
//       unwrap included). Per-op counters batch in the state and write
//       back at retire; the driver retires itself — clearing the cell
//       and restoring ctx._skel_pos — on plan completion, segment cap
//       (it then calls ctx.flush("segment_cap")), a generation bump
//       (lazy._FAST_GEN_CELL mirrors every mechanical invalidation),
//       and ANY mismatch, which falls back to the ordinary gate.
//
// Plain CPython C API (no pybind per the build rules); compiled into
// its own extension .so by _core/native.py next to libpaddle_tpu_rt.
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <structmember.h>

#include <algorithm>
#include <cmath>
#include <vector>

namespace {

// ---- interned pools + bound types (module-lifetime globals)
PyObject* g_dtype_str = nullptr;     // dtype obj -> str(dtype)
PyObject* g_atom_intern = nullptr;   // (shape, dstr, weak) -> itself
PyObject* g_aval_cache = nullptr;    // aval key -> out-aval tuple
PyObject* g_entry_intern = nullptr;  // sig entry -> itself
PyObject* g_attrs_intern = nullptr;  // sorted attrs tuple -> itself
PyObject* g_lazyref_t = nullptr;     // lazy.LazyRef
PyObject* g_tensor_t = nullptr;      // tensor.Tensor
PyObject* g_agmeta_t = nullptr;      // autograd.AutogradMeta
PyObject* g_pending_t = nullptr;     // lazy._PendingOp
PyObject* g_tracer_t = nullptr;      // jax.core.Tracer (optional)

constexpr Py_ssize_t kAvalCacheCap = 65536;
constexpr Py_ssize_t kEntryCap = 65536;
constexpr Py_ssize_t kAttrsCap = 8192;

PyObject* intern_str(const char* s) { return PyUnicode_InternFromString(s); }

// ---- whole-step driver handles (filled by bind_drive)
PyObject* g_drive_t = nullptr;         // lazy._DriveState
PyObject* g_ops = nullptr;             // op_registry._OPS (live dict)
PyObject* g_scalar_tensors = nullptr;  // executor._SCALAR_TENSORS (live)
PyObject* g_gen_cell = nullptr;        // lazy._FAST_GEN_CELL ([gen])
PyObject* g_drive_cell = nullptr;      // lazy._DRIVE_CELL ([state|None])
PyObject* g_lazy_mod = nullptr;        // the lazy module (FAST_OPS)
bool g_drive_ok = false;

// resolved _DriveState slot offsets (all must resolve or the driver
// stays off — bind_drive returns False and lazy keeps _DRIVE_OK False)
struct DriveSlots {
  Py_ssize_t ctx = -1, ctups = -1, in_sig = -1, in_ids = -1,
             in_tensors = -1, in_vals = -1, in_meta = -1, in_pins = -1,
             pending = -1, sig_ops = -1, pinned = -1, pos = -1, gen = -1,
             cap = -1, n_driven = -1, tid = -1, sc_k = -1, sc_v = -1;
};
DriveSlots g_d;

// interned attribute-name strings (filled at module init)
PyObject* g_one = nullptr;        // cached small-int 1
PyObject* g_float_pos = nullptr;  // cached 1.0 / -1.0 (scalar sign keys)
PyObject* g_float_neg = nullptr;
PyObject *s_skel_pos, *s_fast_ops, *s_ops_recorded, *s_multi_output,
    *s_FAST_OPS, *s_dpos, *s_dn, *s_wtag_in, *s_wtag_op;
PyObject *s_payload, *s_shape, *s_dtype, *s_weak_type, *s_stop_gradient,
    *s_autograd_meta, *s_inplace_version, *s_ctx, *s_op_idx, *s_slot,
    *s_aval, *s_requires_grad, *s_trefs, *s_in_ids, *s_in_tensors,
    *s_in_pins, *s_in_vals, *s_in_meta, *s_pending_attr, *s_sig_ops,
    *s_on_flush, *s_grad, *s_grad_node, *s_out_slot, *s_hooks,
    *s_retain_grads, *s_name_attr, *s_persistable, *s_dist_attr, *s_op,
    *s_attrs, *s_wiring, *s_out_refs, *s_n_outs, *s_src, *s_is_lazy_ref;

// ---- resolved __slots__ member offsets (filled by bind_types)
//
// The four classes skel_record reads/mints (Tensor, LazyRef,
// AutogradMeta, _PendingOp) are all __slots__ classes, so every
// attribute is a member descriptor with a fixed byte offset inside the
// instance. Resolving those offsets ONCE lets the hot loop read and
// write slots as direct pointer loads/stores instead of paying
// PyObject_GetAttr/SetAttr's MRO lookup + descriptor dispatch per
// attribute (~20 attr ops per minted op). Any slot that fails to
// resolve — a monkeypatched class, a future slot rename — keeps
// offset -1 and that ONE attribute falls back to the generic path, so
// the optimization can never change semantics.
struct SlotTable {
  // Tensor
  Py_ssize_t t_payload = -1, t_stop_gradient = -1, t_autograd_meta = -1,
             t_inplace_version = -1, t_name = -1, t_persistable = -1,
             t_dist_attr = -1;
  // LazyRef
  Py_ssize_t r_ctx = -1, r_op_idx = -1, r_slot = -1, r_aval = -1,
             r_requires_grad = -1, r_trefs = -1;
  // AutogradMeta
  Py_ssize_t m_grad = -1, m_grad_node = -1, m_out_slot = -1, m_hooks = -1,
             m_retain_grads = -1;
  // _PendingOp
  Py_ssize_t p_op = -1, p_attrs = -1, p_wiring = -1, p_out_refs = -1,
             p_n_outs = -1, p_src = -1;
};
SlotTable g_off;

// offset of one T_OBJECT_EX member descriptor, -1 = use generic attrs
Py_ssize_t slot_offset(PyObject* type, PyObject* name) {
  PyObject* d = PyObject_GetAttr(type, name);
  if (!d) {
    PyErr_Clear();
    return -1;
  }
  Py_ssize_t off = -1;
  if (Py_TYPE(d) == &PyMemberDescr_Type) {
    PyMemberDef* m = ((PyMemberDescrObject*)d)->d_member;
    if (m && m->type == T_OBJECT_EX && !(m->flags & READONLY)) {
      off = m->offset;
    }
  }
  Py_DECREF(d);
  return off;
}

// value is cache-key-safe if hashable AND compares by value:
// primitives and tuples thereof. (Lists/dicts/arrays -> python path.)
bool key_safe(PyObject* v) {
  if (v == Py_None || PyBool_Check(v) || PyLong_Check(v) ||
      PyFloat_Check(v) || PyUnicode_Check(v) || PyBytes_Check(v)) {
    return true;
  }
  if (PyTuple_Check(v)) {
    Py_ssize_t n = PyTuple_GET_SIZE(v);
    for (Py_ssize_t i = 0; i < n; ++i) {
      if (!key_safe(PyTuple_GET_ITEM(v, i))) return false;
    }
    return true;
  }
  return false;
}

PyObject* attrs_key(PyObject*, PyObject* args) {
  PyObject* name;
  PyObject* backend;
  PyObject* attrs;
  if (!PyArg_ParseTuple(args, "OOO!", &name, &backend, &PyDict_Type,
                        &attrs)) {
    return nullptr;
  }

  Py_ssize_t n = PyDict_Size(attrs);
  std::vector<std::pair<PyObject*, PyObject*>> items;
  items.reserve(n);
  PyObject *k, *v;
  Py_ssize_t pos = 0;
  while (PyDict_Next(attrs, &pos, &k, &v)) {
    if (!PyUnicode_Check(k) || !key_safe(v)) {
      Py_RETURN_NONE;  // exotic attr: python fallback builds the key
    }
    items.emplace_back(k, v);
  }
  std::sort(items.begin(), items.end(),
            [](const std::pair<PyObject*, PyObject*>& a,
               const std::pair<PyObject*, PyObject*>& b) {
              return PyUnicode_Compare(a.first, b.first) < 0;
            });

  PyObject* inner = PyTuple_New(n);
  if (!inner) return nullptr;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* pair = PyTuple_New(2);
    if (!pair) {
      Py_DECREF(inner);
      return nullptr;
    }
    Py_INCREF(items[i].first);
    Py_INCREF(items[i].second);
    PyTuple_SET_ITEM(pair, 0, items[i].first);
    PyTuple_SET_ITEM(pair, 1, items[i].second);
    PyTuple_SET_ITEM(inner, i, pair);
  }

  PyObject* key = PyTuple_New(3);
  if (!key) {
    Py_DECREF(inner);
    return nullptr;
  }
  Py_INCREF(name);
  Py_INCREF(backend);
  PyTuple_SET_ITEM(key, 0, name);
  PyTuple_SET_ITEM(key, 1, backend);
  PyTuple_SET_ITEM(key, 2, inner);
  return key;
}

// discover(roots: list[GradNode]) -> dict {node: in_degree}
// Mirrors autograd._discover: BFS over node.edges; an edge object with
// .kind == "node" contributes one in-degree to .node.
PyObject* discover(PyObject*, PyObject* args) {
  PyObject* roots;
  if (!PyArg_ParseTuple(args, "O", &roots)) return nullptr;
  PyObject* seq = PySequence_Fast(roots, "discover expects a sequence");
  if (!seq) return nullptr;

  PyObject* deps = PyDict_New();
  if (!deps) {
    Py_DECREF(seq);
    return nullptr;
  }
  PyObject* zero = PyLong_FromLong(0);
  PyObject* kind_node = PyUnicode_InternFromString("node");
  PyObject* s_edges = PyUnicode_InternFromString("edges");
  PyObject* s_kind = PyUnicode_InternFromString("kind");
  PyObject* s_node = PyUnicode_InternFromString("node");

  std::vector<PyObject*> queue;  // borrowed refs kept alive by deps
  Py_ssize_t nroots = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < nroots; ++i) {
    PyObject* r = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyDict_Contains(deps, r)) {
      if (PyDict_SetItem(deps, r, zero) < 0) goto fail;
      queue.push_back(r);
    }
  }

  for (size_t qi = 0; qi < queue.size(); ++qi) {
    PyObject* node = queue[qi];
    PyObject* edges = PyObject_GetAttr(node, s_edges);
    if (!edges) goto fail;
    PyObject* eseq = PySequence_Fast(edges, "edges must be a sequence");
    Py_DECREF(edges);
    if (!eseq) goto fail;
    Py_ssize_t ne = PySequence_Fast_GET_SIZE(eseq);
    for (Py_ssize_t i = 0; i < ne; ++i) {
      PyObject* e = PySequence_Fast_GET_ITEM(eseq, i);
      PyObject* kind = PyObject_GetAttr(e, s_kind);
      if (!kind) {
        Py_DECREF(eseq);
        goto fail;
      }
      int is_node = PyObject_RichCompareBool(kind, kind_node, Py_EQ);
      Py_DECREF(kind);
      if (is_node < 0) {
        Py_DECREF(eseq);
        goto fail;
      }
      if (!is_node) continue;
      PyObject* child = PyObject_GetAttr(e, s_node);
      if (!child) {
        Py_DECREF(eseq);
        goto fail;
      }
      PyObject* cur = PyDict_GetItem(deps, child);  // borrowed
      long count = cur ? PyLong_AsLong(cur) : 0;
      PyObject* nv = PyLong_FromLong(count + 1);
      int rc = nv ? PyDict_SetItem(deps, child, nv) : -1;
      Py_XDECREF(nv);
      if (rc < 0) {
        Py_DECREF(child);
        Py_DECREF(eseq);
        goto fail;
      }
      if (!cur) queue.push_back(child);
      Py_DECREF(child);
    }
    Py_DECREF(eseq);
  }

  Py_DECREF(zero);
  Py_DECREF(kind_node);
  Py_DECREF(s_edges);
  Py_DECREF(s_kind);
  Py_DECREF(s_node);
  Py_DECREF(seq);
  return deps;

fail:
  Py_XDECREF(zero);
  Py_XDECREF(kind_node);
  Py_XDECREF(s_edges);
  Py_XDECREF(s_kind);
  Py_XDECREF(s_node);
  Py_DECREF(deps);
  Py_DECREF(seq);
  return nullptr;
}

// ------------------------------------------------- native record core

// intern `obj` in `pool` (cap -> clear, the python overflow rule).
// Returns a NEW reference to the canonical object, or null on error.
PyObject* pool_intern(PyObject* pool, PyObject* obj, Py_ssize_t cap) {
  PyObject* found = PyDict_GetItem(pool, obj);  // borrowed, no errors
  if (found) {
    Py_INCREF(found);
    return found;
  }
  if (PyDict_Size(pool) > cap) PyDict_Clear(pool);
  if (PyDict_SetItem(pool, obj, obj) < 0) return nullptr;
  Py_INCREF(obj);
  return obj;
}

// sorted_attrs(attrs: dict) -> interned ((k, v), ...) | None (exotic)
PyObject* sorted_attrs(PyObject*, PyObject* args) {
  PyObject* attrs;
  if (!PyArg_ParseTuple(args, "O!", &PyDict_Type, &attrs)) return nullptr;
  Py_ssize_t n = PyDict_Size(attrs);
  std::vector<std::pair<PyObject*, PyObject*>> items;
  items.reserve(n);
  PyObject *k, *v;
  Py_ssize_t pos = 0;
  while (PyDict_Next(attrs, &pos, &k, &v)) {
    if (!PyUnicode_Check(k) || !key_safe(v)) Py_RETURN_NONE;
    items.emplace_back(k, v);
  }
  std::sort(items.begin(), items.end(),
            [](const std::pair<PyObject*, PyObject*>& a,
               const std::pair<PyObject*, PyObject*>& b) {
              return PyUnicode_Compare(a.first, b.first) < 0;
            });
  PyObject* key = PyTuple_New(n);
  if (!key) return nullptr;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* pair = PyTuple_New(2);
    if (!pair) {
      Py_DECREF(key);
      return nullptr;
    }
    Py_INCREF(items[i].first);
    Py_INCREF(items[i].second);
    PyTuple_SET_ITEM(pair, 0, items[i].first);
    PyTuple_SET_ITEM(pair, 1, items[i].second);
    PyTuple_SET_ITEM(key, i, pair);
  }
  PyObject* interned = pool_intern(g_attrs_intern, key, kAttrsCap);
  Py_DECREF(key);
  return interned;
}

// sig_entry(entry: tuple) -> the interned canonical entry
PyObject* sig_entry(PyObject*, PyObject* args) {
  PyObject* entry;
  if (!PyArg_ParseTuple(args, "O", &entry)) return nullptr;
  return pool_intern(g_entry_intern, entry, kEntryCap);
}

// str(dtype) memoized per dtype object. NEW reference.
PyObject* dtype_str(PyObject* dt) {
  PyObject* s = PyDict_GetItem(g_dtype_str, dt);  // borrowed
  if (s) {
    Py_INCREF(s);
    return s;
  }
  s = PyObject_Str(dt);
  if (!s) return nullptr;
  if (PyDict_SetItem(g_dtype_str, dt, s) < 0) {
    Py_DECREF(s);
    return nullptr;
  }
  return s;
}

// (tuple(shape), str(dtype), weak_type) atom for one aval, interned.
// NEW reference; null on error (caller clears + falls back).
PyObject* aval_atom(PyObject* a) {
  PyObject* shape = PyObject_GetAttr(a, s_shape);
  if (!shape) return nullptr;
  if (!PyTuple_Check(shape)) {
    PyObject* t = PySequence_Tuple(shape);
    Py_DECREF(shape);
    if (!t) return nullptr;
    shape = t;
  }
  PyObject* dt = PyObject_GetAttr(a, s_dtype);
  if (!dt) {
    Py_DECREF(shape);
    return nullptr;
  }
  PyObject* ds = dtype_str(dt);
  Py_DECREF(dt);
  if (!ds) {
    Py_DECREF(shape);
    return nullptr;
  }
  PyObject* weak = PyObject_GetAttr(a, s_weak_type);
  if (!weak) {
    PyErr_Clear();
    weak = Py_False;
    Py_INCREF(weak);
  }
  PyObject* atom = PyTuple_New(3);
  if (!atom) {
    Py_DECREF(shape);
    Py_DECREF(ds);
    Py_DECREF(weak);
    return nullptr;
  }
  PyTuple_SET_ITEM(atom, 0, shape);
  PyTuple_SET_ITEM(atom, 1, ds);
  PyTuple_SET_ITEM(atom, 2, weak);
  PyObject* interned = pool_intern(g_atom_intern, atom, kAvalCacheCap);
  Py_DECREF(atom);
  return interned;
}

// (name, backend, akey, (atom|None, ...)) — NEW reference.
PyObject* build_aval_key(PyObject* name, PyObject* backend, PyObject* akey,
                         PyObject* avals) {
  PyObject* seq = PySequence_Fast(avals, "avals must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject* atoms = PyTuple_New(n);
  if (!atoms) {
    Py_DECREF(seq);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PySequence_Fast_GET_ITEM(seq, i);
    if (a == Py_None) {
      Py_INCREF(Py_None);
      PyTuple_SET_ITEM(atoms, i, Py_None);
      continue;
    }
    PyObject* atom = aval_atom(a);
    if (!atom) {
      Py_DECREF(atoms);
      Py_DECREF(seq);
      return nullptr;
    }
    PyTuple_SET_ITEM(atoms, i, atom);
  }
  Py_DECREF(seq);
  PyObject* key = PyTuple_New(4);
  if (!key) {
    Py_DECREF(atoms);
    return nullptr;
  }
  Py_INCREF(name);
  Py_INCREF(backend);
  Py_INCREF(akey);
  PyTuple_SET_ITEM(key, 0, name);
  PyTuple_SET_ITEM(key, 1, backend);
  PyTuple_SET_ITEM(key, 2, akey);
  PyTuple_SET_ITEM(key, 3, atoms);
  return key;
}

// aval_cache_get(name, backend, akey, avals) -> outs tuple | None
PyObject* aval_cache_get(PyObject*, PyObject* args) {
  PyObject *name, *backend, *akey, *avals;
  if (!PyArg_ParseTuple(args, "OOOO", &name, &backend, &akey, &avals)) {
    return nullptr;
  }
  PyObject* key = build_aval_key(name, backend, akey, avals);
  if (!key) return nullptr;
  PyObject* v = PyDict_GetItem(g_aval_cache, key);  // borrowed
  Py_DECREF(key);
  if (v) {
    Py_INCREF(v);
    return v;
  }
  Py_RETURN_NONE;
}

// aval_cache_put(name, backend, akey, avals, outs[, cap]) — `cap`
// (FLAGS_executable_cache_capacity, read by the cold-path caller)
// bounds the pool: past it the cache clears in full (simple-clear
// rather than LRU — inserts are compile-path cold). 0/absent = the
// built-in 65536 ceiling.
PyObject* aval_cache_put(PyObject*, PyObject* args) {
  PyObject *name, *backend, *akey, *avals, *outs;
  Py_ssize_t cap = 0;
  if (!PyArg_ParseTuple(args, "OOOOO|n", &name, &backend, &akey, &avals,
                        &outs, &cap)) {
    return nullptr;
  }
  if (cap <= 0 || cap > kAvalCacheCap) cap = kAvalCacheCap;
  PyObject* key = build_aval_key(name, backend, akey, avals);
  if (!key) return nullptr;
  if (PyDict_Size(g_aval_cache) > cap) PyDict_Clear(g_aval_cache);
  int rc = PyDict_SetItem(g_aval_cache, key, outs);
  Py_DECREF(key);
  if (rc < 0) return nullptr;
  Py_RETURN_NONE;
}

PyObject* aval_cache_clear(PyObject*, PyObject*) {
  PyDict_Clear(g_aval_cache);
  Py_RETURN_NONE;
}

PyObject* intern_sizes(PyObject*, PyObject*) {
  return Py_BuildValue(
      "{s:n,s:n,s:n,s:n,s:n}", "aval_cache", PyDict_Size(g_aval_cache),
      "aval_atoms", PyDict_Size(g_atom_intern), "sig_entry",
      PyDict_Size(g_entry_intern), "attrs", PyDict_Size(g_attrs_intern),
      "dtype_str", PyDict_Size(g_dtype_str));
}

// bind_types(LazyRef, Tensor, AutogradMeta, _PendingOp, Tracer)
PyObject* bind_types(PyObject*, PyObject* args) {
  PyObject *lr, *tt, *ag, *po, *tr;
  if (!PyArg_ParseTuple(args, "OOOOO", &lr, &tt, &ag, &po, &tr)) {
    return nullptr;
  }
  Py_XDECREF(g_lazyref_t);
  Py_XDECREF(g_tensor_t);
  Py_XDECREF(g_agmeta_t);
  Py_XDECREF(g_pending_t);
  Py_XDECREF(g_tracer_t);
  Py_INCREF(lr);
  Py_INCREF(tt);
  Py_INCREF(ag);
  Py_INCREF(po);
  Py_INCREF(tr);
  g_lazyref_t = lr;
  g_tensor_t = tt;
  g_agmeta_t = ag;
  g_pending_t = po;
  g_tracer_t = tr;
  g_off.t_payload = slot_offset(tt, s_payload);
  g_off.t_stop_gradient = slot_offset(tt, s_stop_gradient);
  g_off.t_autograd_meta = slot_offset(tt, s_autograd_meta);
  g_off.t_inplace_version = slot_offset(tt, s_inplace_version);
  g_off.t_name = slot_offset(tt, s_name_attr);
  g_off.t_persistable = slot_offset(tt, s_persistable);
  g_off.t_dist_attr = slot_offset(tt, s_dist_attr);
  g_off.r_ctx = slot_offset(lr, s_ctx);
  g_off.r_op_idx = slot_offset(lr, s_op_idx);
  g_off.r_slot = slot_offset(lr, s_slot);
  g_off.r_aval = slot_offset(lr, s_aval);
  g_off.r_requires_grad = slot_offset(lr, s_requires_grad);
  g_off.r_trefs = slot_offset(lr, s_trefs);
  g_off.m_grad = slot_offset(ag, s_grad);
  g_off.m_grad_node = slot_offset(ag, s_grad_node);
  g_off.m_out_slot = slot_offset(ag, s_out_slot);
  g_off.m_hooks = slot_offset(ag, s_hooks);
  g_off.m_retain_grads = slot_offset(ag, s_retain_grads);
  g_off.p_op = slot_offset(po, s_op);
  g_off.p_attrs = slot_offset(po, s_attrs);
  g_off.p_wiring = slot_offset(po, s_wiring);
  g_off.p_out_refs = slot_offset(po, s_out_refs);
  g_off.p_n_outs = slot_offset(po, s_n_outs);
  g_off.p_src = slot_offset(po, s_src);
  Py_RETURN_NONE;
}

// allocate an instance of a bound slots class WITHOUT running __init__
// (the C analog of object.__new__(cls)); slots are filled by SetAttr.
PyObject* alloc_instance(PyObject* type) {
  PyTypeObject* tp = (PyTypeObject*)type;
  return tp->tp_alloc(tp, 0);
}

// write one slot of an instance alloc'd from the EXACT bound type
// (direct store at the resolved offset; objects come from tp_alloc so
// unresolved slots are NULL and the generic fallback stays correct)
bool set_slot(PyObject* obj, Py_ssize_t off, PyObject* name, PyObject* v) {
  if (off >= 0) {
    PyObject** addr = (PyObject**)((char*)obj + off);
    Py_INCREF(v);
    PyObject* old = *addr;
    *addr = v;
    Py_XDECREF(old);
    return true;
  }
  return PyObject_SetAttr(obj, name, v) == 0;
}

// read one slot at a resolved offset — the CALLER guarantees obj is an
// exact instance of the type the offset was resolved against; an
// unset slot (or off -1) degrades to the generic lookup. NEW ref.
PyObject* read_slot(PyObject* obj, Py_ssize_t off, PyObject* name) {
  if (off >= 0) {
    PyObject* v = *(PyObject**)((char*)obj + off);
    if (v) {
      Py_INCREF(v);
      return v;
    }
  }
  return PyObject_GetAttr(obj, name);
}

// read a Tensor slot: offsets apply only to EXACT Tensor instances
// (a subclass may re-slot); anything else takes the generic path
PyObject* tensor_slot(PyObject* t, Py_ssize_t off, PyObject* name) {
  if (Py_TYPE(t) != (PyTypeObject*)g_tensor_t) off = -1;
  return read_slot(t, off, name);
}

// the result protocol of skel_record: nullptr = python error raised;
// MISS  -> Py_None (skeleton mismatch, caller takes the full path);
// PUNT  -> Py_NotImplemented (C cannot judge; python fast path decides)
PyObject* miss() { Py_RETURN_NONE; }
PyObject* punt() {
  PyErr_Clear();
  Py_RETURN_NOTIMPLEMENTED;
}

// ---- the shared replay core of skel_record / drive_record.
//
// Judge ONE record against `ctup` (the retained skeleton op at the
// replay cursor) and, if admitted, register fresh external inputs and
// mint the LazyRef/Tensor outputs + _PendingOp from the cached avals.
// `tv` is a C array of the already-coerced operand tensors (borrowed;
// the caller keeps them alive for the duration of the call). The
// CALLER owns cursor advance and counters. Returns the out-tensor
// tuple, or miss()/punt()/nullptr per the skel_record result protocol;
// NOTHING is mutated unless the whole op validated.
// ctup = (op, akey, attrs, fast_attrs, wiring, out_avals, out_req,
//         req, has_inexact, entry, n_outs, multi_output).
PyObject* replay_one(PyObject* ctx, PyObject* ctup, PyObject* in_sig,
                     PyObject* op, PyObject* const* tv, Py_ssize_t n_ts,
                     PyObject* attrs, PyObject* ige, PyObject* in_ids,
                     PyObject* in_tensors, PyObject* in_vals,
                     PyObject* in_meta, PyObject* in_pins, bool pinned,
                     PyObject* pending, PyObject* sig_ops) {
  PyObject* skel_op = PyTuple_GET_ITEM(ctup, 0);
  PyObject* s_attrs_d = PyTuple_GET_ITEM(ctup, 2);
  PyObject* fast_attrs = PyTuple_GET_ITEM(ctup, 3);
  PyObject* wiring = PyTuple_GET_ITEM(ctup, 4);
  PyObject* out_avals = PyTuple_GET_ITEM(ctup, 5);
  PyObject* out_req = PyTuple_GET_ITEM(ctup, 6);
  PyObject* s_req = PyTuple_GET_ITEM(ctup, 7);
  PyObject* has_inexact = PyTuple_GET_ITEM(ctup, 8);
  PyObject* entry = PyTuple_GET_ITEM(ctup, 9);

  if (skel_op != op) return miss();
  if (fast_attrs != Py_True) return punt();  // exotic attrs: python path
  if (!PyTuple_Check(wiring)) return punt();
  Py_ssize_t n_in = PyTuple_GET_SIZE(wiring);
  if (n_ts != n_in) return miss();
  int eq;
  if (PyDict_CheckExact(attrs) && PyDict_CheckExact(s_attrs_d) &&
      PyDict_GET_SIZE(attrs) == 0 && PyDict_GET_SIZE(s_attrs_d) == 0) {
    eq = 1;  // empty-vs-empty (the common elementwise case): no compare
  } else {
    eq = PyObject_RichCompareBool(attrs, s_attrs_d, Py_EQ);
  }
  if (eq < 0) return punt();
  if (!eq) return miss();

  Py_ssize_t base_in = PyList_GET_SIZE(in_vals);
  std::vector<PyObject*> new_ext;  // borrowed (alive via tv)
  bool req = false;
  bool result_miss = false;
  bool result_punt = false;

  for (Py_ssize_t i = 0; i < n_in; ++i) {
    PyObject* t = tv[i];                        // borrowed
    PyObject* w = PyTuple_GET_ITEM(wiring, i);  // borrowed
    if (t == Py_None) {
      if (w != Py_None) {
        result_miss = true;
        break;
      }
      continue;
    }
    PyObject* p = tensor_slot(t, g_off.t_payload, s_payload);
    if (!p) {
      result_punt = true;
      break;
    }
    if (Py_TYPE(p) == (PyTypeObject*)g_lazyref_t) {
      // op-ref input: must point at the same (op, slot) of THIS ctx
      PyObject* pctx = read_slot(p, g_off.r_ctx, s_ctx);
      PyObject* pidx = read_slot(p, g_off.r_op_idx, s_op_idx);
      PyObject* pslot = read_slot(p, g_off.r_slot, s_slot);
      PyObject* preq = read_slot(p, g_off.r_requires_grad,
                                 s_requires_grad);
      bool ok = pctx && pidx && pslot && preq;
      bool match = false;
      if (ok && pctx == ctx && pidx != Py_None && w != Py_None &&
          PyTuple_Check(w) && PyTuple_GET_SIZE(w) == 3) {
        PyObject* w0 = PyTuple_GET_ITEM(w, 0);
        // identity first: wiring tags are source literals, interned by
        // the compiler like our s_wtag_* handles
        int is_op = w0 == s_wtag_op ||
                    (PyUnicode_Check(w0) &&
                     PyUnicode_CompareWithASCIIString(w0, "op") == 0);
        if (is_op &&
            PyObject_RichCompareBool(PyTuple_GET_ITEM(w, 1), pidx,
                                     Py_EQ) == 1 &&
            PyObject_RichCompareBool(PyTuple_GET_ITEM(w, 2), pslot,
                                     Py_EQ) == 1) {
          match = true;
          if (preq == Py_True) req = true;
        }
      }
      Py_XDECREF(pctx);
      Py_XDECREF(pidx);
      Py_XDECREF(pslot);
      Py_XDECREF(preq);
      Py_DECREF(p);
      if (!ok) {
        result_punt = true;
        break;
      }
      if (!match) {
        result_miss = true;
        break;
      }
      continue;
    }
    // tracer payload: the op runs under an enclosing jax trace and
    // must NEVER be recorded into the fusion window — punt so the
    // executor's slow path dispatches it inline (its own tracer scan
    // re-detects this)
    if (g_tracer_t && PyObject_TypeCheck(p, (PyTypeObject*)g_tracer_t)) {
      Py_DECREF(p);
      result_punt = true;
      break;
    }
    // external input: wiring must be ("in", idx) at the index this
    // tensor lands on, with the sealed in-signature's aval when fresh
    if (w == Py_None || !PyTuple_Check(w) || PyTuple_GET_SIZE(w) != 2) {
      Py_DECREF(p);
      result_miss = true;
      break;
    }
    {
      PyObject* w0 = PyTuple_GET_ITEM(w, 0);
      if (w0 != s_wtag_in &&
          (!PyUnicode_Check(w0) ||
           PyUnicode_CompareWithASCIIString(w0, "in") != 0)) {
        Py_DECREF(p);
        result_miss = true;
        break;
      }
    }
    Py_ssize_t widx = PyLong_AsSsize_t(PyTuple_GET_ITEM(w, 1));
    if (widx < 0 && PyErr_Occurred()) {
      Py_DECREF(p);
      result_punt = true;
      break;
    }
    PyObject* idkey = PyLong_FromVoidPtr(t);
    if (!idkey) {
      Py_DECREF(p);
      result_punt = true;
      break;
    }
    PyObject* idxo = PyDict_GetItem(in_ids, idkey);  // borrowed
    Py_ssize_t idx = -1;
    if (idxo) {
      idx = PyLong_AsSsize_t(idxo);
      // validate against id() reuse: the weakref at that slot must
      // still point at THIS tensor
      if (idx >= 0 && idx < PyList_GET_SIZE(in_tensors)) {
        PyObject* wr = PyList_GET_ITEM(in_tensors, idx);
        if (!PyWeakref_Check(wr)) {
          Py_DECREF(idkey);
          Py_DECREF(p);
          result_punt = true;
          break;
        }
        if (PyWeakref_GetObject(wr) != t) idx = -1;
      } else {
        idx = -1;
      }
    }
    if (idx < 0) {
      // not registered yet — maybe earlier in THIS op's operand list
      for (size_t k = 0; k < new_ext.size(); ++k) {
        if (new_ext[k] == t) {
          idx = base_in + (Py_ssize_t)k;
          break;
        }
      }
    }
    if (idx < 0) {
      idx = base_in + (Py_ssize_t)new_ext.size();
      // fresh registration: validate the payload aval against the
      // sealed segment's in-signature at this index
      if (!PyTuple_Check(in_sig) || idx >= PyTuple_GET_SIZE(in_sig)) {
        Py_DECREF(idkey);
        Py_DECREF(p);
        result_miss = true;
        break;
      }
      PyObject* isig = PyTuple_GET_ITEM(in_sig, idx);
      if (!PyTuple_Check(isig) || PyTuple_GET_SIZE(isig) != 3) {
        Py_DECREF(idkey);
        Py_DECREF(p);
        result_punt = true;
        break;
      }
      PyObject* atom = aval_atom(p);
      if (!atom) {
        Py_DECREF(idkey);
        Py_DECREF(p);
        result_punt = true;
        break;
      }
      // atom = (shape, dstr, weak); isig = (shape, dstr, weak_bool)
      int m1 = PyObject_RichCompareBool(PyTuple_GET_ITEM(atom, 0),
                                        PyTuple_GET_ITEM(isig, 0), Py_EQ);
      int m2 = PyObject_RichCompareBool(PyTuple_GET_ITEM(atom, 1),
                                        PyTuple_GET_ITEM(isig, 1), Py_EQ);
      int w_truth = PyObject_IsTrue(PyTuple_GET_ITEM(atom, 2));
      int s_truth = PyObject_IsTrue(PyTuple_GET_ITEM(isig, 2));
      Py_DECREF(atom);
      if (m1 < 0 || m2 < 0 || w_truth < 0 || s_truth < 0) {
        Py_DECREF(idkey);
        Py_DECREF(p);
        result_punt = true;
        break;
      }
      if (m1 != 1 || m2 != 1 || w_truth != s_truth) {
        Py_DECREF(idkey);
        Py_DECREF(p);
        result_miss = true;
        break;
      }
      new_ext.push_back(t);
    }
    Py_DECREF(idkey);
    Py_DECREF(p);
    if (widx != idx) {
      result_miss = true;
      break;
    }
    PyObject* sg = tensor_slot(t, g_off.t_stop_gradient, s_stop_gradient);
    if (!sg) {
      result_punt = true;
      break;
    }
    if (sg == Py_False) req = true;
    Py_DECREF(sg);
  }
  if (result_punt) return punt();
  if (result_miss) return miss();

  if (has_inexact == Py_True) {
    bool effective = false;
    if (req) {
      PyObject* g = PyObject_CallObject(ige, nullptr);
      if (!g) return punt();
      int truth = PyObject_IsTrue(g);
      Py_DECREF(g);
      if (truth < 0) return punt();
      effective = truth == 1;
    }
    if (effective != (s_req == Py_True)) return miss();
  }

  // ---- commit (everything validated; nothing was mutated above)
  for (size_t k = 0; k < new_ext.size(); ++k) {
    PyObject* t = new_ext[k];
    PyObject* idkey = PyLong_FromVoidPtr(t);
    PyObject* idxo = PyLong_FromSsize_t(base_in + (Py_ssize_t)k);
    PyObject* wr = idkey && idxo ? PyWeakref_NewRef(t, nullptr) : nullptr;
    PyObject* p = wr ? tensor_slot(t, g_off.t_payload, s_payload) : nullptr;
    PyObject* sg =
        p ? tensor_slot(t, g_off.t_stop_gradient, s_stop_gradient) : nullptr;
    PyObject* ag =
        sg ? tensor_slot(t, g_off.t_autograd_meta, s_autograd_meta) : nullptr;
    PyObject* iv = ag ? tensor_slot(t, g_off.t_inplace_version,
                                    s_inplace_version)
                      : nullptr;
    PyObject* meta = nullptr;
    if (iv) {
      meta = PyTuple_New(3);
      if (meta) {
        PyObject* nreq = sg == Py_True ? Py_False : Py_True;
        Py_INCREF(nreq);
        PyTuple_SET_ITEM(meta, 0, nreq);
        Py_INCREF(ag);
        PyTuple_SET_ITEM(meta, 1, ag);
        Py_INCREF(iv);
        PyTuple_SET_ITEM(meta, 2, iv);
      }
    }
    bool ok = meta && PyDict_SetItem(in_ids, idkey, idxo) == 0 &&
              PyList_Append(in_tensors, wr) == 0 &&
              (!pinned || PyList_Append(in_pins, t) == 0) &&
              PyList_Append(in_vals, p) == 0 &&
              PyList_Append(in_meta, meta) == 0;
    Py_XDECREF(idkey);
    Py_XDECREF(idxo);
    Py_XDECREF(wr);
    Py_XDECREF(p);
    Py_XDECREF(sg);
    Py_XDECREF(ag);
    Py_XDECREF(iv);
    Py_XDECREF(meta);
    if (!ok) return nullptr;  // commit failed: propagate (fatal)
  }

  Py_ssize_t op_idx = PyList_GET_SIZE(pending);
  Py_ssize_t n_outs = PyTuple_GET_SIZE(out_avals);
  PyObject* op_idx_o = PyLong_FromSsize_t(op_idx);
  PyObject* out_refs = PyList_New(n_outs);
  PyObject* outs = PyTuple_New(n_outs);
  if (!op_idx_o || !out_refs || !outs) {
    Py_XDECREF(op_idx_o);
    Py_XDECREF(out_refs);
    Py_XDECREF(outs);
    return nullptr;
  }
  PyObject* zero = PyLong_FromLong(0);
  bool ok = zero != nullptr;
  for (Py_ssize_t slot = 0; ok && slot < n_outs; ++slot) {
    PyObject* rg = PyTuple_GET_ITEM(out_req, slot);      // borrowed bool
    PyObject* aval = PyTuple_GET_ITEM(out_avals, slot);  // borrowed
    PyObject* slot_o = PyLong_FromSsize_t(slot);
    PyObject* trefs = PyList_New(0);
    PyObject* ref = alloc_instance(g_lazyref_t);
    ok = slot_o && trefs && ref &&
         set_slot(ref, g_off.r_ctx, s_ctx, ctx) &&
         set_slot(ref, g_off.r_op_idx, s_op_idx, op_idx_o) &&
         set_slot(ref, g_off.r_slot, s_slot, slot_o) &&
         set_slot(ref, g_off.r_aval, s_aval, aval) &&
         set_slot(ref, g_off.r_requires_grad, s_requires_grad, rg) &&
         set_slot(ref, g_off.r_trefs, s_trefs, trefs);
    PyObject* meta = ok ? alloc_instance(g_agmeta_t) : nullptr;
    ok = ok && meta && set_slot(meta, g_off.m_grad, s_grad, Py_None) &&
         set_slot(meta, g_off.m_grad_node, s_grad_node, Py_None) &&
         set_slot(meta, g_off.m_out_slot, s_out_slot, zero);
    PyObject* hooks = ok ? PyList_New(0) : nullptr;
    ok = ok && hooks && set_slot(meta, g_off.m_hooks, s_hooks, hooks) &&
         set_slot(meta, g_off.m_retain_grads, s_retain_grads, Py_False);
    PyObject* tensor = ok ? alloc_instance(g_tensor_t) : nullptr;
    ok = ok && tensor &&
         set_slot(tensor, g_off.t_payload, s_payload, ref) &&
         set_slot(tensor, g_off.t_stop_gradient, s_stop_gradient,
                  rg == Py_True ? Py_False : Py_True) &&
         set_slot(tensor, g_off.t_autograd_meta, s_autograd_meta, meta) &&
         set_slot(tensor, g_off.t_inplace_version, s_inplace_version,
                  zero) &&
         set_slot(tensor, g_off.t_name, s_name_attr, Py_None) &&
         set_slot(tensor, g_off.t_persistable, s_persistable, Py_False) &&
         set_slot(tensor, g_off.t_dist_attr, s_dist_attr, Py_None);
    // ref.add_tref(tensor): the alias backref is a weakref
    if (ok) {
      PyObject* twr = PyWeakref_NewRef(tensor, nullptr);
      ok = twr && PyList_Append(trefs, twr) == 0;
      Py_XDECREF(twr);
    }
    if (ok) {
      Py_INCREF(ref);
      PyList_SET_ITEM(out_refs, slot, ref);
      Py_INCREF(tensor);
      PyTuple_SET_ITEM(outs, slot, tensor);
    }
    Py_XDECREF(slot_o);
    Py_XDECREF(trefs);
    Py_XDECREF(ref);
    Py_XDECREF(meta);
    Py_XDECREF(hooks);
    Py_XDECREF(tensor);
  }
  PyObject* pop = ok ? alloc_instance(g_pending_t) : nullptr;
  PyObject* n_outs_o = ok ? PyLong_FromSsize_t(n_outs) : nullptr;
  ok = ok && pop && n_outs_o && set_slot(pop, g_off.p_op, s_op, op) &&
       set_slot(pop, g_off.p_attrs, s_attrs, s_attrs_d) &&
       set_slot(pop, g_off.p_wiring, s_wiring, wiring) &&
       set_slot(pop, g_off.p_out_refs, s_out_refs, out_refs) &&
       set_slot(pop, g_off.p_n_outs, s_n_outs, n_outs_o) &&
       set_slot(pop, g_off.p_src, s_src, Py_None) &&
       PyList_Append(pending, pop) == 0 &&
       PyList_Append(sig_ops, entry) == 0;
  Py_XDECREF(pop);
  Py_XDECREF(n_outs_o);
  Py_XDECREF(op_idx_o);
  Py_XDECREF(out_refs);
  Py_XDECREF(zero);
  if (!ok) {
    Py_DECREF(outs);
    return nullptr;
  }
  return outs;
}

// skel_record(ctx, ctups, in_sig, op, ts, attrs, ige) — see file
// header. Fetches the context's segment state, delegates validation +
// commit to replay_one, then advances ctx._skel_pos and bumps
// ctx._fast_ops / ctx.ops_recorded itself so the python wrapper is one
// call + one result check per replayed op.
PyObject* skel_record(PyObject*, PyObject* const* fargs,
                      Py_ssize_t nargs) {
  if (nargs != 7) {
    PyErr_SetString(PyExc_TypeError, "skel_record expects 7 arguments");
    return nullptr;
  }
  PyObject* ctx = fargs[0];
  PyObject* ctups = fargs[1];
  PyObject* in_sig = fargs[2];
  PyObject* op = fargs[3];
  PyObject* ts = fargs[4];
  PyObject* attrs = fargs[5];
  PyObject* ige = fargs[6];
  if (!PyList_Check(ctups) || !g_lazyref_t) return punt();
  PyObject* pos_o = PyObject_GetAttr(ctx, s_skel_pos);
  if (!pos_o) return punt();
  Py_ssize_t pos = PyLong_AsSsize_t(pos_o);
  Py_DECREF(pos_o);
  if (pos < 0 && PyErr_Occurred()) return punt();
  if (pos >= PyList_GET_SIZE(ctups)) return miss();
  PyObject* ctup = PyList_GET_ITEM(ctups, pos);  // borrowed
  if (!PyTuple_Check(ctup) || PyTuple_GET_SIZE(ctup) != 12) {
    return punt();
  }
  PyObject* tseq = PySequence_Fast(ts, "ts must be a sequence");
  if (!tseq) return punt();

  // context state (fresh lists per segment; read once per record)
  PyObject* in_ids = PyObject_GetAttr(ctx, s_in_ids);
  PyObject* in_tensors = PyObject_GetAttr(ctx, s_in_tensors);
  PyObject* in_vals = PyObject_GetAttr(ctx, s_in_vals);
  PyObject* in_meta = PyObject_GetAttr(ctx, s_in_meta);
  PyObject* in_pins = PyObject_GetAttr(ctx, s_in_pins);
  PyObject* on_flush = PyObject_GetAttr(ctx, s_on_flush);
  PyObject* pending = PyObject_GetAttr(ctx, s_pending_attr);
  PyObject* sig_ops = PyObject_GetAttr(ctx, s_sig_ops);

  struct Cleanup {
    std::vector<PyObject*> owned;
    ~Cleanup() {
      for (PyObject* o : owned) Py_XDECREF(o);
    }
  } cl;
  cl.owned = {in_ids, in_tensors, in_vals, in_meta, in_pins,
              on_flush,  pending,   sig_ops, tseq};

  if (!in_ids || !in_tensors || !in_vals || !in_meta || !in_pins ||
      !on_flush || !pending || !sig_ops || !PyDict_Check(in_ids) ||
      !PyList_Check(in_tensors) || !PyList_Check(in_vals) ||
      !PyList_Check(in_meta) || !PyList_Check(in_pins) ||
      !PyList_Check(pending) || !PyList_Check(sig_ops)) {
    return punt();
  }

  PyObject* outs = replay_one(
      ctx, ctup, in_sig, op, PySequence_Fast_ITEMS(tseq),
      PySequence_Fast_GET_SIZE(tseq), attrs, ige, in_ids, in_tensors,
      in_vals, in_meta, in_pins, on_flush != Py_None, pending, sig_ops);
  if (!outs || !PyTuple_Check(outs)) return outs;  // error / miss / punt

  // advance the replay cursor + per-segment / lifetime counters so the
  // python wrapper is one call per replayed op
  PyObject* next_pos = PyLong_FromSsize_t(pos + 1);
  bool ok = next_pos && PyObject_SetAttr(ctx, s_skel_pos, next_pos) == 0;
  Py_XDECREF(next_pos);
  for (PyObject* ctr : {s_fast_ops, s_ops_recorded}) {
    if (!ok) break;
    PyObject* cur = PyObject_GetAttr(ctx, ctr);
    ok = cur != nullptr;
    if (ok) {
      PyObject* inc = PyNumber_Add(cur, g_one);
      ok = inc && PyObject_SetAttr(ctx, ctr, inc) == 0;
      Py_XDECREF(inc);
      Py_DECREF(cur);
    }
  }
  if (!ok) {
    Py_DECREF(outs);
    return nullptr;
  }
  return outs;
}

// ------------------------------------------------- whole-step driver

// borrowed read of one resolved _DriveState slot (may be null if the
// slot was never assigned — _arm_drive fills every slot before
// publishing, so null means a foreign object and the driver bails)
inline PyObject* dslot(PyObject* d, Py_ssize_t off) {
  return *(PyObject**)((char*)d + off);
}

// write the driven cursor + batched counters back to the context and
// clear the cell (disarm). Best-effort: preserves any already-raised
// python error, swallows its own. Mirrors lazy._drive_reconcile —
// keep the two in lockstep.
void drive_retire(PyObject* drv) {
  PyObject *et, *ev, *tb;
  PyErr_Fetch(&et, &ev, &tb);
  PyObject* ctx = dslot(drv, g_d.ctx);
  PyObject* pos = dslot(drv, g_d.pos);
  PyObject* nd = dslot(drv, g_d.n_driven);
  if (ctx && pos && PyObject_SetAttr(ctx, s_skel_pos, pos) < 0) {
    PyErr_Clear();
  }
  long n = 0;
  if (nd) {
    n = PyLong_AsLong(nd);
    if (n == -1 && PyErr_Occurred()) {
      PyErr_Clear();
      n = 0;
    }
  }
  if (n > 0 && ctx) {
    PyObject* owners[3] = {ctx, ctx, g_lazy_mod};
    PyObject* names[3] = {s_fast_ops, s_ops_recorded, s_FAST_OPS};
    for (int i = 0; i < 3; ++i) {
      if (!owners[i]) continue;
      PyObject* cur = PyObject_GetAttr(owners[i], names[i]);
      if (!cur) {
        PyErr_Clear();
        continue;
      }
      PyObject* nv = PyNumber_Add(cur, nd);
      Py_DECREF(cur);
      if (!nv) {
        PyErr_Clear();
        continue;
      }
      if (PyObject_SetAttr(owners[i], names[i], nv) < 0) PyErr_Clear();
      Py_DECREF(nv);
    }
    PyObject* zero = PyLong_FromLong(0);
    if (zero) {
      set_slot(drv, g_d.n_driven, s_dn, zero);
      Py_DECREF(zero);
    }
  }
  // disarm: the cell read is the apply() prologue's only gate
  Py_INCREF(Py_None);
  PyList_SetItem(g_drive_cell, 0, Py_None);
  PyErr_Restore(et, ev, tb);
}

// drive_record(drv, op_name, inputs, attrs, ige) — see file header.
// Returns the final user-facing value (Tensor or tuple), None on a
// mismatch/retire (fall through to the full dispatch path) or
// NotImplemented on a punt (ditto). In every non-success case the
// driver has already retired, EXCEPT a cross-thread call, which falls
// through without touching the owning thread's state.
PyObject* drive_record(PyObject*, PyObject* const* fargs,
                       Py_ssize_t nargs) {
  if (nargs != 5) {
    PyErr_SetString(PyExc_TypeError, "drive_record expects 5 arguments");
    return nullptr;
  }
  PyObject* drv = fargs[0];
  PyObject* op_name = fargs[1];
  PyObject* inputs = fargs[2];
  PyObject* attrs = fargs[3];
  PyObject* ige = fargs[4];
  if (!g_drive_ok || !g_drive_cell ||
      Py_TYPE(drv) != (PyTypeObject*)g_drive_t || !PyTuple_Check(inputs) ||
      !PyDict_Check(attrs)) {
    // not a drive state this build understands: disarm so apply()
    // stops paying the prologue, fall through to the full path
    if (g_drive_cell) {
      Py_INCREF(Py_None);
      PyList_SetItem(g_drive_cell, 0, Py_None);
    }
    Py_RETURN_NONE;
  }
  // thread guard: another thread's dispatch must not move this
  // context's cursor — fall through WITHOUT retiring (the owning
  // thread's next op continues the drive)
  PyObject* tid = dslot(drv, g_d.tid);
  if (!tid || !PyLong_Check(tid) ||
      PyLong_AsUnsignedLong(tid) != PyThread_get_thread_ident()) {
    if (PyErr_Occurred()) PyErr_Clear();
    Py_RETURN_NONE;
  }
  // generation guard: mesh epoch bumps and watched-flag flips mirror
  // into g_gen_cell — an in-flight drive retires at its very next op.
  // Identity first (the cell holds the SAME int object the state
  // captured while valid); value equality as the fallback.
  PyObject* gen = dslot(drv, g_d.gen);
  PyObject* cell_gen =
      PyList_GET_SIZE(g_gen_cell) > 0 ? PyList_GET_ITEM(g_gen_cell, 0)
                                      : nullptr;
  if (gen == nullptr || cell_gen == nullptr ||
      (gen != cell_gen &&
       PyObject_RichCompareBool(gen, cell_gen, Py_EQ) != 1)) {
    if (PyErr_Occurred()) PyErr_Clear();
    drive_retire(drv);
    Py_RETURN_NONE;
  }
  PyObject* ctups = dslot(drv, g_d.ctups);
  PyObject* pos_o = dslot(drv, g_d.pos);
  PyObject* pending = dslot(drv, g_d.pending);
  if (!ctups || !pos_o || !pending || !PyList_Check(ctups) ||
      !PyList_Check(pending) || !PyLong_Check(pos_o)) {
    drive_retire(drv);
    Py_RETURN_NONE;
  }
  Py_ssize_t pos = PyLong_AsSsize_t(pos_o);
  Py_ssize_t n_ops = PyList_GET_SIZE(ctups);
  // the cursor must mirror the segment EXACTLY: any op that reached
  // the pending list behind the driver's back (a full-path record, a
  // sanitizer rewrite) breaks whole-step equivalence — demote
  if (pos <= 0 || pos >= n_ops || PyList_GET_SIZE(pending) != pos) {
    if (PyErr_Occurred()) PyErr_Clear();
    drive_retire(drv);
    Py_RETURN_NONE;
  }
  PyObject* ctup = PyList_GET_ITEM(ctups, pos);  // borrowed
  if (!PyTuple_Check(ctup) || PyTuple_GET_SIZE(ctup) != 12) {
    drive_retire(drv);
    Py_RETURN_NOTIMPLEMENTED;
  }
  // multi_output rides in the ctup as canonical True/False — read it
  // BEFORE any retire/flush below can touch the skeleton
  int multi = PyTuple_GET_ITEM(ctup, 11) == Py_True;
  PyObject* op = PyDict_GetItem(g_ops, op_name);  // borrowed
  if (!op) {
    // unknown op: the full path raises the canonical error
    drive_retire(drv);
    Py_RETURN_NOTIMPLEMENTED;
  }
  if (op != PyTuple_GET_ITEM(ctup, 0)) {
    drive_retire(drv);  // stream diverged: per-op gate judges the rest
    Py_RETURN_NONE;
  }
  // C-side operand coercion (apply()'s coerce loop): exact Tensors
  // pass; python scalars resolve through the SHARED wrapper cache —
  // the live executor._SCALAR_TENSORS dict, so eviction can never
  // leave a stale entry here; a cache miss or exotic operand punts to
  // the python coerce (which also REGISTERS the scalar for next time)
  Py_ssize_t n_in = PyTuple_GET_SIZE(inputs);
  PyObject* sc_k = dslot(drv, g_d.sc_k);
  PyObject* sc_v = dslot(drv, g_d.sc_v);
  bool memo_ok = sc_k && sc_v && PyList_CheckExact(sc_k) &&
                 PyList_CheckExact(sc_v);
  PyObject* tv[16];
  Py_ssize_t owned = 0;  // tv[0..owned) hold NEW refs
  bool coerce_punt = n_in > 16;
  for (Py_ssize_t i = 0; !coerce_punt && i < n_in; ++i) {
    PyObject* x = PyTuple_GET_ITEM(inputs, i);
    PyObject* t = nullptr;
    if (Py_TYPE(x) == (PyTypeObject*)g_tensor_t || x == Py_None) {
      t = x;
      Py_INCREF(t);
    } else if (PyFloat_CheckExact(x) || PyLong_CheckExact(x) ||
               PyBool_Check(x)) {
      // per-drive identity memo first: scalar literals keep object
      // identity across iterations (co_consts / small-int interning),
      // and identity implies same type+value+sign, so a hit skips the
      // key-tuple hash probe entirely. The memo lives only as long as
      // this drive, so it can never disagree with the in_ids indices
      // registered through it.
      if (memo_ok) {
        Py_ssize_t nm = PyList_GET_SIZE(sc_k);
        if (PyList_GET_SIZE(sc_v) < nm) nm = PyList_GET_SIZE(sc_v);
        for (Py_ssize_t k = 0; k < nm; ++k) {
          if (PyList_GET_ITEM(sc_k, k) == x) {
            t = PyList_GET_ITEM(sc_v, k);
            Py_INCREF(t);
            break;
          }
        }
      }
      if (!t) {
        // shared wrapper cache (the live executor._SCALAR_TENSORS):
        // float keys carry copysign(1.0, x) so -0.0 stays distinct
        // from +0.0 (hash-equal, division-different)
        PyObject* key;
        if (PyFloat_CheckExact(x)) {
          double dv = PyFloat_AS_DOUBLE(x);
          PyObject* sign = std::signbit(dv) ? g_float_neg : g_float_pos;
          key = PyTuple_Pack(3, (PyObject*)&PyFloat_Type, x, sign);
        } else {
          key = PyTuple_Pack(2, (PyObject*)Py_TYPE(x), x);
        }
        t = key ? PyDict_GetItem(g_scalar_tensors, key) : nullptr;
        Py_XDECREF(key);
        if (t) {
          Py_INCREF(t);
          if (memo_ok && PyList_GET_SIZE(sc_k) < 8) {
            if (PyList_Append(sc_k, x) < 0 ||
                PyList_Append(sc_v, t) < 0) {
              PyErr_Clear();  // memo is best-effort only
            }
          }
        } else {
          coerce_punt = true;  // python _coerce registers it for later
        }
      }
    } else if (PyObject_TypeCheck(x, (PyTypeObject*)g_tensor_t)) {
      t = x;  // Tensor subclass passes through, like python _coerce
      Py_INCREF(t);
    } else {
      coerce_punt = true;  // ndarray / list / foreign scalar
    }
    if (!coerce_punt) tv[owned++] = t;
  }
  if (coerce_punt) {
    for (Py_ssize_t i = 0; i < owned; ++i) Py_DECREF(tv[i]);
    if (PyErr_Occurred()) PyErr_Clear();
    drive_retire(drv);
    Py_RETURN_NOTIMPLEMENTED;
  }
  PyObject* ctx = dslot(drv, g_d.ctx);
  PyObject* in_sig = dslot(drv, g_d.in_sig);
  PyObject* in_ids = dslot(drv, g_d.in_ids);
  PyObject* in_tensors = dslot(drv, g_d.in_tensors);
  PyObject* in_vals = dslot(drv, g_d.in_vals);
  PyObject* in_meta = dslot(drv, g_d.in_meta);
  PyObject* in_pins = dslot(drv, g_d.in_pins);
  PyObject* sig_ops = dslot(drv, g_d.sig_ops);
  PyObject* pinned_o = dslot(drv, g_d.pinned);
  if (!ctx || !in_sig || !in_ids || !in_tensors || !in_vals || !in_meta ||
      !in_pins || !sig_ops || !pinned_o) {
    for (Py_ssize_t i = 0; i < owned; ++i) Py_DECREF(tv[i]);
    drive_retire(drv);
    Py_RETURN_NONE;
  }
  PyObject* outs = replay_one(ctx, ctup, in_sig, op, tv, n_in, attrs,
                              ige, in_ids, in_tensors, in_vals, in_meta,
                              in_pins, pinned_o == Py_True, pending,
                              sig_ops);
  for (Py_ssize_t i = 0; i < owned; ++i) Py_DECREF(tv[i]);
  if (!outs) {
    drive_retire(drv);
    return nullptr;
  }
  if (!PyTuple_Check(outs)) {  // miss (None) / punt (NotImplemented)
    drive_retire(drv);
    return outs;
  }
  // committed: advance the drive cursor + the batched counter in the
  // state (direct slot stores; ctx write-back happens once, at retire)
  PyObject* next = PyLong_FromSsize_t(pos + 1);
  PyObject* nd = dslot(drv, g_d.n_driven);
  PyObject* ndn = nd ? PyNumber_Add(nd, g_one) : nullptr;
  if (!next || !ndn) {
    Py_XDECREF(next);
    Py_XDECREF(ndn);
    Py_DECREF(outs);
    drive_retire(drv);
    return nullptr;
  }
  set_slot(drv, g_d.pos, s_dpos, next);
  set_slot(drv, g_d.n_driven, s_dn, ndn);
  Py_DECREF(next);
  Py_DECREF(ndn);
  if (pos + 1 >= n_ops) {
    // plan complete: retire; the seal happens at the next sync point
    // (lazy._step_plan_sig prices it as segment::replay_step)
    drive_retire(drv);
  } else {
    PyObject* cap_o = dslot(drv, g_d.cap);
    Py_ssize_t cap =
        cap_o && PyLong_Check(cap_o) ? PyLong_AsSsize_t(cap_o) : -1;
    if (cap >= 0 && PyList_GET_SIZE(pending) >= cap) {
      drive_retire(drv);
      PyObject* fr =
          PyObject_CallMethod(ctx, "flush", "(s)", "segment_cap");
      if (!fr) {
        Py_DECREF(outs);
        return nullptr;
      }
      Py_DECREF(fr);
    } else if (cap < 0 && PyErr_Occurred()) {
      PyErr_Clear();
    }
  }
  // unwrap per op.multi_output (the apply() tail)
  if (multi) return outs;
  PyObject* r0 = PyTuple_GET_ITEM(outs, 0);
  Py_INCREF(r0);
  Py_DECREF(outs);
  return r0;
}

// bind_drive(_DriveState, ops, scalar_tensors, gen_cell, drive_cell,
//            lazy_module) -> bool — register the whole-step driver's
// handles and resolve the _DriveState slot offsets. Returns False
// (and keeps the driver off) when any offset fails to resolve.
PyObject* bind_drive(PyObject*, PyObject* args) {
  PyObject *dt, *ops, *scal, *gen_cell, *drive_cell, *lazy_mod;
  if (!PyArg_ParseTuple(args, "OO!O!O!O!O", &dt, &PyDict_Type, &ops,
                        &PyDict_Type, &scal, &PyList_Type, &gen_cell,
                        &PyList_Type, &drive_cell, &lazy_mod)) {
    return nullptr;
  }
  Py_XDECREF(g_drive_t);
  Py_XDECREF(g_ops);
  Py_XDECREF(g_scalar_tensors);
  Py_XDECREF(g_gen_cell);
  Py_XDECREF(g_drive_cell);
  Py_XDECREF(g_lazy_mod);
  Py_INCREF(dt);
  Py_INCREF(ops);
  Py_INCREF(scal);
  Py_INCREF(gen_cell);
  Py_INCREF(drive_cell);
  Py_INCREF(lazy_mod);
  g_drive_t = dt;
  g_ops = ops;
  g_scalar_tensors = scal;
  g_gen_cell = gen_cell;
  g_drive_cell = drive_cell;
  g_lazy_mod = lazy_mod;
  struct Slot {
    Py_ssize_t* off;
    const char* name;
  };
  const Slot slots[] = {
      {&g_d.ctx, "ctx"},           {&g_d.ctups, "ctups"},
      {&g_d.in_sig, "in_sig"},     {&g_d.in_ids, "in_ids"},
      {&g_d.in_tensors, "in_tensors"}, {&g_d.in_vals, "in_vals"},
      {&g_d.in_meta, "in_meta"},   {&g_d.in_pins, "in_pins"},
      {&g_d.pending, "pending"},   {&g_d.sig_ops, "sig_ops"},
      {&g_d.pinned, "pinned"},     {&g_d.pos, "pos"},
      {&g_d.gen, "gen"},           {&g_d.cap, "cap"},
      {&g_d.n_driven, "n_driven"}, {&g_d.tid, "tid"},
      {&g_d.sc_k, "sc_k"},         {&g_d.sc_v, "sc_v"}};
  bool ok = true;
  for (const Slot& s : slots) {
    PyObject* name = intern_str(s.name);
    *s.off = name ? slot_offset(dt, name) : -1;
    Py_XDECREF(name);
    if (*s.off < 0) ok = false;
  }
  g_drive_ok = ok;
  if (ok) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

PyMethodDef methods[] = {
    {"attrs_key", attrs_key, METH_VARARGS,
     "Canonical (name, backend, sorted attrs) executable-cache key; "
     "None if any attr value needs the python fallback."},
    {"discover", discover, METH_VARARGS,
     "Backward-engine in-degree BFS over GradNode.edges."},
    {"sorted_attrs", sorted_attrs, METH_VARARGS,
     "Interned attrs-only canonical key; None for exotic values."},
    {"sig_entry", sig_entry, METH_VARARGS,
     "Content-intern one per-op segment signature entry (pool cleared "
     "past 65536 entries)."},
    {"aval_cache_get", aval_cache_get, METH_VARARGS,
     "Record-time out-aval cache probe: key built in one C pass over "
     "interned (shape, dtype-str, weak_type) atoms."},
    {"aval_cache_put", aval_cache_put, METH_VARARGS,
     "Insert one out-aval tuple under the C-built key."},
    {"aval_cache_clear", aval_cache_clear, METH_NOARGS,
     "Drop every cached out-aval entry."},
    {"intern_sizes", intern_sizes, METH_NOARGS,
     "Sizes of the C-side intern pools (tests)."},
    {"bind_types", bind_types, METH_VARARGS,
     "Register the LazyRef/Tensor/AutogradMeta/_PendingOp classes "
     "skel_record constructs."},
    {"skel_record", (PyCFunction)(void (*)())skel_record, METH_FASTCALL,
     "Trace-stable skeleton replay of one record: validate against the "
     "retained skeleton op and mint the outputs from its cached avals. "
     "Returns outs | None (mismatch) | NotImplemented (punt)."},
    {"bind_drive", bind_drive, METH_VARARGS,
     "Register the whole-step driver's handles (_DriveState, op "
     "registry, scalar cache, gen/drive cells, lazy module) and "
     "resolve the _DriveState slot offsets. False = driver stays off."},
    {"drive_record", (PyCFunction)(void (*)())drive_record, METH_FASTCALL,
     "Whole-step driven dispatch of one op against the armed plan "
     "cursor: C-side coercion + op resolve + replay commit in one "
     "call. Returns the final value | None (retired, fall through) | "
     "NotImplemented (punt, retired)."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "pt_eager_core",
                      "Eager hot-path primitives (csrc/eager_core.cc).",
                      -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit_pt_eager_core(void) {
  g_dtype_str = PyDict_New();
  g_atom_intern = PyDict_New();
  g_aval_cache = PyDict_New();
  g_entry_intern = PyDict_New();
  g_attrs_intern = PyDict_New();
  if (!g_dtype_str || !g_atom_intern || !g_aval_cache || !g_entry_intern ||
      !g_attrs_intern) {
    return nullptr;
  }
  g_one = PyLong_FromLong(1);
  g_float_pos = PyFloat_FromDouble(1.0);
  g_float_neg = PyFloat_FromDouble(-1.0);
  if (!g_one || !g_float_pos || !g_float_neg) return nullptr;
  s_multi_output = intern_str("multi_output");
  s_FAST_OPS = intern_str("FAST_OPS");
  s_dpos = intern_str("pos");
  s_dn = intern_str("n_driven");
  s_wtag_in = intern_str("in");
  s_wtag_op = intern_str("op");
  s_skel_pos = intern_str("_skel_pos");
  s_fast_ops = intern_str("_fast_ops");
  s_ops_recorded = intern_str("ops_recorded");
  s_payload = intern_str("_payload");
  s_shape = intern_str("shape");
  s_dtype = intern_str("dtype");
  s_weak_type = intern_str("weak_type");
  s_stop_gradient = intern_str("_stop_gradient");
  s_autograd_meta = intern_str("_autograd_meta");
  s_inplace_version = intern_str("_inplace_version");
  s_ctx = intern_str("ctx");
  s_op_idx = intern_str("op_idx");
  s_slot = intern_str("slot");
  s_aval = intern_str("aval");
  s_requires_grad = intern_str("requires_grad");
  s_trefs = intern_str("trefs");
  s_in_ids = intern_str("_in_ids");
  s_in_tensors = intern_str("_in_tensors");
  s_in_pins = intern_str("_in_pins");
  s_in_vals = intern_str("_in_vals");
  s_in_meta = intern_str("_in_meta");
  s_pending_attr = intern_str("pending");
  s_sig_ops = intern_str("_sig_ops");
  s_on_flush = intern_str("on_flush");
  s_grad = intern_str("grad");
  s_grad_node = intern_str("grad_node");
  s_out_slot = intern_str("out_slot");
  s_hooks = intern_str("hooks");
  s_retain_grads = intern_str("retain_grads");
  s_name_attr = intern_str("name");
  s_persistable = intern_str("persistable");
  s_dist_attr = intern_str("_dist_attr");
  s_op = intern_str("op");
  s_attrs = intern_str("attrs");
  s_wiring = intern_str("wiring");
  s_out_refs = intern_str("out_refs");
  s_n_outs = intern_str("n_outs");
  s_src = intern_str("src");
  s_is_lazy_ref = intern_str("_is_lazy_ref");
  return PyModule_Create(&module);
}
