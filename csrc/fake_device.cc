// Fake custom device plugin: host memory masquerading as two devices.
//
// Analog of the reference's in-tree fake backend for contract tests
// (paddle/phi/backends/custom/fake_cpu_device.h, exercised by
// test/custom_runtime/test_custom_cpu_plugin.py): proves the plugin ABI
// end-to-end without hardware. Built as its own .so (libpt_fake_device)
// and dlopened through pt_plugin_load.
#include <cstdlib>
#include <cstring>
#include <map>

#include "device_ext.h"

namespace {

constexpr int kNumDevices = 2;
int g_current = 0;
size_t g_used[kNumDevices] = {0, 0};
std::map<void*, std::pair<int, size_t>> g_allocs;  // ptr -> (dev, size)
constexpr size_t kCapacity = 1ull << 30;

bool bad_dev(int d) { return d < 0 || d >= kNumDevices; }

PT_Status f_init(void) { return PT_STATUS_OK; }
PT_Status f_deinit(void) { return PT_STATUS_OK; }

PT_Status f_count(int* n) {
  *n = kNumDevices;
  return PT_STATUS_OK;
}

PT_Status f_set(int d) {
  if (d < 0 || d >= kNumDevices) return PT_STATUS_INVALID;
  g_current = d;
  return PT_STATUS_OK;
}

PT_Status f_get(int* d) {
  *d = g_current;
  return PT_STATUS_OK;
}

PT_Status f_malloc(int d, void** ptr, size_t n) {
  if (bad_dev(d)) return PT_STATUS_INVALID;
  *ptr = std::malloc(n);
  if (!*ptr) return PT_STATUS_FAILED;
  g_used[d] += n;
  g_allocs[*ptr] = {d, n};
  return PT_STATUS_OK;
}

PT_Status f_free(int d, void* ptr) {
  if (bad_dev(d)) return PT_STATUS_INVALID;
  auto it = g_allocs.find(ptr);
  if (it != g_allocs.end()) {
    g_used[it->second.first] -= it->second.second;
    g_allocs.erase(it);
  }
  std::free(ptr);
  return PT_STATUS_OK;
}

PT_Status f_h2d(int, void* dst, const void* src, size_t n) {
  std::memcpy(dst, src, n);
  return PT_STATUS_OK;
}

PT_Status f_d2h(int, void* dst, const void* src, size_t n) {
  std::memcpy(dst, src, n);
  return PT_STATUS_OK;
}

PT_Status f_d2d(int, void* dst, const void* src, size_t n) {
  std::memcpy(dst, src, n);
  return PT_STATUS_OK;
}

PT_Status f_stats(int d, size_t* total, size_t* free_) {
  if (bad_dev(d)) return PT_STATUS_INVALID;
  *total = kCapacity;
  *free_ = g_used[d] > kCapacity ? 0 : kCapacity - g_used[d];
  return PT_STATUS_OK;
}

// streams/events: host is synchronous; handles are opaque tags
PT_Status f_stream_create(int, PT_Stream* s) {
  *s = reinterpret_cast<PT_Stream>(new int(0));
  return PT_STATUS_OK;
}
PT_Status f_stream_destroy(int, PT_Stream s) {
  delete reinterpret_cast<int*>(s);
  return PT_STATUS_OK;
}
PT_Status f_stream_sync(int, PT_Stream) { return PT_STATUS_OK; }
PT_Status f_event_create(int, PT_Event* e) {
  *e = reinterpret_cast<PT_Event>(new int(0));
  return PT_STATUS_OK;
}
PT_Status f_event_destroy(int, PT_Event e) {
  delete reinterpret_cast<int*>(e);
  return PT_STATUS_OK;
}
PT_Status f_event_record(int, PT_Stream, PT_Event) { return PT_STATUS_OK; }
PT_Status f_event_sync(int, PT_Event) { return PT_STATUS_OK; }

// single-process "collective": identity (world of one fake fabric)
PT_Status f_all_reduce(int, void*, size_t, int, int) {
  return PT_STATUS_OK;
}
PT_Status f_broadcast(int, void*, size_t, int) { return PT_STATUS_OK; }

}  // namespace

extern "C" __attribute__((visibility("default"))) PT_Status
PT_InitDevicePlugin(PT_DeviceInterface* i) {
  i->abi_version = PT_DEVICE_ABI_VERSION;
  i->device_type = "fake_cpu";
  i->init = f_init;
  i->deinit = f_deinit;
  i->get_device_count = f_count;
  i->set_device = f_set;
  i->get_device = f_get;
  i->device_malloc = f_malloc;
  i->device_free = f_free;
  i->memcpy_h2d = f_h2d;
  i->memcpy_d2h = f_d2h;
  i->memcpy_d2d = f_d2d;
  i->device_mem_stats = f_stats;
  i->stream_create = f_stream_create;
  i->stream_destroy = f_stream_destroy;
  i->stream_synchronize = f_stream_sync;
  i->event_create = f_event_create;
  i->event_destroy = f_event_destroy;
  i->event_record = f_event_record;
  i->event_synchronize = f_event_sync;
  i->ccl_all_reduce = f_all_reduce;
  i->ccl_broadcast = f_broadcast;
  return PT_STATUS_OK;
}
