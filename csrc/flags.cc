// Global runtime flag registry with environment override.
//
// Native analog of the reference's exported-flag system
// (paddle/common/flags.h:242 PHI_DEFINE_EXPORTED_* macro family,
// flags_native.cc): string-keyed registry, values overridable from the
// environment as PT_FLAGS_<name>, queried from both C++ subsystems and
// Python (paddle_tpu.set_flags/get_flags bridge).
#include "pt_common.h"

#include <cstdlib>
#include <mutex>
#include <string>
#include <unordered_map>

namespace pt {
namespace {

std::mutex g_mu;
std::unordered_map<std::string, std::string>& Registry() {
  static std::unordered_map<std::string, std::string> r;
  return r;
}

}  // namespace
}  // namespace pt

PT_EXPORT int pt_flag_define(const char* name, const char* default_value) {
  std::lock_guard<std::mutex> g(pt::g_mu);
  auto& r = pt::Registry();
  if (r.count(name)) return -1;
  std::string env_key = std::string("PT_FLAGS_") + name;
  const char* env = std::getenv(env_key.c_str());
  r[name] = env ? env : default_value;
  return 0;
}

PT_EXPORT int pt_flag_set(const char* name, const char* value) {
  std::lock_guard<std::mutex> g(pt::g_mu);
  auto& r = pt::Registry();
  auto it = r.find(name);
  if (it == r.end()) {
    pt::set_last_error(std::string("unknown flag: ") + name);
    return -1;
  }
  it->second = value;
  return 0;
}

PT_EXPORT int64_t pt_flag_get(const char* name, char* buf,
                              int64_t buf_len) {
  std::lock_guard<std::mutex> g(pt::g_mu);
  auto& r = pt::Registry();
  auto it = r.find(name);
  if (it == r.end()) return -1;
  int64_t n = static_cast<int64_t>(it->second.size());
  if (buf && buf_len > n) {
    std::memcpy(buf, it->second.c_str(), n + 1);
  }
  return n;
}
