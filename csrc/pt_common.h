// Common helpers for the paddle_tpu native runtime library.
//
// TPU-native C++ runtime substrate: the pieces of the reference that live
// in C++ around the accelerator compute path (SURVEY.md §2a/§2e) —
// rendezvous store (paddle/phi/core/distributed/store/tcp_store.h:121),
// host allocator (paddle/phi/core/memory/allocation/, auto_growth strategy),
// data feed (paddle/fluid/framework/data_feed.h), flag registry
// (paddle/common/flags.h:242). Compute stays on XLA; this library serves
// the host side: multi-host rendezvous, staging memory, input pipeline.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>

#if defined(_WIN32)
#error "POSIX only"
#endif

#define PT_EXPORT extern "C" __attribute__((visibility("default")))

namespace pt {

// last error message, per-thread
std::string& last_error();
void set_last_error(const std::string& msg);

}  // namespace pt
