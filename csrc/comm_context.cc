// Native host-side collective engine (CommContext).
//
// TPU-native analog of the reference's comm-context layer
// (paddle/phi/core/distributed/comm_context_manager.h:43 creating
// per-ring contexts, gloo_comm_context.cc for the CPU transport): a full
// TCP mesh between ranks carrying ring collectives for the host-driven
// eager path. In-graph collectives stay XLA-over-ICI; this engine serves
// everything outside jit — gradient sync in eager DataParallel,
// object/checkpoint coordination, host-driven pipeline send/recv — and
// replaces the O(n^2)-through-the-KV-server store transport with direct
// peer sockets (ring all-reduce moves 2*(n-1)/n * bytes per rank).
//
// C ABI (ctypes-consumed, same dlopen shape as device_ext.h:96):
//   ptcc_create(rank, world) -> ctx      (opens listener)
//   ptcc_listen_port(ctx) -> port
//   ptcc_connect(ctx, "h:p,h:p,...")     (mesh handshake)
//   ptcc_all_reduce / ptcc_reduce_scatter (ring, dtype+op aware)
//   ptcc_broadcast / ptcc_all_gather     (byte-oriented ring)
//   ptcc_send / ptcc_recv / ptcc_barrier / ptcc_destroy
#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "pt_common.h"

namespace {

enum class DType : int { kF32 = 0, kF64 = 1, kI32 = 2, kI64 = 3, kU8 = 4 };
enum class ROp : int { kSum = 0, kMax = 1, kMin = 2, kProd = 3 };

size_t dtype_size(DType d) {
  switch (d) {
    case DType::kF32: return 4;
    case DType::kF64: return 8;
    case DType::kI32: return 4;
    case DType::kI64: return 8;
    case DType::kU8: return 1;
  }
  return 0;
}

template <typename T>
void reduce_typed(T* dst, const T* src, int64_t n, ROp op) {
  switch (op) {
    case ROp::kSum:
      for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
      break;
    case ROp::kMax:
      for (int64_t i = 0; i < n; ++i) dst[i] = src[i] > dst[i] ? src[i] : dst[i];
      break;
    case ROp::kMin:
      for (int64_t i = 0; i < n; ++i) dst[i] = src[i] < dst[i] ? src[i] : dst[i];
      break;
    case ROp::kProd:
      for (int64_t i = 0; i < n; ++i) dst[i] *= src[i];
      break;
  }
}

void reduce_buf(void* dst, const void* src, int64_t n, DType d, ROp op) {
  switch (d) {
    case DType::kF32:
      reduce_typed(static_cast<float*>(dst), static_cast<const float*>(src), n, op);
      break;
    case DType::kF64:
      reduce_typed(static_cast<double*>(dst), static_cast<const double*>(src), n, op);
      break;
    case DType::kI32:
      reduce_typed(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src), n, op);
      break;
    case DType::kI64:
      reduce_typed(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src), n, op);
      break;
    case DType::kU8:
      reduce_typed(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src), n, op);
      break;
  }
}

void set_nonblock(int fd, bool nb) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, nb ? (fl | O_NONBLOCK) : (fl & ~O_NONBLOCK));
}

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

struct CommContext {
  int rank = -1;
  int world = 0;
  int listen_fd = -1;
  int listen_port = 0;
  std::vector<int> peer_fd;  // by peer rank; own slot -1

  ~CommContext() {
    for (int fd : peer_fd)
      if (fd >= 0) close(fd);
    if (listen_fd >= 0) close(listen_fd);
  }
};

// Max consecutive 60s poll timeouts with zero progress before a
// transfer is declared dead (peer SIGSTOPped / network partition). A
// peer that dies WITH a socket close is caught immediately by recv==0;
// this bounds the case where it dies without one. Overridable via
// PT_COMM_IDLE_POLL_LIMIT for ranks whose peers may lag a long time
// before entering a collective (e.g. very large first-compile skews).
static int max_idle_polls() {
  static int v = [] {
    const char* e = getenv("PT_COMM_IDLE_POLL_LIMIT");
    int n = e ? atoi(e) : 0;
    return n > 0 ? n : 10;
  }();
  return v;
}

// Blocking-with-poll full write/read on a (possibly nonblocking) fd.
bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  int idle = 0;
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w > 0) {
      p += w;
      n -= static_cast<size_t>(w);
      idle = 0;
      continue;
    }
    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      struct pollfd pf{fd, POLLOUT, 0};
      if (poll(&pf, 1, 60000) == 0 && ++idle >= max_idle_polls()) {
        pt::set_last_error("ptcc: write stalled (peer unresponsive)");
        return false;
      }
      continue;
    }
    if (w < 0 && errno == EINTR) continue;
    return false;
  }
  return true;
}

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  int idle = 0;
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r > 0) {
      p += r;
      n -= static_cast<size_t>(r);
      idle = 0;
      continue;
    }
    if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      struct pollfd pf{fd, POLLIN, 0};
      if (poll(&pf, 1, 60000) == 0 && ++idle >= max_idle_polls()) {
        pt::set_last_error("ptcc: read stalled (peer unresponsive)");
        return false;
      }
      continue;
    }
    if (r < 0 && errno == EINTR) continue;
    return false;  // r == 0: peer closed
  }
  return true;
}

// Interleaved full-duplex exchange: send sbuf on send_fd while receiving
// rbuf on recv_fd. Required for ring steps — serial send-then-recv
// deadlocks once payloads exceed kernel socket buffers.
bool duplex(int send_fd, const void* sbuf, size_t sn, int recv_fd,
            void* rbuf, size_t rn) {
  const char* sp = static_cast<const char*>(sbuf);
  char* rp = static_cast<char*>(rbuf);
  int idle = 0;
  while (sn > 0 || rn > 0) {
    struct pollfd pf[2];
    int k = 0;
    int si = -1, ri = -1;
    if (sn > 0) {
      si = k;
      pf[k++] = {send_fd, POLLOUT, 0};
    }
    if (rn > 0) {
      ri = k;
      pf[k++] = {recv_fd, POLLIN, 0};
    }
    int pr = poll(pf, k, 60000);
    if (pr < 0 && errno != EINTR) return false;
    if (pr == 0 && ++idle >= max_idle_polls()) {
      pt::set_last_error("ptcc: duplex stalled (peer unresponsive)");
      return false;
    }
    if (pr > 0) idle = 0;
    if (si >= 0 && (pf[si].revents & (POLLOUT | POLLERR | POLLHUP))) {
      ssize_t w = ::send(send_fd, sp, sn, MSG_NOSIGNAL);
      if (w > 0) {
        sp += w;
        sn -= static_cast<size_t>(w);
      } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                 errno != EINTR) {
        return false;
      }
    }
    if (ri >= 0 && (pf[ri].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t r = ::recv(recv_fd, rp, rn, 0);
      if (r > 0) {
        rp += r;
        rn -= static_cast<size_t>(r);
      } else if (r == 0) {
        return false;
      } else if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        return false;
      }
    }
  }
  return true;
}

bool resolve_connect(const std::string& host, int port, int* fd_out) {
  // getaddrinfo (not inet_pton) so hostnames work, not just IPv4 literals
  struct addrinfo hints {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0)
    return false;
  int fd = -1;
  bool connected = false;
  for (struct addrinfo* ai = res; ai && !connected; ai = ai->ai_next) {
    // retry while the peer's listener may not be up yet; POSIX leaves a
    // socket in an unspecified state after a failed connect(), so make a
    // fresh one each attempt instead of reusing the fd
    for (int tries = 0; tries < 600; ++tries) {
      fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd < 0) break;
      if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
        connected = true;
        break;
      }
      int cerr = errno;  // close() may clobber errno
      close(fd);
      fd = -1;
      if (cerr == ECONNREFUSED || cerr == ETIMEDOUT ||
          cerr == EHOSTUNREACH) {
        usleep(100000);
        continue;
      }
      break;  // non-retryable: try the next addrinfo entry
    }
  }
  freeaddrinfo(res);
  if (!connected) return false;
  *fd_out = fd;
  return true;
}

}  // namespace



PT_EXPORT void* ptcc_create(int rank, int world) {
  auto* ctx = new CommContext();
  ctx->rank = rank;
  ctx->world = world;
  ctx->peer_fd.assign(world, -1);
  ctx->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (ctx->listen_fd < 0) {
    pt::set_last_error("ptcc: socket() failed");
    delete ctx;
    return nullptr;
  }
  int one = 1;
  setsockopt(ctx->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = 0;
  if (bind(ctx->listen_fd, reinterpret_cast<sockaddr*>(&addr),
           sizeof(addr)) < 0 ||
      listen(ctx->listen_fd, world + 8) < 0) {
    pt::set_last_error("ptcc: bind/listen failed");
    delete ctx;
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(ctx->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  ctx->listen_port = ntohs(addr.sin_port);
  return ctx;
}

PT_EXPORT int ptcc_listen_port(void* h) {
  return static_cast<CommContext*>(h)->listen_port;
}

// endpoints: comma-separated "host:port" in rank order. This rank
// connects to all lower ranks (sending a 4-byte rank handshake) and
// accepts one connection from each higher rank.
PT_EXPORT int ptcc_connect(void* h, const char* endpoints) {
  auto* ctx = static_cast<CommContext*>(h);
  std::vector<std::pair<std::string, int>> eps;
  std::string s(endpoints);
  size_t pos = 0;
  while (pos < s.size()) {
    size_t comma = s.find(',', pos);
    if (comma == std::string::npos) comma = s.size();
    std::string tok = s.substr(pos, comma - pos);
    size_t colon = tok.rfind(':');
    if (colon == std::string::npos) {
      pt::set_last_error("ptcc: bad endpoint");
      return -1;
    }
    eps.emplace_back(tok.substr(0, colon),
                     std::stoi(tok.substr(colon + 1)));
    pos = comma + 1;
  }
  if (static_cast<int>(eps.size()) != ctx->world) {
    pt::set_last_error("ptcc: endpoint count != world");
    return -1;
  }
  for (int peer = 0; peer < ctx->rank; ++peer) {
    int fd = -1;
    if (!resolve_connect(eps[peer].first, eps[peer].second, &fd)) {
      pt::set_last_error("ptcc: connect to peer failed");
      return -1;
    }
    set_nodelay(fd);
    int32_t me = ctx->rank;
    if (!write_full(fd, &me, 4)) {
      pt::set_last_error("ptcc: handshake send failed");
      close(fd);
      return -1;
    }
    ctx->peer_fd[peer] = fd;
  }
  for (int need = ctx->world - 1 - ctx->rank; need > 0; --need) {
    // bounded wait: a peer that died before connecting must surface as
    // an error here, not an indefinite hang
    struct pollfd pf{ctx->listen_fd, POLLIN, 0};
    int pr = poll(&pf, 1, 120000);
    if (pr <= 0) {
      pt::set_last_error("ptcc: timed out waiting for peer connections");
      return -1;
    }
    int fd = accept(ctx->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      pt::set_last_error("ptcc: accept failed");
      return -1;
    }
    set_nodelay(fd);
    int32_t peer = -1;
    if (!read_full(fd, &peer, 4) || peer <= ctx->rank ||
        peer >= ctx->world) {
      pt::set_last_error("ptcc: bad handshake");
      close(fd);
      return -1;
    }
    ctx->peer_fd[peer] = fd;
  }
  for (int fd : ctx->peer_fd)
    if (fd >= 0) set_nonblock(fd, true);
  return 0;
}

PT_EXPORT int ptcc_send(void* h, const void* data, int64_t nbytes,
                        int peer) {
  auto* ctx = static_cast<CommContext*>(h);
  if (peer < 0 || peer >= ctx->world || ctx->peer_fd[peer] < 0) {
    pt::set_last_error("ptcc: no such peer");
    return -1;
  }
  return write_full(ctx->peer_fd[peer], data, nbytes) ? 0 : -1;
}

PT_EXPORT int ptcc_recv(void* h, void* data, int64_t nbytes, int peer) {
  auto* ctx = static_cast<CommContext*>(h);
  if (peer < 0 || peer >= ctx->world || ctx->peer_fd[peer] < 0) {
    pt::set_last_error("ptcc: no such peer");
    return -1;
  }
  return read_full(ctx->peer_fd[peer], data, nbytes) ? 0 : -1;
}

// In-place ring all-reduce: reduce-scatter phase then all-gather phase
// (the classic 2*(n-1) step algorithm NCCL rings use).
PT_EXPORT int ptcc_all_reduce(void* h, void* data, int64_t count,
                              int dtype, int op) {
  auto* ctx = static_cast<CommContext*>(h);
  int n = ctx->world;
  if (n == 1) return 0;
  DType dt = static_cast<DType>(dtype);
  ROp rop = static_cast<ROp>(op);
  size_t esz = dtype_size(dt);
  if (esz == 0) {
    pt::set_last_error("ptcc: bad dtype");
    return -1;
  }
  int next = (ctx->rank + 1) % n, prev = (ctx->rank - 1 + n) % n;
  int sfd = ctx->peer_fd[next], rfd = ctx->peer_fd[prev];
  char* base = static_cast<char*>(data);
  auto chunk_off = [&](int c) { return (count * c) / n; };
  auto chunk_len = [&](int c) { return (count * (c + 1)) / n - chunk_off(c); };
  int64_t max_len = 0;
  for (int c = 0; c < n; ++c)
    max_len = chunk_len(c) > max_len ? chunk_len(c) : max_len;
  std::vector<char> tmp(static_cast<size_t>(max_len) * esz);
  // reduce-scatter
  for (int s = 0; s < n - 1; ++s) {
    int sc = (ctx->rank - s + n) % n;       // chunk we send
    int rc = (ctx->rank - s - 1 + n) % n;   // chunk we receive+reduce
    if (!duplex(sfd, base + chunk_off(sc) * esz, chunk_len(sc) * esz,
                rfd, tmp.data(), chunk_len(rc) * esz)) {
      pt::set_last_error("ptcc: ring exchange failed");
      return -1;
    }
    reduce_buf(base + chunk_off(rc) * esz, tmp.data(), chunk_len(rc), dt,
               rop);
  }
  // all-gather of the reduced chunks
  for (int s = 0; s < n - 1; ++s) {
    int sc = (ctx->rank + 1 - s + n) % n;
    int rc = (ctx->rank - s + n) % n;
    if (!duplex(sfd, base + chunk_off(sc) * esz, chunk_len(sc) * esz,
                rfd, base + chunk_off(rc) * esz, chunk_len(rc) * esz)) {
      pt::set_last_error("ptcc: ring exchange failed");
      return -1;
    }
  }
  return 0;
}

// Reduce-scatter: input is world*count_per_rank elements; out gets the
// fully reduced slice for this rank.
PT_EXPORT int ptcc_reduce_scatter(void* h, const void* in, void* out,
                                  int64_t count_per_rank, int dtype,
                                  int op) {
  auto* ctx = static_cast<CommContext*>(h);
  int n = ctx->world;
  DType dt = static_cast<DType>(dtype);
  ROp rop = static_cast<ROp>(op);
  size_t esz = dtype_size(dt);
  if (esz == 0) {
    pt::set_last_error("ptcc: bad dtype");
    return -1;
  }
  if (n == 1) {
    memcpy(out, in, count_per_rank * esz);
    return 0;
  }
  int next = (ctx->rank + 1) % n, prev = (ctx->rank - 1 + n) % n;
  int sfd = ctx->peer_fd[next], rfd = ctx->peer_fd[prev];
  std::vector<char> work(static_cast<const char*>(in),
                         static_cast<const char*>(in) +
                             static_cast<size_t>(n) * count_per_rank * esz);
  std::vector<char> tmp(static_cast<size_t>(count_per_rank) * esz);
  char* base = work.data();
  int64_t cb = count_per_rank * esz;
  // the ring schedule with origin r0 leaves chunk (r0+1) fully reduced
  // here; origin rank-1 makes that chunk == rank, matching the API
  int r0 = (ctx->rank - 1 + n) % n;
  for (int s = 0; s < n - 1; ++s) {
    int sc = (r0 - s + n) % n;
    int rc = (r0 - s - 1 + n) % n;
    if (!duplex(sfd, base + sc * cb, cb, rfd, tmp.data(), cb)) {
      pt::set_last_error("ptcc: ring exchange failed");
      return -1;
    }
    reduce_buf(base + rc * cb, tmp.data(), count_per_rank, dt, rop);
  }
  memcpy(out, base + ctx->rank * cb, cb);
  return 0;
}

// Ring all-gather: in (nbytes) -> out (world*nbytes, rank-major).
PT_EXPORT int ptcc_all_gather(void* h, const void* in, void* out,
                              int64_t nbytes) {
  auto* ctx = static_cast<CommContext*>(h);
  int n = ctx->world;
  char* base = static_cast<char*>(out);
  memcpy(base + ctx->rank * nbytes, in, nbytes);
  if (n == 1) return 0;
  int next = (ctx->rank + 1) % n, prev = (ctx->rank - 1 + n) % n;
  int sfd = ctx->peer_fd[next], rfd = ctx->peer_fd[prev];
  for (int s = 0; s < n - 1; ++s) {
    int sc = (ctx->rank - s + n) % n;
    int rc = (ctx->rank - s - 1 + n) % n;
    if (!duplex(sfd, base + sc * nbytes, nbytes, rfd, base + rc * nbytes,
                nbytes)) {
      pt::set_last_error("ptcc: ring exchange failed");
      return -1;
    }
  }
  return 0;
}

// Ring broadcast from root (single pass around the ring).
PT_EXPORT int ptcc_broadcast(void* h, void* data, int64_t nbytes,
                             int root) {
  auto* ctx = static_cast<CommContext*>(h);
  int n = ctx->world;
  if (n == 1) return 0;
  int next = (ctx->rank + 1) % n, prev = (ctx->rank - 1 + n) % n;
  bool ok = true;
  if (ctx->rank == root) {
    if (next != root) ok = write_full(ctx->peer_fd[next], data, nbytes);
  } else {
    ok = read_full(ctx->peer_fd[prev], data, nbytes);
    if (ok && next != root)
      ok = write_full(ctx->peer_fd[next], data, nbytes);
  }
  if (!ok) pt::set_last_error("ptcc: broadcast failed");
  return ok ? 0 : -1;
}

PT_EXPORT int ptcc_barrier(void* h) {
  uint8_t token = 1;
  return ptcc_all_reduce(h, &token, 1, static_cast<int>(DType::kU8),
                         static_cast<int>(ROp::kSum));
}

PT_EXPORT void ptcc_destroy(void* h) { delete static_cast<CommContext*>(h); }


