from .gpt import (GPTConfig, GPTModel, GPTForPretraining,  # noqa: F401
                  GPTPretrainingCriterion, build_train_step,
                  init_gpt_params)
