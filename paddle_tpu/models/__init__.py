from .gpt import (GPTConfig, GPTModel, GPTForPretraining,  # noqa: F401
                  GPTPretrainingCriterion, build_train_step,
                  init_gpt_params)
from . import bert  # noqa: F401
from . import llama  # noqa: F401
from .bert import BERT_CONFIGS, BertConfig  # noqa: F401
from .llama import LLAMA_CONFIGS, LlamaConfig  # noqa: F401
