"""GPT model family — the flagship (BASELINE.md configs 4/5 shape).

Two execution paths, mirroring the reference's dygraph/static split:

1. Eager Layer path (`GPTModel`, `GPTForPretraining`): built from fleet TP
   layers (VocabParallelEmbedding / Column/RowParallelLinear — the
   mp_layers.py analogs) so weights carry mp sharding annotations.

2. Compiled functional trainer (`build_train_step`): the TPU-native
   "static graph with parallel passes" (SURVEY §3.5) — ONE jitted XLA
   program per training step:
     - per-block params stacked [L, ...] and scanned (lax.scan) — compile
       time O(1) in depth;
     - jax.checkpoint per block = the reference's recompute pass;
     - GSPMD shardings: dp over batch, mp over hidden (Megatron layout:
       qkv/mlp-in column-sharded, proj/mlp-out row-sharded, embeddings
       vocab-sharded), sp (sequence parallel) shards the activation seq
       dim between blocks, ZeRO-style optimizer-state sharding over dp;
     - fused AdamW update in the same program (no separate optimizer
       dispatch) with bf16 params + fp32 master weights.

Reference parity: python/paddle/distributed/fleet/layers/mpu/mp_layers.py,
semi_auto_llama.py test topology (test/auto_parallel/hybrid_strategy/),
GPT-3 config table from the reference's megatron-style examples.
"""
from __future__ import annotations

import dataclasses
import functools
import math
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import nn
from ..nn import functional as F
from .._core.tensor import Tensor
from ..distributed.fleet.mp_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.0
    attention_dropout_prob: float = 0.0
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    use_recompute: bool = False
    dtype: str = "bfloat16"

    @property
    def ffn(self):
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


# configs matching the reference's model table
GPT_CONFIGS = {
    "gpt2-small": GPTConfig(hidden_size=768, num_layers=12, num_heads=12),
    "gpt2-medium": GPTConfig(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt3-1.3b": GPTConfig(hidden_size=2048, num_layers=24, num_heads=32,
                           max_position_embeddings=2048),
    "gpt3-6.7b": GPTConfig(hidden_size=4096, num_layers=32, num_heads=32,
                           max_position_embeddings=2048),
}


# =====================================================================
# Eager Layer path
# =====================================================================

class GPTEmbeddings(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, position_ids=None):
        from ..ops.creation import arange
        if position_ids is None:
            position_ids = arange(input_ids.shape[1], dtype="int64")
        h = self.word_embeddings(input_ids) \
            + self.position_embeddings(position_ids)
        return self.dropout(h)


class GPTDecoderLayer(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.ln_1 = nn.LayerNorm(h, config.layer_norm_eps)
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, gather_output=False)
        self.out_proj = RowParallelLinear(h, h)
        self.ln_2 = nn.LayerNorm(h, config.layer_norm_eps)
        self.mlp_in = ColumnParallelLinear(h, config.ffn,
                                           gather_output=False)
        self.mlp_out = RowParallelLinear(config.ffn, h)
        self.config = config
        self.attn_dropout = nn.Dropout(config.attention_dropout_prob)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        c = self.config
        residual = x
        y = self.ln_1(x)
        qkv = self.qkv_proj(y)
        b, s = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape([b, s, 3, c.num_heads, c.head_dim])
        q, k, v = qkv.unbind(axis=2)
        attn, _ = F.flash_attention(q, k, v,
                                    dropout=c.attention_dropout_prob,
                                    causal=True, training=self.training)
        attn = attn.reshape([b, s, c.hidden_size])
        x = residual + self.dropout(self.out_proj(attn))
        residual = x
        y = self.ln_2(x)
        y = self.mlp_out(F.gelu(self.mlp_in(y), approximate=True))
        return residual + self.dropout(y)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = nn.LayerList(
            [GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, position_ids)
        for i, layer in enumerate(self.layers):
            if self.config.use_recompute and self.training:
                from ..distributed.fleet.recompute import recompute
                x = recompute(layer, x)
            else:
                x = layer(x)
        return self.ln_f(x)


class GPTForPretraining(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)

    def forward(self, input_ids, position_ids=None):
        h = self.gpt(input_ids, position_ids)
        # tied lm head: logits = h @ W_emb^T
        from ..ops.linalg import matmul
        w = self.gpt.embeddings.word_embeddings.weight
        return matmul(h, w, transpose_y=True)


class GPTPretrainingCriterion(nn.Layer):
    def forward(self, logits, labels, loss_mask=None):
        loss = F.cross_entropy(logits, labels, reduction="none")
        if loss_mask is not None:
            from ..ops.reduction import sum as psum
            flat = loss_mask.reshape(loss.shape)
            return psum(loss * flat) / psum(flat)
        from ..ops.reduction import mean
        return mean(loss)


# =====================================================================
# Compiled functional trainer (the perf path)
# =====================================================================

def init_gpt_params(config: GPTConfig, seed: int = 0) -> Dict[str, Any]:
    """Initialize params as a pytree with per-block arrays stacked on a
    leading layer axis [L, ...] (the scan layout)."""
    key = jax.random.PRNGKey(seed)
    h, f_, L = config.hidden_size, config.ffn, config.num_layers
    v, s_max = config.vocab_size, config.max_position_embeddings
    std = config.initializer_range
    dt = jnp.dtype(config.dtype)
    ks = jax.random.split(key, 8)

    def norm(k, shape, scale=std):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    params = {
        "wte": norm(ks[0], (v, h)),
        "wpe": norm(ks[1], (s_max, h)),
        "blocks": {
            "ln1_g": jnp.ones((L, h), dt), "ln1_b": jnp.zeros((L, h), dt),
            "qkv_w": norm(ks[2], (L, h, 3 * h)),
            "qkv_b": jnp.zeros((L, 3 * h), dt),
            "proj_w": norm(ks[3], (L, h, h),
                           scale=std / math.sqrt(2 * L)),
            "proj_b": jnp.zeros((L, h), dt),
            "ln2_g": jnp.ones((L, h), dt), "ln2_b": jnp.zeros((L, h), dt),
            "fc_w": norm(ks[4], (L, h, f_)),
            "fc_b": jnp.zeros((L, f_), dt),
            "fo_w": norm(ks[5], (L, f_, h),
                         scale=std / math.sqrt(2 * L)),
            "fo_b": jnp.zeros((L, h), dt),
        },
        "lnf_g": jnp.ones((h,), dt),
        "lnf_b": jnp.zeros((h,), dt),
    }
    return params


def param_specs(config: GPTConfig, dp: str = "dp", mp: str = "mp",
                zero_axis: Optional[str] = None,
                pp: Optional[str] = None) -> Dict[str, Any]:
    """GSPMD PartitionSpecs per param (Megatron TP layout). zero_axis, when
    set, additionally shards the 'long' dim of otherwise-replicated params
    for ZeRO-3 style param sharding. pp, when set, shards the stacked layer
    dim of blocks over the pipeline axis (compiled PP)."""
    def spec(*entries):
        return P(*entries)

    blocks = {
        "ln1_g": spec(pp, None), "ln1_b": spec(pp, None),
        "qkv_w": spec(pp, None, mp), "qkv_b": spec(pp, mp),
        "proj_w": spec(pp, mp, None), "proj_b": spec(pp, None),
        "ln2_g": spec(pp, None), "ln2_b": spec(pp, None),
        "fc_w": spec(pp, None, mp), "fc_b": spec(pp, mp),
        "fo_w": spec(pp, mp, None), "fo_b": spec(pp, None),
    }
    return {
        "wte": spec(mp, None),
        "wpe": spec(None, None),
        "blocks": blocks,
        "lnf_g": spec(None), "lnf_b": spec(None),
    }


def _use_flash_kernel(config: GPTConfig, seq: int, mesh_axes) -> bool:
    """Pallas flash attention. Single-chip path calls the kernel
    directly; the sharded path goes through mha_spmd, whose
    custom_partitioning rule keeps batch/head sharding and gathers
    seq/head_dim (so it composes with GSPMD and the compiled pp
    shard_map). Off-TPU the kernel only runs in interpret mode when
    PT_FLASH_INTERPRET=1 (CPU mesh tests / multichip dryrun)."""
    if not config.use_flash_attention or seq % 128:
        return False
    if jax.default_backend() == "tpu":
        return seq >= 256
    if os.environ.get("PT_FLASH_INTERPRET") == "1":
        return True
    from .._core.flags import flag_value
    return bool(flag_value("FLAGS_flash_interpret"))


def _ln(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * g + b


def _block(x, blk, config: GPTConfig, mesh_axes, sp_sharding=None,
           in_manual_pp=False):
    """One decoder block, pure jnp. x: [B, S, H]. With sp=True the
    residual-stream activations are sharded along the sequence dim over the
    mp axis (Megatron-SP, sequence_parallel_utils.py analog) — GSPMD turns
    the boundary into the all-gather/reduce-scatter pair."""
    c = config
    b, s, h = x.shape
    if sp_sharding is not None:
        x = jax.lax.with_sharding_constraint(x, sp_sharding)
    y = _ln(x, blk["ln1_g"], blk["ln1_b"], c.layer_norm_eps)
    qkv = jnp.einsum("bsh,hk->bsk", y, blk["qkv_w"]) + blk["qkv_b"]
    qkv = qkv.reshape(b, s, 3, c.num_heads, c.head_dim)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = jnp.swapaxes(q, 1, 2)  # B,H,S,D
    k = jnp.swapaxes(k, 1, 2)
    v = jnp.swapaxes(v, 1, 2)
    scale = 1.0 / math.sqrt(c.head_dim)
    attn = None
    if _use_flash_kernel(c, s, mesh_axes):
        if mesh_axes is not None and in_manual_pp:
            # compiled-pp manual region: nested shard_map dispatch owned
            # by the op module; None => indivisible, use einsum below
            from ..ops.pallas.flash_attention import mha_manual
            attn = mha_manual(q, k, v, mesh_axes, causal=True,
                              scale=scale)
        elif mesh_axes is not None:
            from ..ops.pallas.flash_attention import mha_spmd
            attn = mha_spmd(q, k, v, causal=True, scale=scale)
        else:
            from ..ops.pallas.flash_attention import mha_forward
            attn = mha_forward(q, k, v, causal=True, scale=scale)
    if attn is None:
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask, logits, jnp.array(-1e30, logits.dtype))
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(
            x.dtype)
        attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    attn = jnp.swapaxes(attn, 1, 2).reshape(b, s, h)
    proj = jnp.einsum("bsh,hk->bsk", attn, blk["proj_w"]) + blk["proj_b"]
    x = x + proj
    y = _ln(x, blk["ln2_g"], blk["ln2_b"], c.layer_norm_eps)
    y = jnp.einsum("bsh,hf->bsf", y, blk["fc_w"]) + blk["fc_b"]
    y = jax.nn.gelu(y, approximate=True)
    y = jnp.einsum("bsf,fh->bsh", y, blk["fo_w"]) + blk["fo_b"]
    out = x + y
    if sp_sharding is not None:
        out = jax.lax.with_sharding_constraint(out, sp_sharding)
    return out


def gpt_forward(params, tokens, config: GPTConfig, mesh_axes=None,
                remat=True, sp_sharding=None, pp_trunk=None,
                return_hidden=False, unroll_layers=False):
    """Pure forward: tokens [B, S] int32 -> logits [B, S, V]. pp_trunk,
    when given (distributed.pipeline_compiled.pipelined_trunk), replaces
    the layer scan with the compiled pp-axis pipeline. unroll_layers
    replaces the layer scan with a Python loop over the stacked block
    leaves — numerically identical, but the program carries no while
    loop: XLA:CPU's SPMD partitioner mis-types the scan transpose's
    dynamic_update_slice index under mp>1 sharding (s64 vs s32 compare,
    HLO-verifier reject), so CPU measurement paths unroll."""
    b, s = tokens.shape
    x = params["wte"][tokens] + params["wpe"][:s]
    x = x.astype(jnp.dtype(config.dtype))

    if pp_trunk is not None:
        x = pp_trunk(params["blocks"], x)
    else:
        blk_fn = functools.partial(_block, config=config,
                                   mesh_axes=mesh_axes,
                                   sp_sharding=sp_sharding)
        if remat:
            blk_fn = jax.checkpoint(blk_fn)

        if unroll_layers:
            for i in range(config.num_layers):
                x = blk_fn(x, jax.tree_util.tree_map(
                    lambda a: a[i], params["blocks"]))
        else:
            def scan_body(carry, blk):
                return blk_fn(carry, blk), None

            x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    x = _ln(x, params["lnf_g"], params["lnf_b"], config.layer_norm_eps)
    if return_hidden:
        return x
    logits = jnp.einsum("bsh,vh->bsv", x, params["wte"])
    return logits


def gpt_loss(params, tokens, labels, config: GPTConfig, mesh_axes=None,
             remat=True, sp_sharding=None, pp_trunk=None,
             unroll_layers=False):
    """Mean LM loss. With an mp>1 mesh the head goes through
    vocab-parallel softmax-cross-entropy (mp_ops.py:77-385 analog):
    wte is vocab-sharded over mp, so the full [B, S, V] logits are never
    materialized — each shard computes [B, S, V/mp] and three small
    collectives finish the loss."""
    if mesh_axes is not None and "mp" in mesh_axes.axis_names \
            and mesh_axes.shape["mp"] > 1 \
            and config.vocab_size % mesh_axes.shape["mp"] == 0:
        from ..distributed.fleet.mp_ops import \
            vocab_parallel_softmax_cross_entropy
        hidden = gpt_forward(params, tokens, config, mesh_axes, remat,
                             sp_sharding, pp_trunk=pp_trunk,
                             return_hidden=True,
                             unroll_layers=unroll_layers)
        loss = vocab_parallel_softmax_cross_entropy(
            hidden, params["wte"], labels, mesh_axes, axis="mp")
        return loss.mean()
    logits = gpt_forward(params, tokens, config, mesh_axes, remat,
                         sp_sharding, pp_trunk=pp_trunk,
                         unroll_layers=unroll_layers)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, -1)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -picked.mean()


def build_train_step(config: GPTConfig, mesh: Optional[Mesh] = None,
                     lr: float = 3e-4, wd: float = 0.1, b1: float = 0.9,
                     b2: float = 0.95, zero1: bool = True,
                     seq_shard: bool = False, remat: bool = True,
                     pp_microbatches: Optional[int] = None,
                     unroll_layers: bool = False):
    """Build (init_fn, step_fn) — step is ONE compiled XLA program:
    fwd + bwd (remat'd scan) + AdamW, with dp/mp/sp/ZeRO1 shardings when
    `mesh` has those axes. A 'pp' mesh axis (size>1) engages the compiled
    collective-permute pipeline (pipeline_compiled.py) over the stacked
    layer dim. Delegates the optimizer/sharding machinery to
    models.trainer.build_adamw_train_step."""
    from .trainer import build_adamw_train_step

    pp_size = (mesh.shape.get("pp", 1) if mesh is not None else 1)
    use_pp = pp_size > 1
    if use_pp and config.num_layers % pp_size:
        raise ValueError(f"num_layers {config.num_layers} not divisible "
                         f"by pp {pp_size}")

    pp_trunk = None
    if use_pp:
        from ..distributed.pipeline_compiled import pipelined_trunk
        n_micro = pp_microbatches or 2 * pp_size
        blk_fn = functools.partial(_block, config=config, mesh_axes=mesh,
                                   sp_sharding=None, in_manual_pp=True)
        pp_trunk = pipelined_trunk(
            lambda x, blk: blk_fn(x, blk), mesh, n_micro, axis_name="pp",
            remat=remat)

    sp_sharding = None
    if seq_shard and mesh is not None and "mp" in mesh.axis_names \
            and "dp" in mesh.axis_names:
        sp_sharding = NamedSharding(mesh, P("dp", "mp", None))

    # decay only matrix weights + embeddings; LayerNorm gains/biases and
    # bias vectors are excluded (Megatron/reference convention)
    _DECAY_KEYS = {"wte", "wpe", "qkv_w", "proj_w", "fc_w", "fo_w"}
    wd_mask = {
        "wte": True, "wpe": True,
        "blocks": {k: (k in _DECAY_KEYS)
                   for k in ["ln1_g", "ln1_b", "qkv_w", "qkv_b",
                             "proj_w", "proj_b", "ln2_g", "ln2_b",
                             "fc_w", "fc_b", "fo_w", "fo_b"]},
        "lnf_g": False, "lnf_b": False,
    }

    def loss_fn(params, tokens, labels):
        return gpt_loss(params, tokens, labels, config, mesh_axes=mesh,
                        remat=remat, sp_sharding=sp_sharding,
                        pp_trunk=pp_trunk, unroll_layers=unroll_layers)

    return build_adamw_train_step(
        loss_fn, functools.partial(init_gpt_params, config),
        param_specs(config, pp="pp" if use_pp else None), wd_mask,
        mesh=mesh, lr=lr, wd=wd, b1=b1, b2=b2, zero1=zero1)
