"""BERT / ERNIE encoder family — functional TPU-compiled path.

ERNIE-3.0-base is architecturally a BERT encoder (12L/768H/12A) with
task-specific pretraining; the driver baseline tracks ERNIE tokens/sec/chip
(BASELINE.md config 5). Same compiled-trainer machinery as gpt/llama:
stacked-layer scan + remat, TP specs on mp, ZeRO-1 over dp; pretraining
objective here is masked-LM (the throughput-relevant part)."""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .trainer import build_adamw_train_step


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: str = "bfloat16"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


BERT_CONFIGS = {
    "bert-tiny": BertConfig(vocab_size=1024, hidden_size=128,
                            num_layers=2, num_heads=2,
                            intermediate_size=512,
                            max_position_embeddings=128),
    "bert-base": BertConfig(),
    "ernie-3.0-base": BertConfig(vocab_size=40000),
    "bert-large": BertConfig(hidden_size=1024, num_layers=24,
                             num_heads=16, intermediate_size=4096),
}


def init_bert_params(config: BertConfig, seed: int = 0) -> Dict:
    key = jax.random.PRNGKey(seed)
    c = config
    h, f, L = c.hidden_size, c.intermediate_size, c.num_layers
    dt = jnp.dtype(c.dtype)
    std = c.initializer_range
    ks = jax.random.split(key, 8)

    def norm(k, shape, scale=std):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "wte": norm(ks[0], (c.vocab_size, h)),
        "wpe": norm(ks[1], (c.max_position_embeddings, h)),
        "wtype": norm(ks[2], (c.type_vocab_size, h)),
        "emb_ln_g": jnp.ones((h,), dt), "emb_ln_b": jnp.zeros((h,), dt),
        "blocks": {
            "qkv_w": norm(ks[3], (L, h, 3 * h)),
            "qkv_b": jnp.zeros((L, 3 * h), dt),
            "proj_w": norm(ks[4], (L, h, h),
                           scale=std / math.sqrt(2 * L)),
            "proj_b": jnp.zeros((L, h), dt),
            "ln1_g": jnp.ones((L, h), dt), "ln1_b": jnp.zeros((L, h), dt),
            "fc_w": norm(ks[5], (L, h, f)), "fc_b": jnp.zeros((L, f), dt),
            "fo_w": norm(ks[6], (L, f, h),
                         scale=std / math.sqrt(2 * L)),
            "fo_b": jnp.zeros((L, h), dt),
            "ln2_g": jnp.ones((L, h), dt), "ln2_b": jnp.zeros((L, h), dt),
        },
        "mlm_w": norm(ks[7], (h, h)), "mlm_b": jnp.zeros((h,), dt),
        "mlm_ln_g": jnp.ones((h,), dt), "mlm_ln_b": jnp.zeros((h,), dt),
    }


def param_specs(config: BertConfig) -> Dict:
    blocks = {
        "qkv_w": P(None, None, "mp"), "qkv_b": P(None, "mp"),
        "proj_w": P(None, "mp", None), "proj_b": P(None, None),
        "ln1_g": P(None, None), "ln1_b": P(None, None),
        "fc_w": P(None, None, "mp"), "fc_b": P(None, "mp"),
        "fo_w": P(None, "mp", None), "fo_b": P(None, None),
        "ln2_g": P(None, None), "ln2_b": P(None, None),
    }
    return {
        "wte": P("mp", None), "wpe": P(None, None), "wtype": P(None, None),
        "emb_ln_g": P(None), "emb_ln_b": P(None),
        "blocks": blocks,
        "mlm_w": P(None, None), "mlm_b": P(None),
        "mlm_ln_g": P(None), "mlm_ln_b": P(None),
    }


def wd_mask(config: BertConfig) -> Dict:
    dec = {"qkv_w", "proj_w", "fc_w", "fo_w"}
    return {
        "wte": True, "wpe": True, "wtype": True,
        "emb_ln_g": False, "emb_ln_b": False,
        "blocks": {k: (k in dec) for k in
                   ["qkv_w", "qkv_b", "proj_w", "proj_b", "ln1_g",
                    "ln1_b", "fc_w", "fc_b", "fo_w", "fo_b", "ln2_g",
                    "ln2_b"]},
        "mlm_w": True, "mlm_b": False,
        "mlm_ln_g": False, "mlm_ln_b": False,
    }


def _ln(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * g + b


def _block(x, blk, config: BertConfig, attn_mask=None):
    """Post-norm encoder block (BERT convention). x [B, S, H];
    attn_mask [B, 1, 1, S] additive or None."""
    c = config
    b, s, h = x.shape
    qkv = jnp.einsum("bsh,hk->bsk", x, blk["qkv_w"]) + blk["qkv_b"]
    qkv = qkv.reshape(b, s, 3, c.num_heads, c.head_dim)
    q = jnp.swapaxes(qkv[:, :, 0], 1, 2)
    k = jnp.swapaxes(qkv[:, :, 1], 1, 2)
    v = jnp.swapaxes(qkv[:, :, 2], 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(c.head_dim)
    if attn_mask is not None:
        logits = logits + attn_mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(x.dtype)
    attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    attn = jnp.swapaxes(attn, 1, 2).reshape(b, s, h)
    attn = jnp.einsum("bsh,hk->bsk", attn, blk["proj_w"]) + blk["proj_b"]
    x = _ln(x + attn, blk["ln1_g"], blk["ln1_b"], c.layer_norm_eps)
    y = jnp.einsum("bsh,hf->bsf", x, blk["fc_w"]) + blk["fc_b"]
    y = jax.nn.gelu(y, approximate=True)
    y = jnp.einsum("bsf,fh->bsh", y, blk["fo_w"]) + blk["fo_b"]
    return _ln(x + y, blk["ln2_g"], blk["ln2_b"], c.layer_norm_eps)


def bert_encode(params, tokens, token_type_ids=None, attention_mask=None,
                config: BertConfig = None, remat=True):
    b, s = tokens.shape
    c = config
    x = params["wte"][tokens] + params["wpe"][:s]
    if token_type_ids is not None:
        x = x + params["wtype"][token_type_ids]
    else:
        x = x + params["wtype"][0]
    x = _ln(x.astype(jnp.dtype(c.dtype)), params["emb_ln_g"],
            params["emb_ln_b"], c.layer_norm_eps)
    add_mask = None
    if attention_mask is not None:
        add_mask = (1.0 - attention_mask[:, None, None, :].astype(
            jnp.float32)) * -1e30

    fn = functools.partial(_block, config=c, attn_mask=add_mask)
    if remat:
        fn = jax.checkpoint(fn)
    x, _ = jax.lax.scan(lambda carry, blk: (fn(carry, blk), None), x,
                        params["blocks"])
    return x


def bert_mlm_logits(params, tokens, config: BertConfig, remat=True,
                    attention_mask=None):
    x = bert_encode(params, tokens, None, attention_mask, config, remat)
    x = jnp.einsum("bsh,hk->bsk", x, params["mlm_w"]) + params["mlm_b"]
    x = jax.nn.gelu(x, approximate=True)
    x = _ln(x, params["mlm_ln_g"], params["mlm_ln_b"],
            config.layer_norm_eps)
    return jnp.einsum("bsh,vh->bsv", x, params["wte"])


def bert_mlm_loss(params, tokens, labels, config: BertConfig, remat=True):
    """labels: -100 for unmasked positions (ignored), else target id."""
    logits = bert_mlm_logits(params, tokens, config, remat)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    safe = jnp.maximum(labels, 0)
    picked = jnp.take_along_axis(logp, safe[..., None], -1)[..., 0]
    mask = (labels >= 0).astype(jnp.float32)
    return -(picked * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def build_train_step(config: BertConfig, mesh: Optional[Mesh] = None,
                     lr: float = 1e-4, remat: bool = True, **adamw):
    loss = functools.partial(bert_mlm_loss, config=config, remat=remat)
    return build_adamw_train_step(
        lambda p, t, l: loss(p, t, l),
        functools.partial(init_bert_params, config),
        param_specs(config), wd_mask(config), mesh=mesh, lr=lr, **adamw)
