"""LLaMA model family — functional TPU-compiled path.

Mirrors the reference test models' LLaMA coverage
(test/auto_parallel/hybrid_strategy/semi_auto_llama.py; PaddleNLP arch):
RMSNorm pre-norm, rotary position embeddings, SwiGLU MLP, grouped-query
attention. Same compiled-trainer machinery as gpt.py: layer-stacked params
scanned (or pipelined over a 'pp' mesh axis), Megatron TP specs on the
mp axis, ZeRO-1 over dp, bf16 compute + fp32 master."""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .trainer import build_adamw_train_step, filter_specs_for_mesh


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5504
    num_layers: int = 24
    num_heads: int = 16
    num_kv_heads: Optional[int] = None        # None = MHA; < heads = GQA
    max_position_embeddings: int = 2048
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    dtype: str = "bfloat16"
    tie_embeddings: bool = False

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self):
        return self.num_kv_heads or self.num_heads


LLAMA_CONFIGS = {
    "llama-tiny": LlamaConfig(vocab_size=1024, hidden_size=128,
                              intermediate_size=352, num_layers=2,
                              num_heads=4, num_kv_heads=2,
                              max_position_embeddings=256),
    "llama-7b": LlamaConfig(),
    "llama2-7b": LlamaConfig(hidden_size=4096, intermediate_size=11008,
                             num_layers=32, num_heads=32),
}


def init_llama_params(config: LlamaConfig, seed: int = 0) -> Dict:
    key = jax.random.PRNGKey(seed)
    c = config
    h, f, L = c.hidden_size, c.intermediate_size, c.num_layers
    kvh = c.kv_heads * c.head_dim
    dt = jnp.dtype(c.dtype)
    std = c.initializer_range
    ks = jax.random.split(key, 9)

    def norm(k, shape, scale=std):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    params = {
        "wte": norm(ks[0], (c.vocab_size, h)),
        "blocks": {
            "ln1_g": jnp.ones((L, h), dt),
            "q_w": norm(ks[1], (L, h, h)),
            "k_w": norm(ks[2], (L, h, kvh)),
            "v_w": norm(ks[3], (L, h, kvh)),
            "o_w": norm(ks[4], (L, h, h), scale=std / math.sqrt(2 * L)),
            "ln2_g": jnp.ones((L, h), dt),
            "gate_w": norm(ks[5], (L, h, f)),
            "up_w": norm(ks[6], (L, h, f)),
            "down_w": norm(ks[7], (L, f, h),
                           scale=std / math.sqrt(2 * L)),
        },
        "lnf_g": jnp.ones((h,), dt),
    }
    if not c.tie_embeddings:
        params["lm_head"] = norm(ks[8], (c.vocab_size, h))
    return params


def param_specs(config: LlamaConfig, pp: Optional[str] = None) -> Dict:
    """Megatron TP layout: q/k/v/gate/up column-split, o/down row-split."""
    blocks = {
        "ln1_g": P(pp, None),
        "q_w": P(pp, None, "mp"), "k_w": P(pp, None, "mp"),
        "v_w": P(pp, None, "mp"), "o_w": P(pp, "mp", None),
        "ln2_g": P(pp, None),
        "gate_w": P(pp, None, "mp"), "up_w": P(pp, None, "mp"),
        "down_w": P(pp, "mp", None),
    }
    specs = {"wte": P("mp", None), "blocks": blocks, "lnf_g": P(None)}
    if not config.tie_embeddings:
        specs["lm_head"] = P("mp", None)
    return specs


def wd_mask(config: LlamaConfig) -> Dict:
    mask = {
        "wte": True,
        "blocks": {k: not k.startswith("ln")
                   for k in ["ln1_g", "q_w", "k_w", "v_w", "o_w", "ln2_g",
                             "gate_w", "up_w", "down_w"]},
        "lnf_g": False,
    }
    if not config.tie_embeddings:
        mask["lm_head"] = True
    return mask


# ------------------------------------------------------------------ rope

def _rope(x, theta: float):
    """x [B, S, H, D] -> rotated. Half-split convention."""
    b, s, h, d = x.shape
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = jnp.arange(s, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1 = x1.astype(jnp.float32)
    xf2 = x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin],
        axis=-1).astype(x.dtype)


def _rms(x, g, eps):
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * g


def _block(x, blk, config: LlamaConfig):
    c = config
    b, s, h = x.shape
    nh, nkv, d = c.num_heads, c.kv_heads, c.head_dim

    y = _rms(x, blk["ln1_g"], c.rms_norm_eps)
    q = jnp.einsum("bsh,hk->bsk", y, blk["q_w"]).reshape(b, s, nh, d)
    k = jnp.einsum("bsh,hk->bsk", y, blk["k_w"]).reshape(b, s, nkv, d)
    v = jnp.einsum("bsh,hk->bsk", y, blk["v_w"]).reshape(b, s, nkv, d)
    q = _rope(q, c.rope_theta)
    k = _rope(k, c.rope_theta)
    if nkv != nh:  # GQA: repeat kv heads
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / math.sqrt(d)
    mask = jnp.tril(jnp.ones((s, s), bool))
    logits = jnp.where(mask, logits, jnp.array(-1e30, logits.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(x.dtype)
    attn = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    attn = jnp.swapaxes(attn, 1, 2).reshape(b, s, h)
    x = x + jnp.einsum("bsh,hk->bsk", attn, blk["o_w"])

    y = _rms(x, blk["ln2_g"], c.rms_norm_eps)
    gate = jnp.einsum("bsh,hf->bsf", y, blk["gate_w"])
    up = jnp.einsum("bsh,hf->bsf", y, blk["up_w"])
    act = jax.nn.silu(gate) * up                       # SwiGLU
    return x + jnp.einsum("bsf,fh->bsh", act, blk["down_w"])


def llama_forward(params, tokens, config: LlamaConfig, remat=True,
                  pp_trunk=None):
    x = params["wte"][tokens].astype(jnp.dtype(config.dtype))
    if pp_trunk is not None:
        x = pp_trunk(params["blocks"], x)
    else:
        fn = functools.partial(_block, config=config)
        if remat:
            fn = jax.checkpoint(fn)
        x, _ = jax.lax.scan(lambda c, blk: (fn(c, blk), None), x,
                            params["blocks"])
    x = _rms(x, params["lnf_g"], config.rms_norm_eps)
    head = params["wte"] if config.tie_embeddings else params["lm_head"]
    return jnp.einsum("bsh,vh->bsv", x, head)


def llama_loss(params, tokens, labels, config: LlamaConfig, remat=True,
               pp_trunk=None):
    logits = llama_forward(params, tokens, config, remat, pp_trunk)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    picked = jnp.take_along_axis(logp, labels[..., None], -1)[..., 0]
    return -picked.mean()


def build_train_step(config: LlamaConfig, mesh: Optional[Mesh] = None,
                     lr: float = 3e-4, remat: bool = True,
                     pp_microbatches: Optional[int] = None, **adamw):
    pp_size = mesh.shape.get("pp", 1) if mesh is not None else 1
    use_pp = pp_size > 1
    if use_pp and config.num_layers % pp_size:
        raise ValueError("num_layers not divisible by pp degree")
    pp_trunk = None
    if use_pp:
        from ..distributed.pipeline_compiled import pipelined_trunk
        pp_trunk = pipelined_trunk(
            functools.partial(_block, config=config), mesh,
            pp_microbatches or 2 * pp_size, axis_name="pp", remat=remat)

    loss = functools.partial(llama_loss, config=config, remat=remat,
                             pp_trunk=pp_trunk)
    return build_adamw_train_step(
        lambda p, t, l: loss(p, t, l),
        functools.partial(init_llama_params, config),
        param_specs(config, pp="pp" if use_pp else None),
        wd_mask(config), mesh=mesh, lr=lr, **adamw)
