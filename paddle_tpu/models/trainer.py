"""Generic compiled trainer: one XLA program = fwd + bwd + fused AdamW.

Shared by the model families (gpt/llama/bert): takes a pure loss fn, a
param-init fn, GSPMD param specs and a weight-decay mask, and returns
(init_fn, step_fn) with dp/mp/pp/ZeRO-1 shardings and buffer donation —
the TPU-native analog of the reference's fused optimizer + DistributedStrategy
plumbing (HybridParallelOptimizer, dygraph_sharding_optimizer.py)."""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def filter_specs_for_mesh(specs, mesh: Optional[Mesh]):
    """Drop references to axes the mesh doesn't have."""
    if mesh is None:
        return specs

    def _filter(sp: P):
        return P(*(e if e in mesh.axis_names else None for e in sp))

    return jax.tree_util.tree_map(_filter, specs,
                                  is_leaf=lambda x: isinstance(x, P))


def zero1_opt_specs(specs, param_shapes, mesh: Optional[Mesh],
                    axis: str = "dp"):
    """ZeRO-1: shard optimizer state over the dp axis on the first
    unsharded, divisible dim (sharding-stage-1; each dp rank keeps 1/dp
    of master/m/v and XLA all-gathers the updated master where needed)."""
    if mesh is None or axis not in mesh.axis_names:
        return specs
    size = mesh.shape[axis]

    def _one(sp: P, shape):
        entries = list(sp) + [None] * (len(shape) - len(sp))
        for i, (e, dim) in enumerate(zip(entries, shape)):
            if e is None and dim % size == 0 and dim >= size:
                entries[i] = axis
                return P(*entries)
        return sp

    return jax.tree_util.tree_map(
        lambda sp, sh: _one(sp, sh.shape), specs, param_shapes,
        is_leaf=lambda x: isinstance(x, P))


def build_adamw_train_step(
        loss_fn: Callable,            # (params, *batch) -> scalar loss
        init_params_fn: Callable,     # (seed) -> params pytree
        specs,                        # PartitionSpec tree (or None)
        wd_mask,                      # bool tree matching params
        mesh: Optional[Mesh] = None,
        lr: float = 3e-4, wd: float = 0.1, b1: float = 0.9,
        b2: float = 0.95, eps: float = 1e-8, zero1: bool = True,
        batch_specs=None,             # specs for batch args (default dp)
        n_batch_args: int = 2):
    """Returns (init_fn, step_fn); step(state, *batch) -> (state, loss)."""
    specs = filter_specs_for_mesh(specs, mesh)
    param_shapes = jax.eval_shape(lambda: init_params_fn(0))
    opt_specs = zero1_opt_specs(specs, param_shapes, mesh) if zero1 \
        else specs

    def to_sharding(tree):
        if mesh is None:
            return None
        return jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), tree,
            is_leaf=lambda x: isinstance(x, P))

    def init_fn(seed=0):
        params = init_params_fn(seed)
        master = jax.tree_util.tree_map(
            lambda p: jnp.array(p, dtype=jnp.float32, copy=True), params)
        m = jax.tree_util.tree_map(jnp.zeros_like, master)
        v = jax.tree_util.tree_map(jnp.zeros_like, master)
        state = {"params": params, "master": master, "m": m, "v": v,
                 "step": jnp.zeros((), jnp.int32)}
        if mesh is not None:
            state = jax.device_put(state, _state_shardings())
        return state

    def _state_shardings():
        return {"params": to_sharding(specs),
                "master": to_sharding(opt_specs),
                "m": to_sharding(opt_specs), "v": to_sharding(opt_specs),
                "step": NamedSharding(mesh, P())}

    def step_fn(state, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], *batch)
        step = state["step"] + 1
        t = step.astype(jnp.float32)

        def upd(p_master, g, m, v, use_wd):
            g = g.astype(jnp.float32)
            m2 = b1 * m + (1 - b1) * g
            v2 = b2 * v + (1 - b2) * g * g
            mhat = m2 / (1 - b1 ** t)
            vhat = v2 / (1 - b2 ** t)
            decay = wd * p_master if use_wd else 0.0
            new_master = p_master - lr * (
                mhat / (jnp.sqrt(vhat) + eps) + decay)
            return new_master, m2, v2

        flat_master, tree = jax.tree_util.tree_flatten(state["master"])
        outs = [upd(pm, g, m, v, w) for pm, g, m, v, w in zip(
            flat_master, jax.tree_util.tree_leaves(grads),
            jax.tree_util.tree_leaves(state["m"]),
            jax.tree_util.tree_leaves(state["v"]),
            jax.tree_util.tree_leaves(wd_mask))]
        new_master = jax.tree_util.tree_unflatten(
            tree, [o[0] for o in outs])
        new_m = jax.tree_util.tree_unflatten(tree, [o[1] for o in outs])
        new_v = jax.tree_util.tree_unflatten(tree, [o[2] for o in outs])
        new_params = jax.tree_util.tree_map(
            lambda pm, p: pm.astype(p.dtype), new_master, state["params"])
        return {"params": new_params, "master": new_master, "m": new_m,
                "v": new_v, "step": step}, loss

    if mesh is not None:
        if batch_specs is None:
            batch_specs = tuple(P("dp" if "dp" in mesh.axis_names
                                  else None, None)
                                for _ in range(n_batch_args))
        st_sh = _state_shardings()
        jstep = jax.jit(
            step_fn,
            in_shardings=(st_sh,) + tuple(
                NamedSharding(mesh, sp) for sp in batch_specs),
            out_shardings=(st_sh, NamedSharding(mesh, P())),
            donate_argnums=(0,))
    else:
        jstep = jax.jit(step_fn, donate_argnums=(0,))
    return init_fn, jstep
