"""paddle_tpu.autograd — PyLayer, backward, grad.

PyLayer analog of python/paddle/autograd/py_layer.py:282 +
paddle/fluid/eager/pylayer/: user-defined forward/backward in Python,
wired into the GradNode engine via a py_bwd node.
"""
from __future__ import annotations

from typing import Any, List

from ._core.autograd import GradNode, _Edge, grad, is_grad_enabled, \
    no_grad, run_backward  # noqa: F401
from ._core.tensor import Tensor

__all__ = ["PyLayer", "PyLayerContext", "backward", "grad", "no_grad"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    run_backward(tensors, grad_tensors, retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved: List[Tensor] = []
        self.materialize_grads = True
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *args):
        self.not_inplace_tensors = args

    def mark_non_differentiable(self, *args):
        self._non_diff = getattr(self, "_non_diff", []) + list(args)
        for t in args:
            t.stop_gradient = True

    def set_materialize_grads(self, value):
        self.materialize_grads = bool(value)


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)
        out_tensors = [o for o in out_list if isinstance(o, Tensor)]

        non_diff = {id(t) for t in getattr(ctx, "_non_diff", [])}
        out_tensors = [t for t in out_tensors if id(t) not in non_diff]
        if is_grad_enabled() and any(not t.stop_gradient
                                     for t in tensor_inputs):
            import jax.numpy as jnp
            edges = []
            for t in tensor_inputs:
                if t.stop_gradient:
                    edges.append(_Edge(None))
                else:
                    meta = t._autograd_meta
                    if meta.grad_node is not None:
                        edges.append(_Edge("node", node=meta.grad_node,
                                           slot=meta.out_slot))
                    else:
                        edges.append(_Edge("leaf", leaf=t))
            node = GradNode(
                None, {}, (), edges,
                out_shapes=tuple(tuple(t.shape) for t in out_tensors),
                out_dtypes=tuple(t._value.dtype for t in out_tensors))
            node.name = cls.__name__

            def py_bwd(gouts, _ctx=ctx, _cls=cls, _n=len(tensor_inputs)):
                gts = [Tensor(g, stop_gradient=True) for g in gouts]
                with no_grad():
                    res = _cls.backward(_ctx, *gts)
                res_list = [res] if isinstance(res, Tensor) or res is None \
                    else list(res)
                out = []
                for r in res_list:
                    out.append(None if r is None else r._value)
                # pad to input count
                while len(out) < _n:
                    out.append(None)
                return tuple(out)

            node.py_bwd = py_bwd
            for i, t in enumerate(out_tensors):
                if jnp.issubdtype(t._value.dtype, jnp.inexact):
                    t.stop_gradient = False
                    m = t._autograd_meta
                    m.grad_node = node
                    m.out_slot = i
        return outs


class LegacyPyLayer(PyLayer):
    pass
