"""paddle.text analog (python/paddle/text): text datasets + ViterbiDecoder.

Datasets mirror the reference's lazy-download surface with local/synthetic
fallbacks (zero-egress environment); ViterbiDecoder is the real CRF
decode op."""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .._core.tensor import Tensor
from ..nn.layer import Layer


class ViterbiDecoder(Layer):
    """CRF Viterbi decode (text/viterbi_decode.py analog).

    transitions [T, T]; forward(potentials [B, L, T], lengths [B]) ->
    (scores [B], paths [B, L]).
    """

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        trans = self.transitions._value.astype(jnp.float32)
        emis = potentials._value.astype(jnp.float32)
        lens = lengths._value if isinstance(lengths, Tensor) else \
            jnp.asarray(lengths)
        scores, paths = _viterbi(emis, trans, lens)
        return Tensor(scores), Tensor(paths)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    dec = ViterbiDecoder(transition_params, include_bos_eos_tag)
    return dec(potentials, lengths)


def _viterbi(emis, trans, lens):
    b, L, t = emis.shape

    def decode_one(bi):
        ln = jnp.clip(lens[bi], 1, L)
        # score at final valid step
        def fwd(carry, i):
            score = carry
            cand = score[:, None] + trans
            nxt = jnp.max(cand, axis=0) + emis[bi, i]
            nxt = jnp.where(i < ln, nxt, score)
            return nxt, jnp.argmax(cand, axis=0)
        score, bks = jax.lax.scan(fwd, emis[bi, 0], jnp.arange(1, L))
        last = jnp.argmax(score)
        final_score = jnp.max(score)

        def back_step(carry, i):
            tag = carry
            prev = bks[i][tag]
            tag = jnp.where(i < ln - 1, prev, tag)
            return tag, tag
        _, path_rev = jax.lax.scan(back_step, last,
                                   jnp.arange(L - 2, -1, -1))
        path = jnp.concatenate([path_rev[::-1], jnp.array([last])])
        return final_score, path

    scores, paths = jax.vmap(decode_one)(jnp.arange(b))
    return scores, paths.astype(jnp.int64)


class _SyntheticTextDataset:
    """Offline stand-in for the downloadable text datasets (Imdb, Conll05
    etc.): deterministic synthetic token sequences + labels."""

    def __init__(self, mode="train", n=256, seq_len=64, vocab=1000,
                 classes=2, seed=0):
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self.data = rng.randint(1, vocab, (n, seq_len)).astype(np.int64)
        self.labels = rng.randint(0, classes, (n,)).astype(np.int64)

    def __getitem__(self, i):
        return self.data[i], self.labels[i]

    def __len__(self):
        return len(self.data)


class Imdb(_SyntheticTextDataset):
    pass


class Movielens(_SyntheticTextDataset):
    pass
