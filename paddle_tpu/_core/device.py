"""Device / place management.

TPU-native analog of the reference's place + device manager
(paddle/phi/common/place.h, paddle/phi/backends/device_manager.h:134,
python/paddle/device/__init__.py:284 set_device). Devices are PJRT devices
enumerated by JAX; "TPUPlace(i)" maps to jax.devices('tpu')[i].
"""
from __future__ import annotations

import jax


class Place:
    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def get_device_id(self):
        return self.device_id


class TPUPlace(Place):
    device_type = "tpu"


class CPUPlace(Place):
    device_type = "cpu"

    def __init__(self):
        super().__init__(0)


class CustomPlace(Place):
    def __init__(self, dev_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = dev_type


_current_device = None


def _default_device_str() -> str:
    backend = jax.default_backend()
    return f"{backend}:0" if backend != "cpu" else "cpu"


def set_device(device: str):
    """paddle.device.set_device analog ('tpu:0', 'cpu')."""
    global _current_device
    _current_device = device
    return get_device_place(device)


def get_device() -> str:
    return _current_device or _default_device_str()


def get_device_place(device: str = None) -> Place:
    device = device or get_device()
    if device == "cpu":
        return CPUPlace()
    if ":" in device:
        kind, idx = device.split(":")
    else:
        kind, idx = device, 0
    if kind in ("tpu", "gpu", "xpu", "axon"):
        return TPUPlace(int(idx)) if kind in ("tpu", "axon") \
            else CustomPlace(kind, int(idx))
    return CustomPlace(kind, int(idx))


def jax_device(place: Place = None):
    """Resolve a Place to a jax Device object."""
    if place is None or isinstance(place, TPUPlace):
        devs = jax.devices()
        idx = 0 if place is None else place.device_id
        return devs[min(idx, len(devs) - 1)]
    if isinstance(place, CPUPlace):
        return jax.devices("cpu")[0]
    return jax.devices()[0]


def place_of(value) -> Place:
    try:
        dev = next(iter(value.devices()))
    except Exception:
        return get_device_place()
    if dev.platform in ("tpu", "axon"):
        return TPUPlace(dev.id)
    if dev.platform == "cpu":
        return CPUPlace()
    return CustomPlace(dev.platform, dev.id)


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True
