"""Declarative op registry.

TPU-native analog of the reference's kernel registry + YAML op definitions
(paddle/phi/core/kernel_registry.h, paddle/phi/ops/yaml/ops.yaml:8-18). An op
here is a pure JAX function (the "kernel body" that XLA compiles for TPU)
plus metadata: an optional custom backward rule (analog of the `backward:`
yaml key) and an optional SPMD sharding rule (analog of `spmd_rule:`,
ops.yaml:97). Forward/backward execution and compile-caching live in
dispatch.py.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional


class OpDef:
    """One registered op.

    fn           : pure function over jax.Arrays: fn(*arrays, **attrs) -> array
                   or tuple of arrays.
    bwd          : optional custom VJP: bwd(saved_inputs, gouts, **attrs) ->
                   tuple of input grads (None allowed). When absent, the
                   dispatcher derives the VJP with jax.vjp (recompute-style,
                   like the reference's TensorWrapper + grad kernel pairing).
    multi_output : fn returns a tuple.
    spmd_rule    : optional sharding propagation rule (used by distributed).
    """

    __slots__ = ("name", "fn", "bwd", "multi_output", "spmd_rule", "doc",
                 "variants", "custom")

    def __init__(self, name: str, fn: Callable, bwd: Optional[Callable] = None,
                 multi_output: bool = False, spmd_rule=None,
                 custom: bool = False):
        self.name = name
        self.fn = fn
        self.bwd = bwd
        self.multi_output = multi_output
        self.spmd_rule = spmd_rule
        self.custom = custom
        self.doc = fn.__doc__
        # backend name -> kernel body override. The default fn is the
        # generic XLA lowering; a variant is the analog of a per-backend
        # kernel registration (kernel_registry.h PD_REGISTER_KERNEL with
        # a Backend key) — e.g. a Pallas body under "tpu" only.
        self.variants: Dict[str, Callable] = {}

    def kernel_for(self, backend: str) -> Callable:
        return self.variants.get(backend, self.fn)


_OPS: Dict[str, OpDef] = {}

_SCHEMA_NAMES = None


def _schema_names():
    """Names declared in ops.yaml (the system of record). Parsed directly
    from the file — no import of the yaml package — so enforcement can
    run during early package init without cycles."""
    global _SCHEMA_NAMES
    if _SCHEMA_NAMES is None:
        import os
        import re
        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "ops", "yaml", "ops.yaml")
        names = set()
        with open(path) as f:
            for line in f:
                m = re.match(r"-\s*op\s*:\s*(\w+)", line.strip())
                if m:
                    names.add(m.group(1))
        _SCHEMA_NAMES = names
    return _SCHEMA_NAMES


def register_op(name: str, fn: Callable = None, *, bwd: Callable = None,
                multi_output: bool = False, spmd_rule=None,
                custom: bool = False):
    """Register an op. Usable as decorator or direct call.

    Framework ops (custom=False) MUST have an entry in ops/yaml/ops.yaml
    — the declarative schema is the system of record, as in the
    reference where every op is declared in phi/ops/yaml/ops.yaml:8-18
    and codegen fails on mismatch. Out-of-tree ops (cpp_extension /
    incubate custom python ops / tests) pass custom=True.
    """
    def _do(f):
        if name in _OPS:
            raise ValueError(f"op '{name}' already registered")
        import os
        if (not custom and name not in _schema_names()
                and not os.environ.get("PADDLE_TPU_BOOTSTRAP")):
            raise ValueError(
                f"op '{name}' has no ops.yaml entry — the declarative "
                f"schema (paddle_tpu/ops/yaml/ops.yaml) is the system "
                f"of record; add an entry (see ops.yaml.bootstrap) or "
                f"register with custom=True for out-of-tree ops")
        op = OpDef(name, f, bwd=bwd, multi_output=multi_output,
                   spmd_rule=spmd_rule, custom=custom)
        _OPS[name] = op
        return op

    if fn is None:
        return _do
    return _do(fn)


def register_kernel(name: str, backend: str, fn: Callable = None):
    """Register a per-backend kernel body for an existing op (the
    KernelFactory multi-backend shape: same op key, backend-selected
    body — kernel_factory.h:316 SelectKernelOrThrowError)."""
    def _do(f):
        op = _OPS.get(name)
        if op is None:
            raise ValueError(f"op '{name}' not registered")
        op.variants[backend] = f
        # drop stale compiled entries so a late registration takes
        # effect even for (op, backend, attrs) keys that already ran
        from . import dispatch
        for cache in (dispatch._FWD_CACHE, dispatch._BWD_CACHE):
            for key in [k for k in cache if k[0] == name]:
                del cache[key]
        return f

    if fn is None:
        return _do
    return _do(fn)


def get_op(name: str) -> OpDef:
    try:
        return _OPS[name]
    except KeyError:
        raise KeyError(f"op '{name}' is not registered") from None


def all_ops() -> Dict[str, OpDef]:
    return dict(_OPS)
