"""Eager op executor: unwrap -> dispatch -> wrap -> record autograd.

This is the analog of the reference's generated `<op>_ad_func` layer
(paddle/fluid/eager/auto_code_generator/generator/eager_gen.py): run the
forward through the compile cache, then, if grad is required, create the
GradNode, capture inputs, and wire slot edges. AMP auto-cast interception
(amp_auto_cast.h analog) hooks in here too.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from . import flags, lazy
from ..observability import _state as _obs
from .autograd import is_grad_enabled, record
from .dispatch import eager_forward
from .op_registry import _OPS, get_op
from .tensor import Tensor


# python-scalar coercion cache: op attrs like `y * 1e-4 + eps` pay a
# full jnp.asarray device-put per dispatch otherwise (~45% of chain
# dispatch time). jax arrays are immutable, so sharing one per distinct
# (type, value) is safe; keyed by type so True does not alias 1.
# _SCALAR_TENSORS additionally shares the TENSOR wrapper per key: the
# wrapper is internal (never handed to user code), stop_gradient, and
# its payload is never swapped — so the record hot path skips a Tensor
# + AutogradMeta allocation per scalar operand, and a segment registers
# each distinct scalar ONCE instead of once per dispatch. The tracer
# fixer evicts both caches in lockstep (analysis/fixes.py).
_SCALAR_CACHE: dict = {}
_SCALAR_TENSORS: dict = {}

# the whole-step driver's arm cell (lazy owns it; bound once here so
# the disarmed prologue check is one global + one list read per op).
# _NC_DRIVE is the native drive_record entry, installed by
# lazy._native_core alongside _DRIVE_OK — the cell can only hold a
# state while _DRIVE_OK is set, so a non-None cell implies a bound fn.
_DRIVE_CELL = lazy._DRIVE_CELL
_NC_DRIVE = None

_TRACER_CLS = jax.core.Tracer


def _coerce(x):
    if x is None or isinstance(x, Tensor):
        return x
    # jnp.asarray keeps python scalars weakly typed so dtype promotion
    # matches jax semantics (x_bf16 + 1.0 stays bf16).
    if isinstance(x, (bool, int, float)):
        # floats key on the sign bit too: hash(-0.0) == hash(0.0), and
        # substituting a cached +0.0 for -0.0 flips e.g. 1/x to +inf
        key = (type(x), x, math.copysign(1.0, x)) \
            if isinstance(x, float) else (type(x), x)
        t = _SCALAR_TENSORS.get(key)
        if t is not None:
            return t
        v = _SCALAR_CACHE.get(key)
        if v is None:
            v = jnp.asarray(x)
            if isinstance(v, _TRACER_CLS):
                # inside a jax trace (to_static/vmap) array creation is
                # staged: caching the tracer would leak it into every
                # dispatch after the trace exits
                return Tensor(v, stop_gradient=True)
            if len(_SCALAR_CACHE) > 4096:
                _SCALAR_CACHE.clear()
                _SCALAR_TENSORS.clear()
            _SCALAR_CACHE[key] = v
        t = Tensor(v, stop_gradient=True)
        if len(_SCALAR_TENSORS) > 4096:
            _SCALAR_TENSORS.clear()
        _SCALAR_TENSORS[key] = t
        return t
    return Tensor(jnp.asarray(x), stop_gradient=True)


def apply(op_name: str, *inputs, **attrs):
    """Execute a registered op eagerly on Tensors. Returns Tensor or tuple.
    Under paddle.static (enable_static), records the op into the current
    Program instead (the ProgramDesc/PIR build path, SURVEY L9/L14)."""
    # whole-step driver (zero-python steady state): while a promoted
    # step plan is armed, ONE C call owns this dispatch end to end —
    # coercion, op resolve, replay commit, multi_output unwrap. The
    # disarmed cost is one list read. None/NotImplemented mean the
    # driver retired (mismatch, plan complete, punt) and this op falls
    # through to the ordinary path below, which re-judges it in full.
    if _APPLY_FAST and _DRIVE_CELL[0] is not None:
        r = _NC_DRIVE(_DRIVE_CELL[0], op_name, inputs,
                      attrs, is_grad_enabled)
        if r is not None and r is not NotImplemented:
            return r
    op = _OPS.get(op_name)
    if op is None:
        op = get_op(op_name)   # raises the canonical unknown-op error
    # coerce pass: the Tensor / cached-scalar cases inline (the common
    # operands of the record hot path); everything else takes _coerce
    ts = []
    for x in inputs:
        tx = type(x)
        if tx is Tensor:
            t = x
        elif tx is float:
            t = _SCALAR_TENSORS.get((float, x, math.copysign(1.0, x)))
            if t is None:
                t = _coerce(x)
        elif tx is int or tx is bool:
            t = _SCALAR_TENSORS.get((tx, x))
            if t is None:
                t = _coerce(x)
        else:
            t = _coerce(x)
        ts.append(t)

    # record fast path, gated at the DISPATCH level: when no dispatch
    # interceptor is installed (_APPLY_FAST: no static recorder, no amp
    # hook, no profiler cb, no per-op mode) and the ambient window is
    # replaying an armed skeleton, one C call records this op — the
    # native matcher punts (NotImplemented) on tracer payloads, exotic
    # attrs and anything else it cannot judge, falling through to the
    # full path below, which re-derives everything itself. This is THE
    # native entry (ctx.record runs only the python matcher); its
    # exclusions mirror lazy._record_fast's self-gating — keep in sync.
    if _APPLY_FAST:
        ctx = lazy.current_context()
        if ctx is not None and ctx._skel_live:
            sk = ctx._skeleton
            if sk is None:
                sk = ctx._select_skel(op)   # first record of a segment
            if sk is not None and lazy._NC is not None \
                    and sk.gen == lazy._FAST_GEN \
                    and not flags.STATIC_CHECKS_ACTIVE \
                    and not (lazy.PERF_SRC or _obs.COMPUTE):
                r = lazy._NC.skel_record(ctx, sk.ctups, sk.in_sig, op,
                                         ts, attrs, is_grad_enabled)
                if r is None:
                    # sibling-shape switch: another skeleton in this
                    # leading-op bucket may own the divergent suffix
                    # (skel_record mutates nothing before a mismatch,
                    # so one retry against the sibling is safe)
                    sk = ctx._switch_skel(op)
                    if sk is not None:
                        r = lazy._NC.skel_record(ctx, sk.ctups,
                                                 sk.in_sig, op, ts,
                                                 attrs, is_grad_enabled)
                if type(r) is tuple:
                    lazy.FAST_OPS += 1
                    cap = ctx._max_override
                    if len(ctx.pending) >= (lazy._MAX_SEG_OPS
                                            if cap is None else cap):
                        ctx.flush("segment_cap")
                    elif sk.plan is not None and _DRIVE_CELL[0] is None:
                        # promoted shape: hand the REST of this segment
                        # to the native whole-step driver (one C call
                        # per op, no gate) — armed after the first fast
                        # record so the drive cursor starts in sync
                        lazy._arm_drive(ctx, sk)
                    return r if op.multi_output else r[0]
                if r is None:
                    ctx._skel_live = False

    # the enclosing-jax-trace scan (amp casting cannot INTRODUCE a
    # tracer into an all-concrete operand list, so scanning the
    # pre-cast operands is equivalent to the old post-cast scan)
    tracer = False
    for t in ts:
        if t is not None and isinstance(t._payload, _TRACER_CLS):
            tracer = True
            break
    if _static_recorder is not None:
        return _static_recorder(op_name, ts, attrs)
    if _amp_hook is not None:
        ts = _amp_hook(op_name, ts)
    ctx = lazy.current_context()
    if ctx is not None and tracer:
        # op runs under an enclosing jax trace (to_static/sot jit body):
        # tracers must never be recorded into the fusion window — a
        # flush after that trace exits would replay dead tracers.
        # Dispatch inline; the nested jit call inlines into the trace.
        ctx = None
    if ctx is not None and (_profile_cb is not None or _PER_OP_MODE):
        # per-op host tracing / NaN scans / per-op timing need per-op
        # dispatch: bypass the fusion window (pending work lands first so
        # event order matches execution order)
        ctx.flush("per_op_mode")
        ctx = None
    if ctx is not None:
        try:
            outs = ctx.record(op, ts, attrs)
        except Exception as e:
            # un-capturable op (data-dependent shapes, host-side body):
            # graph break — run what's pending, then this op eagerly.
            # The failure is stashed (as a string, no traceback pin) so
            # the perf analyzer can name WHY the window broke.
            ctx._last_record_error = (op_name, f"{type(e).__name__}: {e}")
            ctx.flush("record_fallback:" + op_name)
        else:
            # cap-flush OUTSIDE the handler: a segment that fails to
            # compile/run must raise, not be mistaken for a bad op
            ctx.maybe_cap_flush()
            return outs if op.multi_output else outs[0]
    vals = tuple(t._value if t is not None else None for t in ts)
    if _obs.METRICS:
        # per-op dispatches bypassing the fusion window (window off,
        # tracer inputs, per-op profiling modes, record fallbacks) —
        # the counterpart of segment.ops for hot-path health checks
        from ..observability import metrics
        metrics.inc("eager.ops")
    if _profile_cb is not None:
        with _profile_cb(op_name):
            out_vals = eager_forward(op, vals, attrs)
    else:
        out_vals = eager_forward(op, vals, attrs)
    if _obs.MEM:
        # census birth site for per-op eager outputs: the op name
        # (Tensor.__init__ reads the thread-local hint)
        from ..observability import memory as _memtel
        _memtel.set_site("eager:" + op_name)
        try:
            outs = tuple(Tensor(v) for v in out_vals)
        finally:
            _memtel.clear_site()
    else:
        outs = tuple(Tensor(v) for v in out_vals)
    if is_grad_enabled() and any(
            t is not None and not t.stop_gradient for t in ts):
        record(op, attrs, ts, outs)
    return outs if op.multi_output else outs[0]


# Watcher-kept gate for the two per-op-mode flags: the record hot path
# used to pay two registry lookups per DISPATCHED OP re-reading flags
# that flip a handful of times per process. set_flags keeps it coherent
# (the STATIC_CHECKS_ACTIVE pattern), so mid-session flips still bypass
# the fusion window on the very next op.
_PER_OP_MODE = False

# One coherent gate for the dispatch-level record fast path: True iff
# NO dispatch interceptor is installed (static recorder, amp hook,
# profiler cb, per-op NaN/benchmark mode). Kept in sync by the four
# setters below, so apply() pays a single global read per op.
# (The interceptor slots are declared here — before the flag watchers
# fire the first _sync_apply_fast — and documented at their setters.)
_APPLY_FAST = True
_static_recorder = None
_profile_cb = None
_amp_hook = None


def _sync_apply_fast():
    global _APPLY_FAST
    _APPLY_FAST = (_static_recorder is None and _amp_hook is None
                   and _profile_cb is None and not _PER_OP_MODE)
    if not _APPLY_FAST:
        # an interceptor changes what apply() must do per op: retire
        # any armed whole-step drive through its context
        lazy._drive_disarm()


def _sync_per_op_mode(_value=None):
    global _PER_OP_MODE
    _PER_OP_MODE = bool(flags.flag_value("FLAGS_check_nan_inf")
                        or flags.flag_value("FLAGS_benchmark"))
    _sync_apply_fast()


flags.watch_flag("FLAGS_check_nan_inf", _sync_per_op_mode)
flags.watch_flag("FLAGS_benchmark", _sync_per_op_mode)


# Static-graph recorder (installed by paddle_tpu.static.enable_static):
# when set, apply() records ops into the current Program instead of
# executing them.
def set_static_recorder(fn):
    global _static_recorder
    _static_recorder = fn
    _sync_apply_fast()


# Profiler instrumentation hook (host tracer RecordEvent per op; installed
# by paddle_tpu.profiler, the eager_gen.py:326 dispatch-event analog).
def set_profile_cb(fn):
    global _profile_cb
    _profile_cb = fn
    _sync_apply_fast()


# AMP interception is installed by paddle_tpu.amp (kept as a hook here to
# avoid a hard dependency; see amp/auto_cast.py — the hook is live only
# while an auto_cast scope is active somewhere in the process).
def set_amp_hook(fn):
    global _amp_hook
    _amp_hook = fn
    _sync_apply_fast()
