"""Lazy op-capture engine: the eager fusion window + SOT graph builder.

Two reference roles land here, rebuilt the XLA way:

- the *fusion buffer / lazy trace window* the reference gets from CUDA
  stream asynchrony (per-op kernels queue on a stream; the host runs
  ahead): under `lazy_guard()` eager ops are RECORDED instead of
  dispatched one executable at a time, and a whole pending segment runs
  as ONE jitted XLA program the first time any concrete value is needed.
  This removes per-op dispatch latency and lets XLA fuse across op
  boundaries (SURVEY §7 hard part #1).
- the *FunctionGraph* under SOT-style bytecode capture
  (python/paddle/jit/sot/symbolic/symbolic_context.py role): jit/sot's
  OpcodeExecutor runs user bytecode under this context; every framework
  op joins the graph, and any graph break (print, .numpy(), a
  data-dependent branch) is just a flush — the remaining trace resumes
  into a new segment automatically.

Materialization triggers: reading `Tensor._value` (property), exiting
the guard, `backward()`, or the segment hitting
FLAGS_lazy_max_segment_ops. Shape/dtype/ndim metadata reads answer from
the recorded aval WITHOUT materializing.

Compiled segments are cached by a structural signature (op names, attrs,
wiring, input avals), so steady-state replays cost one cache lookup and
one XLA execution per segment.
"""
from __future__ import annotations

import contextlib
import functools
import warnings
import weakref
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import dispatch
from . import flags as _flags
from . import persist as _persist
from ..observability import _state as _OBS
from .async_flush import resolve_pending
from .cache import ExecCache
from .op_registry import OpDef

# Compiled-runner caches (LRU-bounded by FLAGS_executable_cache_capacity):
#   _SEG_CACHE   (signature, donate_mask) -> jitted segment runner
#   _FUSED_CACHE (signature, grad_in, root) -> jitted fwd+vjp step runner
# The stat names feed cache.<name>.{hit,miss} observability counters;
# cache.fused_step is THE steady-state step-cache hit-rate signal.
_SEG_CACHE: Dict[Tuple, Any] = ExecCache(stat="segment")
_FUSED_CACHE: Dict[Tuple, Any] = ExecCache(stat="fused_step")
# out-aval cache for record-time shape inference: LRU-bounded like the
# executable caches (shape-polymorphic workloads mint unbounded keys).
# No ExecCache stat: it is not an executable cache, so its hit/miss
# counters live under record.aval_cache.* (counted in _out_avals) and
# stay OUT of the derived cache_hit_rate headline.
_AVAL_CACHE: Dict[Tuple, Tuple] = ExecCache()

# Mesh epoch: a salt baked into every segment/step-cache signature.
# Elastic re-planning (resilience/adaptive.py) bumps it after moving
# live state onto a new device mesh, so the first post-replan step
# compiles exactly ONE fresh executable against the new layout instead
# of silently hitting a runner whose donation bookkeeping and sharding
# assumptions were fixed on the old mesh; every later step hits the
# re-keyed entry (recompile-exactly-once, asserted in
# tests/test_resilience.py via the compiles.fused_step counter).
MESH_EPOCH = 0

# Ambient SPMD mesh (distributed/spmd.py activates/clears this; lazy
# NEVER imports distributed). While set, cache signatures gain a
# sharding component — (mesh shape+axes, per-input PartitionSpec) —
# and the compile sites lower with GSPMD in_shardings so collectives
# live inside the executable. None = the zero-cost single-device path:
# one module-attr read per flush, zero extra key bytes.
SPMD = None

# sharding-component builds (diagnostics + the bench row-12 off-freeze
# assert: a no-mesh run must never touch the sharding key path)
SHARD_SIG_BUILDS = 0

# Perf-lint flush observer (analysis/perf_checks.py installs
# hooks.on_perf_flush here while a PerfRecorder is active): every seal
# of the fusion window — flush, per-op replay, fused backward — reports
# (ctx, reason, pending) so the static perf analyzer can attribute
# fusion-window breaks and host syncs to the recorded ops' source
# lines. None = one module-attr read per flush, zero work.
PERF_OBSERVER = None

# Forced src capture for perf traces (nesting counter): _PendingOp.src
# is normally captured only under FLAGS_static_checks, but perf
# diagnostics must point at Python source even with the sanitizer off —
# the analysis CLI and check_perf bump this around their own traces.
PERF_SRC = 0

# True when some executable was cached WITHOUT cost_analysis capture
# (compiled while FLAGS_compute_telemetry was off). Entering the
# compute plane bumps MESH_EPOCH only while this is set — so a
# monitoring loop that flips the plane on/off around each budget
# sample (budget.collect) does not invalidate every compiled-program
# cache in the process on every sample once the warm entries already
# carry their analyses.
COST_STALE = False


def mark_cost_stale():
    global COST_STALE
    COST_STALE = True


# ---- trace-stable record fast path (FLAGS_record_fast_path).
# A steady-state train step records the same op sequence every
# iteration — the signature memo proves it at seal time. While proven,
# the context retains the sealed segment's op SKELETON and replays it
# against the incoming (op, attrs, input-wiring) stream
# position-for-position: matching ops skip jax.eval_shape / aval-cache
# key construction / attrs copying / sig-entry interning entirely and
# reuse the skeleton's cached out-avals + interned entries, re-binding
# only external input payloads. Any mismatch falls back to the full
# record path for the rest of the segment. FAST_OPS counts replayed
# ops process-wide (tests + bench row 17); _FAST_GEN is the skeleton
# generation — bumping it (mesh-epoch bump / replan, relevant
# set_flags) invalidates every armed skeleton at its next fast record.
FAST_OPS = 0
_FAST_PATH = True
_FAST_GEN = 0
# C mirror of _FAST_GEN + the whole-step driver's arm cell — declared
# BEFORE the flag watchers below fire (they invalidate at import); the
# driver itself is documented at _DriveState further down
_FAST_GEN_CELL: list = [0]
_DRIVE_CELL: list = [None]
_DRIVE_OK = False


def invalidate_skeletons(_value=None) -> int:
    """Bump the skeleton generation: every context drops its armed
    record skeleton on the next fast-record attempt (re-armed at the
    next memo-proven seal). The C mirror cell retires any in-flight
    whole-step drive at its very next op for the same events."""
    global _FAST_GEN
    _FAST_GEN += 1
    _FAST_GEN_CELL[0] = _FAST_GEN
    return _FAST_GEN


def _sync_fast_path_gate(value):
    global _FAST_PATH
    _FAST_PATH = bool(value)
    invalidate_skeletons()


_flags.watch_flag("FLAGS_record_fast_path", _sync_fast_path_gate)
# sanitizer / provenance / segment-shape mode changes invalidate armed
# skeletons (the fast path re-proves the stream under the new mode)
_flags.watch_flag("FLAGS_static_checks", invalidate_skeletons)
_flags.watch_flag("FLAGS_compute_telemetry", invalidate_skeletons)
_flags.watch_flag("FLAGS_lazy_max_segment_ops", invalidate_skeletons)

# ---- whole-step replay promotion (FLAGS_step_replay_after). A shape
# whose skeleton fully replays N consecutive sealed iterations gets a
# STEP PLAN: the seal skips signature reconstruction entirely and runs
# the cached executable under a ``segment::replay_step`` span (goodput
# prices it as productive execute). Any structural drift, mechanical
# invalidation (mesh epoch, watched flags, note_inplace, grad-mode
# flip — they all break the per-op replay that feeds the plan) or a
# live-set change demotes that shape to per-op skeleton replay and
# re-arms the streak. REPLAY_STEPS counts driven seals process-wide
# (bench rows 17/18 and the off-freeze assertions read it).
REPLAY_STEPS = 0
_STEP_REPLAY_AFTER = 3


def _sync_step_replay_gate(value):
    global _STEP_REPLAY_AFTER
    _STEP_REPLAY_AFTER = int(value or 0)
    invalidate_skeletons()


_flags.watch_flag("FLAGS_step_replay_after", _sync_step_replay_gate)

# ---- the whole-step NATIVE driver (zero-python steady state). Once a
# shape's skeleton carries a promoted step plan, the executor gate arms
# a _DriveState in _DRIVE_CELL after the segment's FIRST fast record:
# from then on apply() hands each dispatch to ONE C call
# (eager_core.drive_record) that coerces operands, validates against
# the plan cursor and mints the outputs — no python-level gate, scalar
# cache probe, context lookup or per-op counter write. The C side holds
# the two mutable cells below (registered once via bind_drive):
# _FAST_GEN_CELL mirrors _FAST_GEN, so every mechanical invalidation
# event (mesh epoch, watched flags, step-replay flag) retires an
# in-flight drive at its next op, and _DRIVE_CELL[0] is the armed
# state (None = disarmed). The driver retires ITSELF on plan
# completion, segment cap and any mismatch; _drive_reconcile writes
# the driven cursor + batched counters back at every python re-entry
# point that reads them (flush, segment reset, note_inplace,
# interceptor installs via executor._sync_apply_fast). When the C
# library is unavailable (_DRIVE_OK stays False) the bit-exact pure
# python driver is the per-op skeleton replay + the _step_plan_sig
# seal — same admissions, same demotions, just not one-call-per-op.


class _DriveState:
    """Flat per-segment view of everything drive_record touches per op,
    one resolved slot offset away: the plan's ctups + sealed in-sig,
    the context's CURRENT segment lists (the same objects the context
    attributes name — the driver appends to them in place), the armed
    generation, the owning thread and the replay cursor. `n_driven`
    batches the per-op counters until retire/reconcile."""

    __slots__ = ("ctx", "ctups", "in_sig", "in_ids", "in_tensors",
                 "in_vals", "in_meta", "in_pins", "pending", "sig_ops",
                 "pinned", "pos", "gen", "cap", "n_driven", "tid",
                 "sc_k", "sc_v")


def _arm_drive(ctx, sk):
    """Publish a drive for the rest of the current segment (called by
    the executor gate right after a successful fast record of a
    plan-carrying skeleton)."""
    if not _DRIVE_OK:
        return
    d = _DriveState()
    d.ctx = ctx
    d.ctups = sk.ctups
    d.in_sig = sk.in_sig
    d.in_ids = ctx._in_ids
    d.in_tensors = ctx._in_tensors
    d.in_vals = ctx._in_vals
    d.in_meta = ctx._in_meta
    d.in_pins = ctx._in_pins
    d.pending = ctx.pending
    d.sig_ops = ctx._sig_ops
    d.pinned = ctx.on_flush is not None
    d.pos = ctx._skel_pos
    d.gen = sk.gen
    cap = ctx._max_override
    d.cap = _MAX_SEG_OPS if cap is None else cap
    d.n_driven = 0
    d.tid = _threading.get_ident()
    # per-drive scalar memo: scalar-OBJECT identity -> wrapper tensor
    # (literals from co_consts keep identity across iterations, so the
    # drive's steady state skips the key-tuple hash probe per operand;
    # the memo lives exactly as long as the drive, so it can never
    # disagree with the in_ids registrations made through it)
    d.sc_k = []
    d.sc_v = []
    _DRIVE_CELL[0] = d


def _drive_reconcile(ctx):
    """Write an armed drive's cursor and batched counters back to its
    context and disarm. Idempotent with the C driver's own retire (the
    cell is cleared first, counters are zeroed on read) — called at
    every python re-entry point that reads _skel_pos/_fast_ops or
    rebinds the segment lists."""
    global FAST_OPS
    d = _DRIVE_CELL[0]
    if d is None or d.ctx is not ctx:
        return
    _DRIVE_CELL[0] = None
    ctx._skel_pos = d.pos
    n = d.n_driven
    if n:
        d.n_driven = 0
        ctx._fast_ops += n
        ctx.ops_recorded += n
        FAST_OPS += n


def _drive_disarm():
    """Retire any armed drive through its context — interceptor
    installs and per-op modes change what apply() must do per op, so
    the plan's whole-step equivalence no longer holds."""
    d = _DRIVE_CELL[0]
    if d is not None:
        _drive_reconcile(d.ctx)


def bump_mesh_epoch() -> int:
    """Invalidate the compiled-segment and fused-step cache keys (the
    old entries age out of the LRU; nothing is recompiled until the
    next flush). Armed record skeletons are invalidated too — a replan
    must re-prove the op stream on the new mesh."""
    global MESH_EPOCH
    MESH_EPOCH += 1
    invalidate_skeletons()
    return MESH_EPOCH


# ---- hot-path flag gates. current_context()/max_ops used to pay ~4
# registry lookups per RECORDED OP; the watcher pattern
# (STATIC_CHECKS_ACTIVE) caches each flag into a module attribute that
# set_flags keeps coherent, so mid-session flips still take effect
# immediately (test_flags_surface contract) at one attribute read.
_LAZY_ENABLE = True
_EAGER_FUSION = True
_MAX_SEG_OPS = 256
_DONATE_INPUTS = True


def _mk_gate(name):
    def _set(v, _n=name):
        globals()[_n] = v
    return _set


_flags.watch_flag("FLAGS_lazy_enable", _mk_gate("_LAZY_ENABLE"))
_flags.watch_flag("FLAGS_eager_fusion", _mk_gate("_EAGER_FUSION"))
_flags.watch_flag("FLAGS_lazy_max_segment_ops", _mk_gate("_MAX_SEG_OPS"))
_flags.watch_flag("FLAGS_lazy_donate_inputs", _mk_gate("_DONATE_INPUTS"))

# flush reasons eligible for the async pipeline: seals where the
# recording thread genuinely runs ahead. A cap mid-record always
# qualifies; a guard EXIT does too — the code after the `with` block
# (or after a SOT-captured call returns) continues on pending values
# and only blocks at a real read. Materialize reads block on the
# result anyway — going async there only adds a thread hop to the
# critical path — and guard_error stays synchronous (unwind path).
_ASYNC_REASONS = frozenset(("segment_cap", "guard_exit"))

# set the first time a segment is flushed asynchronously; gates the
# resolve-scan at consumption points so the sync-only path never pays
# even the per-value getattr walk
_ASYNC_SEEN = False


class _CachedKey:
    """Executable-cache key wrapper with a precomputed hash.

    The steady-state signature memo returns the SAME _CachedKey object
    every step, so the per-step cache lookup costs one cached-int hash
    and one identity compare instead of re-hashing a structure that
    grows with the op count. Subscripting delegates to the wrapped
    tuple (register_segment_grad slices sig[1]/sig[2]/sig[4]
    positionally)."""

    __slots__ = ("sig", "_h")

    def __init__(self, sig):
        self.sig = sig
        self._h = hash(sig)

    def __hash__(self):
        return self._h

    def __eq__(self, other):
        if self is other:
            return True
        if isinstance(other, _CachedKey):
            return self._h == other._h and self.sig == other.sig
        return NotImplemented

    def __getitem__(self, i):
        return self.sig[i]

    def __repr__(self):
        return f"_CachedKey({self.sig!r})"


# Hot-import bindings: record()/_lazy_tensor() run per recorded op, and
# a function-local `from .tensor import Tensor` costs an importlib
# round-trip per call (~190 of them per 32-op chain step in the
# profile).
# Bound once on first use — module top-level import would be cyclic
# during package init (tensor -> autograd -> dispatch while lazy loads).
_TENSOR_CLS = None
_AUTOGRAD_META = None
_IS_GRAD_ENABLED = None


def _bind_hot_imports():
    global _TENSOR_CLS, _AUTOGRAD_META, _IS_GRAD_ENABLED
    from .autograd import AutogradMeta, is_grad_enabled
    from .tensor import Tensor
    _TENSOR_CLS = Tensor
    _AUTOGRAD_META = AutogradMeta
    _IS_GRAD_ENABLED = is_grad_enabled
    return Tensor


# flush reasons that BREAK the fusion window mid-step (vs. the natural
# whole-step seals: materialize, backward_fused, grad_targets, guard
# exits). Each break forfeits the step cache and the optimizer's
# donation fast path for that window — the BUDGET_r06 eager-GPT finding
# (4 record_fallback breaks/step) promoted to a first-class counter.
_WINDOW_BREAK_REASONS = frozenset(
    ("record_fallback", "segment_cap", "ambient_disable", "guard_error"))


def _obs_flush_span(reason: str, n_ops: int, n_inputs: int, n_live: int,
                    n_donate: int, n_fast: int = 0):
    """Counters + the begun flush span. Callers gate on _OBS.ACTIVE —
    this never runs when observability, tracing, and the flight
    recorder are all off."""
    if _OBS.METRICS:
        from ..observability import metrics
        metrics.inc("segment.flushes")
        # record_fallback:<op> collapses to one reason bucket
        head = reason.split(":", 1)[0]
        metrics.inc("segment.flush_reason." + head)
        if head in _WINDOW_BREAK_REASONS:
            metrics.inc("fusion.window_breaks")
            metrics.inc("fusion.window_breaks." + head)
        metrics.inc("segment.ops", n_ops)
        if n_fast:
            # skeleton-replayed records of this segment (counted at
            # seal so the fast path pays zero per-op registry work);
            # budget surfaces record.* next to the segment counters
            metrics.inc("record.fast_ops", n_fast)
        if n_donate:
            metrics.inc("segment.donated_inputs", n_donate)
    from ..observability.spans import span
    return span(f"segment::flush[{reason}]", hist="segment.flush_us",
                reason=reason, ops=n_ops, inputs=n_inputs,
                live=n_live, donated=n_donate).begin()


def _obs_exec_span(compiled: bool, n_ops: int, driven: bool = False):
    """The compile-vs-cached-execute split under a flush span (compile
    counters are bumped at the call sites, which know WHICH cache
    missed: compiles.segment vs compiles.fused_step). A promoted
    whole-step seal takes its own ``segment::replay_step`` name —
    goodput prices it in the execute bucket, and the distinct histogram
    is the step-driver's latency meter."""
    from ..observability.spans import span
    if driven and not compiled:
        return span("segment::replay_step",
                    hist="segment.replay_step_us", ops=n_ops).begin()
    return span("segment::compile" if compiled else "segment::execute",
                hist=("segment.compile_us" if compiled
                      else "segment.execute_us"), ops=n_ops).begin()


def _obs_flush_failed(reason: str, err: BaseException):
    """Failed flush: the flight recorder's post-mortem trigger."""
    if _OBS.FLIGHT:
        from ..observability import flight
        flight.on_error("flush_failed", f"reason={reason}: {err!r}")


def _nan_scan_segment(pending, live, out_vals, kind, in_vals=(),
                      extra=None):
    """FLAGS_check_nan_inf sweep over a flushed/replayed segment's live
    outputs, blaming the producing op WITH its record-time file:line
    provenance (_PendingOp.src, captured while checks are on) — a
    postmortem must name where the tripping value was recorded, not
    just which kernel emitted it. On a trip, the numerics plane's NaN
    forensics re-runs the range propagation over the offending program
    and attaches the ranked suspect ops to the flight dump before the
    FloatingPointError continues up. `extra` is an optional
    (label, values) pair swept after the live outputs (the fused
    backward's gradient bundle)."""
    try:
        for (j, _s), val in zip(live, out_vals):
            p = pending[j]
            src = getattr(p, "src", None)
            dispatch._check_nan_inf(
                f"{p.op.name} ({kind}" + (f" @ {src})" if src else ")"),
                (val,))
        if extra is not None:
            dispatch._check_nan_inf(extra[0], tuple(extra[1]))
    except FloatingPointError:
        from ..analysis import hooks as _ahooks
        _ahooks.on_nan_trip(None, pending, list(in_vals), kind)
        raise


def _oom_convert(e: BaseException, where: str, mem_info=None):
    """RESOURCE_EXHAUSTED at an execute site becomes the typed
    ``base.core.ResourceExhaustedError`` carrying the memory
    postmortem (top live buffers with provenance, failing executable's
    memory analysis, watermark). Anything else passes through at the
    cost of one substring check — this only runs on the error path."""
    if "RESOURCE_EXHAUSTED" not in str(e):
        return e
    from ..observability import memory as _memtel
    return _memtel.on_oom(e, where, mem_info)


def _inject_exec_oom():
    """``exec::oom`` drill site: a synthetic RESOURCE_EXHAUSTED at the
    execute boundary (resilience/faults.py kind ``oom``), fired at all
    three execute sites so the OOM postmortem path — including the
    async worker's typed re-raise at the sync point — is testable
    without exhausting real device memory. Callers pre-gate on
    ``_flags.FAULT_INJECT_ACTIVE``."""
    from ..distributed.resilience import faults as _faults
    _faults.inject("exec::oom")


def _spmd_jit(fn, donate, run_vals, spmd):
    """jit with explicit GSPMD input layouts when an ambient mesh is
    active: every input's committed on-mesh sharding (replicated for
    the rest) becomes an ``in_shardings`` entry, so the ONE compiled
    program is partitioned over the dp×mp mesh and its collectives
    (gradient all-reduce, TP exchanges) are emitted by the compiler
    instead of driven from the host. Tracer inputs fall back to plain
    jit (spmd.in_shardings returns None)."""
    if spmd is not None:
        shardings = spmd.in_shardings(run_vals)
        if shardings is not None:
            if _OBS.METRICS:
                from ..observability import metrics
                metrics.inc("compiles.spmd")
            return jax.jit(fn, donate_argnums=donate,
                           in_shardings=shardings)
    return jax.jit(fn, donate_argnums=donate)


def _note_compiled_comm(cache, key, spmd, in_vals, out_vals, site,
                        gather_only=False):
    """Observability parity for collectives compiled INTO a program:
    estimate their payload from the in/out sharding specs (computed
    once per compile, cached on the ExecCache entry like the memory
    analysis) and count them per execution as
    ``comm.bytes.compiled.<site>`` — so moving collectives off the
    host does not blind the PR-8 comm-overlap report. Callers gate on
    ``_OBS.METRICS and SPMD``."""
    est = cache.comm_info(key)
    if est is None:
        est = spmd.estimate_bytes(in_vals, out_vals,
                                  gather_only=gather_only)
        cache.note_comm(key, est)
    if est:
        from ..observability import metrics
        metrics.inc("comm.bytes.compiled." + site, est)


def _mesh_devices(spmd) -> int:
    """Pricing basis for the compute plane's per-chip cost analysis:
    the ambient mesh's device count (1 without a mesh)."""
    if spmd is None:
        return 1
    n = 1
    for s in spmd.shape:
        n *= int(s)
    return n


def _compile_segment_runner(pending, live, donate, run_vals, sig,
                            spmd=None):
    """Build one segment's cached runner. With the memory or compute
    telemetry plane on (and concrete inputs), compile through the jax
    AOT path so the executable's ``memory_analysis()`` /
    ``cost_analysis()`` land on the ExecCache entry exactly once per
    compile; otherwise the plain jit wrapper. Both are interchangeable
    callables — the cache key already pins the input signature, so an
    AOT-compiled entry only ever sees matching arguments. `spmd` is
    the ambient mesh the caller keyed the segment against (the async
    worker passes its seal-time capture)."""
    jitted = _spmd_jit(_build_segment_fn(pending, live), donate,
                       run_vals, spmd)
    if not _OBS.COMPUTE:
        mark_cost_stale()
    if (_OBS.MEM or _OBS.COMPUTE or _persist.ACTIVE) and not any(
            isinstance(v, jax.core.Tracer) for v in run_vals):
        from ..observability import memory as _memtel
        with _quiet_donation_compile():
            return _memtel.aot_compile(jitted, run_vals, stat="segment",
                                       cache=_SEG_CACHE,
                                       key=(sig, donate),
                                       n_devices=_mesh_devices(spmd))
    return jitted


def _spmd_for_compile(in_vals):
    """The ambient mesh a program should be PINNED against, or None.
    A segment whose key-time inputs include unresolved PendingValues
    compiles without in_shardings: their layout is unknowable at seal
    time, the key carries the "?" sentinel for them, and an unpinned
    jit re-specializes per input layout internally — so one cache
    entry stays correct for every layout the producer hands it."""
    spmd = SPMD
    if spmd is None:
        return None
    if _ASYNC_SEEN and any(getattr(v, "_is_pending_value", False)
                           for v in in_vals):
        return None
    return spmd


def _compile_fused_runner(pending, live, grad_in, root_k, run_vals, key,
                          spmd=None):
    """Fused fwd+vjp step runner, AOT-compiled for its memory / cost
    analysis when a telemetry plane is on (the steady-state step cache
    can then report its compiled footprint and price its FLOPs on
    every later hit)."""
    jitted = _spmd_jit(_build_fused_fn(pending, live, grad_in, root_k),
                       (), run_vals, spmd)
    if not _OBS.COMPUTE:
        mark_cost_stale()
    if (_OBS.MEM or _OBS.COMPUTE or _persist.ACTIVE) and not any(
            isinstance(v, jax.core.Tracer) for v in run_vals):
        from ..observability import memory as _memtel
        with _quiet_donation_compile():
            return _memtel.aot_compile(jitted, run_vals,
                                       stat="fused_step",
                                       cache=_FUSED_CACHE, key=key,
                                       n_devices=_mesh_devices(spmd))
    return jitted


def _persist_sig(sig) -> Tuple:
    """Disk identity of a segment signature: the raw key with its
    MESH_EPOCH component (position 4) zeroed. The epoch salt exists to
    re-key IN-MEMORY entries across elastic re-plans, but every
    structural consequence of a re-plan already lives in the signature
    (shard_sig / input avals / op stream), so two processes — or two
    re-plan cycles landing on the same layout — share one disk entry."""
    raw = sig.sig if isinstance(sig, _CachedKey) else tuple(sig)
    return raw[:4] + (0,) + raw[5:]


def _jit_factory(build_fn, donate, run_vals, spmd):
    """Deferred jit construction for a disk-loaded runner's tracer
    fallback. The in_shardings are resolved NOW (cheap metadata) so
    the retained closure never pins the input BUFFERS — a pinned param
    buffer would defeat the refcount-proof donation checks (lazy's
    _donatable_inputs, the optimizer's _pick_update) for as long as
    the runner lives."""
    shardings = None
    if spmd is not None:
        shardings = spmd.in_shardings(run_vals)

    def factory():
        if shardings is not None:
            return jax.jit(build_fn(), donate_argnums=donate,
                           in_shardings=shardings)
        return jax.jit(build_fn(), donate_argnums=donate)

    return factory


def _disk_runner(kind, norm_key, jit_factory, cache=None, key=None,
                 stat="segment"):
    """Consult the persistent executable cache after an in-memory miss
    and BEFORE ``lower().compile()``. A verified hit rehydrates into a
    runner (telemetry sidecars re-noted so warm loads keep their
    meters) — the caller then takes the cached-execute span and bumps
    no ``compiles.*`` counter. Callers pre-gate on ``_persist.ACTIVE``."""
    payload = _persist.load(kind, norm_key)
    if payload is None:
        return None
    runner = _persist.make_runner(payload, jit_factory)
    if runner is None:
        return None
    _persist.renote(payload, stat, cache, key)
    return runner


def _disk_store(kind, norm_key, runner, cache=None, key=None):
    """Persist a freshly-compiled runner's executable + sidecars. Only
    AOT-compiled runners carry the raw Compiled (`aot_executable`);
    with persistence active the compile helpers always take the AOT
    path for concrete inputs, so a plain-jit runner here means tracer
    inputs — not persistable, skip silently."""
    if getattr(runner, "persisted", False):
        return
    compiled = getattr(runner, "aot_executable", None)
    if compiled is not None:
        _persist.store(kind, norm_key, compiled,
                       _persist.sidecars(runner, cache, key))


def _note_donated_inputs(in_vals, donate):
    """Donation savings accounting: bytes of the input buffers this
    executed program consumed in place (gated on _OBS.MEM by callers)."""
    from ..observability import memory as _memtel
    _memtel.note_donated(sum(getattr(in_vals[i], "nbytes", 0)
                             for i in donate))


@contextlib.contextmanager
def _quiet_donation_compile():
    """Backends without buffer donation (CPU) warn at compile time and
    silently copy instead; donation is a best-effort optimization here,
    not a contract. Scoped around OUR compile-triggering first calls so
    the suppression never leaks into user code, where the same warning
    may be the only signal that their own donate_argnums degraded."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


def _live_aliases(ref):
    """Tensors still ALIASING this pending output. Payload identity is
    the correctness-bearing invariant: a tensor overwritten in place
    mid-segment no longer keeps its old pending output alive — and must
    not be clobbered when the segment's results are bound."""
    return [t for t in (r() for r in ref.trefs)
            if t is not None and t._payload is ref]


class LazyRef:
    """Placeholder payload for one output of one pending op."""

    _is_lazy_ref = True
    __slots__ = ("ctx", "op_idx", "slot", "aval", "requires_grad",
                 "trefs", "__weakref__")

    def __init__(self, ctx, op_idx, slot, aval, requires_grad):
        self.ctx = ctx
        self.op_idx = op_idx
        self.slot = slot
        self.aval = aval              # jax.ShapeDtypeStruct
        self.requires_grad = requires_grad
        self.trefs: List = []         # weakrefs to Tensors aliasing this

    def add_tref(self, tensor):
        self.trefs.append(weakref.ref(tensor))

    def materialize(self):
        self.ctx.flush()


class _PendingOp:
    __slots__ = ("op", "attrs", "wiring", "out_refs", "n_outs", "src")

    def __init__(self, op, attrs, wiring, out_refs, src=None):
        self.op = op
        self.attrs = attrs
        self.wiring = wiring          # per input: ("in", i) | ("op", j, s) | None
        self.out_refs = out_refs      # list[LazyRef]
        self.n_outs = len(out_refs)
        # "file:line" of the recording user frame — captured only under
        # FLAGS_static_checks so diagnostics can point at Python source;
        # deliberately NOT part of the segment signature
        self.src = src


# the view-op family the sanitizer's alias graph tracks (reference
# semantics alias storage). THE authoritative set — it lives here so
# the record hot path gates on it without importing analysis;
# analysis.alias_graph re-exports it as VIEW_OP_NAMES
_VIEW_OP_NAMES = frozenset((
    "reshape", "squeeze", "unsqueeze", "flatten_", "transpose",
    "view_slice", "view_dtype", "strided_slice_", "diagonal_", "split_",
))

# str(np.dtype) costs ~10us a call and the dispatch hot path needs it
# for every input of every signature — memoized per dtype object
_DTYPE_STR: Dict[Any, str] = {}


def _dstr(dt) -> str:
    s = _DTYPE_STR.get(dt)
    if s is None:
        s = _DTYPE_STR[dt] = str(dt)
    return s


# jnp.issubdtype(dt, inexact) walks the numpy type lattice (~1-2us);
# the record hot path asks it per output — memoized per dtype object
_INEXACT_DT: Dict[Any, bool] = {}


def _is_inexact(dt) -> bool:
    r = _INEXACT_DT.get(dt)
    if r is None:
        r = _INEXACT_DT[dt] = bool(jnp.issubdtype(dt, jnp.inexact))
    return r


# Native record core (csrc/eager_core.cc): interned shape/dtype atoms,
# the aval-cache key build + lookup and the sig-entry intern in C.
# Resolved once through dispatch's extension loader; None = the pure
# python path (which must stand alone — the library is best-effort).
# Bench row 17 and the fallback tests force either prong by setting
# _NC/_NC_TRIED directly.
_NC = None
_NC_TRIED = False


def _native_core():
    global _NC, _NC_TRIED, _DRIVE_OK
    _NC_TRIED = True
    ec = dispatch._eager_core()
    if ec is not None and hasattr(ec, "aval_cache_get"):
        if hasattr(ec, "bind_types"):
            from .autograd import AutogradMeta
            from .tensor import Tensor
            ec.bind_types(LazyRef, Tensor, AutogradMeta, _PendingOp,
                          jax.core.Tracer)
        if hasattr(ec, "bind_drive"):
            # whole-step driver registration: the C side keeps direct
            # handles to the op registry, the live scalar-wrapper cache
            # (read per op — can never go stale), the two mutable cells
            # and this module (retire writes FAST_OPS). Refuses (False)
            # when any _DriveState slot offset fails to resolve; the
            # driver then stays off and replay runs per-op.
            try:
                import sys
                from . import executor as _executor
                _DRIVE_OK = bool(ec.bind_drive(
                    _DriveState, _executor._OPS,
                    _executor._SCALAR_TENSORS, _FAST_GEN_CELL,
                    _DRIVE_CELL, sys.modules[__name__]))
                if _DRIVE_OK:
                    _executor._NC_DRIVE = ec.drive_record
            except Exception:
                _DRIVE_OK = False
        _NC = ec
    return _NC


# per-op signature entries interned by content: steady-state memo
# validation compares tuples of IDENTICAL entry objects, so the
# per-step check is n pointer compares (exact, not sampled). Past
# 65536 entries the pool is CLEARED — identity compares degrade to
# tuple equality until repopulation, never correctness (pinned in
# tests/test_record_fastpath.py). The native core keeps its own pool
# with the same overflow rule.
_SIG_ENTRY_INTERN: Dict[Tuple, Tuple] = {}


def _intern_sig_entry(entry: Tuple) -> Tuple:
    nc = _NC if _NC_TRIED else _native_core()
    if nc is not None:
        return nc.sig_entry(entry)
    e = _SIG_ENTRY_INTERN.setdefault(entry, entry)
    if len(_SIG_ENTRY_INTERN) > 65536:
        _SIG_ENTRY_INTERN.clear()
    return e


def _aval_of(x):
    # weak_type MUST survive: python scalars are weak (x64 mode makes
    # them f64-weak) and weak+f32 promotes to f32, not f64
    return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                weak_type=getattr(x, "weak_type", False))


def _out_avals(op: OpDef, attrs, in_avals, akey=None):
    if akey is None:
        akey = dispatch.attrs_key(attrs)
    backend = jax.default_backend()
    nc = _NC if _NC_TRIED else _native_core()
    if nc is not None:
        # C builds the (op, backend, attrs, per-aval atom) key in one
        # pass over interned shape/dtype atoms and probes the C-side
        # cache; the python dict below is the standalone fallback
        hit = nc.aval_cache_get(op.name, backend, akey, in_avals)
        key = None
    else:
        key = (op.name, backend, akey,
               tuple((tuple(a.shape), _dstr(a.dtype), a.weak_type)
                     if a is not None else None for a in in_avals))
        hit = _AVAL_CACHE.get(key)
    if _OBS.METRICS:
        # record.aval_cache.*, NOT cache.*: the derived cache_hit_rate
        # headline sums executable caches only
        from ..observability import metrics
        metrics.inc("record.aval_cache.hit" if hit is not None
                    else "record.aval_cache.miss")
    if hit is not None:
        return hit
    fn = functools.partial(op.kernel_for(backend), **attrs)
    out = jax.eval_shape(fn, *in_avals)
    outs = out if op.multi_output else (out,)
    hit = tuple(jax.tree_util.tree_leaves(outs))
    if len(hit) != len(outs):
        # nested outputs: treat as un-capturable
        raise TypeError(f"op {op.name} has nested outputs")
    if nc is not None:
        # the native pool honors the same capacity flag (clear-on-
        # overflow rather than LRU; inserts are compile-path cold)
        nc.aval_cache_put(op.name, backend, akey, in_avals, hit,
                          int(_flags.flag_value(
                              "FLAGS_executable_cache_capacity")))
    else:
        _AVAL_CACHE[key] = hit
    return hit


def _fast_attr_safe(v) -> bool:
    """True when an attr value is cheap AND safe to compare by dict
    equality on the fast path (primitives and tuples thereof — the
    same class the attrs-key intern treats as canonical). ndarrays /
    lists / exotic values take the interned-key comparison instead."""
    if v is None or type(v) in (bool, int, float, str, bytes):
        return True
    if type(v) is tuple:
        return all(_fast_attr_safe(x) for x in v)
    return False


class _SkelOp:
    """One retained op of a sealed segment's skeleton: everything the
    fast path needs to admit a position-matching record without
    re-deriving it (cached out-avals, interned sig entry, shared attrs
    dict, grad flags)."""

    __slots__ = ("op", "akey", "attrs", "fast_attrs", "wiring",
                 "out_avals", "out_req", "req", "has_inexact", "entry",
                 "n_outs", "ctup")


class _Skeleton:
    """One sealed segment shape's op skeleton (armed only once the
    signature memo proved the stream repeats). `in_sig` is the sealed
    segment's external-input aval signature — the fast path validates
    each fresh registration against it, so reused out-avals can never
    desync from what the inputs imply. `streak` counts consecutive
    fully-replayed seals; at FLAGS_step_replay_after it promotes to a
    whole-step `plan` — (live tuple, _CachedKey, ambient mesh) — that
    lets the seal skip signature reconstruction entirely (the driven
    ``segment::replay_step`` path)."""

    __slots__ = ("ops", "ctups", "in_sig", "gen", "streak", "plan")


class CaptureContext:
    """One lazy trace. Ops recorded since the last flush form the current
    segment; flush() compiles + runs it as one XLA executable."""

    def __init__(self, max_segment_ops: Optional[int] = None):
        self.pending: List[_PendingOp] = []
        # graph inputs of the CURRENT segment: id(tensor) -> index
        self._in_ids: Dict[int, int] = {}
        # WEAK refs to the input tensors: a tensor dying mid-segment must
        # not be pinned by the trace (only its payload snapshot in
        # _in_vals is needed to execute, and a dead input is a donation
        # candidate). _in_pins strong-pins them only under an on_flush
        # observer (SOT capture rebinds inputs at entry-build time).
        self._in_tensors: List = []
        self._in_pins: List = []
        self._in_vals: List = []
        # record-time autograd snapshot per input: (requires_grad,
        # AutogradMeta, inplace_version). The meta OBJECT is strongly
        # held: an intermediate that dies before the flush (a local of a
        # returned-from function) must still chain gradients through its
        # grad_node — only the tensor wrapper is gone, not the graph.
        self._in_meta: List = []
        # incremental structural signature: one entry appended per
        # recorded op, so flush never re-walks the whole pending list
        self._sig_ops: List[Tuple] = []
        self._max_override = max_segment_ops
        # steady-state signature memos, one per SEGMENT SHAPE (keyed by
        # the first interned sig entry — a real train step seals
        # several distinct segment shapes per iteration, e.g. the
        # fwd+bwd window and an optimizer tail, and a single slot would
        # thrash between them): (ops_key, in_sig, live, epoch, backend,
        # shard_sig) -> the _CachedKey handed out at that shape's last
        # seal. Validated by EXACT comparison over interned entries
        # (identity-fast) + the mesh epoch + the ambient-mesh sharding
        # component (None without a mesh), so a replan, a mesh switch
        # or any structural drift rebuilds. _sig_memo aliases the most
        # recent memo (tests read its _CachedKey).
        self._sig_memos: Dict[Any, Tuple] = {}
        self._sig_memo: Optional[Tuple] = None
        # (op_name, repr(error)) of the last record() failure — the
        # executor stashes it on the record_fallback path so the perf
        # analyzer can say WHY an op broke the window
        self._last_record_error = None
        # trace-stable record fast path: the BANK of retained skeletons
        # — one per memo-proven segment shape, bucketed by the shape's
        # first OpDef (the first record of a segment selects MRU-first)
        # and keyed inside the bucket by (length, last entry) like
        # _sig_memos, so two shapes sharing a leading op both keep
        # valid skeletons (mid-stream divergence switches candidates,
        # see _switch_skel) — plus the currently-selected skeleton, the
        # replay cursor into it, whether the CURRENT segment is still
        # matching, and how many of its ops were fast-replayed
        self._skels: Dict[Any, Dict[Tuple, _Skeleton]] = {}
        self._skeleton: Optional[_Skeleton] = None
        self._skel_pos = 0
        self._skel_live = False
        self._fast_ops = 0
        # stats for tests / profiling
        self.segments_run = 0
        self.ops_recorded = 0
        self.breaks: List[str] = []

    @property
    def max_ops(self) -> int:
        """Segment cap, read live (via the watcher-kept gate) so
        set_flags mid-session takes effect on already-open (incl.
        ambient) contexts."""
        if self._max_override is not None:
            return self._max_override
        return _MAX_SEG_OPS

    # ---------------------------------------------------------- recording
    def _input_index(self, tensor) -> int:
        idx = self._in_ids.get(id(tensor))
        # validate against id() reuse: the map entry is only good if the
        # weakref at that slot still points at THIS tensor
        if idx is not None and self._in_tensors[idx]() is tensor:
            return idx
        idx = len(self._in_vals)
        self._in_ids[id(tensor)] = idx
        self._in_tensors.append(weakref.ref(tensor))
        if self.on_flush is not None:
            self._in_pins.append(tensor)
        self._in_vals.append(tensor._payload)
        self._in_meta.append((not tensor.stop_gradient,
                              tensor._autograd_meta,
                              tensor._inplace_version))
        return idx

    def note_inplace(self, tensor):
        """`tensor`'s payload is being overwritten in place. Ops already
        recorded keep the registered snapshot (eager ordering); future
        records must re-register the fresh payload, so the id mapping is
        evicted. The orphaned snapshot becomes a donation candidate at
        flush (its backing tensor no longer aliases it). A mid-segment
        swap also drops the record skeleton: the input stream is being
        re-keyed under the replay's feet, so the fast path re-proves the
        stream at the next sealed steady-state segment instead of
        replaying across the mutation. (Between segments — the fused
        optimizer write-back — there is nothing recorded and the
        skeleton survives.)"""
        if _DRIVE_CELL[0] is not None:
            _drive_reconcile(self)
        self._in_ids.pop(id(tensor), None)
        if self.pending:
            sk = self._skeleton
            self._skeleton = None
            self._skel_live = False
            if sk is not None:
                # evict the banked entry of the shape being replayed
                for op in list(self._skels):
                    bucket = self._skels[op]
                    for k in [k for k, v in bucket.items() if v is sk]:
                        del bucket[k]
                    if not bucket:
                        del self._skels[op]

    def _select_skel(self, op: OpDef):
        """First record of a segment: select the most-recently-used
        banked skeleton whose sealed shape starts with `op` (stale
        generations evict; a mid-stream divergence from the MRU pick
        switches to a sibling shape, see _switch_skel). None = no
        candidate; this segment records through the full path."""
        bucket = self._skels.get(op)
        while bucket:
            k = next(reversed(bucket))
            sk = bucket[k]
            if sk.gen == _FAST_GEN:
                self._skeleton = sk
                return sk
            del bucket[k]
        if bucket is not None:
            del self._skels[op]
        self._skel_live = False
        return None

    def _switch_skel(self, op: OpDef):
        """Mid-stream candidate switch: the selected skeleton just
        mismatched at the replay cursor, but a SIBLING shape (same
        leading OpDef, different (length, last-entry) bucket key) may
        continue the stream — the satellite fix for two segment shapes
        sharing their first op. A candidate is valid only when its
        already-replayed prefix is exactly what this segment recorded:
        identical interned entries (compared by ``==`` — the intern
        pool may have been cleared), out-avals and grad flags, `op` at
        the cursor, and an in-signature prefix covering every external
        input registered so far. Returns the switched skeleton (made
        MRU) or None — nothing was mutated by the failed match, so the
        caller can simply retry the fast record against it."""
        sk = self._skeleton
        pos = self._skel_pos
        if sk is None or not sk.ops:
            return None
        bucket = self._skels.get(sk.ops[0].op)
        if not bucket:
            return None
        n_reg = len(self._in_vals)
        for k in list(reversed(bucket)):
            c = bucket[k]
            if c is sk or c.gen != _FAST_GEN or pos >= len(c.ops):
                continue
            if c.ops[pos].op is not op:
                continue
            if c.in_sig[:n_reg] != sk.in_sig[:n_reg]:
                continue
            ok = True
            for i in range(pos):
                a, b = c.ops[i], sk.ops[i]
                if a.entry != b.entry or a.out_avals != b.out_avals \
                        or a.out_req != b.out_req:
                    ok = False
                    break
            if not ok:
                continue
            del bucket[k]           # MRU refresh
            bucket[k] = c
            self._skeleton = c
            return c
        return None

    def _record_fast(self, op: OpDef, ts, attrs):
        """Trace-stable skeleton replay: admit this record by matching
        the armed skeleton position-for-position instead of re-deriving
        avals/keys. Returns the out-tensor tuple, or None on ANY
        mismatch — nothing was mutated then, and the caller falls back
        to the full record path (this segment stops replaying; the
        skeleton re-arms or rebuilds at the next memo-proven seal).

        Validation per op: same OpDef, equal attrs (dict equality for
        primitive attrs, interned-key equality otherwise), identical
        input wiring — op-ref inputs must point at the same (op, slot),
        external inputs must land on the same input index with the aval
        the sealed segment's in-signature recorded — and the same grad
        intent. Only then are the skeleton's cached out-avals, interned
        sig entry and shared attrs dict reused; external payloads are
        re-bound through the normal registration machinery."""
        global FAST_OPS
        sk = self._skeleton
        if sk is None:
            sk = self._select_skel(op)
        pos = self._skel_pos
        if sk is None or sk.gen != _FAST_GEN or pos >= len(sk.ops) \
                or _flags.STATIC_CHECKS_ACTIVE:
            self._skel_live = False
            if sk is not None and sk.gen != _FAST_GEN:
                self._skeleton = None
            return None
        s = sk.ops[pos]
        if s.op is not op or len(ts) != len(s.wiring):
            self._skel_live = False
            return None
        if s.fast_attrs:
            try:
                if attrs != s.attrs:
                    self._skel_live = False
                    return None
            except ValueError:
                # an ndarray attr value arrived where the armed shape
                # held primitives: dict inequality is ambiguous there —
                # a plain mismatch, NOT an uncapturable op (the full
                # path digests ndarray attrs via _hashable)
                self._skel_live = False
                return None
        elif dispatch.attrs_key(attrs) != s.akey:
            self._skel_live = False
            return None
        in_ids = self._in_ids
        in_tensors = self._in_tensors
        n_in = len(self._in_vals)
        in_sig = sk.in_sig
        new_ext = None      # fresh external registrations, commit later
        new_ids = None
        req = False
        for t, w in zip(ts, s.wiring):
            if t is None:
                if w is not None:
                    self._skel_live = False
                    return None
                continue
            p = t._payload
            if getattr(p, "_is_lazy_ref", False):
                if p.ctx is self and p.op_idx is not None:
                    if w is None or w[0] != "op" or w[1] != p.op_idx \
                            or w[2] != p.slot:
                        self._skel_live = False
                        return None
                    req = req or p.requires_grad
                    continue
                # foreign-context lazy value: the slow path materializes
                self._skel_live = False
                return None
            if w is None or w[0] != "in":
                self._skel_live = False
                return None
            idx = in_ids.get(id(t))
            if idx is not None and in_tensors[idx]() is not t:
                idx = None
            if idx is None and new_ids is not None:
                idx = new_ids.get(id(t))
            if idx is None:
                idx = n_in if new_ext is None else n_in + len(new_ext)
                if idx >= len(in_sig):
                    self._skel_live = False
                    return None
                isig = in_sig[idx]
                if tuple(p.shape) != isig[0] \
                        or _dstr(p.dtype) != isig[1] \
                        or bool(getattr(p, "weak_type", False)) != isig[2]:
                    self._skel_live = False
                    return None
                if new_ext is None:
                    new_ext = [t]
                    new_ids = {id(t): idx}
                else:
                    new_ext.append(t)
                    new_ids[id(t)] = idx
            if w[1] != idx:
                self._skel_live = False
                return None
            req = req or not t._stop_gradient
        if s.has_inexact and (req and _IS_GRAD_ENABLED()) != s.req:
            # grad intent flipped (no_grad scope, stop_gradient toggle):
            # the skeleton's out flags no longer apply
            self._skel_live = False
            return None
        # ---- commit (nothing above mutated the context)
        if new_ext is not None:
            for t in new_ext:
                self._input_index(t)
        src = None
        if PERF_SRC or _OBS.COMPUTE or _flags.NAN_CHECK_ACTIVE:
            # provenance demanded (perf trace / compute plane / armed
            # NaN scan): the fast path still skips aval work but
            # captures the source line per op — diagnostics and
            # named_scope provenance must not degrade under replay
            from ..analysis.hooks import call_site
            src = call_site()
        op_idx = len(self.pending)
        out_refs = []
        outs = []
        for slot in range(s.n_outs):
            rg = s.out_req[slot]
            ref = LazyRef.__new__(LazyRef)
            ref.ctx = self
            ref.op_idx = op_idx
            ref.slot = slot
            ref.aval = s.out_avals[slot]
            ref.requires_grad = rg
            ref.trefs = []
            out_refs.append(ref)
            outs.append(_lazy_tensor(ref, stop_gradient=not rg))
        pop = _PendingOp.__new__(_PendingOp)
        pop.op = op
        pop.attrs = s.attrs
        pop.wiring = s.wiring
        pop.out_refs = out_refs
        pop.n_outs = s.n_outs
        pop.src = src
        self.pending.append(pop)
        self._sig_ops.append(s.entry)
        self._skel_pos = pos + 1
        self.ops_recorded += 1
        self._fast_ops += 1
        FAST_OPS += 1
        return tuple(outs)

    def _build_skeleton(self, in_sig):
        """Retain the just-sealed segment as the replay skeleton (only
        called once the signature memo proved the stream repeats)."""
        ops = []
        for pop, entry in zip(self.pending, self._sig_ops):
            s = _SkelOp()
            s.op = pop.op
            s.akey = entry[1]
            s.attrs = pop.attrs
            s.fast_attrs = all(_fast_attr_safe(v)
                               for v in pop.attrs.values())
            s.wiring = pop.wiring
            s.out_avals = tuple(r.aval for r in pop.out_refs)
            s.out_req = tuple(r.requires_grad for r in pop.out_refs)
            s.req = any(s.out_req)
            s.has_inexact = any(_is_inexact(a.dtype) for a in s.out_avals)
            s.entry = entry
            s.n_outs = pop.n_outs
            # flat tuple for the native matcher: one PyTuple_GET_ITEM
            # per field instead of a slot GetAttr each (multi_output is
            # canonical True/False so C judges it by identity)
            s.ctup = (s.op, s.akey, s.attrs, s.fast_attrs, s.wiring,
                      s.out_avals, s.out_req, s.req, s.has_inexact,
                      s.entry, s.n_outs, True if s.op.multi_output
                      else False)
            ops.append(s)
        sk = _Skeleton()
        sk.ops = ops
        sk.ctups = [s.ctup for s in ops]
        sk.in_sig = in_sig
        sk.gen = _FAST_GEN
        sk.streak = 0
        sk.plan = None
        self._skeleton = sk
        op0 = self.pending[0].op
        bucket = self._skels.get(op0)
        if bucket is None:
            if len(self._skels) > 8:
                self._skels.clear()
            bucket = self._skels[op0] = {}
        # bucket key = (length, last entry), the _sig_memos scheme:
        # same-leading-op shapes coexist instead of thrashing one slot
        bkey = (len(ops), self._sig_ops[-1])
        bucket.pop(bkey, None)
        if len(bucket) > 4:
            bucket.clear()
        bucket[bkey] = sk

    def record(self, op: OpDef, ts, attrs):
        """Record one op application; returns out Tensors (lazy).

        The NATIVE skeleton matcher is entered one level up, in
        executor.apply (the only production caller) — record() itself
        runs the python matcher, which self-gates on the sanitizer /
        provenance modes and stands alone without the C library. The
        two gates are contract twins: a new mode that must bypass the
        replay belongs in _record_fast AND in apply's native gate."""
        if self._skel_live:
            outs = self._record_fast(op, ts, attrs)
            if outs is None and self._skeleton is not None \
                    and self._switch_skel(op) is not None:
                # sibling shape continues the stream: retry once (the
                # failed match mutated nothing)
                self._skel_live = True
                outs = self._record_fast(op, ts, attrs)
            if outs is not None:
                return outs
        is_grad_enabled = _IS_GRAD_ENABLED
        if is_grad_enabled is None:
            _bind_hot_imports()
            is_grad_enabled = _IS_GRAD_ENABLED

        # pass 1: resolve avals WITHOUT mutating the input record, so a
        # failing aval inference (un-capturable op) leaves no ghost
        # inputs behind for the record-fallback path to drag along
        resolved = []
        in_avals = []
        req = False
        for t in ts:
            if t is None:
                resolved.append(None)
                in_avals.append(None)
                continue
            p = t._payload
            if getattr(p, "_is_lazy_ref", False):
                if p.ctx is self and p.op_idx is not None:
                    resolved.append(("op", p.op_idx, p.slot))
                    in_avals.append(p.aval)
                    req = req or p.requires_grad
                    continue
                # lazy value from another context: materialize it
                p.materialize()
                p = t._payload
            resolved.append(("ext", t))
            in_avals.append(_aval_of(p))
            req = req or (not t.stop_gradient)

        akey = dispatch.attrs_key(attrs)
        out_avals = _out_avals(op, attrs, in_avals, akey)

        # pass 2 (cannot fail): register external inputs + build wiring
        wiring = []
        for r in resolved:
            if r is None:
                wiring.append(None)
            elif r[0] == "ext":
                wiring.append(("in", self._input_index(r[1])))
            else:
                wiring.append(r)
        wiring = tuple(wiring)
        req = req and is_grad_enabled()
        op_idx = len(self.pending)
        out_refs = []
        outs = []
        for s, aval in enumerate(out_avals):
            inexact = _is_inexact(aval.dtype)
            ref = LazyRef(self, op_idx, s, aval, req and inexact)
            t = _lazy_tensor(ref, stop_gradient=not (req and inexact))
            out_refs.append(ref)
            outs.append(t)
        src = None
        if _flags.STATIC_CHECKS_ACTIVE:
            from ..analysis.hooks import call_site
            src = call_site()
            if op.name in _VIEW_OP_NAMES:
                # cross-segment alias graph: reference view semantics
                # share storage with the base, so the sanitizer tracks
                # view->base edges process-wide (paddle_tpu.analysis.
                # alias_graph) to catch a later donation/in-place
                # mutation of the base while the view lives on. EVERY
                # output aliases the base (split_ returns N views)
                base = next((t for t in ts if t is not None), None)
                if base is not None:
                    from ..analysis import alias_graph as _ag
                    for _out in outs:
                        _ag.note_view(_out, base, op.name, src)
        elif PERF_SRC or _OBS.COMPUTE or _flags.NAN_CHECK_ACTIVE:
            # perf tracing, the compute telemetry plane AND an armed
            # NaN scan force provenance capture even with the sanitizer
            # off (no alias-graph work — that is the correctness
            # sanitizer's job): perf diagnostics need the line, the
            # compute plane bakes it into each op's named_scope so
            # device profiles group by paddle source, and a NaN trip
            # must name the producing op's file:line in its message
            from ..analysis.hooks import call_site
            src = call_site()
        self.pending.append(_PendingOp(op, dict(attrs), wiring, out_refs,
                                       src))
        entry = _intern_sig_entry((op.name, akey, wiring, len(out_refs)))
        self._sig_ops.append(entry)
        self.ops_recorded += 1
        return tuple(outs)

    def maybe_cap_flush(self):
        """Called by the executor AFTER a successful record, outside its
        record-fallback handler, so a failing segment execution surfaces
        instead of being swallowed as an 'uncapturable op'. Reads the
        cap inline (not via the max_ops property) — this runs once per
        recorded op."""
        cap = self._max_override
        if cap is None:
            cap = _MAX_SEG_OPS
        if len(self.pending) >= cap:
            self.flush("segment_cap")

    def _reset_segment(self):
        if _DRIVE_CELL[0] is not None:
            _drive_reconcile(self)
        self.pending = []
        self._in_ids = {}
        self._in_tensors = []
        self._in_pins = []
        self._in_vals = []
        self._in_meta = []
        self._sig_ops = []
        self._skel_pos = 0
        self._skeleton = None            # selected by the next segment's
        self._skel_live = bool(self._skels)   # first record
        self._fast_ops = 0

    def _live_outputs(self, pending):
        """Lazy refs some Tensor still aliases (see _live_aliases)."""
        live: List[Tuple[int, int]] = []
        live_refs: List[LazyRef] = []
        for j, pop in enumerate(pending):
            for ref in pop.out_refs:
                if _live_aliases(ref):
                    live.append((j, ref.slot))
                    live_refs.append(ref)
        return live, live_refs

    def _signature(self, in_vals, live) -> "_CachedKey":
        # MESH_EPOCH rides after the structural halves:
        # register_segment_grad slices the ops/inputs halves
        # positionally (sig[1]/sig[2]), so the SPMD sharding component
        # — (mesh shape+axes, per-input PartitionSpec) — is appended at
        # the very END and ONLY when a mesh is ambient: a no-mesh
        # session's key stays the 5-tuple it always was (zero extra key
        # bytes) while the same dygraph code under two meshes (or two
        # input layouts) keys two distinct executables. The memo hands
        # back last step's _CachedKey when nothing structural changed —
        # entries are interned, so the comparison is n identity checks,
        # and downstream cache lookups hash a cached int instead of
        # re-walking the whole structure every step.
        ops_key = tuple(self._sig_ops)
        sk = self._skeleton
        if sk is not None and self._skel_live \
                and self._skel_pos == len(sk.ops) \
                and len(in_vals) == len(sk.in_sig):
            # fully skeleton-replayed segment: every external
            # registration was validated against the sealed in-sig, so
            # the tuple is identical by construction — reuse the object
            # (the memo compare below becomes an identity check)
            in_sig = sk.in_sig
        else:
            in_sig = _in_signature(in_vals)
        live_t = tuple(live)
        backend = jax.default_backend()
        spmd = SPMD
        shard_sig = None
        if spmd is not None:
            global SHARD_SIG_BUILDS
            SHARD_SIG_BUILDS += 1
            shard_sig = (spmd.key,
                         tuple(spmd.spec_of(v) for v in in_vals))
        # per-shape memo bucket: first entry + length + last entry
        # disambiguates shapes that share a leading op (entries are
        # interned, so the tuple hashes cheaply). NOTE the skeleton
        # BANK below is still keyed by the first OpDef alone — it must
        # select before anything else is known — so two shapes sharing
        # their first (op, attrs, wiring) entry alternate the bank slot
        # and replay stays off for them (documented limitation; the
        # memo/_CachedKey reuse still works per shape).
        key0 = (self._sig_ops[0], len(self._sig_ops), self._sig_ops[-1])
        memo = self._sig_memos.get(key0)
        if memo is not None and memo[3] == MESH_EPOCH \
                and memo[4] == backend and memo[5] == shard_sig \
                and memo[0] == ops_key \
                and memo[1] == in_sig and memo[2] == live_t:
            # the memo just proved this segment shape repeats: arm (or
            # refresh) its record skeleton — unless the current
            # segment fully replayed it, in which case it is exact
            if _FAST_PATH and not _flags.STATIC_CHECKS_ACTIVE and (
                    sk is None or sk.gen != _FAST_GEN
                    or not (self._skel_live
                            and self._skel_pos == len(sk.ops))):
                self._build_skeleton(memo[1])
            elif sk is not None and sk.gen == _FAST_GEN \
                    and self._skel_live \
                    and self._skel_pos == len(sk.ops):
                # a full clean replay of the armed skeleton just
                # re-proved: advance the whole-step promotion streak,
                # and at the threshold seal the STEP PLAN — live set +
                # _CachedKey + ambient mesh — so later seals of this
                # shape skip signature reconstruction entirely
                sk.streak += 1
                if sk.plan is None and _STEP_REPLAY_AFTER \
                        and sk.streak >= _STEP_REPLAY_AFTER:
                    sk.plan = (memo[2], memo[6], SPMD)
            self._sig_memo = memo
            return memo[6]
        # structural drift for THIS shape: drop its banked skeleton
        # and re-prove before replaying it again — bucket keys carry
        # (length, last entry), so a different shape that merely shares
        # the leading op keeps its valid skeleton
        bucket = self._skels.get(self.pending[0].op)
        if bucket is not None:
            bucket.pop((len(self._sig_ops), self._sig_ops[-1]), None)
            if not bucket:
                del self._skels[self.pending[0].op]
        self._skeleton = None
        base = (backend, ops_key, in_sig, live_t, MESH_EPOCH)
        key = _CachedKey(base if shard_sig is None
                         else base + (shard_sig,))
        if len(self._sig_memos) > 8:
            self._sig_memos.clear()
        memo = (ops_key, in_sig, live_t, MESH_EPOCH, backend,
                shard_sig, key)
        self._sig_memos[key0] = memo
        self._sig_memo = memo
        return key

    def _step_plan_sig(self, live):
        """Whole-step replay admission at seal time. Returns
        ``(sig, True)`` when the current segment fully replayed a
        promoted skeleton and the live set matches its sealed plan —
        the seal then skips _signature() entirely and the execution
        runs under ``segment::replay_step``. Returns ``(None, False)``
        otherwise; a live-set or mesh mismatch against an armed plan
        additionally DEMOTES the shape (streak reset, plan dropped) so
        it re-proves through the normal path before re-promoting.
        The mechanical invalidation events (mesh epoch, watched flags,
        note_inplace, grad-mode flip) never reach this check: they all
        break the per-op replay first, so `_skel_live` is already
        False."""
        sk = self._skeleton
        if sk is None or sk.plan is None or not self._skel_live \
                or self._skel_pos != len(sk.ops) \
                or len(self._in_vals) != len(sk.in_sig):
            return None, False
        plan_live, plan_key, plan_spmd = sk.plan
        if sk.gen != _FAST_GEN or tuple(live) != plan_live \
                or SPMD is not plan_spmd:
            sk.streak = 0
            sk.plan = None
            return None, False
        global REPLAY_STEPS
        REPLAY_STEPS += 1
        if _OBS.METRICS:
            from ..observability import metrics
            metrics.inc("segment.replay_steps")
        self._sig_memo = self._sig_memos.get(
            (self._sig_ops[0], len(self._sig_ops), self._sig_ops[-1]))
        return plan_key, True

    # ------------------------------------------------------------- flush
    def flush(self, reason: str = "materialize"):
        if _DRIVE_CELL[0] is not None:
            # an armed whole-step drive lags the context's cursor and
            # counters (they are written back in batch): reconcile
            # BEFORE anything below reads _skel_pos/_fast_ops
            _drive_reconcile(self)
        if not self.pending:
            # nothing recorded, but clear any input registrations a
            # partially-failed record may have left behind
            self._reset_segment()
            return
        if PERF_OBSERVER is not None:
            PERF_OBSERVER(self, reason, self.pending)
        pending = self.pending
        in_vals = self._in_vals
        in_meta = self._in_meta
        in_tensors = [r() for r in self._in_tensors]  # None = died

        live, live_refs = self._live_outputs(pending)
        sig, driven = self._step_plan_sig(live)
        if sig is None:
            sig = self._signature(in_vals, live)

        # donation: an input whose backing tensor died or was overwritten
        # is dead the moment this program runs — let XLA reuse its buffer
        # for an output (the in-place param.copy_ pattern) instead of
        # copying. Never donate when the segment registers a grad node:
        # saved inputs are the backward residuals. The all-inputs-alive
        # step (the common case) pays ONE identity scan here instead of
        # the set/dict builds + per-buffer refcount probes of the full
        # candidate search.
        donate: Tuple[int, ...] = ()
        from . import flags
        if _DONATE_INPUTS and any(
                t is None or t._payload is not in_vals[i]
                for i, t in enumerate(in_tensors)) and not \
                _segment_needs_grad(in_tensors, in_vals, live_refs, in_meta):
            donate = _donatable_inputs(in_tensors, in_vals, live_refs)

        # async dispatch pipeline: a cap- or guard-exit-sealed segment
        # hands off to the single-worker flush executor so
        # compile+execute leave the recording thread; live outputs
        # become PendingValues that materialize at the next sync
        # point. SOT capture (on_flush observer) rides along: its
        # note_flush accepts pending out tensors (the entry builder
        # reads only avals/identity, never concrete values).
        if _flags.ASYNC_FLUSH_ACTIVE and reason in _ASYNC_REASONS:
            self._flush_async(reason, pending, in_vals, in_meta,
                              in_tensors, live, live_refs, sig, donate,
                              driven)
            return

        # program sanitizer (paddle_tpu.analysis): one cached-gate read
        # when off; in warn/error mode the segment checkers run over the
        # program about to execute (donation safety, in-place races,
        # tracer leaks, shape/dtype drift, cross-segment donation, view
        # aliases). 'error' stops a corrupting launch — drop the trace
        # like a failed compile would. 'fix' repairs the mechanical
        # classes in place and hands back the rewritten (pending,
        # donate) pair; a pruned op list invalidates the incremental
        # live/signature state, so both are recomputed before dispatch.
        _checks_on = False
        if _flags.STATIC_CHECKS_ACTIVE:
            from ..analysis import hooks as _sanitizer
            try:
                _mode = _sanitizer.check_mode()   # full normalization
                if _mode != "off":
                    _checks_on = True
                    _fixed = _sanitizer.on_segment_flush(
                        self, pending, in_vals, in_meta, in_tensors,
                        live, live_refs, donate, _mode, fixable=True,
                        reason=reason)
                    if _fixed is not None:
                        new_pending, donate = _fixed
                        if new_pending is not pending:
                            pending = new_pending
                            live, live_refs = self._live_outputs(pending)
                            sig = self._signature(in_vals, live)
            except Exception:
                self._reset_segment()
                raise

        fspan = _obs_flush_span(reason, len(pending), len(in_vals),
                                len(live), len(donate), self._fast_ops) \
            if _OBS.ACTIVE else None
        dispatch.bump_exec()
        xspan = None
        try:
            # inputs produced by a still-in-flight async flush resolve
            # here (the pipeline's data-dependency sync)
            run_vals = resolve_pending(in_vals) if _ASYNC_SEEN else in_vals
            if _flags.FAULT_INJECT_ACTIVE:
                _inject_exec_oom()
            runner = _SEG_CACHE.get((sig, donate))
            if runner is None and _persist.ACTIVE:
                # disk consult between the in-memory miss and
                # lower().compile(): a verified hit takes the cached-
                # execute span below and bumps no compiles.* counter
                runner = _disk_runner(
                    "segment", (_persist_sig(sig), donate),
                    _jit_factory(
                        lambda: _build_segment_fn(pending, live),
                        donate, run_vals, _spmd_for_compile(in_vals)),
                    cache=_SEG_CACHE, key=(sig, donate))
                if runner is not None:
                    _SEG_CACHE[(sig, donate)] = runner
            # async dispatch: out_vals are in-flight futures — the host
            # returns to tracing the next ops while the device executes;
            # sync happens only at explicit .numpy()/float() reads
            if runner is None:
                if _flags.FAULT_INJECT_ACTIVE:
                    # segment::compile fault site (transient compile
                    # failure): raises inside this try so cleanup is
                    # exactly a real failed compile — trace dropped,
                    # spans closed, flight post-mortem
                    from ..distributed.resilience import faults as _faults
                    _faults.inject("segment::compile")
                if fspan is not None:
                    xspan = _obs_exec_span(True, len(pending))
                if _OBS.METRICS:
                    from ..observability import metrics
                    metrics.inc("compiles.segment")
                runner = _compile_segment_runner(
                    pending, live, donate, run_vals, sig,
                    _spmd_for_compile(in_vals))
                _SEG_CACHE[(sig, donate)] = runner
                if _persist.ACTIVE:
                    _disk_store("segment", (_persist_sig(sig), donate),
                                runner, _SEG_CACHE, (sig, donate))
                with _quiet_donation_compile():   # first call compiles
                    out_vals = runner(*run_vals)
            else:
                if fspan is not None:
                    xspan = _obs_exec_span(False, len(pending), driven)
                out_vals = runner(*run_vals)
            if xspan is not None:
                xspan.end()
        except Exception as e:
            # a failed compile/run must not pin input tensors or poison
            # later records: drop the trace and surface the error (the
            # un-materialized outputs re-raise on read). Spans end
            # BEFORE the flight dump so the report contains the failing
            # flush/compile entry, not just the error note.
            self._reset_segment()
            if xspan is not None:
                xspan.end(error=e)
            if fspan is not None:
                fspan.end(error=e)
            _obs_flush_failed(reason, e)
            oe = _oom_convert(e, f"segment::flush[{reason}]",
                              _SEG_CACHE.memory_info((sig, donate)))
            if oe is not e:
                raise oe from e
            raise
        if _checks_on and donate:
            # cross-segment ledger (sanitizer dataflow): recorded only
            # AFTER the executable ran — a failed compile/run donated
            # nothing, and a phantom entry would turn a valid later
            # program into a false cross_segment_donation error
            from ..analysis.dataflow import note_segment_donation
            note_segment_donation(in_vals, donate, reason, pending)
        if SPMD is not None and _OBS.METRICS:
            _note_compiled_comm(_SEG_CACHE, (sig, donate), SPMD,
                                run_vals, out_vals, "segment")
        if _OBS.COMPUTE:
            # FLOP accounting: price this execution from the cost
            # analysis the compile cached on the entry (zero work when
            # the entry predates the plane)
            from ..observability import compute as _comptel
            _comptel.count_cached(_SEG_CACHE, (sig, donate), "segment")
        if _OBS.MEM and donate:
            _note_donated_inputs(in_vals, donate)
        self._reset_segment()
        self.breaks.append(reason)
        self.segments_run += 1

        try:
            # bind concrete values into every aliasing Tensor; the grad
            # node attaches to a grad-REQUIRING alias — a detach()ed
            # alias must never have its stop_gradient flipped back
            out_tensors = []
            for ref, val in zip(live_refs, out_vals):
                ts = _live_aliases(ref)
                for t in ts:
                    t._payload = val
                grad_ts = [t for t in ts if not t.stop_gradient]
                out_tensors.append(grad_ts[0] if grad_ts
                                   else (ts[0] if ts else None))

            if _OBS.MEM:
                # live-buffer census: segment outputs are born here,
                # provenance = segment signature + producing op (+ the
                # mesh descriptor when the step ran sharded, so an OOM
                # postmortem names which mesh config filled the device)
                from ..observability import memory as _memtel
                _memtel.note_segment_outputs(
                    pending, live, out_vals, sig,
                    mesh=SPMD.desc if SPMD is not None else None)

            # FLAGS_check_nan_inf covers fused-segment outputs too (the
            # per-op eager scan in dispatch.py never sees ops that were
            # recorded before the flag flipped on, nor replayed
            # segments): scan every live output, blaming its producer
            if flags.flag_value("FLAGS_check_nan_inf"):
                _nan_scan_segment(pending, live, out_vals,
                                  "lazy segment output", in_vals)

            self._register_grad(pending, live, live_refs, out_tensors,
                                in_tensors, in_vals, sig, in_meta)

            if self.on_flush is not None:
                self.on_flush(self, reason, pending, live, live_refs,
                              in_tensors, in_vals, sig, out_tensors)
        except Exception as e:
            # a post-execute failure (NaN trip, grad wiring, observer)
            # still closes the flush span and triggers the flight
            # post-mortem — this is exactly the event it exists for
            if fspan is not None:
                fspan.end(error=e)
            _obs_flush_failed(reason, e)
            raise
        if fspan is not None:
            fspan.end()

    def _flush_async(self, reason, pending, in_vals, in_meta, in_tensors,
                     live, live_refs, sig, donate, driven=False):
        """Seal the segment and hand it to the flush executor.

        Caller-thread work is exactly what MUST happen at eager order:
        donation decision (already made — refcount semantics are
        caller-relative), output binding (every live alias gets a
        PendingValue payload), and grad wiring (the graph exists the
        moment eager code moves on). The sanitizer sweep, cache lookup,
        compile, execute, ledger note, and NaN scan all run on the
        worker; failures fail every PendingValue and latch on the
        executor, re-raising at the next sync point (the flight
        post-mortem fires on the worker, so the report carries the
        failing flush)."""
        global _ASYNC_SEEN
        from .async_flush import PendingValue, get_executor

        # mode resolved NOW (a typo'd FLAGS_static_checks raises at the
        # flush site, not from a worker); the sweep itself runs off-thread
        mode = None
        if _flags.STATIC_CHECKS_ACTIVE:
            from ..analysis import hooks as _sanitizer
            mode = _sanitizer.check_mode()
            if mode == "off":
                mode = None
        in_ids = dict(self._in_ids)
        fault_active = _flags.FAULT_INJECT_ACTIVE
        # ambient mesh captured at SEAL time: the signature above was
        # built against it, and the worker must compile/account against
        # the same state even if the recording thread exits the mesh.
        # spmd_pin is None when any sealed input is still pending —
        # such programs compile unpinned (see _spmd_for_compile)
        spmd = SPMD
        spmd_pin = _spmd_for_compile(in_vals)
        fast_n = self._fast_ops
        from . import flags
        nan_check = flags.flag_value("FLAGS_check_nan_inf")

        pvs = [PendingValue(ref.aval) for ref in live_refs]
        out_tensors = []
        for ref, pv in zip(live_refs, pvs):
            ts = _live_aliases(ref)
            for t in ts:
                t._payload = pv
            grad_ts = [t for t in ts if not t.stop_gradient]
            out_tensors.append(grad_ts[0] if grad_ts
                               else (ts[0] if ts else None))
        if _OBS.METRICS:
            from ..observability import metrics
            metrics.inc("segment.async_flushes")

        def job(pending=pending, live=live, live_refs=live_refs,
                sig=sig, donate=donate):
            pvmap = {id(r): pv for r, pv in zip(live_refs, pvs)}
            fspan = xspan = None
            try:
                if mode is not None:
                    from ..analysis import hooks as _sanitizer
                    # fixable=False: fix-mode REPAIRS stay on the
                    # synchronous path — the fixer rewrites context
                    # state that now belongs to the NEXT recording
                    # segment, and the sealed outputs are already bound
                    # to PendingValues. Warn/error semantics (incl. the
                    # deferred StaticCheckError) are identical; ctx is
                    # withheld so nothing can touch live state.
                    _sanitizer.on_segment_flush(
                        None, pending, in_vals, in_meta, in_tensors,
                        live, live_refs, donate, mode, fixable=False,
                        reason=reason, in_ids=in_ids)
                fspan = _obs_flush_span(reason, len(pending),
                                        len(in_vals), len(live),
                                        len(donate), fast_n) \
                    if _OBS.ACTIVE else None
                run_vals = resolve_pending(in_vals)
                dispatch.bump_exec()
                if fault_active:
                    _inject_exec_oom()
                runner = _SEG_CACHE.get((sig, donate))
                if runner is None and _persist.ACTIVE:
                    runner = _disk_runner(
                        "segment", (_persist_sig(sig), donate),
                        _jit_factory(
                            lambda: _build_segment_fn(pending, live),
                            donate, run_vals, spmd_pin),
                        cache=_SEG_CACHE, key=(sig, donate))
                    if runner is not None:
                        _SEG_CACHE[(sig, donate)] = runner
                if runner is None:
                    if fault_active:
                        from ..distributed.resilience import faults \
                            as _faults
                        _faults.inject("segment::compile")
                    if fspan is not None:
                        xspan = _obs_exec_span(True, len(pending))
                    if _OBS.METRICS:
                        from ..observability import metrics
                        metrics.inc("compiles.segment")
                    runner = _compile_segment_runner(pending, live,
                                                     donate, run_vals,
                                                     sig, spmd_pin)
                    _SEG_CACHE[(sig, donate)] = runner
                    if _persist.ACTIVE:
                        _disk_store("segment",
                                    (_persist_sig(sig), donate),
                                    runner, _SEG_CACHE, (sig, donate))
                    with _quiet_donation_compile():
                        out_vals = runner(*run_vals)
                else:
                    if fspan is not None:
                        xspan = _obs_exec_span(False, len(pending),
                                               driven)
                    out_vals = runner(*run_vals)
                if xspan is not None:
                    xspan.end()
                    xspan = None
                if mode is not None and donate:
                    from ..analysis.dataflow import note_segment_donation
                    note_segment_donation(in_vals, donate, reason,
                                          pending)
                if spmd is not None and _OBS.METRICS:
                    _note_compiled_comm(_SEG_CACHE, (sig, donate), spmd,
                                        run_vals, out_vals, "segment")
                if _OBS.COMPUTE:
                    from ..observability import compute as _comptel
                    _comptel.count_cached(_SEG_CACHE, (sig, donate),
                                          "segment")
                if _OBS.MEM:
                    if donate:
                        _note_donated_inputs(in_vals, donate)
                    from ..observability import memory as _memtel
                    _memtel.note_segment_outputs(
                        pending, live, out_vals, sig,
                        mesh=spmd.desc if spmd is not None else None)
                if nan_check:
                    _nan_scan_segment(pending, live, out_vals,
                                      "lazy segment output", in_vals)
                for ref, val in zip(live_refs, out_vals):
                    pv = pvmap.pop(id(ref), None)
                    if pv is not None:
                        pv._fill(val)
                for pv in pvmap.values():   # fixer dropped a live slot
                    pv._fail(RuntimeError(
                        "async flush lost a live output"))
                if fspan is not None:
                    fspan.end()
            except BaseException as e:
                # RESOURCE_EXHAUSTED converts to the typed postmortem
                # HERE, on the worker: the PendingValues and the
                # executor latch carry the typed error, so the sync
                # point re-raises exactly what the sync path would
                oe = _oom_convert(e, "segment::flush[async]",
                                  _SEG_CACHE.memory_info((sig, donate)))
                for pv in pvs:
                    if not pv.done():
                        pv._fail(oe)
                if xspan is not None:
                    xspan.end(error=oe)
                if fspan is not None:
                    fspan.end(error=oe)
                _obs_flush_failed(reason, oe)
                if oe is not e:
                    raise oe from e
                raise

        get_executor().submit(job)
        _ASYNC_SEEN = True
        self._reset_segment()
        self.breaks.append(reason)
        self.segments_run += 1
        self._register_grad(pending, live, live_refs, out_tensors,
                            in_tensors, in_vals, sig, in_meta)
        if self.on_flush is not None:
            # SOT capture observer: the sealed segment's out tensors
            # carry PENDING payloads (they materialize at the first
            # read) — the guarded-entry builder reads only avals and
            # payload identity, so guard-exit seals ride the pipeline
            self.on_flush(self, reason, pending, live, live_refs,
                          in_tensors, in_vals, sig, out_tensors)

    on_flush = None  # observer hook (jit/sot records segment structure)

    def flush_per_op(self, reason: str = "grad_targets"):
        """Land the pending trace as per-op eager dispatches — one
        GradNode per op instead of one fused segment node.

        paddle.grad(outputs, inputs) needs gradients AT interior values;
        a fused segment node only maps output cotangents to segment
        inputs, so a target produced inside the segment would be
        unreachable. Replaying the recorded wiring through the per-op
        path restores that granularity (cost: per-op dispatch, but only
        on the explicit-targets path)."""
        if not self.pending:
            self._reset_segment()
            return
        if PERF_OBSERVER is not None:
            PERF_OBSERVER(self, reason, self.pending)
        pending = self.pending
        in_vals = self._in_vals
        in_meta = self._in_meta
        in_tensors = [r() for r in self._in_tensors]
        # reset FIRST: the per-op dispatches below must not re-record
        # into this context
        self._reset_segment()
        self.breaks.append(reason)
        self.segments_run += 1

        rspan = None
        if _OBS.ACTIVE:
            if _OBS.METRICS:
                from ..observability import metrics
                metrics.inc("segment.replays_per_op")
                metrics.inc("segment.flush_reason."
                            + reason.split(":", 1)[0])
            from ..observability.spans import span
            rspan = span(f"segment::replay_per_op[{reason}]",
                         hist="segment.replay_per_op_us", reason=reason,
                         ops=len(pending)).begin()

        try:
            self._replay_per_op(pending, in_vals, in_meta, in_tensors)
        except Exception as e:
            if rspan is not None:
                rspan.end(error=e)
            raise
        if rspan is not None:
            rspan.end()

    def _replay_per_op(self, pending, in_vals, in_meta, in_tensors):
        from .autograd import record
        from .tensor import Tensor
        if _ASYNC_SEEN:
            # per-op replay hands raw payloads to eager dispatch:
            # in-flight async results resolve first, and tensors whose
            # payload IS the pending snapshot adopt the concrete value
            # so the overwritten-in-place identity check below stays
            # exact
            resolved = resolve_pending(in_vals)
            for t, v, rv in zip(in_tensors, in_vals, resolved):
                if t is not None and t._payload is v:
                    t._payload = rv
            in_vals = resolved
        out_tensors: List[List] = []
        for pop in pending:
            ins = []
            vals = []
            for w in pop.wiring:
                if w is None:
                    ins.append(None)
                    vals.append(None)
                elif w[0] == "in":
                    t = in_tensors[w[1]]
                    v = in_vals[w[1]]
                    if t is None or t._payload is not v:
                        # input died or was overwritten in place after
                        # registration: eager ordering saw the snapshot.
                        # The stand-in adopts the record-time autograd
                        # snapshot so grads still chain through a dead
                        # intermediate's grad_node.
                        req, meta, _ = in_meta[w[1]]
                        t = Tensor(v, stop_gradient=not req)
                        if meta is not None:
                            t._autograd_meta = meta
                    ins.append(t)
                    vals.append(v)
                else:
                    t = out_tensors[w[1]][w[2]]
                    ins.append(t)
                    vals.append(t._payload)
            outs = dispatch.eager_forward(pop.op, tuple(vals), pop.attrs)
            wrapped = []
            for ref, val in zip(pop.out_refs, outs):
                ts = _live_aliases(ref)
                for t in ts:
                    t._payload = val
                tt = next((t for t in ts if not t.stop_gradient), None)
                if tt is None:
                    # no live grad-requiring alias (value interior to the
                    # trace, or only detached aliases survive): wire the
                    # graph through a fresh stand-in
                    tt = Tensor(val, stop_gradient=not ref.requires_grad)
                wrapped.append(tt)
            if any(ref.requires_grad for ref in pop.out_refs):
                record(pop.op, pop.attrs, ins, wrapped, saved_vals=vals)
            out_tensors.append(wrapped)

    # ----------------------------------------------------------- autograd
    def _register_grad(self, pending, live, live_refs, out_tensors,
                       in_tensors, in_vals, sig, in_meta=None):
        register_segment_grad(pending, live, live_refs, out_tensors,
                              in_tensors, in_vals, sig, in_meta)


def _in_grad_records(in_tensors, in_meta):
    """(requires_grad, meta, version) per input. requires_grad is the
    RECORD-time intent when a snapshot exists (eager semantics: flipping
    stop_gradient after the op must not change its grad); meta is read
    live so a grad_node attached between record and flush is seen."""
    if in_meta is not None:
        return in_meta
    return [(False, None, 0) if t is None else
            (not t.stop_gradient, t._autograd_meta, t._inplace_version)
            for t in in_tensors]


def _input_grad_eligible(t, rec, val) -> bool:
    """Can gradients flow INTO this segment input? Dead leaves (no
    grad_node, tensor gone) are excluded: their grads are unobservable."""
    req, meta, _ = rec
    if not req or not jnp.issubdtype(val.dtype, jnp.inexact):
        return False
    return t is not None or (meta is not None
                             and meta.grad_node is not None)


def _segment_needs_grad(in_tensors, in_vals, live_refs, in_meta=None) -> bool:
    """Will register_segment_grad wire a GradNode for this segment? If so
    the inputs are saved as backward residuals and must NOT be donated."""
    recs = _in_grad_records(in_tensors, in_meta)
    grad_in = any(_input_grad_eligible(t, recs[i], in_vals[i])
                  for i, t in enumerate(in_tensors))
    return grad_in and any(ref.requires_grad for ref in live_refs)


def _donatable_inputs(in_tensors, in_vals, live_refs) -> Tuple[int, ...]:
    """Inputs safe to donate: concrete jax arrays registered exactly once
    whose backing tensor is dead or no longer aliases the snapshot, whose
    shape/dtype matches some output (so XLA can actually reuse the
    buffer — avoids 'donated buffer not usable' churn), and which nothing
    else in the process still references."""
    import sys
    out_shapes = {(tuple(r.aval.shape), _dstr(np.dtype(r.aval.dtype)))
                  for r in live_refs}
    counts: Dict[int, int] = {}
    for v in in_vals:
        counts[id(v)] = counts.get(id(v), 0) + 1
    donate = []
    for i, t in enumerate(in_tensors):
        v = in_vals[i]
        if not isinstance(v, jax.Array) or isinstance(v, jax.core.Tracer):
            continue
        if getattr(v, "weak_type", False):
            # weak-typed arrays are the shared python-scalar coercion
            # cache (executor._SCALAR_CACHE): donating a shared buffer
            # would invalidate every later use
            continue
        if counts[id(v)] != 1:
            continue
        if (tuple(v.shape), _dstr(np.dtype(v.dtype))) not in out_shapes:
            continue
        if t is not None and t._payload is v:
            continue
        # sole-ownership proof: the registered tensor died or moved on,
        # but OTHER Tensors may alias the same payload (detach()/
        # Tensor(t) share it) and GradNodes may have saved it as a
        # residual — donating then deletes a buffer something live still
        # reads. Expected refs here: in_vals entry + local v +
        # getrefcount arg = 3; anything above means an outside alias.
        if sys.getrefcount(v) > 3:
            continue
        donate.append(i)
    return tuple(donate)


def register_segment_grad(pending, live, live_refs, out_tensors,
                          in_tensors, in_vals, sig, in_meta=None):
    """Wire fused GradNodes for an executed segment — one per weakly-
    connected component of the recorded dataflow. Two user-level graphs
    captured in the same window (the ambient mode makes this common)
    must stay INDEPENDENT: backward through one must not consume or
    free the other's residuals. live_refs only needs .aval /
    .requires_grad (LazyRef or a replay meta). in_tensors may contain
    None for inputs whose tensor died mid-segment (they can no longer
    receive a gradient).

    NOTE deliberately no is_grad_enabled() check here: grad intent was
    decided at RECORD time (ref.requires_grad), matching eager
    semantics — a flush that happens to run inside no_grad (e.g. a
    logging read) must not drop gradients for ops recorded outside it."""
    recs = _in_grad_records(in_tensors, in_meta)
    grad_in_all = [i for i, t in enumerate(in_tensors)
                   if _input_grad_eligible(t, recs[i], in_vals[i])]
    grad_out_all = [k for k, ref in enumerate(live_refs)
                    if ref.requires_grad]
    if not grad_in_all or not grad_out_all:
        return

    # union-find over op indices [0, n_ops) and inputs [n_ops, ...)
    n_ops = len(pending)
    parent = list(range(n_ops + len(in_vals)))

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    for j, p in enumerate(pending):
        for w in p.wiring:
            if w is None:
                continue
            a = find(j)
            b = find(n_ops + w[1] if w[0] == "in" else w[1])
            if a != b:
                parent[b] = a

    comps: Dict[int, Tuple[List[int], List[int]]] = {}
    for i in grad_in_all:
        comps.setdefault(find(n_ops + i), ([], []))[0].append(i)
    for k in grad_out_all:
        comps.setdefault(find(live[k][0]), ([], []))[1].append(k)
    comps = {r: c for r, c in comps.items() if c[0] and c[1]}
    if not comps:
        return

    # each GradNode saves and differentiates only ITS slice of the
    # segment: a disjoint graph captured in the same ambient window must
    # not have its input buffers pinned as this component's residuals,
    # nor its backward FLOPs paid under a zero cotangent
    ops_by_root: Dict[int, List[int]] = {}
    for j in range(n_ops):
        ops_by_root.setdefault(find(j), []).append(j)
    ins_by_root: Dict[int, List[int]] = {}
    for i in range(len(in_vals)):
        ins_by_root.setdefault(find(n_ops + i), []).append(i)

    for r, (gi_c, go_c) in comps.items():
        comp_ops = ops_by_root[r]
        comp_ins = ins_by_root.get(r, [])
        if len(comp_ops) == n_ops and len(comp_ins) == len(in_vals):
            # sole component spans the whole segment (the steady-state
            # train-step case): no remap, and the cache key stays `sig`
            _register_component_grad(gi_c, go_c, pending, live, live_refs,
                                     out_tensors, in_tensors, in_vals, sig,
                                     recs)
            continue
        op_l = {j: lj for lj, j in enumerate(comp_ops)}
        in_l = {i: li for li, i in enumerate(comp_ins)}
        local_pending = []
        for j in comp_ops:
            p = pending[j]
            wir = tuple(None if w is None else
                        ("in", in_l[w[1]]) if w[0] == "in" else
                        ("op", op_l[w[1]], w[2]) for w in p.wiring)
            local_pending.append(_PendingOp(p.op, p.attrs, wir, p.out_refs,
                                            getattr(p, "src", None)))
        comp_ks = [k for k, (j, _) in enumerate(live) if find(j) == r]
        k_l = {k: lk for lk, k in enumerate(comp_ks)}
        local_live = [(op_l[live[k][0]], live[k][1]) for k in comp_ks]
        # global op/input index lists in the key make two segments that
        # slice to the same local structure share a compile only when
        # the remapping is identical
        comp_sig = (sig[0], tuple(sig[1][j] for j in comp_ops),
                    tuple(sig[2][i] for i in comp_ins), tuple(local_live),
                    tuple(comp_ops), tuple(comp_ins),
                    sig[4])   # MESH_EPOCH rides every derived key too
        raw = sig.sig if isinstance(sig, _CachedKey) else tuple(sig)
        if len(raw) > 5:
            # SPMD sharding component: slice the per-input specs to this
            # component's inputs so the derived backward key re-keys on
            # a re-plan / re-layout exactly like the whole-segment key
            comp_sig += ((raw[5][0],
                          tuple(raw[5][1][i] for i in comp_ins)),)
        _register_component_grad(
            [in_l[i] for i in gi_c], [k_l[k] for k in go_c],
            local_pending, local_live, [live_refs[k] for k in comp_ks],
            [out_tensors[k] for k in comp_ks],
            [in_tensors[i] for i in comp_ins],
            [in_vals[i] for i in comp_ins], comp_sig,
            [recs[i] for i in comp_ins])


def _register_component_grad(grad_in, grad_out, pending, live, live_refs,
                             out_tensors, in_tensors, in_vals, sig, recs):
    """One GradNode for one dataflow component: edges per grad-requiring
    input, output slots per grad-requiring live output (LOCAL indices)."""
    from .autograd import GradNode, _Edge
    edges = []
    versions = []
    refs = []
    for i in grad_in:
        t = in_tensors[i]
        meta = recs[i][1] if t is None else t._autograd_meta
        if meta.grad_node is not None:
            edges.append(_Edge("node", node=meta.grad_node,
                               slot=meta.out_slot))
        elif t is not None:
            edges.append(_Edge("leaf", leaf=t))
        else:       # dead leaf: grads unobservable (filtered above, but
            edges.append(_Edge(None))   # keep alignment defensively)
        versions.append(recs[i][2] if t is None else t._inplace_version)
        refs.append(None if t is None else weakref.ref(t))

    node = GradNode(
        None, {}, tuple(in_vals), edges,
        out_shapes=tuple(tuple(live_refs[k].aval.shape) for k in grad_out),
        out_dtypes=tuple(live_refs[k].aval.dtype for k in grad_out))
    node.name = "lazy_segment"
    node.saved_versions = tuple(versions)
    node.in_refs = tuple(refs)

    bwd = _segment_bwd(sig, pending, live, tuple(grad_in))

    def py_bwd(gouts, _saved=tuple(in_vals), _bwd=bwd, _refs=live_refs,
               _go=tuple(grad_out)):
        if _ASYNC_SEEN:
            # residuals saved from an async step may still be in flight
            _saved = resolve_pending(_saved)
        dispatch.bump_exec()
        # the cached vjp covers the WHOLE segment: seed this component's
        # slots, zeros elsewhere (disjoint components contribute nothing)
        cts = [jnp.zeros(r.aval.shape, r.aval.dtype) for r in _refs]
        for g, k in zip(gouts, _go):
            if g is None:
                continue
            ref = _refs[k]
            if hasattr(g, "astype") and g.dtype != ref.aval.dtype:
                g = g.astype(ref.aval.dtype)
            cts[k] = g
        grads = _bwd(list(_saved), tuple(cts))
        out = []
        for g in grads:
            if g is None or (hasattr(g, "dtype")
                             and g.dtype == jax.dtypes.float0):
                out.append(None)
            else:
                out.append(g)
        return tuple(out)

    node.py_bwd = py_bwd

    for local_k, k in enumerate(grad_out):
        t = out_tensors[k]
        if t is not None and not t.stop_gradient:
            m = t._autograd_meta
            if m.grad_node is None:
                m.grad_node = node
                m.out_slot = local_k


def _in_signature(in_vals):
    return tuple((tuple(v.shape), _dstr(v.dtype),
                  bool(getattr(v, "weak_type", False)))
                 for v in in_vals)


def _build_segment_fn(pending, live):
    """Compile body of one segment. Variadic over inputs so jax.jit's
    donate_argnums can address individual input buffers.

    With the compute telemetry plane on, each op's lowering is wrapped
    in ``jax.named_scope("<op>[<file>:<line>]")`` from its recorded
    ``_PendingOp.src`` — the HLO op_name metadata then carries paddle
    source provenance, so xplane device traces and the profiler
    statistic table can group device time by the line that recorded
    the op (observability/compute.py note_provenance/source_of).
    Decided at build (= compile) time: the off path pays nothing, and
    scope strings are metadata only — they never change what the
    program computes."""
    backend = jax.default_backend()
    scoped = _OBS.COMPUTE
    steps = []
    for p in pending:
        scope = None
        if scoped and p.src is not None:
            from ..observability.compute import scope_name
            scope = scope_name(p.op.name, p.src)
        steps.append((functools.partial(p.op.kernel_for(backend),
                                        **p.attrs),
                      p.wiring, p.op.multi_output, scope))

    def seg_fn(*inputs):
        vals: List[Tuple] = []
        for fn, wiring, multi, scope in steps:
            ins = []
            for w in wiring:
                if w is None:
                    ins.append(None)
                elif w[0] == "in":
                    ins.append(inputs[w[1]])
                else:
                    ins.append(vals[w[1]][w[2]])
            if scope is not None:
                with jax.named_scope(scope):
                    out = fn(*ins)
            else:
                out = fn(*ins)
            vals.append(tuple(out) if multi else (out,))
        return [vals[j][s] for (j, s) in live]

    return seg_fn


def _build_fused_fn(pending, live, grad_in: Tuple[int, ...], root_k: int):
    """Whole-step fusion: forward segment + vjp seeded at live output
    `root_k` as ONE program — the eager analog of the donated jitted
    train step in models/trainer.py. Returns (live_out_vals, grads)."""
    seg = _build_segment_fn(pending, live)

    def fused(*inputs):
        def f(*gvals):
            full = list(inputs)
            for v, i in zip(gvals, grad_in):
                full[i] = v
            outs = seg(*full)
            return outs[root_k], outs

        root_val, pull, outs = jax.vjp(
            f, *[inputs[i] for i in grad_in], has_aux=True)
        grads = pull(jnp.ones(root_val.shape, root_val.dtype))
        return outs, grads

    return fused


_SEG_BWD_CACHE: Dict[Tuple, Any] = ExecCache(stat="segment_bwd")


def _segment_bwd(sig, pending, live, grad_in: Tuple[int, ...]):
    key = (sig, grad_in)
    fn = _SEG_BWD_CACHE.get(key)
    if fn is None:
        if _OBS.METRICS:
            from ..observability import metrics
            metrics.inc("compiles.segment_bwd")
        seg = _build_segment_fn(pending, live)

        def bwd(inputs, cts, _seg=seg, _gi=grad_in):
            def f(*gvals):
                full = list(inputs)
                for v, i in zip(gvals, _gi):
                    full[i] = v
                return _seg(*full)
            _, pull = jax.vjp(f, *[inputs[i] for i in _gi])
            return pull(list(cts))

        fn = jax.jit(bwd)
        _SEG_BWD_CACHE[key] = fn
    return fn


def _lazy_tensor(ref: LazyRef, stop_gradient=True):
    Tensor = _TENSOR_CLS
    if Tensor is None:
        Tensor = _bind_hot_imports()
    t = object.__new__(Tensor)
    t._payload = ref
    t._stop_gradient = stop_gradient
    t._autograd_meta = _AUTOGRAD_META()
    t._inplace_version = 0
    t.name = None
    t.persistable = False
    t._dist_attr = None
    ref.add_tref(t)
    return t


class _RefMeta:
    """Replay stand-in for LazyRef (register_segment_grad contract)."""
    __slots__ = ("aval", "requires_grad")

    def __init__(self, aval, requires_grad):
        self.aval = aval
        self.requires_grad = requires_grad


class ReplayableSegment:
    """A captured segment that can be re-executed directly on fresh input
    tensors — the compiled body of jit/sot's guarded fast path. Built
    from a CaptureContext flush event; replay skips recording entirely:
    fetch inputs, run the cached executable, wrap outputs, register the
    fused GradNode."""

    def __init__(self, pending, live, live_refs, in_vals, sig):
        self.pending = pending
        self.live = live
        self.metas = [_RefMeta(r.aval, r.requires_grad) for r in live_refs]
        self.sig = sig
        # RECORD-time ambient mesh: `sig` was keyed against it, so a
        # replay must compile against the same state — not whatever
        # mesh happens to be ambient at replay time (the key and the
        # runner's sharding regime must never contradict)
        self.spmd = SPMD
        self.in_avals = tuple((tuple(v.shape), _dstr(v.dtype))
                              for v in in_vals)
        # which inputs fed grad-requiring chains at capture (replay must
        # see the same stop_gradient mask to reuse the vjp wiring)
        self.grad_mask = None

    def run(self, in_tensors):
        from .tensor import Tensor
        in_vals = [t._value for t in in_tensors]
        got = tuple((tuple(v.shape), _dstr(v.dtype)) for v in in_vals)
        if got != self.in_avals:
            raise _ReplayMismatch("input avals changed")
        runner = _SEG_CACHE.get((self.sig, ()))
        if runner is None and _persist.ACTIVE:
            runner = _disk_runner(
                "segment", (_persist_sig(self.sig), ()),
                _jit_factory(
                    lambda: _build_segment_fn(self.pending, self.live),
                    (), in_vals, self.spmd),
                cache=_SEG_CACHE, key=(self.sig, ()))
            if runner is not None:
                _SEG_CACHE[(self.sig, ())] = runner
        compiled = runner is None
        if compiled:
            runner = _compile_segment_runner(self.pending, self.live, (),
                                             in_vals, self.sig,
                                             spmd=self.spmd)
            _SEG_CACHE[(self.sig, ())] = runner
            if _persist.ACTIVE:
                _disk_store("segment", (_persist_sig(self.sig), ()),
                            runner, _SEG_CACHE, (self.sig, ()))
            if _OBS.METRICS:
                from ..observability import metrics
                metrics.inc("compiles.segment")
        dispatch.bump_exec()
        xspan = _obs_exec_span(compiled, len(self.pending)) \
            if _OBS.ACTIVE else None
        try:
            out_vals = runner(*in_vals)
        except Exception as e:
            if xspan is not None:
                xspan.end(error=e)
            raise
        if xspan is not None:
            xspan.end()
        from . import flags
        if flags.flag_value("FLAGS_check_nan_inf"):
            _nan_scan_segment(self.pending, self.live, out_vals,
                              "replayed segment output", in_vals)
        if _OBS.COMPUTE:
            from ..observability import compute as _comptel
            _comptel.count_cached(_SEG_CACHE, (self.sig, ()), "segment")
        if _OBS.MEM:
            from ..observability import memory as _memtel
            _memtel.note_segment_outputs(
                self.pending, self.live, out_vals, self.sig,
                mesh=self.spmd.desc if self.spmd is not None else None)
        outs = []
        for meta, val in zip(self.metas, out_vals):
            outs.append(Tensor(val, stop_gradient=not meta.requires_grad))
        register_segment_grad(self.pending, self.live, self.metas, outs,
                              in_tensors, in_vals, self.sig)
        return outs


class _ReplayMismatch(Exception):
    pass


# --------------------------------------------------------------- the guard
# Capture state is PER-THREAD. The window used to be process-global,
# which silently interleaved two threads' records into one segment —
# a DataLoader prefetch thread slicing Tensor batches while the main
# thread records the model corrupts the wiring (op indices race with
# concurrent resets). Per-thread windows give each thread its own
# eager order, exactly like per-thread CUDA streams in the reference;
# cross-thread tensor handoff materializes at the boundary (DataLoader
# does this for every queued batch).
import threading as _threading


class _ThreadState(_threading.local):
    def __init__(self):
        self.active: List[CaptureContext] = []   # explicit lazy_guards
        self.ambient: Optional[CaptureContext] = None


_TLS = _ThreadState()

# every open context, across threads — note_inplace must evict a
# mutated tensor's registration from ALL of them (an optimizer on the
# main thread swapping a payload another thread registered). Guarded:
# WeakSet iteration while another thread registers a context would
# RuntimeError.
_ALL_CTXS = weakref.WeakSet()
_ALL_CTXS_LOCK = _threading.Lock()


def _track_ctx(ctx: CaptureContext):
    with _ALL_CTXS_LOCK:
        _ALL_CTXS.add(ctx)


def current_context() -> Optional[CaptureContext]:
    # FLAGS_lazy_enable / FLAGS_eager_fusion are read through the
    # watcher-kept module gates, so toggling them mid-session (even
    # inside an open guard) still takes effect immediately — no stale
    # ambient state survives a flip, and the per-dispatch cost drops
    # from two registry lookups to two attribute reads
    tls = _TLS
    if not _LAZY_ENABLE:
        return None
    if tls.active:
        return tls.active[-1]
    if _EAGER_FUSION:
        if tls.ambient is None:
            tls.ambient = CaptureContext()
            _track_ctx(tls.ambient)
        return tls.ambient
    if tls.ambient is not None:
        # flag flipped off with ops pending: land them, then retire the
        # ambient context so dispatch is strictly per-op again
        ctx, tls.ambient = tls.ambient, None
        ctx.flush("ambient_disable")
    return None


def flush_active(reason: str = "materialize"):
    ctx = current_context()
    if ctx is not None:
        ctx.flush(reason)


def enable_eager_fusion(enable: bool = True) -> Optional[CaptureContext]:
    """Toggle the ambient fusion window (FLAGS_eager_fusion).

    With fusion on (the default), plain dygraph code (no lazy_guard)
    records ops into an ambient segment that runs as one cached XLA
    program at the next sync point (.numpy()/float()/backward()/segment
    cap) — the TPU-native analog of the reference's CUDA-stream
    run-ahead. Turning it off flushes anything pending and restores
    strict per-op dispatch. Returns the (calling thread's) ambient
    context when enabling."""
    from . import flags
    flags.set_flags({"FLAGS_eager_fusion": enable})
    return current_context() if not _TLS.active else _TLS.ambient


def eager_fusion_enabled() -> bool:
    from . import flags
    return bool(flags.flag_value("FLAGS_eager_fusion"))


def note_inplace(tensor):
    """Called by Tensor._replace_value_inplace: evict the tensor's input
    registration from EVERY open capture context, any thread (see
    CaptureContext.note_inplace; eviction itself is a GIL-atomic
    dict.pop)."""
    with _ALL_CTXS_LOCK:
        ctxs = list(_ALL_CTXS)
    for ctx in ctxs:
        ctx.note_inplace(tensor)


def try_fused_backward(tensors, grad_tensors) -> bool:
    """Whole-step fusion entry: backward() on a root still pending in the
    active window compiles forward+vjp as ONE cached XLA program (the
    "step cache", keyed on the segment signature + grad wiring) instead
    of flushing forward and walking the generic engine. Grads land
    directly on the leaves as in-flight futures; the graph is consumed
    (retain_graph=False semantics). Returns True when handled; any
    fallback condition returns False and the generic path runs."""
    ctx = current_context()
    if ctx is None or not ctx.pending or ctx.on_flush is not None:
        return False
    if len(tensors) != 1:
        return False
    if grad_tensors and any(g is not None for g in grad_tensors):
        return False
    root = tensors[0]
    p = root._payload
    if not getattr(p, "_is_lazy_ref", False) or p.ctx is not ctx \
            or p.op_idx is None or not p.requires_grad:
        return False
    if int(np.prod(p.aval.shape)) != 1:   # implicit seed needs a scalar
        return False
    if root._autograd_meta.hooks:
        return False

    pending = ctx.pending
    in_vals = ctx._in_vals
    in_meta = ctx._in_meta
    in_tensors = [r() for r in ctx._in_tensors]
    live, live_refs = ctx._live_outputs(pending)

    root_k = None
    for k, ref in enumerate(live_refs):
        if ref is p:
            root_k = k
        elif ref.requires_grad:
            # another grad-requiring output survives: the generic engine
            # must own the graph (user may backward through it later)
            return False
    if root_k is None:
        return False

    grad_in: List[int] = []
    for i, t in enumerate(in_tensors):
        req, meta, _ = in_meta[i]
        if not req or not jnp.issubdtype(in_vals[i].dtype, jnp.inexact):
            continue
        if meta.grad_node is not None or meta.hooks:
            # grads flow beyond this segment (even if the intermediate
            # tensor itself died), or a hook must fire: only the generic
            # engine handles that correctly
            return False
        if t is None:
            continue   # dead leaf: its grad is unobservable
        grad_in.append(i)
    if not grad_in:
        return False
    grad_in = tuple(grad_in)

    if PERF_OBSERVER is not None:
        # the fused fwd+vjp path seals the window without calling
        # flush(): report it so a perf trace's seal accounting matches
        # the segment.flush_reason.* counters exactly
        PERF_OBSERVER(ctx, "backward_fused", pending)

    # the sanitizer covers the fused fwd+vjp path exactly like a plain
    # flush — this IS the default steady-state train step, so 'error'
    # mode must stop a corrupted program here too (no donation mask:
    # fused-step inputs are the backward residuals). fixable=False:
    # the root/live layout is baked into the step-cache key, so fix
    # mode reports here instead of rewriting.
    from . import flags
    if _flags.STATIC_CHECKS_ACTIVE:
        from ..analysis import hooks as _sanitizer
        try:
            _mode = _sanitizer.check_mode()
            if _mode != "off":
                _sanitizer.on_segment_flush(
                    ctx, pending, in_vals, in_meta, in_tensors,
                    live, live_refs, (), _mode, fixable=False,
                    reason="backward_fused")
        except Exception:
            ctx._reset_segment()
            raise

    fspan = _obs_flush_span("backward_fused", len(pending), len(in_vals),
                            len(live), 0, ctx._fast_ops) \
        if _OBS.ACTIVE else None
    sig, driven = ctx._step_plan_sig(live)
    if sig is None:
        sig = ctx._signature(in_vals, live)
    key = (sig, grad_in, root_k)
    runner = _FUSED_CACHE.get(key)
    if runner is None and _persist.ACTIVE:
        run_vals = resolve_pending(in_vals) if _ASYNC_SEEN else in_vals
        runner = _disk_runner(
            "fused_step", (_persist_sig(sig), grad_in, root_k),
            _jit_factory(
                lambda: _build_fused_fn(pending, live, grad_in, root_k),
                (), run_vals, _spmd_for_compile(in_vals)),
            cache=_FUSED_CACHE, key=key, stat="fused_step")
        if runner is not None:
            _FUSED_CACHE[key] = runner
    compiled = runner is None
    if compiled and _flags.FAULT_INJECT_ACTIVE:
        # segment::compile fault site on the fused fwd+vjp path too:
        # clean up exactly like a real failed compile
        from ..distributed.resilience import faults as _faults
        try:
            _faults.inject("segment::compile")
        except Exception as e:
            ctx._reset_segment()
            if fspan is not None:
                fspan.end(error=e)
            _obs_flush_failed("backward_fused", e)
            raise
    run_vals = None
    if compiled:
        try:
            spmd_pin = _spmd_for_compile(in_vals)
            run_vals = resolve_pending(in_vals) if _ASYNC_SEEN \
                else in_vals
            runner = _compile_fused_runner(pending, live, grad_in,
                                           root_k, run_vals, key,
                                           spmd_pin)
        except Exception as e:
            # AOT compile (memory telemetry on) or pending-input
            # resolution failed: clean up exactly like a failed compile
            ctx._reset_segment()
            if fspan is not None:
                fspan.end(error=e)
            _obs_flush_failed("backward_fused", e)
            oe = _oom_convert(e, "backward_fused")
            if oe is not e:
                raise oe from e
            raise
        _FUSED_CACHE[key] = runner
        if _persist.ACTIVE:
            _disk_store("fused_step",
                        (_persist_sig(sig), grad_in, root_k),
                        runner, _FUSED_CACHE, key)
        if _OBS.METRICS:
            from ..observability import metrics
            metrics.inc("compiles.fused_step")
    dispatch.bump_exec()
    xspan = _obs_exec_span(compiled, len(pending), driven) \
        if fspan is not None else None
    try:
        if run_vals is None:     # cache hit: not resolved above
            run_vals = resolve_pending(in_vals) if _ASYNC_SEEN \
                else in_vals
        if _flags.FAULT_INJECT_ACTIVE:
            _inject_exec_oom()
        out_vals, grads = runner(*run_vals)
    except Exception as e:
        ctx._reset_segment()
        # spans end BEFORE the flight dump (report must carry them)
        if xspan is not None:
            xspan.end(error=e)
        if fspan is not None:
            fspan.end(error=e)
        _obs_flush_failed("backward_fused", e)
        oe = _oom_convert(e, "backward_fused",
                          _FUSED_CACHE.memory_info(key))
        if oe is not e:
            raise oe from e
        raise
    if xspan is not None:
        xspan.end()

    if flags.flag_value("FLAGS_check_nan_inf"):
        try:
            _nan_scan_segment(pending, live, out_vals,
                              "fused-step output", in_vals,
                              extra=("fused-step gradients", grads))
        except Exception as e:
            # a NaN trip drops the consumed trace like a failed compile
            # (leaving it armed would re-execute the whole forward as a
            # plain segment on the next read), closes the step span,
            # and triggers the flight post-mortem
            ctx._reset_segment()
            if fspan is not None:
                fspan.end(error=e)
            _obs_flush_failed("backward_fused", e)
            raise
    ctx._reset_segment()
    ctx.breaks.append("backward_fused")
    ctx.segments_run += 1

    # bind live outputs (they stay in-flight futures — tracing of the
    # next step overlaps this one's device execution)
    for ref, val in zip(live_refs, out_vals):
        for t in _live_aliases(ref):
            t._payload = val

    if SPMD is not None and _OBS.METRICS:
        # the dp gradient all-reduce (and any TP exchange) of this step
        # ran INSIDE the executable: account its estimated payload so
        # the comm-overlap report is not blind to compiled collectives
        _note_compiled_comm(_FUSED_CACHE, key, SPMD, run_vals,
                            list(out_vals) + list(grads), "fused_step")
    if _OBS.COMPUTE:
        from ..observability import compute as _comptel
        _comptel.count_cached(_FUSED_CACHE, key, "fused_step")
    if _OBS.MEM:
        from ..observability import memory as _memtel
        _memtel.note_segment_outputs(
            pending, live, out_vals, sig,
            mesh=SPMD.desc if SPMD is not None else None)
        for g in grads:
            _memtel.note_buffer(g, "fused_step.grad")

    from .autograd import GradNode, _accum
    from .tensor import Tensor
    for i, g in zip(grad_in, grads):
        t = in_tensors[i]
        meta = t._autograd_meta
        if meta.grad is None:
            meta.grad = Tensor(g, stop_gradient=True)
        else:
            meta.grad = Tensor(_accum(meta.grad._value, g),
                               stop_gradient=True)

    # the graph was consumed (retain_graph=False semantics): leave a
    # FREED GradNode on the root so a second backward() raises the same
    # "second time" error as the generic engine, instead of the root
    # looking like a leaf and the call silently no-opping
    meta = root._autograd_meta
    if meta.grad_node is None:
        tomb = GradNode(None, {}, None, [],
                        out_shapes=(tuple(p.aval.shape),),
                        out_dtypes=(p.aval.dtype,))
        tomb.name = "lazy_segment_fused"
        tomb.freed = True
        meta.grad_node = tomb
        meta.out_slot = 0
    if fspan is not None:
        fspan.end()
    return True


class lazy_guard:
    """Context manager enabling the lazy fusion window.

    with paddle_tpu.framework.lazy_guard() as ctx:
        ... eager code; ops fuse into XLA segments ...
    # exiting flushes everything pending
    """

    def __init__(self, max_segment_ops: Optional[int] = None):
        self._max = max_segment_ops
        self.ctx: Optional[CaptureContext] = None

    def __enter__(self) -> CaptureContext:
        from . import flags
        self.ctx = CaptureContext(self._max)
        if flags.flag_value("FLAGS_lazy_enable"):
            _TLS.active.append(self.ctx)
            _track_ctx(self.ctx)
            self._active = True
        else:
            self._active = False   # kill-switch: pure eager
        return self.ctx

    def __exit__(self, et, ev, tb):
        if not getattr(self, "_active", True):
            return False
        _TLS.active.pop()
        if et is None:
            self.ctx.flush("guard_exit")
        else:
            # error path: still materialize what was recorded — tensors
            # computed before the error are valid (eager would have
            # them), and leaving them lazy would poison later reads.
            # Suppress secondary failures during unwind.
            try:
                self.ctx.flush("guard_error")
            except Exception:
                self.ctx._reset_segment()
        return False


def segment_cache_size() -> int:
    return len(_SEG_CACHE)


def clear_segment_cache():
    _SEG_CACHE.clear()
    _SEG_BWD_CACHE.clear()
    _FUSED_CACHE.clear()
    _AVAL_CACHE.clear()
    if _NC is not None:
        _NC.aval_cache_clear()
