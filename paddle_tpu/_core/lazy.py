"""Lazy op-capture engine: the eager fusion window + SOT graph builder.

Two reference roles land here, rebuilt the XLA way:

- the *fusion buffer / lazy trace window* the reference gets from CUDA
  stream asynchrony (per-op kernels queue on a stream; the host runs
  ahead): under `lazy_guard()` eager ops are RECORDED instead of
  dispatched one executable at a time, and a whole pending segment runs
  as ONE jitted XLA program the first time any concrete value is needed.
  This removes per-op dispatch latency and lets XLA fuse across op
  boundaries (SURVEY §7 hard part #1).
- the *FunctionGraph* under SOT-style bytecode capture
  (python/paddle/jit/sot/symbolic/symbolic_context.py role): jit/sot's
  OpcodeExecutor runs user bytecode under this context; every framework
  op joins the graph, and any graph break (print, .numpy(), a
  data-dependent branch) is just a flush — the remaining trace resumes
  into a new segment automatically.

Materialization triggers: reading `Tensor._value` (property), exiting
the guard, `backward()`, or the segment hitting
FLAGS_lazy_max_segment_ops. Shape/dtype/ndim metadata reads answer from
the recorded aval WITHOUT materializing.

Compiled segments are cached by a structural signature (op names, attrs,
wiring, input avals), so steady-state replays cost one cache lookup and
one XLA execution per segment.
"""
from __future__ import annotations

import functools
import weakref
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from . import dispatch
from .op_registry import OpDef

_SEG_CACHE: Dict[Tuple, Any] = {}
_AVAL_CACHE: Dict[Tuple, Tuple] = {}


class LazyRef:
    """Placeholder payload for one output of one pending op."""

    _is_lazy_ref = True
    __slots__ = ("ctx", "op_idx", "slot", "aval", "requires_grad",
                 "trefs", "__weakref__")

    def __init__(self, ctx, op_idx, slot, aval, requires_grad):
        self.ctx = ctx
        self.op_idx = op_idx
        self.slot = slot
        self.aval = aval              # jax.ShapeDtypeStruct
        self.requires_grad = requires_grad
        self.trefs: List = []         # weakrefs to Tensors aliasing this

    def add_tref(self, tensor):
        self.trefs.append(weakref.ref(tensor))

    def materialize(self):
        self.ctx.flush()


class _PendingOp:
    __slots__ = ("op", "attrs", "wiring", "out_refs", "n_outs")

    def __init__(self, op, attrs, wiring, out_refs):
        self.op = op
        self.attrs = attrs
        self.wiring = wiring          # per input: ("in", i) | ("op", j, s) | None
        self.out_refs = out_refs      # list[LazyRef]
        self.n_outs = len(out_refs)


def _aval_of(x):
    # weak_type MUST survive: python scalars are weak (x64 mode makes
    # them f64-weak) and weak+f32 promotes to f32, not f64
    return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                weak_type=getattr(x, "weak_type", False))


def _out_avals(op: OpDef, attrs, in_avals):
    from .dispatch import attrs_key
    backend = jax.default_backend()
    key = (op.name, backend, attrs_key(attrs),
           tuple((tuple(a.shape), str(a.dtype), a.weak_type)
                 if a is not None else None for a in in_avals))
    hit = _AVAL_CACHE.get(key)
    if hit is None:
        fn = functools.partial(op.kernel_for(backend), **attrs)
        out = jax.eval_shape(fn, *in_avals)
        outs = out if op.multi_output else (out,)
        hit = tuple(jax.tree_util.tree_leaves(outs))
        if len(hit) != len(outs):
            # nested outputs: treat as un-capturable
            raise TypeError(f"op {op.name} has nested outputs")
        _AVAL_CACHE[key] = hit
    return hit


class CaptureContext:
    """One lazy trace. Ops recorded since the last flush form the current
    segment; flush() compiles + runs it as one XLA executable."""

    def __init__(self, max_segment_ops: Optional[int] = None):
        from . import flags
        self.pending: List[_PendingOp] = []
        # graph inputs of the CURRENT segment: id(tensor) -> index
        self._in_ids: Dict[int, int] = {}
        self._in_tensors: List = []   # strong refs (cleared per segment)
        self._in_vals: List = []
        self.max_ops = max_segment_ops if max_segment_ops is not None \
            else flags.flag_value("FLAGS_lazy_max_segment_ops")
        # stats for tests / profiling
        self.segments_run = 0
        self.ops_recorded = 0
        self.breaks: List[str] = []

    # ---------------------------------------------------------- recording
    def _input_index(self, tensor) -> int:
        idx = self._in_ids.get(id(tensor))
        if idx is None:
            idx = len(self._in_vals)
            self._in_ids[id(tensor)] = idx
            self._in_tensors.append(tensor)
            self._in_vals.append(tensor._payload)
        return idx

    def record(self, op: OpDef, ts, attrs):
        """Record one op application; returns out Tensors (lazy)."""
        from .autograd import is_grad_enabled
        from .tensor import Tensor

        # pass 1: resolve avals WITHOUT mutating the input record, so a
        # failing aval inference (un-capturable op) leaves no ghost
        # inputs behind for the record-fallback path to drag along
        resolved = []
        in_avals = []
        req = False
        for t in ts:
            if t is None:
                resolved.append(None)
                in_avals.append(None)
                continue
            p = t._payload
            if getattr(p, "_is_lazy_ref", False):
                if p.ctx is self and p.op_idx is not None:
                    resolved.append(("op", p.op_idx, p.slot))
                    in_avals.append(p.aval)
                    req = req or p.requires_grad
                    continue
                # lazy value from another context: materialize it
                p.materialize()
                p = t._payload
            resolved.append(("ext", t))
            in_avals.append(_aval_of(p))
            req = req or (not t.stop_gradient)

        out_avals = _out_avals(op, attrs, in_avals)

        # pass 2 (cannot fail): register external inputs + build wiring
        wiring = []
        for r in resolved:
            if r is None:
                wiring.append(None)
            elif r[0] == "ext":
                wiring.append(("in", self._input_index(r[1])))
            else:
                wiring.append(r)
        req = req and is_grad_enabled()
        op_idx = len(self.pending)
        out_refs = []
        outs = []
        for s, aval in enumerate(out_avals):
            inexact = jnp.issubdtype(aval.dtype, jnp.inexact)
            ref = LazyRef(self, op_idx, s, aval, req and inexact)
            t = _lazy_tensor(ref, stop_gradient=not (req and inexact))
            out_refs.append(ref)
            outs.append(t)
        self.pending.append(_PendingOp(op, dict(attrs), tuple(wiring),
                                       out_refs))
        self.ops_recorded += 1
        return tuple(outs)

    def maybe_cap_flush(self):
        """Called by the executor AFTER a successful record, outside its
        record-fallback handler, so a failing segment execution surfaces
        instead of being swallowed as an 'uncapturable op'."""
        if len(self.pending) >= self.max_ops:
            self.flush("segment_cap")

    # ------------------------------------------------------------- flush
    def flush(self, reason: str = "materialize"):
        if not self.pending:
            # nothing recorded, but clear any input registrations a
            # partially-failed record may have left behind
            self._in_ids = {}
            self._in_tensors = []
            self._in_vals = []
            return
        pending = self.pending
        in_tensors = self._in_tensors
        in_vals = self._in_vals

        # live outputs: lazy refs some Tensor still aliases
        live: List[Tuple[int, int]] = []
        live_refs: List[LazyRef] = []
        for j, pop in enumerate(pending):
            for ref in pop.out_refs:
                if any(r() is not None for r in ref.trefs):
                    live.append((j, ref.slot))
                    live_refs.append(ref)

        sig = _segment_signature(pending, in_vals, live)
        runner = _SEG_CACHE.get(sig)
        if runner is None:
            runner = jax.jit(_build_segment_fn(pending, live))
            _SEG_CACHE[sig] = runner
        # run BEFORE clearing state: a compile/run failure must leave the
        # trace intact (and surface the real error), not lose it
        out_vals = runner(list(in_vals))
        self.pending = []
        self._in_ids = {}
        self._in_tensors = []
        self._in_vals = []
        self.breaks.append(reason)
        self.segments_run += 1

        # bind concrete values into every alive aliasing Tensor; the
        # grad node attaches to a grad-REQUIRING alias — a detach()ed
        # alias must never have its stop_gradient flipped back
        out_tensors = []
        for ref, val in zip(live_refs, out_vals):
            ts = [r() for r in ref.trefs]
            ts = [t for t in ts if t is not None]
            for t in ts:
                t._payload = val
            grad_ts = [t for t in ts if not t.stop_gradient]
            out_tensors.append(grad_ts[0] if grad_ts
                               else (ts[0] if ts else None))

        self._register_grad(pending, live, live_refs, out_tensors,
                            in_tensors, in_vals, sig)

        if self.on_flush is not None:
            self.on_flush(self, reason, pending, live, live_refs,
                          in_tensors, in_vals, sig, out_tensors)

    on_flush = None  # observer hook (jit/sot records segment structure)

    # ----------------------------------------------------------- autograd
    def _register_grad(self, pending, live, live_refs, out_tensors,
                       in_tensors, in_vals, sig):
        register_segment_grad(pending, live, live_refs, out_tensors,
                              in_tensors, in_vals, sig)


def register_segment_grad(pending, live, live_refs, out_tensors,
                          in_tensors, in_vals, sig):
    """Wire ONE fused GradNode for an executed segment. live_refs only
    needs .aval / .requires_grad (LazyRef or a replay meta)."""
    from .autograd import GradNode, _Edge
    # NOTE deliberately no is_grad_enabled() check here: grad intent was
    # decided at RECORD time (ref.requires_grad), matching eager
    # semantics — a flush that happens to run inside no_grad (e.g. a
    # logging read) must not drop gradients for ops recorded outside it
    grad_in = [i for i, t in enumerate(in_tensors)
               if not t.stop_gradient
               and jnp.issubdtype(in_vals[i].dtype, jnp.inexact)]
    grad_out = [k for k, ref in enumerate(live_refs)
                if ref.requires_grad]
    if not grad_in or not grad_out:
        return

    gi = set(grad_in)
    edges = []
    versions = []
    refs = []
    for i, t in enumerate(in_tensors):
        if i not in gi:
            edges.append(_Edge(None))
            versions.append(t._inplace_version)
            refs.append(None)
            continue
        meta = t._autograd_meta
        if meta.grad_node is not None:
            edges.append(_Edge("node", node=meta.grad_node,
                               slot=meta.out_slot))
        else:
            edges.append(_Edge("leaf", leaf=t))
        versions.append(t._inplace_version)
        refs.append(weakref.ref(t))

    node = GradNode(
        None, {}, tuple(in_vals), edges,
        out_shapes=tuple(tuple(r.aval.shape) for r in live_refs),
        out_dtypes=tuple(r.aval.dtype for r in live_refs))
    node.name = "lazy_segment"
    node.saved_versions = tuple(versions)
    node.in_refs = tuple(refs)

    bwd = _segment_bwd(sig, pending, live, tuple(grad_in))

    def py_bwd(gouts, _saved=tuple(in_vals), _bwd=bwd,
               _refs=live_refs, _n=len(grad_in)):
        cts = []
        for g, ref in zip(gouts, _refs):
            if g is None:
                cts.append(jnp.zeros(ref.aval.shape, ref.aval.dtype))
            elif hasattr(g, "astype") and g.dtype != ref.aval.dtype:
                cts.append(g.astype(ref.aval.dtype))
            else:
                cts.append(g)
        grads = _bwd(list(_saved), tuple(cts))
        out = []
        for g in grads:
            if g is None or (hasattr(g, "dtype")
                             and g.dtype == jax.dtypes.float0):
                out.append(None)
            else:
                out.append(g)
        return tuple(out)

    # edges cover every segment input; py_bwd returns grads aligned
    # with them (None for stop-gradient slots)
    def py_bwd_full(gouts, _inner=py_bwd, _n_in=len(in_tensors),
                    _grad_in=tuple(grad_in)):
        grads = _inner(gouts)
        out = [None] * _n_in
        for g, i in zip(grads, _grad_in):
            out[i] = g
        return tuple(out)

    node.py_bwd = py_bwd_full

    for k, t in enumerate(out_tensors):
        if k in grad_out and t is not None and not t.stop_gradient:
            m = t._autograd_meta
            if m.grad_node is None:
                m.grad_node = node
                m.out_slot = k


def _segment_signature(pending, in_vals, live):
    from .dispatch import attrs_key
    ops_sig = tuple(
        (p.op.name, attrs_key(p.attrs), p.wiring, p.n_outs)
        for p in pending)
    in_sig = tuple((tuple(v.shape), str(v.dtype),
                    bool(getattr(v, "weak_type", False)))
                   for v in in_vals)
    return (jax.default_backend(), ops_sig, in_sig, tuple(live))


def _build_segment_fn(pending, live):
    backend = jax.default_backend()
    steps = []
    for p in pending:
        steps.append((functools.partial(p.op.kernel_for(backend),
                                        **p.attrs),
                      p.wiring, p.op.multi_output))

    def seg_fn(inputs):
        vals: List[Tuple] = []
        for fn, wiring, multi in steps:
            ins = []
            for w in wiring:
                if w is None:
                    ins.append(None)
                elif w[0] == "in":
                    ins.append(inputs[w[1]])
                else:
                    ins.append(vals[w[1]][w[2]])
            out = fn(*ins)
            vals.append(tuple(out) if multi else (out,))
        return [vals[j][s] for (j, s) in live]

    return seg_fn


_SEG_BWD_CACHE: Dict[Tuple, Any] = {}


def _segment_bwd(sig, pending, live, grad_in: Tuple[int, ...]):
    key = (sig, grad_in)
    fn = _SEG_BWD_CACHE.get(key)
    if fn is None:
        seg = _build_segment_fn(pending, live)

        def bwd(inputs, cts, _seg=seg, _gi=grad_in):
            def f(*gvals):
                full = list(inputs)
                for v, i in zip(gvals, _gi):
                    full[i] = v
                return _seg(full)
            _, pull = jax.vjp(f, *[inputs[i] for i in _gi])
            return pull(list(cts))

        fn = jax.jit(bwd)
        _SEG_BWD_CACHE[key] = fn
    return fn


def _lazy_tensor(ref: LazyRef, stop_gradient=True):
    from .tensor import Tensor
    t = object.__new__(Tensor)
    t._payload = ref
    t._stop_gradient = stop_gradient
    from .autograd import AutogradMeta
    t._autograd_meta = AutogradMeta()
    t._inplace_version = 0
    t.name = None
    t.persistable = False
    t._dist_attr = None
    ref.add_tref(t)
    return t


class _RefMeta:
    """Replay stand-in for LazyRef (register_segment_grad contract)."""
    __slots__ = ("aval", "requires_grad")

    def __init__(self, aval, requires_grad):
        self.aval = aval
        self.requires_grad = requires_grad


class ReplayableSegment:
    """A captured segment that can be re-executed directly on fresh input
    tensors — the compiled body of jit/sot's guarded fast path. Built
    from a CaptureContext flush event; replay skips recording entirely:
    fetch inputs, run the cached executable, wrap outputs, register the
    fused GradNode."""

    def __init__(self, pending, live, live_refs, in_vals, sig):
        self.pending = pending
        self.live = live
        self.metas = [_RefMeta(r.aval, r.requires_grad) for r in live_refs]
        self.sig = sig
        self.in_avals = tuple((tuple(v.shape), str(v.dtype))
                              for v in in_vals)
        # which inputs fed grad-requiring chains at capture (replay must
        # see the same stop_gradient mask to reuse the vjp wiring)
        self.grad_mask = None

    def run(self, in_tensors):
        from .tensor import Tensor
        in_vals = [t._value for t in in_tensors]
        got = tuple((tuple(v.shape), str(v.dtype)) for v in in_vals)
        if got != self.in_avals:
            raise _ReplayMismatch("input avals changed")
        runner = _SEG_CACHE.get(self.sig)
        if runner is None:
            runner = jax.jit(_build_segment_fn(self.pending, self.live))
            _SEG_CACHE[self.sig] = runner
        out_vals = runner(list(in_vals))
        outs = []
        for meta, val in zip(self.metas, out_vals):
            outs.append(Tensor(val, stop_gradient=not meta.requires_grad))
        register_segment_grad(self.pending, self.live, self.metas, outs,
                              in_tensors, in_vals, self.sig)
        return outs


class _ReplayMismatch(Exception):
    pass


# --------------------------------------------------------------- the guard
_ACTIVE: List[CaptureContext] = []


def current_context() -> Optional[CaptureContext]:
    return _ACTIVE[-1] if _ACTIVE else None


def flush_active(reason: str = "materialize"):
    if _ACTIVE:
        _ACTIVE[-1].flush(reason)


class lazy_guard:
    """Context manager enabling the lazy fusion window.

    with paddle_tpu.framework.lazy_guard() as ctx:
        ... eager code; ops fuse into XLA segments ...
    # exiting flushes everything pending
    """

    def __init__(self, max_segment_ops: Optional[int] = None):
        self._max = max_segment_ops
        self.ctx: Optional[CaptureContext] = None

    def __enter__(self) -> CaptureContext:
        from . import flags
        self.ctx = CaptureContext(self._max)
        if flags.flag_value("FLAGS_lazy_enable"):
            _ACTIVE.append(self.ctx)
            self._active = True
        else:
            self._active = False   # kill-switch: pure eager
        return self.ctx

    def __exit__(self, et, ev, tb):
        if not getattr(self, "_active", True):
            return False
        _ACTIVE.pop()
        if et is None:
            self.ctx.flush("guard_exit")
        else:
            # error path: still materialize what was recorded — tensors
            # computed before the error are valid (eager would have
            # them), and leaving them lazy would poison later reads.
            # Suppress secondary failures during unwind.
            try:
                self.ctx.flush("guard_error")
            except Exception:
                self.ctx.pending = []
                self.ctx._in_ids = {}
                self.ctx._in_tensors = []
                self.ctx._in_vals = []
        return False


def segment_cache_size() -> int:
    return len(_SEG_CACHE)


def clear_segment_cache():
    _SEG_CACHE.clear()
    _SEG_BWD_CACHE.clear()
    _AVAL_CACHE.clear()
