"""ctypes bridge to the native runtime library (csrc/).

The reference keeps its runtime substrate in C++ (SURVEY.md §2a/§2e); here
the TPU-native equivalents — TCPStore rendezvous, auto-growth best-fit
host allocator, prefetching data feed, flag registry — live in
csrc/libpaddle_tpu_rt.so, built on first use with g++ (no pybind: plain C
ABI + ctypes, the same dlopen shape as the reference's custom-device
plugin ABI, paddle/phi/backends/device_ext.h:96)."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_lib = None
_lib_lock = threading.Lock()
_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "build", "libpaddle_tpu_rt.so")
def _sources():
    # derived, not duplicated: every .cc/.h under csrc/ participates in
    # staleness so build.sh and this list cannot silently diverge
    import glob
    return (glob.glob(os.path.join(_CSRC, "*.cc"))
            + glob.glob(os.path.join(_CSRC, "*.h")))


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    for p in _sources():
        if os.path.getmtime(p) > so_mtime:
            return True
    return False


def _build():
    import sys
    env = dict(os.environ)
    env["PT_PYTHON"] = sys.executable   # ABI-match the extension build
    subprocess.run(["sh", os.path.join(_CSRC, "build.sh")], check=True,
                   capture_output=True, env=env)


def _bind(lib):
    c = ctypes
    lib.pt_last_error.restype = c.c_char_p
    lib.pt_store_server_start.restype = c.c_void_p
    lib.pt_store_server_start.argtypes = [c.c_int]
    lib.pt_store_server_port.restype = c.c_int
    lib.pt_store_server_port.argtypes = [c.c_void_p]
    lib.pt_store_server_stop.argtypes = [c.c_void_p]
    lib.pt_store_client_connect.restype = c.c_void_p
    lib.pt_store_client_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pt_store_client_close.argtypes = [c.c_void_p]
    lib.pt_store_set.restype = c.c_int
    lib.pt_store_set.argtypes = [c.c_void_p, c.c_char_p, c.c_void_p,
                                 c.c_uint32]
    lib.pt_store_get.restype = c.c_int64
    lib.pt_store_get.argtypes = [c.c_void_p, c.c_char_p, c.c_void_p,
                                 c.c_int64, c.c_uint32]
    lib.pt_store_wait.restype = c.c_int
    lib.pt_store_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_uint32]
    lib.pt_store_del.restype = c.c_int
    lib.pt_store_del.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_store_add.restype = c.c_int64
    lib.pt_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]

    lib.pt_alloc_create.restype = c.c_void_p
    lib.pt_alloc_create.argtypes = [c.c_uint64]
    lib.pt_alloc_destroy.argtypes = [c.c_void_p]
    lib.pt_alloc_malloc.restype = c.c_void_p
    lib.pt_alloc_malloc.argtypes = [c.c_void_p, c.c_uint64]
    lib.pt_alloc_free.restype = c.c_int
    lib.pt_alloc_free.argtypes = [c.c_void_p, c.c_void_p]
    lib.pt_alloc_stats.argtypes = [c.c_void_p,
                                   c.POINTER(c.c_uint64),
                                   c.POINTER(c.c_uint64)]

    lib.pt_feed_create.restype = c.c_void_p
    lib.pt_feed_create.argtypes = [c.c_char_p, c.c_int64, c.c_int64,
                                   c.c_int, c.c_uint64, c.c_int]
    lib.pt_feed_num_windows.restype = c.c_int64
    lib.pt_feed_num_windows.argtypes = [c.c_void_p]
    lib.pt_feed_next.restype = c.c_int
    lib.pt_feed_next.argtypes = [c.c_void_p, c.c_void_p]
    lib.pt_feed_destroy.argtypes = [c.c_void_p]

    lib.pt_flag_define.restype = c.c_int
    lib.pt_flag_define.argtypes = [c.c_char_p, c.c_char_p]
    lib.pt_flag_set.restype = c.c_int
    lib.pt_flag_set.argtypes = [c.c_char_p, c.c_char_p]
    lib.pt_flag_get.restype = c.c_int64
    lib.pt_flag_get.argtypes = [c.c_char_p, c.c_char_p, c.c_int64]

    lib.ptcc_create.restype = c.c_void_p
    lib.ptcc_create.argtypes = [c.c_int, c.c_int]
    lib.ptcc_listen_port.restype = c.c_int
    lib.ptcc_listen_port.argtypes = [c.c_void_p]
    lib.ptcc_connect.restype = c.c_int
    lib.ptcc_connect.argtypes = [c.c_void_p, c.c_char_p]
    lib.ptcc_all_reduce.restype = c.c_int
    lib.ptcc_all_reduce.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                    c.c_int, c.c_int]
    lib.ptcc_reduce_scatter.restype = c.c_int
    lib.ptcc_reduce_scatter.argtypes = [c.c_void_p, c.c_void_p,
                                        c.c_void_p, c.c_int64, c.c_int,
                                        c.c_int]
    lib.ptcc_all_gather.restype = c.c_int
    lib.ptcc_all_gather.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                    c.c_int64]
    lib.ptcc_broadcast.restype = c.c_int
    lib.ptcc_broadcast.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                   c.c_int]
    lib.ptcc_send.restype = c.c_int
    lib.ptcc_send.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.c_int]
    lib.ptcc_recv.restype = c.c_int
    lib.ptcc_recv.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.c_int]
    lib.ptcc_barrier.restype = c.c_int
    lib.ptcc_barrier.argtypes = [c.c_void_p]
    lib.ptcc_destroy.argtypes = [c.c_void_p]

    lib.pt_plugin_load.restype = c.c_char_p
    lib.pt_plugin_load.argtypes = [c.c_char_p]
    lib.pt_plugin_device_count.restype = c.c_int
    lib.pt_plugin_device_count.argtypes = [c.c_char_p]
    lib.pt_plugin_malloc.restype = c.c_void_p
    lib.pt_plugin_malloc.argtypes = [c.c_char_p, c.c_int, c.c_uint64]
    lib.pt_plugin_free.restype = c.c_int
    lib.pt_plugin_free.argtypes = [c.c_char_p, c.c_int, c.c_void_p]
    lib.pt_plugin_memcpy.restype = c.c_int
    lib.pt_plugin_memcpy.argtypes = [c.c_char_p, c.c_int, c.c_void_p,
                                     c.c_void_p, c.c_uint64, c.c_int]
    lib.pt_plugin_mem_stats.restype = c.c_int
    lib.pt_plugin_mem_stats.argtypes = [c.c_char_p, c.c_int,
                                        c.POINTER(c.c_uint64),
                                        c.POINTER(c.c_uint64)]
    lib.pt_plugin_stream_check.restype = c.c_int
    lib.pt_plugin_stream_check.argtypes = [c.c_char_p, c.c_int]
    lib.pt_plugin_ccl_all_reduce.restype = c.c_int
    lib.pt_plugin_ccl_all_reduce.argtypes = [c.c_char_p, c.c_int,
                                             c.c_void_p, c.c_uint64,
                                             c.c_int, c.c_int]
    lib.pt_custom_op_load.restype = c.c_int
    lib.pt_custom_op_load.argtypes = [c.c_char_p, c.c_char_p]
    lib.pt_custom_op_call.restype = c.c_int
    lib.pt_custom_op_call.argtypes = [c.c_char_p,
                                      c.POINTER(c.c_void_p),
                                      c.POINTER(c.c_int64), c.c_int,
                                      c.c_void_p, c.c_int64]
    return lib


def get_lib(required: bool = False) -> Optional[ctypes.CDLL]:
    """Load (building if stale) the native runtime; None when the
    toolchain is unavailable and required=False."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        try:
            if _needs_build():
                _build()
            _lib = _bind(ctypes.CDLL(_SO))
        except Exception:
            if required:
                raise
            return None
    return _lib


def last_error() -> str:
    lib = get_lib()
    return lib.pt_last_error().decode() if lib is not None else ""


def bind_jit(lib):
    """ctypes signatures for the C++ jit layer (bound lazily: only the
    inference path needs them)."""
    import ctypes as c
    if getattr(lib, "_jit_bound", False):
        return lib
    lib.pt_jit_open.restype = c.c_void_p
    lib.pt_jit_open.argtypes = [c.c_char_p]
    lib.pt_jit_num_params.restype = c.c_int
    lib.pt_jit_num_params.argtypes = [c.c_void_p]
    lib.pt_jit_param_name.restype = c.c_char_p
    lib.pt_jit_param_name.argtypes = [c.c_void_p, c.c_int]
    lib.pt_jit_param_dtype.restype = c.c_char_p
    lib.pt_jit_param_dtype.argtypes = [c.c_void_p, c.c_int]
    lib.pt_jit_param_shape.restype = c.c_int
    lib.pt_jit_param_shape.argtypes = [c.c_void_p, c.c_int,
                                       c.POINTER(c.c_int64), c.c_int]
    lib.pt_jit_param_data.restype = c.c_void_p
    lib.pt_jit_param_data.argtypes = [c.c_void_p, c.c_int,
                                      c.POINTER(c.c_uint64)]
    lib.pt_jit_program.restype = c.c_void_p
    lib.pt_jit_program.argtypes = [c.c_void_p, c.POINTER(c.c_uint64)]
    lib.pt_jit_close.argtypes = [c.c_void_p]
    lib._jit_bound = True
    return lib


_HOST_POOL = None


def host_pool():
    """Process-wide native host memory pool (csrc/allocator.cc), sized
    by FLAGS_host_alloc_chunk_kb at first use — the python face of the
    reference's host AllocatorFacade."""
    global _HOST_POOL
    if _HOST_POOL is None:
        from . import flags
        lib = get_lib(required=True)
        _HOST_POOL = lib.pt_alloc_create(
            int(flags.flag_value("FLAGS_host_alloc_chunk_kb")) * 1024)
    return _HOST_POOL


_EAGER_CORE = None
_EAGER_CORE_TRIED = False


def get_eager_core():
    """The eager hot-path CPython extension (csrc/eager_core.cc):
    dispatch-key construction, backward in-degree BFS, and the NATIVE
    RECORD CORE — interned shape/dtype atoms, the record-time out-aval
    cache (C key build + lookup), the sig-entry intern, and the
    trace-stable skeleton matcher ``skel_record`` that replays one
    recorded op per C call (lazy.py arms/validates the skeleton and
    stands alone in pure python when this returns None). Returns None
    when unavailable (python fallbacks stay correct); set
    PT_DISABLE_NATIVE_EAGER=1 to force the python path. Consumers
    cache their own resolution (dispatch._EAGER_CORE, lazy._NC) so
    bench row 17 and the fallback tests can force either prong
    in-process."""
    global _EAGER_CORE, _EAGER_CORE_TRIED
    if _EAGER_CORE_TRIED:
        return _EAGER_CORE
    _EAGER_CORE_TRIED = True
    if os.environ.get("PT_DISABLE_NATIVE_EAGER") == "1":
        return None
    try:
        get_lib(required=True)   # builds csrc (including the extension)
        import importlib.util
        so = os.path.join(_CSRC, "build", "pt_eager_core.so")
        spec = importlib.util.spec_from_file_location("pt_eager_core", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _EAGER_CORE = mod
    except Exception:
        _EAGER_CORE = None
    return _EAGER_CORE
