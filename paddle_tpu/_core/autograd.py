"""Eager autograd engine.

TPU-native analog of the reference's eager autograd:
  - AutogradMeta  <- paddle/fluid/eager/autograd_meta.h:61
  - GradNode      <- paddle/fluid/eager/grad_node_info.h:197 (slot-wise edges)
  - saved inputs  <- TensorWrapper (tensor_wrapper.h) incl. inplace-version check
  - run_backward  <- egr::RunBackward, queue + in-degree topological traversal
                     (paddle/fluid/eager/backward.cc:106,226)
  - grad()        <- partial-graph paddle.grad (general_grad.h)

Device work stays async on the TPU stream: the engine only orchestrates
which cached XLA executables run; accumulation itself is a jitted add.
"""
from __future__ import annotations

import threading
import weakref
from collections import defaultdict, deque
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from . import dispatch
from ..observability import _state as _OBS
from .op_registry import OpDef

# ---------------------------------------------------------------- grad mode

_STATE = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_STATE, "grad_enabled", True)


def _set_grad_enabled(v: bool):
    _STATE.grad_enabled = v


class no_grad:
    """Context manager / decorator disabling grad recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = is_grad_enabled()
        _set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        _set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)
        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = is_grad_enabled()
        _set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        _set_grad_enabled(self._prev)
        return False


class set_grad_enabled:
    def __init__(self, mode: bool):
        self._mode = bool(mode)

    def __enter__(self):
        self._prev = is_grad_enabled()
        _set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        _set_grad_enabled(self._prev)
        return False


# ---------------------------------------------------------------- graph types

class AutogradMeta:
    """Per-tensor autograd info (autograd_meta.h:61)."""

    __slots__ = ("grad", "grad_node", "out_slot", "hooks", "retain_grads")

    def __init__(self):
        self.grad = None           # Tensor
        self.grad_node: Optional["GradNode"] = None
        self.out_slot: int = 0
        self.hooks: List = []
        self.retain_grads = False


class _Edge:
    """Edge from a node input to the producer of that input."""
    __slots__ = ("kind", "node", "slot", "leaf")

    def __init__(self, kind, node=None, slot=0, leaf=None):
        self.kind = kind      # 'node' | 'leaf' | None
        self.node = node
        self.slot = slot
        self.leaf = leaf      # weak-ish direct ref to the leaf Tensor


class GradNode:
    """One recorded op application (grad_node_info.h:197)."""

    __slots__ = ("op", "attrs", "saved", "saved_versions", "edges",
                 "out_shapes", "out_dtypes", "out_hooks", "name", "py_bwd",
                 "in_refs", "freed")

    def __init__(self, op: OpDef, attrs, saved, edges, out_shapes, out_dtypes):
        self.op = op
        self.attrs = attrs
        self.saved = saved                  # raw jax values (TensorWrapper)
        self.saved_versions = None          # filled by record() for inputs
        self.edges: List[_Edge] = edges     # one per op input
        self.out_shapes = out_shapes
        self.out_dtypes = out_dtypes
        self.out_hooks: Dict[int, List] = {}
        self.name = op.name if op is not None else "pylayer"
        self.py_bwd = None                  # set for PyLayer-style nodes
        self.in_refs = None                 # weakrefs for version checks
        self.freed = False                  # saved buffers released

    def _check_versions(self):
        """TensorWrapper safety (tensor_wrapper.h): an input mutated
        in-place after being saved for backward corrupts gradients —
        fail loudly instead."""
        if self.in_refs is None or self.saved_versions is None:
            return
        for i, ref in enumerate(self.in_refs):
            t = ref() if ref is not None else None
            if t is not None and \
                    t._inplace_version != self.saved_versions[i]:
                raise RuntimeError(
                    f"a variable needed for the backward of op "
                    f"'{self.name}' (input {i}) was modified by an "
                    f"inplace operation (saved version "
                    f"{self.saved_versions[i]}, current "
                    f"{t._inplace_version}); clone() it before the "
                    f"inplace update")

    def apply(self, gouts: Tuple) -> Tuple:
        if self.freed:
            raise RuntimeError(
                "trying to run backward through the graph a second time "
                "(saved activations already freed); call "
                "backward(retain_graph=True) if you need to")
        self._check_versions()
        if self.py_bwd is not None:
            return self.py_bwd(gouts)
        return dispatch.eager_backward(self.op, self.saved, self.attrs, gouts)

    def free(self):
        """Release saved activations after backward (retain_graph=False
        semantics, the reference's buffer release in backward.cc)."""
        self.saved = None
        self.freed = True


_accum_jit = jax.jit(jnp.add)


def _accum(a, b):
    dispatch.bump_exec()
    return _accum_jit(a, b)


def record(op: OpDef, attrs, in_tensors, out_tensors, saved_vals=None):
    """Record a GradNode linking outputs to inputs (eager_gen.py analog).

    Called by the op executor when grad mode is on and any input requires
    grad. Integer/bool outputs never require grad.
    """
    edges = []
    versions = []
    for t in in_tensors:
        if t is None or t.stop_gradient:
            edges.append(_Edge(None))
            versions.append(0 if t is None else t._inplace_version)
            continue
        meta = t._autograd_meta
        if meta.grad_node is not None:
            edges.append(_Edge("node", node=meta.grad_node, slot=meta.out_slot))
        else:
            edges.append(_Edge("leaf", leaf=t))
        versions.append(t._inplace_version)

    saved = tuple(None if t is None else t._value for t in in_tensors) \
        if saved_vals is None else tuple(saved_vals)
    node = GradNode(
        op, attrs, saved, edges,
        out_shapes=tuple(t.shape for t in out_tensors),
        out_dtypes=tuple(t._value.dtype for t in out_tensors))
    node.saved_versions = tuple(versions)
    node.in_refs = tuple(
        None if t is None else weakref.ref(t) for t in in_tensors)

    for i, t in enumerate(out_tensors):
        if jnp.issubdtype(t._value.dtype, jnp.inexact):
            t.stop_gradient = False
            m = t._autograd_meta
            m.grad_node = node
            m.out_slot = i
    return node


# ---------------------------------------------------------------- the engine

def _discover(roots: List[GradNode]):
    """BFS the grad graph; return per-node in-degree (edge reference
    counts). The C extension (csrc/eager_core.cc discover) runs the
    same walk in one C loop; this python body is the fallback."""
    from .dispatch import _eager_core
    ec = _eager_core()
    if ec is not None:
        return ec.discover(roots)
    deps: Dict[GradNode, int] = defaultdict(int)
    visited = set()
    q = deque(roots)
    for r in roots:
        visited.add(id(r))
        deps[r] += 0
    id2node = {id(r): r for r in roots}
    while q:
        node = q.popleft()
        for e in node.edges:
            if e.kind == "node":
                deps[e.node] += 1
                if id(e.node) not in visited:
                    visited.add(id(e.node))
                    id2node[id(e.node)] = e.node
                    q.append(e.node)
    return deps


def _zeros_like_slot(node: GradNode, slot: int):
    return jnp.zeros(node.out_shapes[slot], node.out_dtypes[slot])


_post_backward_callbacks = []


def register_post_backward_callback(fn):
    """Run ``fn()`` after every completed ``backward()`` walk — the hook
    the DataParallel Reducer uses to fire its bucketed gradient
    all-reduce once all local grads exist (reducer.cc finalize analog).
    Returns a deregistration callable."""
    _post_backward_callbacks.append(fn)

    def _remove():
        try:
            _post_backward_callbacks.remove(fn)
        except ValueError:
            pass
    return _remove


def run_backward(tensors, grad_tensors=None, retain_graph=False):
    """loss.backward(): seed roots, traverse, write .grad on leaves
    (backward.cc:106). retain_graph=False frees saved activations as
    the walk consumes them; a second backward over the same graph then
    raises instead of silently recomputing."""
    _engine_run(tensors, grad_tensors, targets=None,
                retain_graph=bool(retain_graph))
    for cb in list(_post_backward_callbacks):
        cb()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """Partial-graph gradients (paddle.grad / general_grad.h)."""
    if create_graph:
        raise NotImplementedError(
            "create_graph=True (higher-order eager grad) is not supported; "
            "use the functional/static path (paddle_tpu.jit) for higher-order "
            "derivatives via jax.grad composition.")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    captured = _engine_run(outputs, grad_outputs, targets=list(inputs),
                           retain_graph=bool(retain_graph)
                           if retain_graph is not None else False)
    from .tensor import Tensor
    res = []
    for t in inputs:
        g = captured.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(
                "one of the differentiated tensors appears unused in the "
                "graph; pass allow_unused=True to return None for it")
        res.append(None if g is None else Tensor(g, stop_gradient=True))
    return res


def _engine_run(tensors, grad_tensors, targets, retain_graph=False):
    from .tensor import Tensor  # local import to avoid cycle

    from . import lazy
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]

    # whole-step fusion fast path: when the root is still pending in the
    # lazy window, forward + vjp compile and run as ONE XLA program and
    # grads land directly on the leaves — no flush, no graph walk
    if targets is None and not retain_graph \
            and lazy.try_fused_backward(tensors, grad_tensors):
        if _OBS.METRICS:
            from ..observability import metrics
            metrics.inc("autograd.fused_steps")
        return {}
    # generic engine walk (fallbacks from whole-step fusion land here —
    # a rising engine_runs/fused_steps ratio is the signal a training
    # loop fell off the fused hot path)
    if _OBS.METRICS:
        from ..observability import metrics
        metrics.inc("autograd.engine_runs")

    # otherwise a pending lazy capture must land before the walk: the
    # fused segment GradNodes are only wired in at flush. paddle.grad
    # with explicit targets needs gradients AT interior values, which a
    # fused segment node cannot address — land those per-op instead.
    if targets is not None:
        ctx = lazy.current_context()
        if ctx is not None:
            ctx.flush_per_op("grad_targets")
    else:
        lazy.flush_active("backward")
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    grad_tensors = [g._value if isinstance(g, Tensor) else g
                    for g in grad_tensors]

    # Target capture maps for paddle.grad mode.
    capture_by_tensor_id: Dict[int, object] = {}
    target_slots: Dict[Tuple[int, int], List[int]] = defaultdict(list)
    target_leaves: Dict[int, List[int]] = defaultdict(list)
    if targets is not None:
        for t in targets:
            m = t._autograd_meta
            if m.grad_node is not None:
                target_slots[(id(m.grad_node), m.out_slot)].append(id(t))
            else:
                target_leaves[id(t)].append(id(t))

    holders: Dict[int, Dict[int, object]] = defaultdict(dict)  # id(node)->slot->val
    id2node: Dict[int, GradNode] = {}
    roots: List[GradNode] = []

    def _leaf_accumulate(t, g):
        if targets is not None:
            if id(t) in target_leaves:
                prev = capture_by_tensor_id.get(id(t))
                capture_by_tensor_id[id(t)] = g if prev is None else _accum(prev, g)
            return
        for hook in t._autograd_meta.hooks:
            out = hook(Tensor(g, stop_gradient=True))
            if out is not None:
                g = out._value if isinstance(out, Tensor) else out
        meta = t._autograd_meta
        if meta.grad is None:
            meta.grad = Tensor(g, stop_gradient=True)
        else:
            meta.grad = Tensor(_accum(meta.grad._value, g), stop_gradient=True)

    # Seed.
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError("tensor has stop_gradient=True; nothing to do "
                               "in backward()")
        if g is None:
            if t._value.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    "pass grad_tensors for non-scalar roots")
            g = jnp.ones_like(t._value)
        meta = t._autograd_meta
        if meta.grad_node is None:
            _leaf_accumulate(t, g)
            continue
        node, slot = meta.grad_node, meta.out_slot
        h = holders[id(node)]
        h[slot] = g if slot not in h else _accum(h[slot], g)
        if id(node) not in id2node:
            id2node[id(node)] = node
            roots.append(node)

    if not roots:
        return capture_by_tensor_id

    deps = _discover(roots)
    # Root nodes seeded from user tensors may also be interior (referenced by
    # other roots); only start with nodes whose in-degree is 0.
    ready = deque(n for n in roots if deps[n] == 0)
    pending_roots = {id(n) for n in roots if deps[n] != 0}
    processed = set()

    while ready:
        node = ready.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))
        h = holders.pop(id(node), {})
        gouts = []
        for s in range(len(node.out_shapes)):
            g = h.get(s)
            gouts.append(_zeros_like_slot(node, s) if g is None else g)
        # Slot hooks (tensor.register_hook on non-leaf tensors).
        for s, hooks in node.out_hooks.items():
            for hook in hooks:
                out = hook(Tensor(gouts[s], stop_gradient=True))
                if out is not None:
                    gouts[s] = out._value if isinstance(out, Tensor) else out
        # paddle.grad capture of non-leaf targets.
        if targets is not None:
            for s in range(len(gouts)):
                key = (id(node), s)
                if key in target_slots:
                    for tid in target_slots[key]:
                        prev = capture_by_tensor_id.get(tid)
                        capture_by_tensor_id[tid] = gouts[s] if prev is None \
                            else _accum(prev, gouts[s])

        grads = node.apply(tuple(gouts))
        if not retain_graph:
            node.free()
        if len(grads) != len(node.edges):
            raise RuntimeError(
                f"op '{node.name}' backward returned {len(grads)} grads for "
                f"{len(node.edges)} inputs")

        for e, g in zip(node.edges, grads):
            if e.kind is None:
                continue
            if e.kind == "leaf":
                if g is not None:
                    _leaf_accumulate(e.leaf, g)
                continue
            # the in-degree decrement must happen even for a None grad —
            # otherwise the producer node stalls and drops contributions
            # from its other consumers (mirrors backward.cc edge handling)
            nxt = e.node
            if g is not None:
                hh = holders[id(nxt)]
                hh[e.slot] = g if e.slot not in hh else _accum(hh[e.slot], g)
            deps[nxt] -= 1
            if deps[nxt] == 0:
                ready.append(nxt)
                pending_roots.discard(id(nxt))
        # A seeded root that was also interior becomes ready once all its
        # downstream consumers ran.
        for rid in list(pending_roots):
            n = id2node[rid]
            if deps[n] == 0:
                pending_roots.discard(rid)
                ready.append(n)

    return capture_by_tensor_id
