"""Global RNG state.

TPU-native analog of the reference's generator (paddle/phi/core/generator.h)
built on threefry key splitting. A single global key is split per random op;
`paddle_tpu.seed(n)` reseeds. Mesh-axis-consistent RNG for TP dropout (the
reference's RNGStatesTracker, fleet/layers/mpu/random.py:34) lives in
paddle_tpu.distributed and folds axis indices into these keys.
"""
from __future__ import annotations

import threading

import jax

from . import flags

_LOCK = threading.Lock()
_state = {"key": None, "seed": None}


def seed(s: int):
    with _LOCK:
        _state["seed"] = int(s)
        _state["key"] = jax.random.PRNGKey(int(s))
    return s


def get_seed():
    return _state["seed"]


def next_key():
    """Split the global key; returns a fresh subkey for one random op."""
    with _LOCK:
        if _state["key"] is None:
            _state["seed"] = flags.flag_value("FLAGS_seed")
            _state["key"] = jax.random.PRNGKey(_state["seed"])
        _state["key"], sub = jax.random.split(_state["key"])
        return sub


def fold_in(data: int):
    """Derive a deterministic key from the current seed and `data` without
    advancing global state (used for per-rank / per-axis derivation)."""
    base = _state["seed"] if _state["seed"] is not None else \
        flags.flag_value("FLAGS_seed")
    return jax.random.fold_in(jax.random.PRNGKey(base), data)
