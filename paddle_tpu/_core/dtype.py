"""Data types for paddle_tpu.

TPU-native analog of the reference's dtype enum (paddle/phi/common/data_type.h).
Dtypes are thin named wrappers over numpy/jax dtypes so user code can write
``paddle_tpu.float32`` the way Paddle users write ``paddle.float32``.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp


class DType:
    """A framework dtype. Compares equal to its string name and numpy dtype."""

    __slots__ = ("name", "np_dtype")

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype) if np_dtype is not None else None

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            return self.name == other or str(self.np_dtype) == other
        try:
            return self.np_dtype == np.dtype(other)
        except TypeError:
            return NotImplemented

    def __hash__(self):
        return hash(self.name)


bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", jnp.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)

_ALL = [bool_, uint8, int8, int16, int32, int64, float16, bfloat16,
        float32, float64, complex64, complex128]
_BY_NAME = {d.name: d for d in _ALL}
_BY_NAME["bool_"] = bool_

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGER = {uint8, int8, int16, int32, int64}
_COMPLEX = {complex64, complex128}


def to_dtype(d) -> DType:
    """Coerce str / numpy dtype / DType / jnp dtype to a framework DType."""
    if d is None:
        return None
    if isinstance(d, DType):
        return d
    if isinstance(d, str):
        if d in _BY_NAME:
            return _BY_NAME[d]
        return from_np(np.dtype(d))
    return from_np(d)


def from_np(np_dtype) -> DType:
    name = np.dtype(np_dtype).name
    if name in _BY_NAME:
        return _BY_NAME[name]
    raise TypeError(f"unsupported dtype: {np_dtype!r}")


def to_np(d):
    return to_dtype(d).np_dtype


def is_floating_point(d) -> bool:
    return to_dtype(d) in _FLOATING


def is_integer(d) -> bool:
    return to_dtype(d) in _INTEGER


def is_complex(d) -> bool:
    return to_dtype(d) in _COMPLEX
