"""The eager Tensor.

TPU-native analog of paddle::Tensor (paddle/phi/api/include/tensor.h:82) +
its pybind eager methods (paddle/fluid/pybind/eager_method.cc). The payload
is a jax.Array living on the TPU via PJRT — device memory management,
streams, and async execution are PJRT's job (the analog of the reference's
allocator + DeviceContext stack, SURVEY.md §2a). Autograd state hangs off
`_autograd_meta` (autograd_meta.h:61).

Most operator methods are monkey-patched onto this class by paddle_tpu.ops
(mirroring python/paddle's monkey_patch of Tensor methods).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes_mod
from ..observability import _state as _OBS
from .autograd import AutogradMeta, is_grad_enabled, no_grad, run_backward


class Tensor:
    __slots__ = ("_payload", "_stop_gradient", "_autograd_meta",
                 "_inplace_version", "name", "persistable", "_dist_attr",
                 "__weakref__")

    def __init__(self, value, stop_gradient: bool = True, name: str = None):
        if isinstance(value, Tensor):
            value = value._payload
        if getattr(value, "_is_lazy_ref", False):
            # alias a pending lazy value (keeps the fusion window open:
            # wrapping/detaching a lazy tensor must not force a flush)
            value.add_tref(self)
        elif getattr(value, "_is_pending_value", False):
            # alias an in-flight async-flush output: resolution happens
            # lazily at the first _value read, like any other alias
            pass
        elif not isinstance(value, (jax.Array, jax.core.Tracer)):
            value = jnp.asarray(value)
        self._payload = value
        self._stop_gradient = bool(stop_gradient)
        self._autograd_meta = AutogradMeta()
        self._inplace_version = 0
        self.name = name
        self.persistable = False
        self._dist_attr = None  # set by paddle_tpu.distributed for DistTensor
        if _OBS.MEM:
            # live-buffer census (FLAGS_memory_telemetry): Tensor
            # creation is THE eager choke point for concrete payloads;
            # the birth site comes from the dispatcher's thread-local
            # hint (eager:<op>) or defaults to tensor.create. Weakref
            # only — the census never extends a buffer's lifetime.
            from ..observability import memory as _memtel
            _memtel.note_buffer(self._payload)

    # ----------------------------------------------------------- raw value
    @property
    def _value(self):
        """The raw jax payload. Reading it while a lazy capture is pending
        MATERIALIZES the pending segment (one compiled XLA execution) —
        the flush point of the fusion window / SOT graph break."""
        v = self._payload
        if getattr(v, "_is_lazy_ref", False):
            v.materialize()
            v = self._payload
            if getattr(v, "_is_lazy_ref", False):
                raise RuntimeError("lazy value failed to materialize")
        if getattr(v, "_is_pending_value", False):
            # in-flight async-flush output: THE sync point — block on
            # the worker, re-raise its (typed) failure, cache the
            # concrete array so later reads are free
            v = v.resolve()
            self._payload = v
        return v

    @_value.setter
    def _value(self, new):
        self._payload = new

    # ------------------------------------------------------------- metadata
    @property
    def shape(self):
        return list(self._meta_aval().shape)

    def _meta_aval(self):
        """shape/dtype metadata WITHOUT materializing a lazy payload."""
        v = self._payload
        return v.aval if getattr(v, "_is_lazy_ref", False) else v

    @property
    def ndim(self):
        return len(self._meta_aval().shape)

    @property
    def rank(self):
        return self.ndim

    @property
    def size(self):
        shp = self._meta_aval().shape
        return int(np.prod(shp)) if shp else 1

    @property
    def dtype(self):
        return dtypes_mod.from_np(np.dtype(self._meta_aval().dtype))

    @property
    def place(self):
        from . import device
        return device.place_of(self._value)

    @property
    def is_leaf(self):
        return self._autograd_meta.grad_node is None

    @property
    def stop_gradient(self):
        return self._stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v: bool):
        self._stop_gradient = bool(v)

    @property
    def grad(self) -> Optional["Tensor"]:
        return self._autograd_meta.grad

    @grad.setter
    def grad(self, g):
        if g is not None and not isinstance(g, Tensor):
            g = Tensor(g, stop_gradient=True)
        self._autograd_meta.grad = g

    # ------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph=False):
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._autograd_meta.grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        """Register a gradient hook (fires with this tensor's grad during
        backward). Returns a removable handle."""
        meta = self._autograd_meta
        if meta.grad_node is not None:
            hooks = meta.grad_node.out_hooks.setdefault(meta.out_slot, [])
        else:
            hooks = meta.hooks
        hooks.append(hook)

        class _Handle:
            def remove(self_h):
                try:
                    hooks.remove(hook)
                except ValueError:
                    pass
        return _Handle()

    def detach(self) -> "Tensor":
        t = Tensor(self._payload, stop_gradient=True)
        t.name = self.name
        return t

    def detach_(self) -> "Tensor":
        self._stop_gradient = True
        self._autograd_meta.grad_node = None
        return self

    # ------------------------------------------------------------- transfer
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self):
        return self._value.item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._value

    def _replace_value_inplace(self, new_value):
        """In-place mutation: bump version (tensor_wrapper.h safety model).
        Open capture contexts are notified so ops recorded AFTER the swap
        see the fresh payload (and the orphaned snapshot can be donated)."""
        from . import lazy
        lazy.note_inplace(self)
        self._value = new_value
        self._inplace_version += 1
        if _OBS.MEM:
            # the swapped-in payload is a fresh buffer born HERE (the
            # optimizer write-back path) — without this the census
            # would lose every parameter after its first update
            from ..observability import memory as _memtel
            _memtel.note_buffer(self._payload)
        return self

    def set_value(self, value):
        from . import lazy
        aval = self._meta_aval()
        if isinstance(value, Tensor):
            vp = value._payload
            if getattr(vp, "_is_lazy_ref", False) and \
                    lazy.current_context() is not None:
                # stay in the fusion window: alias the pending value
                # (casting through the op layer if dtypes differ)
                # instead of materializing both sides — the in-place
                # `param.copy_(new)` train-step pattern stays one fused,
                # donation-eligible segment
                if tuple(value._meta_aval().shape) != tuple(aval.shape):
                    raise ValueError(
                        f"set_value shape mismatch: "
                        f"{tuple(value._meta_aval().shape)} vs "
                        f"{tuple(aval.shape)}")
                src = value
                if np.dtype(value._meta_aval().dtype) != np.dtype(aval.dtype):
                    from ..ops import cast
                    src = cast(value, dtypes_mod.from_np(np.dtype(aval.dtype)))
                newp = src._payload
                if getattr(newp, "_is_lazy_ref", False):
                    lazy.note_inplace(self)
                    self._payload = newp
                    newp.add_tref(self)
                    self._inplace_version += 1
                    return self
                value = newp   # cast materialized: fall through
            else:
                value = value._value
        value = jnp.asarray(value, dtype=np.dtype(aval.dtype))
        if tuple(value.shape) != tuple(aval.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs "
                f"{tuple(aval.shape)}")
        return self._replace_value_inplace(value)

    def copy_(self, other):
        return self.set_value(other)

    def get_tensor(self):
        return self

    def clone(self) -> "Tensor":
        from ..ops import assign
        return assign(self)

    def to(self, *args, **kwargs):
        # .to(dtype) / .to(device) minimal support
        from ..ops import cast
        for a in list(args) + list(kwargs.values()):
            try:
                d = dtypes_mod.to_dtype(a)
                if d is not None:
                    return cast(self, d)
            except TypeError:
                continue
        return self

    def block_until_ready(self):
        jax.block_until_ready(self._value)
        return self

    # ------------------------------------------------------------- misc
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._meta_aval().shape[0]

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return format(str(self), spec)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        grad_info = "" if self._stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_info},\n       {np.asarray(self._value)!r})")

    def __hash__(self):
        return id(self)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor analog (device placement handled by JAX default)."""
    if isinstance(data, Tensor):
        val = data._value
        if dtype is not None:
            val = val.astype(dtypes_mod.to_np(dtype))
        return Tensor(val, stop_gradient=stop_gradient)
    np_dtype = dtypes_mod.to_np(dtype) if dtype is not None else None
    if isinstance(data, (list, tuple)) and any(
            isinstance(x, Tensor) for x in jax.tree_util.tree_leaves(data)):
        data = [x.numpy() if isinstance(x, Tensor) else x for x in data]
    val = jnp.asarray(data, dtype=np_dtype)
    if np_dtype is None and val.dtype == jnp.float64:
        val = val.astype(jnp.float32)  # paddle default is fp32
    if np_dtype is None and val.dtype == jnp.int64 and not isinstance(
            data, np.ndarray):
        # python ints default to int64 in both frameworks; keep as is
        pass
    return Tensor(val, stop_gradient=stop_gradient)


def _wrap(value, stop_gradient=True) -> Tensor:
    return Tensor(value, stop_gradient=stop_gradient)


def unwrap(x):
    return x._value if isinstance(x, Tensor) else x
