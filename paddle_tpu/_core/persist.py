"""Persistent compiled-executable cache (FLAGS_executable_cache_dir).

Process restart, elastic re-plan and serving cold-start used to pay
``lower().compile()`` for every sealed segment, fused step and
optimizer update — the goodput ledger's compile bucket prices exactly
this badput (bench row 8's ~740ms re-plan was mostly recompile). This
module serializes compiled executables through jax's AOT surface
(SNIPPETS [1] pjit Lowered/compile split -> serialize_executable) under
a content-addressed filename, so an ``ExecCache`` miss consults disk
before compiling.

Key scheme: sha256 over ``repr((VERSION, jax version, backend, kind,
normalized key))`` where the caller passes its cache key with the
session-local ``MESH_EPOCH`` component replaced by 0 — the epoch salt
exists to invalidate *in-memory* entries across re-plans, but every
structural consequence of a re-plan (mesh layout, shard specs, world
size) is already inside the signature (``shard_sig`` / spmd specs), so
two processes or two re-plan cycles with the same structure share one
disk entry. Every key component is an interned primitive (strings,
ints, tuples), making ``repr`` stable across processes.

File layout (``<kind>-<digest>.ptxc``): MAGIC + hex sha256 of the
payload + newline + pickled payload dict. Writes are atomic
(temp + fsync + os.replace — the checkpoint.py torn-save pattern);
loads verify magic, checksum, version, jax version, backend and the
full key repr BEFORE trusting the pickle, so a truncated, corrupted or
wrong-version file falls back to a clean recompile with a
``cache.persist.reject`` counter and a flight-recorder note — never a
crash. The PR-9/PR-12 memory/cost analyses and the compiled-comm
estimate ride the payload so warm loads keep their meters.
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import tempfile
from typing import Any, Dict, Optional

from . import flags as _flags
from ..observability import _state as _OBS

_LOG = logging.getLogger(__name__)

VERSION = 1
MAGIC = b"PTXC1\n"
_SUFFIX = ".ptxc"

# Watcher-cached gate (the STATIC_CHECKS_ACTIVE pattern): ACTIVE is True
# iff FLAGS_executable_cache_dir names a directory. Hot paths pay one
# module-attribute read while the cache is off.
ACTIVE = False
_DIR = ""


def _sync_dir_gate(value):
    global ACTIVE, _DIR
    _DIR = str(value or "").strip()
    ACTIVE = bool(_DIR)


_flags.watch_flag("FLAGS_executable_cache_dir", _sync_dir_gate)


def _count(stat: str, reason: str = None):
    if _OBS.METRICS:
        from ..observability import metrics
        metrics.inc("cache.persist." + stat)
    if reason is not None:
        _LOG.warning("persistent executable cache: %s", reason)
        if _OBS.FLIGHT:
            from ..observability import flight
            flight.note("cache.persist", stat, reason=reason)


def _env() -> tuple:
    import jax
    return jax.__version__, jax.default_backend()


def digest(kind: str, norm_key) -> str:
    """Content digest of a normalized cache key. The caller has already
    zeroed the MESH_EPOCH component; everything else (op stream, input
    signature, donation, shard structure) is part of the identity."""
    jver, backend = _env()
    text = repr((VERSION, jver, backend, kind, norm_key))
    return hashlib.sha256(text.encode()).hexdigest()


def path_for(kind: str, norm_key) -> str:
    return os.path.join(_DIR, kind + "-" + digest(kind, norm_key) + _SUFFIX)


# ------------------------------------------------------------------ store

def store(kind: str, norm_key, compiled, extra: Optional[Dict] = None):
    """Serialize one compiled executable (plus its telemetry sidecars)
    under its digest. Failures are logged and swallowed — persistence
    must never take down the step that compiled."""
    if not ACTIVE:
        return False
    try:
        from jax.experimental.serialize_executable import serialize
        blob, in_tree, out_tree = serialize(compiled)
        jver, backend = _env()
        payload = {
            "version": VERSION,
            "jax": jver,
            "backend": backend,
            "kind": kind,
            "key": repr(norm_key),
            "blob": blob,
            "in_tree": in_tree,
            "out_tree": out_tree,
        }
        if extra:
            payload.update(extra)
        raw = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        body = MAGIC + hashlib.sha256(raw).hexdigest().encode() + b"\n" + raw
        os.makedirs(_DIR, exist_ok=True)
        path = path_for(kind, norm_key)
        fd, tmp = tempfile.mkstemp(
            dir=_DIR, prefix=".tmp_" + os.path.basename(path))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _count("store")
        _prune_disk()
        return True
    except Exception as e:                      # pragma: no cover - env
        _LOG.warning("persistent executable cache: store failed for "
                     "%s: %s", kind, e)
        return False


def _prune_disk():
    """Oldest-mtime eviction down to FLAGS_executable_cache_disk_max_mb
    after each store (0 = unbounded)."""
    budget = _flags.flag_value("FLAGS_executable_cache_disk_max_mb")
    if not budget:
        return
    budget_bytes = int(budget) << 20
    try:
        entries = []
        for name in os.listdir(_DIR):
            if not name.endswith(_SUFFIX):
                continue
            p = os.path.join(_DIR, name)
            st = os.stat(p)
            entries.append((st.st_mtime, st.st_size, p))
        total = sum(e[1] for e in entries)
        entries.sort()
        while total > budget_bytes and entries:
            mtime, size, p = entries.pop(0)
            os.unlink(p)
            total -= size
    except OSError:
        pass


# ------------------------------------------------------------------- load

def load(kind: str, norm_key) -> Optional[Dict]:
    """Return the verified payload dict for a key, or None (miss or
    reject). Every integrity failure — short file, bad magic, torn
    write, checksum mismatch, version/backend/key drift — is a clean
    recompile with a logged reason, never a crash."""
    if not ACTIVE:
        return None
    path = path_for(kind, norm_key)
    try:
        with open(path, "rb") as f:
            body = f.read()
    except OSError:
        _count("miss")
        return None
    try:
        if not body.startswith(MAGIC):
            raise ValueError("bad magic (not a cache entry)")
        rest = body[len(MAGIC):]
        nl = rest.find(b"\n")
        if nl != 64:
            raise ValueError("malformed checksum header")
        expect = rest[:64].decode("ascii")
        raw = rest[65:]
        got = hashlib.sha256(raw).hexdigest()
        if got != expect:
            raise ValueError(
                f"checksum mismatch (recorded {expect[:12]}.., "
                f"computed {got[:12]}..) — torn or corrupted entry")
        payload = pickle.loads(raw)
        jver, backend = _env()
        if payload.get("version") != VERSION:
            raise ValueError(
                f"format version {payload.get('version')} != {VERSION}")
        if payload.get("jax") != jver:
            raise ValueError(
                f"jax version {payload.get('jax')} != {jver}")
        if payload.get("backend") != backend:
            raise ValueError(
                f"backend {payload.get('backend')} != {backend}")
        if payload.get("key") != repr(norm_key):
            raise ValueError("key repr mismatch (digest collision or "
                             "stale entry)")
    except Exception as e:
        _count("reject", reason=f"{os.path.basename(path)}: {e}; "
                                "recompiling")
        return None
    _count("hit")
    return payload


def make_runner(payload: Dict, jit_factory, kwargs: Optional[Dict] = None):
    """Rehydrate a loaded payload into the aot_compile runner shape:
    the deserialized executable serves concrete-array calls; tracer
    arguments fall back to a jit wrapper built ON DEMAND by
    `jit_factory` (a Compiled object cannot inline into an enclosing
    trace, but building the wrapper eagerly would bump the compile
    counters a warm load exists to avoid). Returns None when
    deserialization itself fails (payload from a device topology this
    process cannot load), which the caller treats as a miss."""
    import jax
    try:
        from jax.experimental.serialize_executable import \
            deserialize_and_load
        compiled = deserialize_and_load(
            payload["blob"], payload["in_tree"], payload["out_tree"])
    except Exception as e:
        _count("reject", reason=f"deserialize failed ({e}); recompiling")
        return None

    jit_cell = []

    def runner(*vals, _compiled=compiled, _kw=dict(kwargs or {}),
               _tracer=jax.core.Tracer):
        for v in vals:
            if isinstance(v, _tracer):
                if not jit_cell:
                    jit_cell.append(jit_factory())
                return jit_cell[0](*vals, **_kw)
        return _compiled(*vals)

    runner.memory_analysis_info = payload.get("mem")
    runner.cost_analysis_info = payload.get("cost")
    runner.persisted = True
    return runner


def sidecars(compiled_or_runner, cache=None, key=None) -> Dict:
    """Collect the telemetry sidecars to persist alongside a compiled
    executable: the aot_compile runner's captured analyses plus the
    cache entry's compiled-comm estimate."""
    extra = {}
    mem = getattr(compiled_or_runner, "memory_analysis_info", None)
    if mem:
        extra["mem"] = mem
    cost = getattr(compiled_or_runner, "cost_analysis_info", None)
    if cost:
        extra["cost"] = cost
    if cache is not None and key is not None:
        comm = cache.comm_info(key) if hasattr(cache, "comm_info") else None
        if comm:
            extra["comm"] = comm
    return extra


def renote(payload: Dict, stat: str, cache=None, key=None):
    """Re-attach persisted analyses to the in-memory cache entry and
    the telemetry logs so a warm load keeps its meters (budget/stats
    aggregate over note_executable; ExecCache entries price comm and
    FLOPs per execution)."""
    mem = payload.get("mem")
    cost = payload.get("cost")
    comm = payload.get("comm")
    if cache is not None and key is not None:
        if mem and hasattr(cache, "note_memory"):
            cache.note_memory(key, mem)
        if cost and hasattr(cache, "note_cost"):
            cache.note_cost(key, cost)
        if comm and hasattr(cache, "note_comm"):
            cache.note_comm(key, comm)
    if mem and _OBS.MEM:
        from ..observability import memory as _memtel
        _memtel.note_executable(stat, key, dict(mem, persisted=True))
    if cost and _OBS.COMPUTE:
        from ..observability import compute as _comptel
        _comptel.note_executable(stat, key, dict(cost, persisted=True))
