"""Eager op dispatch with persistent compile cache.

TPU-native analog of the reference's kernel dispatch
(paddle/phi/api/lib/kernel_dispatch.h, KernelFactory::SelectKernelOrThrowError
paddle/phi/core/kernel_factory.h:326). Where the reference selects a
precompiled CUDA kernel by (name, backend, layout, dtype), we select a cached
XLA executable by (op, attrs); jax.jit then further specializes per
shape/dtype. First call of a signature compiles; later calls hit the cache —
the idiomatic TPU replacement for per-op CUDA kernels (SURVEY.md §7.2).

Backward uses jax.vjp over the forward body (recompute-style: saved inputs
are the residuals, the analog of TensorWrapper capture in
paddle/fluid/eager/tensor_wrapper.h) unless the op registered a custom bwd.
"""
from __future__ import annotations

import functools
import hashlib
import weakref
from typing import Any, Dict, Tuple

import jax
import numpy as np

from . import flags
from ..observability import _state as _obs
from .cache import ExecCache
from .op_registry import OpDef

_FWD_CACHE: Dict[Tuple, Any] = ExecCache(
    extra_flag="FLAGS_eager_compile_cache_size", stat="eager_fwd")
_BWD_CACHE: Dict[Tuple, Any] = ExecCache(
    extra_flag="FLAGS_eager_compile_cache_size", stat="eager_bwd")

# ndarray attrs (e.g. index tables, window vectors) are hashed by content;
# digesting v.tobytes() on EVERY dispatch is O(size) per op. Arrays used
# as attrs are config-like and treated as immutable between calls, so
# large-array digests are memoized per array identity — validated by
# weakref (a recycled id can't alias a dead array's digest) plus an O(1)
# sampled fingerprint, so a realloc, shape/dtype change, or in-place
# mutation touching a sampled position recomputes instead of reusing a
# stale cached executable. Small arrays are digested in full every call
# (it's ~free), so they can never go stale at all; mutations of a LARGE
# attr array at only-unsampled positions are outside the contract.
_ARR_DIGEST: Dict[int, Tuple] = {}
_ARR_MEMO_MIN_BYTES = 2048


def _full_digest(v: np.ndarray):
    return (v.shape, str(v.dtype), hashlib.sha1(v.tobytes()).hexdigest())


def _fingerprint(v: np.ndarray):
    idx = np.linspace(0, v.size - 1, num=min(v.size, 16)).astype(np.int64)
    return (v.shape, str(v.dtype), v.flat[idx].tobytes())


def _digest_array(v: np.ndarray):
    if v.nbytes <= _ARR_MEMO_MIN_BYTES:
        return _full_digest(v)
    ent = _ARR_DIGEST.get(id(v))
    if ent is not None and ent[0]() is v and ent[1] == _fingerprint(v):
        return ent[2]
    key = _full_digest(v)
    try:
        wr = weakref.ref(v)
    except TypeError:  # un-weakref-able subclass: skip memoization
        return key
    if len(_ARR_DIGEST) >= 4096:
        for k in [k for k, e in _ARR_DIGEST.items() if e[0]() is None]:
            del _ARR_DIGEST[k]
        # still over cap (all entries live): evict oldest down to half so
        # the purge scan amortizes instead of running on every insert
        while len(_ARR_DIGEST) >= 2048:
            del _ARR_DIGEST[next(iter(_ARR_DIGEST))]
    _ARR_DIGEST[id(v)] = (wr, _fingerprint(v), key)
    return key


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, np.ndarray):
        return _digest_array(v)
    return v


# Interning pool: steady-state dispatch sees the same few hundred attr
# signatures over and over; returning the SAME tuple object makes the
# downstream cache keys and segment signatures compare by identity
# fast-path and hash once (the KernelKey-interning role of
# kernel_factory.h:58).
_KEY_INTERN: Dict[Tuple, Tuple] = {}


def attrs_key(attrs: Dict[str, Any]):
    ec = _eager_core()
    if ec is not None:
        # one C pass: sort + intern (None = exotic values, python path).
        # A given attrs value-class always takes the same branch, so
        # the two intern pools never alias the same key.
        key = ec.sorted_attrs(attrs)
        if key is not None:
            return key
    key = tuple(sorted((k, _hashable(v)) for k, v in attrs.items()))
    if len(_KEY_INTERN) > 8192:
        _KEY_INTERN.clear()
    return _KEY_INTERN.setdefault(key, key)


# Framework-issued XLA executable launches (segment runners, fused
# fwd+bwd steps, eager per-op calls, grad accumulations, fused optimizer
# updates). The eager hot-path contract — one fused fwd+bwd program plus
# one donated optimizer program per steady-state train step — is
# asserted against this counter by tests/test_eager_hotpath.py.
_EXEC_COUNT = 0


def bump_exec(n: int = 1):
    global _EXEC_COUNT
    _EXEC_COUNT += n


def exec_count() -> int:
    return _EXEC_COUNT


def _full_key(name: str, backend: str, attrs: Dict[str, Any]):
    """(name, backend, canonical attrs): the KernelKey of the executable
    cache. The C extension builds it in one pass for primitive attrs
    (kernel_factory.h:58 role); exotic values take the python path."""
    ec = _eager_core()
    if ec is not None:
        key = ec.attrs_key(name, backend, attrs)
        if key is not None:
            return key
    return (name, backend, attrs_key(attrs))


_EAGER_CORE = False   # tri-state: False = not looked up yet


def _eager_core():
    global _EAGER_CORE
    if _EAGER_CORE is False:
        from . import native
        _EAGER_CORE = native.get_eager_core()
        if _EAGER_CORE is not None \
                and not hasattr(_EAGER_CORE, "sorted_attrs"):
            # a stale pre-record-core build (the extension build is
            # best-effort): the python paths stand alone instead of
            # AttributeError-ing per dispatch
            _EAGER_CORE = None
    return _EAGER_CORE


def fwd_callable(op: OpDef, attrs: Dict[str, Any]):
    backend = jax.default_backend()  # kernel-key Backend component
    key = _full_key(op.name, backend, attrs)
    fn = _FWD_CACHE.get(key)
    if fn is None:
        fn = jax.jit(functools.partial(op.kernel_for(backend), **attrs))
        _FWD_CACHE[key] = fn   # ExecCache evicts LRU past either cap flag
        if _obs.METRICS:
            from ..observability import metrics
            metrics.inc("compiles.eager_fwd")
    return fn


def eager_forward(op: OpDef, vals: Tuple, attrs: Dict[str, Any]) -> Tuple:
    """Run the op's forward. Returns a tuple of raw outputs."""
    bump_exec()
    out = fwd_callable(op, attrs)(*vals)
    if flags.flag_value("FLAGS_benchmark"):
        jax.block_until_ready(out)
    outs = out if op.multi_output else (out,)
    if flags.flag_value("FLAGS_check_nan_inf"):
        _check_nan_inf(op.name, outs, site=True)
    return tuple(outs)


def bwd_callable(op: OpDef, attrs: Dict[str, Any]):
    backend = jax.default_backend()
    key = _full_key(op.name, backend, attrs)
    fn = _BWD_CACHE.get(key)
    if fn is not None:
        return fn
    if op.bwd is not None:
        fn = jax.jit(functools.partial(op.bwd, **attrs))
    else:
        # differentiate the SAME body the forward ran (variant-aware) so
        # fwd/bwd numerics always pair up
        fwd = functools.partial(op.kernel_for(backend), **attrs)

        def _vjp(saved, gouts, _fwd=fwd, _multi=op.multi_output):
            _, pull = jax.vjp(_fwd, *saved)
            return pull(tuple(gouts) if _multi else gouts[0])

        fn = jax.jit(_vjp)
    _BWD_CACHE[key] = fn
    if _obs.METRICS:
        from ..observability import metrics
        metrics.inc("compiles.eager_bwd")
    return fn


def eager_backward(op: OpDef, saved: Tuple, attrs: Dict[str, Any],
                   gouts: Tuple) -> Tuple:
    """Compute input gradients. float0 / integer cotangents become None."""
    bump_exec()
    grads = bwd_callable(op, attrs)(tuple(saved), tuple(gouts))
    out = []
    for g in grads:
        if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
            out.append(None)
        else:
            out.append(g)
    return tuple(out)


def _check_nan_inf(name: str, outs, site: bool = False):
    # Analog of FLAGS_check_nan_inf (paddle/fluid/eager/nan_inf_utils.h:38).
    import jax.numpy as jnp
    for i, o in enumerate(outs):
        if hasattr(o, "dtype") and jnp.issubdtype(o.dtype, jnp.inexact):
            bad = bool(jnp.any(~jnp.isfinite(o)))
            if bad:
                msg = f"NaN/Inf detected in output {i} of op '{name}'"
                if site:
                    # per-op eager scan: the dispatching user frame is
                    # still on the stack — name the producing file:line
                    # (trip path only; the clean scan pays nothing)
                    from ..analysis.hooks import call_site
                    src = call_site()
                    if src:
                        msg += f" @ {src}"
                if _obs.GOODPUT:
                    # job-health anomaly regardless of the scan's
                    # raise/warn level: the goodput plane's NaN watch
                    # rides the existing scan instead of re-scanning
                    from ..observability import goodput
                    goodput.note_nan(name)
                if flags.flag_value("FLAGS_check_nan_inf_level") >= 1:
                    import warnings
                    warnings.warn(msg)
                else:
                    raise FloatingPointError(msg)


def clear_compile_cache():
    _FWD_CACHE.clear()
    _BWD_CACHE.clear()


def compile_cache_size():
    return len(_FWD_CACHE) + len(_BWD_CACHE)
