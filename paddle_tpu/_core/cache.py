"""Bounded LRU for compiled-executable caches.

Every cache of jitted runners (eager per-op `_FWD_CACHE`/`_BWD_CACHE`,
lazy `_SEG_CACHE`/`_SEG_BWD_CACHE`/`_FUSED_CACHE`) used to be an
unbounded dict — a leak under shape-polymorphic workloads where every
new shape mints a new signature. `ExecCache` is a drop-in dict
replacement with LRU eviction; the capacity is read live from a flag at
insertion time so `set_flags` takes effect mid-session (the analog of
the reference's FLAGS_* cache-size knobs, kernel_factory.h cache role).

A cache constructed with `stat="segment"` additionally reports hit/miss
counts into the observability registry (`cache.segment.{hit,miss}`)
when metrics collection is on — one module-level check per lookup when
it is off.
"""
from __future__ import annotations

from collections import OrderedDict

from ..observability import _state as _obs


class ExecCache(OrderedDict):
    """dict-compatible LRU. Capacity comes from ``flag`` (0 = unlimited);
    an optional second flag acts as an additional bound (the legacy
    FLAGS_eager_compile_cache_size spelling for the eager caches)."""

    def __init__(self, flag: str = "FLAGS_executable_cache_capacity",
                 extra_flag: str = None, stat: str = None):
        super().__init__()
        self._flag = flag
        self._extra_flag = extra_flag
        # per-entry XLA memory analysis (observability/memory.py fills
        # this at compile time while FLAGS_memory_telemetry is on), so
        # a step-cache hit can report its compiled footprint without
        # re-lowering anything; pruned with the entry it describes
        self._mem: dict = {}
        # per-entry compiled-collective byte estimate (lazy.py fills
        # this once per sharded compile from the in/out specs); a
        # steady-state hit re-counts the cached number per execution
        self._comm: dict = {}
        # per-entry XLA cost analysis (observability/compute.py fills
        # this at compile time while FLAGS_compute_telemetry is on);
        # every execution of the entry prices its cached FLOPs
        self._cost: dict = {}
        # direct Counter handles: metrics.reset() zeroes them in place,
        # so holding the objects (no per-lookup name resolution) is safe
        if stat is not None:
            from ..observability import metrics
            self._hit = metrics.counter(f"cache.{stat}.hit")
            self._miss = metrics.counter(f"cache.{stat}.miss")
        else:
            self._hit = self._miss = None

    def _capacity(self) -> int:
        from . import flags
        cap = flags.flag_value(self._flag)
        if self._extra_flag is not None:
            extra = flags.flag_value(self._extra_flag)
            if extra and (not cap or extra < cap):
                cap = extra
        return cap

    def get(self, key, default=None):
        try:
            val = OrderedDict.__getitem__(self, key)
        except KeyError:
            if _obs.METRICS and self._miss is not None:
                self._miss.inc()
            return default
        try:
            # the other thread's eviction loop may delete this key
            # between the successful read above and the LRU touch —
            # the value is already in hand, so a lost touch is benign
            self.move_to_end(key)
        except KeyError:
            pass
        if _obs.METRICS and self._hit is not None:
            self._hit.inc()
        return val

    def __getitem__(self, key):
        val = OrderedDict.__getitem__(self, key)
        try:
            self.move_to_end(key)
        except KeyError:
            pass
        return val

    def __setitem__(self, key, val):
        OrderedDict.__setitem__(self, key, val)
        self.move_to_end(key)
        cap = self._capacity()
        while cap and len(self) > cap:
            # NOT popitem(): OrderedDict.popitem re-enters the overridden
            # __getitem__ after unlinking the entry -> KeyError.
            # The async flush worker and the recording thread can both
            # insert: each C-level dict op is GIL-atomic, but the oldest
            # key read here may be evicted by the other thread between
            # the two calls — losing that race is benign, so tolerate it
            try:
                oldest = next(iter(self))
                OrderedDict.__delitem__(self, oldest)
                self._mem.pop(oldest, None)
                self._comm.pop(oldest, None)
                self._cost.pop(oldest, None)
            except (KeyError, StopIteration, RuntimeError):
                break

    def note_memory(self, key, info: dict):
        """Attach a compiled executable's memory analysis to its cache
        entry (observability/memory.py, FLAGS_memory_telemetry)."""
        self._mem[key] = info

    def memory_info(self, key, default=None):
        return self._mem.get(key, default)

    def note_comm(self, key, nbytes: int):
        """Attach the compiled-collective byte estimate to its cache
        entry (lazy._note_compiled_comm, ambient SPMD mesh)."""
        self._comm[key] = int(nbytes)

    def comm_info(self, key, default=None):
        return self._comm.get(key, default)

    def note_cost(self, key, info: dict):
        """Attach a compiled executable's cost analysis to its cache
        entry (observability/compute.py, FLAGS_compute_telemetry)."""
        self._cost[key] = info

    def cost_info(self, key, default=None):
        return self._cost.get(key, default)

    def clear(self):
        OrderedDict.clear(self)
        self._mem.clear()
        self._comm.clear()
        self._cost.clear()
