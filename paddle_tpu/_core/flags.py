"""Global runtime flag registry.

TPU-native analog of the reference's exported-flag registry
(paddle/common/flags.h:242-291 `PHI_DEFINE_EXPORTED_*`, ~187 flags in
flags.cc) with env-var override and get/set from Python
(python/paddle/base/framework.py:132,157 set_flags/get_flags).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterable, Union

_LOCK = threading.RLock()
_REGISTRY: Dict[str, "Flag"] = {}


class Flag:
    __slots__ = ("name", "default", "value", "type", "help")

    def __init__(self, name: str, default: Any, help: str = ""):
        self.name = name
        self.default = default
        self.type = type(default)
        self.help = help
        env = os.environ.get(name)
        self.value = _parse(env, self.type) if env is not None else default


def _parse(text: str, ty: type):
    if ty is bool:
        return text.lower() in ("1", "true", "yes", "on")
    return ty(text)


def define_flag(name: str, default: Any, help: str = "") -> Flag:
    with _LOCK:
        if name in _REGISTRY:
            return _REGISTRY[name]
        flag = Flag(name, default, help)
        _REGISTRY[name] = flag
        return flag


def get_flags(flags: Union[str, Iterable[str]]) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    with _LOCK:
        out = {}
        for name in flags:
            key = _resolve(name)
            if key not in _REGISTRY:
                raise ValueError(f"unknown flag: {name}")
            out[name] = _REGISTRY[key].value
        return out


def set_flags(flags: Dict[str, Any]) -> None:
    with _LOCK:
        # resolve + parse EVERYTHING before mutating anything: a typo'd
        # name or unparseable value mid-dict must not leave earlier
        # flags written to the registry with their watcher-cached gates
        # (STATIC_CHECKS_ACTIVE, observability _state) never updated
        updates = []
        for name, value in flags.items():
            key = _resolve(name)
            if key not in _REGISTRY:
                raise ValueError(f"unknown flag: {name}")
            flag = _REGISTRY[key]
            parsed = _parse(value, flag.type) \
                if isinstance(value, str) and flag.type is not str \
                else flag.type(value)
            updates.append((key, flag, parsed))
        fire = []
        for key, flag, parsed in updates:
            flag.value = parsed
            for cb in _WATCHERS.get(key, ()):
                fire.append((cb, parsed))
    # callbacks run outside the registry lock (they may read other flags)
    for cb, value in fire:
        cb(value)


# flag-change watchers: subsystems that cache a flag into a module-level
# fast gate (observability ACTIVE, profiler host-tracer level) register
# here so set_flags keeps the cached copy coherent without the hot path
# paying a registry lookup per event.
_WATCHERS: Dict[str, list] = {}


def watch_flag(name: str, callback) -> None:
    """Invoke `callback(value)` now and after every set_flags update of
    `name` (alias-resolved)."""
    with _LOCK:
        key = _resolve(name)
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag: {name}")
        _WATCHERS.setdefault(key, []).append(callback)
        value = _REGISTRY[key].value
    callback(value)


# reference-name aliases: the subset of the reference's ~187 PHI flags
# (paddle/common/flags.cc) with a live TPU-native equivalent maps here
# so get/set accept the reference spelling. Flags whose job is absorbed
# by XLA/PJRT (allocator fractions, cudnn autotune, stream pools) have
# no entry — silently accepting them would be cosmetic.
_ALIASES = {
    "FLAGS_fuse_parameter_memory_size": "FLAGS_fuse_buffer_size_mb",
    "FLAGS_pg_timeout": "FLAGS_comm_task_timeout_s",
}


def _resolve(name: str) -> str:
    return _ALIASES.get(name, name)


def flag_value(name: str):
    return _REGISTRY[_resolve(name)].value


# Core flags (analogs of the reference's most-used ones).
define_flag("FLAGS_check_nan_inf", False,
            "Scan op outputs for NaN/Inf after each eager op (debug).")

# Watcher-kept gate (STATIC_CHECKS_ACTIVE pattern): the lazy record
# path captures per-op source provenance while the NaN scan is armed,
# so a FloatingPointError names the producing op's file:line even with
# the sanitizer off.
NAN_CHECK_ACTIVE = False


def _sync_nan_check_gate(value):
    global NAN_CHECK_ACTIVE
    NAN_CHECK_ACTIVE = bool(value)


watch_flag("FLAGS_check_nan_inf", _sync_nan_check_gate)
define_flag("FLAGS_call_stack_level", 1,
            "Error message verbosity: 0 brief, 1 python stack, 2 full.")
define_flag("FLAGS_eager_compile_cache_size", 4096,
            "Max cached compiled executables for eager op dispatch "
            "(0 = unlimited).")
define_flag("FLAGS_log_compiles", False, "Log XLA compilations of eager ops.")
define_flag("FLAGS_seed", 0, "Default global random seed.")
define_flag("FLAGS_tpu_matmul_precision", "default",
            "Matmul precision: default|high|highest.")
define_flag("FLAGS_benchmark", False, "Block on every eager op (for timing).")
define_flag("FLAGS_apply_ir_passes", True,
            "run the IR pass pipeline when compiling static Programs")

# ---- distributed runtime knobs (each read by a live consumer)
define_flag("FLAGS_fuse_buffer_size_mb", 25,
            "DataParallel gradient-fusion bucket size in MB "
            "(reducer comm_buffer_size default).")
define_flag("FLAGS_comm_task_timeout_s", 1800.0,
            "CommTaskManager watchdog timeout per collective (the "
            "reference's FLAGS_pg_timeout role).")
define_flag("FLAGS_comm_idle_poll_limit", 10,
            "Native collective engine: consecutive 60s zero-progress "
            "polls before a transfer is declared dead.")
define_flag("FLAGS_tcp_store_timeout_s", 300.0,
            "TCPStore client connect/get timeout in seconds.")
define_flag("FLAGS_launch_max_restarts", 0,
            "Launcher: restarts-with-rerank before giving up "
            "(elastic manager behavior).")

define_flag("FLAGS_lazy_max_segment_ops", 256,
            "Lazy fusion window: pending ops per segment before a forced "
            "flush (caps XLA program size and peak trace memory).")

# ---- compile / memory knobs
define_flag("FLAGS_recompute_segments", 2,
            "Default segment count for the recompute program pass "
            "(jax.checkpoint regions).")
define_flag("FLAGS_amp_dtype", "bfloat16",
            "Default auto-cast dtype for amp O1/O2 (bf16 is the TPU "
            "tensor-core dtype the way fp16 is CUDA's).")
define_flag("FLAGS_flash_block_q", 512,
            "Pallas flash-attention max query block size.")
define_flag("FLAGS_flash_block_k", 512,
            "Pallas flash-attention max key block size.")

# ---- io / misc
define_flag("FLAGS_dataloader_num_workers", 0,
            "Default DataLoader worker count when not passed.")
define_flag("FLAGS_profiler_dir", "",
            "Directory for chrome-trace exports ('' = cwd).")
define_flag("FLAGS_dataloader_prefetch_factor", 2,
            "Default DataLoader prefetch batches per worker.")

# ---- SOT / lazy capture knobs (jit/sot, _core/lazy)
define_flag("FLAGS_sot_cache_entries", 8,
            "Max guarded fast-path entries kept per SotFunction.")
define_flag("FLAGS_sot_inline_depth", 8,
            "Max recursive bytecode-inline depth in the SOT executor.")
define_flag("FLAGS_sot_step_budget", 2_000_000,
            "Max interpreted bytecode steps per SOT frame before the "
            "frame falls back to native execution.")
define_flag("FLAGS_sot_guard_size_cap", 64,
            "Largest container/array value-guarded by SOT; larger "
            "inputs refuse the fast path instead.")
define_flag("FLAGS_lazy_enable", True,
            "Kill-switch for the lazy fusion window: when false, "
            "lazy_guard() becomes a no-op and ops dispatch eagerly.")
define_flag("FLAGS_eager_fusion", True,
            "Ambient fusion window: plain dygraph code (no lazy_guard) "
            "records into a segment that runs as one cached XLA program "
            "at the next sync point. The eager hot-path default; false "
            "restores strict per-op dispatch.")
define_flag("FLAGS_executable_cache_capacity", 1024,
            "LRU capacity for each compiled-executable cache (lazy "
            "segment/bwd/fused-step + eager fwd/bwd); 0 = unbounded.")
define_flag("FLAGS_lazy_donate_inputs", True,
            "Donate lazy-segment input buffers whose backing tensor is "
            "dead or overwritten at flush (XLA reuses them in place).")
define_flag("FLAGS_record_fast_path", True,
            "Trace-stable record fast path: after a sealed segment's "
            "signature memo proves the op stream repeats, later "
            "iterations replay the retained op skeleton — matching "
            "(op, attrs, input wiring) position-for-position and "
            "skipping aval inference / cache-key construction / attrs "
            "copying per recorded op, re-binding only external input "
            "payloads. Any mismatch falls back to the full record path "
            "for the rest of the segment; mesh-epoch bumps, replans, "
            "relevant set_flags and mid-segment in-place swaps "
            "invalidate the skeleton. Off = the exact pre-existing "
            "per-op record behavior.")
define_flag("FLAGS_step_replay_after", 3,
            "Whole-step driver promotion threshold: after this many "
            "consecutive clean skeleton replays of a sealed segment "
            "(runner already cached), the seal path promotes to a "
            "step plan — one driver call validates liveness/donation "
            "and executes the cached executable directly, skipping "
            "signature memo probing and flush bookkeeping; recording "
            "itself drops per-op validation to wiring identity checks. "
            "Any mismatch demotes that step to per-op skeleton replay "
            "and re-arms the streak. 0 disables promotion.")
define_flag("FLAGS_executable_cache_dir", "",
            "Persistent compiled-executable cache directory ('' = "
            "off): sealed-segment / fused-step / optimizer runners are "
            "serialized (jax AOT) under an epoch-normalized signature "
            "digest with checksum + version/backend stamps, and cache "
            "misses consult disk before lower().compile() — process "
            "restart, elastic re-plan and serving cold-start load "
            "instead of recompiling. Memory/cost analyses persist "
            "alongside so warm loads keep their meters.")
define_flag("FLAGS_executable_cache_disk_max_mb", 512,
            "Persistent executable cache disk budget in MB: after each "
            "store, oldest-mtime entries are pruned until the cache "
            "directory fits (0 = unbounded).")
define_flag("FLAGS_async_flush", False,
            "Hand sealed lazy segments to a single-worker flush "
            "executor: compile+execute launch off the Python thread "
            "while eager recording continues; results materialize "
            "through pending-value placeholders and worker errors "
            "re-raise at the next sync point (_value read, backward, "
            "drain). Off = the exact pre-existing synchronous path.")
define_flag("FLAGS_prefetch_depth", 2,
            "Device-feed double-buffer depth: DevicePrefetcher (and the "
            "bench input path) keeps this many upcoming batches' "
            "host->device transfers in flight so step N+1's inputs "
            "land while step N executes (0/1 = no overlap).")
define_flag("FLAGS_optimizer_donate_params", True,
            "Donate old parameter/state buffers into the fused optimizer "
            "update so XLA updates them in place (no per-step copy).")

# ---- AMP / GradScaler defaults (amp/grad_scaler.py)
define_flag("FLAGS_amp_init_loss_scaling", 65536.0,
            "GradScaler default init_loss_scaling.")
define_flag("FLAGS_amp_incr_every_n_steps", 2000,
            "GradScaler default good-step interval before scale growth.")
define_flag("FLAGS_amp_decr_every_n_nan_or_inf", 1,
            "GradScaler default bad-step count before scale shrink.")

# ---- debug nets
define_flag("FLAGS_check_nan_inf_level", 0,
            "NaN/Inf scan action: 0 raise, 1 warn and continue.")
define_flag("FLAGS_static_checks", "off",
            "Program sanitizer level: 'off' (no cost), 'warn' (run the "
            "paddle_tpu.analysis checkers over every flushed lazy "
            "segment, IR pass, reshard lowering, pipeline build and "
            "SOT capture, emitting StaticCheckWarning), 'error' (raise "
            "StaticCheckError on any violation), 'fix' (repair the "
            "mechanical classes — missing note_inplace, unsafe "
            "donation, dead captures — in place, re-check, and warn "
            "for whatever could not be repaired).")
define_flag("FLAGS_dead_capture_min_flops", 1024,
            "Dead-capture lint floor: segments whose dead ops waste "
            "fewer estimated FLOPs than this AND fewer output bytes "
            "than FLAGS_dead_capture_min_bytes are not reported "
            "(scalar bookkeeping the user cannot act on; 0 reports "
            "everything). Fix-mode pruning honors the same floor.")
define_flag("FLAGS_dead_capture_min_bytes", 4096,
            "Dead-capture lint floor companion: minimum wasted output "
            "bytes before a dead capture below the FLOPs floor is "
            "still reported.")
define_flag("FLAGS_numerics_seed_log2max", 4.0,
            "Numerics plane input range seed: segment inputs are "
            "assumed bounded by 2^this (|x| <= 16 by default — "
            "normalized activations/params). The range lattice "
            "(analysis/numerics.py) propagates from here; raising it "
            "makes the overflow_risk checker more pessimistic.")
define_flag("FLAGS_numerics_accum_k", 16384,
            "accum_dtype lint floor: minimum reduction length K before "
            "a matmul/reduction accumulating directly into fp16/bf16 "
            "is flagged (sqrt(K)*eps relative error reaches ~0.5 for "
            "bf16 at K=16384; 0 flags every low-precision reduction).")
define_flag("FLAGS_numerics_min_snr_db", 20.0,
            "quant_error_budget gate: minimum statically-priced "
            "quantization SNR (dB) per gradient bucket before an "
            "int8/fp8 collective plan passes pre-flight.")
define_flag("FLAGS_sharding_replicated_min_bytes", 1 << 20,
            "Sharding perf lint (analysis/sharding_prop.py): minimum "
            "redundant bytes (tensor size x (mesh size - 1)) before a "
            "fully-replicated input to an otherwise-sharded program is "
            "flagged (small scalars/stats are legitimately replicated; "
            "0 flags everything).")
define_flag("FLAGS_sharding_comm_min_bytes", 1024,
            "Sharding perf lint: minimum total priced compiled-"
            "collective traffic per execution before the ranked "
            "comm-hotspot summary diagnostic is attached to the "
            "report (0 reports any non-zero traffic).")
# off-synonym values the hot-path gates (lazy record/flush, PassManager)
# test membership against — keeps '0'/'false' spellings from paying the
# analysis import or even a str() call per recorded op. The lowercase
# frozenset is the single source of truth (check_mode() normalizes
# against it); STATIC_CHECKS_OFF adds the common case/type variants so
# the raw-value gate needs no normalization, as a frozenset because the
# membership test runs once per recorded op.
STATIC_CHECKS_OFF_WORDS = frozenset(
    ("off", "0", "false", "none", "disable", "disabled", ""))
STATIC_CHECKS_OFF = frozenset(
    w for word in STATIC_CHECKS_OFF_WORDS
    for w in (word, word.capitalize(), word.upper())
) | {0, False, None}

# Cached module-level gate for the record/flush hot paths: True iff
# FLAGS_static_checks is not an off-spelling. A watch_flag callback
# keeps it coherent (env init and every set_flags land here), so the
# per-recorded-op gate is one attribute read instead of a registry
# resolve + frozenset test per op.
STATIC_CHECKS_ACTIVE = False


def _sync_static_checks_gate(value):
    global STATIC_CHECKS_ACTIVE
    STATIC_CHECKS_ACTIVE = value not in STATIC_CHECKS_OFF


watch_flag("FLAGS_static_checks", _sync_static_checks_gate)

# ---- fault tolerance / resilience (distributed/resilience)
define_flag("FLAGS_fault_inject", "",
            "Deterministic fault-injection plan ('' = off, zero cost): "
            "'seed=N;site[@occ]=kind[(arg)][:prob];...' where site is a "
            "named injection point (store::get, pg::init, "
            "comm::all_reduce, segment::compile, exec::oom, step::N, "
            "ckpt::save; trailing * wildcards match) and kind is fail "
            "| die | delay(s) | stuck(s) | oom (synthetic XLA "
            "RESOURCE_EXHAUSTED at the execute sites). See "
            "distributed/resilience/faults.py.")
define_flag("FLAGS_retry_max_attempts", 3,
            "RetryPolicy default attempt budget for transient failures "
            "(TCPStore ops, process-group bring-up, host collectives, "
            "checkpoint I/O).")
define_flag("FLAGS_retry_backoff_s", 0.05,
            "RetryPolicy base backoff delay in seconds (exponential "
            "with deterministic jitter).")
define_flag("FLAGS_elastic_max_retries", 2,
            "ElasticStep: rollback-and-rerun attempts per training step "
            "before the failure propagates.")
define_flag("FLAGS_checkpoint_keep", 3,
            "CheckpointManager: verified checkpoint generations kept on "
            "disk (older generations pruned after each save; load "
            "auto-falls-back to the newest verified older generation "
            "when the latest fails its checksum).")
define_flag("FLAGS_checkpoint_interval_steps", 0,
            "AdaptiveTrainer: auto-checkpoint every N step boundaries "
            "through the retention manager (0 = off). Bounds the "
            "preemption-recovery badput to one interval without a "
            "call-site convention; a trainer built with an explicit "
            "checkpoint_every overrides the flag.")
define_flag("FLAGS_elastic_grow_chunk_kb", 512,
            "grow_world state broadcast: TCPStore chunk size in KiB for "
            "the survivor->joiner state transfer (each chunk is "
            "sha256-checksummed; the whole payload is verified before "
            "unpickling).")

# Cached module-level gate for the fault-injection hot-path hooks
# (store ops, collectives, segment compile, elastic steps): True iff
# FLAGS_fault_inject names a plan. Same watcher-kept-coherent pattern
# as STATIC_CHECKS_ACTIVE — the off path pays one attribute read and
# never imports the resilience package.
FAULT_INJECT_ACTIVE = False


def _sync_fault_inject_gate(value):
    global FAULT_INJECT_ACTIVE
    FAULT_INJECT_ACTIVE = bool(str(value).strip())


watch_flag("FLAGS_fault_inject", _sync_fault_inject_gate)

# Cached module-level gate for the async flush pipeline (the
# STATIC_CHECKS_ACTIVE pattern): True iff FLAGS_async_flush is on. The
# per-flush gate is one attribute read; the executor module is never
# imported while this is False.
ASYNC_FLUSH_ACTIVE = False


def _sync_async_flush_gate(value):
    global ASYNC_FLUSH_ACTIVE
    ASYNC_FLUSH_ACTIVE = bool(value)


watch_flag("FLAGS_async_flush", _sync_async_flush_gate)

# ---- kernels / pallas
define_flag("FLAGS_flash_interpret", False,
            "Force Pallas flash kernels into interpret mode (CPU mesh "
            "tests; PT_FLASH_INTERPRET env is the legacy spelling).")
define_flag("FLAGS_moe_capacity_factor", 1.25,
            "Default MoE gating capacity factor.")

# ---- distributed transport / pipeline
define_flag("FLAGS_pg_native_transport", True,
            "Allow the native socket collective engine; false forces "
            "the pure-python store-relay fallback on every rank.")
define_flag("FLAGS_pipeline_stash_warn_mb", 0,
            "Warn when a pipeline runtime's activation stash exceeds "
            "this many MB (0 = off).")
define_flag("FLAGS_pipeline_max_inflight", 0,
            "Hard cap on stashed in-flight micro-batches per pipeline "
            "rank (0 = unlimited; exceeding raises).")
define_flag("FLAGS_dp_broadcast_params", True,
            "DataParallel broadcasts parameters from rank 0 at wrap "
            "time so replicas start identical.")
define_flag("FLAGS_elastic_heartbeat_interval_s", 0.5,
            "ElasticManager heartbeat/watch interval in seconds.")
define_flag("FLAGS_elastic_eviction_debounce", 3,
            "ElasticManager: consecutive missed/stale heartbeat probes "
            "before a node is evicted from membership (the PR-6 drill "
            "showed 8 cold XLA compiles starve every peer's heartbeat "
            "thread — one slow scan must not publish a member::leave "
            "epoch; 1 restores the old evict-on-first-miss behavior).")
define_flag("FLAGS_watchdog_check_interval_s", 1.0,
            "CommTaskManager watchdog poll interval in seconds.")
define_flag("FLAGS_auto_tuner_max_trials", 0,
            "Auto-tuner default measured-trial count (0 = cost-model "
            "ranking only).")

# ---- compile caches
define_flag("FLAGS_dy2static_cache_limit", 64,
            "Max cached (signature -> executable) entries per "
            "to_static function before oldest eviction.")

# ---- inference defaults (inference/Config)
define_flag("FLAGS_inference_opt_level", 2,
            "Default inference Config optimization level.")
define_flag("FLAGS_inference_donate_inputs", False,
            "Default inference Config input-donation setting.")

# ---- profiler
define_flag("FLAGS_host_tracer_level", 1,
            "Host tracer detail: 0 off, 1 ops, 2 ops+python ranges.")
define_flag("FLAGS_profiler_max_events", 1_000_000,
            "Host tracer event-buffer cap (oldest dropped beyond it).")
define_flag("FLAGS_profiler_fused_runtime", False,
            "Profiler keeps the fusion window ON while recording: no "
            "per-op host events (op::*), the trace instead carries the "
            "fused-runtime spans (segment flush/compile/execute, fused "
            "step, optimizer) the steady-state hot path actually runs.")

# ---- observability (paddle_tpu.observability)
define_flag("FLAGS_distributed_telemetry", False,
            "Cross-rank telemetry plane: each rank periodically "
            "publishes a compact frame (metrics/span-histogram deltas, "
            "step index, mesh epoch, recent span events) through the "
            "TCPStore under __telem/ keys, and rank 0 merges them into "
            "a cluster step table (per-rank skew, straggler flags), a "
            "comm-overlap report, and a merged per-rank chrome trace. "
            "Off = one module-level check per step, zero registry and "
            "zero store work (bench row 10).")
define_flag("FLAGS_distributed_telemetry_interval", 1,
            "Telemetry plane: steps between frame publications (1 = "
            "every step boundary).")
define_flag("FLAGS_distributed_telemetry_events", 4096,
            "Telemetry plane: span events buffered per rank between "
            "frame publications (oldest dropped beyond it).")
define_flag("FLAGS_telemetry_straggler_factor", 1.25,
            "Step-table straggler flag: a rank is flagged when its "
            "per-step time exceeds the step's cross-rank median by "
            "this factor (and by FLAGS_telemetry_straggler_min_us).")
define_flag("FLAGS_telemetry_straggler_min_us", 1000.0,
            "Step-table straggler flag: minimum absolute skew "
            "(slowest minus median, us) before a rank is flagged — "
            "filters factor-trips on micro-second steps.")
define_flag("FLAGS_telemetry_postmortem_grace_s", 3.0,
            "Distributed flight postmortem: how long rank 0 polls the "
            "store for survivor rings before writing the aggregated "
            "report with whatever arrived.")
define_flag("FLAGS_observability", False,
            "Collect runtime metrics (counters/gauges/histograms) at "
            "the fused-runtime instrumentation points; off = the hot "
            "paths pay one module-level check and zero registry work.")
define_flag("FLAGS_memory_telemetry", False,
            "Byte-domain telemetry plane (observability/memory.py): "
            "live-buffer census with birth-site provenance at the "
            "Tensor-creation and lazy bind choke points, per-compile "
            "XLA memory_analysis cached on the executable-cache entry, "
            "donation savings accounting, and OOM postmortems at the "
            "execute sites. Off = one module-level check per choke "
            "point, zero census and zero registry work (bench row 11).")
define_flag("FLAGS_compute_telemetry", False,
            "Compute-efficiency telemetry plane (observability/"
            "compute.py): per-executable XLA cost_analysis (FLOPs, "
            "bytes accessed, transcendentals) captured once per compile "
            "at the three fused-runtime compile sites and cached on the "
            "executable-cache entry, per-execution FLOP counters "
            "(compute.flops.{segment,fused_step,optimizer}), MFU/"
            "roofline columns in the budget tool, and source-attributed "
            "device profiles (each recorded op's lowering wrapped in a "
            "jax.named_scope carrying its paddle file:line). Off = one "
            "module-level check per site, zero registry and zero "
            "analysis work (bench row 14).")
define_flag("FLAGS_device_peak_flops", 0.0,
            "Per-chip peak FLOP/s the MFU column divides by. 0 = "
            "autodetect per backend: TPU from the device_kind table "
            "(v2 45T .. v6e 918T bf16), CPU falls back to a nominal "
            "cores x 2.5 GHz x 16 fp32-FLOPs/cycle AVX2-FMA envelope "
            "(documented in README — CPU MFU is a relative meter, not "
            "an absolute one).")
define_flag("FLAGS_device_peak_membw", 0.0,
            "Per-chip peak memory bandwidth in bytes/s for the "
            "roofline ridge point (peak_flops / peak_membw). 0 = "
            "autodetect: TPU from the device_kind table (v4 1.2TB/s, "
            "v5p 2.8TB/s, ...), CPU falls back to a nominal 25.6 GB/s "
            "two-channel DDR4 envelope.")
define_flag("FLAGS_memory_budget_bytes", 0,
            "Per-device HBM budget in bytes for the cross-rank memory "
            "column: budget --distributed flags the rank whose peak is "
            "nearest this budget (0 = unknown; the highest absolute "
            "peak is flagged instead).")
define_flag("FLAGS_goodput", False,
            "Goodput plane (observability/goodput.py): per-process "
            "wall-clock attribution ledger partitioning the job "
            "timeline into exclusive states (productive execute, "
            "compile, input wait, comm wait, host gap, checkpoint "
            "I/O, recovery, idle) with bucket additivity asserted, a "
            "bounded step-time ring feeding anomaly detection, and a "
            "hang watchdog that captures stacks + dumps the flight "
            "ring when no step progress happens within "
            "FLAGS_goodput_hang_factor x the median step time. Off = "
            "one module-level check per probe, zero ring mutations "
            "(bench row 16).")
define_flag("FLAGS_goodput_hang_factor", 8.0,
            "Goodput hang watchdog: the job is declared hung when no "
            "probe-visible progress happens within this factor x the "
            "rolling median step time (floored by "
            "FLAGS_goodput_hang_min_s).")
define_flag("FLAGS_goodput_hang_min_s", 1.0,
            "Goodput hang watchdog: floor on the dynamic timeout so "
            "micro-second steps cannot arm a hair-trigger deadline "
            "over a legitimate recompile.")
define_flag("FLAGS_goodput_hang_poll_s", 0.25,
            "Goodput hang watchdog: watchdog-thread poll interval in "
            "seconds (bounds detection latency beyond the timeout).")
define_flag("FLAGS_goodput_spike_factor", 3.0,
            "Goodput anomaly detection: a step slower than this "
            "factor x the rolling median counts "
            "goodput.anomalies.step_spike (same factor watches loss "
            "divergence via note_loss).")
define_flag("FLAGS_goodput_ring", 128,
            "Goodput step-time ring capacity (rolling median window "
            "for the spike and hang thresholds).")
define_flag("FLAGS_flight_max_dumps", 32,
            "Flight-recorder dump retention: per-rank cap on "
            "flight_*.txt files kept in FLAGS_flight_recorder_dir "
            "(oldest pruned first after each dump; rank-aware so one "
            "rank's churn cannot evict another rank's postmortem; "
            "0 = unlimited).")
define_flag("FLAGS_flight_recorder", False,
            "Keep a bounded ring buffer of recent runtime events "
            "(spans, flushes, cache decisions) and dump a readable "
            "report on enforce errors, failed flushes, and sanitizer "
            "error-mode trips.")
define_flag("FLAGS_flight_recorder_capacity", 512,
            "Flight-recorder ring size (events kept).")
define_flag("FLAGS_flight_recorder_dir", "",
            "Directory for flight-record dumps ('' = FLAGS_profiler_dir "
            "or cwd).")
define_flag("FLAGS_monitor", False,
            "Live monitoring plane (observability/timeseries.py): a "
            "daemon sampler records counter rates (steps/s, tokens/s, "
            "compiles, cache hit rate), byte/census gauges, goodput "
            "fractions and per-step MFU into bounded per-series rings "
            "every FLAGS_monitor_interval_s, feeding the /metrics "
            "exporter and the online regression watchdog. Off = one "
            "module-level check per step hook, zero registry work, no "
            "sampler thread, no bound port (bench row 20).")
define_flag("FLAGS_monitor_interval_s", 1.0,
            "Monitor sampler period in seconds (each tick appends one "
            "timestamped sample per series).")
define_flag("FLAGS_monitor_port", 0,
            "Monitor HTTP exporter port serving /metrics (Prometheus "
            "text exposition), /healthz, /snapshot and "
            "/timeseries?name=. 0 = no HTTP endpoint (sampler rings "
            "still record for in-process readers).")
define_flag("FLAGS_monitor_host", "127.0.0.1",
            "Monitor exporter bind address. Loopback by default — "
            "bind a routable interface explicitly to let an external "
            "Prometheus scrape the job.")
define_flag("FLAGS_monitor_ring", 512,
            "Monitor per-series ring capacity (samples kept per "
            "series; at the default 1 s interval ~8.5 min of trend).")
define_flag("FLAGS_monitor_regression_factor", 1.5,
            "Online regression watchdog: a headline series (step "
            "duration, tokens/s, goodput fraction) deviating past "
            "this factor from its EWMA baseline, sustained for "
            "FLAGS_monitor_regression_steps consecutive samples, "
            "counts monitor.regressions and leaves a flight note "
            "with baseline-vs-current evidence.")
define_flag("FLAGS_monitor_regression_steps", 5,
            "Consecutive deviating samples required before the "
            "regression watchdog fires (debounce against one-off "
            "recompiles or input stalls).")
define_flag("FLAGS_monitor_deep_capture_steps", 0,
            "When > 0, a fired regression arms a one-shot deep "
            "capture: the profiler (fused_runtime) traces the next K "
            "steps and the trace is dumped beside the flight ring "
            "(subject to the same rank-aware retention).")

# ---- model-surface defaults
define_flag("FLAGS_onnx_opset", 13,
            "Minimum default-domain opset version for ONNX export "
            "(raised per-op when an emitted op needs newer).")
define_flag("FLAGS_hapi_log_freq", 1,
            "hapi ProgBarLogger default step logging frequency.")
define_flag("FLAGS_asp_mask_algo", "mask_1d",
            "Default ASP 2:4 pruning mask algorithm.")
define_flag("FLAGS_quant_bits", 8,
            "Default quantization bit width for observers/QAT.")

# ---- sparse
define_flag("FLAGS_sparse_validate_indices", False,
            "Bounds-check sparse indices at construction (debug).")

# ---- IR
define_flag("FLAGS_ir_pass_disable", "",
            "Comma-separated IR pass names to skip in the pipeline.")
define_flag("FLAGS_enable_auto_layout", False,
            "Run the NHWC auto-layout pass in the static pipeline "
            "(transpose-sunk NHWC convs, auto_layout_pass.cc role).")

# ---- remaining runtime knobs
define_flag("FLAGS_rpc_timeout_s", 180.0,
            "Default rpc_sync/rpc_async call timeout in seconds.")
define_flag("FLAGS_conv_data_format", "NCHW",
            "Default conv/pool data layout when data_format is not "
            "passed (the DataLayout default of the reference).")
define_flag("FLAGS_launch_log_dir", "log",
            "Default --log_dir for paddle.distributed.launch.")
define_flag("FLAGS_host_alloc_chunk_kb", 256,
            "Native host allocator pool chunk size in KB "
            "(csrc/allocator.cc pt_alloc_create).")
define_flag("FLAGS_zb_w_extra_delay", 0,
            "Extra micro-batches of weight-grad (W) deferral in the "
            "ZeroBubble schedule beyond the warmup depth.")
define_flag("FLAGS_amp_level", "O1",
            "Default auto_cast level when not passed.")
define_flag("FLAGS_allow_pickle_load", False,
            "Permit loading legacy pickle parameter files (pickle can "
            "execute code; PT_ALLOW_PICKLE_LOAD=1 is the env spelling).")
define_flag("FLAGS_jit_save_meta", True,
            "jit.save writes the .pdmeta named-IO sidecar used by the "
            "inference AnalysisPredictor.")
define_flag("FLAGS_ckpt_strict_load", True,
            "Distributed checkpoint load fails on missing/unexpected "
            "keys instead of loading the intersection.")
define_flag("FLAGS_guard_log", False,
            "Log SOT guard-set contents and fast-path misses (debug).")



