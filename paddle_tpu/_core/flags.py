"""Global runtime flag registry.

TPU-native analog of the reference's exported-flag registry
(paddle/common/flags.h:242-291 `PHI_DEFINE_EXPORTED_*`, ~187 flags in
flags.cc) with env-var override and get/set from Python
(python/paddle/base/framework.py:132,157 set_flags/get_flags).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterable, Union

_LOCK = threading.RLock()
_REGISTRY: Dict[str, "Flag"] = {}


class Flag:
    __slots__ = ("name", "default", "value", "type", "help")

    def __init__(self, name: str, default: Any, help: str = ""):
        self.name = name
        self.default = default
        self.type = type(default)
        self.help = help
        env = os.environ.get(name)
        self.value = _parse(env, self.type) if env is not None else default


def _parse(text: str, ty: type):
    if ty is bool:
        return text.lower() in ("1", "true", "yes", "on")
    return ty(text)


def define_flag(name: str, default: Any, help: str = "") -> Flag:
    with _LOCK:
        if name in _REGISTRY:
            return _REGISTRY[name]
        flag = Flag(name, default, help)
        _REGISTRY[name] = flag
        return flag


def get_flags(flags: Union[str, Iterable[str]]) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    with _LOCK:
        out = {}
        for name in flags:
            key = _resolve(name)
            if key not in _REGISTRY:
                raise ValueError(f"unknown flag: {name}")
            out[name] = _REGISTRY[key].value
        return out


def set_flags(flags: Dict[str, Any]) -> None:
    with _LOCK:
        for name, value in flags.items():
            key = _resolve(name)
            if key not in _REGISTRY:
                raise ValueError(f"unknown flag: {name}")
            flag = _REGISTRY[key]
            flag.value = _parse(value, flag.type) if isinstance(value, str) and flag.type is not str else flag.type(value)


# reference-name aliases: the subset of the reference's ~187 PHI flags
# (paddle/common/flags.cc) with a live TPU-native equivalent maps here
# so get/set accept the reference spelling. Flags whose job is absorbed
# by XLA/PJRT (allocator fractions, cudnn autotune, stream pools) have
# no entry — silently accepting them would be cosmetic.
_ALIASES = {
    "FLAGS_fuse_parameter_memory_size": "FLAGS_fuse_buffer_size_mb",
    "FLAGS_pg_timeout": "FLAGS_comm_task_timeout_s",
}


def _resolve(name: str) -> str:
    return _ALIASES.get(name, name)


def flag_value(name: str):
    return _REGISTRY[_resolve(name)].value


# Core flags (analogs of the reference's most-used ones).
define_flag("FLAGS_check_nan_inf", False,
            "Scan op outputs for NaN/Inf after each eager op (debug).")
define_flag("FLAGS_call_stack_level", 1,
            "Error message verbosity: 0 brief, 1 python stack, 2 full.")
define_flag("FLAGS_eager_compile_cache_size", 4096,
            "Max cached compiled executables for eager op dispatch.")
define_flag("FLAGS_log_compiles", False, "Log XLA compilations of eager ops.")
define_flag("FLAGS_seed", 0, "Default global random seed.")
define_flag("FLAGS_tpu_matmul_precision", "default",
            "Matmul precision: default|high|highest.")
define_flag("FLAGS_benchmark", False, "Block on every eager op (for timing).")
define_flag("FLAGS_apply_ir_passes", True,
            "run the IR pass pipeline when compiling static Programs")

# ---- distributed runtime knobs (each read by a live consumer)
define_flag("FLAGS_fuse_buffer_size_mb", 25,
            "DataParallel gradient-fusion bucket size in MB "
            "(reducer comm_buffer_size default).")
define_flag("FLAGS_comm_task_timeout_s", 1800.0,
            "CommTaskManager watchdog timeout per collective (the "
            "reference's FLAGS_pg_timeout role).")
define_flag("FLAGS_comm_idle_poll_limit", 10,
            "Native collective engine: consecutive 60s zero-progress "
            "polls before a transfer is declared dead.")
define_flag("FLAGS_tcp_store_timeout_s", 300.0,
            "TCPStore client connect/get timeout in seconds.")
define_flag("FLAGS_launch_max_restarts", 0,
            "Launcher: restarts-with-rerank before giving up "
            "(elastic manager behavior).")

define_flag("FLAGS_lazy_max_segment_ops", 256,
            "Lazy fusion window: pending ops per segment before a forced "
            "flush (caps XLA program size and peak trace memory).")

# ---- compile / memory knobs
define_flag("FLAGS_recompute_segments", 2,
            "Default segment count for the recompute program pass "
            "(jax.checkpoint regions).")
define_flag("FLAGS_amp_dtype", "bfloat16",
            "Default auto-cast dtype for amp O1/O2 (bf16 is the TPU "
            "tensor-core dtype the way fp16 is CUDA's).")
define_flag("FLAGS_flash_block_q", 512,
            "Pallas flash-attention max query block size.")
define_flag("FLAGS_flash_block_k", 512,
            "Pallas flash-attention max key block size.")

# ---- io / misc
define_flag("FLAGS_dataloader_num_workers", 0,
            "Default DataLoader worker count when not passed.")
define_flag("FLAGS_profiler_dir", "",
            "Directory for chrome-trace exports ('' = cwd).")



