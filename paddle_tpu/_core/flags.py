"""Global runtime flag registry.

TPU-native analog of the reference's exported-flag registry
(paddle/common/flags.h:242-291 `PHI_DEFINE_EXPORTED_*`, ~187 flags in
flags.cc) with env-var override and get/set from Python
(python/paddle/base/framework.py:132,157 set_flags/get_flags).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterable, Union

_LOCK = threading.RLock()
_REGISTRY: Dict[str, "Flag"] = {}


class Flag:
    __slots__ = ("name", "default", "value", "type", "help")

    def __init__(self, name: str, default: Any, help: str = ""):
        self.name = name
        self.default = default
        self.type = type(default)
        self.help = help
        env = os.environ.get(name)
        self.value = _parse(env, self.type) if env is not None else default


def _parse(text: str, ty: type):
    if ty is bool:
        return text.lower() in ("1", "true", "yes", "on")
    return ty(text)


def define_flag(name: str, default: Any, help: str = "") -> Flag:
    with _LOCK:
        if name in _REGISTRY:
            return _REGISTRY[name]
        flag = Flag(name, default, help)
        _REGISTRY[name] = flag
        return flag


def get_flags(flags: Union[str, Iterable[str]]) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    with _LOCK:
        out = {}
        for name in flags:
            if name not in _REGISTRY:
                raise ValueError(f"unknown flag: {name}")
            out[name] = _REGISTRY[name].value
        return out


def set_flags(flags: Dict[str, Any]) -> None:
    with _LOCK:
        for name, value in flags.items():
            if name not in _REGISTRY:
                raise ValueError(f"unknown flag: {name}")
            flag = _REGISTRY[name]
            flag.value = _parse(value, flag.type) if isinstance(value, str) and flag.type is not str else flag.type(value)


def flag_value(name: str):
    return _REGISTRY[name].value


# Core flags (analogs of the reference's most-used ones).
define_flag("FLAGS_check_nan_inf", False,
            "Scan op outputs for NaN/Inf after each eager op (debug).")
define_flag("FLAGS_call_stack_level", 1,
            "Error message verbosity: 0 brief, 1 python stack, 2 full.")
define_flag("FLAGS_eager_compile_cache_size", 4096,
            "Max cached compiled executables for eager op dispatch.")
define_flag("FLAGS_log_compiles", False, "Log XLA compilations of eager ops.")
define_flag("FLAGS_seed", 0, "Default global random seed.")
define_flag("FLAGS_tpu_matmul_precision", "default",
            "Matmul precision: default|high|highest.")
define_flag("FLAGS_benchmark", False, "Block on every eager op (for timing).")
define_flag("FLAGS_apply_ir_passes", True,
            "run the IR pass pipeline when compiling static Programs")
