"""Async dispatch pipeline: the off-thread segment-flush executor.

The PR-3 span budget puts the residual steady-state overhead squarely
on the host: with the accelerator holding at <=2 XLA executions per
step, the Python thread still serializes eager RECORDING of step N+1
behind the flush (cache lookup + compile + dispatch) of step N's
segments. This module breaks that serialization the way the reference
gets it free from CUDA-stream asynchrony (and 2011.03641 argues is the
whole game at this regime): `CaptureContext.flush` seals the trace and
hands it to a single-worker executor; the recording thread immediately
resumes, with every live output bound to a `PendingValue` placeholder
that materializes through the existing LazyRef machinery.

Contracts:

- **ordering**: one worker, FIFO queue — segments execute in exactly
  the order they were sealed, so eager ordering (and donation
  reasoning, which is decided at seal time on the recording thread) is
  preserved.
- **sync points**: reading a pending value (`Tensor._value`,
  `.numpy()`, `float()`), `backward()` through a segment whose inputs
  are pending, and `drain()` all block until the in-flight work lands.
- **errors**: a worker failure is latched into every PendingValue of
  the failed job *and* into the executor. Framework exceptions
  (injected faults, StaticCheckError, EnforceNotMet) re-raise with
  their original type at the next sync point — rollback and sanitizer
  contracts see the same exception class as the synchronous path —
  while anything else is wrapped in EnforceNotMet with the flight
  recorder's post-mortem already dumped from the worker.
- **shutdown**: an atexit hook drains and retires the worker; a
  process must not exit with a leaked flush thread (bench_suite row 9
  asserts this).
"""
from __future__ import annotations

import atexit
import queue
import threading
from typing import Any, Callable, List, Optional

_WORKER_NAME = "paddle_tpu-flush-worker"


class PendingValue:
    """Placeholder payload for one output of an in-flight flushed
    segment. Carries the recorded aval so metadata reads (shape/dtype/
    signature building) never block; `resolve()` blocks until the
    worker lands the concrete jax array (or re-raises its error)."""

    _is_pending_value = True
    __slots__ = ("aval", "_event", "_value", "_error", "__weakref__")

    def __init__(self, aval):
        self.aval = aval
        self._event = threading.Event()
        self._value = None
        self._error = None

    # metadata mirrors a jax array so _aval_of/_in_signature/
    # _segment_needs_grad read pending inputs without materializing
    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def weak_type(self):
        return getattr(self.aval, "weak_type", False)

    @property
    def ndim(self):
        return len(self.aval.shape)

    def done(self) -> bool:
        return self._event.is_set()

    def _fill(self, value):
        self._value = value
        self._event.set()

    def _fail(self, error: BaseException):
        self._error = error
        self._event.set()

    def resolve(self):
        self._event.wait()
        if self._error is not None:
            raise _surface_error(self._error)
        return self._value


def resolve_value(v):
    """Concrete payload for `v` (blocking if pending)."""
    if getattr(v, "_is_pending_value", False):
        return v.resolve()
    return v


def resolve_pending(vals) -> list:
    """Resolve every PendingValue in a payload list — the boundary any
    consumer (segment runner, vjp, replay) crosses before handing
    values to jax."""
    return [v.resolve() if getattr(v, "_is_pending_value", False) else v
            for v in vals]


def _surface_error(err: BaseException) -> BaseException:
    """The exception a sync point raises for a worker failure. Typed
    framework errors keep their class (rollback retry-ability, fault
    drills, and sanitizer handling must behave exactly like the
    synchronous path); anything else becomes EnforceNotMet so user
    code gets the framework's error surface, with the original chained
    as __cause__."""
    from ..base.core import EnforceNotMet
    from ..distributed.resilience.faults import FaultError
    try:
        from ..analysis.diagnostics import StaticCheckError
    except Exception:                                # pragma: no cover
        StaticCheckError = ()
    if isinstance(err, (EnforceNotMet, FaultError, StaticCheckError,
                        FloatingPointError)):
        return err
    wrapped = EnforceNotMet(
        f"async segment flush failed off-thread: "
        f"{type(err).__name__}: {err}",
        context="the failure happened on the flush worker; this "
                "re-raise is the next sync point. Set "
                "FLAGS_async_flush=false to fail at the flush site.")
    wrapped.__cause__ = err
    return wrapped


# run-ahead bound: a recording thread with no sync point could
# otherwise seal segments faster than the worker executes them, each
# queued job pinning its trace + input buffers — memory would grow
# linearly with run-ahead where the sync path's stays flat. Classic
# pipeline depth; submit blocks (on the condition, never on the queue)
# once this many jobs are in flight.
_MAX_INFLIGHT = 4


class FlushExecutor:
    """Single-worker FIFO executor for sealed segment flushes."""

    def __init__(self, max_inflight: int = _MAX_INFLIGHT):
        self._max_inflight = max(int(max_inflight), 1)
        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._inflight = 0          # submitted, not yet finished
        self._idle = threading.Condition(self._lock)
        self._latched: List[BaseException] = []
        self._stopped = False

    # ------------------------------------------------------------ worker
    def _ensure_worker_locked(self):
        """Start the worker if needed. Caller holds self._lock — the
        check-and-start must be atomic or two threads' first concurrent
        submits would each start a worker, breaking FIFO ordering and
        leaking the orphan past shutdown."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._loop, name=_WORKER_NAME, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            job = self._q.get()
            if job is None:
                return
            fn, on_error = job
            try:
                fn()
            except BaseException as e:   # latched, surfaced at sync
                with self._lock:
                    self._latched.append(e)
                from ..observability import _state as _OBS
                if _OBS.DIST:
                    # a latched worker error is a postmortem trigger on
                    # the distributed plane too: publish this rank's
                    # ring now — by the time the error re-raises at the
                    # sync point the ring may have wrapped past the
                    # failing flush. Never raises.
                    from ..observability import distributed as _dtel
                    _dtel.trigger_postmortem(
                        f"async_flush worker error: {e!r}")
                if on_error is not None:
                    try:
                        on_error(e)
                    except Exception:    # pragma: no cover
                        pass
            finally:
                with self._idle:
                    self._inflight -= 1
                    self._idle.notify_all()

    # --------------------------------------------------------- interface
    def submit(self, fn: Callable[[], Any],
               on_error: Optional[Callable] = None):
        """Queue one sealed-segment job. `on_error(exc)` runs on the
        worker after a failure (fills the job's PendingValues). The
        whole stopped-check + enqueue is one locked section: a job
        slipping in behind shutdown's sentinel would never run, leaving
        its PendingValues blocked forever. Backpressure waits on the
        condition (which releases the lock), NEVER on a bounded queue —
        a blocking put under the lock would deadlock against the
        worker's completion decrement."""
        with self._idle:
            while not self._stopped \
                    and self._inflight >= self._max_inflight:
                self._idle.wait()
            if self._stopped:
                raise RuntimeError("flush executor is shut down")
            self._inflight += 1
            self._ensure_worker_locked()
            self._q.put((fn, on_error))

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def drain(self, raise_latched: bool = True):
        """Block until every submitted job finished. With
        `raise_latched`, re-raise the first worker error latched since
        the last drain (rollback's detection point); otherwise the
        errors are discarded — the aborted step's pending outputs still
        carry them individually."""
        with self._idle:
            while self._inflight:
                self._idle.wait()
            errs, self._latched = self._latched, []
        if raise_latched and errs:
            raise _surface_error(errs[0])

    def shutdown(self, timeout: float = 5.0):
        """Drain, stop the worker thread, and join it. Errors latched
        by unread jobs are discarded (process is exiting)."""
        with self._idle:
            if self._stopped:
                return
            self._stopped = True
            t = self._thread
            self._idle.notify_all()   # wake submitters blocked on
            #                           backpressure so they raise
        if t is not None and t.is_alive():
            self._q.put(None)
            t.join(timeout)
        with self._lock:
            self._thread = None
            self._latched = []

    def worker_alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()


_EXECUTOR: Optional[FlushExecutor] = None
_EXEC_LOCK = threading.Lock()


def get_executor() -> FlushExecutor:
    global _EXECUTOR
    if _EXECUTOR is None:
        with _EXEC_LOCK:
            if _EXECUTOR is None:
                _EXECUTOR = FlushExecutor()
                atexit.register(shutdown)
    return _EXECUTOR


def drain(raise_latched: bool = True):
    """Drain the pipeline if it ever started (cheap no-op otherwise).
    THE sync primitive rollback/quiesce/checkpoint paths call before
    touching live state."""
    ex = _EXECUTOR
    if ex is not None:
        ex.drain(raise_latched=raise_latched)


def shutdown():
    global _EXECUTOR
    ex = _EXECUTOR
    if ex is not None:
        ex.shutdown()
        _EXECUTOR = None
