"""Mixture-of-Experts functional core (TPU-native).

The reference's MoE stack (incubate/distributed/models/moe/moe_layer.py:261,
gates under moe/gate/, all-to-all dispatch via global_scatter/global_gather,
fused kernel incubate/nn/functional/fused_moe.py) is CUDA-centric: ragged
token dispatch with index scatter/gather. On TPU the idiomatic form is the
GShard/Switch dense-dispatch formulation: fixed expert capacity C, one-hot
dispatch/combine tensors, and einsum dispatch so everything is static-shaped
and lands on the MXU; under GSPMD an 'ep'-sharded expert dim lowers the
dispatch einsums to the same all-to-all the reference issues by hand.

Shapes: tokens x [S, M] (leading group/batch dims folded by callers),
logits [S, E], dispatch/combine [S, E, C], expert weights stacked [E, ...].
Everything is differentiable jnp; usable eagerly (registered ops) and under
jit/pjit.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


# ------------------------------------------------------------------ gating

def _capacity(s: int, e: int, k: int, capacity_factor: float,
              capacity: Optional[int]) -> int:
    if capacity is not None:
        return max(int(capacity), 1)
    return max(int(s * k * capacity_factor / e + 0.999999), 1)


def top2_gating(logits, capacity_factor: float = None,
                capacity: Optional[int] = None):
    """GShard top-2 gating (moe/gate/gshard_gate.py analog).

    logits [S, E] -> (combine [S, E, C], dispatch bool [S, E, C], aux_loss).
    aux_loss is the GShard load-balance loss: E * mean(me * ce).
    """
    if capacity_factor is None:
        from .._core.flags import flag_value
        capacity_factor = flag_value("FLAGS_moe_capacity_factor")
    s, e = logits.shape
    c = _capacity(s, e, 2, capacity_factor, capacity)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [S,E]

    g1_idx = jnp.argmax(probs, axis=-1)                          # [S]
    mask1 = jax.nn.one_hot(g1_idx, e, dtype=probs.dtype)         # [S,E]
    probs2 = probs * (1.0 - mask1)
    g2_idx = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(g2_idx, e, dtype=probs.dtype)

    # load-balance aux loss over the top-1 assignment
    me = jnp.mean(probs, axis=0)                                 # [E]
    ce = jnp.mean(mask1, axis=0)
    aux_loss = jnp.sum(me * ce) * e

    # positions within each expert's buffer (top-1 tokens first)
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1             # [S,E]
    mask1 = mask1 * (pos1 < c)
    pos2 = (jnp.cumsum(mask2, axis=0) - mask2
            + jnp.sum(mask1, axis=0, keepdims=True))
    mask2 = mask2 * (pos2 < c)
    pos2 = pos2 * mask2

    g1 = jnp.sum(probs * mask1, axis=-1)                         # [S]
    g2 = jnp.sum(probs * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    loc1 = jnp.sum(pos1 * mask1, axis=-1).astype(jnp.int32)      # [S]
    loc2 = jnp.sum(pos2, axis=-1).astype(jnp.int32)
    oh_c1 = jax.nn.one_hot(loc1, c, dtype=probs.dtype)           # [S,C]
    oh_c2 = jax.nn.one_hot(loc2, c, dtype=probs.dtype)
    combine = (g1[:, None, None] * mask1[:, :, None] * oh_c1[:, None, :]
               + g2[:, None, None] * mask2[:, :, None] * oh_c2[:, None, :])
    dispatch = combine > 0.0
    return combine, dispatch, aux_loss


def top1_gating(logits, capacity_factor: float = 1.25,
                capacity: Optional[int] = None, jitter_eps: float = 0.0,
                rng=None):
    """Switch-Transformer top-1 gating (moe/gate/switch_gate.py analog)."""
    s, e = logits.shape
    c = _capacity(s, e, 1, capacity_factor, capacity)
    if jitter_eps > 0.0 and rng is not None:
        noise = jax.random.uniform(rng, logits.shape, jnp.float32,
                                   1.0 - jitter_eps, 1.0 + jitter_eps)
        logits = logits * noise
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(idx, e, dtype=probs.dtype)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask, axis=0)
    aux_loss = jnp.sum(me * ce) * e
    pos = jnp.cumsum(mask, axis=0) * mask - mask
    mask = mask * (pos < c)
    gate = jnp.sum(probs * mask, axis=-1)
    loc = jnp.sum(pos * mask, axis=-1).astype(jnp.int32)
    oh_c = jax.nn.one_hot(loc, c, dtype=probs.dtype)
    combine = gate[:, None, None] * mask[:, :, None] * oh_c[:, None, :]
    dispatch = combine > 0.0
    return combine, dispatch, aux_loss


# ------------------------------------------------------------ dispatch/ffn

def moe_dispatch(x, dispatch):
    """x [S, M], dispatch [S, E, C] -> expert inputs [E, C, M] (einsum =
    the TPU-native global_scatter)."""
    return jnp.einsum("sec,sm->ecm", dispatch.astype(x.dtype), x)


def moe_combine(expert_out, combine):
    """expert_out [E, C, M], combine [S, E, C] -> [S, M] (global_gather)."""
    return jnp.einsum("sec,ecm->sm", combine.astype(expert_out.dtype),
                      expert_out)


def moe_ffn(x, gate_w, w0, b0, w1, b1, *, k: int = 2,
            capacity_factor: float = 1.25, capacity: Optional[int] = None,
            activation: str = "gelu"):
    """Full MoE FFN block: gating + dispatch + grouped expert MLP + combine.

    x [S, M]; gate_w [M, E]; stacked expert weights w0 [E, M, H],
    b0 [E, H], w1 [E, H, M], b1 [E, M]. Returns (out [S, M], aux_loss).
    The grouped matmuls keep E as a batched einsum dim — one large MXU op;
    sharding w0/w1 on E over the 'ep' mesh axis makes GSPMD insert the
    dispatch all-to-alls.
    """
    logits = x @ gate_w.astype(x.dtype)
    if k == 1:
        combine, dispatch, aux = top1_gating(logits, capacity_factor,
                                             capacity)
    else:
        combine, dispatch, aux = top2_gating(logits, capacity_factor,
                                             capacity)
    xe = moe_dispatch(x, dispatch)                    # [E, C, M]
    h = jnp.einsum("ecm,emh->ech", xe, w0.astype(x.dtype)) \
        + b0[:, None, :].astype(x.dtype)
    act = getattr(jax.nn, activation)
    h = act(h)
    ye = jnp.einsum("ech,ehm->ecm", h, w1.astype(x.dtype)) \
        + b1[:, None, :].astype(x.dtype)
    out = moe_combine(ye, combine.astype(x.dtype))
    return out, aux.astype(jnp.float32)


# -------------------------------------------------- eager op registration

def _register():
    from .._core.op_registry import register_op

    register_op("moe_gate_top2", top2_gating, multi_output=True)
    register_op("moe_gate_top1",
                lambda logits, capacity_factor=1.25, capacity=None:
                top1_gating(logits, capacity_factor, capacity),
                multi_output=True)
    register_op("moe_dispatch", moe_dispatch)
    register_op("moe_combine", moe_combine)
    register_op("fused_moe", moe_ffn, multi_output=True)


_register()
