"""__getitem__ / __setitem__ with autograd.

Analog of the reference's set_value/slice kernels + eager_method.cc indexing.
Static indices (ints/slices) become jit attrs; tensor indices are op inputs;
boolean masks are resolved to integer indices on host (static shapes for
XLA), then static gather/scatter kernels run on device so grads flow.
"""
from __future__ import annotations

import numbers

import jax.numpy as jnp
import numpy as np

from .._core.executor import apply
from .._core.op_registry import register_op
from .._core.tensor import Tensor


def _decompose(idx, x_shape):
    """Split an index into a hashable spec + tensor operands."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    spec = []
    tensors = []
    for it in idx:
        if isinstance(it, Tensor):
            if it.dtype == "bool":
                # host sync: bool mask -> integer index tensor
                nz = np.nonzero(np.asarray(it._value))
                if len(nz) == 1:
                    tensors.append(Tensor(jnp.asarray(nz[0])))
                    spec.append(("tensor", len(tensors) - 1))
                else:
                    for comp in nz:
                        tensors.append(Tensor(jnp.asarray(comp)))
                        spec.append(("tensor", len(tensors) - 1))
            else:
                tensors.append(it)
                spec.append(("tensor", len(tensors) - 1))
        elif isinstance(it, slice):
            spec.append(("slice",
                         None if it.start is None else int(it.start),
                         None if it.stop is None else int(it.stop),
                         None if it.step is None else int(it.step)))
        elif it is None:
            spec.append(("newaxis",))
        elif it is Ellipsis:
            spec.append(("ellipsis",))
        elif isinstance(it, numbers.Integral):
            spec.append(("int", int(it)))
        elif isinstance(it, (list, np.ndarray)):
            arr = np.asarray(it)
            if arr.dtype == np.bool_:
                nz = np.nonzero(arr)
                for comp in nz:
                    tensors.append(Tensor(jnp.asarray(comp)))
                    spec.append(("tensor", len(tensors) - 1))
            else:
                tensors.append(Tensor(jnp.asarray(arr)))
                spec.append(("tensor", len(tensors) - 1))
        else:
            raise TypeError(f"unsupported index element: {it!r}")
    return tuple(spec), tensors


def _rebuild(spec, tvals):
    key = []
    for s in spec:
        kind = s[0]
        if kind == "tensor":
            key.append(tvals[s[1]])
        elif kind == "slice":
            key.append(slice(s[1], s[2], s[3]))
        elif kind == "newaxis":
            key.append(None)
        elif kind == "ellipsis":
            key.append(Ellipsis)
        elif kind == "int":
            key.append(s[1])
    return tuple(key)


def _getitem_kernel(x, *tvals, spec):
    return x[_rebuild(spec, tvals)]


register_op("getitem_", _getitem_kernel)


def _setitem_kernel(x, v, *tvals, spec):
    return x.at[_rebuild(spec, tvals)].set(jnp.asarray(v).astype(x.dtype))


register_op("setitem_", _setitem_kernel)


def getitem(x: Tensor, idx):
    spec, tensors = _decompose(idx, x.shape)
    return apply("getitem_", x, *tensors, spec=spec)


def setitem(x: Tensor, idx, value):
    spec, tensors = _decompose(idx, x.shape)
    if not isinstance(value, Tensor):
        value = Tensor(jnp.asarray(value))
    out = apply("setitem_", x, value, *tensors, spec=spec)
    x._adopt(out)
    return x


def install():
    Tensor.__getitem__ = getitem
    Tensor.__setitem__ = setitem
