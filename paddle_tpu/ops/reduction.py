"""Reduction ops (python/paddle/tensor/math.py + stat.py analogs)."""
from __future__ import annotations

import numbers

import jax.numpy as jnp

from .._core import dtype as dtypes_mod
from .._core.executor import apply
from .._core.op_registry import register_op
from ._helper import tensor_method


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, numbers.Integral):
        return int(axis)
    if hasattr(axis, "tolist"):
        axis = axis.tolist()
    return tuple(int(a) for a in axis)


def _def_reduce(name, jfn):
    register_op(name, lambda x, axis, keepdim, _f=jfn: _f(
        x, axis=axis, keepdims=keepdim))

    def wrapper(x, axis=None, keepdim=False, name=None, _op=name):
        return apply(_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))
    wrapper.__name__ = name
    from ._helper import _TENSOR_METHODS
    _TENSOR_METHODS[name] = wrapper
    return wrapper


_sum_raw = _def_reduce("sum_", jnp.sum)
mean = _def_reduce("mean", jnp.mean)
max = _def_reduce("max", jnp.max)
min = _def_reduce("min", jnp.min)
amax = _def_reduce("amax", jnp.max)
amin = _def_reduce("amin", jnp.min)
prod = _def_reduce("prod", jnp.prod)
all = _def_reduce("all", jnp.all)
any = _def_reduce("any", jnp.any)
logsumexp_raw = _def_reduce("logsumexp",
                            __import__("jax").scipy.special.logsumexp)
nansum = _def_reduce("nansum", jnp.nansum)
nanmean = _def_reduce("nanmean", jnp.nanmean)


@tensor_method("sum")
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    out = _sum_raw(x, axis=axis, keepdim=keepdim)
    if dtype is not None:
        from .manipulation import cast
        out = cast(out, dtype)
    return out


def logsumexp(x, axis=None, keepdim=False, name=None):
    return logsumexp_raw(x, axis=axis, keepdim=keepdim)


register_op("std_", lambda x, axis, keepdim, ddof: jnp.std(
    x, axis=axis, keepdims=keepdim, ddof=ddof))
register_op("var_", lambda x, axis, keepdim, ddof: jnp.var(
    x, axis=axis, keepdims=keepdim, ddof=ddof))


@tensor_method("std")
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("std_", x, axis=_norm_axis(axis), keepdim=bool(keepdim),
                 ddof=1 if unbiased else 0)


@tensor_method("var")
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("var_", x, axis=_norm_axis(axis), keepdim=bool(keepdim),
                 ddof=1 if unbiased else 0)


register_op("median_", lambda x, axis, keepdim: jnp.median(
    x, axis=axis, keepdims=keepdim))


@tensor_method("median")
def median(x, axis=None, keepdim=False, name=None):
    return apply("median_", x, axis=_norm_axis(axis), keepdim=bool(keepdim))


register_op("quantile_", lambda x, q, axis, keepdim: jnp.quantile(
    x, jnp.asarray(q), axis=axis, keepdims=keepdim))


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply("quantile_", x, q=q, axis=_norm_axis(axis),
                 keepdim=bool(keepdim))


register_op("count_nonzero_", lambda x, axis, keepdim: jnp.count_nonzero(
    x, axis=axis, keepdims=keepdim).astype(jnp.int64))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply("count_nonzero_", x, axis=_norm_axis(axis),
                 keepdim=bool(keepdim))
