"""Tensor creation ops.

Analog of python/paddle/tensor/creation.py + random.py over the reference's
full/empty/arange/gaussian phi kernels. Creation runs directly on device via
jnp; random ops consume the global threefry key (paddle_tpu._core.random).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .._core import dtype as dtypes_mod
from .._core import random as rnd
from .._core.executor import apply
from .._core.op_registry import register_op
from .._core.tensor import Tensor, to_tensor
from ._helper import tensor_method

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "empty", "empty_like", "arange", "linspace", "logspace",
    "eye", "diag", "diagflat", "meshgrid", "tril", "triu", "assign",
    "clone", "numel", "rand", "randn", "uniform", "normal", "standard_normal",
    "randint", "randint_like", "randperm", "bernoulli", "multinomial",
    "ones_like", "tril_indices", "triu_indices", "complex",
]


def _np_dtype(dtype, default="float32"):
    return dtypes_mod.to_np(dtype if dtype is not None else default)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(tuple(shape), _np_dtype(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(tuple(shape), _np_dtype(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        val = jnp.full(tuple(shape), fill_value)
        if val.dtype == jnp.float64:
            val = val.astype(jnp.float32)
        return Tensor(val)
    return Tensor(jnp.full(tuple(shape), fill_value, _np_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    d = _np_dtype(dtype) if dtype is not None else x._value.dtype
    return Tensor(jnp.zeros(x._value.shape, d))


def ones_like(x, dtype=None, name=None):
    d = _np_dtype(dtype) if dtype is not None else x._value.dtype
    return Tensor(jnp.ones(x._value.shape, d))


def full_like(x, fill_value, dtype=None, name=None):
    d = _np_dtype(dtype) if dtype is not None else x._value.dtype
    return Tensor(jnp.full(x._value.shape, fill_value, d))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange with Tensor bounds: pass python scalars")
    d = dtypes_mod.to_np(dtype) if dtype is not None else None
    val = jnp.arange(start, end, step, dtype=d)
    if d is None and val.dtype == jnp.float64:
        val = val.astype(jnp.float32)
    return Tensor(val)


def linspace(start, stop, num, dtype=None, name=None):
    from .._core.executor import apply
    return apply("linspace_k", start=float(start), stop=float(stop),
                 num=int(num), dtype=str(jnp.dtype(_np_dtype(dtype))))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    from .._core.executor import apply
    return apply("logspace_k", start=float(start), stop=float(stop),
                 num=int(num), base=float(base),
                 dtype=str(jnp.dtype(_np_dtype(dtype))))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    from .._core.executor import apply
    return apply("eye_k", n=int(num_rows),
                 m=int(num_columns if num_columns is not None
                       else num_rows),
                 dtype=str(jnp.dtype(_np_dtype(dtype))))


def _diag_k(x, offset, padding_value):
    if x.ndim == 1:
        out = jnp.diag(x, k=offset)
        if padding_value != 0:
            mask = jnp.diag(jnp.ones_like(x, dtype=bool), k=offset)
            out = jnp.where(mask, out, padding_value)
        return out
    return jnp.diag(x, k=offset)


register_op("diag_", _diag_k)
register_op("diagflat_", lambda x, offset: jnp.diagflat(x, k=offset))


def diag(x, offset=0, padding_value=0, name=None):
    return apply("diag_", x, offset=offset, padding_value=padding_value)


def diagflat(x, offset=0, name=None):
    return apply("diagflat_", x, offset=offset)


def meshgrid(*args, **kwargs):
    arrays = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) \
        else args
    outs = jnp.meshgrid(*[a._value for a in arrays], indexing="ij")
    return [Tensor(o) for o in outs]


register_op("tril", lambda x, diagonal: jnp.tril(x, k=diagonal))
register_op("triu", lambda x, diagonal: jnp.triu(x, k=diagonal))


@tensor_method("tril")
def tril(x, diagonal=0, name=None):
    return apply("tril", x, diagonal=int(diagonal))


@tensor_method("triu")
def triu(x, diagonal=0, name=None):
    return apply("triu", x, diagonal=int(diagonal))


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_np_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_np_dtype(dtype)))


register_op("assign", lambda x: x + jnp.zeros((), x.dtype) if jnp.issubdtype(
    x.dtype, jnp.inexact) else jnp.array(x))


@tensor_method("clone")
def assign(x, output=None, name=None):
    if not isinstance(x, Tensor):
        x = to_tensor(x)
    out = apply("assign", x)
    if output is not None:
        output._adopt(out)
        return output
    return out


clone = assign


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def complex(real, imag, name=None):
    return apply("complex_make", real, imag)


register_op("complex_make", lambda r, i: jax.lax.complex(r, i))


# ------------------------------------------------------------------ random

def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(rnd.next_key(), tuple(shape),
                                     _np_dtype(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(rnd.next_key(), tuple(shape),
                                    _np_dtype(dtype)))


standard_normal = randn


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    from .._core.executor import apply
    from .manipulation import cast
    key = rnd.next_key() if not seed else jax.random.PRNGKey(seed)
    out = apply("uniform_k", Tensor(key), shape=tuple(int(s) for s in shape),
                lo=float(min), hi=float(max))
    dt = _np_dtype(dtype)
    return cast(out, str(np.dtype(dt))) if np.dtype(dt) != np.float32 \
        else out


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(
            np.shape(m), np.shape(s)) if shape is None else tuple(shape)
        return Tensor(
            jax.random.normal(rnd.next_key(), out_shape) * s + m)
    shape = shape if shape is not None else []
    return Tensor(jax.random.normal(rnd.next_key(), tuple(shape))
                  * std + mean)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    from .._core.executor import apply
    from .manipulation import cast
    if high is None:
        low, high = 0, low
    out = apply("randint_k", Tensor(rnd.next_key()), low=int(low),
                high=int(high), shape=tuple(int(s) for s in shape))
    dt = np.dtype(_np_dtype(dtype, "int64"))
    return cast(out, str(dt)) if dt != np.int64 else out


def randint_like(x, low=0, high=None, dtype=None, name=None):
    d = dtype if dtype is not None else x.dtype
    return randint(low, high, x.shape, d)


def randperm(n, dtype="int64", name=None):
    from .._core.executor import apply
    from .manipulation import cast
    out = apply("randperm_k", Tensor(rnd.next_key()), n=int(n))
    dt = np.dtype(_np_dtype(dtype, "int64"))
    return cast(out, str(dt)) if dt != np.int64 else out


def bernoulli(x, name=None):
    from .._core.executor import apply
    return apply("bernoulli_k", x, Tensor(rnd.next_key()))


def multinomial(x, num_samples=1, replacement=False, name=None):
    from .._core.executor import apply
    from .manipulation import cast
    out = apply("multinomial_k", x, Tensor(rnd.next_key()),
                num=int(num_samples), replacement=bool(replacement))
    return cast(out, "int64")
