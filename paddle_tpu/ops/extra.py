"""Long-tail tensor ops (python/paddle/tensor/math.py / manipulation.py
coverage completion): kernels are jnp calls compiled by XLA."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .._core.executor import apply
from .._core.op_registry import register_op
from ._helper import def_binary, def_unary, tensor_method

angle = def_unary("angle", jnp.angle)
copysign = def_binary("copysign", jnp.copysign)
ldexp = def_binary("ldexp", lambda x, y: jnp.ldexp(x, y.astype(jnp.int32)))
kron = def_binary("kron", jnp.kron)
polar = def_binary("polar", lambda abs_, angle_:
                   abs_ * jnp.exp(1j * angle_.astype(jnp.float32)))

register_op("bincount",
            lambda x, weights=None, length=1:
            jnp.bincount(x.astype(jnp.int32), weights, length=length))
register_op("diff", lambda x, n=1, axis=-1: jnp.diff(x, n=n, axis=axis))
register_op("rot90", lambda x, k=1, axes=(0, 1):
            jnp.rot90(x, k=k, axes=tuple(axes)))
register_op("vander", lambda x, n=None, increasing=False:
            jnp.vander(x, N=n, increasing=increasing))
register_op("trapezoid", lambda y, x=None, dx=1.0, axis=-1:
            jax.scipy.integrate.trapezoid(y, x=x, dx=dx, axis=axis))
register_op("nanmedian", lambda x, axis=None, keepdim=False:
            jnp.nanmedian(x, axis=axis, keepdims=keepdim))
register_op("histogram_op", lambda x, bins=100, min=0.0, max=0.0:
            jnp.histogram(
                x, bins=bins,
                range=None if min == 0.0 and max == 0.0
                else (min, max))[0])
register_op("take_op", lambda x, index, mode="raise":
            jnp.take(x.reshape(-1), index.astype(jnp.int32),
                     mode="clip" if mode == "clip" else "wrap"))
register_op("tensordot_op", lambda x, y, axes=2:
            jnp.tensordot(x, y, axes=axes))
register_op("renorm_op", lambda x, p=2.0, axis=0, max_norm=1.0:
            _renorm(x, p, axis, max_norm))
register_op("frexp", lambda x: tuple(jnp.frexp(x)), multi_output=True)
register_op("select_scatter_op", lambda x, values, axis=0, index=0:
            _select_scatter(x, values, axis, index))
register_op("unfold_op", lambda x, axis=0, size=1, step=1:
            _unfold(x, axis, size, step))


def _renorm(x, p, axis, max_norm):
    axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


def _select_scatter(x, values, axis, index):
    idx = [slice(None)] * x.ndim
    idx[axis % x.ndim] = index
    return x.at[tuple(idx)].set(values)


def _unfold(x, axis, size, step):
    """paddle unfold: [..., n_windows, ..., size] with window content as
    the LAST dim."""
    axis = axis % x.ndim
    windows = jnp.stack(
        [jax.lax.dynamic_slice_in_dim(x, s, size, axis)
         for s in range(0, x.shape[axis] - size + 1, step)],
        axis=axis)
    return jnp.moveaxis(windows, axis + 1, -1)


@tensor_method("bincount")
def bincount(x, weights=None, minlength=0, name=None):
    # output length is data-dependent (max(x)+1) — resolved host-side so
    # the kernel stays static-shaped for XLA (SURVEY §7 dynamic shapes)
    import numpy as np
    import jax as _jax
    val = x._value if hasattr(x, "_value") else x
    if isinstance(val, _jax.core.Tracer):
        if minlength <= 0:
            raise ValueError("bincount under trace needs minlength (its "
                             "output length is data-dependent)")
        length = minlength
    else:
        mx = int(np.asarray(val).max()) + 1 if val.size else 0
        length = max(mx, minlength, 1)
    return apply("bincount", x, weights, length=length)


@tensor_method("diff")
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return apply("diff", x, n=n, axis=axis)


@tensor_method("rot90")
def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", x, k=k, axes=tuple(axes))


def vander(x, n=None, increasing=False, name=None):
    return apply("vander", x, n=n, increasing=increasing)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return apply("trapezoid", y, x, axis=axis)
    return apply("trapezoid", y, dx=1.0 if dx is None else dx, axis=axis)


@tensor_method("nanmedian")
def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply("nanmedian", x, axis=axis, keepdim=keepdim)


@tensor_method("histogram")
def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    return apply("histogram_op", input, bins=bins, min=float(min),
                 max=float(max))


@tensor_method("take")
def take(x, index, mode="raise", name=None):
    return apply("take_op", x, index, mode=mode)


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a
                     for a in axes)
    return apply("tensordot_op", x, y, axes=axes)


@tensor_method("renorm")
def renorm(x, p, axis, max_norm, name=None):
    return apply("renorm_op", x, p=float(p), axis=axis,
                 max_norm=float(max_norm))


@tensor_method("frexp")
def frexp(x, name=None):
    return apply("frexp", x)


def select_scatter(x, values, axis, index, name=None):
    return apply("select_scatter_op", x, values, axis=axis, index=index)


@tensor_method("unfold")
def unfold(x, axis, size, step, name=None):
    return apply("unfold_op", x, axis=axis, size=size, step=step)


def _accuracy_check_kernel(x, y, fn_name, rtol, atol, equal_nan):
    return jnp.all(jnp.isclose(x, y, rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


# Per-tensor numeric compare op (reference ops.yaml:31 accuracy_check):
# the primitive under the acc-align parity harnesses.
register_op("accuracy_check", _accuracy_check_kernel)


def _quant_linear_i8(x, wq, w_scale, act_scale, qmax):
    """Dynamic-activation int8 linear: quantize x, int8 x int8 matmul
    with an int32 accumulator (the MXU's native int8 path), dequantize
    by act_scale * per-channel w_scale. w_scale is a tensor INPUT, not
    an attr — every layer shares one compiled executable."""
    from jax import lax
    xq = jnp.clip(jnp.round(x / act_scale), -qmax - 1, qmax).astype(
        jnp.int8)
    acc = lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (act_scale * w_scale)


register_op("quant_linear_i8", _quant_linear_i8)
