"""Shape / layout manipulation ops.

Analog of python/paddle/tensor/manipulation.py over the reference's
reshape/transpose/concat/split/pad phi kernels and the stride/view kernel
family (paddle/phi/kernels/stride/). XLA has no aliasing views, so "view"
ops are pure reshapes/slices the compiler folds away (SURVEY.md §7 hard
parts: stride ops -> copy-on-write semantics).
"""
from __future__ import annotations

import numbers

import jax
import jax.numpy as jnp
import numpy as np

from .._core import dtype as dtypes_mod
from .._core.executor import apply
from .._core.op_registry import register_op
from .._core.tensor import Tensor
from ._helper import tensor_method


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    return tuple(int(s) for s in shape)


register_op("reshape", lambda x, shape: jnp.reshape(x, shape))


@tensor_method("reshape")
def reshape(x, shape, name=None):
    return apply("reshape", x, shape=_norm_shape(shape))


register_op("cast", lambda x, dtype: x.astype(dtype))


@tensor_method("cast")
def cast(x, dtype):
    d = dtypes_mod.to_np(dtype)
    # the no-op check reads the recorded aval, not ._value: a cast
    # decision must not force a pending lazy segment to materialize
    if x._meta_aval().dtype == d:
        return x
    return apply("cast", x, dtype=str(d) if d != jnp.bfloat16 else "bfloat16")


@tensor_method("astype")
def astype(x, dtype):
    return cast(x, dtype)


register_op("transpose", lambda x, perm: jnp.transpose(x, perm))


@tensor_method("transpose")
def transpose(x, perm, name=None):
    return apply("transpose", x, perm=tuple(int(p) for p in perm))


@tensor_method("t")
def t(x, name=None):
    if x.ndim < 2:
        return x
    if x.ndim != 2:
        raise ValueError("t() expects 0/1/2-D tensor")
    return transpose(x, [1, 0])


register_op("flatten_", lambda x, start, stop: jnp.reshape(
    x, x.shape[:start] + (-1,) + x.shape[stop + 1:]))


@tensor_method("flatten")
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = max(x.ndim, 1)
    start = start_axis % nd
    stop = stop_axis % nd
    return apply("flatten_", x, start=start, stop=stop)


register_op("squeeze", lambda x, axes: jnp.squeeze(
    x, axis=axes if axes else None))


@tensor_method("squeeze")
def squeeze(x, axis=None, name=None):
    if axis is None:
        axes = ()
    else:
        axes = (axis,) if isinstance(axis, numbers.Integral) else tuple(axis)
        axes = tuple(a % x.ndim for a in axes)
        axes = tuple(a for a in axes if x.shape[a] == 1)
    return apply("squeeze", x, axes=axes)


register_op("unsqueeze", lambda x, axes: jnp.expand_dims(x, axes))


@tensor_method("unsqueeze")
def unsqueeze(x, axis, name=None):
    axes = (axis,) if isinstance(axis, numbers.Integral) else tuple(axis)
    return apply("unsqueeze", x, axes=axes)


def _concat_kernel(*xs, axis):
    return jnp.concatenate(xs, axis=axis)


register_op("concat_", _concat_kernel)


def concat(x, axis=0, name=None):
    xs = list(x)
    return apply("concat_", *xs, axis=int(axis))


def _stack_kernel(*xs, axis):
    return jnp.stack(xs, axis=axis)


register_op("stack_", _stack_kernel)


def stack(x, axis=0, name=None):
    return apply("stack_", *list(x), axis=int(axis))


def _split_kernel(x, indices, axis):
    return tuple(jnp.split(x, indices, axis=axis))


register_op("split_", _split_kernel, multi_output=True)


@tensor_method("split")
def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis) % x.ndim
    dim = x.shape[axis]
    if isinstance(num_or_sections, numbers.Integral):
        n = int(num_or_sections)
        if dim % n != 0:
            raise ValueError(f"dim {dim} not divisible by {n}")
        indices = tuple((dim // n) * i for i in range(1, n))
    else:
        sections = [dim - sum(s for s in num_or_sections if s >= 0)
                    if s < 0 else s for s in num_or_sections]
        cum = np.cumsum(sections)[:-1]
        indices = tuple(int(c) for c in cum)
    outs = apply("split_", x, indices=indices, axis=axis)
    return list(outs)


@tensor_method("chunk")
def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def _unbind_kernel(x, axis):
    return tuple(jnp.moveaxis(x, axis, 0))


register_op("unbind_", _unbind_kernel, multi_output=True)


@tensor_method("unbind")
def unbind(x, axis=0):
    return list(apply("unbind_", x, axis=int(axis) % x.ndim))


register_op("tile", lambda x, reps: jnp.tile(x, reps))


@tensor_method("tile")
def tile(x, repeat_times, name=None):
    return apply("tile", x, reps=_norm_shape(repeat_times))


def _expand_kernel(x, shape):
    return jnp.broadcast_to(x, shape)


register_op("expand", _expand_kernel)


@tensor_method("expand")
def expand(x, shape, name=None):
    shape = list(_norm_shape(shape))
    # paddle semantics: -1 keeps the original dim
    nd_off = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1:
            shape[i] = x.shape[i - nd_off]
    return apply("expand", x, shape=tuple(shape))


@tensor_method("expand_as")
def expand_as(x, y, name=None):
    return apply("expand", x, shape=tuple(y.shape))


@tensor_method("broadcast_to")
def broadcast_to(x, shape, name=None):
    return apply("expand", x, shape=_norm_shape(shape))


def broadcast_tensors(inputs, name=None):
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in inputs])
    return [apply("expand", t, shape=shape) for t in inputs]


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


register_op("flip", lambda x, axes: jnp.flip(x, axes))


@tensor_method("flip")
def flip(x, axis, name=None):
    axes = (axis,) if isinstance(axis, numbers.Integral) else tuple(axis)
    return apply("flip", x, axes=axes)


register_op("roll_", lambda x, shifts, axes: jnp.roll(x, shifts, axes))


@tensor_method("roll")
def roll(x, shifts, axis=None, name=None):
    if axis is None:
        flat = flatten(x)
        out = apply("roll_", flat, shifts=shifts, axes=0)
        return reshape(out, x.shape)
    return apply("roll_", x, shifts=shifts, axes=axis)


register_op("repeat_interleave_",
            lambda x, repeats, axis: jnp.repeat(x, repeats, axis=axis))


@tensor_method("repeat_interleave")
def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = tuple(repeats.tolist())
    if axis is None:
        x = flatten(x)
        axis = 0
    return apply("repeat_interleave_", x, repeats=repeats,
                 axis=int(axis))


def _pad_kernel(x, pad_width, mode, value):
    if mode == "constant":
        return jnp.pad(x, pad_width, mode="constant", constant_values=value)
    return jnp.pad(x, pad_width, mode=mode)


register_op("pad_", _pad_kernel)


def pad(x, pad, mode="constant", value=0.0, data_format=None, name=None):
    """paddle.nn.functional.pad-compatible: `pad` is per-dim [lo, hi] pairs,
    innermost-last ordering when given flat (like paddle/torch)."""
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = list(pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        width = tuple((int(pad[2 * i]), int(pad[2 * i + 1]))
                      for i in range(nd))
    else:
        k = len(pad) // 2
        width = [(0, 0)] * (nd - k)
        for i in range(k):
            # flat list pads last dims, reversed pair order (torch/paddle)
            lo, hi = pad[2 * i], pad[2 * i + 1]
            width.append((int(lo), int(hi)))
        # paddle pads from the last dimension backwards
        head = [(0, 0)] * (nd - k)
        tail = [(int(pad[2 * i]), int(pad[2 * i + 1]))
                for i in range(k - 1, -1, -1)]
        width = tuple(head + tail)
    mode = {"constant": "constant", "reflect": "reflect",
            "replicate": "edge", "circular": "wrap"}[mode]
    return apply("pad_", x, pad_width=width, mode=mode, value=float(value))


register_op("diagonal_", lambda x, offset, axis1, axis2: jnp.diagonal(
    x, offset=offset, axis1=axis1, axis2=axis2))


@tensor_method("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("diagonal_", x, offset=int(offset), axis1=int(axis1),
                 axis2=int(axis2))


register_op("masked_fill_", lambda x, mask, v: jnp.where(mask, v, x))


@tensor_method("masked_fill")
def masked_fill(x, mask, value, name=None):
    return apply("masked_fill_", x, mask, value)


register_op("moveaxis_", lambda x, src, dst: jnp.moveaxis(x, src, dst))


@tensor_method("moveaxis")
def moveaxis(x, source, destination, name=None):
    return apply("moveaxis_", x, src=source, dst=destination)


register_op("as_real", lambda x: jnp.stack([jnp.real(x), jnp.imag(x)], -1))
register_op("as_complex", lambda x: jax.lax.complex(x[..., 0], x[..., 1]))


def as_real(x, name=None):
    return apply("as_real", x)


def as_complex(x, name=None):
    return apply("as_complex", x)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards
    val = input._value
    out = jnp.where((val // size) == shard_id, val % size, ignore_value)
    return Tensor(out)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


view_as = expand_as
