"""Elementwise math / comparison / logical ops.

Analog of the reference's elementwise + activation phi kernels
(paddle/phi/kernels/elementwise_*.h, activation_kernel.h) and the python
surface python/paddle/tensor/math.py. Kernel bodies are jnp/lax calls that
XLA fuses on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .._core import dtype as dtypes_mod
from .._core.executor import apply
from .._core.op_registry import register_op
from ._helper import def_unary, def_binary, tensor_method

# --------------------------------------------------------------- unary
exp = def_unary("exp", jnp.exp)
expm1 = def_unary("expm1", jnp.expm1)
log = def_unary("log", jnp.log)
log2 = def_unary("log2", jnp.log2)
log10 = def_unary("log10", jnp.log10)
log1p = def_unary("log1p", jnp.log1p)
sqrt = def_unary("sqrt", jnp.sqrt)
rsqrt = def_unary("rsqrt", lax.rsqrt)
abs = def_unary("abs", jnp.abs)
absolute = abs
neg = def_unary("neg", jnp.negative)
negative = neg
sign = def_unary("sign", jnp.sign)
floor = def_unary("floor", jnp.floor)
ceil = def_unary("ceil", jnp.ceil)
round = def_unary("round", jnp.round)
trunc = def_unary("trunc", jnp.trunc)
frac = def_unary("frac", lambda x: x - jnp.trunc(x))
sin = def_unary("sin", jnp.sin)
cos = def_unary("cos", jnp.cos)
tan = def_unary("tan", jnp.tan)
asin = def_unary("asin", jnp.arcsin)
acos = def_unary("acos", jnp.arccos)
atan = def_unary("atan", jnp.arctan)
sinh = def_unary("sinh", jnp.sinh)
cosh = def_unary("cosh", jnp.cosh)
tanh = def_unary("tanh", jnp.tanh)
asinh = def_unary("asinh", jnp.arcsinh)
acosh = def_unary("acosh", jnp.arccosh)
atanh = def_unary("atanh", jnp.arctanh)
erf = def_unary("erf", jax.scipy.special.erf)
erfinv = def_unary("erfinv", jax.scipy.special.erfinv)
sigmoid = def_unary("sigmoid", jax.nn.sigmoid)
square = def_unary("square", jnp.square)
reciprocal = def_unary("reciprocal", jnp.reciprocal)
logit = def_unary("logit", jax.scipy.special.logit)
digamma = def_unary("digamma", jax.scipy.special.digamma)
lgamma = def_unary("lgamma", jax.scipy.special.gammaln)
conj = def_unary("conj", jnp.conj)
real = def_unary("real", jnp.real)
imag = def_unary("imag", jnp.imag)
isnan = def_unary("isnan", jnp.isnan)
isinf = def_unary("isinf", jnp.isinf)
isfinite = def_unary("isfinite", jnp.isfinite)

# --------------------------------------------------------------- binary
add = def_binary("add", jnp.add)
subtract = def_binary("subtract", jnp.subtract)
multiply = def_binary("multiply", jnp.multiply)
divide = def_binary("divide", jnp.true_divide)
floor_divide = def_binary("floor_divide", jnp.floor_divide)
mod = def_binary("mod", jnp.mod)
remainder = mod
floor_mod = mod
pow = def_binary("pow", jnp.power)
maximum = def_binary("maximum", jnp.maximum)
minimum = def_binary("minimum", jnp.minimum)
fmax = def_binary("fmax", jnp.fmax)
fmin = def_binary("fmin", jnp.fmin)
atan2 = def_binary("atan2", jnp.arctan2)
logaddexp = def_binary("logaddexp", jnp.logaddexp)
heaviside = def_binary("heaviside", jnp.heaviside)
hypot = def_binary("hypot", lambda x, y: jnp.sqrt(x * x + y * y))
nextafter = def_binary("nextafter", jnp.nextafter)
gcd = def_binary("gcd", jnp.gcd)
lcm = def_binary("lcm", jnp.lcm)

# --------------------------------------------------------------- comparison
equal = def_binary("equal", lambda x, y: jnp.equal(x, y))
not_equal = def_binary("not_equal", jnp.not_equal)
greater_than = def_binary("greater_than", jnp.greater)
greater_equal = def_binary("greater_equal", jnp.greater_equal)
less_than = def_binary("less_than", jnp.less)
less_equal = def_binary("less_equal", jnp.less_equal)

# --------------------------------------------------------------- logical
logical_and = def_binary("logical_and",
                         lambda x, y: jnp.logical_and(x, y))
logical_or = def_binary("logical_or", lambda x, y: jnp.logical_or(x, y))
logical_xor = def_binary("logical_xor", lambda x, y: jnp.logical_xor(x, y))
logical_not = def_unary("logical_not", jnp.logical_not)
bitwise_and = def_binary("bitwise_and", jnp.bitwise_and)
bitwise_or = def_binary("bitwise_or", jnp.bitwise_or)
bitwise_xor = def_binary("bitwise_xor", jnp.bitwise_xor)
bitwise_not = def_unary("bitwise_not", jnp.bitwise_not)

# --------------------------------------------------------------- scale et al
register_op("scale", lambda x, scale, bias, bias_after_scale:
            x * scale + bias if bias_after_scale else (x + bias) * scale)


@tensor_method("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = apply("scale", x, scale=float(scale), bias=float(bias),
                bias_after_scale=bool(bias_after_scale))
    return out


register_op("clip", lambda x, lo, hi: jnp.clip(
    x, None if lo is None else lo, None if hi is None else hi))


@tensor_method("clip")
def clip(x, min=None, max=None, name=None):
    return apply("clip", x, min, max)


register_op("lerp", lambda x, y, w: x + w * (y - x))


@tensor_method("lerp")
def lerp(x, y, weight, name=None):
    return apply("lerp", x, y, weight)


def _cumsum_kernel(x, axis, reverse, dtype):
    if dtype is not None:
        x = x.astype(dtype)
    if reverse:
        x = jnp.flip(x, axis=axis)
    out = jnp.cumsum(x, axis=axis)
    return jnp.flip(out, axis=axis) if reverse else out


register_op("cumsum_", _cumsum_kernel)


@tensor_method("cumsum")
def cumsum(x, axis=None, dtype=None, name=None):
    if axis is None:
        from .manipulation import flatten
        x = flatten(x)
        axis = 0
    d = None if dtype is None else str(dtypes_mod.to_np(dtype))
    return apply("cumsum_", x, axis=int(axis), reverse=False, dtype=d)


register_op("cumprod_", lambda x, axis: jnp.cumprod(x, axis=axis))


@tensor_method("cumprod")
def cumprod(x, dim=None, dtype=None, name=None):
    out = apply("cumprod_", x, axis=int(dim))
    if dtype is not None:
        from .manipulation import cast
        out = cast(out, dtype)
    return out


register_op("logcumsumexp_",
            lambda x, axis: jax.lax.associative_scan(jnp.logaddexp, x,
                                                     axis=axis))


@tensor_method("logcumsumexp")
def logcumsumexp(x, axis=None, dtype=None, name=None):
    if axis is None:
        from .manipulation import flatten
        x = flatten(x)
        axis = 0
    return apply("logcumsumexp_", x, axis=int(axis))


def increment(x, value=1.0, name=None):
    return x._adopt(add(x, value))


register_op("stanh", lambda x, scale_a, scale_b: scale_b * jnp.tanh(
    scale_a * x))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", x, scale_a=float(scale_a), scale_b=float(scale_b))


register_op("rsqrt_grad_friendly", lambda x: lax.rsqrt(x))

register_op("multiply_no_broadcast", jnp.multiply)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("allclose_k", x, y, rtol=float(rtol), atol=float(atol),
                 equal_nan=bool(equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("isclose_k", x, y, rtol=float(rtol), atol=float(atol),
                 equal_nan=bool(equal_nan))


def equal_all(x, y, name=None):
    return apply("equal_all_k", x, y)


register_op("nan_to_num", lambda x, nan, posinf, neginf: jnp.nan_to_num(
    x, nan=nan, posinf=posinf, neginf=neginf))


@tensor_method("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply("nan_to_num", x, nan=float(nan), posinf=posinf,
                 neginf=neginf)
