"""paddle_tpu.ops — the op surface, re-exported into the top-level package.

Also monkey-patches Tensor with methods and python operators (the analog of
python/paddle monkey-patching Tensor methods onto the pybind eager tensor).
"""
from __future__ import annotations

from .._core.tensor import Tensor, to_tensor
from . import moe  # noqa: F401  (registers moe ops)
from . import extra  # noqa: F401
from .extra import (angle, bincount, copysign, diff, frexp, histogram,  # noqa: F401
                    kron, ldexp, nanmedian, polar, renorm, rot90,
                    select_scatter, take, tensordot, trapezoid, unfold,
                    vander)
from . import _helper, creation, indexing, linalg, manipulation, math, \
    reduction, search  # noqa: F401
from . import math_ext  # noqa: F401
from . import parity  # noqa: F401  (reference-parity op batch)
from .parity import (fused_bias_act, fused_dropout_add,  # noqa: F401
                     fused_softmax_mask,
                     fused_softmax_mask_upper_triangle,
                     fused_gemm_epilogue, skip_layernorm,
                     fused_bias_dropout_residual_layer_norm,
                     fused_linear_param_grad_add, as_strided, view_dtype,
                     view_slice, trans_layout, index_select_strided,
                     fill_diagonal_tensor)
from .math_ext import (addmm, baddbmm, cummax, cummin, i0, i0e, i1,  # noqa: F401
                       i1e, gammaln, polygamma, gammainc, gammaincc, dist,
                       cholesky_solve, svdvals, diag_embed, fill_diagonal,
                       fill_diagonal_, multiplex, slice,
                       strided_slice, crop, unstack, reverse, is_empty,
                       bitwise_left_shift, bitwise_right_shift, reduce_as,
                       clip_by_norm, squared_l2_norm, l1_norm, poisson,
                       binomial, standard_gamma, dirichlet, exponential_)

from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403

from .math import (add, subtract, multiply, divide, floor_divide, mod, pow,
                   neg, abs, equal, not_equal, greater_than, greater_equal,
                   less_than, less_equal, logical_and, logical_or,
                   logical_not, bitwise_and, bitwise_or, bitwise_xor,
                   bitwise_not)
from .linalg import matmul


def _adopt(self, out):
    """Adopt a functional result as this tensor's new value (in-place ops).

    The version bump invalidates OTHER nodes that saved this tensor (the
    tensor_wrapper.h inplace check in autograd), but the node that
    produced ``out`` itself recorded the pre-mutation value — sync its
    recorded version so the op's own backward stays valid."""
    val = out._value      # materializes first (may flush a window)
    # notify every still-open capture context BEFORE the swap: a lower
    # context on the guard stack may still map this tensor to its old
    # snapshot, and a record after the swap would silently read stale
    # data (the inplace_race checker's bug class)
    from .._core import lazy as _lazy
    _lazy.note_inplace(self)
    self._value = val
    self._autograd_meta = out._autograd_meta
    self._stop_gradient = out._stop_gradient
    self._inplace_version += 1
    node = self._autograd_meta.grad_node
    if node is not None and node.saved_versions is not None \
            and node.in_refs is not None:
        node.saved_versions = tuple(
            self._inplace_version
            if (ref is not None and ref() is self) else v
            for ref, v in zip(node.in_refs, node.saved_versions))
    return self


Tensor._adopt = _adopt


# ------------------------------------------------------------- operators
def _rbin(fn):
    def op(self, other):
        other = other if isinstance(other, Tensor) else to_tensor(other)
        return fn(other, self)
    return op


# the hot arithmetic dunders bind the op wrappers DIRECTLY (functions
# are descriptors, so `x + y` calls add(x, y) with no lambda frame in
# between — one stack frame per dispatched op on the record hot path)
Tensor.__add__ = add
Tensor.__radd__ = add
Tensor.__sub__ = subtract
Tensor.__rsub__ = _rbin(subtract)
Tensor.__mul__ = multiply
Tensor.__rmul__ = multiply
Tensor.__truediv__ = divide
Tensor.__rtruediv__ = _rbin(divide)
Tensor.__floordiv__ = lambda s, o: floor_divide(s, o)
Tensor.__rfloordiv__ = _rbin(floor_divide)
Tensor.__mod__ = lambda s, o: mod(s, o)
Tensor.__rmod__ = _rbin(mod)
Tensor.__pow__ = lambda s, o: pow(s, o)
Tensor.__rpow__ = _rbin(pow)
Tensor.__neg__ = lambda s: neg(s)
Tensor.__abs__ = lambda s: abs(s)
Tensor.__matmul__ = lambda s, o: matmul(s, o)
Tensor.__rmatmul__ = _rbin(matmul)
Tensor.__eq__ = lambda s, o: equal(s, o)
Tensor.__ne__ = lambda s, o: not_equal(s, o)
Tensor.__gt__ = lambda s, o: greater_than(s, o)
Tensor.__ge__ = lambda s, o: greater_equal(s, o)
Tensor.__lt__ = lambda s, o: less_than(s, o)
Tensor.__le__ = lambda s, o: less_equal(s, o)
Tensor.__and__ = lambda s, o: (logical_and(s, o) if s.dtype == "bool"
                               else bitwise_and(s, o))
Tensor.__or__ = lambda s, o: (logical_or(s, o) if s.dtype == "bool"
                              else bitwise_or(s, o))
Tensor.__xor__ = lambda s, o: (logical_xor(s, o) if s.dtype == "bool"
                               else bitwise_xor(s, o))
Tensor.__invert__ = lambda s: (logical_not(s) if s.dtype == "bool"
                               else bitwise_not(s))
Tensor.__hash__ = lambda s: id(s)

from .math import logical_xor  # noqa: E402

# in-place arithmetic (paddle's add_ / subtract_ / scale_ family)
for _name, _fn in [("add_", add), ("subtract_", subtract),
                   ("multiply_", multiply), ("divide_", divide),
                   ("clip_", math.clip), ("scale_", math.scale),
                   ("exp_", math.exp), ("sqrt_", math.sqrt),
                   ("rsqrt_", math.rsqrt), ("floor_", math.floor),
                   ("ceil_", math.ceil), ("reciprocal_", math.reciprocal),
                   ("round_", math.round), ("abs_", math.abs),
                   ("tanh_", math.tanh),
                   ("squeeze_", manipulation.squeeze),
                   ("unsqueeze_", manipulation.unsqueeze),
                   ("reshape_", manipulation.reshape),
                   ("flatten_", manipulation.flatten)]:
    _helper.make_inplace(_fn, _name)


def _fill_(self, value):
    import jax.numpy as jnp
    # _replace_value_inplace (not a bare _value write): open capture
    # windows must be notified or later records reuse the stale snapshot
    return self._replace_value_inplace(
        jnp.full_like(self._value, value))


def _zero_(self):
    return _fill_(self, 0)


Tensor.fill_ = _fill_
Tensor.zero_ = _zero_

# attach all collected tensor methods
_helper.attach_tensor_methods()
indexing.install()

# `Tensor.item`/`numpy` etc. already defined on the class.
Tensor.mean = reduction.mean
Tensor.cpu = lambda s: s
Tensor.cuda = lambda s, *a, **k: s
Tensor.pin_memory = lambda s: s
