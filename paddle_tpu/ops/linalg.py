"""Linear algebra ops (python/paddle/tensor/linalg.py analog).

matmul is the MXU workhorse: precision is governed by
FLAGS_tpu_matmul_precision; keep operands bf16 for peak throughput.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .._core import flags
from .._core.executor import apply
from .._core.op_registry import register_op
from .._core.tensor import Tensor
from ._helper import tensor_method


def _precision():
    p = flags.flag_value("FLAGS_tpu_matmul_precision")
    return {"default": lax.Precision.DEFAULT, "high": lax.Precision.HIGH,
            "highest": lax.Precision.HIGHEST}.get(p, lax.Precision.DEFAULT)


def _matmul_kernel(x, y, transpose_x, transpose_y):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y, precision=_precision())


register_op("matmul", _matmul_kernel)


@tensor_method("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return apply("matmul", x, y, transpose_x=bool(transpose_x),
                 transpose_y=bool(transpose_y))


@tensor_method("mm")
def mm(x, y, name=None):
    return matmul(x, y)


@tensor_method("bmm")
def bmm(x, y, name=None):
    return matmul(x, y)


def mv(x, vec, name=None):
    return matmul(x, vec)


register_op("dot_", lambda x, y: jnp.sum(x * y, axis=-1))


@tensor_method("dot")
def dot(x, y, name=None):
    return apply("dot_", x, y)


register_op("outer_", lambda x, y: jnp.outer(x, y))


def outer(x, y, name=None):
    return apply("outer_", x, y)


def _einsum_kernel(*xs, equation):
    return jnp.einsum(equation, *xs, precision=_precision())


register_op("einsum_", _einsum_kernel)


def einsum(equation, *operands):
    return apply("einsum_", *operands, equation=equation)


def _norm_kernel(x, p, axis, keepdim):
    if p == "fro" or (p == 2 and axis is None):
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    if p == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim),
        1.0 / p)


register_op("p_norm_", _norm_kernel)


@tensor_method("norm")
def norm(x, p=None, axis=None, keepdim=False, name=None):
    if p is None:
        p = "fro" if axis is None else 2
    ax = axis
    if isinstance(ax, (list, tuple)):
        ax = tuple(int(a) for a in ax)
    elif ax is not None:
        ax = int(ax)
    return apply("p_norm_", x, p=p, axis=ax, keepdim=bool(keepdim))


vector_norm = norm


register_op("trace_", lambda x, offset, axis1, axis2: jnp.trace(
    x, offset=offset, axis1=axis1, axis2=axis2))


@tensor_method("trace")
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace_", x, offset=int(offset), axis1=int(axis1),
                 axis2=int(axis2))


register_op("cholesky_", lambda x, upper: (
    jnp.linalg.cholesky(x).swapaxes(-1, -2).conj() if upper
    else jnp.linalg.cholesky(x)))


@tensor_method("cholesky")
def cholesky(x, upper=False, name=None):
    return apply("cholesky_", x, upper=bool(upper))


register_op("inverse_", jnp.linalg.inv)


@tensor_method("inverse")
def inv(x, name=None):
    return apply("inverse_", x)


inverse = inv

register_op("solve_", jnp.linalg.solve)


def solve(x, y, name=None):
    return apply("solve_", x, y)


register_op("triangular_solve_",
            lambda x, y, upper, transpose, unitriangular:
            jax.scipy.linalg.solve_triangular(
                x, y, lower=not upper, trans=1 if transpose else 0,
                unit_diagonal=unitriangular))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return apply("triangular_solve_", x, y, upper=bool(upper),
                 transpose=bool(transpose), unitriangular=bool(unitriangular))


register_op("cross_", lambda x, y, axis: jnp.cross(x, y, axis=axis))


@tensor_method("cross")
def cross(x, y, axis=9, name=None):
    if axis == 9:
        axis = next((i for i, s in enumerate(x.shape) if s == 3), -1)
    return apply("cross_", x, y, axis=int(axis))


def _svd_kernel(x, full_matrices):
    return tuple(jnp.linalg.svd(x, full_matrices=full_matrices))


register_op("svd_", _svd_kernel, multi_output=True)


def svd(x, full_matrices=False, name=None):
    return apply("svd_", x, full_matrices=bool(full_matrices))


def _qr_kernel(x, mode):
    q, r = jnp.linalg.qr(x, mode=mode)
    return (q, r)


register_op("qr_", _qr_kernel, multi_output=True)


def qr(x, mode="reduced", name=None):
    if mode == "r":
        return apply("qr_", x, mode="reduced")[1]
    return apply("qr_", x, mode=mode)


register_op("det_", jnp.linalg.det)


def det(x, name=None):
    return apply("det_", x)


def _slogdet_kernel(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return (sign, logdet)


register_op("slogdet_", _slogdet_kernel, multi_output=True)


def slogdet(x, name=None):
    sign, logdet = apply("slogdet_", x)
    from .manipulation import stack
    return stack([sign, logdet], axis=0)


register_op("eigh_", lambda x, UPLO: tuple(jnp.linalg.eigh(
    x, symmetrize_input=True)), multi_output=True)


def eigh(x, UPLO="L", name=None):
    return apply("eigh_", x, UPLO=UPLO)


def eigvalsh(x, UPLO="L", name=None):
    return eigh(x, UPLO)[0]


register_op("pinv_", lambda x, rcond: jnp.linalg.pinv(x, rcond=rcond))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv_", x, rcond=float(rcond))


register_op("matrix_power_", lambda x, n: jnp.linalg.matrix_power(x, n))


def matrix_power(x, n, name=None):
    return apply("matrix_power_", x, n=int(n))


def multi_dot(tensors, name=None):
    out = tensors[0]
    for t in tensors[1:]:
        out = matmul(out, t)
    return out


def matrix_transpose(x, name=None):
    from .manipulation import transpose
    perm = list(range(x.ndim))
    perm[-1], perm[-2] = perm[-2], perm[-1]
    return transpose(x, perm)


def cdist(x, y, p=2.0, name=None):
    diff = x.unsqueeze(-2) - y.unsqueeze(-3)
    return norm(diff, p=p, axis=-1)


def householder_product(x, tau, name=None):
    """Q from Householder reflectors (geqrf layout), ops.yaml
    householder_product."""
    return apply("householder_product_", x, tau)


def corrcoef(x, rowvar=True, name=None):
    return Tensor(jnp.corrcoef(x._value, rowvar=rowvar))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return Tensor(jnp.cov(x._value, rowvar=rowvar,
                          ddof=1 if ddof else 0))
