"""Draft ops.yaml entries from the live registry (dev tool).

The schema (ops.yaml) is the system of record; this tool exists to keep
it honest when the registry grows: it reconstructs each op's declared
signature from three evidence sources, strongest first —

1. a dynamic call trace (JSON produced by the tests/trace_ops pytest
   plugin: exact tensor arity + attr names/types observed at run time),
2. a static AST scan of every literal `apply("op", ...)` call site in
   the package (tensor args are positional, attrs are keywords — the
   dispatch contract, _core/executor.py:27),
3. the kernel function's inspect.signature (params without defaults
   default to tensor inputs; defaulted params to attrs).

Entries already present in ops.yaml are preserved verbatim (they may
carry hand-written notes). New drafts sourced ONLY from (3) are marked
`# sig-only` for review.

Usage: python -m paddle_tpu.ops.yaml.bootstrap [--write]
"""
from __future__ import annotations

import ast
import inspect
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

_PKG = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_YAML = os.path.join(os.path.dirname(__file__), "ops.yaml")


def scan_call_sites(pkg_root: str = _PKG) -> Dict[str, List[Tuple]]:
    """op -> list of (npos, has_star, {kw: unparse(value)})."""
    calls: Dict[str, List[Tuple]] = {}
    for root, _, files in os.walk(pkg_root):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            try:
                tree = ast.parse(open(path).read())
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "apply"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                op = node.args[0].value
                npos, star = 0, False
                for a in node.args[1:]:
                    if isinstance(a, ast.Starred):
                        star = True
                    else:
                        npos += 1
                kw = {k.arg: ast.unparse(k.value)
                      for k in node.keywords if k.arg}
                calls.setdefault(op, []).append((npos, star, kw))
    return calls


def _seq_elem_type(vals) -> str:
    kinds = set()
    for v in vals:
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return "any"
        kinds.add("float" if isinstance(v, float) else "int")
    if kinds == {"int"}:
        return "int[]"
    if kinds <= {"int", "float"}:
        return "float[]"
    return "any"


def _attr_type_from_value(v) -> str:
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int"
    if isinstance(v, float):
        return "float"
    if isinstance(v, str):
        return "str"
    if isinstance(v, (list, tuple)):
        return _seq_elem_type(v)
    return "any"


_TRACE_TYPE = {"bool": "bool", "int": "int", "float": "float", "str": "str",
               "seq[int]": "int[]", "seq[float]": "float[]",
               "seq[float|int]": "float[]"}


def _yaml_default(v) -> Optional[str]:
    if v is inspect.Parameter.empty:
        return None
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, str):
        return repr(v)
    if v is None:
        return "None"
    if isinstance(v, tuple):
        return repr(v)
    return None  # unrepresentable -> required


def draft_entry(name: str, op, sites, trace) -> Tuple[str, bool]:
    """Returns (yaml text, sig_only). Evidence precedence:
    trace > AST sites > signature."""
    try:
        params = inspect.signature(op.fn).parameters
    except (TypeError, ValueError):
        params = {}
    plist = [(n, p) for n, p in params.items() if not n.startswith("_")
             and p.kind != inspect.Parameter.VAR_KEYWORD]
    has_varargs = any(p.kind == inspect.Parameter.VAR_POSITIONAL
                      for _, p in plist)
    plist = [(n, p) for n, p in plist
             if p.kind != inspect.Parameter.VAR_POSITIONAL]

    attr_names: List[str] = []
    npos_seen: Optional[int] = None
    optional_pos: set = set()
    kwtypes: Dict[str, str] = {}
    sig_only = True
    if trace:
        sig_only = False
        shapes = trace["shapes"]   # [[npos, [kw, ...]], ...]
        npos_seen = max(s[0] for s in shapes)
        for s in shapes:
            for k in s[1]:
                if k not in attr_names:
                    attr_names.append(k)
        optional_pos = set(trace.get("optional_pos", []))
        for k, kinds in trace.get("kwtypes", {}).items():
            kinds = [k2 for k2 in kinds if k2 != "None"]
            if len(kinds) == 1 and kinds[0] in _TRACE_TYPE:
                kwtypes[k] = _TRACE_TYPE[kinds[0]]
            else:
                kwtypes[k] = "any"
    elif sites:
        sig_only = False
        npos_seen = max(s[0] for s in sites)
        for _, _, kw in sites:
            for k in kw:
                if k not in attr_names:
                    attr_names.append(k)

    # classify each kernel param
    tensor_args: List[Tuple[str, str]] = []
    attrs: List[Tuple[str, str, Optional[str]]] = []
    for i, (n, p) in enumerate(plist):
        is_attr = n in attr_names or (
            npos_seen is not None and i >= npos_seen and not has_varargs)
        if npos_seen is None:
            # signature-only: defaulted params are attrs
            is_attr = p.default is not inspect.Parameter.empty
        if is_attr:
            ty = kwtypes.get(n)
            if ty is None and p.default is not inspect.Parameter.empty \
                    and p.default is not None:
                ty = _attr_type_from_value(p.default)
            attrs.append((n, ty or "any", _yaml_default(p.default)))
        else:
            kind = "?" if (i in optional_pos
                           or p.default is None) else ""
            tensor_args.append((n, kind))
    if has_varargs:
        # variadic tensor tail (e.g. multiplex_'s *inputs)
        va = [n for n, p in params.items()
              if p.kind == inspect.Parameter.VAR_POSITIONAL]
        tensor_args.append((va[0], "[]"))

    parts = [f"{n}: Tensor{k}" for n, k in tensor_args]
    for n, ty, d in attrs:
        parts.append(f"{n}: {ty}" + (f" = {d}" if d is not None else ""))
    out = "Tensor, Tensor" if op.multi_output else "Tensor"
    if trace and trace.get("n_outputs"):
        out = ", ".join(["Tensor"] * trace["n_outputs"])
    lines = [f"- op: {name}"]
    if sig_only:
        lines[0] += "   # sig-only"
    lines.append(f"  args: ({', '.join(parts)})")
    lines.append(f"  output: {out}")
    if op.spmd_rule is not None or _has_named_rule(name):
        lines.append(f"  spmd_rule: {name}")
    lines.append(
        f"  backward: {'custom' if op.bwd is not None else 'auto'}")
    return "\n".join(lines), sig_only


def _has_named_rule(name: str) -> bool:
    from ...distributed.auto_parallel.spmd_rules import _RULES
    return name in _RULES


def main(write: bool = False):
    os.environ["PADDLE_TPU_BOOTSTRAP"] = "1"  # registry precedes schema here
    import paddle_tpu  # noqa: F401  (fills the registry)
    from ..._core.op_registry import _OPS
    from .gen import load_schema

    existing_names = set(load_schema())
    sites = scan_call_sites()
    trace_path = os.environ.get("TRACE_OPS_JSON", "/tmp/op_trace.json")
    trace = {}
    if os.path.exists(trace_path):
        trace = json.load(open(trace_path))

    # group new entries by defining module for readability
    groups: Dict[str, List[str]] = {}
    n_sig_only = 0
    for name in sorted(_OPS):
        if name in existing_names:
            continue
        op = _OPS[name]
        text, sig_only = draft_entry(name, op, sites.get(name),
                                     trace.get(name))
        n_sig_only += bool(sig_only)
        mod = getattr(op.fn, "__module__", None) or "unknown"
        groups.setdefault(mod, []).append(text)

    chunks = []
    for mod in sorted(groups):
        chunks.append(f"# ---- {mod}")
        chunks.extend(groups[mod])
    body = "\n\n".join(chunks) + "\n"
    n_new = sum(len(v) for v in groups.values())
    print(f"{n_new} drafted ({n_sig_only} sig-only), "
          f"{len(existing_names)} preserved", file=sys.stderr)
    if write:
        with open(_YAML, "a") as f:
            f.write("\n" + body)
        print(f"appended to {_YAML}", file=sys.stderr)
    else:
        print(body)


if __name__ == "__main__":
    main(write="--write" in sys.argv)
