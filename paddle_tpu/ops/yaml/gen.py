"""Declarative op schema + generator (paddle/phi/ops/yaml analog).

The reference defines ops declaratively (ops.yaml:8-18 schema: args /
output / infer_meta / kernel / spmd_rule / backward) and generates the
C++ API, autograd nodes and Python bindings from it. The TPU-native
split: kernel BODIES are jax functions registered at import (XLA is the
codegen), so what the YAML layer owns here is the same METADATA the
reference's owns —

- the schema of record for an op: signature, output arity, spmd_rule
  binding, backward pairing;
- consistency enforcement: every YAML entry must agree with the live
  registry (op exists, multi_output matches, the bound spmd_rule is
  registered) — the role of the reference's generator-time checks;
- API generation: `generate_wrappers()` emits the public functional
  wrapper for each entry from its declared signature (the python_c_gen
  role), used by paddle_tpu.ops.generated.

Schema (ops.yaml in this directory; each `args:` spec is ONE line —
the reader is line-based):

    - op: matmul
      args: (x: Tensor, y: Tensor, transpose_x: bool = false, transpose_y: bool = false)
      output: Tensor
      spmd_rule: matmul
      backward: auto          # VJP derived from the forward body
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

_YAML = os.path.join(os.path.dirname(__file__), "ops.yaml")

# Tensor   — required tensor input
# Tensor?  — optional tensor input (wrapper default None)
# Tensor[] — variadic tensor inputs (wrapper *args; must be last tensor)
# any      — opaque attr (nested tuples, dtype objects, …): passed through
_TYPES = {"Tensor", "Tensor?", "Tensor[]",
          "bool", "int", "float", "str", "int[]", "float[]", "any"}


class OpEntry:
    def __init__(self, name: str):
        self.name = name
        self.tensor_args: List[tuple] = []  # (name, kind: ''|'?'|'[]')
        self.attrs: List[tuple] = []   # (name, type, default-or-None)
        self.n_outputs = 1
        self.spmd_rule: Optional[str] = None
        self.backward = "auto"
        self.lazy = False  # registered on first call, not at import
        self.layouts: Optional[List[str]] = None  # sparse_ops.yaml only

    def __repr__(self):
        return (f"OpEntry({self.name}, tensors={self.tensor_args}, "
                f"attrs={[a[0] for a in self.attrs]}, "
                f"out={self.n_outputs})")


def _split_args(inner: str):
    """Split on top-level commas only (depth-aware, so nested tuple
    defaults like `spec: any = ((1, 2), (3, 4))` stay whole)."""
    pieces, depth, start = [], 0, 0
    for i, ch in enumerate(inner):
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        elif ch == "," and depth == 0:
            pieces.append(inner[start:i])
            start = i + 1
    pieces.append(inner[start:])
    return pieces


def _parse_args(text: str, entry: OpEntry):
    # "(x: Tensor, axis: int = -1, keepdim: bool = false)"
    inner = text.strip()
    if inner.startswith("("):
        inner = inner[1:-1]
    if not inner.strip():
        return
    for piece in _split_args(inner):
        piece = piece.strip()
        m = re.match(r"(\w+)\s*:\s*([\w\[\]\?]+)(?:\s*=\s*(.+))?$", piece)
        if not m:
            raise ValueError(
                f"ops.yaml: bad arg spec '{piece}' in op {entry.name}")
        arg, ty, default = m.group(1), m.group(2), m.group(3)
        if ty not in _TYPES:
            raise ValueError(
                f"ops.yaml: unknown type '{ty}' in op {entry.name}")
        if ty.startswith("Tensor"):
            if default is not None:
                raise ValueError(
                    f"ops.yaml: Tensor arg '{arg}' cannot default")
            if entry.attrs:
                raise ValueError(
                    f"ops.yaml: tensor arg '{arg}' after attrs in op "
                    f"{entry.name}")
            kind = ty[len("Tensor"):]
            if kind == "[]" and any(k == "[]" for _, k in entry.tensor_args):
                raise ValueError(
                    f"ops.yaml: two variadic tensor args in op {entry.name}")
            entry.tensor_args.append((arg, kind))
        else:
            entry.attrs.append((arg, ty, default))


def load_schema(path: str = _YAML) -> Dict[str, OpEntry]:
    """Tiny purpose-built reader for the restricted YAML subset the
    schema uses (list of flat mappings) — same spirit as the reference's
    parse_utils.py which also hand-parses its op yaml."""
    entries: Dict[str, OpEntry] = {}
    cur: Optional[OpEntry] = None
    with open(path) as f:
        for ln, raw in enumerate(f, 1):
            line = raw.rstrip("\n")
            if not line.strip() or line.strip().startswith("#"):
                continue
            m = re.match(r"-\s*op\s*:\s*(\w+)\s*(?:#.*)?$", line.strip()) \
                if line.lstrip().startswith("-") else None
            if m:
                cur = OpEntry(m.group(1))
                entries[cur.name] = cur
                continue
            if cur is None:
                raise ValueError(f"ops.yaml:{ln}: key before first op")
            key, _, val = line.strip().partition(":")
            key, val = key.strip(), val.strip()
            if key == "args":
                _parse_args(val, cur)
            elif key == "output":
                cur.n_outputs = 1 if val == "Tensor" else \
                    len(val.split(","))
            elif key == "spmd_rule":
                cur.spmd_rule = val
            elif key == "backward":
                cur.backward = val
            elif key == "lazy":
                cur.lazy = val.lower() == "true"
            elif key == "layouts":
                cur.layouts = [p.strip() for p in val.split(",")]
            else:
                raise ValueError(f"ops.yaml:{ln}: unknown key '{key}'")
    return entries


def validate(entries: Optional[Dict[str, OpEntry]] = None) -> List[str]:
    """Cross-check the schema against the LIVE registry; returns a list
    of problems (empty = consistent). The generator-time error class of
    the reference's codegen."""
    from ..._core.op_registry import _OPS
    from ...distributed.auto_parallel.spmd_rules import _RULES

    import inspect

    entries = entries or load_schema()
    problems = []
    for e in entries.values():
        op = _OPS.get(e.name)
        if op is None:
            if not e.lazy:
                problems.append(f"{e.name}: not in the runtime registry")
            continue
        if bool(op.multi_output) != (e.n_outputs > 1):
            problems.append(
                f"{e.name}: multi_output mismatch (yaml {e.n_outputs} "
                f"outputs, registry multi_output={op.multi_output})")
        # runtime resolution is BY OP NAME (spmd_rules.resolve): a
        # binding naming any other registered rule would validate but
        # silently disagree with live behavior
        if e.spmd_rule is not None:
            if e.spmd_rule not in _RULES:
                problems.append(f"{e.name}: spmd_rule '{e.spmd_rule}' "
                                f"is not registered")
            elif e.spmd_rule != e.name:
                problems.append(
                    f"{e.name}: spmd_rule '{e.spmd_rule}' cannot bind — "
                    f"runtime resolves rules by op name")
        # backward mode must agree with the registry: 'custom' iff the
        # op registered its own VJP, 'auto' iff the dispatcher derives it
        if e.backward == "custom" and op.bwd is None:
            problems.append(f"{e.name}: backward 'custom' but no "
                            f"registered bwd")
        if e.backward == "auto" and op.bwd is not None:
            problems.append(f"{e.name}: backward 'auto' but op has a "
                            f"custom bwd (declare 'custom')")
        # attr names must exist in the kernel signature, or the wrapper
        # TypeErrors at first call instead of at generation time
        try:
            params = inspect.signature(op.fn).parameters
            kernel_params = [p for p in params if not p.startswith("_")]
            has_varargs = any(
                p.kind == inspect.Parameter.VAR_POSITIONAL
                for p in params.values())
        except (TypeError, ValueError):
            kernel_params = None
            has_varargs = False
        if kernel_params is not None:
            n_fixed = len([1 for _, k in e.tensor_args if k != "[]"])
            if n_fixed > len(kernel_params) and not has_varargs:
                problems.append(
                    f"{e.name}: {n_fixed} tensor args but "
                    f"kernel takes {len(kernel_params)} params")
            if any(k == "[]" for _, k in e.tensor_args) and not has_varargs:
                problems.append(
                    f"{e.name}: variadic Tensor[] arg but kernel has "
                    f"no *args")
            for a, _, _ in e.attrs:
                if a not in kernel_params:
                    problems.append(
                        f"{e.name}: attr '{a}' is not a kernel "
                        f"parameter ({kernel_params})")
    return problems


def check_complete(entries: Optional[Dict[str, OpEntry]] = None) -> None:
    """Import-time system-of-record enforcement: EVERY runtime-registered
    op must have a YAML entry and vice versa (the reference fails codegen
    when ops.yaml and the kernel registry disagree). Raises on mismatch —
    adding an op without a schema entry is an error by construction."""
    from ..._core.op_registry import _OPS

    entries = entries if entries is not None else load_schema()
    registered_custom = {n for n, op in _OPS.items()
                         if getattr(op, "custom", False)}
    missing = sorted(set(_OPS) - set(entries) - registered_custom)
    stale = sorted(n for n in set(entries) - set(_OPS)
                   if not entries[n].lazy)
    if missing or stale:
        msg = []
        if missing:
            msg.append(f"{len(missing)} registered op(s) missing from "
                       f"ops.yaml: {', '.join(missing[:10])}"
                       + ("…" if len(missing) > 10 else ""))
        if stale:
            msg.append(f"{len(stale)} ops.yaml entr(ies) not in the "
                       f"registry: {', '.join(stale[:10])}"
                       + ("…" if len(stale) > 10 else ""))
        raise RuntimeError(
            "ops.yaml is the system of record and disagrees with the "
            "runtime registry — " + "; ".join(msg)
            + ". Add/remove the schema entry (paddle_tpu/ops/yaml/"
            "ops.yaml); `python -m paddle_tpu.ops.yaml.bootstrap` drafts "
            "entries from the live registry.")


def generate_wrappers(entries: Optional[Dict[str, OpEntry]] = None) -> str:
    """Emit python source for functional wrappers (python_c_gen.py
    role): signature from the declared args, body = apply(op, ...)."""
    entries = entries or load_schema()
    lines = ['"""AUTO-GENERATED by paddle_tpu.ops.yaml.gen — do not',
             'edit. Regenerate with python -m paddle_tpu.ops.yaml.gen."""',
             "from .._core.executor import apply",
             "",
             "# sentinel for required tensor args that syntactically",
             "# follow an optional (Tensor?) arg",
             "_REQUIRED = object()",
             "", ""]

    def pydefault(ty, d):
        # an attr WITHOUT a yaml default is REQUIRED: fabricating a
        # zero-default would silently corrupt calls (clip(x) clamping
        # everything to [0, 0])
        if d is None:
            return None
        if ty == "str":
            return repr(d.strip("'\""))
        return {"false": "False", "true": "True"}.get(d, d)

    for e in entries.values():
        attr_params = []
        for a, ty, d in e.attrs:
            pd = pydefault(ty, d)
            attr_params.append(a if pd is None else f"{a}={pd}")
        params, call_args, req_checks = [], [], []
        seen_opt = False
        for t, kind in e.tensor_args:
            if kind == "?":
                params.append(f"{t}=None")
                call_args.append(t)
                seen_opt = True
            elif kind == "[]":
                params.append(f"*{t}")
                call_args.append(f"*{t}")
            elif seen_opt:
                # required tensor after an optional one: sentinel default
                # keeps the def legal, the check keeps it required
                params.append(f"{t}=_REQUIRED")
                call_args.append(t)
                req_checks.append(t)
            else:
                params.append(t)
                call_args.append(t)
        variadic = any(k == "[]" for _, k in e.tensor_args)
        # attrs are keyword-only: required attrs may follow defaulted
        # ones in declared order without breaking Python's ordering rule
        if attr_params:
            params += ([] if variadic else ["*"]) + attr_params \
                + ["name=None"]
        elif variadic:
            params += ["name=None"]
        else:
            params += ["name=None"]
        kwargs = ", ".join(f"{a}={a}" for a, _, _ in e.attrs)
        call = ", ".join(call_args)
        inner = ", ".join(p for p in (call, kwargs) if p)
        head = f"'{e.name}', {inner}" if inner else f"'{e.name}'"
        lines.append(f"def {e.name}({', '.join(params)}):")
        lines.append(f'    """Generated from ops.yaml (op: {e.name})."""')
        for t in req_checks:
            lines.append(f"    if {t} is _REQUIRED:")
            lines.append(f"        raise TypeError("
                         f"\"{e.name}() missing required argument: "
                         f"'{t}'\")")
        lines += [f"    return apply({head})", "", ""]
    return "\n".join(lines)


def write_generated(path: Optional[str] = None) -> str:
    path = path or os.path.join(os.path.dirname(__file__), "..",
                                "generated.py")
    problems = validate()
    if problems:
        raise ValueError("ops.yaml inconsistent with registry:\n  "
                         + "\n  ".join(problems))
    src = generate_wrappers()
    with open(path, "w") as f:
        f.write(src)
    return os.path.abspath(path)


if __name__ == "__main__":
    out = write_generated()
    print(f"wrote {out}")
