"""Declarative op schema + generator (paddle/phi/ops/yaml analog).

The reference defines ops declaratively (ops.yaml:8-18 schema: args /
output / infer_meta / kernel / spmd_rule / backward) and generates the
C++ API, autograd nodes and Python bindings from it. The TPU-native
split: kernel BODIES are jax functions registered at import (XLA is the
codegen), so what the YAML layer owns here is the same METADATA the
reference's owns —

- the schema of record for an op: signature, output arity, spmd_rule
  binding, backward pairing;
- consistency enforcement: every YAML entry must agree with the live
  registry (op exists, multi_output matches, the bound spmd_rule is
  registered) — the role of the reference's generator-time checks;
- API generation: `generate_wrappers()` emits the public functional
  wrapper for each entry from its declared signature (the python_c_gen
  role), used by paddle_tpu.ops.generated.

Schema (ops.yaml in this directory; each `args:` spec is ONE line —
the reader is line-based):

    - op: matmul
      args: (x: Tensor, y: Tensor, transpose_x: bool = false, transpose_y: bool = false)
      output: Tensor
      spmd_rule: matmul
      backward: auto          # VJP derived from the forward body
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

_YAML = os.path.join(os.path.dirname(__file__), "ops.yaml")

_TYPES = {"Tensor", "bool", "int", "float", "str", "int[]", "float[]"}


class OpEntry:
    def __init__(self, name: str):
        self.name = name
        self.tensor_args: List[str] = []
        self.attrs: List[tuple] = []   # (name, type, default-or-None)
        self.n_outputs = 1
        self.spmd_rule: Optional[str] = None
        self.backward = "auto"

    def __repr__(self):
        return (f"OpEntry({self.name}, tensors={self.tensor_args}, "
                f"attrs={[a[0] for a in self.attrs]}, "
                f"out={self.n_outputs})")


def _parse_args(text: str, entry: OpEntry):
    # "(x: Tensor, axis: int = -1, keepdim: bool = false)"
    inner = text.strip()
    if inner.startswith("("):
        inner = inner[1:-1]
    if not inner.strip():
        return
    for piece in re.split(r",(?![^\[]*\])", inner):
        piece = piece.strip()
        m = re.match(r"(\w+)\s*:\s*([\w\[\]]+)(?:\s*=\s*(.+))?$", piece)
        if not m:
            raise ValueError(
                f"ops.yaml: bad arg spec '{piece}' in op {entry.name}")
        arg, ty, default = m.group(1), m.group(2), m.group(3)
        if ty not in _TYPES:
            raise ValueError(
                f"ops.yaml: unknown type '{ty}' in op {entry.name}")
        if ty == "Tensor":
            if default is not None:
                raise ValueError(
                    f"ops.yaml: Tensor arg '{arg}' cannot default")
            entry.tensor_args.append(arg)
        else:
            entry.attrs.append((arg, ty, default))


def load_schema(path: str = _YAML) -> Dict[str, OpEntry]:
    """Tiny purpose-built reader for the restricted YAML subset the
    schema uses (list of flat mappings) — same spirit as the reference's
    parse_utils.py which also hand-parses its op yaml."""
    entries: Dict[str, OpEntry] = {}
    cur: Optional[OpEntry] = None
    with open(path) as f:
        for ln, raw in enumerate(f, 1):
            line = raw.rstrip("\n")
            if not line.strip() or line.strip().startswith("#"):
                continue
            m = re.match(r"-\s*op\s*:\s*(\w+)\s*$", line.strip()) \
                if line.lstrip().startswith("-") else None
            if m:
                cur = OpEntry(m.group(1))
                entries[cur.name] = cur
                continue
            if cur is None:
                raise ValueError(f"ops.yaml:{ln}: key before first op")
            key, _, val = line.strip().partition(":")
            key, val = key.strip(), val.strip()
            if key == "args":
                _parse_args(val, cur)
            elif key == "output":
                cur.n_outputs = 1 if val == "Tensor" else \
                    len(val.split(","))
            elif key == "spmd_rule":
                cur.spmd_rule = val
            elif key == "backward":
                cur.backward = val
            else:
                raise ValueError(f"ops.yaml:{ln}: unknown key '{key}'")
    return entries


def validate(entries: Optional[Dict[str, OpEntry]] = None) -> List[str]:
    """Cross-check the schema against the LIVE registry; returns a list
    of problems (empty = consistent). The generator-time error class of
    the reference's codegen."""
    from ..._core.op_registry import _OPS
    from ...distributed.auto_parallel.spmd_rules import _RULES

    import inspect

    entries = entries or load_schema()
    problems = []
    for e in entries.values():
        op = _OPS.get(e.name)
        if op is None:
            problems.append(f"{e.name}: not in the runtime registry")
            continue
        if bool(op.multi_output) != (e.n_outputs > 1):
            problems.append(
                f"{e.name}: multi_output mismatch (yaml {e.n_outputs} "
                f"outputs, registry multi_output={op.multi_output})")
        # runtime resolution is BY OP NAME (spmd_rules.resolve): a
        # binding naming any other registered rule would validate but
        # silently disagree with live behavior
        if e.spmd_rule is not None:
            if e.spmd_rule not in _RULES:
                problems.append(f"{e.name}: spmd_rule '{e.spmd_rule}' "
                                f"is not registered")
            elif e.spmd_rule != e.name:
                problems.append(
                    f"{e.name}: spmd_rule '{e.spmd_rule}' cannot bind — "
                    f"runtime resolves rules by op name")
        # attr names must exist in the kernel signature, or the wrapper
        # TypeErrors at first call instead of at generation time
        try:
            kernel_params = [p for p in
                             inspect.signature(op.fn).parameters
                             if not p.startswith("_")]
        except (TypeError, ValueError):
            kernel_params = None
        if kernel_params is not None:
            if len(e.tensor_args) > len(kernel_params):
                problems.append(
                    f"{e.name}: {len(e.tensor_args)} tensor args but "
                    f"kernel takes {len(kernel_params)} params")
            for a, _, _ in e.attrs:
                if a not in kernel_params:
                    problems.append(
                        f"{e.name}: attr '{a}' is not a kernel "
                        f"parameter ({kernel_params})")
    return problems


def generate_wrappers(entries: Optional[Dict[str, OpEntry]] = None) -> str:
    """Emit python source for functional wrappers (python_c_gen.py
    role): signature from the declared args, body = apply(op, ...)."""
    entries = entries or load_schema()
    lines = ['"""AUTO-GENERATED by paddle_tpu.ops.yaml.gen — do not',
             'edit. Regenerate with python -m paddle_tpu.ops.yaml.gen."""',
             "from .._core.executor import apply",
             "", ""]

    def pydefault(ty, d):
        # an attr WITHOUT a yaml default is REQUIRED: fabricating a
        # zero-default would silently corrupt calls (clip(x) clamping
        # everything to [0, 0])
        if d is None:
            return None
        if ty == "str":
            return repr(d.strip("'\""))
        return {"false": "False", "true": "True"}.get(d, d)

    for e in entries.values():
        attr_params = []
        for a, ty, d in e.attrs:
            pd = pydefault(ty, d)
            attr_params.append(a if pd is None else f"{a}={pd}")
        # attrs are keyword-only: required attrs may follow defaulted
        # ones in declared order without breaking Python's ordering rule
        params = list(e.tensor_args)
        if attr_params:
            params += ["*"] + attr_params + ["name=None"]
        else:
            params += ["name=None"]
        kwargs = ", ".join(f"{a}={a}" for a, _, _ in e.attrs)
        call_args = ", ".join(e.tensor_args)
        sep = ", " if kwargs else ""
        lines += [
            f"def {e.name}({', '.join(params)}):",
            f'    """Generated from ops.yaml (op: {e.name})."""',
            f"    return apply('{e.name}', {call_args}{sep}{kwargs})",
            "", ""]
    return "\n".join(lines)


def write_generated(path: Optional[str] = None) -> str:
    path = path or os.path.join(os.path.dirname(__file__), "..",
                                "generated.py")
    problems = validate()
    if problems:
        raise ValueError("ops.yaml inconsistent with registry:\n  "
                         + "\n  ".join(problems))
    src = generate_wrappers()
    with open(path, "w") as f:
        f.write(src)
    return os.path.abspath(path)


if __name__ == "__main__":
    out = write_generated()
    print(f"wrote {out}")
