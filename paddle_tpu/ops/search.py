"""Search / indexing ops: argmax, gather/scatter, topk, sort, where, ...

Analog of python/paddle/tensor/search.py + the gather/scatter phi kernels.
Dynamic-result ops (nonzero, masked_select, unique) materialize indices on
host first (XLA needs static shapes), then reuse static gather kernels so
autograd still flows — the bucketing/padding policy from SURVEY.md §7.
"""
from __future__ import annotations

import numbers

import jax
import jax.numpy as jnp
import numpy as np

from .._core.executor import apply
from .._core.op_registry import register_op
from .._core.tensor import Tensor
from ._helper import tensor_method
from .manipulation import flatten, reshape

# ------------------------------------------------------ argmax/argmin (nondiff)
register_op("argmax_", lambda x, axis, keepdim, dtype: jnp.argmax(
    x, axis=axis, keepdims=keepdim).astype(dtype))
register_op("argmin_", lambda x, axis, keepdim, dtype: jnp.argmin(
    x, axis=axis, keepdims=keepdim).astype(dtype))


@tensor_method("argmax")
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from .._core import dtype as dm
    return apply("argmax_", x, axis=None if axis is None else int(axis),
                 keepdim=bool(keepdim), dtype=str(dm.to_np(dtype)))


@tensor_method("argmin")
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from .._core import dtype as dm
    return apply("argmin_", x, axis=None if axis is None else int(axis),
                 keepdim=bool(keepdim), dtype=str(dm.to_np(dtype)))


# ------------------------------------------------------ gather family
register_op("take_along_axis_",
            lambda x, idx, axis: jnp.take_along_axis(x, idx, axis=axis))


@tensor_method("take_along_axis")
def take_along_axis(x, indices, axis, broadcast=True, name=None):
    return apply("take_along_axis_", x, indices, axis=int(axis))


def _put_along_axis_kernel(x, idx, v, axis, reduce):
    v = jnp.broadcast_to(v, idx.shape).astype(x.dtype)
    if reduce == "assign":
        return jnp.put_along_axis(x, idx, v, axis=axis, inplace=False)
    dims = list(range(x.ndim))
    # build scatter indices for general reduce
    grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
    full_idx = [grids[d] for d in dims]
    full_idx[axis] = idx
    flat_idx = jnp.stack([g.reshape(-1) for g in full_idx], axis=-1)
    upd = v.reshape(-1)
    if reduce == "add":
        return x.at[tuple(flat_idx[:, d] for d in dims)].add(upd)
    if reduce == "multiply" or reduce == "mul":
        return x.at[tuple(flat_idx[:, d] for d in dims)].multiply(upd)
    raise ValueError(f"unsupported reduce: {reduce}")


register_op("put_along_axis_", _put_along_axis_kernel)


@tensor_method("put_along_axis")
def put_along_axis(x, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    return apply("put_along_axis_", x, indices, values, axis=int(axis),
                 reduce=reduce)


register_op("gather_", lambda x, idx, axis: jnp.take(x, idx, axis=axis))


@tensor_method("gather")
def gather(x, index, axis=0, name=None):
    if index.ndim == 2 and index.shape[1] == 1:
        index = flatten(index)
    return apply("gather_", x, index, axis=int(axis) if not isinstance(
        axis, Tensor) else int(axis.item()))


def _gather_nd_kernel(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


register_op("gather_nd_", _gather_nd_kernel)


@tensor_method("gather_nd")
def gather_nd(x, index, name=None):
    return apply("gather_nd_", x, index)


def _scatter_kernel(x, index, updates, overwrite):
    if index.ndim == 2 and index.shape[-1] == 1:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates.astype(x.dtype))
    # paddle scatter w/ overwrite=False zeroes target rows then adds
    zeroed = x.at[index].set(jnp.zeros_like(updates, dtype=x.dtype))
    return zeroed.at[index].add(updates.astype(x.dtype))


register_op("scatter_", _scatter_kernel)


@tensor_method("scatter")
def scatter(x, index, updates, overwrite=True, name=None):
    return apply("scatter_", x, index, updates, overwrite=bool(overwrite))


def _scatter_nd_add_kernel(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates.astype(x.dtype))


register_op("scatter_nd_add_", _scatter_nd_add_kernel)


def scatter_nd_add(x, index, updates, name=None):
    return apply("scatter_nd_add_", x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    zero = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(zero, index, updates)


register_op("index_select_",
            lambda x, idx, axis: jnp.take(x, idx, axis=axis))


@tensor_method("index_select")
def index_select(x, index, axis=0, name=None):
    return apply("index_select_", x, index, axis=int(axis))


def _index_sample_kernel(x, index):
    return jnp.take_along_axis(x, index, axis=1)


register_op("index_sample_", _index_sample_kernel)


def index_sample(x, index):
    return apply("index_sample_", x, index)


def _index_add_kernel(x, index, value, axis):
    moved = jnp.moveaxis(x, axis, 0)
    v = jnp.moveaxis(value, axis, 0).astype(x.dtype)
    out = moved.at[index].add(v)
    return jnp.moveaxis(out, 0, axis)


register_op("index_add_", _index_add_kernel)


@tensor_method("index_add")
def index_add(x, index, axis, value, name=None):
    return apply("index_add_", x, index, value, axis=int(axis))


def _index_put_kernel(x, v, *idx, accumulate):
    if accumulate:
        return x.at[tuple(idx)].add(v.astype(x.dtype))
    return x.at[tuple(idx)].set(v.astype(x.dtype))


register_op("index_put_", _index_put_kernel)


@tensor_method("index_put")
def index_put(x, indices, value, accumulate=False, name=None):
    return apply("index_put_", x, value, *list(indices),
                 accumulate=bool(accumulate))


# ------------------------------------------------------ topk / sort
register_op("arg_topk_", lambda x, k, axis, largest: (
    jax.lax.top_k(jnp.moveaxis(x if largest else -x, axis, -1), k)[1]))


def _topk_indices(x, k, axis, largest):
    idx = apply("arg_topk_", x, k=int(k), axis=axis, largest=bool(largest))
    # lax.top_k works on the last axis of the moved array; move back
    return idx


@tensor_method("topk")
def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    axis = int(axis) % x.ndim
    idx = _topk_indices(x, k, axis, largest)
    from .manipulation import moveaxis
    if axis != x.ndim - 1:
        idx = moveaxis(idx, -1, axis)
    values = take_along_axis(x, idx, axis)
    idx64 = apply("cast", idx, dtype="int64")
    return values, idx64


register_op("argsort_", lambda x, axis, descending: (
    jnp.argsort(-x if descending else x, axis=axis,
                stable=True).astype(jnp.int64)))


@tensor_method("argsort")
def argsort(x, axis=-1, descending=False, stable=True, name=None):
    return apply("argsort_", x, axis=int(axis), descending=bool(descending))


@tensor_method("sort")
def sort(x, axis=-1, descending=False, stable=True, name=None):
    idx = argsort(x, axis=axis, descending=descending)
    return take_along_axis(x, idx, axis)


def _kthvalue(x, k, axis=-1, keepdim=False, name=None):
    axis = int(axis) % x.ndim
    vals = sort(x, axis=axis)
    idx = argsort(x, axis=axis)
    from . import manipulation as M
    take = [slice(None)] * x.ndim
    take[axis] = slice(k - 1, k)
    v = vals[tuple(take)]
    i = idx[tuple(take)]
    if not keepdim:
        v, i = v.squeeze(axis), i.squeeze(axis)
    return v, i


kthvalue = _kthvalue


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis (ops.yaml mode); ties resolve to
    the smallest value, index is the last occurrence."""
    from .manipulation import transpose
    nd = x.ndim
    axis = axis % nd
    perm = [i for i in range(nd) if i != axis] + [axis]
    xt = transpose(x, perm) if axis != nd - 1 else x
    values, idx = apply("mode_k", xt)
    if keepdim:
        values = values.unsqueeze(axis)
        idx = idx.unsqueeze(axis)
    return values, idx


register_op("searchsorted_",
            lambda a, v, right: jnp.searchsorted(
                a, v, side="right" if right else "left").astype(jnp.int64))


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    out = apply("searchsorted_", sorted_sequence, values, right=bool(right))
    if out_int32:
        from .manipulation import cast
        out = cast(out, "int32")
    return out


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


# ------------------------------------------------------ where / dynamic ops
register_op("where_", lambda c, x, y: jnp.where(c, x, y))


@tensor_method("where")
def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply("where_", condition, x, y)


def nonzero(x, as_tuple=False):
    """Dynamic-shape: synchronizes with host (documented XLA constraint)."""
    idx = np.nonzero(np.asarray(x._value))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i[:, None], dtype=jnp.int64))
                     for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=-1), dtype=jnp.int64)) \
        if idx else Tensor(jnp.zeros((0, x.ndim), jnp.int64))


@tensor_method("masked_select")
def masked_select(x, mask, name=None):
    """Dynamic-shape: indices resolved on host, gather stays on device so
    gradients flow through gather_nd."""
    mval = np.asarray(mask._value)
    if mval.shape != tuple(x.shape):
        mval = np.broadcast_to(mval, x.shape)
    idx = np.stack(np.nonzero(mval), axis=-1)
    index = Tensor(jnp.asarray(idx, dtype=jnp.int64))
    return gather_nd(x, index)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    val = np.asarray(x._value)
    res = np.unique(val, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    val = np.asarray(x._value)
    if axis is None:
        val = val.reshape(-1)
    n = val.shape[0] if val.ndim else 1
    keep = np.ones(n, dtype=bool)
    keep[1:] = np.any(
        val[1:].reshape(n - 1, -1) != val[:-1].reshape(n - 1, -1), axis=1)
    out = Tensor(jnp.asarray(val[keep]))
    results = [out]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        results.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        pos = np.flatnonzero(keep)
        counts = np.diff(np.append(pos, n))
        results.append(Tensor(jnp.asarray(counts)))
    return results[0] if len(results) == 1 else tuple(results)


def _top_p_kernel(x, ps, seed):
    """Nucleus sampling (top_p_sampling op): keep the smallest
    probability mass >= p per row, renormalize, sample one id."""
    sorted_p, sorted_idx = jax.lax.top_k(x, x.shape[-1])
    cum = jnp.cumsum(sorted_p, axis=-1)
    # keep tokens while the mass BEFORE them is < p (always >= 1 token)
    keep = (cum - sorted_p) < ps[..., None]
    filt = jnp.where(keep, sorted_p, 0.0)
    filt = filt / filt.sum(axis=-1, keepdims=True)
    key = jax.random.PRNGKey(seed)
    choice = jax.random.categorical(key, jnp.log(filt + 1e-20), axis=-1)
    ids = jnp.take_along_axis(sorted_idx, choice[..., None], axis=-1)
    probs = jnp.take_along_axis(filt, choice[..., None], axis=-1)
    return probs, ids.astype(jnp.int64)


register_op("top_p_sampling", _top_p_kernel, multi_output=True)


def top_p_sampling(x, ps, threshold=None, seed=None, name=None, **kw):
    """paddle.tensor.top_p_sampling: x [B, V] probabilities, ps [B]
    per-row nucleus mass. Returns (sampled_probs, sampled_ids)."""
    if seed is None or seed < 0:
        # fresh randomness per call (Paddle's seed=-1 semantics), still
        # reproducible under paddle.seed: fold the split global key
        from .._core import random as _rnd
        key = _rnd.next_key()
        seed = int(np.asarray(
            jax.random.key_data(key)).ravel()[-1]) & 0x7FFFFFFF
    return apply("top_p_sampling", x, ps, seed=int(seed))
