"""Reference-parity op batch: fused, strided/view, creation/compare,
and loss families absent from the registry (VERDICT r3 missing #10;
reference paddle/phi/ops/yaml/ops.yaml + fused_ops.yaml).

Every op here is a REGISTERED kernel (compile-cached eager dispatch,
records into static Programs) rather than a raw-jnp wrapper, with the
public functional wrapper beside it. Kernels are pure-jax bodies that
XLA fuses — the fused_* family expresses the reference's hand-fused CUDA
kernels as single registered ops whose bodies XLA fuses into one
executable (fused_ops.yaml: fused_bias_act, fused_dropout_add,
fused_softmax_mask..., fused_gemm_epilogue, skip_layernorm).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .._core import random as rnd
from .._core.executor import apply
from .._core.op_registry import register_op
from .._core.tensor import Tensor

# ============================================================ fused family

register_op("fused_bias_act", lambda x, b, act: {
    "gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu,
    "swiglu": lambda v: jax.nn.silu(v[..., :v.shape[-1] // 2])
    * v[..., v.shape[-1] // 2:],
}[act](x + b))


def fused_bias_act(x, bias, act_method="gelu", name=None):
    """fused_ops.yaml fused_bias_act: bias add + activation, one op."""
    return apply("fused_bias_act", x, bias, act=str(act_method))


def _fused_dropout_add(x, y, key, p, training, mode):
    if training and p > 0.0:
        keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, x / (1.0 - p), 0.0) + y
        return jnp.where(keep, x, 0.0) + y      # downscale_in_infer
    if not training and mode == "downscale_in_infer" and p > 0.0:
        return x * (1.0 - p) + y
    return x + y


register_op("fused_dropout_add", _fused_dropout_add)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """fused_ops.yaml fused_dropout_add: dropout(x) + y in one kernel
    (both dropout_impl modes honored)."""
    return apply("fused_dropout_add", x, y, Tensor(rnd.next_key()),
                 p=float(p), training=bool(training), mode=str(mode))


def _softmax_mask(x, mask):
    return jax.nn.softmax(x + mask, axis=-1)


register_op("fused_softmax_mask", _softmax_mask)


def fused_softmax_mask(x, mask, name=None):
    """fused_softmax_mask: additive mask + softmax (one fused op)."""
    return apply("fused_softmax_mask", x, mask)


def _softmax_mask_triu(x):
    t = x.shape[-1]
    row = lax.broadcasted_iota(jnp.int32, (x.shape[-2], t), 0)
    col = lax.broadcasted_iota(jnp.int32, (x.shape[-2], t), 1)
    return jax.nn.softmax(jnp.where(col <= row, x, -1e9), axis=-1)


register_op("fused_softmax_mask_upper_triangle", _softmax_mask_triu)


def fused_softmax_mask_upper_triangle(x, name=None):
    """Causal (upper-triangle-masked) softmax as one op."""
    return apply("fused_softmax_mask_upper_triangle", x)


register_op("fused_gemm_epilogue",
            lambda x, y, b, act:
            {"none": lambda v: v, "relu": jax.nn.relu,
             "gelu": jax.nn.gelu}[act](x @ y + b))


def fused_gemm_epilogue(x, y, bias, trans_x=False, trans_y=False,
                        activation="none", name=None):
    """fused_gemm_epilogue: matmul + bias + activation epilogue."""
    if trans_x:
        x = x.t() if hasattr(x, "t") else x
    if trans_y:
        y = y.t() if hasattr(y, "t") else y
    return apply("fused_gemm_epilogue", x, y, bias, act=str(activation))


def _skip_layernorm(x, skip, w, b, eps):
    h = x + skip
    mu = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.var(h, axis=-1, keepdims=True)
    return (h - mu) / jnp.sqrt(var + eps) * w + b


register_op("skip_layernorm", _skip_layernorm)


def skip_layernorm(x, skip, weight, bias, epsilon=1e-5, name=None):
    """skip_layernorm (fused residual-add + layer_norm)."""
    return apply("skip_layernorm", x, skip, weight, bias,
                 eps=float(epsilon))


def _fused_bias_dropout_residual_ln(x, residual, bias, w, b, key, p,
                                    training, eps):
    h = x + bias
    if training and p > 0.0:
        keep = jax.random.bernoulli(key, 1.0 - p, h.shape)
        h = jnp.where(keep, h / (1.0 - p), 0.0)
    h = h + residual
    mu = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.var(h, axis=-1, keepdims=True)
    return (h - mu) / jnp.sqrt(var + eps) * w + b


register_op("fused_bias_dropout_residual_layer_norm",
            _fused_bias_dropout_residual_ln)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias, ln_scale, ln_bias, dropout_rate=0.5,
        ln_epsilon=1e-5, training=True, name=None):
    """fused_ops.yaml fused_bias_dropout_residual_layer_norm."""
    return apply("fused_bias_dropout_residual_layer_norm", x, residual,
                 bias, ln_scale, ln_bias, Tensor(rnd.next_key()),
                 p=float(dropout_rate), training=bool(training),
                 eps=float(ln_epsilon))


def _fused_linear_param_grad_add(x, dout, dw_acc, db_acc, has_bias):
    dw = jnp.einsum("...i,...o->io", x, dout)
    dw = dw if dw_acc is None else dw_acc + dw
    if not has_bias:
        return dw, jnp.zeros((dout.shape[-1],), dout.dtype)
    db = jnp.sum(dout.reshape(-1, dout.shape[-1]), axis=0)
    db = db if db_acc is None else db_acc + db
    return dw, db


register_op("fused_linear_param_grad_add", _fused_linear_param_grad_add,
            multi_output=True)


def fused_linear_param_grad_add(x, dout, dweight=None, dbias=None,
                                multi_precision=False, has_bias=True,
                                name=None):
    """fused_linear_param_grad_add: one-op dW/db accumulation (the
    ZeroBubble W-step kernel of the reference)."""
    return apply("fused_linear_param_grad_add", x, dout, dweight, dbias,
                 has_bias=bool(has_bias))


for _name, _f in (("fused_elementwise_add", jnp.add),
                  ("fused_elementwise_sub", jnp.subtract),
                  ("fused_elementwise_mul", jnp.multiply),
                  ("fused_elementwise_div", jnp.divide)):
    register_op(_name, lambda x, y, scale, _f=_f: _f(x, y) * scale)


def fused_elementwise_add(x, y, scale=1.0, name=None):
    return apply("fused_elementwise_add", x, y, scale=float(scale))


def fused_elementwise_sub(x, y, scale=1.0, name=None):
    return apply("fused_elementwise_sub", x, y, scale=float(scale))


def fused_elementwise_mul(x, y, scale=1.0, name=None):
    return apply("fused_elementwise_mul", x, y, scale=float(scale))


def fused_elementwise_div(x, y, scale=1.0, name=None):
    return apply("fused_elementwise_div", x, y, scale=float(scale))


# ====================================================== strided/view family
# The reference's kernels/stride/ family; XLA has no aliasing views, so
# these are gather/copy formulations with view SEMANTICS (SURVEY §7:
# "inplace/stride ops don't map to XLA views — emulate via copy").

def _as_strided(x, shape, stride, offset):
    flat = x.reshape(-1)
    idx = jnp.full(tuple(shape), offset, jnp.int32)
    for d, st in enumerate(stride):
        ar = lax.broadcasted_iota(jnp.int32, tuple(shape), d)
        idx = idx + ar * st
    return jnp.take(flat, idx)


register_op("as_strided",
            lambda x, shape, stride, offset:
            _as_strided(x, shape, stride, offset))


def as_strided(x, shape, stride, offset=0, name=None):
    """kernels/stride as_strided: arbitrary strided view (copy-on-read
    gather on TPU)."""
    return apply("as_strided", x, shape=tuple(int(s) for s in shape),
                 stride=tuple(int(s) for s in stride),
                 offset=int(offset))


def _view_dtype(x, dtype):
    dt = jnp.dtype(dtype)
    src, dst = x.dtype.itemsize, dt.itemsize
    if src == dst:
        return lax.bitcast_convert_type(x, dt)
    if src > dst:      # narrowing: last dim grows by src//dst
        out = lax.bitcast_convert_type(x, dt)   # [..., last, src//dst]
        return out.reshape(x.shape[:-1] + (x.shape[-1] * (src // dst),))
    k = dst // src     # widening: last dim must divide by k
    if x.shape[-1] % k:
        raise ValueError(
            f"view_dtype: last dim {x.shape[-1]} not divisible by "
            f"{k} for {x.dtype} -> {dt}")
    grouped = x.reshape(x.shape[:-1] + (x.shape[-1] // k, k))
    return lax.bitcast_convert_type(grouped, dt)


register_op("view_dtype", _view_dtype)


def view_dtype(x, dtype, name=None):
    """view_dtype: reinterpret the payload bytes (bitcast)."""
    from .._core import dtype as dmod
    np_dt = dmod.to_np(dtype) if hasattr(dmod, "to_np") else dtype
    return apply("view_dtype", x, dtype=str(jnp.dtype(np_dt)))


register_op("view_slice",
            lambda x, begin, end: x[tuple(
                slice(b, e) for b, e in zip(begin, end))])


def view_slice(x, begin, end, name=None):
    """view_slice: contiguous sub-view (slice copy on TPU)."""
    return apply("view_slice", x, begin=tuple(int(b) for b in begin),
                 end=tuple(int(e) for e in end))


register_op("trans_layout", lambda x, perm: jnp.transpose(x, perm))


def trans_layout(x, perm, name=None):
    """trans_layout (layout transposition as an explicit op)."""
    return apply("trans_layout", x, perm=tuple(int(p) for p in perm))


register_op("index_select_strided",
            lambda x, index, axis: jnp.take(x, index, axis=axis))


def index_select_strided(x, index, axis=0, name=None):
    """index_select over a strided source (gather formulation)."""
    return apply("index_select_strided", x, index, axis=int(axis))


def _fill_diagonal_tensor(x, y, offset, dim1, dim2):
    # mask formulation: positions on the (dim1, dim2) diagonal take y
    # (indexed by their position along the diagonal), others keep x
    i1 = lax.broadcasted_iota(jnp.int32, x.shape, dim1)
    i2 = lax.broadcasted_iota(jnp.int32, x.shape, dim2)
    on_diag = (i2 - i1) == offset
    diag_pos = jnp.where(offset >= 0, i1, i2)
    yv = jnp.take(y, jnp.clip(diag_pos, 0, y.shape[-1] - 1), axis=-1) \
        if y.ndim == 1 else y
    return jnp.where(on_diag, yv, x)


register_op("fill_diagonal_tensor", _fill_diagonal_tensor)


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """fill_diagonal_tensor: write y along a (dim1, dim2) diagonal."""
    return apply("fill_diagonal_tensor", x, y, offset=int(offset),
                 dim1=int(dim1), dim2=int(dim2))


# ================================================= creation / compare ops

register_op("eye_k", lambda n, m, dtype: jnp.eye(n, m,
                                                 dtype=jnp.dtype(dtype)))
register_op("linspace_k",
            lambda start, stop, num, dtype: jnp.linspace(
                start, stop, num, dtype=jnp.dtype(dtype)))
register_op("logspace_k",
            lambda start, stop, num, base, dtype: jnp.logspace(
                start, stop, num, base=base, dtype=jnp.dtype(dtype)))
register_op("tril_indices_k",
            lambda rows, cols, offset: jnp.stack(
                jnp.tril_indices(rows, offset, cols)),)
register_op("triu_indices_k",
            lambda rows, cols, offset: jnp.stack(
                jnp.triu_indices(rows, offset, cols)))
register_op("full_k", lambda shape, value, dtype: jnp.full(
    tuple(shape), value, jnp.dtype(dtype)))
register_op("full_like_k", lambda x, value: jnp.full_like(x, value))
register_op("allclose_k",
            lambda x, y, rtol, atol, equal_nan: jnp.allclose(
                x, y, rtol=rtol, atol=atol, equal_nan=equal_nan))
register_op("isclose_k",
            lambda x, y, rtol, atol, equal_nan: jnp.isclose(
                x, y, rtol=rtol, atol=atol, equal_nan=equal_nan))
register_op("equal_all_k", lambda x, y: jnp.array_equal(x, y))
register_op("bmm_k", lambda x, y: jnp.matmul(x, y))
register_op("mv_k", lambda x, v: jnp.matmul(x, v))
register_op("eigvalsh_k", lambda x: jnp.linalg.eigvalsh(x))
register_op("frobenius_norm_k",
            lambda x, axis, keepdim: jnp.sqrt(jnp.sum(
                x * x, axis=axis, keepdims=keepdim)))
register_op("numel_k", lambda x: jnp.asarray(x.size, jnp.int64))
register_op("shape_k", lambda x: jnp.asarray(x.shape, jnp.int32))
register_op("increment_k", lambda x, value: x + value)
register_op("kthvalue_k",
            lambda x, k, axis, keepdim: (
                jnp.take(jnp.sort(x, axis=axis), k - 1, axis=axis),
                jnp.take(jnp.argsort(x, axis=axis), k - 1, axis=axis)),
            multi_output=True)
register_op("mode_k",
            lambda x: _mode_impl(x), multi_output=True)


def _mode_impl(x):
    # mode along the last axis: most frequent value (ties -> smallest)
    sorted_x = jnp.sort(x, axis=-1)
    n = x.shape[-1]
    # run lengths via comparing neighbours
    eq = jnp.concatenate(
        [jnp.ones(x.shape[:-1] + (1,), bool),
         sorted_x[..., 1:] == sorted_x[..., :-1]], axis=-1)
    # for each position, length of the run ending here
    def scan_fn(carry, inp):
        e, v = inp
        run = jnp.where(e, carry + 1, 1)
        return run, run
    runs = jax.lax.scan(
        scan_fn, jnp.zeros(x.shape[:-1], jnp.int32),
        (jnp.moveaxis(eq, -1, 0), jnp.moveaxis(sorted_x, -1, 0)))[1]
    runs = jnp.moveaxis(runs, 0, -1)
    best = jnp.argmax(runs, axis=-1)
    values = jnp.take_along_axis(sorted_x, best[..., None],
                                 axis=-1)[..., 0]
    # index of (last) occurrence in the ORIGINAL tensor
    hit = x == values[..., None]
    idx = jnp.argmax(
        jnp.where(hit, jnp.arange(n), -1), axis=-1)
    return values, idx.astype(jnp.int64)


# kldiv pointwise + sigmoid-CE-with-logits (the remaining loss kernels
# not already registered by nn/functional/extended.py)

register_op("kldiv_pointwise_k",
            lambda x, target: target * (jnp.log(
                jnp.clip(target, 1e-12)) - x))
register_op("sigmoid_cross_entropy_with_logits_k",
            lambda x, label: jnp.maximum(x, 0.0) - x * label
            + jnp.log1p(jnp.exp(-jnp.abs(x))))


def kldiv_loss_pointwise(input, target, name=None):
    return apply("kldiv_pointwise_k", input, target)


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    return apply("sigmoid_cross_entropy_with_logits_k", x, label)


# ============================================== interpolation variants
# ops.yaml bilinear_interp / nearest_interp / bicubic_interp /
# linear_interp / trilinear_interp as distinct registered ops over
# jax.image.resize (NCHW/NCDHW in, like the reference kernels).

def _resize(x, size, method):
    spatial = tuple(int(s) for s in size)
    out_shape = x.shape[:2] + spatial
    return jax.image.resize(x, out_shape, method=method)


register_op("bilinear_interp", lambda x, size: _resize(x, size, "bilinear"))
register_op("nearest_interp", lambda x, size: _resize(x, size, "nearest"))
register_op("bicubic_interp", lambda x, size: _resize(x, size, "cubic"))
register_op("linear_interp", lambda x, size: _resize(x, size, "linear"))
register_op("trilinear_interp",
            lambda x, size: _resize(x, size, "trilinear"))


def bilinear_interp(x, size, name=None):
    return apply("bilinear_interp", x, size=tuple(int(s) for s in size))


def nearest_interp(x, size, name=None):
    return apply("nearest_interp", x, size=tuple(int(s) for s in size))


def bicubic_interp(x, size, name=None):
    return apply("bicubic_interp", x, size=tuple(int(s) for s in size))


def linear_interp(x, size, name=None):
    return apply("linear_interp", x, size=tuple(int(s) for s in size))


def trilinear_interp(x, size, name=None):
    return apply("trilinear_interp", x, size=tuple(int(s) for s in size))


# =============================================== sequence / misc utility

register_op("sequence_mask_k",
            lambda lengths, maxlen: (
                lax.broadcasted_iota(
                    jnp.int32, tuple(lengths.shape) + (maxlen,),
                    lengths.ndim)
                < lengths[..., None]).astype(jnp.int64))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """ops.yaml sequence_mask: [..., maxlen] 0/1 mask from lengths."""
    ml = int(maxlen) if maxlen is not None else int(x.numpy().max())
    out = apply("sequence_mask_k", x, maxlen=ml)
    if str(dtype) != "int64":
        from .manipulation import cast
        out = cast(out, dtype)
    return out


register_op("shard_index_k",
            lambda x, index_num, nshards, shard_id, ignore_value:
            jnp.where(x // (index_num // nshards) == shard_id,
                      x % (index_num // nshards), ignore_value))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """ops.yaml shard_index: recode global ids into per-shard ids."""
    return apply("shard_index_k", input, index_num=int(index_num),
                 nshards=int(nshards), shard_id=int(shard_id),
                 ignore_value=int(ignore_value))


register_op("label_smooth_k",
            lambda x, prior, epsilon: (1.0 - epsilon) * x
            + epsilon * (prior if prior is not None
                         else 1.0 / x.shape[-1]))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    """ops.yaml label_smooth (uniform or given prior distribution)."""
    return apply("label_smooth_k", label, prior_dist,
                 epsilon=float(epsilon))


register_op("gumbel_softmax_k",
            lambda x, key, tau, hard, axis: _gumbel_softmax(
                x, key, tau, hard, axis))


def _gumbel_softmax(x, key, tau, hard, axis):
    g = -jnp.log(-jnp.log(
        jax.random.uniform(key, x.shape, minval=1e-20, maxval=1.0)))
    y = jax.nn.softmax((x + g) / tau, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis)
        one = jnp.moveaxis(jax.nn.one_hot(
            idx, x.shape[axis], dtype=y.dtype), -1, axis)
        y = one + y - lax.stop_gradient(y)  # straight-through
    return y


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    """ops.yaml gumbel_softmax with straight-through hard mode."""
    return apply("gumbel_softmax_k", x, Tensor(rnd.next_key()),
                 tau=float(temperature), hard=bool(hard), axis=int(axis))


register_op("gru_unit_k",
            lambda x, h, wu, wr, wc: _gru_unit(x, h, wu, wr, wc))


def _gru_unit(x, h, wu, wr, wc):
    hx = jnp.concatenate([h, x], axis=-1)
    u = jax.nn.sigmoid(hx @ wu)
    r = jax.nn.sigmoid(hx @ wr)
    c = jnp.tanh(jnp.concatenate([r * h, x], axis=-1) @ wc)
    return (1.0 - u) * h + u * c


def gru_unit(x, hidden, weight_update, weight_reset, weight_cand,
             name=None):
    """ops.yaml gru_unit: one fused GRU cell step."""
    return apply("gru_unit_k", x, hidden, weight_update, weight_reset,
                 weight_cand)


register_op("partial_sum_k",
            lambda *xs, start, length: sum(
                x[:, start:start + length] for x in xs))


def partial_sum(xs, start_index=0, length=-1, name=None):
    """ops.yaml partial_sum: sum a column slice of each input."""
    ln = int(length) if length != -1 else xs[0].shape[1] - start_index
    return apply("partial_sum_k", *xs, start=int(start_index), length=ln)


register_op("partial_concat_k",
            lambda *xs, start, length: jnp.concatenate(
                [x[:, start:start + length] for x in xs], axis=-1))


def partial_concat(xs, start_index=0, length=-1, name=None):
    """ops.yaml partial_concat: concat a column slice of each input."""
    ln = int(length) if length != -1 else xs[0].shape[1] - start_index
    return apply("partial_concat_k", *xs, start=int(start_index),
                 length=ln)


register_op("shuffle_channel_k",
            lambda x, group: x.reshape(
                x.shape[0], group, x.shape[1] // group,
                *x.shape[2:]).swapaxes(1, 2).reshape(x.shape))


def shuffle_channel(x, group=1, name=None):
    return apply("shuffle_channel_k", x, group=int(group))


# ---------------------------------------------------- MoE aux op family
# (ops.yaml number_count / limit_by_capacity / prune_gate_by_capacity /
# random_routing — the reference's expert-parallel bookkeeping kernels)

register_op("number_count_k",
            lambda ids, upper: jnp.sum(
                jax.nn.one_hot(ids, upper, dtype=jnp.int64), axis=0))


def number_count(numbers, upper_range, name=None):
    return apply("number_count_k", numbers, upper=int(upper_range))


register_op("limit_by_capacity_k",
            lambda expert_count, capacity, n_worker:
            jnp.minimum(expert_count,
                        capacity.repeat(n_worker, axis=0)
                        if capacity.shape != expert_count.shape
                        else capacity))


def limit_by_capacity(expert_count, capacity, n_worker, name=None):
    return apply("limit_by_capacity_k", expert_count, capacity,
                 n_worker=int(n_worker))


def _prune_gate(gate_idx, expert_count, n_expert):
    # position of each token within its expert's queue
    one = jax.nn.one_hot(gate_idx, n_expert, dtype=jnp.int32)
    pos = jnp.cumsum(one, axis=0) * one
    rank = jnp.sum(pos, axis=-1) - 1
    cap = jnp.take(expert_count, jnp.clip(gate_idx, 0, n_expert - 1))
    return jnp.where(rank < cap, gate_idx, -1)


register_op("prune_gate_by_capacity_k", _prune_gate)


def prune_gate_by_capacity(gate_idx, expert_count, n_expert, n_worker=1,
                           name=None):
    return apply("prune_gate_by_capacity_k", gate_idx, expert_count,
                 n_expert=int(n_expert))


register_op("random_routing_k",
            lambda prob, topk_value, topk_idx, key:
            jnp.where(jax.random.uniform(key, topk_idx.shape)
                      < jnp.clip(prob, 0.0, 1.0),
                      topk_idx, -1))


def random_routing(topk_idx, topk_value, prob, name=None):
    return apply("random_routing_k", prob, topk_value, topk_idx,
                 Tensor(rnd.next_key()))


# ------------------------------------------------------- random family
# registered forms of the creation-time samplers (ops.yaml randint /
# randperm / uniform / gaussian / bernoulli / multinomial) — key-fed so
# they stay jittable and record into static programs.

register_op("randint_k",
            lambda key, low, high, shape: jax.random.randint(
                key, tuple(shape), low, high, dtype=jnp.int64))
register_op("randperm_k",
            lambda key, n: jax.random.permutation(key, n)
            .astype(jnp.int64))
register_op("uniform_k",
            lambda key, shape, lo, hi: jax.random.uniform(
                key, tuple(shape), minval=lo, maxval=hi))
register_op("gaussian_k",
            lambda key, shape, mean, std: mean
            + std * jax.random.normal(key, tuple(shape)))
register_op("bernoulli_k",
            lambda x, key: jax.random.bernoulli(key, x)
            .astype(x.dtype))
def _multinomial(x, key, num, replacement):
    logits = jnp.log(jnp.clip(x, 1e-30))
    if replacement:
        if x.ndim > 1:
            return jax.random.categorical(
                key, logits, shape=(num,) + x.shape[:-1]).T
        return jax.random.categorical(key, logits, shape=(num,))
    # without replacement: Gumbel top-k (exact for categorical w/o repl)
    g = -jnp.log(-jnp.log(jax.random.uniform(
        key, x.shape, minval=1e-20, maxval=1.0)))
    _, idx = lax.top_k(logits + g, num)
    return idx


register_op("multinomial_k", _multinomial)


# ----------------------------------------------------- metric op family

register_op("accuracy_k",
            lambda pred_idx, label: jnp.mean(
                jnp.any(pred_idx == label.reshape(-1, 1), axis=-1)
                .astype(jnp.float32)))


def accuracy_op(topk_indices, label, name=None):
    """ops.yaml accuracy: fraction of rows whose label is in top-k."""
    return apply("accuracy_k", topk_indices, label)


def _auc_kernel(pred, label, num_thresholds):
    # stateless AUC by threshold buckets (ops.yaml auc, one shot)
    thr = jnp.linspace(0.0, 1.0, num_thresholds)
    p = pred[:, -1] if pred.ndim > 1 else pred
    pos = (label.reshape(-1) > 0).astype(jnp.float32)
    neg = 1.0 - pos
    tp = jnp.sum(pos[None, :] * (p[None, :] >= thr[:, None]), axis=1)
    fp = jnp.sum(neg[None, :] * (p[None, :] >= thr[:, None]), axis=1)
    tpr = tp / jnp.clip(jnp.sum(pos), 1.0)
    fpr = fp / jnp.clip(jnp.sum(neg), 1.0)
    return jnp.trapezoid(jnp.flip(tpr), jnp.flip(fpr))


register_op("auc_k", _auc_kernel)


def auc_op(pred, label, num_thresholds=200, name=None):
    return apply("auc_k", pred, label,
                 num_thresholds=int(num_thresholds))


# ------------------------------------------------------ edit / decoding

def _edit_distance(a, b, a_len, b_len):
    # Levenshtein over padded int sequences via the standard DP,
    # scanned over the second string (fixed shapes; ops.yaml
    # edit_distance semantics, normalized=False)
    ta = a.shape[-1]

    def per_pair(av, bv, al, bl):
        row0 = jnp.arange(ta + 1, dtype=jnp.int32)

        def body(carry, j):
            row = carry
            jv = bv[j]

            def inner(prev_and_row, i):
                prev_diag, newrow = prev_and_row
                cost = jnp.where(av[i] == jv, 0, 1)
                val = jnp.minimum(
                    jnp.minimum(newrow[i] + 1, row[i + 1] + 1),
                    prev_diag + cost)
                return (row[i + 1],
                        newrow.at[i + 1].set(val)), None

            init = row.at[0].set(row[0] + 1)
            (_, newrow), _ = lax.scan(
                inner, (row[0], init), jnp.arange(ta))
            return jnp.where(j < bl, newrow, row), None

        row, _ = lax.scan(body, row0, jnp.arange(b.shape[-1]))
        return row[al]

    return jax.vmap(per_pair)(a, b, a_len, b_len).astype(jnp.float32)


register_op("edit_distance_k", _edit_distance)


def edit_distance(hyps, refs, hyps_len, refs_len, normalized=False,
                  name=None):
    """ops.yaml edit_distance over padded int id sequences."""
    out = apply("edit_distance_k", hyps, refs, hyps_len, refs_len)
    if normalized:
        return out / refs_len.astype("float32")
    return out


def _viterbi(potentials, trans, lengths):
    # scores [B, T, N], trans [N, N] -> best path [B, T] + score.
    # Steps at t >= lengths[b] leave sample b's score untouched and
    # record identity backpointers, so ragged batches decode correctly
    # (padded path tail repeats the final tag).
    b, t, n = potentials.shape
    if lengths is None:
        lengths = jnp.full((b,), t, jnp.int32)

    def step(carry, inp):
        score = carry                      # [B, N]
        emit, tstep = inp
        cand = score[:, :, None] + trans[None]   # [B, N, N]
        best = jnp.max(cand, axis=1) + emit
        back = jnp.argmax(cand, axis=1)
        active = (tstep < lengths)[:, None]
        ident = jnp.broadcast_to(jnp.arange(n)[None, :], (b, n))
        return (jnp.where(active, best, score),
                jnp.where(active, back, ident))

    score0 = potentials[:, 0]
    score, backs = lax.scan(
        step, score0,
        (jnp.moveaxis(potentials[:, 1:], 1, 0),
         jnp.arange(1, t)))
    last = jnp.argmax(score, axis=-1)

    def walk(carry, back):
        idx = carry
        prev = jnp.take_along_axis(back, idx[:, None], axis=-1)[:, 0]
        return prev, prev

    _, path_rev = lax.scan(walk, last, jnp.flip(backs, axis=0))
    path = jnp.concatenate(
        [jnp.flip(path_rev, axis=0), last[None]], axis=0)
    return jnp.moveaxis(path, 0, 1).astype(jnp.int64), \
        jnp.max(score, axis=-1)


register_op("viterbi_decode_k", _viterbi, multi_output=True)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=False, name=None):
    """ops.yaml viterbi_decode (dense CRF decoding)."""
    path, score = apply("viterbi_decode_k", potentials,
                        transition_params, lengths)
    return score, path


register_op("box_clip_k",
            lambda boxes, im_hw: jnp.stack([
                jnp.clip(boxes[..., 0], 0, im_hw[1] - 1),
                jnp.clip(boxes[..., 1], 0, im_hw[0] - 1),
                jnp.clip(boxes[..., 2], 0, im_hw[1] - 1),
                jnp.clip(boxes[..., 3], 0, im_hw[0] - 1)], axis=-1))


def box_clip(input, im_info, name=None):
    """ops.yaml box_clip: clamp xyxy boxes into the image."""
    return apply("box_clip_k", input, im_info)


def _prior_box(fmap_hw, image_hw, min_sizes, max_sizes, aspect_ratios):
    fh, fw = fmap_hw
    ih, iw = image_hw
    sx = iw / fw
    sy = ih / fh
    cx = (jnp.arange(fw) + 0.5) * sx
    cy = (jnp.arange(fh) + 0.5) * sy
    boxes = []
    for ms in min_sizes:
        whs = [(ms, ms)]
        for ar in aspect_ratios:
            whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
        for mx in max_sizes:
            whs.append(((ms * mx) ** 0.5, (ms * mx) ** 0.5))
        for w, h in whs:
            x0 = (cx[None, :] - w / 2) / iw
            y0 = (cy[:, None] - h / 2) / ih
            x1 = (cx[None, :] + w / 2) / iw
            y1 = (cy[:, None] + h / 2) / ih
            boxes.append(jnp.stack(jnp.broadcast_arrays(
                x0, y0, x1, y1), axis=-1))
    return jnp.stack(boxes, axis=2).reshape(fh, fw, len(boxes), 4)


register_op("prior_box_k",
            lambda fh, fw, ih, iw, min_sizes, max_sizes, aspect_ratios:
            _prior_box((fh, fw), (ih, iw), min_sizes, max_sizes,
                       aspect_ratios))


def prior_box(input, image, min_sizes, max_sizes=(), aspect_ratios=(1.0,),
              name=None, **kwargs):
    """ops.yaml prior_box: SSD anchor generation."""
    fh, fw = input.shape[-2], input.shape[-1]
    ih, iw = image.shape[-2], image.shape[-1]
    return apply("prior_box_k", fh=int(fh), fw=int(fw), ih=int(ih),
                 iw=int(iw), min_sizes=tuple(float(m) for m in min_sizes),
                 max_sizes=tuple(float(m) for m in max_sizes),
                 aspect_ratios=tuple(float(a) for a in aspect_ratios))
