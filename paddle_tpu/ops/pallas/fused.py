"""Fused elementwise/norm Pallas kernels.

TPU-native equivalents of the reference's hand-fused CUDA kernels surfaced
via python/paddle/incubate/nn/functional (fused_rms_norm, swiglu,
fused_rotary_position_embedding; CUDA impls under
paddle/phi/kernels/fusion/gpu). Forward runs as a Pallas kernel (VPU,
rows resident in VMEM); backward uses the closed-form jnp VJP — XLA fuses
the backward fine, the win the kernel buys is the single-pass fp32
row-statistics forward on bf16 activations.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _row_block(n: int) -> int:
    for cand in (256, 128, 64, 32, 16, 8):
        if n % cand == 0:
            return cand
    return n


# ----------------------------------------------------------------- rms_norm

def _rms_kernel(x_ref, w_ref, y_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    y_ref[...] = (y * w_ref[...].astype(jnp.float32)).astype(y_ref.dtype)


def _rms_fwd_pallas(x2, w, eps):
    n, h = x2.shape
    bn = _row_block(n)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bn, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x2.dtype),
        interpret=_interpret(),
    )(x2, w.reshape(1, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms(x2, w, eps):
    return _rms_fwd_pallas(x2, w, eps)


def _rms_fwd(x2, w, eps):
    return _rms_fwd_pallas(x2, w, eps), (x2, w)


def _rms_bwd(eps, res, g):
    x2, w = res
    x = x2.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + eps)
    xhat = x * r
    dw = jnp.sum(gf * xhat, axis=0).astype(w.dtype)
    gx = gf * wf
    h = x.shape[-1]
    dx = r * (gx - xhat * jnp.sum(gx * xhat, axis=-1, keepdims=True) / h)
    return dx.astype(x2.dtype), dw


_rms.defvjp(_rms_fwd, _rms_bwd)


def _is_tensor(x):
    from ..._core.tensor import Tensor
    return isinstance(x, Tensor)


def rms_norm(x, weight, epsilon=1e-6):
    """fused_rms_norm analog on raw arrays or Tensors; normalizes the last
    axis. Returns same-shape output."""
    unwrap = _is_tensor(x)
    xv = x._value if unwrap else x
    wv = weight._value if _is_tensor(weight) else weight
    shape = xv.shape
    y = _rms(xv.reshape(-1, shape[-1]), wv, float(epsilon)).reshape(shape)
    if unwrap:
        from ..._core.executor import apply
        from ..._core.op_registry import all_ops, register_op
        if "fused_rms_norm" not in all_ops():
            register_op(
                "fused_rms_norm",
                lambda xa, wa, eps: _rms(
                    xa.reshape(-1, xa.shape[-1]), wa, eps).reshape(xa.shape))
        return apply("fused_rms_norm", x, weight, eps=float(epsilon))
    return y


# ------------------------------------------------------------------ swiglu

def _swiglu_kernel(x_ref, g_ref, y_ref):
    x = x_ref[...].astype(jnp.float32)
    y_ref[...] = (jax.nn.silu(x) * g_ref[...].astype(jnp.float32)).astype(
        y_ref.dtype)


def _swiglu_fwd_pallas(x2, g2):
    n, h = x2.shape
    bn = _row_block(n)
    spec = pl.BlockSpec((bn, h), lambda i: (i, 0))
    return pl.pallas_call(
        _swiglu_kernel, grid=(n // bn,),
        in_specs=[spec, spec], out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((n, h), x2.dtype),
        interpret=_interpret(),
    )(x2, g2)


@jax.custom_vjp
def _swiglu(x2, g2):
    return _swiglu_fwd_pallas(x2, g2)


def _swiglu_fwd(x2, g2):
    return _swiglu_fwd_pallas(x2, g2), (x2, g2)


def _swiglu_bwd(res, dout):
    x2, g2 = res
    x = x2.astype(jnp.float32)
    g = g2.astype(jnp.float32)
    d = dout.astype(jnp.float32)
    sig = jax.nn.sigmoid(x)
    silu = x * sig
    dsilu = sig * (1 + x * (1 - sig))
    return ((d * g * dsilu).astype(x2.dtype),
            (d * silu).astype(g2.dtype))


_swiglu.defvjp(_swiglu_fwd, _swiglu_bwd)


def _swiglu_body(xa, ga):
    if ga is None:
        xa, ga = jnp.split(xa, 2, axis=-1)
    shape = xa.shape
    return _swiglu(xa.reshape(-1, shape[-1]),
                   ga.reshape(-1, shape[-1])).reshape(shape)


def swiglu(x, gate=None):
    """silu(x) * gate; with gate=None splits x in half on the last axis
    (reference incubate/nn/functional/swiglu semantics)."""
    if _is_tensor(x):
        from ..._core.executor import apply
        from ..._core.op_registry import all_ops, register_op
        if "fused_swiglu" not in all_ops():
            register_op("fused_swiglu", _swiglu_body)
        return apply("fused_swiglu", x, gate)
    return _swiglu_body(x, gate)


# -------------------------------------------------------------------- rope

def _rope_half(x, cos, sin):
    # rotate-half convention on the last axis, fp32 trig applied per
    # position; cos/sin: [S, D] broadcast over batch/heads.
    x1, x2 = jnp.split(x, 2, axis=-1)
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return (x.astype(jnp.float32) * cos + rot.astype(jnp.float32) * sin
            ).astype(x.dtype)


def _rope_body(q, k, cos, sin):
    # q/k: [B, S, H, D]; cos/sin: [S, D] or [1, S, 1, D]
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    qo = _rope_half(q, cos, sin)
    ko = _rope_half(k, cos, sin) if k is not None else None
    return (qo, ko) if ko is not None else qo


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """incubate/nn/functional/fused_rotary_position_embedding analog.

    Returns (q, k, v) tuple like the reference; v passes through
    unrotated when given.
    """
    from ..._core.tensor import Tensor
    qv = q._value if isinstance(q, Tensor) else q
    kv = k._value if isinstance(k, Tensor) else k
    s, d = qv.shape[1], qv.shape[-1]
    if cos is None:
        inv = 1.0 / (10000.0 ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        t = jnp.arange(s, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        emb = jnp.concatenate([freqs, freqs], axis=-1)
        cosv, sinv = jnp.cos(emb), jnp.sin(emb)
    else:
        cosv = cos._value if _is_tensor(cos) else cos
        sinv = sin._value if _is_tensor(sin) else sin
        cosv = cosv.reshape(cosv.shape[-2], cosv.shape[-1])
        sinv = sinv.reshape(sinv.shape[-2], sinv.shape[-1])
    if position_ids is not None:
        pid = position_ids._value if _is_tensor(position_ids) \
            else position_ids
        cosv = jnp.take(cosv, pid, axis=0)[0]
        sinv = jnp.take(sinv, pid, axis=0)[0]
    if isinstance(q, Tensor) and k is not None:
        from ..._core.executor import apply
        from ..._core.op_registry import all_ops, register_op
        if "fused_rope" not in all_ops():
            register_op("fused_rope", _rope_body, multi_output=True)
        qo, ko = apply("fused_rope", q, k, Tensor(cosv), Tensor(sinv))
        return qo, ko, v
    out = _rope_body(qv, kv, cosv, sinv)
    if kv is None:
        return out, None, v
    return out[0], out[1], v
