"""Pallas TPU kernel layer.

This is the TPU-native replacement for two reference subsystems at once:
the dynloaded CUDA flash-attention library
(paddle/phi/backends/dynload/flashattn.cc) and the hand-fused CUDA kernels
under paddle/phi/kernels/fusion/gpu (fused_attention, fused_rms_norm,
swiglu, rope). Instead of NVRTC/CINN codegen, hot ops are written directly
against the TPU memory hierarchy (HBM -> VMEM -> MXU/VPU) with
jax.experimental.pallas; everything falls back to the fused XLA path off-TPU
(interpret mode keeps the kernels testable on the CPU mesh).
"""
from .flash_attention import flash_attention, mha_forward
from .fused import rms_norm, swiglu, fused_rotary_position_embedding

__all__ = [
    "flash_attention", "mha_forward", "rms_norm", "swiglu",
    "fused_rotary_position_embedding",
]
