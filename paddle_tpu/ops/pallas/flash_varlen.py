"""Block-sparse varlen + flashmask attention Pallas kernels.

The reference treats variable-length (cu_seqlens) and flashmask
(startend_row_indices) attention as first-class flash kernels
(python/paddle/nn/functional/flash_attention.py:756 flash_attn_unpadded,
:1299 flashmask_attention, dynloaded CUDA flashattn underneath). The
TPU-native equivalents here are Pallas kernels that never materialise a
[T, T] mask:

- varlen: ragged batches packed as [total_tokens, H, D]. Per-token
  segment ids + in-segment positions drive the mask; per-query-block
  key-block bounds (computed from cu_seqlens with O(T) work) make the
  kernel skip key blocks outside the query block's segments, so compute
  is O(sum_i T_i^2 / block) and memory O(T·block) — not O(T^2).
- flashmask: per-key-column [start, end) banned query-row intervals.
  Key blocks whose columns ban the whole query block are skipped with
  lax.cond; everything else gets a per-element mask in-register.
  Query rows whose keys are ALL banned produce zeros (the flash l == 0
  convention; a dense softmax would degenerate to uniform attention).

Both have full custom-VJP backward (dKV over key blocks, dQ over query
blocks) with identical block skipping. Off-TPU the kernels run in
interpret mode, so the CPU test mesh executes the same code the TPU
compiles (the numerics-parity tests compare against the dense-mask
reference path in nn/functional/flash_attention.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _interpret, _no_x64

_BQ = 128
_BK = 128


def _pad_to(x, n, axis):
    pad = n - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _cdiv(a, b):
    return -(-a // b)


# ===================================================== varlen (cu_seqlens)

def _varlen_meta(cu, t_pad, pad_seg):
    """Per-token segment id (+pad_seg for padding) and in-segment
    position, all int32, shaped [t_pad, 1] for TPU-friendly blocks."""
    cu = cu.astype(jnp.int32)
    nseg = cu.shape[0] - 1
    tok = jnp.arange(t_pad, dtype=jnp.int32)
    seg = jnp.searchsorted(cu, tok, side="right").astype(jnp.int32) - 1
    seg = jnp.clip(seg, 0, nseg - 1)
    pos = tok - jnp.take(cu, seg)
    seg = jnp.where(tok < cu[-1], seg, pad_seg)
    return seg[:, None], pos[:, None]


def _varlen_qblock_bounds(seg_q, pos_q, cu_k, bq, bk, tk_pad, causal):
    """[nqb] int32 lo/hi key-block bounds per query block."""
    cu_k = cu_k.astype(jnp.int32)
    nseg = cu_k.shape[0] - 1
    nqb = seg_q.shape[0] // bq
    s2 = seg_q[:, 0].reshape(nqb, bq)
    valid = s2 >= 0
    smin = jnp.min(jnp.where(valid, s2, nseg), axis=1)
    smax = jnp.max(jnp.where(valid, s2, -1), axis=1)
    any_valid = jnp.any(valid, axis=1)
    lo_tok = jnp.take(cu_k, jnp.clip(smin, 0, nseg))
    hi_tok = jnp.take(cu_k, jnp.clip(smax + 1, 0, nseg))
    if causal:
        p2 = pos_q[:, 0].reshape(nqb, bq)
        base = jnp.take(cu_k, jnp.clip(s2, 0, nseg - 1))
        kmax = jnp.where(valid, base + p2 + 1, 0)
        hi_tok = jnp.minimum(hi_tok, jnp.max(kmax, axis=1))
    lo = jnp.where(any_valid, lo_tok // bk, 0).astype(jnp.int32)
    hi = jnp.where(any_valid, jnp.minimum(_cdiv(hi_tok, bk), tk_pad // bk),
                   0).astype(jnp.int32)
    return lo, hi


def _varlen_kblock_bounds(seg_k, pos_k, cu_q, bk, bq, tq_pad, causal):
    """[nkb] int32 lo/hi QUERY-block bounds per key block (for dKV)."""
    cu_q = cu_q.astype(jnp.int32)
    nseg = cu_q.shape[0] - 1
    nkb = seg_k.shape[0] // bk
    s2 = seg_k[:, 0].reshape(nkb, bk)
    valid = s2 >= 0
    smin = jnp.min(jnp.where(valid, s2, nseg), axis=1)
    smax = jnp.max(jnp.where(valid, s2, -1), axis=1)
    any_valid = jnp.any(valid, axis=1)
    lo_tok = jnp.take(cu_q, jnp.clip(smin, 0, nseg))
    hi_tok = jnp.take(cu_q, jnp.clip(smax + 1, 0, nseg))
    if causal:
        # a key at (seg, pos) is visible only to queries at pos_q >= pos
        p2 = pos_k[:, 0].reshape(nkb, bk)
        base = jnp.take(cu_q, jnp.clip(s2, 0, nseg - 1))
        qmin = jnp.where(valid, base + p2, tq_pad)
        lo_tok = jnp.maximum(lo_tok, jnp.min(qmin, axis=1))
    lo = jnp.where(any_valid, lo_tok // bq, 0).astype(jnp.int32)
    hi = jnp.where(any_valid, jnp.minimum(_cdiv(hi_tok, bq), tq_pad // bq),
                   0).astype(jnp.int32)
    return lo, hi


def _v_fwd_kernel(q_ref, k_ref, v_ref, sq_ref, pq_ref, sk_ref, pk_ref,
                  lo_ref, hi_ref, o_ref, lse_ref, *, scale, causal,
                  block_k):
    q = q_ref[0]                                     # [bq, d]
    bq, d = q.shape
    seg_q = sq_ref[...]                              # [bq, 1]
    pos_q = pq_ref[...]
    qi = pl.program_id(1)
    lo = lo_ref[qi]
    hi = hi_ref[qi]

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        seg_k = jnp.swapaxes(sk_ref[pl.ds(j * block_k, block_k), :], 0, 1)
        pos_k = jnp.swapaxes(pk_ref[pl.ds(j * block_k, block_k), :], 0, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = seg_q == seg_k                        # [bq, bk]
        if causal:
            mask &= pos_k <= pos_q
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(lo, hi, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = jnp.where(l[:, None] == 0.0, 0.0,
                           m[:, None] + jnp.log(l_safe[:, None]))


def _v_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                  sq_ref, pq_ref, sk_ref, pk_ref, lo_ref, hi_ref,
                  dk_ref, dv_ref, *, scale, causal, block_q):
    k = k_ref[0]                                     # [bk, d]
    v = v_ref[0]
    bk, d = k.shape
    seg_k = jnp.swapaxes(sk_ref[...], 0, 1)          # [1, bk]
    pos_k = jnp.swapaxes(pk_ref[...], 0, 1)
    kj = pl.program_id(1)
    lo = lo_ref[kj]
    hi = hi_ref[kj]

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :]
        seg_q = sq_ref[pl.ds(i * block_q, block_q), :]   # [bq, 1]
        pos_q = pq_ref[pl.ds(i * block_q, block_q), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = seg_q == seg_k
        if causal:
            mask &= pos_k <= pos_q
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv_new = dv + jax.lax.dot_general(
            p, do.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_new = dk + jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, hi, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _v_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 sq_ref, pq_ref, sk_ref, pk_ref, lo_ref, hi_ref,
                 dq_ref, *, scale, causal, block_k):
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]
    delta = delta_ref[0]
    bq, d = q.shape
    seg_q = sq_ref[...]
    pos_q = pq_ref[...]
    qi = pl.program_id(1)
    lo = lo_ref[qi]
    hi = hi_ref[qi]

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        seg_k = jnp.swapaxes(sk_ref[pl.ds(j * block_k, block_k), :], 0, 1)
        pos_k = jnp.swapaxes(pk_ref[pl.ds(j * block_k, block_k), :], 0, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = seg_q == seg_k
        if causal:
            mask &= pos_k <= pos_q
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(lo, hi, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _v_specs(h, t_pad, tk_pad, d, bq, bk):
    qspec = pl.BlockSpec((1, bq, d), lambda hh, i: (hh, i, 0))
    kfull = pl.BlockSpec((1, tk_pad, d), lambda hh, i: (hh, 0, 0))
    mq = pl.BlockSpec((bq, 1), lambda hh, i: (i, 0))
    mkfull = pl.BlockSpec((tk_pad, 1), lambda hh, i: (0, 0))
    bound = pl.BlockSpec(memory_space=pltpu.SMEM)
    return qspec, kfull, mq, mkfull, bound


def _varlen_fwd(q, k, v, segq, posq, segk, posk, lo, hi, scale, causal,
                bq, bk):
    h, tq_pad, d = q.shape
    tk_pad = k.shape[1]
    qspec, kfull, mq, mkfull, bound = _v_specs(h, tq_pad, tk_pad, d, bq, bk)
    with _no_x64():
        out, lse = pl.pallas_call(
            functools.partial(_v_fwd_kernel, scale=scale, causal=causal,
                              block_k=bk),
            grid=(h, tq_pad // bq),
            in_specs=[qspec, kfull, kfull, mq, mq, mkfull, mkfull,
                      bound, bound],
            out_specs=[qspec,
                       pl.BlockSpec((1, bq, 1), lambda hh, i: (hh, i, 0))],
            out_shape=[jax.ShapeDtypeStruct((h, tq_pad, d), q.dtype),
                       jax.ShapeDtypeStruct((h, tq_pad, 1), jnp.float32)],
            interpret=_interpret(),
        )(q, k, v, segq, posq, segk, posk, lo, hi)
    return out, lse


def _varlen_bwd(q, k, v, out, lse, do, segq, posq, segk, posk,
                qlo, qhi, klo, khi, scale, causal, bq, bk):
    h, tq_pad, d = q.shape
    tk_pad = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    qfull = pl.BlockSpec((1, tq_pad, d), lambda hh, j: (hh, 0, 0))
    rowfull = pl.BlockSpec((1, tq_pad, 1), lambda hh, j: (hh, 0, 0))
    kspec = pl.BlockSpec((1, bk, d), lambda hh, j: (hh, j, 0))
    mqfull = pl.BlockSpec((tq_pad, 1), lambda hh, j: (0, 0))
    mk = pl.BlockSpec((bk, 1), lambda hh, j: (j, 0))
    kbound = pl.BlockSpec(memory_space=pltpu.SMEM)
    with _no_x64():
        dk, dv = pl.pallas_call(
            functools.partial(_v_dkv_kernel, scale=scale, causal=causal,
                              block_q=bq),
            grid=(h, tk_pad // bk),
            in_specs=[qfull, kspec, kspec, qfull, rowfull, rowfull,
                      mqfull, mqfull, mk, mk, kbound, kbound],
            out_specs=[kspec, kspec],
            out_shape=[jax.ShapeDtypeStruct((h, tk_pad, d), k.dtype),
                       jax.ShapeDtypeStruct((h, tk_pad, d), v.dtype)],
            interpret=_interpret(),
        )(q, k, v, do, lse, delta, segq, posq, segk, posk, klo, khi)

    qspec = pl.BlockSpec((1, bq, d), lambda hh, i: (hh, i, 0))
    row = pl.BlockSpec((1, bq, 1), lambda hh, i: (hh, i, 0))
    kf = pl.BlockSpec((1, tk_pad, d), lambda hh, i: (hh, 0, 0))
    mq = pl.BlockSpec((bq, 1), lambda hh, i: (i, 0))
    mkf = pl.BlockSpec((tk_pad, 1), lambda hh, i: (0, 0))
    qbound = pl.BlockSpec(memory_space=pltpu.SMEM)
    with _no_x64():
        dq = pl.pallas_call(
            functools.partial(_v_dq_kernel, scale=scale, causal=causal,
                              block_k=bk),
            grid=(h, tq_pad // bq),
            in_specs=[qspec, kf, kf, qspec, row, row,
                      mq, mq, mkf, mkf, qbound, qbound],
            out_specs=qspec,
            out_shape=jax.ShapeDtypeStruct((h, tq_pad, d), q.dtype),
            interpret=_interpret(),
        )(q, k, v, do, lse, delta, segq, posq, segk, posk, qlo, qhi)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(11, 12, 13, 14))
def _varlen(q, k, v, segq, posq, segk, posk, qlo, qhi, klo, khi,
            scale, causal, bq, bk):
    out, _ = _varlen_fwd(q, k, v, segq, posq, segk, posk, qlo, qhi,
                         scale, causal, bq, bk)
    return out


def _varlen_fwd_rule(q, k, v, segq, posq, segk, posk, qlo, qhi, klo, khi,
                     scale, causal, bq, bk):
    out, lse = _varlen_fwd(q, k, v, segq, posq, segk, posk, qlo, qhi,
                           scale, causal, bq, bk)
    return out, (q, k, v, out, lse, segq, posq, segk, posk,
                 qlo, qhi, klo, khi)


def _varlen_bwd_rule(scale, causal, bq, bk, res, do):
    (q, k, v, out, lse, segq, posq, segk, posk, qlo, qhi, klo, khi) = res
    dq, dk, dv = _varlen_bwd(q, k, v, out, lse, do, segq, posq, segk,
                             posk, qlo, qhi, klo, khi, scale, causal,
                             bq, bk)
    return (dq, dk, dv) + (None,) * 8


_varlen.defvjp(_varlen_fwd_rule, _varlen_bwd_rule)


def _varlen_body(q, k, v, cu_q, cu_k, scale, causal):
    """Registered kernel body: packed [T, H, D] inputs."""
    tq, h, d = q.shape
    tk = k.shape[0]
    bq = min(_BQ, _cdiv(tq, 1))
    bk = min(_BK, _cdiv(tk, 1))
    tq_pad = _cdiv(tq, bq) * bq
    tk_pad = _cdiv(tk, bk) * bk
    qt = _pad_to(jnp.moveaxis(q, 1, 0), tq_pad, 1)     # [H, Tq, D]
    kt = _pad_to(jnp.moveaxis(k, 1, 0), tk_pad, 1)
    vt = _pad_to(jnp.moveaxis(v, 1, 0), tk_pad, 1)
    segq, posq = _varlen_meta(cu_q, tq_pad, pad_seg=-1)
    segk, posk = _varlen_meta(cu_k, tk_pad, pad_seg=-2)
    qlo, qhi = _varlen_qblock_bounds(segq, posq, cu_k, bq, bk, tk_pad,
                                     causal)
    klo, khi = _varlen_kblock_bounds(segk, posk, cu_q, bk, bq, tq_pad,
                                     causal)
    out = _varlen(qt, kt, vt, segq, posq, segk, posk, qlo, qhi, klo, khi,
                  float(scale), bool(causal), bq, bk)
    return jnp.moveaxis(out[:, :tq, :], 0, 1)          # [Tq, H, D]


def flash_attn_varlen(query, key, value, cu_seqlens_q, cu_seqlens_k,
                      scale=None, causal=False):
    """Public block-sparse varlen entry on framework Tensors. Packed
    layout [total_tokens, num_heads, head_dim] with int32 cu_seqlens."""
    from ..._core.executor import apply
    from ..._core.op_registry import all_ops, register_op
    if "flash_attn_varlen" not in all_ops():
        register_op("flash_attn_varlen", _varlen_body)
    if scale is None:
        scale = 1.0 / (query.shape[-1] ** 0.5)
    return apply("flash_attn_varlen", query, key, value, cu_seqlens_q,
                 cu_seqlens_k, scale=float(scale), causal=bool(causal))


# ============================================ flashmask (startend indices)

def _fm_fwd_kernel(q_ref, k_ref, v_ref, st_ref, en_ref, o_ref, lse_ref, *,
                   scale, causal, block_k, kv_len):
    qi = pl.program_id(1)
    q = q_ref[0]
    bq, d = q.shape
    sk_pad = k_ref.shape[1]
    nkb = sk_pad // block_k
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
    q_lo = qi * bq
    q_hi = q_lo + bq

    def compute(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        st = jnp.swapaxes(st_ref[0, pl.ds(j * block_k, block_k), :], 0, 1)
        en = jnp.swapaxes(en_ref[0, pl.ds(j * block_k, block_k), :], 0, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        ban = (q_pos >= st) & (q_pos < en)
        mask = ~ban & (k_pos < kv_len)
        if causal:
            mask &= k_pos <= q_pos
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    def body(j, carry):
        # skip key blocks whose every column bans the whole query block
        # (int32 min-reduction: Mosaic only scalarises 32-bit types)
        st = st_ref[0, pl.ds(j * block_k, block_k), :]
        en = en_ref[0, pl.ds(j * block_k, block_k), :]
        ok = ((st <= q_lo) & (en >= q_hi)).astype(jnp.int32)
        full_ban = jnp.min(ok) == 1
        return jax.lax.cond(full_ban, lambda c: c,
                            lambda c: compute(j, c), carry)

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        nkb_eff = jnp.minimum(((qi * bq + bq - 1) // block_k) + 1, nkb)
    else:
        nkb_eff = nkb
    m, l, acc = jax.lax.fori_loop(0, nkb_eff, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = jnp.where(l[:, None] == 0.0, 0.0,
                           m[:, None] + jnp.log(l_safe[:, None]))


def _fm_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   st_ref, en_ref, dk_ref, dv_ref, *, scale, causal,
                   block_q, kv_len):
    kj = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    bk, d = k.shape
    sq = q_ref.shape[1]
    nqb = sq // block_q
    st_col = jnp.swapaxes(st_ref[0], 0, 1)           # [1, bk]
    en_col = jnp.swapaxes(en_ref[0], 0, 1)
    k_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def compute(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0)
        ban = (q_pos >= st_col) & (q_pos < en_col)
        mask = ~ban & (k_pos < kv_len)
        if causal:
            mask &= k_pos <= q_pos
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv_new = dv + jax.lax.dot_general(
            p, do.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_new = dk + jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    def body(i, carry):
        q_lo = i * block_q
        q_hi = q_lo + block_q
        ok = ((st_col <= q_lo) & (en_col >= q_hi)).astype(jnp.int32)
        full_ban = jnp.min(ok) == 1
        return jax.lax.cond(full_ban, lambda c: c,
                            lambda c: compute(i, c), carry)

    if causal:
        first = jnp.maximum((kj * bk) // block_q, 0)
    else:
        first = 0
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first, nqb, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fm_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                  st_ref, en_ref, dq_ref, *, scale, causal, block_k,
                  kv_len):
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]
    delta = delta_ref[0]
    bq, d = q.shape
    sk = k_ref.shape[1]
    nkb = sk // block_k
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
    q_lo = qi * bq
    q_hi = q_lo + bq

    def compute(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        st = jnp.swapaxes(st_ref[0, pl.ds(j * block_k, block_k), :], 0, 1)
        en = jnp.swapaxes(en_ref[0, pl.ds(j * block_k, block_k), :], 0, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        ban = (q_pos >= st) & (q_pos < en)
        mask = ~ban & (k_pos < kv_len)
        if causal:
            mask &= k_pos <= q_pos
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def body(j, dq):
        st = st_ref[0, pl.ds(j * block_k, block_k), :]
        en = en_ref[0, pl.ds(j * block_k, block_k), :]
        ok = ((st <= q_lo) & (en >= q_hi)).astype(jnp.int32)
        full_ban = jnp.min(ok) == 1
        return jax.lax.cond(full_ban, lambda c: c,
                            lambda c: compute(j, c), dq)

    if causal:
        nkb_eff = jnp.minimum(((qi * bq + bq - 1) // block_k) + 1, nkb)
    else:
        nkb_eff = nkb
    dq = jax.lax.fori_loop(0, nkb_eff, body,
                           jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _fm_fwd(q, k, v, st, en, scale, causal, bq, bk, kv_len):
    bh, sq_pad, d = q.shape
    sk_pad = k.shape[1]
    qspec = pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0))
    kfull = pl.BlockSpec((1, sk_pad, d), lambda b, i: (b, 0, 0))
    colfull = pl.BlockSpec((1, sk_pad, 1), lambda b, i: (b, 0, 0))
    with _no_x64():
        out, lse = pl.pallas_call(
            functools.partial(_fm_fwd_kernel, scale=scale, causal=causal,
                              block_k=bk, kv_len=kv_len),
            grid=(bh, sq_pad // bq),
            in_specs=[qspec, kfull, kfull, colfull, colfull],
            out_specs=[qspec,
                       pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0))],
            out_shape=[jax.ShapeDtypeStruct((bh, sq_pad, d), q.dtype),
                       jax.ShapeDtypeStruct((bh, sq_pad, 1), jnp.float32)],
            interpret=_interpret(),
        )(q, k, v, st, en)
    return out, lse


def _fm_bwd(q, k, v, out, lse, do, st, en, scale, causal, bq, bk, kv_len):
    bh, sq_pad, d = q.shape
    sk_pad = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    qfull = pl.BlockSpec((1, sq_pad, d), lambda b, j: (b, 0, 0))
    rowfull = pl.BlockSpec((1, sq_pad, 1), lambda b, j: (b, 0, 0))
    kspec = pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0))
    colspec = pl.BlockSpec((1, bk, 1), lambda b, j: (b, j, 0))
    with _no_x64():
        dk, dv = pl.pallas_call(
            functools.partial(_fm_dkv_kernel, scale=scale, causal=causal,
                              block_q=bq, kv_len=kv_len),
            grid=(bh, sk_pad // bk),
            in_specs=[qfull, kspec, kspec, qfull, rowfull, rowfull,
                      colspec, colspec],
            out_specs=[kspec, kspec],
            out_shape=[jax.ShapeDtypeStruct((bh, sk_pad, d), k.dtype),
                       jax.ShapeDtypeStruct((bh, sk_pad, d), v.dtype)],
            interpret=_interpret(),
        )(q, k, v, do, lse, delta, st, en)

    qspec = pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0))
    row = pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0))
    kf = pl.BlockSpec((1, sk_pad, d), lambda b, i: (b, 0, 0))
    colf = pl.BlockSpec((1, sk_pad, 1), lambda b, i: (b, 0, 0))
    with _no_x64():
        dq = pl.pallas_call(
            functools.partial(_fm_dq_kernel, scale=scale, causal=causal,
                              block_k=bk, kv_len=kv_len),
            grid=(bh, sq_pad // bq),
            in_specs=[qspec, kf, kf, qspec, row, row, colf, colf],
            out_specs=qspec,
            out_shape=jax.ShapeDtypeStruct((bh, sq_pad, d), q.dtype),
            interpret=_interpret(),
        )(q, k, v, do, lse, delta, st, en)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _fmask(q, k, v, st, en, scale, causal, bq, bk, kv_len):
    out, _ = _fm_fwd(q, k, v, st, en, scale, causal, bq, bk, kv_len)
    return out


def _fmask_fwd_rule(q, k, v, st, en, scale, causal, bq, bk, kv_len):
    out, lse = _fm_fwd(q, k, v, st, en, scale, causal, bq, bk, kv_len)
    return out, (q, k, v, out, lse, st, en)


def _fmask_bwd_rule(scale, causal, bq, bk, kv_len, res, do):
    q, k, v, out, lse, st, en = res
    dq, dk, dv = _fm_bwd(q, k, v, out, lse, do, st, en, scale, causal,
                         bq, bk, kv_len)
    return dq, dk, dv, None, None


_fmask.defvjp(_fmask_fwd_rule, _fmask_bwd_rule)


def _flashmask_body(q, k, v, startend, scale, causal):
    """Registered kernel body. q/k/v [B, S, H, D]; startend
    [B, H or 1, S, 1 or 2] int (LT semantics: key column j is banned for
    query rows in [start_j, end_j), matching the dense reference in
    nn/functional/flash_attention.py:_flashmask_to_dense)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    bq = min(_BQ, sq)
    bk = min(_BK, sk)
    sq_pad = _cdiv(sq, bq) * bq
    sk_pad = _cdiv(sk, bk) * bk
    qt = _pad_to(jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d), sq_pad, 1)
    kt = _pad_to(jnp.swapaxes(k, 1, 2).reshape(b * h, sk, d), sk_pad, 1)
    vt = _pad_to(jnp.swapaxes(v, 1, 2).reshape(b * h, sk, d), sk_pad, 1)
    idx = startend.astype(jnp.int32)
    if idx.shape[1] == 1 and h > 1:
        idx = jnp.broadcast_to(idx, (b, h, sk) + idx.shape[3:])
    st = idx[..., 0].reshape(b * h, sk)
    if idx.shape[-1] > 1:
        en = idx[..., 1].reshape(b * h, sk)
    else:
        # open-ended ban: use int32 max, not sk_pad + 1, so query rows
        # beyond the key length (sq > sk) are still inside the interval
        en = jnp.full_like(st, jnp.iinfo(jnp.int32).max)
    # padded key columns: banned everywhere via kv_len; padded query rows
    # produce zeros (l == 0) and are sliced off
    st = _pad_to(st, sk_pad, 1)[..., None]
    en = _pad_to(en, sk_pad, 1)[..., None]
    out = _fmask(qt, kt, vt, st, en, float(scale), bool(causal),
                 bq, bk, sk)
    return jnp.swapaxes(out[:, :sq, :].reshape(b, h, sq, d), 1, 2)


def flashmask_attention_pallas(query, key, value, startend_row_indices,
                               scale=None, causal=True):
    """Public block-sparse flashmask entry on framework Tensors."""
    from ..._core.executor import apply
    from ..._core.op_registry import all_ops, register_op
    if "flashmask_attention" not in all_ops():
        register_op("flashmask_attention", _flashmask_body)
    if scale is None:
        scale = 1.0 / (query.shape[-1] ** 0.5)
    return apply("flashmask_attention", query, key, value,
                 startend_row_indices, scale=float(scale),
                 causal=bool(causal))
