"""Flash attention as a Pallas TPU kernel with a custom VJP.

Online-softmax blocked attention (the same math the reference reaches via
the dynloaded flashattn CUDA lib, paddle/phi/backends/dynload/flashattn.cc;
surface at python/paddle/nn/functional/flash_attention.py). Forward streams
K/V blocks through VMEM against a resident Q block, carrying (m, l, acc)
accumulators; backward is the standard two-kernel split (dKV over key
blocks, dQ over query blocks) using the saved log-sum-exp rows.

Layout inside the kernels is [batch*heads, seq, head_dim]; the public entry
takes paddle's [batch, seq, heads, head_dim]. Logit math is fp32 on the MXU
(preferred_element_type), IO dtype is whatever the caller passes (bf16 on
TPU). Off-TPU the kernels run in interpret mode so the CPU test mesh
exercises identical code.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _no_x64():
    """Trace pallas kernels with x64 OFF: the framework enables
    jax_enable_x64 globally (paddle int64 parity), but int64 scalars in
    Mosaic kernels hit an infinite convert_element_type recursion in the
    TPU lowering. Kernel math is int32/fp32/bf16 regardless.

    Toolchains without the scoped ``jax.enable_x64`` override (it
    landed in newer jax) run WITHOUT the toggle: the old
    ``jax.experimental`` context manager only scopes trace-time dtype
    decisions while interpret-mode lowering happens later outside it
    (mixed i64/i32 loop carries -> verifier errors), and the kernels
    pin every dtype explicitly anyway, so x64 mode changes nothing
    they compute. This is also what lets ``flash_attention`` RECORD
    into the fusion window on such toolchains — the old AttributeError
    at record-time aval inference was the eager-GPT 4-breaks/step
    ``record_fallback`` class the perf lint attributed here."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(False)
    import contextlib
    return contextlib.nullcontext()


def _block_sizes(sq: int, sk: int, d: int):
    from ..._core.flags import flag_value
    cap_q = int(flag_value("FLAGS_flash_block_q"))
    cap_k = int(flag_value("FLAGS_flash_block_k"))
    bq = min(cap_q, sq) if sq % cap_q == 0 else min(128, sq)
    bk = min(cap_k, sk) if sk % cap_k == 0 else min(128, sk)
    if sq % bq:
        bq = sq  # small/ragged: single block (wrapper pads first)
    if sk % bk:
        bk = sk
    return bq, bk


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale,
                block_k, kv_len, q_offset):
    qi = pl.program_id(1)
    q = q_ref[0]                                    # [bq, d]
    bq, d = q.shape
    sk_pad = k_ref.shape[1]
    nkb = sk_pad // block_k

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]          # [bk, d]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        mask = k_pos < kv_len
        if causal:
            mask &= k_pos <= q_pos + q_offset
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        # keys beyond the last valid diagonal block never contribute
        last = (qi * bq + bq - 1) + q_offset
        nkb_eff = jnp.minimum((last // block_k) + 1, nkb)
    else:
        nkb_eff = nkb
    m, l, acc = jax.lax.fori_loop(0, nkb_eff, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # [bq, 1]: the trailing singleton keeps the block's last dim equal to
    # the array's (TPU tiling rule) and broadcasts cleanly in the bwd
    lse_ref[0] = (m + jnp.log(l_safe))[:, None]


def _fwd(q, k, v, causal, scale, block_q, block_k, kv_len, q_offset):
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q)
    with _no_x64():
        out, lse = _fwd_call(q, k, v, causal, scale, block_k, kv_len,
                             q_offset, block_q, grid, bh, sq, sk, d)
    return out, lse


def _fwd_call(q, k, v, causal, scale, block_k, kv_len, q_offset, block_q,
              grid, bh, sq, sk, d):
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, scale=scale,
                          block_k=block_k, kv_len=kv_len, q_offset=q_offset),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse





# ---------------------------------------------------------------- backward

def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, causal, scale, block_q, kv_len, q_offset):
    kj = pl.program_id(1)
    k = k_ref[0]                                    # [bk, d]
    v = v_ref[0]
    bk, d = k.shape
    sq = q_ref.shape[1]
    nqb = sq // block_q
    k_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]   # [bq, 1]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0)
        mask = k_pos < kv_len
        if causal:
            mask &= k_pos <= q_pos + q_offset
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)            # [bq, bk]
        dv_new = dv + jax.lax.dot_general(
            p, do.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, bk]
        ds = p * (dp - delta) * scale
        dk_new = dk + jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, d]
        return dk_new, dv_new

    if causal:
        # query rows before this key block's first diagonal see none of it
        first = jnp.maximum((kj * bk - q_offset) // block_q, 0)
    else:
        first = 0
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first, nqb, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               causal, scale, block_k, kv_len, q_offset):
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]       # [bq, 1]
    delta = delta_ref[0]   # [bq, 1]
    bq, d = q.shape
    sk = k_ref.shape[1]
    nkb = sk // block_k
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        mask = k_pos < kv_len
        if causal:
            mask &= k_pos <= q_pos + q_offset
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        last = (qi * bq + bq - 1) + q_offset
        nkb_eff = jnp.minimum((last // block_k) + 1, nkb)
    else:
        nkb_eff = nkb
    dq = jax.lax.fori_loop(0, nkb_eff, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd(q, k, v, out, lse, do, causal, scale, block_q, block_k, kv_len,
         q_offset):
    bh, sq, d = q.shape
    sk = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                    # [bh, sq, 1]
    qspec = pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0))
    full_q = pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0))
    full_row = pl.BlockSpec((1, sq, 1), lambda b, j: (b, 0, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0))
    full_k = pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0))

    with _no_x64():
        dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, kv_len=kv_len, q_offset=q_offset),
        grid=(bh, sk // block_k),
        in_specs=[full_q, kspec, kspec, full_q, full_row, full_row],
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, sk, d), v.dtype)],
            interpret=_interpret(),
        )(q, k, v, do, lse, delta)

    rowspec = pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0))
    with _no_x64():
        dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale,
                          block_k=block_k, kv_len=kv_len, q_offset=q_offset),
        grid=(bh, sq // block_q),
        in_specs=[qspec, full_k, full_k, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            interpret=_interpret(),
        )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------- public

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _mha(q, k, v, causal, scale):
    out, _ = _mha_fwd(q, k, v, causal, scale)[0], None
    return out


def _mha_fwd(q, k, v, causal, scale):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _block_sizes(sq, sk, d)
    out, lse = _fwd(q, k, v, causal, scale, bq, bk, kv_len=sk,
                    q_offset=sk - sq)
    return out, (q, k, v, out, lse)


def _mha_bwd(causal, scale, res, do):
    q, k, v, out, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _block_sizes(sq, sk, d)
    dq, dk, dv = _bwd(q, k, v, out, lse, do, causal, scale, bq, bk,
                      kv_len=sk, q_offset=sk - sq)
    return dq, dk, dv


_mha.defvjp(_mha_fwd, _mha_bwd)


def mha_forward(q, k, v, causal=False, scale=None):
    """Differentiable blocked attention on [BH or B,H fused, S, D] arrays.

    Accepts [B, H, S, D] or [BH, S, D]; returns the same rank it was given.
    """
    squeeze = q.ndim == 4
    if squeeze:
        b, h, sq, d = q.shape
        q = q.reshape(b * h, sq, d)
        k = k.reshape(b * h, k.shape[2], d)
        v = v.reshape(b * h, v.shape[2], d)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    out = _mha(q, k, v, bool(causal), float(scale))
    if squeeze:
        out = out.reshape(b, h, sq, d)
    return out


def _fa_kernel_body(q, k, v, causal, scale):
    # paddle layout [B, S, H, D] -> [BH, S, D]
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qt = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * h, sk, d)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * h, sk, d)
    out = _mha(qt, kt, vt, causal, scale)
    return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)


def flash_attention(query, key, value, causal=False, scale=None):
    """Public entry on framework Tensors (or raw arrays), paddle layout
    [batch, seq, heads, head_dim]. Seq lens must tile by 128 (the nn
    wrapper falls back to fused-XLA SDPA otherwise)."""
    from ..._core.executor import apply
    from ..._core.op_registry import all_ops, register_op
    if "flash_attention" not in all_ops():
        register_op("flash_attention", _fa_kernel_body)
    d = (query.shape[-1])
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    sq, sk = query.shape[1], key.shape[1]
    if sq % 128 or sk % 128:
        raise ValueError(f"flash_attention pallas kernel needs seq % 128 == 0"
                         f" (got q={sq}, k={sk})")
    return apply("flash_attention", query, key, value, causal=bool(causal),
                 scale=float(scale))


# ------------------------------------------------ SPMD (GSPMD-composable)
# custom_partitioning teaches the partitioner that the kernel shards
# freely over batch/head and needs seq/head_dim replicated — the TPU
# analog of the reference wiring flash-attn into its SPMD rules
# (phi/infermeta/spmd_rules). Composes with the compiled pp shard_map
# (partial-manual: dp/mp stay GSPMD-managed inside the pp body).
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as _P


_WARNED_REPLICATED = False


def _bh_spec(arg_shapes, mesh):
    sh = arg_shapes[0].sharding
    spec = getattr(sh, "spec", None)
    if spec is None:
        # GSPMDSharding (e.g. inside the compiled-pp partial-manual
        # shard_map): recover a PartitionSpec over the mesh, else
        # replicate (correct, just less parallel)
        try:
            from jax._src.sharding_impls import parse_flatten_op_sharding
            parsed = parse_flatten_op_sharding(
                sh._to_xla_hlo_sharding(len(arg_shapes[0].shape)), mesh)[0]
            spec = parsed.get_partition_spec()
        except Exception:
            global _WARNED_REPLICATED
            if not _WARNED_REPLICATED:
                _WARNED_REPLICATED = True
                import warnings
                warnings.warn(
                    "mha_spmd: could not recover a PartitionSpec from "
                    f"{type(sh).__name__}; flash attention will run "
                    "fully replicated over batch/head on this call site")
            spec = _P()
    b = spec[0] if len(spec) > 0 else None
    h = spec[1] if len(spec) > 1 else None
    return b, h


def _fwd4(q, k, v, causal, scale):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = _block_sizes(sq, sk, d)
    out, lse = _fwd(q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
                    v.reshape(b * h, sk, d), causal, scale, bq, bk,
                    kv_len=sk, q_offset=sk - sq)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq, 1)


def _bwd4(q, k, v, out, lse, do, causal, scale):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = _block_sizes(sq, sk, d)
    dq, dk, dv = _bwd(q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
                      v.reshape(b * h, sk, d), out.reshape(b * h, sq, d),
                      lse.reshape(b * h, sq, 1), do.reshape(b * h, sq, d),
                      causal, scale, bq, bk, kv_len=sk, q_offset=sk - sq)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


def _make_partitioned(fn, n_arrays, n_outs, rule):
    p = custom_partitioning(fn, static_argnums=(n_arrays, n_arrays + 1))

    def infer(causal, scale, mesh, arg_shapes, result_shape):
        b, h = _bh_spec(arg_shapes, mesh)
        sh4 = NamedSharding(mesh, _P(b, h, None, None))
        return (sh4,) * n_outs if n_outs > 1 else sh4

    def part(causal, scale, mesh, arg_shapes, result_shape):
        b, h = _bh_spec(arg_shapes, mesh)
        sh4 = NamedSharding(mesh, _P(b, h, None, None))
        args = (sh4,) * n_arrays
        outs = (sh4,) * n_outs if n_outs > 1 else sh4

        def lower(*arrays):
            return fn(*arrays, causal, scale)

        return mesh, lower, outs, args

    # Shardy propagation: b/h shard freely, seq/head_dim factors must be
    # replicated at the kernel boundary. The rule builder is private jax
    # API; guard it so a future rename only disables the Shardy path
    # instead of breaking `import paddle_tpu.ops.pallas` for everyone.
    try:
        from jax._src.custom_partitioning_sharding_rule import \
            str_to_sdy_sharding_rule
        sdy_rule = str_to_sdy_sharding_rule(
            rule, need_replication_factors=("i", "j", "k", "l"))
    except Exception:  # pragma: no cover - jax-version dependent
        sdy_rule = None
    try:
        p.def_partition(infer_sharding_from_operands=infer, partition=part,
                        sharding_rule=sdy_rule)
    except TypeError:  # pragma: no cover - jax-version dependent
        # older jax: def_partition has no sharding_rule kwarg (GSPMD-only
        # propagation); the Shardy rule is an optimization, not required
        p.def_partition(infer_sharding_from_operands=infer, partition=part)
    return p


_FWD_RULE = "b h i j, b h k j, b h k j -> b h i j, b h i l"
_BWD_RULE = ("b h i j, b h k j, b h k j, b h i j, b h i l, b h i j "
             "-> b h i j, b h k j, b h k j")


_fwd4_p = _make_partitioned(_fwd4, 3, 2, _FWD_RULE)
_bwd4_p = _make_partitioned(_bwd4, 6, 3, _BWD_RULE)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def mha_spmd(q, k, v, causal=False, scale=None):
    """Flash attention on sharded [B, H, S, D] arrays under jit/GSPMD:
    b/h partitioning preserved, s/d gathered. Use on the multi-chip
    model path (models/gpt.py); single-chip callers use mha_forward."""
    out, _ = _mha_spmd_fwd(q, k, v, causal, scale)
    return out


def _mha_spmd_fwd(q, k, v, causal, scale):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    out, lse = _fwd4_p(q, k, v, bool(causal), float(scale))
    return out, (q, k, v, out, lse)


def _mha_spmd_bwd(causal, scale, res, do):
    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    dq, dk, dv = _bwd4_p(q, k, v, out, lse, do, bool(causal),
                         float(scale))
    return dq, dk, dv


mha_spmd.defvjp(_mha_spmd_fwd, _mha_spmd_bwd)


def mha_manual(q, k, v, mesh, causal=False, scale=None):
    """Flash dispatch for partial-manual regions (compiled-pp bodies),
    where custom_partitioning sees an empty mesh: shard batch over 'dp'
    and heads over 'mp' with a nested shard_map on the CONTEXT abstract
    mesh. Returns None when no axis is shardable (indivisible batch or
    heads) — the caller must fall back to a GSPMD-friendly path."""
    axes = tuple(
        a for a, dim in (("dp", q.shape[0]), ("mp", q.shape[1]))
        if a in mesh.axis_names and mesh.shape[a] > 1
        and dim % mesh.shape[a] == 0)
    if not axes:
        return None
    spec = _P("dp" if "dp" in axes else None,
              "mp" if "mp" in axes else None, None, None)
    ctx_mesh = jax.sharding.get_abstract_mesh()
    return jax.shard_map(
        functools.partial(mha_forward, causal=causal, scale=scale),
        mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=set(axes), check_vma=False)(q, k, v)
