"""Helpers to define ops tersely.

The mechanical analog of the reference's YAML->codegen pipeline
(paddle/phi/ops/yaml/ops.yaml + api_gen.py): each def_* call registers the
kernel body (pure JAX fn) and returns the user-facing wrapper that routes
through the eager executor (autograd recording + compile cache).
"""
from __future__ import annotations

from .._core.executor import apply
from .._core.op_registry import register_op
from .._core.tensor import Tensor

_TENSOR_METHODS = {}


def tensor_method(name):
    """Mark a function to also become a Tensor method."""
    def deco(fn):
        _TENSOR_METHODS[name] = fn
        return fn
    return deco


def attach_tensor_methods():
    for name, fn in _TENSOR_METHODS.items():
        setattr(Tensor, name, fn)


def def_unary(name, jfn):
    register_op(name, lambda x, _f=jfn: _f(x))

    def wrapper(x, name=None, _op=name):
        return apply(_op, x)
    wrapper.__name__ = name
    _TENSOR_METHODS[name] = wrapper
    return wrapper


def def_binary(name, jfn):
    register_op(name, lambda x, y, _f=jfn: _f(x, y))

    def wrapper(x, y, name=None, _op=name):
        return apply(_op, x, y)
    wrapper.__name__ = name
    _TENSOR_METHODS[name] = wrapper
    return wrapper


def make_inplace(fn, name):
    """Build the `op_` in-place variant: functional result adopted into self
    (inplace-version bump preserves TensorWrapper safety semantics)."""
    def inplace(self, *args, **kwargs):
        out = fn(self, *args, **kwargs)
        return self._adopt(out)
    inplace.__name__ = name
    _TENSOR_METHODS[name] = inplace
    return inplace
