"""Long-tail math/tensor ops (reference paddle/phi/ops/yaml/ops.yaml:
addmm, baddbmm, cummax/cummin, Bessel i0/i0e/i1/i1e, polygamma,
gammaln/gammainc/gammaincc, dist, cholesky_solve, svdvals, diag_embed,
fill_diagonal, multiplex, slice/strided_slice, crop, bit shifts,
reduce_as, clip_by_norm, l1/squared_l2 norms, random distributions)."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import jax.scipy.special as jss
from jax import lax

from .._core import random as rnd
from .._core.executor import apply
from .._core.op_registry import register_op
from .._core.tensor import Tensor
from ._helper import def_binary, def_unary, tensor_method

# --------------------------------------------------- blas-style composites
register_op("addmm_", lambda inp, x, y, beta, alpha:
            beta * inp + alpha * (x @ y))
register_op("baddbmm_", lambda inp, x, y, beta, alpha:
            beta * inp + alpha * jnp.matmul(x, y))


@tensor_method("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply("addmm_", input, x, y, beta=float(beta),
                 alpha=float(alpha))


def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply("baddbmm_", input, x, y, beta=float(beta),
                 alpha=float(alpha))


# ----------------------------------------------------- cumulative min/max
def _cummaxmin(x, axis, op):
    axis = axis % x.ndim
    val = op(x, axis=axis)
    # indices: position of the running extremum along axis
    eq = x == val
    ar = jnp.arange(x.shape[axis]).reshape(
        [-1 if i == (axis % x.ndim) else 1 for i in range(x.ndim)])
    idx = jax.lax.cummax(jnp.where(eq, ar, -1), axis=axis)
    return val, idx.astype(jnp.int64)


register_op("cummax_", lambda x, axis: _cummaxmin(x, axis, lax.cummax),
            multi_output=True)
register_op("cummin_", lambda x, axis: _cummaxmin(x, axis, lax.cummin),
            multi_output=True)


@tensor_method("cummax")
def cummax(x, axis=-1, dtype="int64", name=None):
    return apply("cummax_", x, axis=int(axis))


@tensor_method("cummin")
def cummin(x, axis=-1, dtype="int64", name=None):
    return apply("cummin_", x, axis=int(axis))


# ------------------------------------------------------ special functions
i0 = def_unary("i0", jss.i0)
i0e = def_unary("i0e", jss.i0e)
i1 = def_unary("i1", jss.i1)
i1e = def_unary("i1e", jss.i1e)
gammaln = def_unary("gammaln", jss.gammaln)

register_op("polygamma_", lambda x, n: jss.polygamma(n, x))
register_op("gammainc_", jss.gammainc)
register_op("gammaincc_", jss.gammaincc)


@tensor_method("polygamma")
def polygamma(x, n, name=None):
    return apply("polygamma_", x, n=int(n))


def gammainc(x, y, name=None):
    return apply("gammainc_", x, y)


def gammaincc(x, y, name=None):
    return apply("gammaincc_", x, y)


# ------------------------------------------------------------- distances
register_op("dist_", lambda x, y, p: jnp.linalg.norm(
    (x - y).reshape(-1), ord=p))


def dist(x, y, p=2.0, name=None):
    return apply("dist_", x, y, p=float(p))


# ---------------------------------------------------------------- linalg
register_op("cholesky_solve_", lambda x, y, upper:
            jax.scipy.linalg.cho_solve((y, not upper), x))
register_op("svdvals_", lambda x: jnp.linalg.svd(x, compute_uv=False))


def cholesky_solve(x, y, upper=False, name=None):
    """Solve A X = B given the Cholesky factor ``y`` of A (B is ``x``)."""
    return apply("cholesky_solve_", x, y, upper=bool(upper))


def svdvals(x, name=None):
    return apply("svdvals_", x)


def _householder_product_2d(x, tau):
    m, n = x.shape[-2], x.shape[-1]
    k = tau.shape[-1]  # may be < n: only k reflectors exist
    eye = jnp.eye(m, dtype=x.dtype)

    def body(q, i):
        v = jnp.where(jnp.arange(m) < i, 0.0,
                      jnp.where(jnp.arange(m) == i, 1.0, x[:, i]))
        h = eye - tau[i] * jnp.outer(v, v)
        return q @ h, None

    q, _ = lax.scan(body, eye, jnp.arange(k))
    return q[:, :n]


def _householder_product_kernel(x, tau):
    if x.ndim == 2:
        return _householder_product_2d(x, tau)
    batch = x.shape[:-2]
    xf = x.reshape((-1,) + x.shape[-2:])
    tf = tau.reshape((-1, tau.shape[-1]))
    qf = jax.vmap(_householder_product_2d)(xf, tf)
    return qf.reshape(batch + qf.shape[-2:])


register_op("householder_product_", _householder_product_kernel)

# -------------------------------------------------------- diagonal tools
register_op("diag_embed_", lambda x, offset, dim1, dim2: _diag_embed(
    x, offset, dim1, dim2))


def _diag_embed(x, offset, dim1, dim2):
    n = x.shape[-1] + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    rows = jnp.arange(x.shape[-1]) + max(-offset, 0)
    cols = jnp.arange(x.shape[-1]) + max(offset, 0)
    out = out.at[..., rows, cols].set(x)
    # move the two new axes to dim1/dim2
    nd = out.ndim
    perm = [i for i in range(nd) if i < nd - 2]
    d1, d2 = dim1 % nd, dim2 % nd
    order = []
    k = 0
    for i in range(nd):
        if i == d1:
            order.append(nd - 2)
        elif i == d2:
            order.append(nd - 1)
        else:
            order.append(perm[k])
            k += 1
    return jnp.transpose(out, order)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    return apply("diag_embed_", x, offset=int(offset), dim1=int(dim1),
                 dim2=int(dim2))


def _fill_diagonal_kernel(x, value, offset, wrap):
    if x.ndim > 2:
        # space diagonal x[i,i,...,i] (torch/numpy fill_diagonal ndim>2)
        n = min(x.shape)
        idx = jnp.arange(n)
        return x.at[tuple(idx for _ in range(x.ndim))].set(
            jnp.asarray(value, x.dtype))
    h, w = x.shape[-2], x.shape[-1]
    rows = jnp.arange(h)[:, None]
    cols = jnp.arange(w)[None, :]
    if wrap and h > w:
        # numpy wrap semantics: the diagonal restarts every w+1 rows
        mask = (rows % (w + 1)) == cols
    else:
        mask = (cols - rows) == offset
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


register_op("fill_diagonal_", _fill_diagonal_kernel)


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    return apply("fill_diagonal_", x, value=float(value),
                 offset=int(offset), wrap=bool(wrap))


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    return x._adopt(fill_diagonal(x, value, offset, wrap))


# ------------------------------------------------------- select / slicing
def _multiplex_kernel(index, *ins):
    stacked = jnp.stack(ins, axis=0)  # [k, N, ...]
    idx = index.reshape(-1).astype(jnp.int32)
    return stacked[idx, jnp.arange(stacked.shape[1])]


register_op("multiplex_", _multiplex_kernel)


def multiplex(inputs, index, name=None):
    """Row-wise select: out[i] = inputs[index[i]][i] (ops.yaml multiplex)."""
    return apply("multiplex_", index, *inputs)


register_op("strided_slice_", lambda x, spec: x[
    tuple(builtins.slice(*s) for s in spec)])


def slice(input, axes, starts, ends, name=None):
    return strided_slice(input, axes, starts, ends,
                         [1] * len(list(axes)))


def strided_slice(x, axes, starts, ends, strides, name=None):
    spec = [(None, None, None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        spec[ax] = (int(st), int(en), int(sd))
    return apply("strided_slice_", x, spec=tuple(spec))


register_op("crop_", lambda x, offsets, shape: lax.dynamic_slice(
    x, offsets, shape))


def crop(x, shape=None, offsets=None, name=None):
    offsets = list(offsets) if offsets is not None else [0] * x.ndim
    shape = list(shape) if shape is not None else [-1] * x.ndim
    # -1/None means "to the end" from the offset (reference crop)
    shape = [x.shape[i] - offsets[i] if s in (-1, None) else int(s)
             for i, s in enumerate(shape)]
    return apply("crop_", x, offsets=tuple(int(o) for o in offsets),
                 shape=tuple(shape))


def unstack(x, axis=0, num=None, name=None):
    from .manipulation import unbind
    return unbind(x, axis=axis)


def reverse(x, axis, name=None):
    from .manipulation import flip
    return flip(x, axis)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


# ------------------------------------------------------------ bit shifts
bitwise_left_shift = def_binary("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = def_binary("bitwise_right_shift", jnp.right_shift)


# ----------------------------------------------------------- norm family
def _reduce_as_kernel(x, tshape):
    axes = []
    off = x.ndim - len(tshape)
    for i in range(x.ndim):
        if i < off:
            axes.append(i)
        elif tshape[i - off] == 1 and x.shape[i] != 1:
            axes.append(i)
    out = jnp.sum(x, axis=tuple(axes), keepdims=True) if axes else x
    return out.reshape(tshape)


register_op("reduce_as_", _reduce_as_kernel)


def reduce_as(x, target, name=None):
    """Sum x down to target's shape (ops.yaml reduce_as)."""
    return apply("reduce_as_", x, tshape=tuple(target.shape))


register_op("clip_by_norm_", lambda x, max_norm: x * jnp.minimum(
    1.0, max_norm / jnp.maximum(jnp.linalg.norm(x.reshape(-1)), 1e-12)))
register_op("squared_l2_norm_", lambda x: jnp.sum(x * x).reshape(1))
register_op("l1_norm_", lambda x: jnp.sum(jnp.abs(x)))


def clip_by_norm(x, max_norm, name=None):
    return apply("clip_by_norm_", x, max_norm=float(max_norm))


def squared_l2_norm(x, name=None):
    return apply("squared_l2_norm_", x)


def l1_norm(x, name=None):
    return apply("l1_norm_", x)


# ---------------------------------------------------- random distributions
def poisson(x, name=None):
    return Tensor(jax.random.poisson(rnd.next_key(), x._value).astype(
        x._value.dtype))


def binomial(count, prob, name=None):
    c = count._value if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._value if isinstance(prob, Tensor) else jnp.asarray(prob)
    return Tensor(jax.random.binomial(rnd.next_key(), c.astype("float32"),
                                      p).astype("int64"))


def standard_gamma(x, name=None):
    return Tensor(jax.random.gamma(rnd.next_key(), x._value))


def dirichlet(concentration, name=None):
    return Tensor(jax.random.dirichlet(rnd.next_key(),
                                       concentration._value))


def exponential_(x, lam=1.0, name=None):
    sample = jax.random.exponential(
        rnd.next_key(), x.shape, x._value.dtype) / lam
    return x._adopt(Tensor(sample))
