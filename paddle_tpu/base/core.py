"""Typed framework errors + enforce helpers (enforce.h / errors.h
analog). The class names and hierarchy mirror common::errors so user
code catching paddle.base.core.<Error> ports directly."""
from __future__ import annotations

import traceback
from typing import Any, Sequence

__all__ = ["EnforceNotMet", "InvalidArgumentError", "NotFoundError",
           "OutOfRangeError", "PreconditionNotMetError",
           "ResourceExhaustedError", "UnavailableError",
           "UnimplementedError", "enforce", "enforce_eq", "enforce_gt",
           "enforce_shape_match"]


class EnforceNotMet(RuntimeError):
    """Base framework error (enforce.h EnforceNotMet): message + the
    user-code frame that triggered it (the call_stack_level=1 summary)."""

    def __init__(self, message: str, context: str = ""):
        frame = _user_frame()
        parts = [message]
        if context:
            parts.append(f"  [Hint: {context}]")
        if frame:
            parts.append(f"  [operator < {frame} > error]")
        super().__init__("\n".join(parts))
        self.message = message
        self.context = context
        from ..observability import _state as _obs
        if _obs.FLIGHT:
            # framework error with the flight recorder armed: dump the
            # recent runtime events alongside the enforce message
            from ..observability import flight
            flight.on_error("enforce", message)


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


def _user_frame() -> str:
    """Innermost stack frame outside paddle_tpu — what the user called.
    Only the module filter skips frames (no fixed-depth slicing: direct
    raises and enforce() have different intermediate depths)."""
    for f in reversed(traceback.extract_stack()[:-1]):
        if "paddle_tpu" not in (f.filename or ""):
            return f"{f.filename}:{f.lineno} {f.name}"
    return ""


def enforce(cond: Any, message: str, context: str = "",
            error_cls=None):
    """PADDLE_ENFORCE analog: raise a typed framework error when the
    condition is false."""
    if not cond:
        raise (error_cls or PreconditionNotMetError)(message, context)


def enforce_eq(a, b, message: str = "", context: str = ""):
    if a != b:
        raise InvalidArgumentError(
            message or f"expected equality, got {a!r} != {b!r}", context)


def enforce_gt(a, b, message: str = "", context: str = ""):
    if not a > b:
        raise InvalidArgumentError(
            message or f"expected {a!r} > {b!r}", context)


def enforce_shape_match(shape_a: Sequence, shape_b: Sequence,
                        message: str = "", context: str = ""):
    """Broadcast-unaware exact shape check with a detailed message
    (the common InferMeta error shape)."""
    if list(shape_a) != list(shape_b):
        raise InvalidArgumentError(
            message or (f"shape mismatch: {list(shape_a)} vs "
                        f"{list(shape_b)}"), context)
