"""paddle.base — error types + enforce helpers.

Analog of the reference's error system (paddle/common/enforce.h
PADDLE_ENFORCE* macros + common::errors error builders, surfaced to
Python as paddle.base.core.EnforceNotMet and typed subclasses). Errors
carry the op/API context frame the way the reference's
FLAGS_call_stack_level error summaries do.
"""
from . import core  # noqa: F401
from .core import (  # noqa: F401
    EnforceNotMet,
    InvalidArgumentError,
    NotFoundError,
    OutOfRangeError,
    PreconditionNotMetError,
    ResourceExhaustedError,
    UnavailableError,
    UnimplementedError,
    enforce,
    enforce_eq,
    enforce_gt,
    enforce_shape_match,
)

__all__ = ["core", "EnforceNotMet", "InvalidArgumentError",
           "NotFoundError", "OutOfRangeError", "PreconditionNotMetError",
           "ResourceExhaustedError", "UnavailableError",
           "UnimplementedError", "enforce", "enforce_eq", "enforce_gt",
           "enforce_shape_match"]
