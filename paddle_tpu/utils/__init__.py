"""paddle.utils (python/paddle/utils analog): cpp extension loading,
custom-device plugins, environment self-check."""
from . import cpp_extension  # noqa: F401
from .cpp_extension import (  # noqa: F401
    CustomDevice,
    get_all_custom_device_type,
    load_custom_device_lib,
    load_op_library,
)
from .install_check import run_check  # noqa: F401

__all__ = ["run_check", "cpp_extension", "load_custom_device_lib",
           "get_all_custom_device_type", "load_op_library", "CustomDevice"]


def accuracy_check(x, y, fn_name="", rtol=1e-5, atol=1e-8,
                   equal_nan=False, raise_on_fail=True):
    """Per-tensor numeric parity check (reference accuracy_check op,
    ops.yaml:31 — the primitive of the acc-align harnesses in
    test/auto_parallel/hybrid_strategy/semi_auto_llama_acc_align.py).

    Runs the registered `accuracy_check` op; on mismatch raises (or
    returns False) with max-abs/rel-diff detail.
    """
    import numpy as np

    from .._core.executor import apply
    from .._core.tensor import Tensor

    if not isinstance(x, Tensor):
        x = Tensor(x)
    if not isinstance(y, Tensor):
        y = Tensor(y)
    # fn_name stays OUT of the op attrs: it would join the jit
    # compile-cache key and force one compilation per checked tensor
    ok = bool(apply("accuracy_check", x, y, fn_name="",
                    rtol=float(rtol), atol=float(atol),
                    equal_nan=bool(equal_nan)).numpy())
    if ok:
        return True
    xv = np.asarray(x.numpy(), np.float64)
    yv = np.asarray(y.numpy(), np.float64)
    ad = np.abs(xv - yv)
    with np.errstate(divide="ignore", invalid="ignore"):
        rd = np.where(yv != 0, ad / np.abs(yv), np.inf)
    msg = (f"accuracy_check failed for '{fn_name or 'tensor'}': "
           f"max_abs_diff={ad.max():.3e} max_rel_diff={rd.max():.3e} "
           f"(rtol={rtol}, atol={atol}, {int((ad > atol).sum())}/"
           f"{ad.size} elements over atol)")
    if raise_on_fail:
        raise AssertionError(msg)
    return False
