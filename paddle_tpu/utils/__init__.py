"""paddle.utils (python/paddle/utils analog): cpp extension loading,
custom-device plugins, environment self-check."""
from . import cpp_extension  # noqa: F401
from .cpp_extension import (  # noqa: F401
    CustomDevice,
    get_all_custom_device_type,
    load_custom_device_lib,
    load_op_library,
)
from .install_check import run_check  # noqa: F401

__all__ = ["run_check", "cpp_extension", "load_custom_device_lib",
           "get_all_custom_device_type", "load_op_library", "CustomDevice"]
