"""paddle.utils.run_check (python/paddle/utils/install_check.py:215
analog): a self-test a user runs after install — single-device fwd/bwd
numerics, then a sharded matmul across every visible device."""
from __future__ import annotations

import numpy as np


def _check_single():
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype(np.float32),
        stop_gradient=False)
    w = paddle.to_tensor(
        np.random.RandomState(1).randn(8, 4).astype(np.float32),
        stop_gradient=False)
    y = F.relu(paddle.matmul(x, w))
    loss = y.sum()
    loss.backward()
    ref = np.maximum(x.numpy() @ w.numpy(), 0).sum()
    np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-4)
    assert x.grad is not None and w.grad is not None
    return True


def _check_all_devices(n: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = np.asarray(jax.devices()[:n])
    mesh = Mesh(devs, ("dp",))
    x = jnp.ones((n * 2, 8), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, PartitionSpec("dp")))
    w = jnp.ones((8, 4), jnp.float32)
    out = jax.jit(lambda a, b: a @ b)(xs, w)
    np.testing.assert_allclose(np.asarray(out),
                               np.full((n * 2, 4), 8.0, np.float32))
    return True


def run_check():
    """Prints the same kind of report the reference does
    (install_check.py: 'PaddlePaddle is installed successfully!...')."""
    import jax
    import paddle_tpu

    n = len(jax.devices())
    plat = jax.devices()[0].platform
    _check_single()
    print(f"PaddleTPU works on 1 {plat} device.")
    if n > 1:
        _check_all_devices(n)
        print(f"PaddleTPU works on {n} {plat} devices "
              f"(sharded matmul verified).")
    print("PaddleTPU is installed successfully! Let's start deep "
          "learning with PaddleTPU now.")
    return True
