"""Out-of-tree C++ extension points.

Two seams, mirroring the reference:

- Custom DEVICE plugins (paddle/phi/backends/device_ext.h:96 +
  DeviceManager::LoadCustomRuntimeLib, device_manager.h:298): a vendor
  .so exporting PT_InitDevicePlugin is dlopened and driven through the
  C fn-pointer table in csrc/device_ext.h. `CustomDevice` exposes the
  memory/stream/collective contract to Python.
- Custom OPS (paddle/extension.h + fluid/framework/custom_operator.cc +
  paddle.utils.cpp_extension JIT build): a .so exporting pt_op_<name>
  host-buffer kernels is registered into the op registry; under jit the
  op runs through jax.pure_callback, eagerly it is the same path — the
  TPU-native equivalent of a CPU custom kernel (device custom kernels
  are Pallas functions registered directly, no C ABI needed).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Callable, List, Optional, Sequence

import numpy as np

from .._core import native

_loaded_device_types: List[str] = []


class CustomDevice:
    """Handle to one loaded plugin device type (CustomDevice adapter,
    custom_device.cc:42 analog)."""

    def __init__(self, dev_type: str):
        self.device_type = dev_type
        self._lib = native.get_lib(required=True)

    def device_count(self) -> int:
        return self._lib.pt_plugin_device_count(self.device_type.encode())

    def memory_stats(self, device: int = 0):
        total = ctypes.c_uint64()
        free = ctypes.c_uint64()
        rc = self._lib.pt_plugin_mem_stats(
            self.device_type.encode(), device,
            ctypes.byref(total), ctypes.byref(free))
        if rc != 0:
            raise RuntimeError(native.last_error() or "mem_stats failed")
        return {"total": total.value, "free": free.value}

    def stream_check(self, device: int = 0) -> bool:
        """Create stream -> record+sync event -> destroy (the contract
        smoke the reference's fake-device tests drive)."""
        return self._lib.pt_plugin_stream_check(
            self.device_type.encode(), device) == 0

    def round_trip(self, arr: np.ndarray, device: int = 0) -> np.ndarray:
        """h2d then d2h through plugin memory: the memcpy contract."""
        arr = np.ascontiguousarray(arr)
        dev = self.device_type.encode()
        ptr = self._lib.pt_plugin_malloc(dev, device, arr.nbytes)
        if not ptr:
            raise RuntimeError("plugin malloc failed")
        try:
            src = arr.ctypes.data_as(ctypes.c_void_p)
            rc = self._lib.pt_plugin_memcpy(dev, device, ptr, src,
                                            arr.nbytes, 0)  # h2d
            out = np.empty_like(arr)
            rc |= self._lib.pt_plugin_memcpy(
                dev, device, out.ctypes.data_as(ctypes.c_void_p),
                ptr, arr.nbytes, 1)  # d2h
            if rc != 0:
                raise RuntimeError("plugin memcpy failed")
            return out
        finally:
            self._lib.pt_plugin_free(dev, device, ptr)

    def ccl_all_reduce(self, arr: np.ndarray, device: int = 0,
                       op: str = "sum") -> np.ndarray:
        """Route through the plugin's xccl hook (device_ext.h:557
        analog); identity for single-member fabrics."""
        arr = np.ascontiguousarray(arr).copy()
        codes = {"float32": 0, "float64": 1, "int32": 2, "int64": 3}
        ops = {"sum": 0, "max": 1, "min": 2, "prod": 3}
        rc = self._lib.pt_plugin_ccl_all_reduce(
            self.device_type.encode(), device,
            arr.ctypes.data_as(ctypes.c_void_p), arr.size,
            codes[arr.dtype.name], ops[op])
        if rc != 0:
            raise RuntimeError("plugin ccl_all_reduce failed")
        return arr


def load_custom_device_lib(path: str) -> CustomDevice:
    """dlopen a device plugin .so (LoadCustomRuntimeLib analog)."""
    lib = native.get_lib(required=True)
    name = lib.pt_plugin_load(os.fspath(path).encode())
    if not name:
        raise RuntimeError(
            f"failed to load device plugin {path}: {native.last_error()}")
    dev_type = name.decode()
    if dev_type not in _loaded_device_types:
        _loaded_device_types.append(dev_type)
    return CustomDevice(dev_type)


def get_all_custom_device_type() -> List[str]:
    return list(_loaded_device_types)


# ------------------------------------------------------------ custom ops

def load_op_library(path: str, op_name: str,
                    out_shape_fn: Optional[Callable] = None):
    """Load pt_op_<op_name> from a .so and register it as a framework op.

    The C kernel computes on float32 host buffers; output shape defaults
    to the first input's (elementwise contract) unless out_shape_fn is
    given. Works eagerly and under jit via jax.pure_callback — the role
    of the reference's custom-op registration (custom_operator.cc) with
    the CPU kernel path; TPU-resident custom kernels are Pallas functions
    registered with register_op directly.
    """
    import jax
    import jax.numpy as jnp

    lib = native.get_lib(required=True)
    rc = lib.pt_custom_op_load(os.fspath(path).encode(), op_name.encode())
    if rc != 0:
        raise RuntimeError(
            f"failed to load op {op_name}: {native.last_error()}")

    def host_call(*arrays):
        arrays = [np.ascontiguousarray(np.asarray(a, np.float32))
                  for a in arrays]
        n = len(arrays)
        ins = (ctypes.c_void_p * n)(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
        sizes = (ctypes.c_int64 * n)(*[a.size for a in arrays])
        out_shape = (out_shape_fn(*[a.shape for a in arrays])
                     if out_shape_fn else arrays[0].shape)
        out = np.empty(out_shape, np.float32)
        if lib.pt_custom_op_call(op_name.encode(), ins, sizes, n,
                                 out.ctypes.data_as(ctypes.c_void_p),
                                 out.size) != 0:
            raise RuntimeError(f"custom op {op_name} failed: "
                               f"{native.last_error()}")
        return out

    def op_fn(*xs):
        shape = (out_shape_fn(*[x.shape for x in xs]) if out_shape_fn
                 else xs[0].shape)
        return jax.pure_callback(
            host_call, jax.ShapeDtypeStruct(tuple(shape), jnp.float32),
            *xs)

    from .._core.op_registry import register_op
    register_op(op_name, op_fn, custom=True)

    from .._core.executor import apply

    def user_fn(*tensors):
        return apply(op_name, *tensors)

    return user_fn


def compile_and_load_op(source: str, op_name: str,
                        out_shape_fn: Optional[Callable] = None,
                        extra_cflags: Sequence[str] = ()):
    """JIT-build a custom-op .so from C++ source text and register it
    (paddle.utils.cpp_extension.load analog, g++ instead of nvcc)."""
    workdir = tempfile.mkdtemp(prefix=f"pt_op_{op_name}_")
    src = os.path.join(workdir, f"{op_name}.cc")
    so = os.path.join(workdir, f"lib{op_name}.so")
    with open(src, "w") as f:
        f.write(source)
    cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared",
           *extra_cflags, src, "-o", so]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"custom op build failed:\n{proc.stderr}")
    return load_op_library(so, op_name, out_shape_fn=out_shape_fn)
