"""Native prefetching token loader for LLM pretraining.

Python surface over csrc/data_feed.cc (the reference's C++ DataFeed role,
paddle/fluid/framework/data_feed.h): a C++ worker thread mmap-reads a flat
int32 token file and keeps a prefetch ring of [batch, seq_len+1] windows;
next() returns (tokens [B,S], labels [B,S]) ready for the train step, so
input never blocks the TPU step loop."""
from __future__ import annotations

import ctypes

import numpy as np

from .._core import native


class NativeTokenLoader:
    def __init__(self, path: str, seq_len: int, batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 prefetch_depth: int = 4):
        self._lib = native.get_lib(required=True)
        self._h = self._lib.pt_feed_create(
            str(path).encode(), seq_len, batch_size, 1 if shuffle else 0,
            seed, prefetch_depth)
        if not self._h:
            raise RuntimeError(
                f"NativeTokenLoader failed: {native.last_error()}")
        self.seq_len = seq_len
        self.batch_size = batch_size
        self._buf = np.empty((batch_size, seq_len + 1), np.int32)

    @property
    def num_windows(self) -> int:
        return int(self._lib.pt_feed_num_windows(self._h))

    def next(self):
        """Blocking: returns (tokens [B, S], labels [B, S]) int32."""
        if self._lib.pt_feed_next(
                self._h, self._buf.ctypes.data_as(ctypes.c_void_p)) != 0:
            raise StopIteration
        window = self._buf
        return window[:, :-1].copy(), window[:, 1:].copy()

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def close(self):
        if getattr(self, "_h", None):
            self._lib.pt_feed_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
