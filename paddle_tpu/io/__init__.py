"""paddle_tpu.io — Dataset / DataLoader.

Analog of python/paddle/io (reader.py:262, dataloader_iter.py:368). The
loader composes batches with numpy workers (threads — host-side IO is
GIL-releasing) and hands device placement to JAX; a one-batch prefetch
pipeline overlaps host batch assembly with TPU compute.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Iterable, List, Optional

import numpy as np

from .._core import random as rnd
from .._core.tensor import Tensor

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "Subset",
           "ConcatDataset", "random_split", "DataLoader", "BatchSampler",
           "Sampler", "SequenceSampler", "RandomSampler",
           "DistributedBatchSampler", "default_collate_fn",
           "DevicePrefetcher"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        d = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if d == 0 else int(self.cum[d - 1])
        return self.datasets[d][idx - prev]


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(round(l * n)) for l in lengths]
        lengths[-1] = n - sum(lengths[:-1])
    idx = np.random.RandomState(rnd.get_seed() or 0).permutation(n)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, idx[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.default_rng()
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks
    (python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[:(self.total_size - n)]
        local = indices[self.local_rank::self.nranks]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        arrs = [s.numpy() for s in batch]
        return Tensor(np.stack(arrs))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    return batch


def _np_collate(batch):
    """Collate with numpy leaves — the worker-process form (workers
    should avoid jax: forked children inherit the XLA runtime; Tensor
    samples are read back via .numpy() as a best effort).
    default_collate_fn == _tree_to_tensor(_np_collate(batch))."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return [_np_collate(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    return batch


def _tree_to_np(obj):
    if isinstance(obj, Tensor):
        return obj.numpy()
    if isinstance(obj, (tuple, list)):
        return [_tree_to_np(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _tree_to_np(v) for k, v in obj.items()}
    return obj


def _tree_to_tensor(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (tuple, list)):
        return [_tree_to_tensor(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _tree_to_tensor(v) for k, v in obj.items()}
    return obj


def _materialize_tree(obj):
    """Land any lazy/pending payloads in a batch ON the thread that
    built it. The fusion window is per-thread: a Tensor whose value is
    still pending in the prefetch thread's window must not cross the
    queue, or the consumer would flush (and race) another thread's
    capture context mid-record."""
    if isinstance(obj, Tensor):
        obj._value       # property read = the window's sync point
        return obj
    if isinstance(obj, (tuple, list)):
        for o in obj:
            _materialize_tree(o)
        return obj
    if isinstance(obj, dict):
        for v in obj.values():
            _materialize_tree(v)
        return obj
    return obj


def _mp_worker_loop(dataset, collate, index_q, data_q):
    """Worker process body (dataloader_iter.py:368 analog): pull batch
    index lists, build + collate the batch host-side, push numpy."""
    while True:
        item = index_q.get()
        if item is None:
            break
        seq, idx = item
        try:
            out = collate([dataset[j] for j in idx])
            data_q.put((seq, _tree_to_np(out), None))
        except Exception as e:  # surfaced in the parent
            data_q.put((seq, None, f"{type(e).__name__}: {e}"))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=None, use_buffer_reader=True,
                 prefetch_factor=None,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        from .._core.flags import flag_value
        if num_workers is None:
            num_workers = flag_value("FLAGS_dataloader_num_workers")
        if prefetch_factor is None:
            prefetch_factor = flag_value(
                "FLAGS_dataloader_prefetch_factor")
        self.num_workers = num_workers
        self.timeout = timeout or 0
        self.prefetch = max(prefetch_factor, 1) if use_buffer_reader else 0
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _produce(self):
        if isinstance(self.dataset, IterableDataset):
            buf = []
            bs = self.batch_sampler.batch_size if self.batch_sampler else 1
            for item in self.dataset:
                buf.append(item)
                if len(buf) == bs:
                    yield self.collate_fn(buf)
                    buf = []
            if buf:
                yield self.collate_fn(buf)
            return
        for batch_idx in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in batch_idx])

    def __iter__(self):
        if self.num_workers and self.num_workers > 0:
            yield from self._mp_iter()
            return
        yield from self._thread_iter()

    def _thread_iter(self):
        if self.prefetch == 0:
            yield from self._produce()
            return
        # background-thread prefetch pipeline (overlaps host batch prep
        # with device compute; dataloader_iter.py:368 analog)
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        err = []

        def worker():
            try:
                for item in self._produce():
                    q.put(_materialize_tree(item))
            except Exception as e:  # pragma: no cover
                err.append(e)
            finally:
                q.put(sentinel)

        th = threading.Thread(target=worker, daemon=True)
        th.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        th.join()
        if err:
            raise err[0]

    def _mp_iter(self):
        """num_workers>0: real worker PROCESSES (the reference's
        multiprocess DataLoader, io/dataloader/dataloader_iter.py:368).
        Batches are built + collated in forked children with numpy only
        and re-wrapped as Tensors here; output order is preserved."""
        import multiprocessing as mp
        if isinstance(self.dataset, IterableDataset):
            # iterable datasets cannot be index-sharded across workers
            # (reference splits via worker_info; not implemented) —
            # fall back to the threaded prefetch path
            import warnings
            warnings.warn("DataLoader: num_workers>0 with an "
                          "IterableDataset falls back to threaded "
                          "prefetch")
            yield from self._thread_iter()
            return
        ctx = mp.get_context("fork")
        index_q = ctx.Queue()
        data_q = ctx.Queue()
        user_collate = self.collate_fn
        if user_collate is default_collate_fn:
            collate = _np_collate
        else:
            collate = user_collate
        procs = [ctx.Process(target=_mp_worker_loop,
                             args=(self.dataset, collate, index_q,
                                   data_q), daemon=True)
                 for _ in range(self.num_workers)]
        for p in procs:
            p.start()
        n_batches = 0
        try:
            for batch_idx in self.batch_sampler:
                index_q.put((n_batches, list(batch_idx)))
                n_batches += 1
            for _ in procs:
                index_q.put(None)
            import queue as _queue
            pending = {}
            want = 0
            deadline = getattr(self, "timeout", None) or 120.0
            while want < n_batches:
                try:
                    seq, data, err = data_q.get(timeout=deadline)
                except _queue.Empty:
                    dead = [p.pid for p in procs if not p.is_alive()]
                    raise RuntimeError(
                        f"DataLoader timed out after {deadline}s waiting "
                        f"for batch {want}"
                        + (f"; worker(s) {dead} died" if dead else ""))
                if err is not None:
                    raise RuntimeError(
                        f"DataLoader worker failed on batch {seq}: {err}")
                pending[seq] = data
                while want in pending:
                    yield _tree_to_tensor(pending.pop(want))
                    want += 1
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)


class DevicePrefetcher:
    """Double-buffered host→device input feed.

    Wraps any iterator of batches (numpy arrays, Tensors, or nested
    tuples/lists/dicts of them — a DataLoader, a NativeTokenLoader, a
    generator) and keeps the next `depth` batches' host→device
    transfers IN FLIGHT while the current step executes: `jax.device_put`
    is async under PJRT, so issuing it a batch early overlaps the PCIe/
    ICI copy with step N's compute instead of serializing it into step
    N+1's dispatch (`FLAGS_prefetch_depth`, default 2, is the classic
    double buffer; 0/1 degrades to synchronous placement).

    The span budget shows this as host-gap time: with per-step input
    feed the gap carries the transfer, with the prefetcher it rides
    under `segment::execute`/device time. Used by the bench input path
    and available for any training loop::

        for tokens, labels in DevicePrefetcher(loader):
            loss = train_step(tokens, labels)
    """

    def __init__(self, source, depth: int = None):
        from .._core.flags import flag_value
        self._source = iter(source)
        self._depth = flag_value("FLAGS_prefetch_depth") \
            if depth is None else int(depth)

    @staticmethod
    def _to_device(obj):
        import jax
        if isinstance(obj, Tensor):
            # a Tensor batch already landed (or is lazily pending);
            # touch nothing — placement was the loader's job
            return obj
        if isinstance(obj, np.ndarray):
            from ..observability import _state as _obs
            if _obs.ACTIVE:
                # io::h2d carries the payload bytes, so the budget's
                # host gap and the comm-overlap report price the input
                # feed like any other transfer (device_put is async —
                # the span times the dispatch, the bytes price the
                # copy). Census birth site rides the same gate.
                from ..observability.spans import span
                _memtel = None
                if _obs.MEM:
                    from ..observability import memory as _memtel
                    _memtel.set_site("io:h2d")
                try:
                    with span("io::h2d", hist="io.h2d_us",
                              bytes=int(obj.nbytes)):
                        return Tensor(jax.device_put(obj))
                finally:
                    if _memtel is not None:
                        _memtel.clear_site()
            return Tensor(jax.device_put(obj))
        if isinstance(obj, (tuple, list)):
            return type(obj)(DevicePrefetcher._to_device(o) for o in obj)
        if isinstance(obj, dict):
            return {k: DevicePrefetcher._to_device(v)
                    for k, v in obj.items()}
        return obj

    def __iter__(self):
        depth = max(self._depth, 1)
        import collections
        ring = collections.deque()
        it = self._source
        from ..observability import _state as _obs
        try:
            while True:
                while len(ring) < depth:
                    # an empty ring means the NEXT pull blocks the
                    # training thread on the source (the feed stall
                    # that used to hide inside the host gap): the
                    # io::input_wait span + io.input_wait_us histogram
                    # make it visible and feed the goodput plane's
                    # input-wait bucket. Top-up pulls with a batch
                    # already buffered are prefetch work, not a stall.
                    starved = not ring and _obs.ACTIVE
                    try:
                        if starved:
                            from ..observability.spans import span
                            with span("io::input_wait",
                                      hist="io.input_wait_us"):
                                nxt = next(it)
                        else:
                            # device_put returns immediately; the
                            # transfer proceeds while earlier batches
                            # compute
                            nxt = next(it)
                        ring.append(self._to_device(nxt))
                    except StopIteration:
                        break
                if not ring:
                    return
                yield ring.popleft()
        finally:
            ring.clear()


from .token_feed import NativeTokenLoader  # noqa: E402,F401

__all__.append("NativeTokenLoader")
