"""CLI: run a small workload with telemetry on and print the stats.

    python -m paddle_tpu.observability [stats|budget]
        [--model chain|lenet|resnet50|gpt2] [--steps N]
        [--json] [--trace PATH] [--flight] [--async-flush]

Modes:

- ``stats`` (default): run the workload with metrics on, print the
  registry snapshot (counters / derived rates / histograms).
- ``budget``: the per-step time-budget profile — spans aggregated into
  a ranked table (segment flush/compile/execute, sot::, optimizer::,
  comm::, plus the unspanned **host gap**), the measurement that
  decides which hot-path item to burn next (observability/budget.py).

`chain` is the dispatch microbench's elementwise chain — fast,
exercises segment record/flush/cache. `lenet` runs real train steps
through the whole-step fusion path (step cache, fused optimizer).
`resnet50` / `gpt2` run the eager dygraph train loops of the bench
models (batch via BUDGET_BATCH, default small — sized for a quick
profile, not a benchmark). `--trace PATH` additionally records the run
under a fused-runtime profiler session and exports the chrome trace.
`--async-flush` turns the async dispatch pipeline on for the run so
before/after budgets come from one command. Exit code 0.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _run_chain(steps: int):
    import numpy as np
    import paddle_tpu as paddle

    x = paddle.to_tensor(np.ones((16, 16), "float32"))
    for _ in range(steps):
        y = x
        for _ in range(16):
            y = y * 1.0001 + 0.0001
        np.asarray(y._value)


def _train_loop(model, opt, x, y, loss_fn):
    import numpy as np

    def one():
        loss = loss_fn(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        np.asarray(loss._value)
    return one


def _lenet_step():
    """LeNet train step fed through the REAL input path — a DataLoader
    wrapped in DevicePrefetcher (FLAGS_prefetch_depth double buffer) —
    so the budget's host gap includes input feed the way a training
    loop pays it. Also the workload bench row 9 snapshots."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.io import DataLoader, Dataset, DevicePrefetcher
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    rng = np.random.RandomState(0)
    b = int(os.environ.get("BUDGET_BATCH", "32"))
    xs = rng.randn(4 * b, 1, 28, 28).astype(np.float32)
    ys = rng.randint(0, 10, (4 * b,)).astype(np.int64)

    class _Synth(Dataset):
        def __len__(self):
            return len(xs)

        def __getitem__(self, i):
            return xs[i], ys[i]

    def batches():
        while True:
            for xb, yb in DevicePrefetcher(
                    DataLoader(_Synth(), batch_size=b, drop_last=True)):
                yield xb, yb

    feed = batches()

    def one():
        x, y = next(feed)
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        np.asarray(loss._value)
    return one


def _resnet50_step():
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50()
    opt = paddle.optimizer.Momentum(0.1, parameters=model.parameters())
    rng = np.random.RandomState(0)
    b = int(os.environ.get("BUDGET_BATCH", "4"))
    x = paddle.to_tensor(rng.randn(b, 3, 224, 224).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 1000, (b,)).astype(np.int64))
    return _train_loop(model, opt, x, y, F.cross_entropy)


def _gpt2_step():
    """Eager dygraph GPT train step (the fusion-window path — the
    compiled functional trainer bench.py measures has no per-op host
    work to budget). Layer count/width via BUDGET_GPT_LAYERS/HIDDEN."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import (GPTConfig, GPTForPretraining,
                                       GPTPretrainingCriterion)

    paddle.seed(0)
    cfg = GPTConfig(
        vocab_size=1024,
        hidden_size=int(os.environ.get("BUDGET_GPT_HIDDEN", "128")),
        num_layers=int(os.environ.get("BUDGET_GPT_LAYERS", "4")),
        num_heads=4, dtype="float32", use_flash_attention=False,
        max_position_embeddings=int(os.environ.get("BUDGET_GPT_SEQ",
                                                   "128")))
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    rng = np.random.RandomState(0)
    b = int(os.environ.get("BUDGET_BATCH", "2"))
    seq = cfg.max_position_embeddings
    x = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                     (b, seq)).astype(np.int64))
    y = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                     (b, seq)).astype(np.int64))

    def one():
        logits = model(x)
        loss = crit(logits, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        np.asarray(loss._value)
    return one


_MODELS = {"chain": None, "lenet": _lenet_step,
           "resnet50": _resnet50_step, "gpt2": _gpt2_step}


def _render(snap: dict) -> str:
    lines = ["== paddle_tpu.observability stats =="]
    lines.append(f"  compiles:            {snap['compiles']}")
    for k in ("cache_hit_rate", "step_cache_hit_rate"):
        v = snap[k]
        lines.append(f"  {k + ':':<21}"
                     + ("n/a" if v is None else f"{v:.3f}"))
    lines.append("  counters:")
    for k in sorted(snap["counters"]):
        lines.append(f"    {k:<40} {snap['counters'][k]}")
    if snap["histograms"]:
        lines.append("  histograms (us):")
        for k in sorted(snap["histograms"]):
            h = snap["histograms"][k]
            if not h["count"]:
                continue
            lines.append(
                f"    {k:<40} n={h['count']} avg={h['avg']:.1f} "
                f"min={h['min']:.1f} max={h['max']:.1f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability")
    ap.add_argument("mode", nargs="?", default="stats",
                    choices=("stats", "budget"),
                    help="stats = registry snapshot; budget = ranked "
                         "per-step time-budget table")
    ap.add_argument("--model", default="chain",
                    choices=tuple(_MODELS))
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--json", action="store_true",
                    help="print the result as JSON")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="also export a fused-runtime chrome trace")
    ap.add_argument("--flight", action="store_true",
                    help="enable the flight recorder and print the ring")
    ap.add_argument("--async-flush", action="store_true",
                    help="run with FLAGS_async_flush on (before/after "
                         "budget comparisons from one command)")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs

    if args.async_flush:
        paddle.set_flags({"FLAGS_async_flush": True})

    if args.mode == "budget":
        from paddle_tpu.observability import budget as _budget
        make = _MODELS[args.model]
        step = (lambda: _run_chain(1)) if make is None else make()
        out = _budget.collect(step, steps=args.steps)
        out["model"] = args.model
        out["async_flush"] = bool(args.async_flush)
        print(json.dumps(out) if args.json
              else _budget.render(out, f"per-step budget [{args.model}]"))
        from paddle_tpu._core import async_flush
        async_flush.drain()
        return 0

    obs.enable(flight_recorder=args.flight or None)
    obs.reset()
    if args.model == "chain":
        run = _run_chain
    else:
        step = _MODELS[args.model]()

        def run(steps):
            for _ in range(steps):
                step()

    if args.trace:
        from paddle_tpu.profiler import Profiler, ProfilerTarget
        with Profiler(targets=[ProfilerTarget.CPU],
                      fused_runtime=True) as p:
            run(args.steps)
        path = p.export(args.trace)
        print(f"chrome trace written to {path}", file=sys.stderr)
    else:
        run(args.steps)

    # land any in-flight async flushes BEFORE snapshotting: counters
    # mid-flight would under-report, and an unread worker failure must
    # fail the command, not vanish into the atexit shutdown
    from paddle_tpu._core import async_flush
    async_flush.drain()
    snap = obs.stats()
    print(json.dumps(snap) if args.json else _render(snap))
    if args.flight:
        print(obs.flight_record())
    return 0


if __name__ == "__main__":
    sys.exit(main())
