"""CLI: run a small workload with telemetry on and print the stats.

    python -m paddle_tpu.observability [stats|budget|merge|top]
        [--model chain|lenet|resnet50|gpt2] [--steps N]
        [--json] [--trace PATH] [--flight] [--async-flush]
        [--distributed] [--nranks N]
        merge <dir>
        top [--port P | --store DIR] [--interval S] [--count N]

Modes:

- ``stats`` (default): run the workload with metrics on, print the
  registry snapshot (counters / derived rates / histograms).
- ``budget``: the per-step time-budget profile — spans aggregated into
  a ranked table (segment flush/compile/execute, sot::, optimizer::,
  comm::, io::, plus the unspanned **host gap**), the measurement that
  decides which hot-path item to burn next (observability/budget.py).
  The memory AND compute telemetry planes ride along: the header
  carries per-step byte columns (census peak watermark, compiled temp
  footprint, donated bytes per step) and the compute-efficiency
  columns — achieved GFLOP/s, MFU against the per-chip peak
  (FLAGS_device_peak_flops), and the roofline verdict (arithmetic
  intensity vs the ridge point: compute-bound vs memory-bound); the
  --json payload carries them as ``compute.mfu`` /
  ``compute.flops_per_step`` / ``compute.arith_intensity``.
- ``budget --distributed``: the cross-rank edition — spawns
  ``--nranks`` local trainer ranks over the distributed launcher, each
  publishing telemetry frames through a shared TCPStore while running
  compute + a host-driven gradient all-reduce per step; rank 0 merges
  them and the command prints the cluster step table (per-rank skew,
  straggler flags) and the comm-overlap report (the baseline the
  overlapped-collectives work must beat — ~0 today), and leaves the
  per-rank dumps + merged chrome trace in a scratch dir.
- ``merge <dir>``: offline aggregation — merge ``telem_rank*.json``
  dumps (written by TelemetryPublisher.dump) found in <dir> into the
  same step table + overlap report, and write ``merged_trace.json``
  (one chrome-trace lane per rank, clock-rebased) next to them.
- ``top``: a refreshing terminal table (per-rank step rate, step time,
  MFU, goodput fraction, peak MB, straggler flag) from either a LIVE
  monitor endpoint (``--port``/``--host`` — the ``/snapshot`` route of
  a job running with FLAGS_monitor + FLAGS_monitor_port) or a
  dumped-frames dir (``--store DIR`` holding ``telem_rank*.json``).
  ``--interval`` sets the refresh period, ``--count N`` stops after N
  renders (0 = until interrupted).

`chain` is the dispatch microbench's elementwise chain — fast,
exercises segment record/flush/cache. `lenet` runs real train steps
through the whole-step fusion path (step cache, fused optimizer).
`resnet50` / `gpt2` run the eager dygraph train loops of the bench
models (batch via BUDGET_BATCH, default small — sized for a quick
profile, not a benchmark). `--trace PATH` additionally records the run
under a fused-runtime profiler session and exports the chrome trace.
`--async-flush` turns the async dispatch pipeline on for the run so
before/after budgets come from one command. Exit code 0.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _run_chain(steps: int):
    import numpy as np
    import paddle_tpu as paddle

    x = paddle.to_tensor(np.ones((16, 16), "float32"))
    for _ in range(steps):
        y = x
        for _ in range(16):
            y = y * 1.0001 + 0.0001
        np.asarray(y._value)


def _train_loop(model, opt, x, y, loss_fn):
    import numpy as np

    def one():
        loss = loss_fn(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        np.asarray(loss._value)
    return one


def _lenet_step():
    """LeNet train step fed through the REAL input path — a DataLoader
    wrapped in DevicePrefetcher (FLAGS_prefetch_depth double buffer) —
    so the budget's host gap includes input feed the way a training
    loop pays it. Also the workload bench row 9 snapshots."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.io import DataLoader, Dataset, DevicePrefetcher
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    rng = np.random.RandomState(0)
    b = int(os.environ.get("BUDGET_BATCH", "32"))
    xs = rng.randn(4 * b, 1, 28, 28).astype(np.float32)
    ys = rng.randint(0, 10, (4 * b,)).astype(np.int64)

    class _Synth(Dataset):
        def __len__(self):
            return len(xs)

        def __getitem__(self, i):
            return xs[i], ys[i]

    def batches():
        while True:
            for xb, yb in DevicePrefetcher(
                    DataLoader(_Synth(), batch_size=b, drop_last=True)):
                yield xb, yb

    feed = batches()

    def one():
        x, y = next(feed)
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        np.asarray(loss._value)
    return one


def _resnet50_step():
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50

    # the perf lint's segment_cap remedy (its diagnostic hint says
    # `set FLAGS_lazy_max_segment_ops >= 547`): the eager train step
    # records 547 ops, so the default 256 cap paid 2 window breaks per
    # step — forfeiting the step cache and optimizer donation — that
    # the analyzer already diagnosed
    paddle.set_flags({"FLAGS_lazy_max_segment_ops": 1024})
    paddle.seed(0)
    model = resnet50()
    opt = paddle.optimizer.Momentum(0.1, parameters=model.parameters())
    rng = np.random.RandomState(0)
    b = int(os.environ.get("BUDGET_BATCH", "4"))
    x = paddle.to_tensor(rng.randn(b, 3, 224, 224).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 1000, (b,)).astype(np.int64))
    return _train_loop(model, opt, x, y, F.cross_entropy)


def _gpt2_step():
    """Eager dygraph GPT train step (the fusion-window path — the
    compiled functional trainer bench.py measures has no per-op host
    work to budget). Layer count/width via BUDGET_GPT_LAYERS/HIDDEN."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import (GPTConfig, GPTForPretraining,
                                       GPTPretrainingCriterion)

    paddle.seed(0)
    cfg = GPTConfig(
        vocab_size=1024,
        hidden_size=int(os.environ.get("BUDGET_GPT_HIDDEN", "128")),
        num_layers=int(os.environ.get("BUDGET_GPT_LAYERS", "4")),
        num_heads=4, dtype="float32", use_flash_attention=False,
        max_position_embeddings=int(os.environ.get("BUDGET_GPT_SEQ",
                                                   "128")))
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    rng = np.random.RandomState(0)
    b = int(os.environ.get("BUDGET_BATCH", "2"))
    seq = cfg.max_position_embeddings
    x = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                     (b, seq)).astype(np.int64))
    y = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                     (b, seq)).astype(np.int64))

    def one():
        # one expression: a surviving grad-requiring `logits` local
        # would route backward() to the generic engine instead of the
        # fused fwd+vjp step — with the flash-attention record fix the
        # GPT step now reaches its fused steady state
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        np.asarray(loss._value)
    return one


_MODELS = {"chain": None, "lenet": _lenet_step,
           "resnet50": _resnet50_step, "gpt2": _gpt2_step}


# ------------------------------------------------- distributed budget
# One trainer rank of the local drill: compute chain + host-driven
# gradient all-reduce per step under ElasticStep (so the step:: fault
# sites and the telemetry on_step hook both fire), frames published
# through the shared TCPStore. Env knobs (set by the CLI/test parent):
#   TELEM_OUT        output dir (dumps, merged artifacts)
#   TELEM_STEPS      steps per rank
#   TELEM_SLOW_RANK  optional straggler: that rank runs with an
#                    injected step::*=delay fault (TELEM_SLOW_DELAY s)
#   TELEM_KILL_RANK/TELEM_KILL_STEP  optional death drill: SIGKILL
#                    self after completing that step; the kill rank is
#                    excluded from the comm group up front so survivor
#                    collectives never hang on a dead peer (collective
#                    death handling is the resilience layer's job, not
#                    this measurement's)
_DISTRIBUTED_DRILL = """
import json, os, signal, sys, time
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.communication import Group, all_reduce
from paddle_tpu.distributed.process_group import ProcessGroup
from paddle_tpu.distributed.resilience import ElasticStep
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.observability import distributed as dtel

RANK = int(os.environ["PADDLE_TRAINER_ID"])
WORLD = int(os.environ["PADDLE_TRAINERS_NUM"])
OUT = os.environ["TELEM_OUT"]
STEPS = int(os.environ.get("TELEM_STEPS", "10"))
SLOW = int(os.environ.get("TELEM_SLOW_RANK", "-1"))
KILL = int(os.environ.get("TELEM_KILL_RANK", "-1"))
KILL_STEP = int(os.environ.get("TELEM_KILL_STEP", "2"))

paddle.set_flags({"FLAGS_observability": True,
                  "FLAGS_flight_recorder": True,
                  "FLAGS_distributed_telemetry": True,
                  "FLAGS_memory_telemetry": True,
                  "FLAGS_compute_telemetry": True,
                  "FLAGS_goodput": True})
if RANK == SLOW:
    delay = os.environ.get("TELEM_SLOW_DELAY", "0.05")
    paddle.set_flags({"FLAGS_fault_inject":          # @* = every step
                      "step::*@*=delay(%s)" % delay})

store = TCPStore(os.environ["MASTER_ADDR"],
                 int(os.environ["MASTER_PORT"]),
                 is_master=(RANK == 0), world_size=WORLD, timeout=120)
pub = dtel.init(store, rank=RANK, world_size=WORLD)

comm_ranks = [r for r in range(WORLD) if r != KILL]
group = None
if RANK in comm_ranks and len(comm_ranks) > 1:
    group = Group(comm_ranks,
                  pg=ProcessGroup(store, RANK, comm_ranks, gid=1))

x = paddle.to_tensor(np.ones((64, 64), "float32"))
grad = paddle.to_tensor(
    np.ones((256, 256), "float32"))        # 256 KB payload
w = paddle.to_tensor(np.zeros((64, 64), "float32"))
opt = paddle.optimizer.SGD(0.0, parameters=[w])
elastic = ElasticStep(optimizer=opt)


def step():
    y = x
    for _ in range(16):
        y = y * 1.0001 + 0.0001
    np.asarray(y._value)                   # compute lands
    if group is not None:
        all_reduce(grad, group=group)      # host-driven gradient sync
    return y


for s in range(1, STEPS + 1):
    elastic.run(step)
    if RANK == KILL and s == KILL_STEP:
        pub.flush()
        os.kill(os.getpid(), signal.SIGKILL)

pub.flush()
pub.dump(OUT)
if group is not None:
    group.pg.barrier()                     # every dump + frame landed
if KILL >= 0:
    # death drill: survivors publish their flight rings; rank 0 also
    # aggregates the interleaved report (grace-bounded store polls)
    post = dtel.trigger_postmortem(
        "drill: rank %d killed at step %d" % (KILL, KILL_STEP))
else:
    post = None

if RANK == 0:
    agg = dtel.TelemetryAggregator()
    agg.poll_store(store, list(range(WORLD)))
    for r in range(WORLD):   # prefer full offline dumps when present
        p = os.path.join(OUT, "telem_rank%d.json" % r)
        if os.path.exists(p):
            agg.add_dump(p)
    out = {"nranks": WORLD, "steps": STEPS,
           "step_table": agg.step_table(),
           "overlap": agg.overlap_report(),
           "goodput": agg.goodput_report(),
           "postmortem": post}
    agg.merged_trace(os.path.join(OUT, "merged_trace.json"))
    with open(os.path.join(OUT, "distributed_budget.json"), "w") as f:
        json.dump(out, f)
if group is not None:
    group.pg.barrier()                     # hold the store master open
pub.shutdown()
store.close()
"""


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _budget_distributed(args) -> int:
    """Spawn `--nranks` local ranks over the distributed launcher, let
    rank 0 aggregate, print the merged step table + overlap report."""
    import subprocess
    import tempfile

    out_dir = args.out or tempfile.mkdtemp(prefix="pt_telem_")
    os.makedirs(out_dir, exist_ok=True)
    script = os.path.join(out_dir, "_telem_drill.py")
    with open(script, "w") as f:
        f.write(_DISTRIBUTED_DRILL)
    env = dict(os.environ)
    env["TELEM_OUT"] = out_dir
    env["TELEM_STEPS"] = str(args.steps)
    env.pop("MASTER_ADDR", None)
    env.pop("MASTER_PORT", None)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", str(args.nranks),
         "--elastic_mode", "shrink", "--min_np", "1",
         "--log_dir", os.path.join(out_dir, "log"),
         "--master", f"127.0.0.1:{_free_port()}", script],
        env=env, cwd=out_dir, capture_output=True, text=True,
        timeout=600)
    result_path = os.path.join(out_dir, "distributed_budget.json")
    if proc.returncode != 0 or not os.path.exists(result_path):
        sys.stderr.write(proc.stderr)
        logdir = os.path.join(out_dir, "log")
        if os.path.isdir(logdir):
            for name in sorted(os.listdir(logdir)):
                with open(os.path.join(logdir, name)) as f:
                    tail = f.read()[-1500:]
                sys.stderr.write(f"\n--- {name}\n{tail}\n")
        print(f"distributed budget failed (rc={proc.returncode})",
              file=sys.stderr)
        return proc.returncode or 1
    with open(result_path) as f:
        out = json.load(f)
    out["out_dir"] = out_dir
    if args.json:
        print(json.dumps(out))
    else:
        from paddle_tpu.observability import distributed as dtel
        print(dtel.render_step_table(out["step_table"]))
        print(dtel.render_overlap(out["overlap"]))
        print(dtel.render_goodput(out.get("goodput")))
        if out.get("postmortem"):
            print(f"distributed postmortem: {out['postmortem']}")
        print(f"artifacts (dumps, merged_trace.json) in {out_dir}")
    return 0


def _merge(args) -> int:
    """Offline aggregation over telem_rank*.json dumps in a dir."""
    import glob

    from paddle_tpu.observability import distributed as dtel

    d = args.path
    if not d or not os.path.isdir(d):
        print(f"merge: {d!r} is not a directory", file=sys.stderr)
        return 2
    dumps = sorted(glob.glob(os.path.join(d, "telem_rank*.json")))
    if not dumps:
        print(f"merge: no telem_rank*.json dumps in {d}",
              file=sys.stderr)
        return 2
    agg = dtel.TelemetryAggregator()
    for p in dumps:
        agg.add_dump(p)
    trace_path = os.path.join(d, "merged_trace.json")
    agg.merged_trace(trace_path)
    out = {"ranks": agg.ranks, "step_table": agg.step_table(),
           "overlap": agg.overlap_report(),
           "goodput": agg.goodput_report(), "trace": trace_path}
    if args.json:
        print(json.dumps(out))
    else:
        print(dtel.render_step_table(out["step_table"]))
        print(dtel.render_overlap(out["overlap"]))
        if out.get("goodput"):
            print(dtel.render_goodput(out["goodput"]))
        print(f"merged chrome trace written to {trace_path}")
    return 0


def _top_once(args) -> str:
    """One rendered top table (store dir or live endpoint)."""
    from paddle_tpu.observability import exporter

    if args.store:
        import glob

        from paddle_tpu.observability import distributed as dtel
        dumps = sorted(glob.glob(
            os.path.join(args.store, "telem_rank*.json")))
        if not dumps:
            raise FileNotFoundError(
                f"top: no telem_rank*.json dumps in {args.store}")
        agg = dtel.TelemetryAggregator()
        for p in dumps:
            agg.add_dump(p)
        return exporter.render_top(exporter.cluster_rows(agg),
                                   title=args.store)
    import urllib.request
    url = f"http://{args.host}:{args.port}/snapshot"
    with urllib.request.urlopen(url, timeout=10) as resp:
        snap = json.loads(resp.read().decode("utf-8"))
    rows = snap.get("cluster_rows")
    if rows is None:
        # single-process job: one row from the monitor's newest samples
        mon = snap.get("monitor", {})
        s = mon.get("series_latest", {})
        rows = [{"rank": snap.get("rank", 0),
                 "steps_per_s": s.get("steps_per_s"),
                 "step_time_ms": s.get("step_time_ms"),
                 "mfu": s.get("mfu"),
                 "goodput_frac": s.get("goodput_frac"),
                 "peak_bytes": s.get("mem_peak_bytes"),
                 "straggler_steps": 0}]
    return exporter.render_top(rows, title=url)


def _top(args) -> int:
    import time as _time
    n = 0
    while True:
        try:
            text = _top_once(args)
        except (OSError, FileNotFoundError) as e:
            print(f"top: {e}", file=sys.stderr)
            return 2
        if args.count != 1:
            sys.stdout.write("\x1b[2J\x1b[H")   # clear + home
        print(text, flush=True)
        n += 1
        if args.count and n >= args.count:
            return 0
        try:
            _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def _render(snap: dict) -> str:
    lines = ["== paddle_tpu.observability stats =="]
    lines.append(f"  compiles:            {snap['compiles']}")
    for k in ("cache_hit_rate", "step_cache_hit_rate"):
        v = snap[k]
        lines.append(f"  {k + ':':<21}"
                     + ("n/a" if v is None else f"{v:.3f}"))
    mem = snap.get("memory")
    if mem:
        lines.append(f"  memory:              live {mem['live_bytes']} B"
                     f", peak {mem['peak_bytes']} B, donated "
                     f"{mem['donated_bytes']} B, census {mem['census']} "
                     f"buffer(s)")
    good = snap.get("goodput")
    if good and good.get("goodput_frac") is not None:
        top = good.get("top_badput")
        lines.append(
            f"  goodput:             "
            f"{good['goodput_frac'] * 100.0:.1f}% productive over "
            f"{good['steps']} step(s)"
            + (f", top badput {top['bucket']}" if top else ""))
    lines.append("  counters:")
    for k in sorted(snap["counters"]):
        lines.append(f"    {k:<40} {snap['counters'][k]}")
    if snap["histograms"]:
        lines.append("  histograms (us):")
        for k in sorted(snap["histograms"]):
            h = snap["histograms"][k]
            if not h["count"]:
                continue
            lines.append(
                f"    {k:<40} n={h['count']} avg={h['avg']:.1f} "
                f"min={h['min']:.1f} max={h['max']:.1f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability")
    ap.add_argument("mode", nargs="?", default="stats",
                    choices=("stats", "budget", "merge", "top"),
                    help="stats = registry snapshot; budget = ranked "
                         "per-step time-budget table; merge = offline "
                         "aggregation of per-rank telemetry dumps; "
                         "top = refreshing per-rank cluster table from "
                         "a live monitor endpoint or dumped frames")
    ap.add_argument("path", nargs="?", default=None,
                    help="merge mode: directory holding "
                         "telem_rank*.json dumps")
    ap.add_argument("--model", default="chain",
                    choices=tuple(_MODELS))
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--distributed", action="store_true",
                    help="budget mode: spawn --nranks local trainer "
                         "ranks over the launcher and print the merged "
                         "cross-rank step table + comm-overlap report")
    ap.add_argument("--nranks", type=int, default=4,
                    help="rank count for budget --distributed")
    ap.add_argument("--out", default=None, metavar="DIR",
                    help="budget --distributed: artifact directory "
                         "(default: a fresh temp dir)")
    ap.add_argument("--json", action="store_true",
                    help="print the result as JSON")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="also export a fused-runtime chrome trace")
    ap.add_argument("--flight", action="store_true",
                    help="enable the flight recorder and print the ring")
    ap.add_argument("--async-flush", action="store_true",
                    help="run with FLAGS_async_flush on (before/after "
                         "budget comparisons from one command)")
    ap.add_argument("--port", type=int, default=0,
                    help="top mode: live monitor endpoint port "
                         "(FLAGS_monitor_port of the running job)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="top mode: live monitor endpoint host")
    ap.add_argument("--store", default=None, metavar="DIR",
                    help="top mode: render from telem_rank*.json "
                         "dumps in DIR instead of a live endpoint")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="top mode: refresh period in seconds")
    ap.add_argument("--count", type=int, default=0,
                    help="top mode: stop after N renders "
                         "(0 = until interrupted)")
    ap.add_argument("--static-diff", action="store_true",
                    help="budget mode: reconcile the static perf "
                         "analyzer's predictions (one traced step, "
                         "analysis/perf_checks) against the measured "
                         "seal-reason / window-break / compiled-comm "
                         "counters over --steps steps; exit 1 on a "
                         "mismatch")
    args = ap.parse_args(argv)

    if args.mode == "merge":
        return _merge(args)
    if args.mode == "top":
        if not args.store and not args.port:
            print("top: pass --port (live endpoint) or --store DIR "
                  "(dumped frames)", file=sys.stderr)
            return 2
        return _top(args)
    if args.mode == "budget" and args.distributed:
        return _budget_distributed(args)

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs

    if args.async_flush:
        paddle.set_flags({"FLAGS_async_flush": True})

    if args.mode == "budget":
        from paddle_tpu.observability import budget as _budget
        make = _MODELS[args.model]
        step = (lambda: _run_chain(1)) if make is None else make()
        if args.static_diff:
            out = _budget.static_diff(step, steps=args.steps)
            out["model"] = args.model
            print(json.dumps(out) if args.json
                  else _budget.render_static_diff(
                      out, f"static vs measured [{args.model}]"))
            from paddle_tpu._core import async_flush
            async_flush.drain()
            return 0 if out["ok"] else 1
        out = _budget.collect(step, steps=args.steps)
        out["model"] = args.model
        out["async_flush"] = bool(args.async_flush)
        print(json.dumps(out) if args.json
              else _budget.render(out, f"per-step budget [{args.model}]"))
        from paddle_tpu._core import async_flush
        async_flush.drain()
        return 0

    obs.enable(flight_recorder=args.flight or None)
    obs.reset()
    if args.model == "chain":
        run = _run_chain
    else:
        step = _MODELS[args.model]()

        def run(steps):
            for _ in range(steps):
                step()

    if args.trace:
        from paddle_tpu.profiler import Profiler, ProfilerTarget
        with Profiler(targets=[ProfilerTarget.CPU],
                      fused_runtime=True) as p:
            run(args.steps)
        path = p.export(args.trace)
        print(f"chrome trace written to {path}", file=sys.stderr)
    else:
        run(args.steps)

    # land any in-flight async flushes BEFORE snapshotting: counters
    # mid-flight would under-report, and an unread worker failure must
    # fail the command, not vanish into the atexit shutdown
    from paddle_tpu._core import async_flush
    async_flush.drain()
    snap = obs.stats()
    print(json.dumps(snap) if args.json else _render(snap))
    if args.flight:
        print(obs.flight_record())
    return 0


if __name__ == "__main__":
    sys.exit(main())
