"""CLI: run a small workload with telemetry on and print the stats.

    python -m paddle_tpu.observability [--model chain|lenet] [--steps N]
                                       [--json] [--trace PATH] [--flight]

`chain` (default) is the dispatch microbench's elementwise chain —
fast, exercises segment record/flush/cache. `lenet` runs real train
steps through the whole-step fusion path (step cache, fused optimizer).
`--trace PATH` additionally records the run under a fused-runtime
profiler session and exports the chrome trace there. Exit code 0.
"""
from __future__ import annotations

import argparse
import json
import sys


def _run_chain(steps: int):
    import numpy as np
    import paddle_tpu as paddle

    x = paddle.to_tensor(np.ones((16, 16), "float32"))
    for _ in range(steps):
        y = x
        for _ in range(16):
            y = y * 1.0001 + 0.0001
        np.asarray(y._value)


def _run_lenet(steps: int):
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(32, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (32,)).astype(np.int64))
    for _ in range(steps):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        np.asarray(loss._value)


def _render(snap: dict) -> str:
    lines = ["== paddle_tpu.observability stats =="]
    lines.append(f"  compiles:            {snap['compiles']}")
    for k in ("cache_hit_rate", "step_cache_hit_rate"):
        v = snap[k]
        lines.append(f"  {k + ':':<21}"
                     + ("n/a" if v is None else f"{v:.3f}"))
    lines.append("  counters:")
    for k in sorted(snap["counters"]):
        lines.append(f"    {k:<40} {snap['counters'][k]}")
    if snap["histograms"]:
        lines.append("  histograms (us):")
        for k in sorted(snap["histograms"]):
            h = snap["histograms"][k]
            if not h["count"]:
                continue
            lines.append(
                f"    {k:<40} n={h['count']} avg={h['avg']:.1f} "
                f"min={h['min']:.1f} max={h['max']:.1f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability")
    ap.add_argument("--model", default="chain",
                    choices=("chain", "lenet"))
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--json", action="store_true",
                    help="print the stats snapshot as JSON")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="also export a fused-runtime chrome trace")
    ap.add_argument("--flight", action="store_true",
                    help="enable the flight recorder and print the ring")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs

    obs.enable(flight_recorder=args.flight or None)
    obs.reset()
    run = _run_lenet if args.model == "lenet" else _run_chain

    if args.trace:
        from paddle_tpu.profiler import Profiler, ProfilerTarget
        with Profiler(targets=[ProfilerTarget.CPU],
                      fused_runtime=True) as p:
            run(args.steps)
        path = p.export(args.trace)
        print(f"chrome trace written to {path}", file=sys.stderr)
    else:
        run(args.steps)

    snap = obs.stats()
    print(json.dumps(snap) if args.json else _render(snap))
    if args.flight:
        print(obs.flight_record())
    return 0


if __name__ == "__main__":
    sys.exit(main())
