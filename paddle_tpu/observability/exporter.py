"""Zero-dependency HTTP exporter for the live monitoring plane.

A stdlib `http.server` endpoint (started by the `FLAGS_monitor` watcher
when `FLAGS_monitor_port` is set; loopback-bound by default via
`FLAGS_monitor_host`) serving:

- ``/metrics`` — Prometheus text exposition (version 0.0.4): every
  registry counter as a ``counter``, registry gauges + the monitor
  rings' newest samples as ``gauge``s, histogram count/total pairs,
  all name-sanitized and labeled with this process's trainer ``rank``.
  With a cluster source attached (rank 0 polling the PR-8 telemetry
  frames), per-rank step-rate/MFU/goodput/peak-bytes gauges plus
  straggler and skew columns ride along under ``rank`` labels.
- ``/healthz`` — liveness verdict: hang-watchdog state, last step age,
  membership epoch. A tripped hang watchdog maps to HTTP 503 so an
  external prober can page without parsing the body.
- ``/snapshot`` — the full ``observability.stats()`` JSON plus the
  monitor's newest samples and fired regressions (and the cluster rows
  when attached).
- ``/timeseries?name=`` — one ring dumped as ``[[t_wall, value], ...]``
  (no name = the series directory).

Scrapes read snapshots only; the exporter never mutates the registry.
"""
from __future__ import annotations

import json
import os
import re
import threading
from typing import Callable, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from . import _state

_SERVER = None
_THREAD: Optional[threading.Thread] = None
_LOCK = threading.Lock()

# cluster mode: (aggregator, poll_fn) — poll_fn (may be None) refreshes
# the aggregator's frame intake before each scrape
_CLUSTER = None

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _rank() -> int:
    r = os.environ.get("PADDLE_TRAINER_ID")
    return int(r) if r and r.isdigit() else 0


def sanitize(name: str) -> str:
    """Prometheus metric-name sanitization: every illegal character
    becomes '_', a leading digit gets a '_' prefix."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _line(out: List[str], name: str, kind: str, value,
          labels: Optional[Dict[str, object]] = None,
          typed: Optional[set] = None):
    full = "paddle_tpu_" + sanitize(name)
    if typed is not None and full not in typed:
        typed.add(full)
        out.append(f"# TYPE {full} {kind}")
    lab = dict(labels or {})
    lab.setdefault("rank", _rank())
    pairs = ",".join(f'{k}="{v}"' for k, v in sorted(lab.items()))
    try:
        v = float(value)
    except (TypeError, ValueError):
        return
    body = repr(int(v)) if v == int(v) else repr(v)
    out.append(f"{full}{{{pairs}}} {body}")


# series whose dotted suffix is a label, not part of the name
_SERIES_LABELS = {"mem_device_bytes": "device", "badput_frac": "bucket"}


def render_metrics() -> str:
    """The /metrics payload (also directly callable for tests and for
    scrape-free consumers)."""
    from . import metrics, timeseries
    out: List[str] = []
    typed: set = set()
    snap = metrics.snapshot()
    for k in sorted(snap["counters"]):
        _line(out, k + "_total", "counter", snap["counters"][k],
              typed=typed)
    for k in sorted(snap["gauges"]):
        _line(out, k, "gauge", snap["gauges"][k], typed=typed)
    for k in sorted(snap["histograms"]):
        h = snap["histograms"][k]
        _line(out, k + "_count", "counter", h["count"] or 0,
              typed=typed)
        _line(out, k + "_sum", "counter", h["total"] or 0.0,
              typed=typed)
    for name, value in sorted(timeseries.latest().items()):
        base, _, tail = name.partition(".")
        key = _SERIES_LABELS.get(base)
        if key and tail:
            _line(out, "monitor_" + base, "gauge", value,
                  labels={key: tail}, typed=typed)
        else:
            _line(out, "monitor_" + sanitize(name), "gauge", value,
                  typed=typed)
    cluster = _cluster_section()
    if cluster:
        for row in cluster["rows"]:
            lab = {"rank": row["rank"]}
            for col, kind in (("steps_per_s", "gauge"),
                              ("step_time_ms", "gauge"),
                              ("mfu", "gauge"),
                              ("goodput_frac", "gauge"),
                              ("peak_bytes", "gauge"),
                              ("straggler_steps", "gauge")):
                if row.get(col) is not None:
                    _line(out, "cluster_" + col, kind, row[col],
                          labels=lab, typed=typed)
        if cluster.get("skew_us") is not None:
            _line(out, "cluster_step_skew_us", "gauge",
                  cluster["skew_us"], typed=typed)
    return "\n".join(out) + "\n"


# ------------------------------------------------------------- cluster

def attach_cluster(aggregator, poll: Optional[Callable] = None):
    """Rank 0 attaches its TelemetryAggregator (and optionally a
    refresh callable — e.g. ``lambda: agg.poll_store(store, ranks)``)
    so /metrics and /snapshot merge the whole job under rank labels."""
    global _CLUSTER
    _CLUSTER = (aggregator, poll)


def detach_cluster():
    global _CLUSTER
    _CLUSTER = None


def cluster_rows(agg) -> List[Dict]:
    """Per-rank summary rows from a TelemetryAggregator: step rate,
    mean step time, MFU, goodput fraction, peak bytes, straggler step
    count — the `top` table and the cluster /metrics section."""
    table = agg.step_table()
    rep = agg.goodput_report() or {}
    mem = (table.get("memory") or {}).get("ranks", {})
    comp = (table.get("compute") or {}).get("ranks", {})
    strag = table.get("straggler_counts", {})
    rows = []
    for r in agg.ranks:
        rs = str(r)
        durs = [row["ranks"][rs] for row in table["steps"]
                if rs in row["ranks"]]
        mean_us = (sum(durs) / len(durs)) if durs else None
        good = (rep.get("ranks", {}).get(rs) or {})
        rows.append({
            "rank": int(r),
            "steps_per_s": (round(1e6 / mean_us, 3)
                            if mean_us else None),
            "step_time_ms": (round(mean_us / 1e3, 3)
                             if mean_us else None),
            "mfu": comp.get(rs, {}).get("mfu"),
            "goodput_frac": good.get("goodput_frac"),
            "top_badput": (good.get("top_badput") or {}).get("bucket")
            if good.get("top_badput") else None,
            "peak_bytes": mem.get(rs, {}).get("peak"),
            "straggler_steps": int(strag.get(rs, 0)),
        })
    return rows


def _cluster_section() -> Optional[Dict]:
    c = _CLUSTER
    if c is None:
        return None
    agg, poll = c
    try:
        if poll is not None:
            poll()
        table_rows = cluster_rows(agg)
        skew = None
        table = agg.step_table()
        if table["steps"]:
            skew = table["steps"][-1].get("skew_us")
        return {"rows": table_rows, "skew_us": skew}
    except Exception:
        return None


def render_top(rows: List[Dict], title: str = "cluster") -> str:
    """The `python -m paddle_tpu.observability top` table body."""
    lines = [f"== paddle_tpu top [{title}] ==",
             "  rank | steps/s | step ms |   MFU  | goodput | "
             "peak MB | straggler"]
    for row in rows:
        def fmt(v, spec):
            if v is None:   # keep the column width: pad the dash
                return format("-", ">" + spec.split(".")[0])
            return format(v, spec)
        strag = row.get("straggler_steps") or 0
        flag = (f"YES x{strag}" if strag else "-")
        bad = row.get("top_badput")
        good = row.get("goodput_frac")
        goodcell = (f"{good * 100:5.1f}%" if good is not None else "-")
        if bad and good is not None:
            goodcell += f" ({bad})"
        lines.append(
            f"  r{row['rank']:<3} | {fmt(row.get('steps_per_s'), '7.2f')}"
            f" | {fmt(row.get('step_time_ms'), '7.2f')}"
            f" | {fmt(row.get('mfu'), '6.4f')}"
            f" | {goodcell:>7}"
            f" | {fmt((row.get('peak_bytes') or 0) / 1048576.0, '7.1f')}"
            f" | {flag}")
    if len(lines) == 2:
        lines.append("  (no frames yet)")
    return "\n".join(lines)


# -------------------------------------------------------------- health

def health() -> Dict:
    """The /healthz verdict. Unhealthy (HTTP 503) iff the goodput hang
    watchdog has tripped; the body always carries the staleness and
    membership columns so a prober can apply its own policy too."""
    import sys
    from . import timeseries
    hang = None
    hangs = 0
    good = sys.modules.get(__package__ + ".goodput")
    if good is not None:
        hangs = good.LEDGER.hangs
        if good.LEDGER.last_hang:
            hang = {k: v for k, v in good.LEDGER.last_hang.items()
                    if k != "stacks"}
    from .._core import lazy
    return {"ok": hang is None,
            "hang": hang, "hangs": hangs,
            "last_step_age_s": timeseries.last_step_age_s(),
            "steps": timeseries.STEPS,
            "membership_epoch": lazy.MESH_EPOCH}


def snapshot() -> Dict:
    """The /snapshot payload: stats() + the monitor surface."""
    from . import stats, timeseries
    snap = stats()
    snap["rank"] = _rank()
    snap["monitor"] = {
        "series_latest": timeseries.latest(),
        "series": timeseries.series_names(),
        "steps": timeseries.STEPS,
        "tokens": timeseries.TOKENS,
        "last_step_age_s": timeseries.last_step_age_s(),
        "regressions": list(timeseries.REGRESSIONS),
    }
    cluster = _cluster_section()
    if cluster:
        snap["cluster_rows"] = cluster["rows"]
        snap["cluster_skew_us"] = cluster["skew_us"]
    return snap


# ------------------------------------------------------------- server

def _make_handler():
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        server_version = "paddle_tpu_monitor"

        def log_message(self, *a):   # scrapes must not spam stderr
            pass

        def _send(self, code: int, body: str, ctype: str):
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            try:
                url = urlparse(self.path)
                if url.path == "/metrics":
                    self._send(200, render_metrics(),
                               "text/plain; version=0.0.4; "
                               "charset=utf-8")
                elif url.path == "/healthz":
                    h = health()
                    self._send(200 if h["ok"] else 503,
                               json.dumps(h), "application/json")
                elif url.path == "/snapshot":
                    self._send(200, json.dumps(snapshot()),
                               "application/json")
                elif url.path == "/timeseries":
                    from . import timeseries
                    q = parse_qs(url.query)
                    name = (q.get("name") or [None])[0]
                    if name is None:
                        body = {"series": timeseries.series_names()}
                    else:
                        body = {"name": name,
                                "samples": timeseries.series(name)}
                    self._send(200, json.dumps(body),
                               "application/json")
                else:
                    self._send(404, json.dumps(
                        {"error": "unknown path", "paths": [
                            "/metrics", "/healthz", "/snapshot",
                            "/timeseries"]}), "application/json")
            except BrokenPipeError:
                pass
            except Exception as e:   # a bad scrape must not kill serving
                try:
                    self._send(500, json.dumps({"error": repr(e)}),
                               "application/json")
                except Exception:
                    pass

    return Handler


def start(port: int, host: str = "127.0.0.1") -> int:
    """Bind and serve on a daemon thread (idempotent); returns the
    bound port (useful with port 0)."""
    global _SERVER, _THREAD
    from http.server import ThreadingHTTPServer
    with _LOCK:
        if _SERVER is not None:
            return _SERVER.server_address[1]
        srv = ThreadingHTTPServer((host, int(port)), _make_handler())
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever,
                             name="pt-monitor-exporter", daemon=True)
        t.start()
        _SERVER, _THREAD = srv, t
        return srv.server_address[1]


def stop():
    global _SERVER, _THREAD
    with _LOCK:
        srv, t = _SERVER, _THREAD
        _SERVER = _THREAD = None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if t is not None:
        t.join(timeout=2.0)


def bound_port() -> Optional[int]:
    srv = _SERVER
    return srv.server_address[1] if srv is not None else None
