"""Byte-domain telemetry plane: HBM accounting for the fused runtime.

Every other observability surface (spans, budgets, cross-rank frames)
measures the framework in the TIME domain; this module measures it in
BYTES — the resource that actually bounds batch/model scaling on TPUs
("Exploring the limits of Concurrency in ML Training on Google TPUs":
memory, not FLOPs, picks the mesh degree). Four pillars:

- **live-buffer census**: a weakref registry of device-backed payloads
  (nbytes, dtype, shape, birth site) maintained at the Tensor-creation
  and lazy bind/materialize choke points. Feeds the
  ``memory.live_bytes`` / ``memory.peak_bytes`` watermark gauges and a
  top-N accessor. The census NEVER holds a strong reference — a buffer
  leaves the moment its last owner drops it (donation included).
- **per-executable XLA memory analysis**: compile sites (plain segment
  flush, fused fwd+vjp step, fused optimizer update) route through the
  jax AOT path while the plane is on, so ``compiled.memory_analysis()``
  (temp / argument / output / generated-code bytes) is captured exactly
  ONCE per compile and cached on the ExecCache entry — the step cache
  reports its steady-state compiled footprint without re-running
  anything.
- **donation savings accounting**: the lazy-flush donation mask and the
  fused optimizer's ``donate_argnums`` sites report the bytes donated
  per step (``memory.donated_bytes``) — the concrete number the
  donation machinery buys, and what a ``fusion.window_breaks`` step
  forfeits.
- **OOM postmortem**: the three execute sites catch XLA
  RESOURCE_EXHAUSTED (and the seedable ``exec::oom`` fault-injection
  drill), write a postmortem naming the top-N live buffers with
  provenance plus the failing executable's memory analysis and the
  current watermark, then re-raise as the typed
  ``base.core.ResourceExhaustedError`` (the async flush worker latches
  the typed error, so the sync point sees the same class).

Off-cost follows the house pattern: ``FLAGS_memory_telemetry`` is
watcher-cached into the ``_state.MEM`` module gate (folded into
``_state.ACTIVE``); off = one module-attribute read at every choke
point, zero census and zero registry work (bench_suite row 11 asserts
both exactly).
"""
from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from . import _state

# census lock is REENTRANT: a gc triggered while it is held can fire a
# dead buffer's weakref callback (_drop) on the same thread. Metrics /
# profiler calls always happen OUTSIDE it (their locks nest the other
# way on other threads).
_LOCK = threading.RLock()


class _Entry:
    __slots__ = ("ref", "nbytes", "pd_nbytes", "shape", "dtype", "site",
                 "t_birth")


_CENSUS: Dict[int, _Entry] = {}

# running totals (ints, no registry): the census works even when the
# metrics registry is off; gauges mirror these only under _state.METRICS
LIVE_BYTES = 0
PEAK_BYTES = 0
# PER-DEVICE watermark: a buffer sharded over an N-device mesh costs
# each device only its shard — THE number that sizes dp×mp against the
# HBM budget (spmd.suggest_mesh_degree). Equals the global totals for
# unsharded runs.
LIVE_PD_BYTES = 0
PEAK_PD_BYTES = 0
DONATED_BYTES = 0
ANALYSIS_CALLS = 0
OOM_POSTMORTEMS = 0

# newest static per-device peak prediction (analysis/mem_liveness):
# {pd_bytes, desc, mesh} — the OOM postmortem prints it next to the
# measured watermark so the report says whether the OOM was statically
# foreseeable. Best-effort provenance: the last program analyzed.
STATIC_PREDICTION: Optional[Dict] = None

# per-executable memory analysis log: (cache stat, cache key) -> info.
# Bounded like the executable caches it shadows.
_EXECS: "OrderedDict[Tuple, Dict]" = OrderedDict()
_EXEC_CAP = 512

_OOM_SEQ = 0


class _SiteTLS(threading.local):
    site = None


_SITE = _SiteTLS()


def set_site(site: str):
    """Birth-site hint for buffers registered on this thread until
    clear_site() — the eager dispatch wrap point tags its outputs with
    the op name this way (Tensor.__init__ reads it)."""
    _SITE.site = site


def clear_site():
    _SITE.site = None


# ------------------------------------------------------------------ census

def note_buffer(val, site: Optional[str] = None):
    """Register one device-backed payload. Callers gate on
    ``_state.MEM``; anything that is not a concrete jax array (tracers,
    lazy refs, pending values) is ignored. Holding only a weakref, the
    census can never extend a buffer's lifetime."""
    k = id(val)
    hit = _CENSUS.get(k)        # GIL-atomic read: the common re-wrap
    if hit is not None and hit.ref() is val:
        return                  # already tracked (first birth site wins
        #                         — the shared scalar-coercion cache
        #                         re-wraps the same array every op, so
        #                         this path must stay O(dict get))
    import jax
    if not isinstance(val, jax.Array) or isinstance(val, jax.core.Tracer):
        return
    try:
        nb = int(val.nbytes)
    except Exception:
        return
    # per-device cost: a NamedSharding-committed buffer occupies only
    # its shard on each device (one isinstance check on the unsharded
    # path; shard_shape is metadata-only)
    pd = nb
    try:
        sh = val.sharding
        from jax.sharding import NamedSharding as _NS
        if isinstance(sh, _NS) and nb:
            shard = sh.shard_shape(tuple(val.shape))
            n = 1
            for s in shard:
                n *= int(s)
            tot = 1
            for s in val.shape:
                tot *= int(s)
            pd = int(nb * n / tot) if tot else nb
    except Exception:
        pd = nb
    if site is None:
        site = _SITE.site or "tensor.create"
    global LIVE_BYTES, PEAK_BYTES, LIVE_PD_BYTES, PEAK_PD_BYTES
    with _LOCK:
        ex = _CENSUS.get(k)
        if ex is not None:
            if ex.ref() is not None:
                return
            # id reuse beat the dead entry's callback: replace it
            LIVE_BYTES -= ex.nbytes
            LIVE_PD_BYTES -= ex.pd_nbytes
            del _CENSUS[k]
        e = _Entry()
        e.ref = weakref.ref(val, lambda _r, _k=k: _drop(_k))
        e.nbytes = nb
        e.pd_nbytes = pd
        e.shape = tuple(val.shape)
        e.dtype = str(val.dtype)
        e.site = site
        e.t_birth = time.perf_counter()
        _CENSUS[k] = e
        LIVE_BYTES += nb
        LIVE_PD_BYTES += pd
        if LIVE_BYTES > PEAK_BYTES:
            PEAK_BYTES = LIVE_BYTES
        if LIVE_PD_BYTES > PEAK_PD_BYTES:
            PEAK_PD_BYTES = LIVE_PD_BYTES
        live, peak = LIVE_BYTES, PEAK_BYTES
    _publish(live, peak)


def _drop(k: int):
    """Weakref callback: the payload died (freed, or deleted by
    donation and then released) — remove it from the census."""
    global LIVE_BYTES, LIVE_PD_BYTES
    with _LOCK:
        e = _CENSUS.get(k)
        if e is None or e.ref() is not None:
            return              # already replaced by an id-reuse insert
        del _CENSUS[k]
        LIVE_BYTES -= e.nbytes
        LIVE_PD_BYTES -= e.pd_nbytes
        live, peak = LIVE_BYTES, PEAK_BYTES
    _publish(live, peak)


def _publish(live: int, peak: int):
    """Mirror the census totals into the consumers that are on. Called
    OUTSIDE the census lock (see _LOCK note)."""
    if _state.METRICS:
        from . import metrics
        metrics.gauge("memory.live_bytes").set(live)
        metrics.gauge("memory.peak_bytes").set(peak)
    if _state.TRACE:
        from ..profiler import _add_counter_event
        _add_counter_event("memory.live_bytes", live)


def note_segment_outputs(pending, live, out_vals, sig=None, mesh=None):
    """Census registration for a flushed/replayed segment's live
    outputs: birth site = segment signature tag + producing op, plus
    the ambient mesh descriptor when the step ran sharded
    (``seg@<sig>:<op>#i@dp2xmp4``) — an OOM postmortem on a sharded
    run then names which mesh configuration filled the device."""
    try:
        tag = (hash(sig) & 0xFFFF) if sig is not None else 0
    except TypeError:
        tag = 0
    suffix = f"@{mesh}" if mesh else ""
    for (j, _s), val in zip(live, out_vals):
        note_buffer(val, f"seg@{tag:04x}:{pending[j].op.name}#{j}{suffix}")


def note_donated(nbytes: int):
    """Account bytes handed to XLA via buffer donation this step (lazy
    flush donation mask, optimizer donate_argnums)."""
    global DONATED_BYTES
    n = int(nbytes)
    with _LOCK:
        DONATED_BYTES += n
    if _state.METRICS:
        from . import metrics
        metrics.inc("memory.donated_bytes", n)


def note_static_prediction(pd_bytes: int, desc: str,
                           mesh: Optional[str] = None):
    """Record the newest static per-device peak prediction (the
    mem-liveness pass calls this whenever it analyzes a program as it
    will actually run — not for candidate-shape sweeps). Read back by
    the OOM postmortem."""
    global STATIC_PREDICTION
    STATIC_PREDICTION = {"pd_bytes": int(pd_bytes), "desc": str(desc),
                         "mesh": mesh}


def device_bytes() -> Dict[str, int]:
    """Live census bytes per device id — STRING-keyed (device ids are
    ints; an int-keyed map silently becomes string-keyed after one
    json round trip, the PR-8 step-table bug class, so the map is born
    string-keyed). Sharded buffers charge each device its own shard;
    resolution failures fall back to device '0'."""
    out: Dict[str, int] = {}
    with _LOCK:
        vals = [e.ref() for e in _CENSUS.values()]
    for val in vals:
        if val is None:
            continue
        try:
            for sh in val.addressable_shards:
                k = str(sh.device.id)
                out[k] = out.get(k, 0) + int(sh.data.nbytes)
        except Exception:
            try:
                out["0"] = out.get("0", 0) + int(val.nbytes)
            except Exception:
                pass
    return out


def live_bytes() -> int:
    return LIVE_BYTES


def peak_bytes() -> int:
    return PEAK_BYTES


def per_device_bytes() -> int:
    return LIVE_PD_BYTES


def peak_per_device_bytes() -> int:
    return PEAK_PD_BYTES


def donated_bytes() -> int:
    return DONATED_BYTES


def census_size() -> int:
    return len(_CENSUS)


def reset_peak():
    """Re-anchor the watermarks at the current live totals (budget /
    bench measurement windows)."""
    global PEAK_BYTES, PEAK_PD_BYTES
    with _LOCK:
        PEAK_BYTES = LIVE_BYTES
        PEAK_PD_BYTES = LIVE_PD_BYTES


def census(top: Optional[int] = None) -> List[Dict]:
    """Live buffers, largest first: [{nbytes, shape, dtype, site,
    age_s}]. Pure metadata — no payload references escape."""
    now = time.perf_counter()
    with _LOCK:
        rows = [{"nbytes": e.nbytes, "shape": list(e.shape),
                 "dtype": e.dtype, "site": e.site,
                 "age_s": round(now - e.t_birth, 3)}
                for e in _CENSUS.values()]
    rows.sort(key=lambda r: -r["nbytes"])
    return rows[:top] if top else rows


def reset():
    """Drop the census and zero every total (tests / fresh measurement
    baselines). Dead entries' pending callbacks tolerate the clear."""
    global LIVE_BYTES, PEAK_BYTES, DONATED_BYTES, ANALYSIS_CALLS
    global OOM_POSTMORTEMS, LIVE_PD_BYTES, PEAK_PD_BYTES
    global STATIC_PREDICTION
    with _LOCK:
        _CENSUS.clear()
        _EXECS.clear()
        LIVE_BYTES = PEAK_BYTES = DONATED_BYTES = 0
        LIVE_PD_BYTES = PEAK_PD_BYTES = 0
        ANALYSIS_CALLS = OOM_POSTMORTEMS = 0
        STATIC_PREDICTION = None


# -------------------------------------------- per-executable memory analysis

def analyze(compiled) -> Dict:
    """``compiled.memory_analysis()`` as a plain dict (counted: tests
    assert exactly one call per compile). Backends without the stat
    (some PJRT plugins) degrade to an error note instead of raising."""
    global ANALYSIS_CALLS
    with _LOCK:
        ANALYSIS_CALLS += 1
    if _state.METRICS:
        from . import metrics
        metrics.inc("memory.analysis_calls")
    try:
        ma = compiled.memory_analysis()
        return {"temp_bytes": int(ma.temp_size_in_bytes),
                "argument_bytes": int(ma.argument_size_in_bytes),
                "output_bytes": int(ma.output_size_in_bytes),
                "alias_bytes": int(ma.alias_size_in_bytes),
                "generated_code_bytes":
                    int(ma.generated_code_size_in_bytes)}
    except Exception as e:                           # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


_EXEC_SEQ = 0


def exec_seq() -> int:
    """Monotonic cursor over note_executable calls: snapshot it before
    a measurement window to tell THIS run's compiles apart from every
    earlier workload's in the process-global log."""
    return _EXEC_SEQ


def note_executable(stat: str, key, info: Dict):
    """Record one compiled executable's analysis under its cache
    identity (bounded; budget/stats aggregate over this log)."""
    global _EXEC_SEQ
    try:
        k = (stat, key)
        hash(k)
    except TypeError:
        k = (stat, id(key))
    with _LOCK:
        _EXEC_SEQ += 1
        _EXECS[k] = dict(info, seq=_EXEC_SEQ)
        _EXECS.move_to_end(k)
        while len(_EXECS) > _EXEC_CAP:
            _EXECS.popitem(last=False)
    if _state.METRICS and "error" not in info:
        from . import metrics
        for field in ("temp_bytes", "argument_bytes", "output_bytes",
                      "generated_code_bytes"):
            v = info.get(field)
            if v:
                metrics.inc("compiles.bytes." + field[:-6], v)


def aot_compile(jitted, args, kwargs: Optional[Dict] = None,
                stat: str = "segment", cache=None, key=None,
                n_devices: int = 1):
    """Compile a jitted callable through the AOT path so the Compiled
    executable (donation baked in) doubles as the cached runner AND its
    analyses are captured exactly once per compile — a later cache hit
    runs the same executable with zero analysis work. One
    ``lower().compile()`` serves BOTH telemetry planes: the memory
    plane's ``memory_analysis()`` (``_state.MEM``) and the compute
    plane's ``cost_analysis()`` + HLO source provenance
    (``_state.COMPUTE``) — callers gate on either being on. Returns a
    runner callable with the same concrete-array arguments (the
    executable cache key already pins the input signature); tracer
    arguments on a later call fall back to the jit wrapper, because a
    Compiled object cannot inline into an enclosing jax trace — and
    the cached runner outlives the telemetry session."""
    import jax
    compiled = jitted.lower(*args, **(kwargs or {})).compile()
    info = None
    if _state.MEM:
        info = analyze(compiled)
        note_executable(stat, key, info)
        if cache is not None and key is not None \
                and hasattr(cache, "note_memory"):
            cache.note_memory(key, info)
    cinfo = None
    if _state.COMPUTE:
        from . import compute as _comptel
        cinfo = _comptel.analyze(compiled, n_devices)
        _comptel.note_executable(stat, key, cinfo)
        if cache is not None and key is not None \
                and hasattr(cache, "note_cost"):
            cache.note_cost(key, cinfo)
        _comptel.note_provenance(compiled)

    def runner(*vals, _compiled=compiled, _jitted=jitted,
               _kw=dict(kwargs or {}), _tracer=jax.core.Tracer):
        for v in vals:
            if isinstance(v, _tracer):
                # static kwargs are baked into the Compiled; the jit
                # fallback needs them passed explicitly
                return _jitted(*vals, **_kw)
        return _compiled(*vals)

    runner.memory_analysis_info = info
    runner.cost_analysis_info = cinfo
    # the raw Compiled rides along so the persistent executable cache
    # (_core/persist.py) can serialize it without re-lowering
    runner.aot_executable = compiled
    return runner


def executable_stats() -> List[Dict]:
    """[{cache, <analysis fields>}] for every recorded executable."""
    with _LOCK:
        return [{"cache": k[0], **info} for k, info in _EXECS.items()]


def summary() -> Dict:
    """The byte-domain snapshot stats()/frames surface."""
    execs = executable_stats()
    return {
        "live_bytes": LIVE_BYTES,
        "peak_bytes": PEAK_BYTES,
        "live_per_device_bytes": LIVE_PD_BYTES,
        "peak_per_device_bytes": PEAK_PD_BYTES,
        "donated_bytes": DONATED_BYTES,
        "census": census_size(),
        "analysis_calls": ANALYSIS_CALLS,
        "oom_postmortems": OOM_POSTMORTEMS,
        # STRING-keyed per-device byte map (json-round-trip safe — the
        # PR-8 step-table key-type bug class)
        "per_device": device_bytes(),
        "static_prediction": dict(STATIC_PREDICTION)
        if STATIC_PREDICTION else None,
        "top": census(8),
        "executables": execs[-8:],
    }


# ---------------------------------------------------------- OOM postmortem

def is_oom(err: BaseException) -> bool:
    """XLA RESOURCE_EXHAUSTED (real, or the synthetic ``exec::oom``
    fault kind — both carry the status name in their message)."""
    return "RESOURCE_EXHAUSTED" in str(err)


def on_oom(err: BaseException, where: str, mem_info: Optional[Dict] = None,
           top: int = 16):
    """Build the OOM postmortem and return the typed error to raise.
    Already-typed framework errors pass through untouched (no double
    wrapping when an async worker's converted error re-surfaces)."""
    from ..base.core import EnforceNotMet, ResourceExhaustedError
    if isinstance(err, EnforceNotMet):
        return err
    global OOM_POSTMORTEMS
    with _LOCK:
        OOM_POSTMORTEMS += 1
    top_rows = census(top) if _state.MEM else []
    path = None
    try:
        path = _write_postmortem(where, err, top_rows, mem_info)
    except Exception:                                # pragma: no cover
        path = None
    if _state.METRICS:
        from . import metrics
        metrics.inc("memory.oom_postmortems")
    if _state.FLIGHT:
        from . import flight
        flight.note("oom", where, live_bytes=LIVE_BYTES,
                    peak_bytes=PEAK_BYTES)
    if top_rows:
        r = top_rows[0]
        head = (f"largest live buffer {r['nbytes']} B "
                f"{r['dtype']}{r['shape']} born at {r['site']}")
    else:
        head = ("census empty — was FLAGS_memory_telemetry on while "
                "the workload ran?")
    hint = (f"memory postmortem written to {path}" if path
            else "set FLAGS_memory_telemetry=true for a live-buffer "
                 "census in this report")
    e = ResourceExhaustedError(
        f"XLA out of memory (RESOURCE_EXHAUSTED) at {where}: "
        f"live {LIVE_BYTES} B, peak {PEAK_BYTES} B, {head}",
        context=hint)
    e.postmortem_path = path
    e.__cause__ = err
    return e


def _write_postmortem(where: str, err: BaseException, top_rows: List[Dict],
                      mem_info: Optional[Dict]) -> str:
    """One readable report: watermark, the failing executable's memory
    analysis, the top live buffers with provenance, and the flight ring
    when it is armed. Filed next to (and pruned with) the flight
    dumps."""
    from . import flight
    global _OOM_SEQ
    lines = [f"== paddle_tpu OOM postmortem ({where}) ==",
             f"error: {repr(err)[:500]}",
             f"watermark: live={LIVE_BYTES} B  peak={PEAK_BYTES} B  "
             f"donated_total={DONATED_BYTES} B  "
             f"census={census_size()} buffer(s)"]
    sp = STATIC_PREDICTION
    if sp:
        # was this OOM statically foreseeable? Compare the mem-lint
        # prediction for the program against the measured per-device
        # PEAK watermark — the high-water mark the device actually
        # reached, not whatever happens to be live at failure time
        verdict = ("FORESEEABLE — the static plan predicted at least "
                   "the measured watermark; `python -m "
                   "paddle_tpu.analysis --mem` would have flagged "
                   "oom_risk before the first run"
                   if sp["pd_bytes"] >= PEAK_PD_BYTES else
                   "under-predicted — the measured watermark exceeds "
                   "the static plan (untracked allocations or a "
                   "workload the recorded program does not cover)")
        lines.append(
            f"static predicted peak: {sp['pd_bytes']} B/device "
            f"({sp['desc']}, mesh {sp['mesh'] or 'dp1'}) vs measured "
            f"peak {PEAK_PD_BYTES} B/device: {verdict}")
    else:
        lines.append("static predicted peak: none recorded (run the "
                     "mem lint — analysis.check_memory / `--mem` — "
                     "over the step to know OOM risk before running)")
    if mem_info:
        pretty = " ".join(f"{k}={v}" for k, v in mem_info.items())
        lines.append(f"failing executable memory analysis: {pretty}")
    else:
        lines.append("failing executable memory analysis: unavailable "
                     "(compile predates FLAGS_memory_telemetry, or the "
                     "compile itself failed)")
    lines.append(f"top {len(top_rows)} live buffer(s) by size:")
    for i, r in enumerate(top_rows, 1):
        lines.append(f"  {i:>3}. {r['nbytes']:>12} B  "
                     f"{r['dtype']}{r['shape']}  {r['site']}  "
                     f"age={r['age_s']}s")
    if not top_rows:
        lines.append("  (none recorded)")
    lines.append("")
    lines.append(flight.record() if _state.FLIGHT
                 else "(flight recorder off — no event ring)")
    d = flight._dump_dir()
    os.makedirs(d, exist_ok=True)
    with _LOCK:
        _OOM_SEQ += 1
        seq = _OOM_SEQ
    rank = flight._rank()
    tag = f"r{rank}_" if rank is not None else ""
    path = os.path.join(d, f"flight_oom_{tag}{os.getpid()}_{seq}.txt")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    flight._prune_dumps(d, rank)
    return path
