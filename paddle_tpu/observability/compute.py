"""Compute-efficiency telemetry plane: FLOPs accounting, MFU, roofline.

The observability stack meters time (spans/budget), bytes (memory.py)
and comm (the overlap report); this module meters the FLOP domain — the
question every MLPerf-on-pods scaling argument (1909.09756, 2011.03641)
starts from: *what fraction of the hardware's peak are we achieving,
and which ops burn the FLOPs?* Four pillars:

- **per-executable cost analysis**: the three fused-runtime compile
  sites (plain segment flush sync+async, fused fwd+vjp step, fused
  optimizer update) route through the jax AOT path while the plane is
  on, so ``compiled.cost_analysis()`` (flops, bytes accessed,
  transcendentals) is captured exactly ONCE per compile and cached on
  the ExecCache entry (``note_cost``/``cost_info``, pruned with the
  entry). Under an ambient SPMD mesh the analysis covers the
  partitioned (per-device) module, so the number is per-chip by
  construction — asserted in tests against a dp-mesh dryrun.
- **per-execution FLOP counters**: every execution of a cost-analyzed
  runner adds its cached FLOPs to ``compute.flops.{segment,fused_step,
  optimizer}`` (and ``compute.bytes_accessed``) — the meters the
  budget tool's MFU/roofline columns and ``--static-diff`` divide.
- **MFU / roofline**: ``peak_flops()`` resolves
  ``FLAGS_device_peak_flops`` (0 = per-backend autodetect with a
  documented CPU fallback); achieved FLOP/s over a measured window
  divided by it is the model-FLOPs-utilization column, and
  flops / bytes-accessed vs the ridge point (peak_flops / peak_membw)
  says compute-bound vs memory-bound.
- **source-attributed device profiles**: segment compile wraps each
  recorded op's lowering in ``jax.named_scope("<op>[<file>:<line>]")``
  from the already-captured ``_PendingOp.src``; ``note_provenance``
  parses the compiled HLO once per compile into an
  instruction-name -> ``op@file:line`` map, so xplane device traces
  and the profiler statistic table group device time by paddle source
  line (``Profiler.source_summary``).

Off-cost follows the house pattern: ``FLAGS_compute_telemetry`` is
watcher-cached into ``_state.COMPUTE`` (folded into ``_state.ACTIVE``);
off = one module-attribute read per site, zero registry and zero
analysis work (bench_suite row 14 asserts both exactly).
"""
from __future__ import annotations

import os
import re
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from . import _state

_LOCK = threading.Lock()

# cost_analysis() invocations — tests assert exactly one per compile
COST_CALLS = 0

# running totals (ints, registry-independent like memory.py's
# LIVE_BYTES): per-device FLOPs / bytes-accessed priced per execution
FLOPS_EXECUTED = 0
BYTES_ACCESSED = 0
_SITE_FLOPS: Dict[str, int] = {}

# per-executable cost log: (cache stat, key) -> info. Bounded like the
# executable caches it shadows.
_EXECS: "OrderedDict" = OrderedDict()
_EXEC_CAP = 512
_EXEC_SEQ = 0

# HLO-instruction -> "op@file:line" provenance parsed from compiled
# executables (note_provenance); the profiler's source_summary consumes
# it. Bounded drop-oldest.
_HLO_SRC: "OrderedDict[str, str]" = OrderedDict()
_HLO_SRC_CAP = 16384

# achieved-GFLOP/s counter-track state: (perf_counter at last emit,
# flops accumulated since) — emitted into the chrome trace while a
# profiler records
_RATE_T0 = None
_RATE_FLOPS = 0


# ------------------------------------------------------------ analysis

def _cost_dict(compiled) -> Dict:
    """``compiled.cost_analysis()`` normalized across jax versions
    (list-of-dicts on 0.4.x, plain dict on newer)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def analyze(compiled, n_devices: int = 1) -> Dict:
    """Capture one compiled executable's cost analysis as a plain dict
    (counted: tests assert exactly one call per compile). The flops /
    bytes numbers describe the PARTITIONED module when the program was
    compiled against a mesh — i.e. per-chip; ``n_devices`` records the
    pricing basis. Backends without the stat degrade to an error note
    instead of raising."""
    global COST_CALLS
    with _LOCK:
        COST_CALLS += 1
    if _state.METRICS:
        from . import metrics
        metrics.inc("compute.cost_analysis_calls")
    try:
        ca = _cost_dict(compiled)
        flops = ca.get("flops")
        nbytes = ca.get("bytes accessed")
        trans = ca.get("transcendentals")
        return {
            "flops": int(flops) if flops and flops > 0 else 0,
            "bytes_accessed": int(nbytes) if nbytes and nbytes > 0 else 0,
            "transcendentals": int(trans) if trans and trans > 0 else 0,
            "n_devices": int(n_devices),
        }
    except Exception as e:                            # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}",
                "n_devices": int(n_devices)}


def exec_seq() -> int:
    """Monotonic cursor over note_executable calls (the memory-plane
    pattern): snapshot before a measurement window to tell THIS run's
    compiles apart from earlier workloads'."""
    return _EXEC_SEQ


def note_executable(stat: str, key, info: Dict):
    """Record one compiled executable's cost analysis under its cache
    identity (bounded; budget aggregates over this log)."""
    global _EXEC_SEQ
    try:
        k = (stat, key)
        hash(k)
    except TypeError:
        k = (stat, id(key))
    with _LOCK:
        _EXEC_SEQ += 1
        _EXECS[k] = dict(info, seq=_EXEC_SEQ)
        _EXECS.move_to_end(k)
        while len(_EXECS) > _EXEC_CAP:
            _EXECS.popitem(last=False)


def note_execution(info: Optional[Dict], site: str):
    """One execution of a cost-analyzed runner: add its cached FLOPs /
    bytes to the module totals (and the ``compute.flops.<site>``
    counters when metrics are on). Callers gate on ``_state.COMPUTE``;
    a None/errored info (compiled before the plane was on, or the
    backend has no cost stat) is a no-op."""
    if not info or "error" in info:
        return
    flops = info.get("flops", 0)
    nbytes = info.get("bytes_accessed", 0)
    global FLOPS_EXECUTED, BYTES_ACCESSED
    with _LOCK:
        FLOPS_EXECUTED += flops
        BYTES_ACCESSED += nbytes
        _SITE_FLOPS[site] = _SITE_FLOPS.get(site, 0) + flops
    if _state.METRICS:
        from . import metrics
        if flops:
            metrics.inc("compute.flops." + site, flops)
        if nbytes:
            metrics.inc("compute.bytes_accessed", nbytes)
    if _state.TRACE and flops:
        _emit_rate(flops)


def count_cached(cache, key, site: str):
    """Per-execution counting for the ExecCache-backed sites: read the
    cost info the compile attached to this entry and price one
    execution. One dict get when the entry carries no analysis."""
    note_execution(cache.cost_info(key), site)


def _emit_rate(flops: int):
    """Achieved-GFLOP/s counter track while a profiler records: rate
    over the window since the last emission (>=1ms so a burst of tiny
    executions doesn't explode the trace)."""
    global _RATE_T0, _RATE_FLOPS
    now = time.perf_counter()
    with _LOCK:
        if _RATE_T0 is None:
            _RATE_T0, _RATE_FLOPS = now, flops
            return
        _RATE_FLOPS += flops
        dt = now - _RATE_T0
        if dt < 1e-3:
            return
        gflops = _RATE_FLOPS / dt / 1e9
        _RATE_T0, _RATE_FLOPS = now, 0
    from ..profiler import _add_counter_event
    _add_counter_event("compute.achieved_gflops", gflops, key="gflops")


def executed_flops() -> int:
    return FLOPS_EXECUTED


def executed_bytes() -> int:
    return BYTES_ACCESSED


def site_flops() -> Dict[str, int]:
    with _LOCK:
        return dict(_SITE_FLOPS)


def executable_stats() -> List[Dict]:
    with _LOCK:
        return [{"cache": k[0], **info} for k, info in _EXECS.items()]


def reset():
    """Zero every total and drop the logs (tests / fresh baselines)."""
    global COST_CALLS, FLOPS_EXECUTED, BYTES_ACCESSED
    global _RATE_T0, _RATE_FLOPS
    with _LOCK:
        COST_CALLS = 0
        FLOPS_EXECUTED = BYTES_ACCESSED = 0
        _SITE_FLOPS.clear()
        _EXECS.clear()
        _HLO_SRC.clear()
        _RATE_T0, _RATE_FLOPS = None, 0


# --------------------------------------------------------- peak / roofline

# published per-chip peak FLOP/s (bf16/matmul units — the MLPerf MFU
# convention) by TPU device_kind substring, newest-first so "v5p"
# matches before "v5"
_TPU_PEAK_FLOPS = (
    ("v6e", 918e12), ("v6", 918e12),
    ("v5p", 459e12), ("v5e", 197e12), ("v5", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
)
_TPU_PEAK_MEMBW = (
    ("v6e", 1640e9), ("v6", 1640e9),
    ("v5p", 2765e9), ("v5e", 819e9), ("v5", 819e9),
    ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
)

# documented CPU fallbacks (README "Compute efficiency & MFU"): a
# nominal AVX2-FMA envelope per core and two-channel DDR4 bandwidth.
# CPU MFU is a RELATIVE meter (regressions across rounds on one box),
# not an absolute one.
_CPU_GHZ = 2.5e9
_CPU_FLOPS_PER_CYCLE = 16          # 8 fp32 lanes x FMA
_CPU_MEMBW = 25.6e9


def _kind_lookup(table, kind: str, fallback: float) -> float:
    kind = (kind or "").lower()
    for sub, peak in table:
        if sub in kind:
            return peak
    return fallback


def peak_flops() -> float:
    """Per-chip peak FLOP/s: FLAGS_device_peak_flops, or the backend
    autodetect when the flag is 0."""
    from .._core.flags import flag_value
    v = float(flag_value("FLAGS_device_peak_flops"))
    if v > 0:
        return v
    import jax
    backend = jax.default_backend()
    cpu_peak = (os.cpu_count() or 1) * _CPU_GHZ * _CPU_FLOPS_PER_CYCLE
    if backend != "tpu":
        return cpu_peak
    kind = getattr(jax.devices()[0], "device_kind", "")
    return _kind_lookup(_TPU_PEAK_FLOPS, kind, cpu_peak)


def peak_membw() -> float:
    """Per-chip peak memory bandwidth (bytes/s) for the roofline
    ridge: FLAGS_device_peak_membw, or the backend autodetect."""
    from .._core.flags import flag_value
    v = float(flag_value("FLAGS_device_peak_membw"))
    if v > 0:
        return v
    import jax
    if jax.default_backend() != "tpu":
        return _CPU_MEMBW
    kind = getattr(jax.devices()[0], "device_kind", "")
    return _kind_lookup(_TPU_PEAK_MEMBW, kind, _CPU_MEMBW)


def mfu(achieved_flops_per_s: float,
        peak: Optional[float] = None) -> float:
    """Model-FLOPs-utilization: achieved / per-chip peak."""
    peak = peak_flops() if peak is None else float(peak)
    if peak <= 0:
        return 0.0
    return achieved_flops_per_s / peak


def roofline(flops: int, bytes_accessed: int,
             peak: Optional[float] = None,
             membw: Optional[float] = None) -> Dict:
    """Arithmetic intensity (FLOP per byte accessed) against the ridge
    point peak_flops/peak_membw: above the ridge the kernel mix is
    compute-bound, below it memory-bound."""
    peak = peak_flops() if peak is None else float(peak)
    membw = peak_membw() if membw is None else float(membw)
    intensity = flops / bytes_accessed if bytes_accessed else 0.0
    ridge = peak / membw if membw else 0.0
    bound = None
    if flops:
        bound = "compute-bound" if intensity >= ridge else "memory-bound"
    return {"arith_intensity": round(intensity, 3),
            "ridge_intensity": round(ridge, 3),
            "bound": bound}


def summary() -> Dict:
    """The FLOP-domain snapshot stats()/frames surface."""
    return {
        "cost_analysis_calls": COST_CALLS,
        "flops_executed": FLOPS_EXECUTED,
        "bytes_accessed": BYTES_ACCESSED,
        "site_flops": site_flops(),
        "peak_flops": peak_flops(),
        "executables": executable_stats()[-8:],
        "provenance_entries": len(_HLO_SRC),
    }


# ------------------------------------------------- source attribution

# one HLO-text line: "%instr = ... metadata={op_name="..." ...}"
_HLO_LINE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=.*op_name=\"([^\"]*)\"")
# the scope fragment the segment builder emits: <op>[<file>:<line>];
# the LAST match in an op_name path is the innermost (most specific)
_SCOPE_RE = re.compile(r"([\w.\-]+)\[([^\[\]]+:\d+)\]")


def scope_name(op_name: str, src: str) -> str:
    """The named_scope string for one recorded op: ``<op>[<file>:
    <line>]`` — jax drops scope names containing '@', so brackets
    carry the provenance through HLO op_name metadata and
    ``source_of`` renders it back as ``op@file:line``."""
    return f"{op_name}[{src}]"


def note_provenance(compiled):
    """Parse one compiled executable's HLO text into instruction-name
    -> ``op@file:line`` entries (once per compile, only while the
    plane is on). Device trace events are named after HLO instructions
    ("fusion.3", "dot.2"), so this map is what lets the profiler group
    device time by paddle source line."""
    try:
        txt = compiled.as_text()
    except Exception:                                 # pragma: no cover
        return
    found = {}
    for line in txt.splitlines():
        m = _HLO_LINE_RE.match(line)
        if m is None:
            continue
        scopes = _SCOPE_RE.findall(m.group(2))
        if not scopes:
            continue
        op, src = scopes[-1]
        found[m.group(1)] = f"{op}@{src}"
    if not found:
        return
    with _LOCK:
        _HLO_SRC.update(found)
        while len(_HLO_SRC) > _HLO_SRC_CAP:
            _HLO_SRC.popitem(last=False)


def source_of(event_name: str) -> Optional[str]:
    """``op@file:line`` provenance for one device-trace event name, or
    None. Thunk-level suffixes (".clone") and kernel-wrapper prefixes
    are normalized away before the lookup."""
    hit = _HLO_SRC.get(event_name)
    if hit is not None:
        return hit
    base = event_name.split(" ")[0]
    for suffix in (".clone",):
        if base.endswith(suffix):
            base = base[: -len(suffix)]
    return _HLO_SRC.get(base)


def provenance_size() -> int:
    return len(_HLO_SRC)
