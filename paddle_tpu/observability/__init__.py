"""paddle_tpu.observability — fused-runtime telemetry.

Three consumers over one set of instrumentation points (segment
record→flush, compile vs. cached execute, executable-cache hit/miss,
donation decisions, fused-backward step cache, per-op replay, SOT guard
evaluation, distributed collectives, optimizer updates):

- **metrics registry** (`FLAGS_observability` / `enable()`): process-
  wide counters/gauges/histograms, snapshot via `stats()`;
- **structured spans**: while a `paddle_tpu.profiler.Profiler` is
  recording, the same points emit timed span events into the chrome
  trace (`segment::flush[reason]` with compile/execute children);
- **flight recorder** (`FLAGS_flight_recorder`): bounded ring of recent
  events, auto-dumped to a report on enforce errors, failed flushes,
  and sanitizer error-mode trips (rank-aware retention via
  FLAGS_flight_max_dumps).

Plus the byte-domain plane (`FLAGS_memory_telemetry`, memory.py):
live-buffer census with birth-site provenance, per-executable XLA
memory analysis cached at compile time, donation savings accounting,
and OOM postmortems with a typed re-raise — `stats()` gains a
``memory`` section while it is on.

Cost when everything is off: one module-level boolean check per
instrumentation point (`observability._state.ACTIVE`), zero registry
work — asserted by bench_suite row 6.

    python -m paddle_tpu.observability        # demo workload + stats
"""
from __future__ import annotations

from .._core import flags as _flags
from . import _state, flight, metrics, spans
from .metrics import counter, gauge, histogram
from .spans import span

__all__ = ["stats", "reset", "enable", "disable", "enabled",
           "counter", "gauge", "histogram", "span",
           "flight_record", "dump_flight_record"]

# keep the module-level fast gates coherent with the flags (env spelling
# FLAGS_observability=1 works from first import; set_flags mid-session
# flips the gate immediately)
_flags.watch_flag("FLAGS_observability", _state.set_metrics)
_flags.watch_flag("FLAGS_flight_recorder", _state.set_flight)
_flags.watch_flag("FLAGS_distributed_telemetry", _state.set_dist)
_flags.watch_flag("FLAGS_memory_telemetry", _state.set_mem)


def _on_compute_flag(on):
    was = _state.COMPUTE
    _state.set_compute(on)
    if on and not was:
        # ENTERING the compute plane re-keys the compiled-program
        # caches (mesh-epoch salt): the next execution of each
        # workload compiles exactly ONE fresh executable whose
        # cost_analysis() and named-scope provenance are captured —
        # a warm pre-plane cache would otherwise report zero FLOPs
        # forever (analyses are captured at compile time only). Only
        # when some runner was actually cached without cost capture
        # (COST_STALE): a monitoring loop flipping the plane around
        # each budget sample must not recompile the world per sample
        # once the warm entries already carry their analyses.
        from .._core import lazy
        if lazy.COST_STALE:
            lazy.bump_mesh_epoch()
            lazy.COST_STALE = False


_flags.watch_flag("FLAGS_compute_telemetry", _on_compute_flag)


def _on_goodput_flag(on):
    import sys as _sys
    _state.set_goodput(bool(on))
    # the goodput module is only imported once the plane is first
    # turned ON (the resilience-package laziness discipline); after
    # that, flips keep its ledger/watchdog coherent
    mod = _sys.modules.get(__name__ + ".goodput")
    if on:
        from . import goodput as mod
    if mod is not None:
        mod._sync(bool(on))


_flags.watch_flag("FLAGS_goodput", _on_goodput_flag)


def _on_monitor_flag(on):
    import sys as _sys
    _state.set_monitor(bool(on))
    # same laziness discipline as the goodput plane: the timeseries
    # module (sampler thread + HTTP exporter) is only imported once the
    # monitor is first turned ON; later flips start/stop in place
    mod = _sys.modules.get(__name__ + ".timeseries")
    if on:
        from . import timeseries as mod
    if mod is not None:
        mod._sync(bool(on))


_flags.watch_flag("FLAGS_monitor", _on_monitor_flag)


def enable(flight_recorder: bool = None):
    """Turn on metrics collection (and optionally the flight recorder)."""
    f = {"FLAGS_observability": True}
    if flight_recorder is not None:
        f["FLAGS_flight_recorder"] = bool(flight_recorder)
    _flags.set_flags(f)


def disable():
    _flags.set_flags({"FLAGS_observability": False})


def enabled() -> bool:
    return _state.METRICS


def reset():
    """Zero every metric and drop the flight ring (counter snapshots
    restart from a clean baseline)."""
    metrics.reset()
    flight.reset()


def _derived(counters: dict) -> dict:
    hits = misses = 0
    for k, v in counters.items():
        if k.startswith("cache."):
            if k.endswith(".hit"):
                hits += v
            elif k.endswith(".miss"):
                misses += v
    step_hit = counters.get("cache.fused_step.hit", 0)
    step_miss = counters.get("cache.fused_step.miss", 0)
    return {
        "compiles": sum(v for k, v in counters.items()
                        if k.startswith("compiles.")),
        "cache_hit_rate": (hits / (hits + misses)
                           if hits + misses else None),
        "step_cache_hit_rate": (step_hit / (step_hit + step_miss)
                                if step_hit + step_miss else None),
        # every fusion-window break costs the step cache + optimizer
        # donation — the BUDGET_r06 eager-GPT finding, now a headline
        # number instead of raw span archaeology
        "fusion_window_breaks": counters.get("fusion.window_breaks", 0),
    }


def stats(reset_after: bool = False) -> dict:
    """Snapshot of the registry plus derived headline numbers:

    - ``compiles``: framework-issued XLA compilations (sum of the
      ``compiles.*`` counters) — steady state adds zero;
    - ``cache_hit_rate``: hit fraction across every executable cache;
    - ``step_cache_hit_rate``: the fused fwd+vjp "step cache" alone —
      THE steady-state train-step health signal.
    """
    snap = metrics.snapshot()
    snap.update(_derived(snap["counters"]))
    if _state.MEM:
        # byte-domain headline (census watermark + cached per-
        # executable memory analysis) rides along whenever the memory
        # telemetry plane is on
        from . import memory as _memory
        snap["memory"] = _memory.summary()
    if _state.COMPUTE:
        # FLOP-domain headline (cost-analysis log + executed-FLOPs
        # totals + the per-chip peak the MFU column divides by)
        from . import compute as _compute
        snap["compute"] = _compute.summary()
    if _state.GOODPUT:
        # job-level wall attribution: the exclusive bucket partition,
        # goodput fraction and top badput source from the ledger
        from . import goodput as _goodtel
        snap["goodput"] = _goodtel.summary()
    if reset_after:
        reset()
    return snap


def flight_record() -> str:
    """The flight-recorder ring formatted as a report."""
    return flight.record()


def dump_flight_record(path: str = None) -> str:
    """Write the flight record to a file; returns the path."""
    return flight.dump(reason="manual dump", path=path)
