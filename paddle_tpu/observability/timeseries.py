"""Live monitoring plane: bounded time-series rings fed by a daemon
sampler, plus an online regression watchdog.

While `FLAGS_monitor` is on, a daemon thread wakes every
`FLAGS_monitor_interval_s` and appends one timestamped sample per
series into a bounded ring (capacity `FLAGS_monitor_ring`):

- **rates** from monotonic counters: `steps_per_s`, `tokens_per_s`,
  `compiles_per_s`, `cache_hit_rate`, `fusion_breaks_per_s`,
  `comm_bytes_per_s` (registry counters only move while
  FLAGS_observability is on; the step/token feed comes from the
  ElasticStep hook and is monitor-local, so the headline throughput
  series work with the metrics plane off);
- **gauges** from the byte plane: `mem_live_bytes`, `mem_peak_bytes`,
  `mem_census`, per-device `mem_device_bytes.<dev>`;
- **goodput** bucket fractions over the sample window from the PR-14
  ledger (`goodput_frac`, `badput_frac.<bucket>`);
- **efficiency**: windowed `mfu` from the PR-12 compute plane, and
  `step_time_ms` (mean step duration inside the window).

The regression watchdog keeps an EWMA baseline per headline series
(`step_time_ms` up-bad, `tokens_per_s` / `goodput_frac` down-bad). A
deviation past `FLAGS_monitor_regression_factor`, sustained for
`FLAGS_monitor_regression_steps` consecutive samples, fires once:
`monitor.regressions` increments, a flight note carries the
baseline-vs-current evidence, and (when
`FLAGS_monitor_deep_capture_steps` > 0) a one-shot deep capture arms —
the next K steps run under a fused-runtime profiler whose chrome trace
is dumped beside the flight ring under the same rank-aware retention.
After firing, the baseline re-anchors at the deviant level so a
sustained shift is reported exactly once, not every sample.

Off = the usual discipline: ONE module-attribute read per step hook
(`_state.MONITOR`), no sampler thread, no bound port, zero registry
mutations — asserted by bench row 20.
"""
from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Dict, List, Optional

from . import _state

_LOG = logging.getLogger("paddle_tpu.observability")

_LOCK = threading.Lock()

# series name -> deque[(t_wall, value)]; every ring-held series is a
# gauge in exposition terms (rates are instantaneous values)
_SERIES: "collections.OrderedDict[str, collections.deque]" = \
    collections.OrderedDict()

# the headline throughput feed (monitor-local so it works with the
# metrics registry off): ElasticStep's hook bumps STEPS, trainers that
# know their batch geometry call note_tokens()
STEPS = 0
TOKENS = 0
_LAST_STEP_WALL: Optional[float] = None   # time.time() — /healthz age
_STEP_T0: Optional[float] = None          # perf_counter of last boundary
_WIN_DUR_S = 0.0                          # step-duration mass this window
_WIN_N = 0

_SAMPLER: Optional["_Sampler"] = None
_WATCHDOG: Optional["_Regression"] = None
REGRESSIONS: List[Dict] = []              # fired events (evidence copies)

# one-shot deep capture: armed by a fired regression, consumed by the
# step hook (profiler must bracket steps, not sampler ticks)
_DEEP = {"armed": 0, "left": 0, "prof": None, "path": None}


def _cap() -> int:
    from .._core.flags import flag_value
    return max(int(flag_value("FLAGS_monitor_ring")), 2)


def _append(name: str, t: float, v) -> None:
    if v is None:
        return
    with _LOCK:
        ring = _SERIES.get(name)
        if ring is None:
            ring = _SERIES[name] = collections.deque(maxlen=_cap())
        ring.append((t, float(v)))


def series_names() -> List[str]:
    with _LOCK:
        return list(_SERIES)


def series(name: str) -> List:
    """Ring dump: [[t_wall, value], ...] oldest first."""
    with _LOCK:
        ring = _SERIES.get(name)
        return [[t, v] for t, v in ring] if ring is not None else []


def latest() -> Dict[str, float]:
    """name -> newest sample value (the /metrics gauge surface)."""
    with _LOCK:
        return {k: ring[-1][1] for k, ring in _SERIES.items() if ring}


def last_step_age_s() -> Optional[float]:
    """Seconds since the last step boundary (None before the first) —
    the /healthz staleness column."""
    t = _LAST_STEP_WALL
    return None if t is None else max(time.time() - t, 0.0)


# ---------------------------------------------------------- step feed

def on_step(step_index: int) -> None:
    """Step-boundary hook (ElasticStep.run calls this behind the
    `_state.MONITOR` gate; AdaptiveTrainer rides through its inner
    ElasticStep). Cheap: two clocks + integer bumps under the lock."""
    global STEPS, _LAST_STEP_WALL, _STEP_T0, _WIN_DUR_S, _WIN_N
    now = time.perf_counter()
    with _LOCK:
        STEPS += 1
        _LAST_STEP_WALL = time.time()
        if _STEP_T0 is not None:
            _WIN_DUR_S += now - _STEP_T0
            _WIN_N += 1
        _STEP_T0 = now
    _deep_capture_tick()


def note_tokens(n: int) -> None:
    """Throughput feed: a trainer that knows its batch geometry calls
    this once per step with the tokens (or samples) consumed; the
    sampler turns the running total into the tokens_per_s series."""
    global TOKENS
    if not _state.MONITOR:
        return
    with _LOCK:
        TOKENS += int(n)


# --------------------------------------------------- regression watch

class _Regression:
    """EWMA baseline per headline series; a deviation past `factor`,
    sustained for `steps` consecutive samples, fires exactly once and
    re-anchors the baseline at the deviant level."""

    _ALPHA = 0.2
    # direction: True = a larger value is a regression
    _HEADLINES = {"step_time_ms": True,
                  "tokens_per_s": False,
                  "goodput_frac": False}

    def __init__(self, factor: float, steps: int):
        self.factor = max(float(factor), 1.0 + 1e-9)
        self.steps = max(int(steps), 1)
        self._state: Dict[str, Dict] = {}

    def judge(self, name: str, value: Optional[float], t: float):
        up_bad = self._HEADLINES.get(name)
        if up_bad is None or value is None or value <= 0.0:
            return
        st = self._state.setdefault(name, {"ewma": None, "consec": 0})
        base = st["ewma"]
        if base is None or base <= 0.0:
            st["ewma"] = float(value)
            return
        dev = (value / base) if up_bad else (base / value)
        if dev >= self.factor:
            st["consec"] += 1
            if st["consec"] >= self.steps:
                self._fire(name, base, value, t)
                # re-anchor: a sustained shift is ONE event, not one
                # per sample forever after
                st["ewma"] = float(value)
                st["consec"] = 0
            return
        st["consec"] = 0
        st["ewma"] = base + self._ALPHA * (value - base)

    def _fire(self, name: str, baseline: float, current: float,
              t: float):
        from . import flight, metrics
        ev = {"series": name, "baseline": round(baseline, 3),
              "current": round(current, 3),
              "factor": round(self.factor, 3),
              "sustained": self.steps, "t_wall": t}
        REGRESSIONS.append(ev)
        metrics.inc("monitor.regressions")
        # evidence rides the flight ring (no-op when FLAGS_flight_
        # recorder is off)
        flight.note("monitor", "regression", **ev)
        _LOG.warning(
            "monitor: %s regressed — baseline %.3f vs current %.3f "
            "(>= %.2fx for %d sample(s))", name, baseline, current,
            self.factor, self.steps)
        from .._core.flags import flag_value
        k = int(flag_value("FLAGS_monitor_deep_capture_steps"))
        if k > 0 and _DEEP["armed"] == 0 and _DEEP["prof"] is None:
            _DEEP["armed"] = k


# ---------------------------------------------------------- deep capture

def _deep_capture_tick():
    """Called from on_step: start the armed profiler at the next step
    boundary, stop after K steps and dump the trace beside the flight
    ring (same rank-aware retention as the text dumps)."""
    if _DEEP["armed"] <= 0 and _DEEP["prof"] is None:
        return
    try:
        if _DEEP["prof"] is None:
            from ..profiler import Profiler, ProfilerTarget
            prof = Profiler(targets=[ProfilerTarget.CPU],
                            fused_runtime=True)
            prof.start()
            _DEEP["prof"] = prof
            _DEEP["left"] = _DEEP["armed"]
            _DEEP["armed"] = 0
            return
        _DEEP["left"] -= 1
        if _DEEP["left"] > 0:
            return
        prof = _DEEP["prof"]
        _DEEP["prof"] = None
        prof.stop()
        from . import flight, metrics
        path = prof.export(flight.trace_path())
        flight.prune_dumps()
        _DEEP["path"] = path
        metrics.inc("monitor.deep_captures")
        _LOG.warning("monitor: deep-capture trace written to %s", path)
    except Exception:
        # capture is advisory; it must never take the train step down
        _DEEP["prof"] = None
        _DEEP["armed"] = 0


# -------------------------------------------------------------- sampler

class _Sampler(threading.Thread):
    """Daemon tick loop: one batch of ring appends per interval plus
    the watchdog pass. All registry reads are snapshots — the sampler
    never mutates counters other than monitor.* on a fired event."""

    def __init__(self, interval_s: float):
        super().__init__(name="pt-monitor-sampler", daemon=True)
        self.interval_s = max(float(interval_s), 0.01)
        self._stop_ev = threading.Event()
        self._prev: Optional[Dict] = None

    def stop(self, timeout: float = 2.0):
        self._stop_ev.set()
        self.join(timeout=timeout)

    def run(self):
        while not self._stop_ev.wait(self.interval_s):
            try:
                sample_once(self._prev_box())
            except Exception:
                _LOG.exception("monitor sampler tick failed")

    def _prev_box(self) -> Dict:
        if self._prev is None:
            self._prev = {}
        return self._prev


def _counter_sums(counters: Dict[str, int]) -> Dict[str, float]:
    out = {"compiles": 0.0, "comm_bytes": 0.0,
           "cache_hit": 0.0, "cache_miss": 0.0,
           "fusion_breaks": float(
               counters.get("fusion.window_breaks", 0))}
    for k, v in counters.items():
        if k.startswith("compiles."):
            out["compiles"] += v
        elif k.startswith("comm.bytes."):
            out["comm_bytes"] += v
        elif k.startswith("cache."):
            if k.endswith(".hit"):
                out["cache_hit"] += v
            elif k.endswith(".miss"):
                out["cache_miss"] += v
    return out


def sample_once(prev: Dict) -> None:
    """One sampler tick: compute window deltas against `prev` (mutated
    in place), append samples, run the watchdog. Exposed un-threaded so
    tests drive deterministic seeded windows."""
    global _WIN_DUR_S, _WIN_N
    from . import metrics
    now = time.time()
    t_prev = prev.get("t")
    dt = (now - t_prev) if t_prev else None
    prev["t"] = now

    with _LOCK:
        steps, tokens = STEPS, TOKENS
        # window step-duration accumulators are consumed per tick
        win_dur, win_n = _WIN_DUR_S, _WIN_N
        _WIN_DUR_S -= win_dur
        _WIN_N -= win_n

    snap = metrics.snapshot()
    sums = _counter_sums(snap["counters"])

    def rate(key: str, cur: float) -> Optional[float]:
        last = prev.get(key)
        prev[key] = cur
        if last is None or dt is None or dt <= 0.0:
            return None
        return max(cur - last, 0.0) / dt

    steps_rate = rate("steps", float(steps))
    tok_rate = rate("tokens", float(tokens))
    _append("steps_per_s", now, steps_rate)
    _append("tokens_per_s", now, tok_rate)
    _append("compiles_per_s", now, rate("compiles", sums["compiles"]))
    _append("comm_bytes_per_s", now,
            rate("comm_bytes", sums["comm_bytes"]))
    _append("fusion_breaks_per_s", now,
            rate("fusion_breaks", sums["fusion_breaks"]))
    dh = rate("cache_hit", sums["cache_hit"])
    dm = rate("cache_miss", sums["cache_miss"])
    if dh is not None and dm is not None and dh + dm > 0:
        _append("cache_hit_rate", now, dh / (dh + dm))

    step_time_ms = (win_dur / win_n * 1e3) if win_n else None
    _append("step_time_ms", now, step_time_ms)

    # byte plane gauges (zeros while FLAGS_memory_telemetry is off)
    from . import memory
    _append("mem_live_bytes", now, memory.live_bytes())
    _append("mem_peak_bytes", now, memory.peak_bytes())
    _append("mem_census", now, memory.census_size())
    for dev, b in memory.device_bytes().items():
        _append(f"mem_device_bytes.{dev}", now, b)

    # goodput bucket fractions over THIS window (ledger deltas)
    goodput_frac = None
    if _state.GOODPUT:
        from . import goodput
        gsnap = goodput.snapshot()
        gprev = prev.get("goodput")
        prev["goodput"] = gsnap
        if gprev is not None:
            d = goodput.delta(gprev, gsnap)
            total = sum(d["buckets"].values())
            if total > 0:
                goodput_frac = d["buckets"].get("execute", 0.0) / total
                _append("goodput_frac", now, goodput_frac)
                for b, v in d["buckets"].items():
                    if b != "execute" and v > 0:
                        _append(f"badput_frac.{b}", now, v / total)

    # windowed MFU from the compute plane's executed-FLOPs ledger
    if _state.COMPUTE:
        from . import compute
        df = rate("flops", float(compute.executed_flops()))
        peak = compute.peak_flops()
        if df is not None and peak > 0:
            _append("mfu", now, compute.mfu(df, peak))

    wd = _WATCHDOG
    if wd is not None:
        wd.judge("step_time_ms", step_time_ms, now)
        if steps_rate:
            # only judge throughput on windows where steps happened —
            # an idle gap (eval, checkpoint) is not a regression
            wd.judge("tokens_per_s", tok_rate, now)
        wd.judge("goodput_frac", goodput_frac, now)


# ------------------------------------------------------------- control

def sampler_alive() -> bool:
    s = _SAMPLER
    return s is not None and s.is_alive()


def _sync(on: bool):
    """Flag watcher body (observability/__init__): start/stop the
    sampler thread and the HTTP exporter with the plane."""
    global _SAMPLER, _WATCHDOG
    from .._core.flags import flag_value
    from . import exporter
    if on:
        _WATCHDOG = _Regression(
            flag_value("FLAGS_monitor_regression_factor"),
            flag_value("FLAGS_monitor_regression_steps"))
        if _SAMPLER is None or not _SAMPLER.is_alive():
            _SAMPLER = _Sampler(flag_value("FLAGS_monitor_interval_s"))
            _SAMPLER.start()
        port = int(flag_value("FLAGS_monitor_port"))
        if port:
            exporter.start(port, str(flag_value("FLAGS_monitor_host")))
    else:
        if _SAMPLER is not None:
            _SAMPLER.stop()
            _SAMPLER = None
        _WATCHDOG = None
        exporter.stop()


def reset():
    """Drop every ring and the throughput totals (tests)."""
    global STEPS, TOKENS, _LAST_STEP_WALL, _STEP_T0, _WIN_DUR_S, _WIN_N
    with _LOCK:
        _SERIES.clear()
        STEPS = TOKENS = 0
        _LAST_STEP_WALL = _STEP_T0 = None
        _WIN_DUR_S, _WIN_N = 0.0, 0
    del REGRESSIONS[:]
    _DEEP.update(armed=0, left=0, prof=None, path=None)
