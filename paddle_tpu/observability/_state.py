"""Module-level fast gates for the observability layer.

Every instrumented hot path (segment flush, cache lookup, collective,
SOT guard eval) pays exactly ONE module-attribute read when everything
here is off — the same discipline as FLAGS_static_checks. The gates are
kept coherent with their flags via flags.watch_flag (registered in
observability/__init__) and with the profiler's recording state via
profiler start/stop/step.

This module must stay import-light (stdlib only): _core.cache and
_core.lazy import it at module load.
"""
from __future__ import annotations

METRICS = False   # FLAGS_observability: registry collection at hot sites
TRACE = False     # profiler is recording: spans land in the host trace
FLIGHT = False    # FLAGS_flight_recorder: ring-buffer event capture
DIST = False      # FLAGS_distributed_telemetry: cross-rank frame plane
MEM = False       # FLAGS_memory_telemetry: live-buffer census + bytes
COMPUTE = False   # FLAGS_compute_telemetry: FLOPs accounting + MFU
GOODPUT = False   # FLAGS_goodput: wall-clock attribution ledger
MONITOR = False   # FLAGS_monitor: live time-series sampler + exporter

# The single gate hot paths read: any consumer on.
ACTIVE = False


def recompute():
    global ACTIVE
    ACTIVE = METRICS or TRACE or FLIGHT or DIST or MEM or COMPUTE \
        or GOODPUT or MONITOR


def set_metrics(on: bool):
    global METRICS
    METRICS = bool(on)
    recompute()


def set_trace(on: bool):
    global TRACE
    TRACE = bool(on)
    recompute()


def set_flight(on: bool):
    global FLIGHT
    FLIGHT = bool(on)
    recompute()


def set_dist(on: bool):
    global DIST
    DIST = bool(on)
    recompute()


def set_mem(on: bool):
    global MEM
    MEM = bool(on)
    recompute()


def set_compute(on: bool):
    global COMPUTE
    COMPUTE = bool(on)
    recompute()


def set_goodput(on: bool):
    global GOODPUT
    GOODPUT = bool(on)
    recompute()


def set_monitor(on: bool):
    global MONITOR
    MONITOR = bool(on)
    recompute()
