"""Process-wide metrics registry: counters, gauges, histograms.

The registry itself is always functional — gating lives at the
instrumented CALL SITES (one `_state.ACTIVE`/`_state.METRICS` check),
so a subsystem that is already behind its own flag (the program
sanitizer's sweep counter) can count unconditionally. `MUTATIONS`
counts every registry update; bench_suite row 6 asserts it stays
frozen across the dispatch microbench with observability off — the
"zero instrumentation work when disabled" contract, exact and immune
to wall-clock noise (same technique as the sanitizer's row 5).

Thread safety: one registry lock around every mutation. Increments are
cheap enough that contention only matters in enabled mode, whose
overhead row 6 reports rather than hides.
"""
from __future__ import annotations

import bisect
import threading
from typing import Dict, Optional

_LOCK = threading.Lock()

# total registry mutations since process start (or last hard reset) —
# the observability-off work counter
MUTATIONS = 0


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1):
        global MUTATIONS
        with _LOCK:
            self.value += n
            MUTATIONS += 1


class Gauge:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v):
        global MUTATIONS
        with _LOCK:
            self.value = v
            MUTATIONS += 1


# histogram bucket upper bounds, microseconds (last bucket = +inf)
_BOUNDS = (10.0, 50.0, 100.0, 500.0, 1e3, 5e3, 1e4, 5e4, 1e5, 5e5, 1e6)


class Histogram:
    __slots__ = ("name", "count", "total", "min", "max", "buckets")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.buckets = [0] * (len(_BOUNDS) + 1)

    def observe(self, v: float):
        global MUTATIONS
        with _LOCK:
            self.count += 1
            self.total += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            self.buckets[bisect.bisect_left(_BOUNDS, v)] += 1
            MUTATIONS += 1

    def summary(self) -> dict:
        out = {"count": self.count, "total": self.total,
               "min": self.min, "max": self.max}
        out["avg"] = self.total / self.count if self.count else None
        return out


_COUNTERS: Dict[str, Counter] = {}
_GAUGES: Dict[str, Gauge] = {}
_HISTS: Dict[str, Histogram] = {}


def counter(name: str) -> Counter:
    c = _COUNTERS.get(name)
    if c is None:
        with _LOCK:
            c = _COUNTERS.setdefault(name, Counter(name))
    return c


def gauge(name: str) -> Gauge:
    g = _GAUGES.get(name)
    if g is None:
        with _LOCK:
            g = _GAUGES.setdefault(name, Gauge(name))
    return g


def histogram(name: str) -> Histogram:
    h = _HISTS.get(name)
    if h is None:
        with _LOCK:
            h = _HISTS.setdefault(name, Histogram(name))
    return h


def inc(name: str, n: int = 1):
    counter(name).inc(n)


def observe(name: str, v: float):
    histogram(name).observe(v)


def snapshot() -> dict:
    """Point-in-time copy: {counters, gauges, histograms}."""
    with _LOCK:
        return {
            "counters": {k: c.value for k, c in _COUNTERS.items()},
            "gauges": {k: g.value for k, g in _GAUGES.items()},
            "histograms": {k: h.summary() for k, h in _HISTS.items()},
        }


def reset():
    """Zero every metric IN PLACE. Instrumentation sites (ExecCache)
    hold direct Counter references, so reset must not replace the
    objects — only their values."""
    global MUTATIONS
    with _LOCK:
        for c in _COUNTERS.values():
            c.value = 0
        for g in _GAUGES.values():
            g.value = 0.0
        for h in _HISTS.values():
            h.count = 0
            h.total = 0.0
            h.min = None
            h.max = None
            h.buckets = [0] * (len(_BOUNDS) + 1)
        MUTATIONS = 0
