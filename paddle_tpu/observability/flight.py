"""Flight recorder: a bounded ring of recent runtime events, dumped to
a human-readable report when something goes wrong.

While `FLAGS_flight_recorder` is on, the instrumented runtime pushes
one entry per span/flush/cache decision into a deque (capacity =
FLAGS_flight_recorder_capacity). Three triggers auto-dump the ring:

- an `EnforceNotMet` (framework error) being constructed,
- a failed segment flush (compile/run error in the fusion window),
- a sanitizer error-mode trip (`StaticCheckError`).

so post-mortem debugging gets the last N runtime events — flush
reasons, cache hits, donation decisions — without re-running the
workload under a profiler session.
"""
from __future__ import annotations

import collections
import os
import re
import threading
import time
from typing import Optional

from . import _state

_LOCK = threading.Lock()
_RING: Optional[collections.deque] = None
_DUMP_SEQ = 0


def _ring() -> collections.deque:
    global _RING
    if _RING is None:
        from .._core import flags
        cap = max(int(flags.flag_value("FLAGS_flight_recorder_capacity")),
                  1)
        _RING = collections.deque(maxlen=cap)
    return _RING


def _on_capacity_change(v):
    """Resize a live ring in place (keeping the newest entries) so a
    set_flags capacity change takes effect immediately, not at the
    next reset()."""
    global _RING
    with _LOCK:
        if _RING is not None:
            _RING = collections.deque(_RING, maxlen=max(int(v), 1))


from .._core import flags as _flags  # noqa: E402

_flags.watch_flag("FLAGS_flight_recorder_capacity", _on_capacity_change)


def note(kind: str, name: str, **detail):
    """Append one event. Callers gate on `_state.FLIGHT`; calling when
    off is a cheap no-op (so non-hot paths may call unconditionally)."""
    if not _state.FLIGHT:
        return
    with _LOCK:
        _ring().append((time.perf_counter_ns(), kind, name, detail))


def reset():
    global _RING
    with _LOCK:
        _RING = None     # re-read capacity flag on next use


def entries() -> list:
    """Copy of the raw ring: [(perf_ns, kind, name, detail), ...] —
    the distributed postmortem publishes this, rebased, to rank 0."""
    with _LOCK:
        return list(_RING) if _RING is not None else []


def _rank():
    """Trainer rank for dump tagging (None outside a launched job).
    Read per dump, not at import: the launcher sets the env after the
    worker process starts importing."""
    r = os.environ.get("PADDLE_TRAINER_ID")
    return int(r) if r and r.isdigit() else None


def record() -> str:
    """The current ring formatted as a report (oldest first)."""
    with _LOCK:
        entries = list(_RING) if _RING is not None else []
    now = time.perf_counter_ns()
    rank = _rank()
    who = (f"rank {rank} pid {os.getpid()}" if rank is not None
           else f"pid {os.getpid()}")
    lines = [f"== paddle_tpu flight record: {len(entries)} event(s), "
             f"{who} =="]
    for t, kind, name, detail in entries:
        rel = (t - now) / 1e9
        extra = " ".join(f"{k}={v}" for k, v in detail.items())
        lines.append(f"  {rel:+10.6f}s  {kind:<6} {name}"
                     + (f"  {extra}" if extra else ""))
    if not entries:
        lines.append("  (empty — was FLAGS_flight_recorder on while the "
                     "workload ran?)")
    return "\n".join(lines)


def _dump_dir() -> str:
    from .._core import flags
    return (flags.flag_value("FLAGS_flight_recorder_dir")
            or flags.flag_value("FLAGS_profiler_dir") or ".")


# auto-named dump files eligible for retention pruning: plain dumps,
# OOM postmortems, and monitor deep-capture traces (.json), tagged
# (group 1 = rank) or untagged. Distributed postmortem reports
# (flight_distributed_*) and any explicit-path dump never match, so
# retention can never eat them.
_PRUNABLE_RE = re.compile(
    r"^flight_(?:oom_|trace_)?(?:r(\d+)_)?\d+_\d+\.(?:txt|json)$")


def _prune_dumps(d: str, rank: Optional[int]):
    """Retention: keep the newest FLAGS_flight_max_dumps auto-named
    dumps in `d` BELONGING TO THIS RANK (rank-aware — a churning rank
    pruning only its own files can never evict another rank's
    postmortem from a shared dump dir). 0 disables pruning."""
    from .._core import flags
    keep = int(flags.flag_value("FLAGS_flight_max_dumps"))
    if keep <= 0:
        return
    mine = []
    try:
        names = os.listdir(d)
    except OSError:
        return
    for name in names:
        m = _PRUNABLE_RE.match(name)
        if m is None:
            continue
        r = int(m.group(1)) if m.group(1) is not None else None
        if r != rank:
            continue
        p = os.path.join(d, name)
        try:
            mine.append((os.path.getmtime(p), p))
        except OSError:
            continue
    if len(mine) <= keep:
        return
    mine.sort()            # oldest first
    for _, p in mine[:len(mine) - keep]:
        try:
            os.remove(p)
        except OSError:
            pass


def dump(reason: str = "", path: str = None) -> str:
    """Write the report to a file and return its path. The default
    filename is rank-tagged (`flight_r<rank>_<pid>_<seq>.txt` inside a
    launched job) so concurrent multi-process dumps into one shared
    FLAGS_flight_recorder_dir can never clobber each other; after each
    auto-named dump the oldest files beyond FLAGS_flight_max_dumps are
    pruned (this rank's only)."""
    global _DUMP_SEQ
    prune_dir = None
    rank = None
    if path is None:
        d = _dump_dir()
        os.makedirs(d, exist_ok=True)
        with _LOCK:
            _DUMP_SEQ += 1
            seq = _DUMP_SEQ
        rank = _rank()
        tag = f"r{rank}_" if rank is not None else ""
        path = os.path.join(d, f"flight_{tag}{os.getpid()}_{seq}.txt")
        prune_dir = d
    body = record()
    if reason:
        body = f"trigger: {reason}\n{body}"
    with open(path, "w") as f:
        f.write(body + "\n")
    if prune_dir is not None:
        _prune_dumps(prune_dir, rank)
    from . import metrics
    metrics.inc("flight.dumps")
    return path


def trace_path() -> str:
    """Auto-named path for a monitor deep-capture trace, beside the
    text dumps and under the same rank-aware retention (call
    prune_dumps() after writing it)."""
    global _DUMP_SEQ
    d = _dump_dir()
    os.makedirs(d, exist_ok=True)
    with _LOCK:
        _DUMP_SEQ += 1
        seq = _DUMP_SEQ
    rank = _rank()
    tag = f"r{rank}_" if rank is not None else ""
    return os.path.join(d, f"flight_trace_{tag}{os.getpid()}_{seq}.json")


def prune_dumps():
    """Public retention hook for callers that write auto-named files
    without going through dump() (the monitor's deep-capture trace)."""
    _prune_dumps(_dump_dir(), _rank())


def on_error(kind: str, message: str):
    """Auto-dump trigger (enforce error / sanitizer trip / failed
    flush). Gated by the caller on `_state.FLIGHT`; never raises — a
    dump failure must not mask the original error."""
    note("error", kind, message=message[:200])
    try:
        path = dump(reason=f"{kind}: {message[:200]}")
        import logging
        logging.getLogger("paddle_tpu.observability").error(
            "flight record dumped to %s (%s)", path, kind)
    except Exception:
        pass
