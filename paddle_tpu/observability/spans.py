"""Structured runtime spans.

A span is one timed region of the fused runtime (a segment flush, an
XLA compile, a collective) that fans out to every enabled consumer:

- metrics:  duration observed into a histogram (`hist` name, so e.g.
  every `segment::flush[<reason>]` variant feeds ONE `segment.flush_us`
  histogram instead of fragmenting per reason);
- trace:    an event appended to the profiler's host-event buffer, so
  the chrome-trace export shows the span on the recording thread's
  lane, nested under/over other host events by time;
- flight:   a ring-buffer entry for post-mortem dumps.

Callers pre-gate on `_state.ACTIVE` — constructing a span when
everything is off never happens on a hot path.
"""
from __future__ import annotations

import time

from . import _state, metrics


class Span:
    __slots__ = ("name", "hist", "args", "_t0")

    def __init__(self, name: str, hist=None, args=None):
        self.name = name
        self.hist = hist
        self.args = args
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        if _state.GOODPUT:
            # the attribution ledger's state transition: entering a
            # mapped span (execute/compile/comm/io/ckpt/...) switches
            # the wall-clock bucket the goodput plane accrues into
            from . import goodput
            goodput.on_span_begin(self.name, self._t0)
        return self

    def end(self, error=None):
        if self._t0 is None:
            return
        t0, self._t0 = self._t0, None
        now_ns = time.perf_counter_ns()
        dur_us = (now_ns - t0) / 1000.0
        if _state.GOODPUT:
            from . import goodput
            goodput.on_span_end(self.name, now_ns, dur_us)
        if _state.METRICS and self.hist is not None:
            metrics.observe(self.hist, dur_us)
        if _state.TRACE:
            from ..profiler import _add_span_event
            _add_span_event(self.name, t0 / 1000.0, dur_us, self.args)
        if _state.FLIGHT:
            from . import flight
            detail = dict(self.args) if self.args else {}
            detail["dur_us"] = round(dur_us, 1)
            if error is not None:
                detail["error"] = repr(error)
            flight.note("span", self.name, **detail)
        if _state.DIST:
            from . import distributed
            distributed.note_span(
                self.name, t0, dur_us,
                (self.args or {}).get("bytes", 0))

    def __enter__(self):
        return self.begin()

    def __exit__(self, et, ev, tb):
        self.end(error=ev)
        return False


def span(name: str, hist: str = None, **args) -> Span:
    return Span(name, hist, args or None)


class _NullSpan:
    """Shared no-op stand-in (stateless, safe to reuse) so call sites
    can write `with maybe_span(...)` without a branch."""

    def begin(self):
        return self

    def end(self, error=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()
