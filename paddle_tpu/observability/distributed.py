"""Cross-rank telemetry plane: merged rank timelines, straggler and
comm-overlap analysis, distributed flight postmortems.

Every other observability surface (metrics registry, spans, flight
recorder, the budget tool) is single-process; the distributed runtime
is not. This module makes per-rank telemetry a cluster-wide artifact:

- **publisher** (every rank): at step boundaries each rank publishes a
  compact *telemetry frame* — metrics-snapshot deltas, per-step span
  histogram deltas, recent span events, the step index and mesh epoch,
  and a (wall, perf) clock anchor — through the existing TCPStore
  under ``__telem/`` keys. Publication happens on a daemon thread over
  a bounded drop-oldest queue, so a slow store can never block
  training; the aggregator reads with `try_get` probes, so aggregation
  never blocks either.
- **aggregator** (rank 0, or the offline `merge` CLI verb): merges the
  frames into (a) a cluster **step table** with per-rank durations,
  per-span-family skew columns (slowest rank minus median) and
  straggler flagging, (b) a **comm-overlap report** computing, per
  step, the fraction of ``comm::*`` span time overlapped with
  compute/worker spans — and, from the payload bytes the comm spans
  now carry, the achieved host-collective bandwidth — and (c) a
  **merged chrome trace** with one lane per rank, every rank's
  perf-counter timeline rebased onto a common store-derived clock
  offset.
- **distributed postmortem**: on rank death or a latched async-flush
  worker error, survivors publish their bounded flight-recorder rings
  under ``__telem/post/<rank>`` and rank 0 writes ONE interleaved,
  rank-tagged report next to the (rank-tagged) per-process dumps.

Store key namespace::

    __telem/seq/<rank>          newest published frame seq (ascii int)
    __telem/frame/<rank>/<slot> frame ring, slot = seq % keep (zlib'd
                                json, self-describing) — the store
                                holds at most `keep` frames per rank,
                                however long the job runs
    __telem/post/<rank>         postmortem ring blob

Off-cost follows the house pattern: `FLAGS_distributed_telemetry` is
cached into the `_state.DIST` module gate by a flag watcher; when off,
the step hook is one module-attribute read and NO registry or store
work happens (bench_suite row 10 asserts both exactly).
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence

from . import _state

FRAME_VERSION = 1

_SEQ_KEY = "__telem/seq/{rank}"
_FRAME_KEY = "__telem/frame/{rank}/{slot}"
_POST_KEY = "__telem/post/{rank}"

# frames retained in the store per rank (ring of slot keys): bounds
# store growth on long runs while letting a periodically-polling
# aggregator catch up on the recent window
FRAME_KEEP = 64


# ------------------------------------------------------------ frame codec

def encode_frame(frame: Dict) -> bytes:
    """Compact wire form: minified json, zlib-compressed. Each frame
    lands in a per-rank slot ring (seq % FRAME_KEEP), so the store
    holds at most world_size * FRAME_KEEP of them."""
    return zlib.compress(
        json.dumps(frame, separators=(",", ":")).encode())


def decode_frame(blob: bytes) -> Dict:
    frame = json.loads(zlib.decompress(blob).decode())
    v = frame.get("v")
    if v != FRAME_VERSION:
        raise ValueError(f"telemetry frame version {v!r} "
                         f"(expected {FRAME_VERSION})")
    return frame


# -------------------------------------------------------- span event feed

_EVENTS_LOCK = threading.Lock()
_EVENTS: Optional[collections.deque] = None


def _events_ring() -> collections.deque:
    global _EVENTS
    if _EVENTS is None:
        from .._core import flags
        cap = max(int(flags.flag_value(
            "FLAGS_distributed_telemetry_events")), 16)
        _EVENTS = collections.deque(maxlen=cap)
    return _EVENTS


def note_span(name: str, t0_ns: int, dur_us: float, nbytes: int = 0):
    """One finished span, fed by spans.Span.end while `_state.DIST` is
    on: (name, start in perf-us, duration us, payload bytes). Bounded
    ring — a rank that never publishes cannot grow without bound."""
    with _EVENTS_LOCK:
        _events_ring().append(
            (name, t0_ns / 1000.0, dur_us, int(nbytes)))


def _drain_events() -> List:
    with _EVENTS_LOCK:
        if _EVENTS is None:
            return []
        out = list(_EVENTS)
        _EVENTS.clear()
    return out


# -------------------------------------------------------------- publisher

class TelemetryPublisher:
    """Per-rank frame publication at step boundaries.

    `on_step(step)` is the only hot call: it stamps the step boundary
    and, every `FLAGS_distributed_telemetry_interval` steps, snapshots
    the registry delta + drained span events into a frame and hands it
    to the publish thread. The store `set` runs entirely off-thread
    behind a bounded drop-oldest queue — telemetry can lag, training
    cannot block."""

    def __init__(self, store, rank: int, world_size: int,
                 interval: Optional[int] = None):
        from .._core import flags
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.interval = max(int(
            interval if interval is not None
            else flags.flag_value("FLAGS_distributed_telemetry_interval")),
            1)
        self._seq = 0
        self._steps_since = 0
        self._last_counters: Dict[str, int] = {}
        self._last_hists: Dict[str, tuple] = {}
        # (executed-FLOPs total, t_perf_us) at the last publication:
        # the compute section ships per-frame deltas so rank 0 can put
        # an MFU column next to the straggler flags
        self._last_compute = None
        # goodput-ledger snapshot at the last publication: frames ship
        # per-window bucket DELTAS so rank 0 can sum them into the
        # cluster goodput report
        self._last_goodput = None
        self._last_step_t: Optional[float] = None
        self._marks: List = []   # [step_index, end_us, dur_us]
        # retained for the offline dump; bounded so a long training
        # run cannot grow rank memory with its step count
        self.frames: collections.deque = collections.deque(
            maxlen=4 * FRAME_KEEP)
        self._q: collections.deque = collections.deque(maxlen=8)
        self._have_work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._published_seq = 0   # last seq CONFIRMED written
        self._publish_us = None   # metrics.histogram, bound lazily

    # ------------------------------------------------------------ steps
    def on_step(self, step_index: int):
        now = time.perf_counter_ns() / 1000.0
        if self._last_step_t is not None:
            self._marks.append(
                [int(step_index), now, now - self._last_step_t])
        self._last_step_t = now
        self._steps_since += 1
        if self._steps_since >= self.interval:
            self.publish(step_index)

    def publish(self, step_index: int):
        """Build one frame from the deltas since the last publication
        and enqueue it for the store thread."""
        t0 = time.perf_counter_ns()
        self._steps_since = 0
        self._seq += 1
        from . import metrics
        snap = metrics.snapshot()
        counters = {}
        for k, v in snap["counters"].items():
            d = v - self._last_counters.get(k, 0)
            if d:
                counters[k] = d
            self._last_counters[k] = v
        hists = {}
        for k, h in snap["histograms"].items():
            prev = self._last_hists.get(k, (0.0, 0))
            d_total = (h["total"] or 0.0) - prev[0]
            d_count = (h["count"] or 0) - prev[1]
            if d_count or d_total:
                hists[k] = [round(d_total, 3), d_count]
            self._last_hists[k] = ((h["total"] or 0.0), (h["count"] or 0))
        from .._core import lazy
        frame = {
            "v": FRAME_VERSION,
            "rank": self.rank,
            "pid": os.getpid(),
            "seq": self._seq,
            "step": int(step_index),
            "mesh_epoch": int(getattr(lazy, "MESH_EPOCH", 0)),
            "t_wall": time.time(),
            "t_perf_us": time.perf_counter_ns() / 1000.0,
            "counters": counters,
            "hists": hists,
            # json-normalized (lists, rounded) so a retained frame is
            # byte-identical to its store round trip
            "spans": [[n, round(t0, 3), round(d, 3), b]
                      for n, t0, d, b in _drain_events()],
            "marks": [[s, round(t, 3), round(d, 3)]
                      for s, t, d in self._marks],
        }
        if _state.MEM:
            # byte-domain deltas ride the frame: rank 0's step table
            # grows a per-rank memory column from these (watermark +
            # census size + donation total, all O(1) reads)
            from . import memory as _memtel
            frame["mem"] = {"live": _memtel.live_bytes(),
                            "peak": _memtel.peak_bytes(),
                            "donated": _memtel.donated_bytes(),
                            "census": _memtel.census_size(),
                            # STRING-keyed per-device map: survives the
                            # json round trip through the store (the
                            # PR-8 step-table key-type bug class)
                            "per_device": _memtel.device_bytes()}
        if _state.COMPUTE:
            # FLOP-domain deltas: executed FLOPs since the last frame
            # over the elapsed window -> this rank's achieved GFLOP/s
            # and MFU against its OWN backend peak (each rank prices
            # itself, so a heterogeneous pod stays honest). The step
            # table's straggler column reads this to say "slow AND
            # idle" vs "slow but saturated".
            from . import compute as _comptel
            flops = _comptel.executed_flops()
            now_us = frame["t_perf_us"]
            peak = _comptel.peak_flops()
            comp = {"peak": peak}
            if self._last_compute is not None:
                d_flops = flops - self._last_compute[0]
                dt_us = now_us - self._last_compute[1]
                comp["flops"] = int(d_flops)
                if dt_us > 0:
                    ach = d_flops / (dt_us * 1e-6)
                    comp["gflops"] = round(ach / 1e9, 3)
                    comp["mfu"] = round(_comptel.mfu(ach, peak), 6)
            else:
                comp["flops"] = int(flops)
            frame["compute"] = comp
            self._last_compute = (flops, now_us)
        if _state.GOODPUT:
            # wall-attribution deltas: each rank's exclusive bucket
            # partition since the last frame — rank 0 sums these into
            # the per-rank goodput column and the job-end cluster
            # goodput report (productive / total chip-seconds)
            from . import goodput as _goodtel
            sec, self._last_goodput = _goodtel.frame_delta(
                self._last_goodput)
            if sec and sec.get("buckets"):
                frame["goodput"] = sec
        self._marks = []
        self.frames.append(frame)
        self._q.append(frame)        # drop-oldest: never blocks
        self._have_work.set()
        self._ensure_thread()
        if _state.METRICS:
            if self._publish_us is None:
                self._publish_us = metrics.histogram(
                    "telemetry.publish_us")
            metrics.inc("telemetry.frames")
            self._publish_us.observe(
                (time.perf_counter_ns() - t0) / 1000.0)

    # --------------------------------------------------- publish thread
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            t = threading.Thread(target=self._publish_loop,
                                 name="pt-telemetry-publish",
                                 daemon=True)
            self._thread = t
            t.start()

    def _publish_loop(self):
        while not self._stop.is_set():
            self._have_work.wait(timeout=0.5)
            self._have_work.clear()
            while True:
                try:
                    frame = self._q.popleft()
                except IndexError:
                    break
                try:
                    self.store.set(
                        _FRAME_KEY.format(
                            rank=self.rank,
                            slot=frame["seq"] % FRAME_KEEP),
                        encode_frame(frame))
                    # seq key LAST: an aggregator that sees the seq
                    # always finds the slot populated
                    self.store.set(_SEQ_KEY.format(rank=self.rank),
                                   str(frame["seq"]).encode())
                    self._published_seq = frame["seq"]
                except Exception:
                    # a dead store must not kill the loop; the frame is
                    # lost, training is not
                    if _state.METRICS:
                        from . import metrics
                        metrics.inc("telemetry.publish_errors")

    def flush(self, timeout: float = 5.0):
        """Block until every enqueued frame is CONFIRMED in the store
        (not merely dequeued — a caller about to die must know its last
        frame landed). Drills and tests; training never calls this."""
        deadline = time.time() + timeout
        self._ensure_thread()
        while self._published_seq < self._seq \
                and time.time() < deadline:
            self._have_work.set()
            time.sleep(0.01)

    def shutdown(self):
        self._stop.set()
        self._have_work.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # ----------------------------------------------------- offline dump
    def dump(self, path: str) -> str:
        """Write every frame this rank produced to `telem_rank<R>.json`
        (or `path` if it names a file) for the offline `merge` verb."""
        if os.path.isdir(path):
            path = os.path.join(path, f"telem_rank{self.rank}.json")
        with open(path, "w") as f:
            json.dump({"rank": self.rank,
                       "frames": list(self.frames)}, f)
        return path

    # ------------------------------------------------------- postmortem
    def publish_postmortem(self, reason: str):
        """Publish this rank's bounded flight ring (plus a clock anchor
        so the aggregator can rebase it) under __telem/post/<rank>.
        Synchronous and best-effort: the caller is already handling a
        failure."""
        from . import flight
        events = [[t / 1000.0, kind, name,
                   " ".join(f"{k}={v}" for k, v in detail.items())]
                  for t, kind, name, detail in flight.entries()]
        blob = encode_frame({
            "v": FRAME_VERSION,
            "rank": self.rank,
            "pid": os.getpid(),
            "reason": reason,
            "t_wall": time.time(),
            "t_perf_us": time.perf_counter_ns() / 1000.0,
            "events": events,
        })
        try:
            self.store.set(_POST_KEY.format(rank=self.rank), blob)
        except Exception:
            pass


# ------------------------------------------------------------- aggregator

def clock_anchor(frame: Dict) -> float:
    """A rank's wall-clock origin of its perf timeline, in us: adding
    this to any of the rank's perf-us timestamps yields epoch-us. Two
    ranks' anchors differ by exactly their clock offset, so rebasing
    every rank onto one base rank needs only the frames themselves —
    the store carried the (wall, perf) pair."""
    return frame["t_wall"] * 1e6 - frame["t_perf_us"]


def _interval_union(intervals: List) -> List:
    """Merge [start, end) intervals into a disjoint sorted list."""
    out: List = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out


def _overlap_len(a: List, b: List) -> float:
    """Total intersection length of two disjoint sorted interval
    lists."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def span_family(name: str) -> str:
    """Span names group into families by their `::` prefix:
    `comm::all_reduce` -> `comm`, `segment::flush[...]` -> `segment`."""
    return name.split("::", 1)[0]


class TelemetryAggregator:
    """Merge per-rank frames into cluster-wide reports. Frames come
    from live store probes (`poll_store`), the offline dump files
    (`add_dump`), or directly (`add_frame`); all three feed the same
    merge."""

    def __init__(self):
        self._frames: Dict[int, List[Dict]] = {}
        self._seen: set = set()
        self._next_seq: Dict[int, int] = {}   # per-rank poll cursor
        self._bucket_memo = None   # (frame_count, per_rank, spans)

    # ------------------------------------------------------------ intake
    def add_frame(self, frame: Dict):
        key = (frame["rank"], frame.get("seq"))
        if frame.get("seq") is not None and key in self._seen:
            return
        self._seen.add(key)
        self._frames.setdefault(int(frame["rank"]), []).append(frame)

    def add_dump(self, path: str):
        with open(path) as f:
            doc = json.load(f)
        for frame in doc["frames"]:
            self.add_frame(frame)

    def poll_store(self, store, ranks: Sequence[int]):
        """One non-blocking probe pass: read each rank's latest seq,
        then fetch every not-yet-seen frame still inside its slot ring
        (try_get probes throughout — a missing or slow rank is skipped,
        never waited for)."""
        for r in ranks:
            raw = store.try_get(_SEQ_KEY.format(rank=r), timeout=0.05)
            if not raw:
                continue
            try:
                latest = int(raw.decode())
            except ValueError:
                continue
            start = max(self._next_seq.get(r, 1),
                        latest - FRAME_KEEP + 1)
            for seq in range(start, latest + 1):
                blob = store.try_get(
                    _FRAME_KEY.format(rank=r, slot=seq % FRAME_KEEP),
                    timeout=0.05)
                if not blob:
                    continue
                try:
                    frame = decode_frame(blob)
                except (ValueError, zlib.error):
                    continue
                if frame.get("seq") == seq:   # slot not yet rewritten
                    self.add_frame(frame)
            self._next_seq[r] = latest + 1

    @property
    def ranks(self) -> List[int]:
        return sorted(self._frames)

    def frames(self, rank: int) -> List[Dict]:
        return sorted(self._frames.get(rank, ()),
                      key=lambda f: f.get("seq", 0))

    # ------------------------------------------------------- clock rebase
    def clock_offsets(self, base_rank: Optional[int] = None) -> Dict:
        """Per-rank offset (us) rebasing each rank's perf timeline onto
        `base_rank`'s (default: lowest rank seen). Derived from the
        newest frame's (wall, perf) anchor per rank."""
        if not self._frames:
            return {}
        if base_rank is None:
            base_rank = self.ranks[0]
        anchors = {}
        for r in self.ranks:
            fs = self.frames(r)
            anchors[r] = clock_anchor(fs[-1])
        base = anchors.get(base_rank, next(iter(anchors.values())))
        return {r: a - base for r, a in anchors.items()}

    # --------------------------------------------------------- step table
    def _per_rank_steps(self) -> Dict[int, Dict[int, Dict]]:
        """rank -> step index -> {dur_us, start_us, end_us} (rank-local
        perf timeline)."""
        out: Dict[int, Dict[int, Dict]] = {}
        for r in self.ranks:
            steps: Dict[int, Dict] = {}
            for frame in self.frames(r):
                for step, end_us, dur_us in frame.get("marks", ()):
                    steps[int(step)] = {"dur_us": dur_us,
                                        "start_us": end_us - dur_us,
                                        "end_us": end_us}
            out[r] = steps
        return out

    def _spans_by_step(self, per_rank: Dict) -> Dict:
        """rank -> step -> {"comm": [intervals], "other": [intervals],
        "bytes": payload} — every span event bucketed into its rank's
        step window by midpoint (rank-local timeline; no cross-rank
        clock involved). Transfers are the ``comm::*`` collectives AND
        the ``io::*`` device-feed spans (io::h2d carries payload bytes
        the same way), so the input feed is priced like any other
        transfer."""
        import bisect
        out: Dict[int, Dict[int, Dict]] = {}
        for r in self.ranks:
            windows = per_rank.get(r, {})
            # windows are disjoint: bisect over sorted starts keeps
            # aggregation O((events + steps) log steps) per rank
            ordered = sorted((w["start_us"], w["end_us"], s)
                             for s, w in windows.items())
            starts = [w[0] for w in ordered]
            buckets: Dict[int, Dict] = {}

            def _step_of(t_us):
                i = bisect.bisect_right(starts, t_us) - 1
                if i >= 0 and t_us < ordered[i][1]:
                    return ordered[i][2]
                return None

            for frame in self.frames(r):
                for ev in frame.get("spans", ()):
                    name, t0_us, dur_us = ev[0], ev[1], ev[2]
                    nbytes = ev[3] if len(ev) > 3 else 0
                    s = _step_of(t0_us + dur_us / 2.0)
                    if s is None:
                        continue
                    b = buckets.setdefault(
                        s, {"comm": [], "other": [], "bytes": 0})
                    iv = (t0_us, t0_us + dur_us)
                    if span_family(name) in ("comm", "io"):
                        b["comm"].append(iv)
                        b["bytes"] += int(nbytes)
                    else:
                        b["other"].append(iv)
            out[r] = buckets
        return out

    def _buckets(self):
        """(per_rank_steps, spans_by_step), memoized on the frame
        count: step_table() and overlap_report() are always called
        back-to-back over the same intake, and the bucketing pass is
        the aggregation's heaviest."""
        n = sum(len(fs) for fs in self._frames.values())
        if self._bucket_memo is None or self._bucket_memo[0] != n:
            per_rank = self._per_rank_steps()
            self._bucket_memo = (n, per_rank,
                                 self._spans_by_step(per_rank))
        return self._bucket_memo[1], self._bucket_memo[2]

    def step_table(self) -> Dict:
        """The cluster step table: one row per step index with per-rank
        durations, the cross-rank median, the skew column (slowest
        minus median) and a straggler flag; plus per-span-family skew
        aggregated over the run (slowest rank minus median, us/step).

        Straggler detection uses TWO signals, because a synchronizing
        collective equalizes every rank's wall time: (a) wall skew —
        the slowest rank when no barrier hides it — and (b) comm-wait
        deficit — under a barrier the laggard is the rank that waits
        LEAST inside ``comm::*`` while its peers idle there (MLPerf-
        on-pods' skew attribution, arxiv 1909.09756)."""
        from .._core.flags import flag_value
        factor = float(flag_value("FLAGS_telemetry_straggler_factor"))
        min_us = float(flag_value("FLAGS_telemetry_straggler_min_us"))
        per_rank, spans = self._buckets()
        all_steps = sorted({s for steps in per_rank.values()
                            for s in steps})
        rows = []
        strag_counts: Dict[int, int] = {}
        # per-rank windowed MFU by frame step (the compute plane's
        # frame section): lets a straggler flag say "slow AND idle"
        # (its device is starving — chase input feed / host dispatch)
        # vs "slow but saturated" (its device is busy — chase the work
        # imbalance). Each frame's MFU covers the steps since the
        # previous frame, so a step row reads the first frame at or
        # after it — not the newest frame, which would stamp the
        # end-of-run verdict onto every historical row.
        mfu_frames: Dict[int, list] = {}
        for r in self.ranks:
            pts = sorted(
                (int(f["step"]), f["compute"]["mfu"])
                for f in self.frames(r)
                if f.get("compute", {}).get("mfu") is not None)
            if pts:
                mfu_frames[int(r)] = pts
        # per-rank goodput sections by frame step: a straggler row reads
        # the window COVERING the step (first frame at-or-after) to name
        # its top badput source — the input-wait bucket upgrades the
        # verdict to "input_bound" (the rank is slow because its feed
        # is, not because its work is)
        good_frames: Dict[int, list] = {}
        for r in self.ranks:
            # key on the step alone: a replayed step (checkpoint
            # restore rewinds the index) publishes two frames with the
            # same step value, and the tuple sort would fall through
            # to comparing the goodput dicts — TypeError
            pts = sorted(
                ((int(f["step"]), f["goodput"])
                 for f in self.frames(r) if f.get("goodput")),
                key=lambda p: p[0])
            if pts:
                good_frames[int(r)] = pts
        for s in all_steps:
            durs = {r: steps[s]["dur_us"]
                    for r, steps in per_rank.items() if s in steps}
            if not durs:
                continue
            vals = sorted(durs.values())
            # lower-middle median: skew stays meaningful at even counts
            median = vals[(len(vals) - 1) // 2]
            mx = vals[-1]
            slowest = max(durs, key=durs.get)
            skew = mx - median
            straggler, via = None, None
            if len(durs) > 1 and skew >= min_us \
                    and mx >= factor * median:
                straggler, via = slowest, "wall"
            else:
                # comm-wait deficit: everyone waits in the collective
                # for the laggard, who is the one NOT waiting
                comm = {r: sum(e - b for b, e in _interval_union(
                            spans.get(r, {}).get(s, {}).get("comm",
                                                            [])))
                        for r in durs}
                with_comm = {r: c for r, c in comm.items() if c > 0.0}
                if len(with_comm) > 1:
                    cvals = sorted(with_comm.values())
                    cmed = cvals[(len(cvals) - 1) // 2]
                    laggard = min(with_comm, key=with_comm.get)
                    cmin = with_comm[laggard]
                    if cmed - cmin >= min_us \
                            and cmed >= factor * max(cmin, 1.0):
                        straggler, via = laggard, "comm_wait"
            if straggler is not None:
                strag_counts[straggler] = \
                    strag_counts.get(straggler, 0) + 1
            compute_verdict = None
            badput_name = None
            if straggler is not None:
                mfus = {r: next((m for st, m in mfu_frames[r]
                                 if st >= s), mfu_frames[r][-1][1])
                        for r in durs if r in mfu_frames}
                if straggler in mfus and len(mfus) > 1:
                    cvals2 = sorted(mfus.values())
                    cmed = cvals2[(len(cvals2) - 1) // 2]
                    compute_verdict = ("idle" if mfus[straggler]
                                       < 0.6 * max(cmed, 1e-12)
                                       else "saturated")
                pts = good_frames.get(straggler)
                if pts:
                    sec = next((g for st, g in pts if st >= s),
                               pts[-1][1])
                    buckets = sec.get("buckets", {})
                    bad = sorted(((k, v) for k, v in buckets.items()
                                  if k != "execute"),
                                 key=lambda kv: -kv[1])
                    total = sum(buckets.values())
                    if bad and bad[0][1] > 0:
                        badput_name = bad[0][0]
                        if badput_name == "input_wait" and total \
                                and bad[0][1] >= 0.1 * total:
                            # the straggler's window is dominated by
                            # feed stalls: slow because starved, the
                            # MLPerf input-bound case
                            compute_verdict = "input_bound"
            # per-rank maps are string-keyed so the table survives a
            # json round trip (the CLI ships it between processes)
            rows.append({"step": s,
                         "ranks": {str(r): round(d, 1)
                                   for r, d in sorted(durs.items())},
                         "median_us": round(median, 1),
                         "max_us": round(mx, 1),
                         "skew_us": round(skew, 1),
                         "straggler": straggler,
                         "straggler_via": via,
                         "straggler_compute": compute_verdict,
                         "straggler_badput": badput_name})
        # span-family skew: per rank us/step for each family, then
        # slowest-minus-median across ranks
        fam_rank: Dict[str, Dict[int, float]] = {}
        steps_per_rank = {r: max(len(per_rank[r]), 1) for r in per_rank}
        for r in self.ranks:
            for frame in self.frames(r):
                for hist, (total, _count) in frame.get("hists",
                                                       {}).items():
                    # the plane's own publish cost is priced by bench
                    # row 10, not a runtime span family
                    if not hist.endswith("_us") \
                            or hist.startswith("telemetry."):
                        continue
                    fam = hist[:-3].split(".", 1)[0]
                    fam_rank.setdefault(fam, {}).setdefault(r, 0.0)
                    fam_rank[fam][r] += total
        families = {}
        for fam, by_rank in sorted(fam_rank.items()):
            per_step = {r: v / steps_per_rank.get(r, 1)
                        for r, v in by_rank.items()}
            vals = sorted(per_step.values())
            median = vals[(len(vals) - 1) // 2]   # lower-middle: skew stays meaningful at even rank counts
            slowest = max(per_step, key=per_step.get)
            families[fam] = {
                "ranks": {str(r): round(v, 1)
                          for r, v in sorted(per_step.items())},
                "median_us": round(median, 1),
                "skew_us": round(per_step[slowest] - median, 1),
                "slowest": slowest}
        return {"ranks": self.ranks, "steps": rows,
                "families": families,
                "memory": self._memory_column(),
                "compute": self._compute_column(),
                "goodput": self._goodput_column(),
                "straggler_counts": {str(r): n for r, n in
                                     strag_counts.items()}}

    def _goodput_totals(self) -> Dict[int, Dict[str, float]]:
        """Per-rank bucket totals: the frame DELTAS summed over the
        observed window (each frame ships the partition since its
        predecessor, so the sum is the rank's cumulative ledger)."""
        out: Dict[int, Dict[str, float]] = {}
        for r in self.ranks:
            buckets: Dict[str, float] = {}
            for f in self.frames(r):
                sec = f.get("goodput")
                if not sec:
                    continue
                for k, v in sec.get("buckets", {}).items():
                    buckets[k] = buckets.get(k, 0.0) + float(v)
            if buckets:
                out[int(r)] = buckets
        return out

    def _goodput_column(self) -> Optional[Dict]:
        """Per-rank goodput fraction + top badput source for the step
        table — the job-health column next to memory and MFU."""
        totals = self._goodput_totals()
        if not totals:
            return None
        col: Dict[str, Dict] = {}
        for r, buckets in sorted(totals.items()):
            total = sum(buckets.values())
            prod = buckets.get("execute", 0.0)
            bad = sorted(((k, v) for k, v in buckets.items()
                          if k != "execute"), key=lambda kv: -kv[1])
            col[str(r)] = {
                "goodput_frac": round(prod / total, 4) if total else None,
                "top_badput": bad[0][0] if bad and bad[0][1] > 0
                else None}
        return {"ranks": col}

    def goodput_report(self) -> Optional[Dict]:
        """The job-end CLUSTER goodput report: productive chip-seconds
        over total chip-seconds (every rank's wall is a chip's wall),
        per-rank goodput fraction with the top badput source named —
        the end-to-end efficiency lens the MLPerf TPU-pod papers grade
        every scaling recipe through, and the bar a pod run must clear
        before burning real chip hours."""
        totals = self._goodput_totals()
        if not totals:
            return None
        ranks: Dict[str, Dict] = {}
        tot_us = prod_us = 0.0
        for r, buckets in sorted(totals.items()):
            total = sum(buckets.values())
            prod = buckets.get("execute", 0.0)
            tot_us += total
            prod_us += prod
            bad = sorted(((k, v) for k, v in buckets.items()
                          if k != "execute"), key=lambda kv: -kv[1])
            top = bad[0] if bad and bad[0][1] > 0 else None
            hang = any(f.get("goodput", {}).get("hang")
                       for f in self.frames(r))
            ranks[str(r)] = {
                "total_us": round(total, 1),
                "productive_us": round(prod, 1),
                "goodput_frac": round(prod / total, 4) if total
                else None,
                "top_badput": ({"bucket": top[0],
                                "us": round(top[1], 1),
                                "frac": round(top[1] / total, 4)}
                               if top and total else None),
                # same dominance rule as the step-table verdict: a few
                # stray microseconds of feed wait on a near-perfect
                # rank must not fail the 'no input-bound rank' pod bar
                "input_bound": bool(top and top[0] == "input_wait"
                                    and total
                                    and top[1] >= 0.1 * total),
                "hang": bool(hang),
                "buckets_us": {k: round(v, 1)
                               for k, v in sorted(buckets.items())},
            }
        return {
            "ranks": ranks,
            "cluster": {
                "total_chip_s": round(tot_us / 1e6, 4),
                "productive_chip_s": round(prod_us / 1e6, 4),
                "goodput_frac": (round(prod_us / tot_us, 4)
                                 if tot_us else None),
            }}

    def _compute_column(self) -> Optional[Dict]:
        """Per-rank achieved GFLOP/s + MFU from the newest frame that
        carried a ``compute`` section (FLAGS_compute_telemetry on that
        rank) — the per-chip-MFU acceptance column the pod-scale
        ROADMAP item grades against."""
        col: Dict[str, Dict] = {}
        for r in self.ranks:
            for frame in reversed(self.frames(r)):
                c = frame.get("compute")
                if c:
                    col[str(r)] = c
                    break
        return {"ranks": col} if col else None

    def _memory_column(self) -> Optional[Dict]:
        """Per-rank byte watermark from the newest frame that carried a
        ``mem`` section (FLAGS_memory_telemetry on that rank), plus the
        rank nearest its HBM budget: peak/FLAGS_memory_budget_bytes
        when the budget is known, highest absolute peak otherwise —
        THE number that picks the mesh degree before scaling a model
        up (memory, not FLOPs, binds first on TPUs)."""
        col: Dict[str, Dict] = {}
        for r in self.ranks:
            for frame in reversed(self.frames(r)):
                m = frame.get("mem")
                if m:
                    col[str(r)] = m
                    break
        if not col:
            return None
        from .._core.flags import flag_value
        budget_b = int(flag_value("FLAGS_memory_budget_bytes"))
        nearest = max(col, key=lambda rs: col[rs].get("peak", 0))
        frac = (round(col[nearest].get("peak", 0) / budget_b, 4)
                if budget_b > 0 else None)
        return {"ranks": col, "budget_bytes": budget_b,
                "nearest_budget": int(nearest),
                "nearest_budget_frac": frac}

    # ----------------------------------------------------- comm overlap
    def overlap_report(self) -> Dict:
        """Per step, the fraction of ``comm::*`` span time that ran
        concurrently with compute/worker spans (interval intersection
        on each rank's own timeline — no cross-rank clock needed), and
        the achieved bandwidth priced from the payload bytes the comm
        spans carry. Host-driven collectives serialize against the
        step loop, so today's baseline is ~0 — the number the
        overlapped-collectives work must beat."""
        per_rank, spans = self._buckets()
        steps: Dict[int, Dict] = {}
        for r in self.ranks:
            for s, b in spans.get(r, {}).items():
                if not b["comm"]:
                    continue
                cu = _interval_union(b["comm"])
                ou = _interval_union(b["other"])
                comm_us = sum(e - beg for beg, e in cu)
                row = steps.setdefault(
                    s, {"comm_us": 0.0, "overlap_us": 0.0, "bytes": 0})
                row["comm_us"] += comm_us
                row["overlap_us"] += _overlap_len(cu, ou)
                row["bytes"] += b["bytes"]
        rows = []
        tot_comm = tot_overlap = tot_bytes = 0.0
        for s in sorted(steps):
            row = steps[s]
            tot_comm += row["comm_us"]
            tot_overlap += row["overlap_us"]
            tot_bytes += row["bytes"]
            frac = (row["overlap_us"] / row["comm_us"]
                    if row["comm_us"] else None)
            bw = (row["bytes"] / (row["comm_us"] / 1e6) / 1e9
                  if row["comm_us"] else None)
            rows.append({"step": s,
                         "comm_us": round(row["comm_us"], 1),
                         "overlap_us": round(row["overlap_us"], 1),
                         "overlap_frac": (round(frac, 4)
                                          if frac is not None else None),
                         "bytes": int(row["bytes"]),
                         "gbps": round(bw, 4) if bw is not None else None})
        total = {
            "comm_us": round(tot_comm, 1),
            "overlap_us": round(tot_overlap, 1),
            "overlap_frac": (round(tot_overlap / tot_comm, 4)
                             if tot_comm else None),
            "bytes": int(tot_bytes),
            "gbps": (round(tot_bytes / (tot_comm / 1e6) / 1e9, 4)
                     if tot_comm else None),
        }
        return {"steps": rows, "total": total,
                "compiled": self._compiled_comm(per_rank)}

    def _compiled_comm(self, per_rank) -> Optional[Dict]:
        """Collectives the SPMD step compiled INTO its executables are
        invisible to the comm::* span layer — their estimated payload
        rides the frames as ``comm.bytes.compiled.<site>`` counter
        deltas (lazy._note_compiled_comm). Summed here so moving the
        collectives off the host keeps them priced: a run whose host
        comm_us dropped to ~0 while compiled bytes are nonzero MOVED
        its traffic into the program instead of losing it."""
        prefix = "comm.bytes.compiled."
        sites: Dict[str, int] = {}
        per_step = 0.0
        for r in self.ranks:
            rank_total = 0
            for frame in self.frames(r):
                for k, v in frame.get("counters", {}).items():
                    if k.startswith(prefix):
                        sites[k[len(prefix):]] = \
                            sites.get(k[len(prefix):], 0) + int(v)
                        rank_total += int(v)
            steps = len(per_rank.get(r, ()))
            if rank_total and steps:
                per_step += rank_total / steps
        if not sites:
            return None
        return {"sites": sites, "bytes": sum(sites.values()),
                "bytes_per_step": round(per_step, 1)}

    # ----------------------------------------------------- merged trace
    def merged_trace(self, path: Optional[str] = None) -> Dict:
        """Chrome trace with one process lane per rank, every event's
        timestamp rebased onto the base rank's timeline via the
        store-derived clock offsets. Returns the trace dict; writes it
        to `path` when given."""
        offsets = self.clock_offsets()
        events: List[Dict] = []
        for r in self.ranks:
            events.append({"name": "process_name", "ph": "M", "pid": r,
                           "tid": 0, "args": {"name": f"rank {r}"}})
            off = offsets.get(r, 0.0)
            for frame in self.frames(r):
                for ev in frame.get("spans", ()):
                    name, t0_us, dur_us = ev[0], ev[1], ev[2]
                    nbytes = ev[3] if len(ev) > 3 else 0
                    e = {"name": name, "ph": "X", "pid": r, "tid": 0,
                         "ts": round(t0_us + off, 3),
                         "dur": round(dur_us, 3), "cat": "runtime"}
                    if nbytes:
                        e["args"] = {"bytes": nbytes}
                    events.append(e)
        trace = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path:
            with open(path, "w") as f:
                json.dump(trace, f)
        return trace

    # ------------------------------------------------------- postmortem
    def aggregate_postmortem(self, store, ranks: Sequence[int],
                             reason: str = "",
                             grace_s: Optional[float] = None,
                             path: Optional[str] = None) -> Optional[str]:
        """Rank 0's half of the distributed postmortem: poll
        ``__telem/post/<rank>`` for every rank (try_get, bounded by the
        grace window), interleave all arrived rings by rebased time
        with a ``[rN]`` tag per line, and write one report next to the
        per-process flight dumps. Returns the path (None when nothing
        arrived)."""
        from .._core.flags import flag_value
        if grace_s is None:
            grace_s = float(flag_value(
                "FLAGS_telemetry_postmortem_grace_s"))
        blobs: Dict[int, Dict] = {}
        t_start = time.time()
        deadline = t_start + max(grace_s, 0.0)
        while True:
            for r in ranks:
                if r in blobs:
                    continue
                raw = store.try_get(_POST_KEY.format(rank=r))
                if raw:
                    try:
                        doc = decode_frame(raw)
                    except (ValueError, zlib.error):
                        continue
                    # freshness: a ring published for a PREVIOUS
                    # incident (survivor died before rank 0's delete
                    # below, or a late publish after it) must not be
                    # attributed to this one
                    if doc.get("t_wall", 0.0) >= t_start - 60.0:
                        blobs[r] = doc
            if len(blobs) >= len(ranks) or time.time() >= deadline:
                break
            time.sleep(0.05)
        # consume the keys: the next incident's aggregation starts
        # clean instead of re-reading this one's rings
        for r in list(blobs):
            try:
                store.delete(_POST_KEY.format(rank=r))
            except Exception:
                pass
        if not blobs:
            return None
        # rebase every ring onto the lowest-rank publisher's timeline
        base = clock_anchor(blobs[min(blobs)])
        merged = []
        for r, doc in blobs.items():
            off = clock_anchor(doc) - base
            for t_us, kind, name, detail in doc.get("events", ()):
                merged.append((t_us + off, r, kind, name, detail))
        merged.sort()
        missing = [r for r in ranks if r not in blobs]
        lines = [f"== paddle_tpu DISTRIBUTED flight record: "
                 f"{len(merged)} event(s) from rank(s) "
                 f"{sorted(blobs)} ==",
                 f"trigger: {reason}" if reason else "trigger: (none)"]
        if missing:
            lines.append(f"missing rank(s) (no ring published within "
                         f"{grace_s:.1f}s): {missing}")
        for r, doc in sorted(blobs.items()):
            lines.append(f"  [r{r}] pid {doc.get('pid')} "
                         f"reason={doc.get('reason')!r} "
                         f"events={len(doc.get('events', ()))}")
        now = max((m[0] for m in merged), default=0.0)
        for t_us, r, kind, name, detail in merged:
            rel = (t_us - now) / 1e6
            lines.append(f"  {rel:+10.6f}s  [r{r}] {kind:<6} {name}"
                         + (f"  {detail}" if detail else ""))
        body = "\n".join(lines) + "\n"
        if path is None:
            from . import flight
            d = flight._dump_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_distributed_r{min(blobs)}_"
                   f"{os.getpid()}.txt")
        with open(path, "w") as f:
            f.write(body)
        if _state.METRICS:
            from . import metrics
            metrics.inc("telemetry.postmortems")
        return path


# ------------------------------------------------------------ module API

_PUB: Optional[TelemetryPublisher] = None
_WORLD_RANKS: Optional[List[int]] = None


def init(store, rank: int, world_size: int,
         interval: Optional[int] = None) -> TelemetryPublisher:
    """Create this process's publisher (idempotent per process). Does
    NOT flip the flag: `FLAGS_distributed_telemetry` stays the single
    on/off switch so the off path costs nothing even when a publisher
    exists."""
    global _PUB, _WORLD_RANKS
    if _PUB is not None:
        _PUB.shutdown()
    _PUB = TelemetryPublisher(store, rank, world_size, interval)
    _WORLD_RANKS = list(range(int(world_size)))
    return _PUB


def publisher() -> Optional[TelemetryPublisher]:
    return _PUB


def shutdown():
    global _PUB
    if _PUB is not None:
        _PUB.shutdown()
        _PUB = None
    with _EVENTS_LOCK:
        if _EVENTS is not None:
            _EVENTS.clear()


def on_step(step_index: int):
    """Step-boundary hook (ElasticStep.run calls this behind the
    `_state.DIST` gate). A process with no publisher ignores it."""
    if _PUB is not None:
        _PUB.on_step(step_index)


def trigger_postmortem(reason: str) -> Optional[str]:
    """Distributed postmortem trigger (rank death seen by the adaptive
    loop, latched async-flush worker error): publish THIS rank's
    flight ring; on rank 0, also poll the survivors' rings and write
    the interleaved report. Never raises — this runs inside failure
    handling."""
    if _PUB is None:
        return None
    try:
        _PUB.publish_postmortem(reason)
        if _PUB.rank == 0:
            return TelemetryAggregator().aggregate_postmortem(
                _PUB.store, _WORLD_RANKS or [0], reason=reason)
    except Exception:
        pass
    return None


# ------------------------------------------------------------- rendering

def render_step_table(table: Dict) -> str:
    ranks = table["ranks"]
    lines = ["== cluster step table =="]
    header = "  step | " + " | ".join(f"r{r:<2}" for r in ranks) \
        + " | median | skew | straggler"
    lines.append(header)
    for row in table["steps"]:
        cells = " | ".join(
            f"{row['ranks'][str(r)] / 1000.0:7.2f}"
            if str(r) in row["ranks"] else "      -"
            for r in ranks)
        flag = "-"
        if row["straggler"] is not None:
            via = row.get("straggler_via")
            verdict = row.get("straggler_compute")
            detail = ", ".join(x for x in (via, verdict) if x)
            flag = f"r{row['straggler']}" \
                + (f" ({detail})" if detail else "")
        lines.append(f"  {row['step']:>4} | {cells} | "
                     f"{row['median_us'] / 1000.0:6.2f} | "
                     f"{row['skew_us'] / 1000.0:5.2f} | {flag}")
    lines.append("  (cells in ms)")
    if table["families"]:
        lines.append("  span-family skew (us/step, slowest - median):")
        for fam, info in table["families"].items():
            lines.append(f"    {fam:<12} skew={info['skew_us']:>10.1f} "
                         f"slowest=r{info['slowest']} "
                         f"median={info['median_us']:.1f}")
    if table.get("memory"):
        mem = table["memory"]
        cells = "  ".join(
            f"r{r}={mem['ranks'][str(r)].get('peak', 0) / 1048576.0:.1f}"
            f"MB" for r in ranks if str(r) in mem["ranks"])
        near = mem["nearest_budget"]
        if mem.get("budget_bytes"):
            frac = mem.get("nearest_budget_frac")
            tail = (f"nearest budget: r{near} at "
                    f"{frac * 100.0:.0f}% of "
                    f"{mem['budget_bytes'] / 1048576.0:.0f}MB")
        else:
            tail = f"highest peak: r{near} (no FLAGS_memory_budget_bytes)"
        lines.append(f"  per-rank peak memory: {cells}  [{tail}]")
    if table.get("compute"):
        comp = table["compute"]
        cells = "  ".join(
            f"r{r}={comp['ranks'][str(r)].get('mfu', 0) * 100.0:.3f}%"
            f"/{comp['ranks'][str(r)].get('gflops', 0):.1f}GF"
            for r in ranks if str(r) in comp["ranks"])
        lines.append(f"  per-rank MFU / achieved GFLOP/s: {cells}")
    if table.get("goodput"):
        good = table["goodput"]
        cells = []
        for r in ranks:
            g = good["ranks"].get(str(r))
            if not g or g.get("goodput_frac") is None:
                continue
            tail = (f" ({g['top_badput']})" if g.get("top_badput")
                    else "")
            cells.append(f"r{r}={g['goodput_frac'] * 100.0:.1f}%{tail}")
        if cells:
            lines.append("  per-rank goodput (top badput): "
                         + "  ".join(cells))
    if table["straggler_counts"]:
        lines.append(f"  straggler flags: "
                     + ", ".join(f"r{r}x{n}" for r, n in
                                 sorted(table["straggler_counts"]
                                        .items())))
    return "\n".join(lines)


def render_goodput(report: Optional[Dict]) -> str:
    if not report:
        return ("== cluster goodput report ==\n  (no goodput frames — "
                "was FLAGS_goodput on while the ranks ran?)")
    c = report["cluster"]
    frac = ("n/a" if c["goodput_frac"] is None
            else f"{c['goodput_frac'] * 100.0:.1f}%")
    lines = ["== cluster goodput report ==",
             f"  cluster: {frac} productive "
             f"({c['productive_chip_s']:.3f} of {c['total_chip_s']:.3f} "
             f"chip-seconds)"]
    for r, g in sorted(report["ranks"].items(), key=lambda kv:
                       int(kv[0])):
        top = g.get("top_badput")
        tail = (f"top badput: {top['bucket']} "
                f"{top['frac'] * 100.0:.1f}%" if top else "no badput")
        marks = []
        if g.get("input_bound"):
            marks.append("INPUT-BOUND")
        if g.get("hang"):
            marks.append("HANG")
        lines.append(f"  r{r}: {g['goodput_frac'] * 100.0:5.1f}% "
                     f"productive | {tail}"
                     + (f"  [{', '.join(marks)}]" if marks else ""))
    return "\n".join(lines)


def render_overlap(report: Dict) -> str:
    lines = ["== comm-overlap report =="]
    t = report["total"]
    frac = ("n/a" if t["overlap_frac"] is None
            else f"{t['overlap_frac']:.3f}")
    bw = "n/a" if t["gbps"] is None else f"{t['gbps']:.3f} GB/s"
    lines.append(f"  total comm: {t['comm_us'] / 1000.0:.2f} ms, "
                 f"overlapped: {t['overlap_us'] / 1000.0:.2f} ms, "
                 f"fraction: {frac}, payload: {t['bytes']} B, "
                 f"achieved: {bw}")
    comp = report.get("compiled")
    if comp:
        sites = ", ".join(f"{k}={v}" for k, v in
                          sorted(comp["sites"].items()))
        lines.append(f"  compiled-in-program collectives (est): "
                     f"{comp['bytes']} B total, "
                     f"{comp['bytes_per_step']} B/step ({sites})")
    for row in report["steps"]:
        frac = ("n/a" if row["overlap_frac"] is None
                else f"{row['overlap_frac']:.3f}")
        lines.append(f"    step {row['step']:>4}: "
                     f"comm {row['comm_us'] / 1000.0:7.2f} ms  "
                     f"overlap {frac:>6}  bytes {row['bytes']:>10}")
    return "\n".join(lines)
