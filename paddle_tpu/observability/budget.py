"""Per-step time-budget profile: where a train step's host time goes.

Spends the PR-3 telemetry the way the flat-bench rounds demanded: run a
workload with metrics on, take the span-histogram delta, and rank every
instrumented component (segment flush / compile / execute, per-op
replay, SOT guard evaluation, optimizer fused step, collectives,
resilience) against the measured wall time per step. Whatever the spans
do NOT account for is the **host gap** — Python dispatch, input feed,
cache-key hashing, autograd glue, and device wait — i.e. exactly the
overhead class "Exploring the limits of Concurrency in ML Training on
Google TPUs" (2011.03641) fingers once the accelerator is saturated.

`segment::flush` brackets its compile/execute children, so the table
reports the flush ENTRY as exclusive scheduling overhead
(flush − compile − execute − replay) to keep the ranking additive.

    python -m paddle_tpu.observability budget --model lenet --steps 20
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

# known histogram -> (display name, parent whose span brackets this
# one). Children subtract out of their parent so the ranked entries sum
# to the accounted total without double counting; any other *_us
# histogram (comm.<op>_us, resilience.*) gets its own top-level row.
_KNOWN = {
    "segment.flush_us": ("segment::flush (scheduling)", None),
    "segment.compile_us": ("segment::compile", "segment.flush_us"),
    "segment.execute_us": ("segment::execute", "segment.flush_us"),
    "segment.replay_per_op_us": ("segment::replay_per_op", None),
    "optimizer.step_us": ("optimizer::fused_step", None),
    "sot.guard_eval_us": ("sot::guard_eval", None),
}


def collect(run_fn: Callable[[], None], steps: int,
            warmup: int = 3) -> Dict:
    """Run `run_fn` (ONE step per call) `steps` times with metrics on
    and return the ranked per-step budget dict. Compile warms up
    off-clock so the budget describes the steady state; the compile
    rows of the ranked table then show residual (cache-miss) compiles
    only.

    The memory AND compute telemetry planes are switched on for the
    whole run — including the warmup, so the warmup compiles capture
    their ``memory_analysis()`` / ``cost_analysis()`` — and the result
    gains a ``memory`` section (peak bytes, compiled temp footprint,
    donated bytes per step) plus a ``compute`` section: per-step FLOPs
    from the executed-runner counters, achieved GFLOP/s over the
    measured wall window, MFU against the per-chip peak
    (FLAGS_device_peak_flops, autodetected when 0), and the roofline
    verdict (arithmetic intensity = flops / bytes-accessed vs the
    ridge point) saying compute-bound vs memory-bound."""
    from . import enable, disable, stats
    from . import compute as _comptel
    from . import goodput as _goodtel
    from . import memory as _memtel
    from .._core.flags import flag_value, set_flags

    mem_was = flag_value("FLAGS_memory_telemetry")
    comp_was = flag_value("FLAGS_compute_telemetry")
    good_was = flag_value("FLAGS_goodput")
    planes = {}
    if not mem_was:
        planes["FLAGS_memory_telemetry"] = True
    if not comp_was:
        planes["FLAGS_compute_telemetry"] = True
    if not good_was:
        planes["FLAGS_goodput"] = True
    if planes:
        set_flags(planes)

    def stepped():
        # the goodput ledger's step boundary: outermost marks only, so
        # a workload that already runs under ElasticStep (whose run()
        # marks its own steps) nests instead of double counting. A
        # step that raises ABORTS (no ring entry, recovery state
        # unwound) instead of being recorded as completed.
        _goodtel.step_begin()
        try:
            run_fn()
        except BaseException:
            _goodtel.step_abort()
            raise
        _goodtel.step_end()
    try:
        seq0 = _memtel.exec_seq()
        cseq0 = _comptel.exec_seq()
        for _ in range(warmup):
            stepped()
        was_on = flag_value("FLAGS_observability")
        enable()
        # delta against a pre-run snapshot, NOT reset(): a session that
        # already has observability on (bench rows freeze-asserting
        # counters around this call) must not have its registry wiped
        before = stats()
        _memtel.reset_peak()
        donated0 = _memtel.donated_bytes()
        flops0 = _comptel.executed_flops()
        cbytes0 = _comptel.executed_bytes()
        calls0 = _comptel.COST_CALLS
        good0 = _goodtel.snapshot()
        t0 = time.perf_counter()
        for _ in range(steps):
            stepped()
        wall_us = (time.perf_counter() - t0) * 1e6
        good1 = _goodtel.snapshot()
        snap = _delta(before, stats())
        peak = _memtel.peak_bytes()
        peak_pd = _memtel.peak_per_device_bytes()
        live = _memtel.live_bytes()
        donated = _memtel.donated_bytes() - donated0
        execs = _memtel.executable_stats()
        flops = _comptel.executed_flops() - flops0
        cbytes = _comptel.executed_bytes() - cbytes0
        cost_calls = _comptel.COST_CALLS - calls0
        cexecs = [e for e in _comptel.executable_stats()
                  if e.get("seq", 0) > cseq0]
        peak_fl = _comptel.peak_flops()
        if not was_on:
            disable()
    finally:
        restore = {}
        if not mem_was:
            restore["FLAGS_memory_telemetry"] = False
        if not comp_was:
            restore["FLAGS_compute_telemetry"] = False
        if not good_was:
            restore["FLAGS_goodput"] = False
        if restore:
            set_flags(restore)
    out = _rank(snap, wall_us, steps)
    # job-level wall attribution over the measured window, from the
    # SAME ledger the spans feed (no second timing source); the bucket
    # additivity identity is asserted inside budget_section
    out["goodput"] = _goodtel.budget_section(good0, good1, steps)
    achieved = flops / (wall_us * 1e-6) if wall_us else 0.0
    out["compute"] = {
        "flops_per_step": round(flops / steps, 1),
        "gflops_per_s": round(achieved / 1e9, 3),
        "mfu": round(_comptel.mfu(achieved, peak_fl), 6),
        "peak_flops": peak_fl,
        # cost_analysis() calls DURING the measured window: a warm
        # steady state makes ZERO (captured-once-per-compile contract,
        # counter-asserted in tests and the bench row)
        "cost_analysis_calls_measured": int(cost_calls),
        **_comptel.roofline(flops, cbytes, peak=peak_fl),
        "executables": cexecs[-6:],
    }
    # prefer executables compiled DURING this collect (warmup included)
    # so another workload's entries in the process-global log can't
    # pollute the column; a fully-warm process (no new compiles — the
    # caches already hold this workload, analyzed earlier) falls back
    # to the whole log
    fresh = [e for e in execs if e.get("seq", 0) > seq0]
    execs = fresh or execs
    temps = [e.get("temp_bytes") or 0 for e in execs]
    out["memory"] = {
        "peak_bytes": int(peak),
        # shard-priced watermark: what the static mem-liveness pass
        # predicts, and what sizes a mesh against the HBM budget
        "peak_per_device_bytes": int(peak_pd),
        "live_bytes": int(live),
        "donated_bytes_per_step": round(donated / steps, 1),
        # largest temp allocation among the compiled executables this
        # workload runs — its steady-state compiled footprint
        "temp_bytes": int(max(temps)) if temps else 0,
        "executables": execs[-6:],
    }
    return out


def _delta(before: Dict, after: Dict) -> Dict:
    b_hists = before.get("histograms", {})
    hists = {}
    for k, h in after.get("histograms", {}).items():
        bh = b_hists.get(k, {})
        hists[k] = {"total": (h.get("total") or 0.0)
                    - (bh.get("total") or 0.0),
                    "count": (h.get("count") or 0)
                    - (bh.get("count") or 0)}
    b_ctrs = before.get("counters", {})
    counters = {k: v - b_ctrs.get(k, 0)
                for k, v in after.get("counters", {}).items()}
    return {"histograms": hists, "counters": counters,
            "step_cache_hit_rate": after.get("step_cache_hit_rate")}


def _rank(snap: Dict, wall_us: float, steps: int) -> Dict:
    hists = snap.get("histograms", {})
    entries: List[Dict] = []
    accounted = 0.0
    for hist, h in hists.items():
        if not hist.endswith("_us"):
            continue
        total, count = (h.get("total") or 0.0), (h.get("count") or 0)
        if not count and not total:
            continue
        name, parent = _KNOWN.get(hist, (hist[:-3].replace(".", "::"),
                                         None))
        entries.append({"name": name, "hist": hist,
                        "us_per_step": total / steps,
                        "calls_per_step": count / steps,
                        "_parent": parent})
    # make parents exclusive
    for e in entries:
        child_sum = sum(c["us_per_step"] for c in entries
                        if c["_parent"] == e["hist"])
        if child_sum:
            e["us_per_step"] = max(e["us_per_step"] - child_sum, 0.0)
    for e in entries:
        e.pop("_parent", None)
        accounted += e["us_per_step"]
    wall_per_step = wall_us / steps
    host_gap = max(wall_per_step - accounted, 0.0)
    entries.append({"name": "host gap (dispatch / input feed / "
                            "device wait — unspanned)",
                    "hist": None, "us_per_step": host_gap,
                    "calls_per_step": None})
    entries.sort(key=lambda e: -e["us_per_step"])
    for e in entries:
        e["pct_of_step"] = round(100.0 * e["us_per_step"] / wall_per_step,
                                 2) if wall_per_step else None
        e["us_per_step"] = round(e["us_per_step"], 2)
        if e["calls_per_step"] is not None:
            e["calls_per_step"] = round(e["calls_per_step"], 3)
    counters = snap.get("counters", {})
    return {
        "steps": steps,
        "wall_us_per_step": round(wall_per_step, 2),
        "accounted_us_per_step": round(accounted, 2),
        "host_gap_us_per_step": round(host_gap, 2),
        # span time in excess of wall time = work that ran CONCURRENTLY
        # with the step loop (the async flush worker's lane) — the
        # direct evidence the pipeline took dispatch off the critical
        # path rather than merely relabeling it
        "overlap_us_per_step": round(max(accounted - wall_per_step, 0.0),
                                     2),
        "entries": entries,
        "counters": {k: counters[k] for k in sorted(counters)
                     if k.startswith(("segment.", "cache.", "compiles.",
                                      "optimizer.", "sot.", "eager.",
                                      "fusion.", "comm.", "memory.",
                                      "compute.", "io.", "record."))},
        "step_cache_hit_rate": snap.get("step_cache_hit_rate"),
    }


# ------------------------------------------------------- static diff

def static_diff(step_fn: Callable[[], None], steps: int = 5) -> Dict:
    """Reconcile the STATIC perf analyzer's predictions against the
    measured meters (the analyzer held to the counters PRs 7–10
    built): trace one step under a PerfRecorder (analysis/perf_checks)
    for the predicted seal-reason histogram and static comm estimate,
    then measure `steps` steps through `collect` and compare against
    the ``segment.flush_reason.*`` / ``fusion.window_breaks`` /
    ``comm.bytes.compiled.*`` counters per step.

    Exact-match gate on the seal rows (a steady-state step's seal
    structure is deterministic); the comm row is an estimator
    cross-check — two different models price the same collectives, so
    the gate is "static must not claim CLEAN when the meters show
    traffic" (and vice versa), not byte equality."""
    from ..analysis import perf_checks

    report, predicted, rec = perf_checks.trace_step(step_fn)
    measured = collect(step_fn, steps=steps)
    counters = measured["counters"]

    heads = set(predicted)
    for k in counters:
        if k.startswith("segment.flush_reason."):
            heads.add(k[len("segment.flush_reason."):])
    heads.discard("perf_trace")   # the recorder's own boundary seal
    rows: List[Dict] = []
    ok = True
    for h in sorted(heads):
        stat = predicted.get(h, 0)
        meas = counters.get("segment.flush_reason." + h, 0) / steps
        match = abs(stat - meas) < 1e-9
        ok = ok and match
        rows.append({"class": "seal:" + h, "static": stat,
                     "measured_per_step": round(meas, 3),
                     "match": match})

    stat_breaks = sum(predicted.get(h, 0)
                      for h in perf_checks.BREAK_REASONS)
    meas_breaks = counters.get("fusion.window_breaks", 0) / steps
    breaks_match = abs(stat_breaks - meas_breaks) < 1e-9
    ok = ok and breaks_match
    rows.append({"class": "fusion.window_breaks", "static": stat_breaks,
                 "measured_per_step": round(meas_breaks, 3),
                 "match": breaks_match})

    stat_syncs = sum(predicted.get(h, 0)
                     for h in perf_checks.SYNC_REASONS)
    rows.append({"class": "host_syncs", "static": stat_syncs,
                 "measured_per_step": round(
                     sum(counters.get("segment.flush_reason." + h, 0)
                         for h in perf_checks.SYNC_REASONS) / steps, 3),
                 "match": True})   # folded into the per-head rows

    meas_comm = sum(v for k, v in counters.items()
                    if k.startswith("comm.bytes.compiled.")) / steps
    comm_match = (rec.comm_bytes > 0) == (meas_comm > 0)
    ok = ok and comm_match
    rows.append({"class": "comm.bytes.compiled", "static": rec.comm_bytes,
                 "measured_per_step": round(meas_comm, 1),
                 "match": comm_match})

    # static FLOP model vs the measured compute.flops.* counters: two
    # different estimators price the same step (the static model counts
    # forward op math, cost_analysis counts the fused fwd+vjp module),
    # so the gate is the PR-11 no-false-clean form — static must not
    # claim zero compute when the meters count some, and vice versa —
    # not numeric equality
    meas_flops = sum(v for k, v in counters.items()
                     if k.startswith("compute.flops.")) / steps
    flops_match = (rec.static_flops > 0) == (meas_flops > 0)
    ok = ok and flops_match
    rows.append({"class": "compute.flops", "static": rec.static_flops,
                 "measured_per_step": round(meas_flops, 1),
                 "match": flops_match})

    # static per-device peak-HBM prediction (mem_liveness over the
    # traced step's sealed programs) vs the measured census per-device
    # watermark: two estimators of the BYTE peak (the static pass
    # counts the recorded program's buffers, the census counts what
    # the runtime actually bound), so the gate is the no-false-clean
    # form — the mem lint must not claim an empty footprint while the
    # byte plane measured one, and vice versa
    meas_peak = measured.get("memory", {}).get(
        "peak_per_device_bytes",
        measured.get("memory", {}).get("peak_bytes", 0))
    stat_peak = getattr(rec, "static_peak_bytes", 0)
    peak_match = (stat_peak > 0) == (meas_peak > 0)
    ok = ok and peak_match
    rows.append({"class": "memory.peak", "static": stat_peak,
                 "measured_per_step": int(meas_peak),
                 "match": peak_match})

    return {
        "ok": bool(ok),
        "steps_measured": steps,
        "rows": rows,
        "static_findings": [d.render() for d in report.diagnostics],
        "measured_wall_us_per_step": measured["wall_us_per_step"],
    }


def render_static_diff(diff: Dict, title: str = "static vs measured"
                       ) -> str:
    lines = [f"== {title} ==",
             f"  {'class':<28} {'static':>10} {'measured':>10}  verdict"]
    for r in diff["rows"]:
        mark = "MATCH" if r["match"] else "MISMATCH"
        lines.append(f"  {r['class']:<28} {r['static']:>10g} "
                     f"{r['measured_per_step']:>10g}  {mark}")
    verdict = ("OK: static predictions match the meters" if diff["ok"]
               else "FAILED: static analysis diverges from the "
                    "measured counters")
    lines.append(f"  => {verdict}")
    for f in diff["static_findings"]:
        lines.append("  " + f)
    return "\n".join(lines)


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB"):
        if abs(n) < 1024.0:
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.2f} GB"


def render(budget: Dict, title: str = "per-step budget") -> str:
    lines = [f"== {title} ==",
             f"  wall/step:      {budget['wall_us_per_step']:>12.1f} us",
             f"  accounted:      {budget['accounted_us_per_step']:>12.1f}"
             f" us",
             f"  host gap:       {budget['host_gap_us_per_step']:>12.1f}"
             f" us"]
    mem = budget.get("memory")
    if mem:
        lines.append(
            f"  memory:         peak {_fmt_bytes(mem['peak_bytes'])} | "
            f"temp {_fmt_bytes(mem['temp_bytes'])} | "
            f"donated/step {_fmt_bytes(mem['donated_bytes_per_step'])} |"
            f" live(end) {_fmt_bytes(mem['live_bytes'])}")
    comp = budget.get("compute")
    if comp and comp.get("flops_per_step"):
        bound = comp.get("bound") or "n/a"
        lines.append(
            f"  compute:        {comp['gflops_per_s']:.2f} GFLOP/s | "
            f"MFU {comp['mfu'] * 100.0:.3f}% of "
            f"{comp['peak_flops'] / 1e9:.0f} GFLOP/s peak | "
            f"AI {comp['arith_intensity']:.2f} FLOP/B vs ridge "
            f"{comp['ridge_intensity']:.2f} ({bound})")
    good = budget.get("goodput")
    if good:
        from . import goodput as _goodtel
        lines.append("  " + _goodtel.render_line(good))
    lines.append("  ranked components:")
    for e in budget["entries"]:
        calls = ("" if e["calls_per_step"] is None
                 else f"  x{e['calls_per_step']:g}/step")
        lines.append(f"    {e['us_per_step']:>10.1f} us "
                     f"{e['pct_of_step']:>6.2f}%  {e['name']}{calls}")
    return "\n".join(lines)
