"""Goodput plane: job-level wall-clock attribution, step anomalies,
hang watchdog.

Every other meter prices a *step* in one domain — spans (time), frames
(cross-rank), bytes, FLOPs. This module accounts for the **job**: a
per-process wall-clock **attribution ledger** that partitions the
timeline into exclusive states, the MLPerf-on-TPU-pods end-to-end
efficiency lens (arxiv 1909.09756, 2011.03641) applied to this
runtime:

=============  =====================================================
bucket         what lands there
=============  =====================================================
``execute``    productive device work: ``segment::execute``,
               per-op replay, the fused optimizer update
``compile``    ``segment::compile`` (XLA compilation)
``input_wait`` the ``io::*`` feed spans — h2d transfer dispatch and
               the new ``io::input_wait`` stall probe (training
               thread blocked on an empty DevicePrefetcher source)
``comm_wait``  host-driven ``comm::*`` collectives
``ckpt_io``    ``ckpt::save`` / ``ckpt::load`` checkpoint I/O
``recovery``   rollback + re-plan + checkpoint restore: from fault
               detection (ElasticStep) to the first successful
               re-run, plus ``resilience::*`` spans outside a
               failure window. STICKY: sub-states inside a recovery
               window stay attributed to recovery, so the bucket
               matches ``resilience.recovery_us`` — redone work is
               badput, not goodput.
``host``       in-step remainder: Python dispatch, cache keys,
               autograd glue (the budget tool's host gap)
``idle``       outside any step (before the first, between jobs)
=============  =====================================================

The ledger is a state machine over the **job thread** (the thread
that marks step boundaries): span begin/end events from
`spans.Span` push/pop mapped states, step marks flip the host/idle
base, recovery probes set the sticky flag. Accrual happens at every
transition, so the **additivity identity** — bucket sum == wall
since ledger start — holds by construction (asserted by
`check_additivity`, the budget tool and bench row 16). Spans from
OTHER threads (the async flush worker) are overlapped work, not wall
time: their durations land in a side `offthread` map, never the
partition.

Riding the ledger:

- a bounded **step-time ring** feeding anomaly detection: a step
  slower than ``FLAGS_goodput_spike_factor`` x the rolling median
  counts ``goodput.anomalies.step_spike``; the existing NaN scan
  (`FLAGS_check_nan_inf`) reports into ``goodput.anomalies.nan``,
  and `note_loss` watches for divergence the same way;
- a **hang watchdog** (reusing `distributed.watchdog`): when no
  probe activity happens within
  ``max(FLAGS_goodput_hang_factor x median step,
  FLAGS_goodput_hang_min_s)``, the watchdog thread captures every
  thread's stack and dumps the flight ring WHILE THE JOB IS STILL
  ALIVE — a stuck collective is named before the job dies silently.

Cluster-wide, each rank's bucket deltas ride the PR-8 telemetry
frames; rank 0's step table gains a goodput column and
`TelemetryAggregator.goodput_report` renders the job-end **cluster
goodput report** (productive chip-seconds / total chip-seconds, top
badput source per rank).

Off-cost is the house pattern: `FLAGS_goodput` is watcher-cached into
`_state.GOODPUT` (folded into `_state.ACTIVE` so spans exist when
only this plane is on); off = one module-attribute read per probe,
zero ring mutations, frozen registry (bench row 16).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional, Tuple

from . import _state

BUCKETS = ("execute", "compile", "input_wait", "comm_wait", "ckpt_io",
           "recovery", "host", "idle")
BADPUT = tuple(b for b in BUCKETS if b != "execute")

# step/loss ring appends since process start: the bench-row-16 freeze
# counter (plane off => this never moves)
RING_MUTATIONS = 0

# span-name -> bucket map, longest prefix wins; names not listed are
# TRANSPARENT (segment::flush brackets its compile/execute children and
# must not shadow them; sot::/telemetry:: are host-side bookkeeping)
_PREFIX_BUCKET = (
    ("segment::execute", "execute"),
    ("segment::replay_per_op", "execute"),
    ("segment::replay_step", "execute"),
    ("optimizer::", "execute"),
    ("segment::compile", "compile"),
    ("comm::", "comm_wait"),
    ("io::", "input_wait"),
    ("ckpt::", "ckpt_io"),
    ("resilience::", "recovery"),
)
_MISS = object()
_BUCKET_MEMO: Dict[str, Optional[str]] = {}


def bucket_of(name: str) -> Optional[str]:
    """The ledger bucket a span name transitions into (None =
    transparent). Memoized — span names are interned formats."""
    b = _BUCKET_MEMO.get(name, _MISS)
    if b is _MISS:
        b = None
        for prefix, bucket in _PREFIX_BUCKET:
            if name.startswith(prefix):
                b = bucket
                break
        _BUCKET_MEMO[name] = b
    return b


class Ledger:
    """Exclusive wall-clock partition of one process's job timeline."""

    def __init__(self):
        self._lock = threading.RLock()
        self._started = False
        self._thread: Optional[int] = None   # the job thread's ident
        self._t_start = 0
        self._t_last = 0
        self._stack = []                     # mapped-span bucket stack
        self._step_depth = 0
        self._recover_depth = 0
        self.buckets: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self.offthread: Dict[str, float] = {}
        self.steps = 0
        self.ring: collections.deque = collections.deque(maxlen=128)
        self.loss_ring: collections.deque = collections.deque(maxlen=128)
        self._t_step_begin = 0
        self.hangs = 0
        self.last_hang: Optional[Dict] = None

    # ------------------------------------------------------- lifecycle
    def start(self, ring_capacity: int = 128):
        with self._lock:
            now = time.perf_counter_ns()
            self._started = True
            self._thread = threading.get_ident()
            self._t_start = self._t_last = now
            self._stack = []
            self._step_depth = 0
            self._recover_depth = 0
            self.buckets = {b: 0.0 for b in BUCKETS}
            self.offthread = {}
            self.steps = 0
            self.ring = collections.deque(maxlen=max(ring_capacity, 8))
            self.loss_ring = collections.deque(
                maxlen=max(ring_capacity, 8))
            self.hangs = 0
            self.last_hang = None

    def stop(self):
        with self._lock:
            if self._started:
                self._accrue(time.perf_counter_ns())
                self._started = False

    # -------------------------------------------------------- accrual
    def _cur(self) -> str:
        if self._recover_depth:
            return "recovery"
        if self._stack:
            return self._stack[-1]
        return "host" if self._step_depth else "idle"

    def _accrue(self, now_ns: int):
        # caller holds the lock
        dt = (now_ns - self._t_last) / 1000.0
        if dt > 0:
            self.buckets[self._cur()] += dt
        self._t_last = now_ns

    # ---------------------------------------------------------- spans
    def on_span_begin(self, name: str, t_ns: int):
        if not self._started:
            return
        if threading.get_ident() != self._thread:
            return
        bucket = bucket_of(name)
        if bucket is None:
            return
        with self._lock:
            self._accrue(t_ns)
            self._stack.append(bucket)
        _hang_beat()

    def on_span_end(self, name: str, t_ns: int, dur_us: float):
        if not self._started:
            return
        bucket = bucket_of(name)
        if bucket is None:
            return
        if threading.get_ident() != self._thread:
            # overlapped work (async flush worker, publisher): priced,
            # but never part of the wall partition
            with self._lock:
                self.offthread[bucket] = \
                    self.offthread.get(bucket, 0.0) + dur_us
            return
        with self._lock:
            self._accrue(t_ns)
            if self._stack:
                self._stack.pop()
        _hang_beat()

    # ---------------------------------------------------------- steps
    def step_begin(self, step_index: Optional[int] = None):
        if not self._started:
            return
        with self._lock:
            now = time.perf_counter_ns()
            self._step_depth += 1
            if self._step_depth == 1:
                # the outermost step mark claims the job thread: the
                # training loop is wherever steps actually run
                self._thread = threading.get_ident()
                self._accrue(now)
                self._t_step_begin = now
        _hang_beat()

    def step_end(self, step_index: Optional[int] = None,
                 loss=None, ok: bool = True):
        global RING_MUTATIONS
        if not self._started:
            return
        dur_us = None
        prior_median = None
        with self._lock:
            if self._step_depth == 0:
                return
            if self._step_depth > 1:
                self._step_depth -= 1
                return
            if ok:
                # step duration stamped NOW (the honest step time the
                # ring feeds); the anomaly/watchdog bookkeeping below
                # runs before the step closes, so its cost accrues to
                # the host bucket instead of polluting idle
                now = time.perf_counter_ns()
                dur_us = (now - self._t_step_begin) / 1000.0
                prior_median = self.median_us()
                self.steps += 1
                self.ring.append(dur_us)
                RING_MUTATIONS += 1
        if dur_us is not None:
            _on_step_complete(dur_us, prior_median)
            if loss is not None:
                self.note_loss(loss)
        with self._lock:
            if self._step_depth:
                self._accrue(time.perf_counter_ns())
                self._step_depth -= 1

    def step_abort(self):
        """Unwind a failed step (exception propagating out of the
        wrapper): clears the in-step and recovery states without
        feeding the ring."""
        with self._lock:
            if self._step_depth:
                now = time.perf_counter_ns()
                self._accrue(now)
                self._step_depth -= 1
                if self._step_depth == 0:
                    self._recover_depth = 0
                    self._stack = []

    # ------------------------------------------------------- recovery
    def recovery_begin(self):
        if not self._started:
            return
        with self._lock:
            self._accrue(time.perf_counter_ns())
            self._recover_depth += 1

    def recovery_end(self):
        if not self._started:
            return
        with self._lock:
            if self._recover_depth:
                self._accrue(time.perf_counter_ns())
                self._recover_depth -= 1

    # ------------------------------------------------------ anomalies
    def note_loss(self, value):
        global RING_MUTATIONS
        if not self._started:
            return
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        if v != v or v in (float("inf"), float("-inf")):
            note_nan("loss")
            return
        with self._lock:
            ring = self.loss_ring
            prior = sorted(abs(x) for x in ring)
            ring.append(v)
            RING_MUTATIONS += 1
        if len(prior) >= 5:
            med = prior[(len(prior) - 1) // 2]
            from .._core.flags import flag_value
            factor = float(flag_value("FLAGS_goodput_spike_factor"))
            if med > 0 and abs(v) > factor * med:
                from . import metrics
                metrics.inc("goodput.anomalies.loss_divergence")
                if _state.FLIGHT:
                    from . import flight
                    flight.note("goodput", "loss_divergence",
                                loss=round(v, 6),
                                median=round(med, 6))

    def median_us(self) -> Optional[float]:
        vals = sorted(self.ring)
        if not vals:
            return None
        return vals[(len(vals) - 1) // 2]

    # ------------------------------------------------------- snapshot
    def snapshot(self) -> Dict:
        """Point-in-time copy: cumulative buckets (us), wall since
        start, steps, ring stats. The partition is accrued up to NOW,
        so ``sum(buckets) == wall`` by construction."""
        with self._lock:
            if self._started:
                self._accrue(time.perf_counter_ns())
            wall = (self._t_last - self._t_start) / 1000.0
            return {
                "buckets": dict(self.buckets),
                "wall_us": wall,
                "steps": self.steps,
                "median_step_us": self.median_us(),
                "offthread_us": dict(self.offthread),
                "hangs": self.hangs,
            }


LEDGER = Ledger()


# ------------------------------------------------------- hang watchdog

_HANG_LOCK = threading.Lock()
_HANG_MGR = None           # dedicated CommTaskManager
_HANG_TASK = "goodput::step"
_HANG_ARMED = False


def _hang_beat():
    """Any probe-visible progress resets the hang clock — a stuck
    collective (blocked INSIDE its comm span) stops producing
    transitions and times out; a long compile keeps beating at its
    span boundaries only, so the dynamic timeout still bounds it."""
    if _HANG_ARMED:
        mgr = _HANG_MGR
        if mgr is not None:
            mgr.heartbeat(_HANG_TASK)


def _on_step_complete(dur_us: float, prior_median_us: Optional[float]):
    """Outermost step finished: spike detection + (re)arm the hang
    watchdog with a timeout derived from the rolling median."""
    from .._core.flags import flag_value
    if prior_median_us and len(LEDGER.ring) >= 5:
        factor = float(flag_value("FLAGS_goodput_spike_factor"))
        if dur_us > factor * prior_median_us:
            from . import metrics
            metrics.inc("goodput.anomalies.step_spike")
            if _state.FLIGHT:
                from . import flight
                flight.note("goodput", "step_spike",
                            dur_us=round(dur_us, 1),
                            median_us=round(prior_median_us, 1))
    median = LEDGER.median_us()
    if median is None or len(LEDGER.ring) < 2:
        return
    factor = float(flag_value("FLAGS_goodput_hang_factor"))
    floor_s = float(flag_value("FLAGS_goodput_hang_min_s"))
    timeout = max(factor * median / 1e6, floor_s)
    _hang_arm(timeout)


def _hang_arm(timeout_s: float):
    global _HANG_MGR, _HANG_ARMED
    with _HANG_LOCK:
        if _HANG_MGR is None:
            from .._core.flags import flag_value
            from ..distributed.watchdog import CommTaskManager
            _HANG_MGR = CommTaskManager(
                check_interval=float(
                    flag_value("FLAGS_goodput_hang_poll_s")),
                on_timeout=_on_hang)
        if not _HANG_ARMED:
            _HANG_MGR.register(_HANG_TASK, timeout=timeout_s)
            _HANG_ARMED = True
        else:
            _HANG_MGR.heartbeat(_HANG_TASK)
            _HANG_MGR.set_timeout(_HANG_TASK, timeout_s)


def _hang_disarm():
    global _HANG_MGR, _HANG_ARMED
    with _HANG_LOCK:
        if _HANG_MGR is not None:
            _HANG_MGR.deregister(_HANG_TASK)
            _HANG_MGR.shutdown()
            _HANG_MGR = None
        _HANG_ARMED = False


def _on_hang(task):
    """Watchdog-thread handler: the job made no probe-visible progress
    for the dynamic timeout. Count it, record the evidence (which
    bucket it hung in, the captured stacks, the detection latency) and
    leave the stack-carrying flight dump to the watchdog's own
    `_account_fired` — all while the job is still alive; nothing here
    raises in the training thread."""
    from . import metrics
    metrics.inc("goodput.hangs")
    with LEDGER._lock:
        bucket = LEDGER._cur()
        LEDGER.hangs += 1
    LEDGER.last_hang = {
        "bucket": bucket,
        "timeout_s": task.timeout,
        "latency_s": time.monotonic() - task.last_beat,
        "stacks": task.stacks,
        "t_wall": time.time(),
    }
    if _state.FLIGHT:
        from . import flight
        flight.note("goodput", "hang", bucket=bucket,
                    timeout_s=round(task.timeout, 3))


# --------------------------------------------------------- module API

def _sync(on: bool):
    """Flag watcher body (observability/__init__): start/stop the
    ledger with the plane."""
    if on:
        from .._core.flags import flag_value
        LEDGER.start(ring_capacity=int(flag_value("FLAGS_goodput_ring")))
    else:
        _hang_disarm()
        LEDGER.stop()


def on_span_begin(name: str, t_ns: int):
    LEDGER.on_span_begin(name, t_ns)


def on_span_end(name: str, t_ns: int, dur_us: float):
    LEDGER.on_span_end(name, t_ns, dur_us)


def step_begin(step_index: Optional[int] = None):
    if _state.GOODPUT:
        LEDGER.step_begin(step_index)


def step_end(step_index: Optional[int] = None, loss=None):
    if _state.GOODPUT:
        LEDGER.step_end(step_index, loss=loss)


def step_abort():
    if _state.GOODPUT:
        LEDGER.step_abort()


def recovery_begin():
    if _state.GOODPUT:
        LEDGER.recovery_begin()


def recovery_end():
    if _state.GOODPUT:
        LEDGER.recovery_end()


def note_loss(value):
    if _state.GOODPUT:
        LEDGER.note_loss(value)


def note_nan(site: str):
    """The NaN scan's goodput hook (`dispatch._check_nan_inf`): a
    non-finite value is a job-health anomaly whatever the scan's
    raise/warn level does next."""
    if not _state.GOODPUT:
        return
    from . import metrics
    metrics.inc("goodput.anomalies.nan")
    if _state.FLIGHT:
        from . import flight
        flight.note("goodput", "nan", site=site)


def snapshot() -> Dict:
    return LEDGER.snapshot()


def delta(before: Dict, after: Dict) -> Dict:
    """Bucket-wise difference of two snapshots (the budget window /
    telemetry frame form)."""
    b0 = before.get("buckets", {})
    return {
        "buckets": {k: after["buckets"][k] - b0.get(k, 0.0)
                    for k in after["buckets"]},
        "wall_us": after["wall_us"] - before.get("wall_us", 0.0),
        "steps": after["steps"] - before.get("steps", 0),
        "median_step_us": after.get("median_step_us"),
    }


def check_additivity(snap: Dict, rel_tol: float = 0.05) -> bool:
    """The additivity identity: bucket sum == wall within rel_tol
    (the accrual construction makes it exact up to float rounding;
    the tolerance absorbs snapshot-boundary skew on deltas)."""
    total = sum(snap["buckets"].values())
    wall = snap["wall_us"]
    return abs(total - wall) <= max(rel_tol * max(wall, 1.0), 50.0)


def goodput_fraction(snap: Dict) -> Optional[float]:
    total = sum(snap["buckets"].values())
    if total <= 0:
        return None
    return snap["buckets"].get("execute", 0.0) / total


def top_badput(snap: Dict) -> Optional[Tuple[str, float]]:
    """(bucket, us) of the largest non-productive bucket."""
    items = [(b, snap["buckets"].get(b, 0.0)) for b in BADPUT]
    items.sort(key=lambda kv: -kv[1])
    if not items or items[0][1] <= 0:
        return None
    return items[0]


def summary() -> Dict:
    """The `observability.stats()` section while the plane is on."""
    snap = snapshot()
    tb = top_badput(snap)
    snap["goodput_frac"] = goodput_fraction(snap)
    snap["top_badput"] = (
        {"bucket": tb[0], "us": round(tb[1], 1)} if tb else None)
    snap["additivity_ok"] = check_additivity(snap)
    snap["last_hang"] = (
        {k: v for k, v in LEDGER.last_hang.items() if k != "stacks"}
        if LEDGER.last_hang else None)
    snap["buckets"] = {k: round(v, 1) for k, v in snap["buckets"].items()}
    snap["offthread_us"] = {k: round(v, 1)
                            for k, v in snap["offthread_us"].items()}
    return snap


def frame_delta(prev: Optional[Dict]) -> Tuple[Optional[Dict], Dict]:
    """(frame section, new snapshot) for the telemetry publisher: the
    per-rank bucket DELTAS since the last publication, json-normalized
    (rounded floats, string keys)."""
    snap = snapshot()
    d = delta(prev, snap) if prev else dict(
        snap, buckets=dict(snap["buckets"]))
    section = {
        "buckets": {k: round(v, 1) for k, v in d["buckets"].items()
                    if v > 0.0},
        "steps": d["steps"],
    }
    med = snap.get("median_step_us")
    if med is not None:
        section["median_step_us"] = round(med, 1)
    if LEDGER.last_hang is not None:
        section["hang"] = {
            "bucket": LEDGER.last_hang["bucket"],
            "timeout_s": round(LEDGER.last_hang["timeout_s"], 3)}
    return section, snap


def budget_section(before: Dict, after: Dict, steps: int) -> Dict:
    """The budget tool's goodput line, from the SAME ledger the spans
    feed — no second timing source. Asserts the additivity identity
    over the measured window."""
    d = delta(before, after)
    total = sum(d["buckets"].values())
    wall = d["wall_us"]
    # explicit raise, not assert: the identity must hold under
    # python -O too (bench row 16 gates on it)
    if abs(total - wall) > max(0.05 * max(wall, 1.0), 50.0):
        raise RuntimeError(
            f"goodput additivity violated: bucket sum {total:.1f}us != "
            f"ledger wall {wall:.1f}us over the measured window")
    frac = (d["buckets"].get("execute", 0.0) / total) if total else None
    n = max(steps, 1)
    return {
        "goodput_frac": round(frac, 4) if frac is not None else None,
        "wall_us_per_step": round(wall / n, 1),
        "buckets_us_per_step": {k: round(v / n, 1)
                                for k, v in d["buckets"].items()},
        "additivity_ok": True,
    }


def render_line(section: Dict) -> str:
    frac = section.get("goodput_frac")
    head = ("n/a" if frac is None else f"{frac * 100.0:.1f}% productive")
    parts = []
    per = section.get("buckets_us_per_step", {})
    total = sum(per.values()) or 1.0
    for b in BUCKETS:
        v = per.get(b, 0.0)
        if b != "execute" and v > 0.005 * total:
            parts.append(f"{b} {100.0 * v / total:.1f}%")
    return f"goodput:        {head}" + \
        (" | " + " | ".join(parts) if parts else "")
